"""Marker interplay regression (ISSUE 1 satellite).

Tier-1 runs ``-m 'not slow'`` which REPLACES the ``-m "not tpu"``
default from pytest.ini's addopts — so any test marked ``tpu`` but
not ``slow`` would silently join the fast lane and compile TPU
kernels for minutes.  Contract: every tpu-marked test is also
slow-marked, i.e. ``-m "tpu and not slow"`` collects nothing.
"""
import os
import re
import subprocess
import sys
from pathlib import Path

TESTS_DIR = Path(__file__).resolve().parent
REPO_ROOT = TESTS_DIR.parent


def test_static_every_tpu_marker_rides_with_slow():
    """Fast static check: a module-level ``pytestmark`` naming tpu
    must name slow in the same assignment; a file using only
    decorator-level tpu marks must mention the slow mark somewhere
    (the subprocess test below proves per-test pairing)."""
    offenders = []
    for path in sorted(TESTS_DIR.glob("test_*.py")):
        src = path.read_text(encoding="utf-8")
        if "mark.tpu" not in src:
            continue
        for m in re.finditer(r"^pytestmark\s*=\s*(.+)$", src, re.M):
            if (
                "mark.tpu" in m.group(1)
                and "mark.slow" not in m.group(1)
            ):
                offenders.append(f"{path.name}: {m.group(0).strip()}")
        if "mark.slow" not in src:
            offenders.append(f"{path.name}: tpu without any slow mark")
    assert not offenders, (
        "tpu-marked tests missing the slow marker (they would leak "
        f"into the -m 'not slow' fast lane): {offenders}"
    )


def test_no_tpu_test_collected_under_not_slow():
    """The real contract, end-to-end through pytest's own collector:
    ``-m "tpu and not slow"`` must select zero tests."""
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest", str(TESTS_DIR),
            "--collect-only", "-q", "-m", "tpu and not slow",
            "-p", "no:cacheprovider", "-p", "no:randomly",
            "--continue-on-collection-errors",
        ],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=220,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    # the selection count is the contract (exit code varies with
    # unrelated collection errors elsewhere in the tree)
    selected = [
        ln
        for ln in proc.stdout.splitlines()
        if "::" in ln and " " not in ln.strip()
    ]
    assert selected == [], (
        f"tpu tests leaked into the fast lane: {selected}"
    )
    # pytest prints "N/M tests collected (K deselected)" when a
    # marker expression deselects — match both spellings
    collected = re.search(
        r"^(\d+)(?:/\d+)? tests? collected", proc.stdout, re.M
    )
    assert collected is None, proc.stdout[-2000:]
