"""abci-cli client commands (reference abci/cmd/abci-cli): console,
batch, and one-shot requests over one socket connection (VERDICT r2
missing #5)."""

import asyncio
import io
import threading

import pytest

from cometbft_tpu.cmd.abci_cli import AbciCli, string_or_hex_to_bytes
from cometbft_tpu.models.kvstore import KVStoreApplication


def test_string_or_hex_to_bytes():
    assert string_or_hex_to_bytes("0x00ff") == b"\x00\xff"
    assert string_or_hex_to_bytes("0XAB") == b"\xab"
    assert string_or_hex_to_bytes('"a=1"') == b"a=1"
    with pytest.raises(ValueError, match="quoted"):
        string_or_hex_to_bytes("bare")
    with pytest.raises(ValueError, match="hex"):
        string_or_hex_to_bytes("0xzz")


@pytest.fixture()
def socket_app():
    """kvstore app hosted over the real socket ABCI server, in a
    background event loop; yields the dial address."""
    from cometbft_tpu.abci.server import ABCIServer

    app = KVStoreApplication()
    server = ABCIServer(app, "tcp://127.0.0.1:0")
    loop = asyncio.new_event_loop()
    started = threading.Event()

    async def run():
        await server.start()
        started.set()
        await asyncio.Event().wait()

    t = threading.Thread(
        target=lambda: loop.run_until_complete(run()), daemon=True
    )
    t.start()
    assert started.wait(10)
    yield server.listen_addr
    loop.call_soon_threadsafe(loop.stop)


def test_batch_script_over_socket(socket_app):
    from cometbft_tpu.abci.socket_client import SocketClient

    client = SocketClient(socket_app)
    out = io.StringIO()
    cli = AbciCli(client, out=out)
    script = io.StringIO(
        "# kvstore batch (reference example.file shape)\n"
        'check_tx "a=1"\n'
        'finalize_block "a=1" "b=2"\n'
        "commit\n"
        'query "a"\n'
        "info\n"
    )
    cli.batch(script)
    client.close()
    text = out.getvalue()
    assert text.count("-> code: OK") >= 4
    assert "-> value: 0x31" in text  # query "a" -> "1"
    # info after commit reports the app hash (height stays whatever the
    # finalize request carried — the reference CLI sends none either)
    assert "last_block_app_hash: 0x" in text


def test_console_runs_commands_and_exits(socket_app):
    from cometbft_tpu.abci.socket_client import SocketClient

    client = SocketClient(socket_app)
    out = io.StringIO()
    cli = AbciCli(client, out=out)
    cli.console(io.StringIO("echo hello\nbogus_cmd\nexit\n"))
    client.close()
    text = out.getvalue()
    assert "-> data: hello" in text
    assert "unknown command" in text


def test_one_shot_error_paths(socket_app):
    from cometbft_tpu.abci.socket_client import SocketClient

    client = SocketClient(socket_app)
    out = io.StringIO()
    cli = AbciCli(client, out=out)
    cli.run_line("check_tx bare-arg")  # unquoted -> error, not a crash
    assert "error" in out.getvalue()
    cli.run_line('check_tx "junk-no-equals"')
    assert "-> code: 1" in out.getvalue()  # kvstore rejects bad format
    client.close()
