"""Statesync chunk-fetch robustness: full-jitter retry backoff +
bans for peers serving corrupt snapshot chunks (mirrors the
blocksync pool's peer bans; reference statesync/syncer.go RETRY /
reject_senders handling)."""

import asyncio
import random

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.statesync.chunks import ChunkQueue
from cometbft_tpu.statesync.syncer import SnapshotKey, Syncer


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


CHUNKS = [b"chunk-%d" % i for i in range(4)]
SNAP_HASH = b"\x11" * 32


class _Provider:
    def app_hash(self, height):
        return b"\x22" * 32

    def state(self, height):
        return {"height": height}

    def commit(self, height):
        return {"commit": height}


class _SnapshotConn:
    """App snapshot surface: accepts the offer; flags chunks that do
    not match the canonical payload as RETRY (corrupt), naming the
    sender — exactly what a checksumming app does."""

    def __init__(self):
        self.retries = []
        self.applied = []

    def offer_snapshot(self, snap, app_hash):
        return abci.ResponseOfferSnapshot(
            result=abci.OFFER_SNAPSHOT_ACCEPT
        )

    def apply_snapshot_chunk(self, index, chunk, sender):
        if chunk != CHUNKS[index]:
            self.retries.append((index, sender))
            return abci.ResponseApplySnapshotChunk(
                result=abci.APPLY_CHUNK_RETRY
            )
        self.applied.append(index)
        return abci.ResponseApplySnapshotChunk(
            result=abci.APPLY_CHUNK_ACCEPT
        )


class _QueryConn:
    def info(self, req):
        return abci.ResponseInfo(
            last_block_height=10, last_block_app_hash=b"\x22" * 32
        )


class _Proxy:
    def __init__(self):
        self.snapshot = _SnapshotConn()
        self.query = _QueryConn()


def _mk_syncer(request_chunk, chunk_timeout_s=5.0):
    return Syncer(
        _Proxy(),
        _Provider(),
        request_chunk=request_chunk,
        chunk_timeout_s=chunk_timeout_s,
        rng=random.Random(7),
    )


def _key():
    return SnapshotKey(
        height=10, format=1, chunks=len(CHUNKS), hash=SNAP_HASH
    )


def test_corrupt_chunk_sender_is_banned_and_sync_completes():
    """One peer serves garbage for every chunk: the app's RETRY on
    its first chunk bans it, its queued chunks are discarded, and the
    good peer completes the restore."""
    calls = []

    async def request_chunk(peer, height, fmt, index):
        calls.append((peer, index))
        if peer == "evil":
            return b"garbage"
        return CHUNKS[index]

    async def main():
        syncer = _mk_syncer(request_chunk)
        state, commit = await syncer._sync_one(
            _key(), {"evil", "good"}
        )
        assert state == {"height": 10}
        assert "evil" in syncer.banned_peers
        assert sorted(syncer.proxy.snapshot.applied) == [0, 1, 2, 3]
        # after the ban the rotation stopped asking the evil peer
        last_evil = max(
            i for i, c in enumerate(calls) if c[0] == "evil"
        )
        assert any(
            c[0] == "good" and i > last_evil
            for i, c in enumerate(calls)
        )

    run(main())


def test_reject_senders_directive_bans_and_discards():
    """The app can name corrupt senders on ANY verdict
    (reject_senders); their queued chunks are discarded and they are
    banned from further fetches."""
    q = ChunkQueue(3)
    q.add(0, b"a", "good")
    q.add(1, b"b", "shady")
    q.add(2, b"c", "shady")

    syncer = _mk_syncer(lambda *a: None)
    syncer._apply_directives(
        q,
        abci.ResponseApplySnapshotChunk(
            result=abci.APPLY_CHUNK_ACCEPT,
            reject_senders=["shady"],
            refetch_chunks=[0],
        ),
    )
    assert "shady" in syncer.banned_peers
    # shady's chunks dropped + the app-directed refetch honored
    assert q.wanted() == {0, 1, 2}


def test_all_peers_banned_rejects_snapshot_not_hangs():
    """Every peer of the snapshot serves corrupt data: the fetchers
    stop, the apply loop times out, and the snapshot attempt fails
    bounded (the caller's sync_any then tries the next snapshot)."""

    async def request_chunk(peer, height, fmt, index):
        return b"garbage"

    async def main():
        syncer = _mk_syncer(request_chunk, chunk_timeout_s=0.5)
        with pytest.raises(asyncio.TimeoutError):
            await syncer._sync_one(_key(), {"evil1", "evil2"})
        assert syncer.banned_peers == {"evil1", "evil2"}

    run(main())


def test_fetch_failures_back_off_with_jitter():
    """Request failures sleep through the shared full-jitter Backoff
    (utils/backoff.py) instead of a flat retry hammer: the fetch
    succeeds after transient failures, and the failure sleeps grow
    from the seeded backoff stream."""
    fails = {"count": 0}
    sleeps = []

    async def request_chunk(peer, height, fmt, index):
        if fails["count"] < 3:
            fails["count"] += 1
            raise ConnectionError("transient")
        return CHUNKS[index]

    async def main():
        syncer = _mk_syncer(request_chunk)

        real_sleep = asyncio.sleep

        async def spy_sleep(d):
            sleeps.append(d)
            await real_sleep(0)  # keep the test fast

        orig = asyncio.sleep
        asyncio.sleep = spy_sleep
        try:
            state, _ = await syncer._sync_one(_key(), {"flaky"})
        finally:
            asyncio.sleep = orig
        assert state == {"height": 10}
        # three failure sleeps drawn from the jittered stream: all
        # bounded by the growing ceiling, not a constant
        fail_sleeps = [s for s in sleeps if s != 0.05]
        assert len(fail_sleeps) >= 3
        assert all(0.0 <= s <= 2.0 for s in fail_sleeps)

    run(main())


def test_chunk_queue_discard_sender():
    q = ChunkQueue(4)
    q.add(0, b"a", "p1")
    q.add(1, b"b", "p2")
    q.add(2, b"c", "p1")
    dropped = q.discard_sender("p1")
    assert sorted(dropped) == [0, 2]
    assert q.wanted() == {0, 2, 3}
    assert q.discard_sender("p1") == []


def test_sender_ban_never_rewinds_applied_chunks():
    """Chunks the app ACCEPTED must survive a later ban of their
    sender: re-applying them unasked corrupts append-style restores
    (kvstore buffers every apply call). Only an EXPLICIT app-directed
    refetch re-opens an applied chunk."""

    async def main():
        q = ChunkQueue(3)
        q.add(0, b"a", "evil")
        q.add(1, b"b", "evil")
        i, _, _ = await q.next(1.0)
        assert i == 0
        q.mark_applied(0)
        # ban evil AFTER chunk 0 was applied: only the unapplied
        # chunk 1 is discarded, next_index does not rewind
        assert q.discard_sender("evil") == [1]
        assert q.next_index == 1 and 0 in q.chunks
        assert q.wanted() == {1, 2}
        # an explicit refetch directive DOES re-open an applied chunk
        q.discard(0)
        assert 0 not in q.applied and q.next_index == 0

    run(main())


def test_reject_senders_directive_spares_applied_chunks():
    """An app response that bans a sender (reject_senders) while that
    sender's earlier chunk was already ACCEPTED must not rewind the
    accepted chunk — the ban discards only its unapplied ones."""
    q = ChunkQueue(3)
    q.add(0, b"a", "shady")
    q.add(1, b"b", "shady")
    q.mark_applied(0)
    syncer = _mk_syncer(lambda *a: None)
    syncer._apply_directives(
        q,
        abci.ResponseApplySnapshotChunk(
            result=abci.APPLY_CHUNK_ACCEPT, reject_senders=["shady"]
        ),
    )
    assert "shady" in syncer.banned_peers
    assert 0 in q.chunks and 0 in q.applied  # accepted chunk intact
    assert q.wanted() == {1, 2}  # only the unapplied one refetches
