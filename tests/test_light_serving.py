"""Light-client serving plane (ISSUE 13): shared verified-header
cache, single-flight dedup, coalesced cross-client verification,
bounded instrumented sessions — and the divergence-detection /
cache-poisoning guarantees:

- forked-header detection still fires when bisection anchors ride
  cache HITS (a lunatic fork verifies crypto-wise, the witness
  cross-check halts it);
- a poisoned cache entry is impossible by construction: publication
  happens only after verification + cross-check, failed verification
  publishes nothing, and the cache re-validates internal consistency.
"""

import dataclasses
import threading
import time

import pytest

import cometbft_tpu.types as T
from cometbft_tpu.light import serving
from cometbft_tpu.light.client import Client, LightClientError, TrustOptions
from cometbft_tpu.light.detector import DivergenceError
from cometbft_tpu.light.provider import StoreBackedProvider
from cometbft_tpu.light.serving import (
    CachePoisonError,
    CoalescedCommitVerifier,
    LightServingPlane,
    ServingOverloadError,
    VerifiedHeaderCache,
)
from cometbft_tpu.light.types import LightBlock
from cometbft_tpu.node.inprocess import make_genesis
from cometbft_tpu.utils.chaingen import make_chain

N_VALS = 4
CHAIN_LEN = 14
TRUST_PERIOD_NS = 24 * 3600 * 10**9


@pytest.fixture(scope="module")
def chain():
    gen, pvs = make_genesis(N_VALS, chain_id="serve-chain")
    node = make_chain(gen, [pv.priv_key for pv in pvs], CHAIN_LEN)
    yield gen, pvs, node
    node.close_stores()


def _provider(gen, node):
    return StoreBackedProvider(
        gen.chain_id, node.block_store, node.state_store
    )


def _client(gen, node, provider=None, **kw):
    provider = provider or _provider(gen, node)
    root = provider.light_block(1)
    return Client(
        gen.chain_id,
        TrustOptions(period_ns=TRUST_PERIOD_NS, height=1, hash=root.hash()),
        provider,
        **kw,
    )


# --- VerifiedHeaderCache ------------------------------------------------


def test_cache_hit_miss_ttl_and_lru(chain, monkeypatch):
    gen, _, node = chain
    lb5 = _provider(gen, node).light_block(5)
    cache = VerifiedHeaderCache(gen.chain_id, max_entries=2, ttl_s=100.0)
    assert cache.get(5) is None and cache.misses == 1
    cache.publish(lb5)
    assert cache.get(5) is lb5 and cache.hits == 1

    # TTL expiry (virtual clock)
    now = [time.monotonic()]
    monkeypatch.setattr(serving, "_monotonic", lambda: now[0])
    cache2 = VerifiedHeaderCache(gen.chain_id, ttl_s=10.0)
    cache2.publish(lb5)
    assert cache2.get(5) is lb5
    now[0] += 11.0
    assert cache2.get(5) is None and cache2.expired == 1

    # LRU bound: max_entries=2, publishing a third evicts the oldest
    prov = _provider(gen, node)
    cache.publish(prov.light_block(6))
    cache.publish(prov.light_block(7))
    assert len(cache) == 2 and cache.peek(5) is None
    # latest_before respects the strict bound
    assert cache.latest_before(7).height == 6
    assert cache.latest_before(6) is None  # 5 was evicted


def test_cache_refuses_inconsistent_blocks(chain):
    """Defense in depth: even the sanctioned write path re-validates
    the header/commit/valset binding — an internally inconsistent
    block can never enter, whatever the caller's bug."""
    gen, _, node = chain
    lb = _provider(gen, node).light_block(5)
    cache = VerifiedHeaderCache(gen.chain_id)
    poisoned = LightBlock(
        header=dataclasses.replace(lb.header, app_hash=b"\x55" * 32),
        commit=lb.commit,  # commit binds to the REAL header
        validator_set=lb.validator_set,
    )
    with pytest.raises(CachePoisonError):
        cache.publish(poisoned)
    assert len(cache) == 0
    # wrong chain id is refused too
    with pytest.raises(CachePoisonError):
        VerifiedHeaderCache("other-chain").publish(lb)


def test_failed_verification_publishes_nothing(chain):
    """The ONLY insertion paths run post-verification: a verify_fn
    that raises leaves the cache empty, and every waiting follower
    shares the leader's error."""
    gen, _, node = chain
    cache = VerifiedHeaderCache(gen.chain_id)
    calls = []

    def bad_verify(height):
        calls.append(height)
        time.sleep(0.05)
        raise LightClientError("verification failed")

    errs = []

    def req():
        try:
            cache.get_or_verify(9, bad_verify)
        except LightClientError as e:
            errs.append(e)

    ths = [threading.Thread(target=req) for _ in range(6)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    assert len(calls) == 1  # single flight even on failure
    assert len(errs) == 6
    assert len(cache) == 0 and cache.peek(9) is None


def test_single_flight_dedups_concurrent_requests(chain):
    gen, _, node = chain
    prov = _provider(gen, node)
    cache = VerifiedHeaderCache(gen.chain_id)
    calls = []
    lb8 = prov.light_block(8)

    def verify(height):
        calls.append(height)
        time.sleep(0.05)  # hold the flight so followers pile up
        return lb8

    got = []
    ths = [
        threading.Thread(
            target=lambda: got.append(cache.get_or_verify(8, verify))
        )
        for _ in range(10)
    ]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    assert len(calls) == 1, "N concurrent requests must verify ONCE"
    assert all(b is lb8 for b in got)
    assert cache.flight_waits >= 1
    assert cache.peek(8) is lb8  # leader's result was published


# --- coalesced verification --------------------------------------------


def test_coalesced_verdicts_serial_equivalent(chain):
    """The engine's verdicts — success AND failure kinds — must be
    exactly what the serial verify_commit_light/_trusting produce,
    including forged-signature and not-enough-power cases."""
    from fractions import Fraction

    gen, _, node = chain
    prov = _provider(gen, node)
    good = prov.light_block(5)
    forged = dataclasses.replace(
        good.commit,
        signatures=[
            dataclasses.replace(
                good.commit.signatures[0], signature=bytes(64)
            )
        ]
        + list(good.commit.signatures[1:]),
    )
    # a "trusting" check against a foreign valset: nobody overlaps ->
    # not enough trusted power
    foreign, _ = T.random_validator_set(4)

    jobs = [
        ("light", good.validator_set, good.commit.block_id,
         good.height, good.commit),
        ("light", good.validator_set, good.commit.block_id,
         good.height, forged),
        ("trusting", good.validator_set, good.commit, Fraction(1, 3)),
        ("trusting", foreign, good.commit, Fraction(1, 3)),
    ]

    def serial(job):
        try:
            if job[0] == "light":
                T.verify_commit_light(
                    gen.chain_id, job[1], job[2], job[3], job[4]
                )
            else:
                T.verify_commit_light_trusting(
                    gen.chain_id, job[1], job[2], trust_level=job[3]
                )
            return None
        except T.CommitVerifyError as e:
            return type(e)

    want = [serial(j) for j in jobs]
    assert want[1] is T.ErrInvalidSignature
    assert want[3] is T.ErrNotEnoughVotingPower

    engine = CoalescedCommitVerifier(gen.chain_id, window_s=0.02)
    got = [None] * len(jobs)

    def submit(i, job):
        try:
            if job[0] == "light":
                engine.verify_commit_light(
                    job[1], job[2], job[3], job[4]
                )
            else:
                engine.verify_commit_light_trusting(
                    job[1], job[2], job[3]
                )
        except T.CommitVerifyError as e:
            got[i] = type(e)

    ths = [
        threading.Thread(target=submit, args=(i, j))
        for i, j in enumerate(jobs)
    ]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    assert got == want
    st = engine.stats()
    assert st["submitted"] == 4
    assert st["max_batch"] >= 2, "concurrent jobs must share a batch"


def test_verdict_cache_skips_reverification(chain):
    """The promoted cross-client verdict: the same commit verified by
    one session resolves instantly for the next (keyed by content,
    not object identity)."""
    gen, _, node = chain
    prov = _provider(gen, node)
    cache = VerifiedHeaderCache(gen.chain_id)
    engine = CoalescedCommitVerifier(
        gen.chain_id, verdict_cache=cache, window_s=0.001
    )
    lb = prov.light_block(6)
    engine.verify_commit_light(
        lb.validator_set, lb.commit.block_id, lb.height, lb.commit
    )
    assert engine.dispatches == 1
    # a FRESH fetch of the same height = different objects, same key
    lb2 = _provider(gen, node).light_block(6)
    assert lb2 is not lb
    engine.verify_commit_light(
        lb2.validator_set, lb2.commit.block_id, lb2.height, lb2.commit
    )
    assert engine.dispatches == 1  # no second crypto dispatch
    assert engine.verdict_hits == 1
    # failures were NOT recorded: a forged commit re-verifies (and
    # fails again) rather than riding any cached verdict
    forged = dataclasses.replace(
        lb.commit,
        signatures=[
            dataclasses.replace(
                lb.commit.signatures[0], signature=bytes(64)
            )
        ]
        + list(lb.commit.signatures[1:]),
    )
    for _ in range(2):
        with pytest.raises(T.ErrInvalidSignature):
            engine.verify_commit_light(
                lb.validator_set, lb.commit.block_id, lb.height, forged
            )
    assert engine.dispatches == 3


# --- sessions / admission ----------------------------------------------


def test_plane_shares_verification_across_sessions(chain):
    gen, _, node = chain
    prov = _provider(gen, node)
    plane = LightServingPlane(
        [_client(gen, node, prov), _client(gen, node, prov)]
    )
    fetched_before = None
    results = []

    def one(h):
        with plane.open_session() as s:
            results.append(s.verified_block(h))

    ths = [
        threading.Thread(target=one, args=(4 + (i % 3),))
        for i in range(12)
    ]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    assert len(results) == 12
    by_h = {lb.height for lb in results}
    assert by_h == {4, 5, 6}
    st = plane.stats()
    # single flight: three distinct heights -> at most three
    # verifications entered the engine/flights no matter the 12
    # sessions (anchors/pivots may add a few cache ops, but every
    # served height was published exactly once)
    assert st["cache"]["published"] <= CHAIN_LEN
    # concurrent arrivals shared flights (or the late ones hit)
    assert st["cache"]["flight_waits"] + st["cache"]["hits"] > 0
    # second wave is pure cache
    before = st["cache"]["published"]
    for h in (4, 5, 6):
        with plane.open_session() as s:
            assert s.verified_block(h).height == h
    st2 = plane.stats()
    assert st2["cache"]["published"] == before
    assert st2["cache"]["hits"] > 0
    del fetched_before


def test_plane_session_bound_sheds_and_counts(chain):
    gen, _, node = chain
    plane = LightServingPlane([_client(gen, node)], max_sessions=2)
    s1 = plane.open_session()
    s2 = plane.open_session()
    with pytest.raises(ServingOverloadError):
        plane.open_session()
    assert plane.sessions_shed == 1
    assert plane.gate.stats()["dropped"] >= 1
    s1.close()
    s3 = plane.open_session()  # freed slot admits again
    s3.close()
    s2.close()
    assert plane.active_sessions() == 0


def test_plane_inflight_gate_sheds_under_storm(chain):
    gen, _, node = chain
    plane = LightServingPlane(
        [_client(gen, node)],
        max_inflight=1,
        admit_timeout_s=0.0,
    )
    release = threading.Event()
    entered = threading.Event()
    orig = plane._verify

    def slow_verify(height):
        entered.set()
        release.wait(5.0)
        return orig(height)

    plane._verify = slow_verify
    out = {}

    def leader():
        with plane.open_session() as s:
            out["leader"] = s.verified_block(9)

    t = threading.Thread(target=leader)
    t.start()
    assert entered.wait(5.0)
    # gate full (the leader holds the one slot): shed, not queue
    with pytest.raises(ServingOverloadError):
        plane.serve(10)
    assert plane.requests_shed == 1
    release.set()
    t.join()
    assert out["leader"].height == 9


def test_plane_queue_registry_contract(chain):
    from cometbft_tpu.obs import QueueRegistry

    gen, _, node = chain
    plane = LightServingPlane([_client(gen, node)], max_inflight=7)
    reg = QueueRegistry()
    plane.register_queues(reg)
    st = reg.get("light.serve")
    # the maxsize convention: one bounded gate, depth>=maxsize is
    # overload (obs/queues.py register docstring)
    assert st["maxsize"] == 7
    for k in ("depth", "high_watermark", "enqueued", "dropped"):
        assert k in st


def test_serve_spans_recorded(chain):
    from cometbft_tpu.trace.tracer import Tracer

    gen, _, node = chain
    tracer = Tracer(name="t", size=4096)
    plane = LightServingPlane([_client(gen, node)], tracer=tracer)
    with plane.open_session() as s:
        s.verified_block(5)
        s.verified_block(5)
    names = {e["name"] for e in tracer.snapshot()}
    assert "light.serve.request" in names
    assert "light.cache.miss" in names
    assert "light.cache.hit" in names
    assert "light.verify.coalesced" in names


# --- divergence detection with the shared cache -------------------------


def _forge_lunatic(gen, pvs, node, height):
    """A valid-fork (lunatic) light block at ``height``: 2 of 4
    validators (1/2 power — passes 1/3 trusting) sign a forged header
    claiming a 2-validator set (passes its own 2/3)."""
    real = node.block_store.load_block(height)
    vs = gen.validator_set()
    byz = [pvs[2], pvs[3]]
    by_addr = {pv.pub_key().address(): pv for pv in byz}
    fvs = T.ValidatorSet(
        [
            vs.get_by_address(pv.pub_key().address())[1]
            for pv in byz
        ]
    )
    forged_header = dataclasses.replace(
        real.header,
        app_hash=b"\x66" * 32,
        validators_hash=fvs.hash(),
        next_validators_hash=fvs.hash(),
    )
    fbid = T.BlockID(
        forged_header.hash(), T.PartSetHeader(1, forged_header.hash())
    )
    ts = forged_header.time_ns
    sigs = []
    for i, val in enumerate(fvs.validators):
        v = T.Vote(
            type_=T.PRECOMMIT,
            height=height,
            round=0,
            block_id=fbid,
            timestamp_ns=ts,
            validator_address=val.address,
            validator_index=i,
        )
        sigs.append(
            T.CommitSig(
                block_id_flag=T.BLOCK_ID_FLAG_COMMIT,
                validator_address=val.address,
                timestamp_ns=ts,
                signature=by_addr[val.address].priv_key.sign(
                    v.sign_bytes(gen.chain_id)
                ),
            )
        )
    return LightBlock(
        header=forged_header,
        commit=T.Commit(height, 0, fbid, sigs),
        validator_set=fvs,
    )


class _ForkingPrimary:
    """Honest store-backed provider, except at the attack height."""

    def __init__(self, gen, node, forged):
        self.inner = StoreBackedProvider(
            gen.chain_id, node.block_store, node.state_store
        )
        self.chain_id = gen.chain_id
        self.forged = forged
        self.reported = []

    def light_block(self, height):
        if height == self.forged.height:
            return self.forged
        return self.inner.light_block(height)

    def report_evidence(self, ev):
        self.reported.append(ev)


def test_divergence_fires_through_cache_and_fork_never_cached(chain):
    """The satellite's core claim, proven end to end: with bisection
    anchors riding shared-cache HITS, a lunatic fork that VERIFIES
    cryptographically still triggers witness divergence — and the
    forked block never lands in the shared cache (publication is
    gated on the cross-check)."""
    gen, pvs, node = chain
    ATTACK_H = 10
    forged = _forge_lunatic(gen, pvs, node, ATTACK_H)
    cache = VerifiedHeaderCache(gen.chain_id)

    # session A (honest) verifies heights below the attack — the
    # cache now holds anchors the attacked session will HIT
    honest = _client(gen, node, header_cache=cache)
    honest.verify_light_block_at_height(6)
    assert cache.peek(6) is not None

    # session B: forking primary, honest witness, SAME shared cache
    primary = _ForkingPrimary(gen, node, forged)
    witness = _provider(gen, node)
    byz_client = _client(
        gen,
        node,
        provider=primary,
        witnesses=[witness],
        header_cache=cache,
    )
    with pytest.raises(DivergenceError):
        byz_client.verify_light_block_at_height(ATTACK_H)
    # detection fired WHILE the trust anchor rode the cache: the
    # attacked client's bisection anchor is the SHARED cached object
    # session A verified (adopted via _best_trusted_before), not a
    # re-verified copy
    assert byz_client.store.get(6) is cache.peek(6)
    # ...and the fork is NOT in the shared cache: nothing at the
    # attack height, and every cached entry matches the honest chain
    assert cache.peek(ATTACK_H) is None
    for h in range(1, CHAIN_LEN + 1):
        ent = cache.peek(h)
        if ent is not None:
            want = node.block_store.load_block_meta(h).block_id.hash
            assert bytes(ent.hash()) == bytes(want)
    # the attack was REPORTED (evidence built both ways)
    assert primary.reported or witness.reported


def test_intermediate_hops_cross_checked_before_publication(chain):
    """Review-hardening regression: EVERY staged block — bisection
    pivots / sequential hops, not just the target — is witness
    cross-checked before ANY of them is published. A fork at a hop
    height (the target itself agreeing with every witness) must halt
    publication and leave the shared cache empty."""
    from cometbft_tpu.light.client import SEQUENTIAL

    gen, pvs, node = chain
    HOP_H = 5
    forged_at_hop = _forge_lunatic(gen, pvs, node, HOP_H)
    cache = VerifiedHeaderCache(gen.chain_id)
    # witness diverges at the HOP height only; primary fully honest —
    # sequential mode makes every height 2..8 a staged hop
    witness = _ForkingPrimary(gen, node, forged_at_hop)
    client = _client(
        gen,
        node,
        witnesses=[witness],
        header_cache=cache,
        verification_mode=SEQUENTIAL,
    )
    with pytest.raises(DivergenceError):
        client.verify_light_block_at_height(8)
    # nothing was published: the hop conflict aborted the whole
    # publication batch (check-all-then-publish-all)
    assert len(cache) == 0
    assert cache.published == 0


def test_cached_height_conflict_detected(chain):
    """The direct conflict branch: a primary serving a header that
    disagrees with a cross-client verified cache entry at the same
    height is refused — detection on a cache hit, by hash compare,
    no crypto needed."""
    gen, pvs, node = chain
    H = 8
    cache = VerifiedHeaderCache(gen.chain_id)
    honest = _client(gen, node, header_cache=cache)
    honest.verify_light_block_at_height(H)
    assert cache.peek(H) is not None

    forged = _forge_lunatic(gen, pvs, node, H)
    victim = _client(gen, node, header_cache=cache)
    with pytest.raises(LightClientError, match="conflicts with"):
        victim.verify_header(forged, time.time_ns())
    # the honest entry survived untouched
    assert bytes(cache.peek(H).hash()) == bytes(
        node.block_store.load_block_meta(H).block_id.hash
    )


# --- statesync sharing --------------------------------------------------


def test_statesync_provider_shares_header_cache(chain, monkeypatch):
    """A joining node's light-verified restore rides verification
    work concurrent sessions already did (and vice versa): heights a
    serving client verified come out of the shared cache with ZERO
    provider fetches by the statesync client."""
    from cometbft_tpu.statesync import stateprovider as sp_mod

    gen, _, node = chain

    class FakeHTTPProvider(StoreBackedProvider):
        """Counts fetches; stands in for the HTTP provider so the
        statesync wiring is testable in-process."""

        def __init__(self, chain_id, url, *a, **k):
            super().__init__(chain_id, node.block_store, node.state_store)
            self.fetches = 0

        def light_block(self, height):
            self.fetches += 1
            return super().light_block(height)

        def close(self):
            pass

    monkeypatch.setattr(sp_mod, "HTTPProvider", FakeHTTPProvider)

    cache = VerifiedHeaderCache(gen.chain_id)
    # a serving session verifies the restore heights first
    serving_client = _client(gen, node, header_cache=cache)
    for h in (5, 6, 7):
        serving_client.verify_light_block_at_height(h)

    root = _provider(gen, node).light_block(1)
    provider = sp_mod.LightClientStateProvider(
        gen.chain_id,
        ["fake://primary"],
        1,
        bytes(root.hash()),
        TRUST_PERIOD_NS,
        header_cache=cache,
    )
    fetched_after_init = provider.primary.fetches
    # the statesync surface: app_hash(5) needs header 6, commit(6),
    # both already verified by the serving session
    assert provider.app_hash(5) == bytes(
        node.block_store.load_block_meta(6).header.app_hash
    )
    assert provider.commit(6).height == 6
    assert provider.primary.fetches == fetched_after_init, (
        "cached heights must not re-fetch (shared verification work)"
    )
    stats = provider.cache_stats()
    assert stats["hits"] > 0
    # ...and what statesync verifies is published for the sessions
    before = cache.published
    provider.commit(9)
    assert cache.published > before
    provider.close()


# --- http provider retry ------------------------------------------------


def test_http_provider_bounded_retry_with_jitter(chain):
    import random

    from cometbft_tpu.light.http_provider import HTTPProvider
    from cometbft_tpu.light.provider import (
        LightBlockNotFound,
        ProviderError,
    )
    from cometbft_tpu.rpc.client import RPCClientError

    gen, _, node = chain
    lb3 = _provider(gen, node).light_block(3)
    prov = HTTPProvider(
        gen.chain_id,
        "127.0.0.1:1",
        timeout_s=1.0,
        retries=3,
        rng=random.Random(7),
    )
    try:
        attempts = []

        async def flaky(height):
            attempts.append(height)
            if len(attempts) < 3:
                raise ConnectionError("transient")
            return lb3

        prov._light_block = flaky
        t0 = time.monotonic()
        got = prov.light_block(3)
        assert got is lb3
        assert len(attempts) == 3 and prov.retries_used == 2

        # not-found never retries (a missing height is an answer)
        attempts.clear()

        async def not_found(height):
            attempts.append(height)
            raise RPCClientError(-32603, "height 99 not available")

        prov._light_block = not_found
        with pytest.raises(LightBlockNotFound):
            prov.light_block(99)
        assert len(attempts) == 1

        # persistent failure surfaces after the bounded budget
        attempts.clear()

        async def dead(height):
            attempts.append(height)
            raise ConnectionError("down")

        prov._light_block = dead
        with pytest.raises(ProviderError, match="after 3 attempts"):
            prov.light_block(3)
        assert len(attempts) == 3

        # a result-timeout is NOT retried (the coroutine is still
        # in flight — retrying would stack duplicate RPCs) and the
        # abandoned coroutine is cancelled
        attempts.clear()
        prov._timeout_s = 0.2
        cancelled = []

        async def slow(height):
            import asyncio

            attempts.append(height)
            try:
                await asyncio.sleep(5.0)
            except asyncio.CancelledError:
                cancelled.append(height)
                raise
            return lb3

        prov._light_block = slow
        with pytest.raises(ProviderError, match="timed out"):
            prov.light_block(3)
        assert len(attempts) == 1  # no retry pile-up
        deadline = time.monotonic() + 2.0
        while not cancelled and time.monotonic() < deadline:
            time.sleep(0.01)
        assert cancelled == [3]
        del t0
    finally:
        prov.close()


def test_http_client_session_reused():
    """One aiohttp session per provider: repeated calls ride the same
    ClientSession object (keep-alive), not a connection per call."""
    import asyncio

    from cometbft_tpu.rpc.client import HTTPClient

    async def main():
        c = HTTPClient("127.0.0.1:1")
        s1 = await c._sess()
        s2 = await c._sess()
        assert s1 is s2
        await c.close()

    asyncio.run(main())


# --- metrics (both prometheus tiers) ------------------------------------


def _emit_light_spans(tracer):
    t0 = time.monotonic_ns()
    tracer.complete("light.cache.hit", t0, 0, "light", height=5)
    tracer.complete("light.cache.hit", t0, 0, "light", height=5)
    tracer.complete("light.cache.miss", t0, 0, "light", height=6)
    tracer.complete("light.verify.coalesced", t0, 1000, "light", n=7)


def test_light_metrics_real_tier(chain):
    from cometbft_tpu.trace.tracer import Tracer
    from cometbft_tpu.utils import metrics as metrics_mod

    if not metrics_mod.HAVE_PROMETHEUS:
        pytest.skip("prometheus_client wheel not installed")
    gen, _, node = chain
    m = metrics_mod.NodeMetrics("serve-metrics")
    tracer = Tracer(name="m", size=256)
    plane = LightServingPlane([_client(gen, node)])
    sess = plane.open_session()
    m.attach_light_serving(tracer, plane)
    _emit_light_spans(tracer)
    body = m.render().decode()
    assert "cometbft_light_cache_hits_total" in body
    assert "cometbft_light_cache_misses_total" in body
    assert "cometbft_light_verify_batch_size" in body
    assert "cometbft_light_sessions" in body

    def val(name):
        for line in body.splitlines():
            if line.startswith(name + "{"):
                return float(line.rsplit(" ", 1)[1])
        raise AssertionError(f"{name} not rendered")

    assert val("cometbft_light_cache_hits_total") == 2.0
    assert val("cometbft_light_cache_misses_total") == 1.0
    assert val("cometbft_light_sessions") == 1.0
    assert val("cometbft_light_verify_batch_size_count") == 1.0
    assert val("cometbft_light_verify_batch_size_sum") == 7.0
    sess.close()
    assert m.render().decode()  # render still healthy post-close


def test_light_metrics_shim_tier(chain):
    """With the wheel absent everything degrades to the no-op shim:
    the plane attaches, spans flow, render serves the placeholder."""
    import importlib
    import sys

    from cometbft_tpu.trace.tracer import Tracer
    from cometbft_tpu.utils import metrics as metrics_mod

    gen, _, node = chain
    saved = {
        k: v
        for k, v in sys.modules.items()
        if k == "prometheus_client"
        or k.startswith("prometheus_client.")
    }
    for k in saved:
        sys.modules[k] = None
    sys.modules["prometheus_client"] = None
    try:
        shimmed = importlib.reload(metrics_mod)
        assert not shimmed.HAVE_PROMETHEUS
        m = shimmed.NodeMetrics("serve-metrics-shim")
        tracer = Tracer(name="m", size=256)
        plane = LightServingPlane([_client(gen, node)])
        m.attach_light_serving(tracer, plane)
        _emit_light_spans(tracer)
        assert b"unavailable" in m.render()
    finally:
        for k in list(sys.modules):
            if k == "prometheus_client" or k.startswith(
                "prometheus_client."
            ):
                del sys.modules[k]
        sys.modules.update(saved)
        importlib.reload(metrics_mod)


def test_health_reports_shared_header_cache(chain):
    """rpc wiring: once the node's shared header cache holds verified
    entries (statesync restore / co-resident plane), the health route
    surfaces its stats."""
    from cometbft_tpu.rpc.core import health
    from cometbft_tpu.rpc.env import Environment

    gen, _, node = chain
    cache = VerifiedHeaderCache(gen.chain_id)
    env = Environment(
        chain_id=gen.chain_id,
        block_store=node.block_store,
        light_header_cache_fn=lambda: cache,
    )
    assert "light_header_cache" not in health(env)  # empty: omitted
    _client(gen, node, header_cache=cache).verify_light_block_at_height(5)
    out = health(env)
    assert out["light_header_cache"]["entries"] >= 1
    assert out["light_header_cache"]["published"] >= 1


# --- proxy integration --------------------------------------------------


def test_proxy_serves_through_plane_and_sheds(chain):
    import asyncio

    import aiohttp

    from cometbft_tpu.light.proxy import RPC_OVERLOADED, LightProxy

    gen, _, node = chain

    async def main():
        client = _client(gen, node)
        proxy = LightProxy(
            client, "127.0.0.1:1", max_sessions=2, max_inflight=4
        )
        await proxy.start("127.0.0.1:0")
        try:
            base = f"http://{proxy.listen_addr}"
            async with aiohttp.ClientSession() as http:
                async with http.get(f"{base}/header?height=5") as r:
                    body = await r.json()
                assert body["result"]["verified"] is True
                # second request = cache hit, same payload
                async with http.get(f"{base}/header?height=5") as r:
                    body2 = await r.json()
                assert (
                    body2["result"]["header_b64"]
                    == body["result"]["header_b64"]
                )
                async with http.get(f"{base}/serving_status") as r:
                    st = (await r.json())["result"]
                assert st["requests"] >= 2
                assert st["cache"]["hits"] >= 1
                # exhaust the session bound -> JSON-RPC overload code
                held = [
                    proxy.plane.open_session() for _ in range(2)
                ]
                async with http.get(f"{base}/header?height=6") as r:
                    shed = await r.json()
                assert shed["error"]["code"] == RPC_OVERLOADED
                for s in held:
                    s.close()
                async with http.get(f"{base}/header?height=6") as r:
                    ok = await r.json()
                assert ok["result"]["verified"] is True
        finally:
            await proxy.stop()

    asyncio.run(asyncio.wait_for(main(), 120))
