"""Reference ed25519 oracle vs the `cryptography` library (OpenSSL)."""

import os

import pytest

pytest.importorskip(
    "cryptography",
    reason="differential oracle needs the OpenSSL wheel; the ctypes-"
    "libcrypto tier is covered by tests/test_crypto_fallback.py",
)
from cryptography.hazmat.primitives.asymmetric.ed25519 import (  # noqa: E402
    Ed25519PrivateKey,
)

from cometbft_tpu.crypto import ref_ed25519 as ref


def test_sign_matches_openssl():
    for i in range(8):
        seed = os.urandom(32)
        sk = Ed25519PrivateKey.from_private_bytes(seed)
        msg = os.urandom(i * 17)
        ours = ref.sign(seed, msg)
        from cryptography.hazmat.primitives import serialization

        pub = sk.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        assert ref.public_from_seed(seed) == pub
        theirs = sk.sign(msg)
        assert ours == theirs


def test_verify_roundtrip_and_negatives():
    seed = os.urandom(32)
    pub = ref.public_from_seed(seed)
    msg = b"cometbft_tpu vote sign bytes"
    sig = ref.sign(seed, msg)
    assert ref.verify_zip215(pub, msg, sig)
    assert not ref.verify_zip215(pub, msg + b"x", sig)
    bad = bytearray(sig)
    bad[3] ^= 1
    assert not ref.verify_zip215(pub, msg, bytes(bad))
    # non-canonical S rejected
    s = int.from_bytes(sig[32:], "little") + ref.L
    if s < 2**256:
        assert not ref.verify_zip215(pub, msg, sig[:32] + s.to_bytes(32, "little"))


def test_zip215_liberal_decoding():
    # y >= p encodings must be accepted as points (reduced mod p).
    # Encoding of y = p + 1 == y = 1 (the identity's y); with sign 0.
    enc = (ref.P + 1).to_bytes(32, "little")
    pt = ref.point_decompress(enc)
    assert pt is not None
    assert ref.point_equal(pt, ref.IDENTITY)
    # small-order point (y = -1, order 2) decodes fine
    enc2 = (ref.P - 1).to_bytes(32, "little")
    assert ref.point_decompress(enc2) is not None


def test_small_order_pubkey_cofactored():
    # A signature by the identity pubkey: A = identity, R = identity, S = 0
    # verifies under the cofactored equation for h*identity = identity,
    # S*B = identity iff S = 0.
    ident = ref.point_compress(ref.IDENTITY)
    sig = ident + b"\x00" * 32
    assert ref.verify_zip215(ident, b"anything", sig)
