"""Light client bisection over the real RPC HTTP provider against a
live node (reference analog: light/client_test.go + provider/http)."""

import asyncio

from cometbft_tpu.config.config import test_config as make_test_cfg
from cometbft_tpu.light import Client, TrustOptions
from cometbft_tpu.light.http_provider import HTTPProvider
from cometbft_tpu.node.inprocess import make_genesis
from cometbft_tpu.node.node import Node


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def test_light_bisection_over_http():
    async def main():
        gen, pvs = make_genesis(2, chain_id="light-http")
        cfg = make_test_cfg(".")
        n0 = Node(cfg, gen, privval=pvs[0])
        n1 = Node(make_test_cfg("."), gen, privval=pvs[1])
        await n0.start()
        await n1.start()
        await n0.dial(n1.listen_addr)
        while n0.height < 6:
            await asyncio.sleep(0.05)
        trusted = n0.parts.block_store.load_block(1)
        target_height = n0.height

        provider = HTTPProvider("light-http", n0.rpc_server.listen_addr)
        witness = HTTPProvider("light-http", n1.rpc_server.listen_addr)

        def verify():
            cli = Client(
                "light-http",
                TrustOptions(
                    period_ns=3600 * 10**9,
                    height=1,
                    hash=trusted.hash(),
                ),
                primary=provider,
                witnesses=[witness],
            )
            lb = cli.verify_light_block_at_height(
                target_height, now_ns=None
            )
            return lb

        # provider blocks its calling thread; run off the event loop
        lb = await asyncio.to_thread(verify)
        assert lb.height == target_height
        assert bytes(lb.hash()) == bytes(
            n0.parts.block_store.load_block(target_height).hash()
        )
        provider.close()
        witness.close()
        await n0.stop()
        await n1.stop()

    run(main())
