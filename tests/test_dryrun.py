"""Driver-dryrun regression tests (VERDICT r2 next-round #1).

Two rounds of red MULTICHIP signals came from budget mismatches between
the dryrun's internal kernel-leg budget and the driver's overall
timeout — nothing in the default test lane ran the dryrun end to end,
so the regression shipped unseen. These tests close that hole:

  1. the default kernel-leg budget is pinned to fit the driver window;
  2. the FULL dryrun flow (subprocess, default budget, cold or warm
     cache) must finish under a hard wall-clock cap;
  3. the quorum reducer — the collective the dryrun exists to prove —
     runs directly on the 8-device CPU mesh.
"""

import importlib.util
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENTRY = os.path.join(REPO, "__graft_entry__.py")

# The driver killed the round-2 dryrun from outside (rc=124) before the
# 600s kernel-leg budget elapsed; anything near that is too slow. The
# full dryrun must fit comfortably inside this cap including process
# startup and the quorum-step compile.
DRYRUN_WALL_CAP_S = 240


def _load_entry_module():
    spec = importlib.util.spec_from_file_location("graft_entry", ENTRY)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_default_kernel_budget_fits_driver_window():
    mod = _load_entry_module()
    # startup (~15s) + leg budget + quorum compile (~15s) must stay
    # well inside the 240s wall cap below (MULTICHIP_r02 was rc=124
    # with a 600s budget; the sharded leg measures ~40s cold-cache)
    assert mod.DEFAULT_KERNEL_BUDGET_S <= 120, (
        "kernel-leg budget must leave the driver's overall dryrun "
        "timeout room for startup + quorum compile"
    )


def test_dryrun_flow_completes_under_wall_cap():
    """Run the real dryrun exactly as the driver does — fresh process,
    default budgets — under a hard wall clock. A regression that pushes
    the dryrun past the driver's window fails HERE, not in the round
    report."""
    env = dict(os.environ)
    env.pop("GRAFT_DRYRUN_KERNEL_BUDGET_S", None)
    env.pop("GRAFT_DRYRUN_KERNEL", None)  # ambient =inline is unbudgeted
    try:
        proc = subprocess.run(
            [sys.executable, ENTRY, "--dryrun", "8"],
            env=env,
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=DRYRUN_WALL_CAP_S,
        )
    except subprocess.TimeoutExpired:
        pytest.fail(
            f"dryrun exceeded the {DRYRUN_WALL_CAP_S}s wall cap — the "
            "driver would have killed it (MULTICHIP rc=124 regression)"
        )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    assert "dryrun_multichip OK" in proc.stdout, proc.stdout[-2000:]
    # The sharded kernel leg must GENUINELY execute (compact field
    # mode makes the graph CPU-compilable inside the budget, ~40s
    # cold / seconds warm — VERDICT r3 #1/#4). The host-verifier
    # fallback is a resource-exhaustion backstop only; shipping green
    # via fallback is a regression. GRAFT_ALLOW_KERNEL_FALLBACK=1
    # tolerates it for debugging on starved boxes.
    mode_line = next(
        l for l in proc.stdout.splitlines() if "kernel_leg=" in l
    )
    if os.environ.get("GRAFT_ALLOW_KERNEL_FALLBACK"):
        assert (
            "sharded-kernel" in mode_line
            or "host-verifier-fallback" in mode_line
        ), mode_line
    else:
        assert "sharded-kernel" in mode_line, mode_line


def test_quorum_reducer_on_8_device_mesh():
    """The psum collective on the actual 8-device CPU mesh: weighted
    tally + quorum compare, one invalid lane."""
    from cometbft_tpu.parallel.mesh import make_mesh
    from cometbft_tpu.parallel.sharded_verify import make_quorum_reducer

    assert len(jax.devices()) >= 8
    mesh = make_mesh(8)
    n = 16
    ok = np.ones(n, bool)
    ok[5] = False
    powers = np.arange(1, n + 1, dtype=np.int32)
    total = int(powers.sum())
    reducer = make_quorum_reducer(mesh)
    quorum, tally, ok_lanes = reducer(
        jnp.asarray(ok), jnp.asarray(powers), jnp.int32(total * 2 // 3)
    )
    want_tally = total - 6
    assert int(tally) == want_tally
    assert bool(quorum) == (want_tally * 3 > total * 2)
    assert list(np.asarray(ok_lanes)) == list(ok)


def test_quorum_reducer_rejects_int32_overflow():
    from cometbft_tpu.parallel.mesh import make_mesh
    from cometbft_tpu.parallel.sharded_verify import make_quorum_reducer

    mesh = make_mesh(8)
    reducer = make_quorum_reducer(mesh)
    powers = np.full(8, 2**28, np.int64)  # sums past 2**31
    with pytest.raises(ValueError, match="voting power"):
        reducer(
            jnp.ones(8, bool), jnp.asarray(powers), jnp.int32(0)
        )
