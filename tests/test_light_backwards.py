"""Light-client backwards verification (reference light/client.go
backwards): verify headers BELOW the trust root via the header hash
chain, no signatures needed."""

import time

import pytest

from cometbft_tpu.light.client import Client, LightClientError, TrustOptions
from cometbft_tpu.light.provider import StoreBackedProvider
from cometbft_tpu.node.inprocess import make_genesis
from cometbft_tpu.utils.chaingen import make_chain

N_VALS = 4
CHAIN_LEN = 20


@pytest.fixture(scope="module")
def chain():
    gen, pvs = make_genesis(N_VALS, chain_id="back-chain")
    node = make_chain(gen, [pv.priv_key for pv in pvs], CHAIN_LEN)
    return gen, node


def _client(gen, node, trust_height):
    provider = StoreBackedProvider(
        gen.chain_id, node.block_store, node.state_store
    )
    root = provider.light_block(trust_height)
    return Client(
        gen.chain_id,
        TrustOptions(
            period_ns=3600 * 10**9 * 24,
            height=trust_height,
            hash=root.hash(),
        ),
        provider,
    )


def test_backwards_walk_to_earlier_height(chain):
    gen, node = chain
    client = _client(gen, node, 15)
    lb = client.verify_light_block_at_height(5)
    assert lb.height == 5
    # walked 10 hash-chain hops
    assert client.hops == 10
    # now in the store: immediate
    again = client.verify_light_block_at_height(5)
    assert again.hash() == lb.hash()


def test_backwards_rejects_forged_header(chain):
    gen, node = chain

    import dataclasses

    class Tamper(StoreBackedProvider):
        def light_block(self, height):
            lb = super().light_block(height)
            if height == 7:
                # frozen header: rebuild with a different app_hash
                lb = type(lb)(
                    dataclasses.replace(
                        lb.header, app_hash=b"\xff" * 32
                    ),
                    lb.commit,
                    lb.validator_set,
                )
            return lb

    provider = Tamper(gen.chain_id, node.block_store, node.state_store)
    root = provider.light_block(12)
    client = Client(
        gen.chain_id,
        TrustOptions(
            period_ns=3600 * 10**9, height=12, hash=root.hash()
        ),
        provider,
    )
    with pytest.raises(LightClientError, match="chain broken"):
        client.verify_light_block_at_height(5)


def test_backwards_rejects_non_monotonic_time(chain):
    """ADVICE r2 (low): a primary serving hash-chained headers with
    out-of-order times must be rejected (reference VerifyBackwards
    checks untrusted.Time < trusted.Time on every hop). The hash chain
    itself breaks when a header is modified, so the tamper here swaps
    the WHOLE hop: provider serves a header whose time is pushed
    forward — the hash-link check would catch the edit, but the time
    check must fire FIRST (defense in depth; ordering asserted via the
    error message)."""
    import dataclasses

    class TimeWarp(StoreBackedProvider):
        def light_block(self, height):
            lb = super().light_block(height)
            if height == 9:
                lb = type(lb)(
                    dataclasses.replace(
                        lb.header,
                        # jump past the trust root's time
                        time_ns=lb.header.time_ns + 10**15,
                    ),
                    lb.commit,
                    lb.validator_set,
                )
            return lb

    gen, node = chain
    provider = TimeWarp(gen.chain_id, node.block_store, node.state_store)
    root = provider.light_block(12)
    client = Client(
        gen.chain_id,
        TrustOptions(period_ns=3600 * 10**9, height=12, hash=root.hash()),
        provider,
    )
    with pytest.raises(LightClientError, match="non-monotonic"):
        client.verify_light_block_at_height(5)


def test_backwards_rejects_wrong_chain_id(chain):
    gen, node = chain
    import dataclasses

    class WrongChain(StoreBackedProvider):
        def light_block(self, height):
            lb = super().light_block(height)
            if height == 9:
                lb = type(lb)(
                    dataclasses.replace(lb.header, chain_id="evil"),
                    lb.commit,
                    lb.validator_set,
                )
            return lb

    provider = WrongChain(gen.chain_id, node.block_store, node.state_store)
    root = provider.light_block(12)
    client = Client(
        gen.chain_id,
        TrustOptions(period_ns=3600 * 10**9, height=12, hash=root.hash()),
        provider,
    )
    with pytest.raises((LightClientError, ValueError), match="chain"):
        client.verify_light_block_at_height(5)
