"""Legacy gRPC broadcast API (reference rpc/grpc/api.go + grpc_test.go):
Ping + BroadcastTx against a live 2-node net, via the codegen-free
client, checking the tx actually lands in committed state."""

import asyncio

from cometbft_tpu.config.config import test_config as make_test_cfg
from cometbft_tpu.node.inprocess import make_genesis
from cometbft_tpu.node.node import Node
from cometbft_tpu.rpc.grpc_api import GRPCBroadcastClient


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def test_grpc_ping_and_broadcast_tx():
    gen, pvs = make_genesis(2, chain_id="grpc-chain")

    async def main():
        cfg = make_test_cfg(".")
        cfg.rpc.grpc_laddr = "tcp://127.0.0.1:0"
        n0 = Node(cfg, gen, privval=pvs[0])
        n1 = Node(make_test_cfg("."), gen, privval=pvs[1])
        await n0.start()
        await n1.start()
        await n0.dial(n1.listen_addr)
        while n0.height < 2:
            await asyncio.sleep(0.05)

        cli = GRPCBroadcastClient(f"127.0.0.1:{n0.grpc_server.port}")

        def drive():
            cli.ping()  # liveness
            return cli.broadcast_tx(b"grpckey=grpcval")

        # the gRPC client blocks; the node's loop must stay free to
        # commit the tx, so drive from a worker thread
        res = await asyncio.to_thread(drive)
        assert res["check_tx"]["code"] == 0, res
        assert res["tx_result"]["code"] == 0, res
        assert int(res["height"]) >= 1, res

        # invalid tx surfaces the CheckTx error
        bad = await asyncio.to_thread(cli.broadcast_tx, b"no-equals")
        assert bad["check_tx"]["code"] != 0, bad

        assert n0.parts.app.state.get(b"grpckey") == b"grpcval"
        cli.close()
        await n0.stop()
        await n1.stop()

    run(main())
