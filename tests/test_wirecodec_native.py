"""Native wire codec vs pure Python (native/wirecodec.cpp).

The native commit encode/decode, SHA-256 and RFC 6962 merkle fold
must be byte-identical to the Python implementations — the Python
path stays the semantic source of truth and the no-compiler fallback.
Skips cleanly when the extension cannot build.
"""

import hashlib
import random

import pytest

from cometbft_tpu.crypto import merkle
from cometbft_tpu.types.block import (
    BlockID,
    Commit,
    CommitSig,
    PartSetHeader,
)
from cometbft_tpu.utils import codec, proto, wirecodec

nat = wirecodec.module()
pytestmark = pytest.mark.skipif(
    nat is None, reason="native wirecodec unavailable (no compiler)"
)

rng = random.Random(7)


def _commit(n_sigs):
    sigs = []
    for _ in range(n_sigs):
        sigs.append(
            CommitSig(
                block_id_flag=rng.choice([1, 2, 3]),
                validator_address=(
                    bytes(rng.randbytes(20)) if rng.random() > 0.15 else b""
                ),
                timestamp_ns=rng.randrange(0, 2**62),
                signature=(
                    bytes(rng.randbytes(64)) if rng.random() > 0.15 else b""
                ),
            )
        )
    return Commit(
        height=rng.randrange(1, 2**45),
        round=rng.randrange(0, 10),
        block_id=BlockID(
            bytes(rng.randbytes(32)),
            PartSetHeader(3, bytes(rng.randbytes(32))),
        ),
        signatures=sigs,
    )


def _py_encode_commit(c):
    out = proto.field_varint(1, c.height) + proto.field_varint(2, c.round)
    out += proto.field_message(3, c.block_id.encode())
    for cs in c.signatures:
        out += proto.field_message(4, codec.encode_commit_sig(cs))
    return out


def test_encode_byte_identical_and_roundtrip():
    for _ in range(40):
        c = _commit(rng.randrange(0, 180))
        enc = codec.encode_commit(c)
        assert enc == _py_encode_commit(c)
        d = codec.decode_commit(enc)
        assert (
            d.height == c.height
            and d.round == c.round
            and d.block_id == c.block_id
            and d.signatures == c.signatures
        )


def test_native_decode_defers_to_python_on_malformed():
    """Truncated / garbage input must raise ValueError identically
    (the wrapper falls back to the Python reader, which raises)."""
    c = _commit(5)
    enc = codec.encode_commit(c)
    for bad in (enc[:-3], b"\xff" * 10, enc + b"\x07"):
        with pytest.raises(ValueError):
            codec.decode_commit(bad)


def _py_only_decode(b):
    saved = wirecodec._mod
    wirecodec._mod = None
    try:
        try:
            c = codec.decode_commit(b)
            return (
                "ok",
                c.height,
                c.round,
                [
                    (s.block_id_flag, s.validator_address,
                     s.timestamp_ns, s.signature)
                    for s in c.signatures
                ],
            )
        except ValueError as e:
            return ("err",)
    finally:
        wirecodec._mod = saved


def test_adversarial_inputs_agree_with_python():
    """Code-review r4 findings: crafted peer bytes that once hit
    unsigned-overflow / >64-bit-varint / timestamp-overflow paths in
    the native reader must either error in BOTH paths or decode to
    the SAME values (the native reader errors internally -> wrapper
    falls back to Python, so divergence is structurally impossible;
    these vectors pin it)."""
    vectors = [
        # field-4 length 2^64-1: the OOB-read attempt
        bytes([0x22]) + b"\xff" * 9 + b"\x01",
        # 10-byte varint height (value past 2^63)
        bytes([0x08]) + b"\x80" * 9 + b"\x03",
        # 11-byte varint (Python accepts shift<=70)
        bytes([0x08]) + b"\x80" * 10 + b"\x01",
        # timestamp secs = 2^62 inside a commit sig
        proto.field_message(
            4, proto.field_message(3, proto.field_varint(1, 2**62))
        ),
    ]
    for i, b in enumerate(vectors):
        py = _py_only_decode(b)
        try:
            c = codec.decode_commit(b)
            got = (
                "ok",
                c.height,
                c.round,
                [
                    (s.block_id_flag, s.validator_address,
                     s.timestamp_ns, s.signature)
                    for s in c.signatures
                ],
            )
        except ValueError:
            got = ("err",)
        assert py[0] == got[0], (i, py, got)
        if py[0] == "ok":
            assert py[1:] == got[1:], i


def test_merkle_root_matches_python():
    for _ in range(60):
        n = rng.randrange(0, 40)
        leaves = [
            bytes(rng.randbytes(rng.randrange(0, 300))) for _ in range(n)
        ]
        # pure-Python reference (small lists bypass native routing, so
        # force the reference by computing the fold inline)
        if n == 0:
            want = hashlib.sha256(b"").digest()
        else:
            stack = []
            for it in leaves:
                h = hashlib.sha256(b"\x00" + it).digest()
                s = 1
                while stack and stack[-1][1] == s:
                    ph, _ = stack.pop()
                    h = hashlib.sha256(b"\x01" + ph + h).digest()
                    s *= 2
                stack.append((h, s))
            h, _ = stack.pop()
            while stack:
                ph, _ = stack.pop()
                h = hashlib.sha256(b"\x01" + ph + h).digest()
            want = h
        assert nat.merkle_root(leaves) == want
        assert merkle.hash_from_byte_slices(leaves) == want


def test_native_sha256_edge_lengths():
    for ln in (0, 1, 55, 56, 57, 63, 64, 65, 127, 128, 4096):
        b = bytes(rng.randbytes(ln))
        assert (
            nat.merkle_root([b])
            == hashlib.sha256(b"\x00" + b).digest()
        ), ln


def test_varints_byte_identical():
    for _ in range(50):
        nums = [
            rng.randrange(-(2**63), 2**63)
            for _ in range(rng.randrange(0, 60))
        ]
        assert nat.varints(nums) == b"".join(
            proto.varint(x) for x in nums
        )


def test_commit_hash_native_equals_python():
    for _ in range(20):
        c = _commit(rng.randrange(0, 160))
        want = merkle.hash_from_byte_slices(
            [cs.encode() for cs in c.signatures]
        )
        assert nat.commit_merkle_root(c.signatures) == want
        assert c.hash() == want
