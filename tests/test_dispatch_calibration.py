"""Measured host/device dispatch crossover (VERDICT r2 weak #3: a
static _MIN_TPU_BATCH routed 150-sig commits to a 98ms tunnel dispatch
that costs 12ms on host). The calibrator learns both costs from
observed walls and routes each batch to whichever path is predicted
faster; set_min_tpu_batch(1) still forces the device (dryrun/tests)."""

import pytest

from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.crypto.batch import _Calibration
from cometbft_tpu.crypto.keys import Ed25519PrivKey


def test_tunnel_like_flat_cost_moves_crossover_past_commit_sizes():
    c = _Calibration()
    # two post-compile dispatches on a tunneled link (~90ms flat)
    c.observe_device(4800, 0.105)
    c.observe_device(4800, 0.095)
    c.observe_host(150, 150 * 80e-6)
    assert not c.device_wins(150), "150-sig commit must stay on host"
    assert not c.device_wins(64)
    assert c.device_wins(4800), "replay windows must still dispatch"
    assert 500 < c.crossover() < 3000


def test_local_chip_flat_cost_keeps_vote_waves_on_device():
    c = _Calibration()
    c.observe_device(256, 0.004)  # ~3ms flat local chip
    c.observe_device(256, 0.0045)
    c.observe_host(150, 150 * 80e-6)
    assert c.device_wins(150), "local chip should win a 150-sig wave"
    assert c.crossover() < 100


def test_wall_floor_rejects_non_blocking_samples():
    """block_until_ready does not block through the axon tunnel
    (ADVICE r5): a watcher whose wait returned without blocking would
    record a near-enqueue-time wall and pull flat_s optimistic, so
    small commits keep routing to a ~120 ms link. Sub-floor walls
    never enter the EWMA; genuine dispatch walls do."""
    c = _Calibration()
    flat0 = c.flat_s
    c.observe_device(150, 3e-5)  # enqueue-time artifact
    assert c.flat_s == flat0 and c.device_samples == 0
    c.observe_device(150, 0.004)  # genuine local-chip dispatch+fetch
    assert c.device_samples == 1


def test_compile_walls_never_poison_the_ewma():
    c = _Calibration()
    flat0 = c.flat_s
    c.observe_device(4800, 180.0)  # first-call XLA compile
    assert c.flat_s == flat0 and c.device_samples == 0


def test_routing_uses_calibration(monkeypatch):
    # host-favored calibration: a 100-sig batch must route to host even
    # on the tpu backend, without touching the device at all
    monkeypatch.setattr(
        crypto_batch, "calibration", _Calibration()
    )
    crypto_batch.calibration.observe_device(4800, 0.1)
    crypto_batch.calibration.observe_device(4800, 0.1)

    old = crypto_batch._default_backend
    old_min = crypto_batch._MIN_TPU_BATCH
    crypto_batch.set_default_backend("tpu")
    crypto_batch.set_min_tpu_batch(64)
    try:
        v = crypto_batch.create_batch_verifier()
        privs = [Ed25519PrivKey.generate() for _ in range(100)]
        for i, p in enumerate(privs):
            m = b"route|%d" % i
            v.add(p.pub_key(), m, p.sign(m))
        ok, verdicts = v.verify()
        assert ok and all(verdicts)
        assert crypto_batch.LAST_ROUTE["path"] == "host"
        assert crypto_batch.LAST_ROUTE["n"] == 100
        assert crypto_batch.LAST_ROUTE["crossover"] > 100
    finally:
        crypto_batch.set_min_tpu_batch(old_min)
        crypto_batch.set_default_backend(old)


def test_force_min_batch_1_bypasses_calibration(monkeypatch):
    """The dryrun/test force-switch must still reach the device path
    regardless of what calibration thinks (here: fake the kernel)."""
    monkeypatch.setattr(crypto_batch, "calibration", _Calibration())
    crypto_batch.calibration.observe_device(4800, 0.5)  # device looks awful

    calls = {}

    def fake_verify_batch(items):
        calls["n"] = len(items)
        return [True] * len(items)

    from cometbft_tpu.ops import ed25519 as ed

    monkeypatch.setattr(ed, "verify_batch", fake_verify_batch)
    old = crypto_batch._default_backend
    old_min = crypto_batch._MIN_TPU_BATCH
    crypto_batch.set_default_backend("tpu")
    crypto_batch.set_min_tpu_batch(1)
    try:
        v = crypto_batch.create_batch_verifier()
        p = Ed25519PrivKey.generate()
        v.add(p.pub_key(), b"m", p.sign(b"m"))
        ok, _ = v.verify()
        assert ok and calls["n"] == 1
        assert crypto_batch.LAST_ROUTE["path"] == "device"
    finally:
        crypto_batch.set_min_tpu_batch(old_min)
        crypto_batch.set_default_backend(old)


def test_exploration_heals_poisoned_flat_cost():
    """A 1-10s recompile wall that slips past the first-sample filter
    inflates flat_s; periodic exploration must route a batch to the
    device anyway so a healthy sample can pull the estimate back."""
    c = _Calibration()
    c.observe_device(4800, 0.1)       # healthy first sample
    c.observe_device(4800, 3.0)       # per-shape recompile slips in
    assert not c.device_wins(4800), "poisoned estimate routes host"
    # every EXPLORE_EVERY'th eligible host-routed batch explores
    explored = [c.should_explore() for _ in range(c.EXPLORE_EVERY)]
    assert explored.count(True) == 1 and explored[-1] is True
    # each explored dispatch lands a healthy wall; the EWMA (alpha
    # 0.4) converges back within a handful of explore cycles
    cycles = 0
    while not c.device_wins(4800):
        cycles += 1
        assert cycles <= 10, "exploration failed to heal the estimate"
        while not c.should_explore():
            pass
        c.observe_device(4800, 0.11)
    assert 1 <= cycles <= 10
    # device traffic resets the streak
    c.note_device_used()
    assert not c.should_explore()


def test_async_seam_feeds_calibration(monkeypatch):
    """BENCH_r05 first run: commit150's auto leg routed a 150-sig
    commit to the device at 10x the host wall — the async seam (the
    one verify_commit_light actually takes) never fed the EWMA, so
    the optimistic flat-cost seed was never corrected. verify_async's
    readiness watcher must observe the dispatch wall."""
    import time

    monkeypatch.setattr(crypto_batch, "calibration", _Calibration())
    cal = crypto_batch.calibration

    class FakeHandle:
        def wait(self):
            return self

        def wait_fetch(self):
            # the watcher observes via a minimal result fetch; a real
            # round trip always costs more than the calibration's
            # wall floor
            time.sleep(0.002)
            return self

        def result(self):
            return [True] * 150

    from cometbft_tpu.ops import ed25519 as ed

    monkeypatch.setattr(
        ed, "verify_batch_async", lambda items: FakeHandle()
    )
    old = crypto_batch._default_backend
    old_min = crypto_batch._MIN_TPU_BATCH
    crypto_batch.set_default_backend("tpu")
    crypto_batch.set_min_tpu_batch(1)  # force the device route
    try:
        v = crypto_batch.create_batch_verifier()
        privs = [Ed25519PrivKey.generate() for _ in range(150)]
        for i, p in enumerate(privs):
            m = b"async|%d" % i
            v.add(p.pub_key(), m, p.sign(m))
        pending = v.verify_async()
        ok, verdicts = pending.result()
        assert ok and len(verdicts) == 150
        # the watcher thread races result(); poll briefly
        deadline = time.time() + 2.0
        while cal.device_samples == 0 and time.time() < deadline:
            time.sleep(0.005)
        assert cal.device_samples == 1, (
            "readiness watcher never fed the device EWMA"
        )
    finally:
        crypto_batch.set_min_tpu_batch(old_min)
        crypto_batch.set_default_backend(old)


def test_result_time_overlap_does_not_poison_flat_cost(monkeypatch):
    """The watcher observes READINESS, not result() latency: a caller
    that sits on the handle for seconds of host work (the replay
    pipeline) must not inflate the EWMA and flip bulk windows to
    host."""
    import time

    monkeypatch.setattr(crypto_batch, "calibration", _Calibration())
    cal = crypto_batch.calibration

    class FakeHandle:
        def wait(self):
            return self  # device ready ~instantly

        def wait_fetch(self):
            time.sleep(0.002)  # ~instant, but a genuine round trip
            return self

        def result(self):
            return [True] * 150

    from cometbft_tpu.ops import ed25519 as ed

    monkeypatch.setattr(
        ed, "verify_batch_async", lambda items: FakeHandle()
    )
    old = crypto_batch._default_backend
    old_min = crypto_batch._MIN_TPU_BATCH
    crypto_batch.set_default_backend("tpu")
    crypto_batch.set_min_tpu_batch(1)
    try:
        v = crypto_batch.create_batch_verifier()
        privs = [Ed25519PrivKey.generate() for _ in range(150)]
        for i, p in enumerate(privs):
            m = b"late|%d" % i
            v.add(p.pub_key(), m, p.sign(m))
        pending = v.verify_async()
        deadline = time.time() + 2.0
        while cal.device_samples == 0 and time.time() < deadline:
            time.sleep(0.005)
        assert cal.device_samples == 1
        flat_after_ready = cal.flat_s
        time.sleep(0.2)  # caller overlaps host work before resolving
        pending.result()
        assert cal.device_samples == 1, "result() must not re-observe"
        assert cal.flat_s == flat_after_ready, (
            "overlapped resolution leaked into the EWMA"
        )
    finally:
        crypto_batch.set_min_tpu_batch(old_min)
        crypto_batch.set_default_backend(old)
