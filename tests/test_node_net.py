"""Full-node networking tests: real consensus over the real p2p stack
(TCP loopback / in-memory transports, encrypted + multiplexed), late
nodes catching up via blocksync net reactor, tx gossip via the mempool
reactor. Reference analog: consensus/reactor_test.go nets via
p2p.MakeConnectedSwitches."""

import asyncio

import pytest

from cometbft_tpu.config.config import test_config as make_test_cfg
from cometbft_tpu.node.inprocess import make_genesis
from cometbft_tpu.node.node import Node

N_VALS = 4


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def _mk_node(gen, pv, i, blocksync=False, adaptive=False):
    cfg = make_test_cfg(".")
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.base.moniker = f"node{i}"
    cfg.blocksync.enable = blocksync
    cfg.blocksync.adaptive_sync = adaptive
    if not blocksync:
        cfg.blocksync.enable = False
    return Node(cfg, gen, privval=pv)


async def _connect_all(nodes):
    for i, a in enumerate(nodes):
        for b in nodes[i + 1:]:
            await a.dial(b.listen_addr)
    for n in nodes:
        for _ in range(200):
            if n.switch.num_peers() >= len(nodes) - 1:
                break
            await asyncio.sleep(0.05)


async def _wait_height(nodes, h, timeout=60):
    async def waiter():
        while not all(n.height >= h for n in nodes):
            await asyncio.sleep(0.05)

    await asyncio.wait_for(waiter(), timeout)


def test_consensus_over_tcp_net():
    gen, pvs = make_genesis(N_VALS, chain_id="net-chain")

    async def main():
        nodes = [_mk_node(gen, pv, i) for i, pv in enumerate(pvs)]
        for n in nodes:
            await n.start()
        await _connect_all(nodes)
        await _wait_height(nodes, 3)
        # all nodes agree on block 2
        h2 = {bytes(n.parts.block_store.load_block(2).hash()) for n in nodes}
        assert len(h2) == 1
        for n in nodes:
            await n.stop()

    run(main())


def test_tx_gossip_reaches_blocks():
    gen, pvs = make_genesis(N_VALS, chain_id="txg-chain")

    async def main():
        nodes = [_mk_node(gen, pv, i) for i, pv in enumerate(pvs)]
        for n in nodes:
            await n.start()
        await _connect_all(nodes)
        # submit a tx at node 3 only; it must end up in some block
        nodes[3].parts.mempool.check_tx(b"gossip=works")
        await _wait_height(nodes, 2)

        async def tx_committed():
            while True:
                for n in nodes:
                    for h in range(1, n.height + 1):
                        blk = n.parts.block_store.load_block(h)
                        if blk and b"gossip=works" in blk.data.txs:
                            return h
                await asyncio.sleep(0.05)

        h = await asyncio.wait_for(tx_committed(), 30)
        assert h >= 1
        for n in nodes:
            await n.stop()

    run(main())


def test_late_node_blocksyncs_then_joins_consensus():
    gen, pvs = make_genesis(N_VALS, chain_id="late-chain")

    async def main():
        vals = [_mk_node(gen, pv, i) for i, pv in enumerate(pvs[:3])]
        for n in vals:
            await n.start()
        await _connect_all(vals)
        # 3 of 4 validators have +2/3 (each power 10 of 40)? No: 30/40 OK
        await _wait_height(vals, 4)

        late = _mk_node(gen, pvs[3], 3, blocksync=True)
        await late.start()
        for v in vals:
            await late.dial(v.listen_addr)
        # late node must catch up and then participate in consensus
        target = max(v.height for v in vals) + 3
        await _wait_height([late], target, timeout=90)
        assert late._cs_started
        # its blocks match the others
        blk = late.parts.block_store.load_block(2)
        assert bytes(blk.hash()) == bytes(
            vals[0].parts.block_store.load_block(2).hash()
        )
        for n in vals + [late]:
            await n.stop()

    run(main())
