"""Fork-feature tests: app-side mempool gossip, autopool scaling,
light RPC proxy (reference app_mempool/app_reactor, internal/autopool,
light/proxy)."""

import asyncio

import aiohttp
import pytest

from cometbft_tpu.config.config import test_config as make_test_cfg
from cometbft_tpu.node.inprocess import make_genesis
from cometbft_tpu.node.node import Node
from cometbft_tpu.utils.autopool import AutoPool


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def test_app_mempool_net_commits_txs():
    """Nodes with the app-owned mempool: tx submitted at one node is
    gossiped, stored by the APP, reaped into a block network-wide."""
    gen, pvs = make_genesis(3, chain_id="appmem-chain")

    async def main():
        from cometbft_tpu.models.kvstore import AppMempoolKVStore

        nodes = []
        for i, pv in enumerate(pvs):
            cfg = make_test_cfg(".")
            cfg.base.moniker = f"node{i}"
            cfg.blocksync.enable = False
            cfg.mempool.type_ = "app"
            nodes.append(
                Node(cfg, gen, privval=pv, app=AppMempoolKVStore())
            )
        for n in nodes:
            await n.start()
        for i, a in enumerate(nodes):
            for b in nodes[i + 1:]:
                await a.dial(b.listen_addr)
        # submit through the reactor's local path (RPC equivalent)
        nodes[1].mempool_reactor.submit_local(b"appmem=works")

        async def committed():
            while True:
                for n in nodes:
                    for h in range(1, n.height + 1):
                        blk = n.parts.block_store.load_block(h)
                        if blk and b"appmem=works" in blk.data.txs:
                            return True
                await asyncio.sleep(0.05)

        assert await asyncio.wait_for(committed(), 30)
        # the app answers queries for the committed tx (node 2 may
        # apply the block a moment after the first node commits)
        from cometbft_tpu.abci import types as abci

        async def queryable():
            while True:
                res = nodes[2].parts.proxy.query.query(
                    abci.RequestQuery(path="/store", data=b"appmem")
                )
                if res.value == b"works":
                    return True
                await asyncio.sleep(0.05)

        assert await asyncio.wait_for(queryable(), 15)
        for n in nodes:
            await n.stop()

    run(main())


def test_autopool_scales_up_and_down():
    async def main():
        pool = AutoPool(min_workers=1, max_workers=4)
        pool.start()
        assert pool.size == 1
        gate = asyncio.Event()

        async def slow_job():
            await gate.wait()

        for _ in range(400):
            pool.submit(slow_job)
        # scaler should grow the pool against the backlog
        for _ in range(40):
            if pool.size >= 2:
                break
            await asyncio.sleep(0.1)
        assert pool.size >= 2
        gate.set()
        # drain, then shrink back toward min
        for _ in range(100):
            if pool.queue.qsize() == 0 and pool.size == 1:
                break
            await asyncio.sleep(0.1)
        assert pool.queue.qsize() == 0
        assert pool.size == 1
        assert pool.processed >= 400
        await pool.stop()

    run(main())


def test_light_proxy_serves_verified_data():
    gen, pvs = make_genesis(2, chain_id="lproxy-chain")

    async def main():
        n0 = Node(make_test_cfg("."), gen, privval=pvs[0])
        n1 = Node(make_test_cfg("."), gen, privval=pvs[1])
        await n0.start()
        await n1.start()
        await n0.dial(n1.listen_addr)
        while n0.height < 5:
            await asyncio.sleep(0.05)

        from cometbft_tpu.light import Client, TrustOptions
        from cometbft_tpu.light.http_provider import HTTPProvider
        from cometbft_tpu.light.proxy import LightProxy

        trust = n0.parts.block_store.load_block(1)
        lc = await asyncio.to_thread(
            Client,
            "lproxy-chain",
            TrustOptions(
                period_ns=3600 * 10**9, height=1, hash=trust.hash()
            ),
            HTTPProvider("lproxy-chain", n0.rpc_server.listen_addr),
        )
        proxy = LightProxy(lc, n0.rpc_server.listen_addr)
        await proxy.start("127.0.0.1:0")

        async with aiohttp.ClientSession() as s:
            async with s.get(
                f"http://{proxy.listen_addr}/commit?height=3"
            ) as resp:
                body = await resp.json()
        r = body["result"]
        assert r["verified"] is True
        assert int(r["signed_header"]["header"]["height"]) == 3
        # block route cross-checks primary data against verified header
        async with aiohttp.ClientSession() as s:
            async with s.get(
                f"http://{proxy.listen_addr}/block?height=3"
            ) as resp:
                body = await resp.json()
        assert body["result"]["verified"] is True
        await proxy.stop()
        await n0.stop()
        await n1.stop()

    run(main())
