"""Differential tests: JAX limb field arithmetic vs python big ints.

Layout convention: limb axis first, batch last — shape (20, N).
"""

import random

import jax
import numpy as np
import jax.numpy as jnp

from cometbft_tpu.ops import fe25519 as fe

import pytest

pytestmark = [pytest.mark.tpu, pytest.mark.slow]  # tpu implies slow: keeps the `-m 'not slow'` fast lane kernel-free

P = fe.P
rng = random.Random(1234)


def rand_ints(n):
    vals = [0, 1, 2, P - 1, P - 2, P, P + 1, 2 * P - 1, (1 << 255) - 1]
    while len(vals) < n:
        vals.append(rng.randrange(0, 1 << 256))
    return vals[:n]


def limbs_of(vals):
    return fe.unstack(
        jnp.asarray(np.stack([fe.to_limbs(v) for v in vals], axis=1))
    )


def check_all(got_limbs, want_ints):
    got = np.asarray(fe.stack(got_limbs))
    for i, w in enumerate(want_ints):
        assert fe.from_limbs(got[:, i]) == w % P, (
            f"lane {i}: got {fe.from_limbs(got[:, i])} want {w % P}"
        )


def test_roundtrip():
    vals = rand_ints(16)
    check_all(limbs_of(vals), vals)


def test_add_sub_mul_square():
    vals_a = rand_ints(32)
    vals_b = list(reversed(rand_ints(32)))
    a, b = limbs_of(vals_a), limbs_of(vals_b)
    check_all(fe.add(a, b), [x + y for x, y in zip(vals_a, vals_b)])
    check_all(fe.sub(a, b), [x - y for x, y in zip(vals_a, vals_b)])
    check_all(fe.neg(a), [-x for x in vals_a])
    check_all(fe.mul(a, b), [x * y for x, y in zip(vals_a, vals_b)])
    check_all(fe.square(a), [x * x for x in vals_a])
    check_all(fe.mul_scalar(a, 121666), [x * 121666 for x in vals_a])


def test_mul_chains_stay_bounded():
    # repeated dependent muls must keep limbs in a range where the
    # convolution cannot overflow int32
    vals = rand_ints(8)
    a = limbs_of(vals)
    mulj = jax.jit(fe.mul)
    acc_limbs = a
    acc_int = list(vals)
    for _ in range(30):
        acc_limbs = mulj(acc_limbs, a)
        acc_int = [x * y for x, y in zip(acc_int, vals)]
        assert int(jnp.max(jnp.abs(fe.stack(acc_limbs)))) < (1 << 14)
    check_all(acc_limbs, acc_int)


def test_add_then_mul():
    vals_a, vals_b = rand_ints(16), list(reversed(rand_ints(16)))
    a, b = limbs_of(vals_a), limbs_of(vals_b)
    s = fe.add(a, b)
    check_all(fe.mul(s, s), [(x + y) ** 2 for x, y in zip(vals_a, vals_b)])
    d = fe.sub(a, b)
    check_all(fe.mul(d, d), [(x - y) ** 2 for x, y in zip(vals_a, vals_b)])


def test_invert_pow2523():
    vals = [v for v in rand_ints(16) if v % P != 0]
    a = limbs_of(vals)
    check_all(jax.jit(fe.invert)(a), [pow(v, P - 2, P) for v in vals])
    check_all(jax.jit(fe.pow2523)(a), [pow(v, (P - 5) // 8, P) for v in vals])


def test_fuzz_op_sequences():
    """Regression for redundant-representation bugs: random dependent op
    chains must track python ints exactly (caught a dropped 2^520 carry)."""
    import jax

    n = 16
    vals = rand_ints(n)
    a = limbs_of(vals)
    cur_l, cur_i = a, list(vals)
    ops = [
        ("mul", jax.jit(fe.mul), lambda x, y: x * y),
        ("add", fe.add, lambda x, y: x + y),
        ("sub", fe.sub, lambda x, y: x - y),
        ("sq", jax.jit(fe.square), None),
    ]
    hist = []
    for step in range(60):
        name, f_l, f_i = ops[rng.randrange(len(ops))]
        hist.append(name)
        if name == "sq":
            cur_l = f_l(cur_l)
            cur_i = [x * x for x in cur_i]
        else:
            cur_l = f_l(cur_l, a)
            cur_i = [f_i(x, y) for x, y in zip(cur_i, vals)]
        cur_i = [x % P for x in cur_i]
        assert int(jnp.max(jnp.abs(fe.stack(cur_l)))) < (1 << 15)
    check_all(cur_l, cur_i)


def test_predicates():
    vals = [0, P, 2 * P, 1, P - 1, P + 1, 5, 2 * P - 1]
    a = limbs_of(vals)
    z = np.asarray(fe.is_zero(a))
    assert list(z) == [v % P == 0 for v in vals]
    par = np.asarray(fe.parity(a))
    assert list(par) == [(v % P) & 1 for v in vals]
    # negative representations
    b = fe.sub(fe.zero((len(vals),)), a)
    z2 = np.asarray(fe.is_zero(b))
    assert list(z2) == [v % P == 0 for v in vals]
    par2 = np.asarray(fe.parity(b))
    assert list(par2) == [(-v) % P & 1 for v in vals]


def test_from_bytes():
    vals = rand_ints(16)
    raw = np.stack(
        [np.frombuffer(v.to_bytes(32, "little"), np.uint8) for v in vals],
        axis=1,
    )
    limbs, sign = fe.from_bytes_255(jnp.asarray(raw))
    for i, v in enumerate(vals):
        assert (
            fe.from_limbs(np.asarray(limbs)[:, i])
            == (v & ((1 << 255) - 1)) % P
        )
        assert int(sign[i]) == v >> 255
    limbs256 = fe.from_bytes_256(jnp.asarray(raw))
    for i, v in enumerate(vals):
        got = 0
        arr = np.asarray(limbs256)[:, i]
        for j in reversed(range(fe.NLIMBS)):
            got = (got << fe.LIMB_BITS) + int(arr[j])
        assert got == v
