"""Committee-scale complexity plane: loop-domain classification
(ASY117/118/119 behaviors beyond the basic fixtures in
test_bftlint.py), the empirical scaling probe (analysis/scaling.py),
its chaos drain, the CLI satellites (--json / --changed-only),
suppression hygiene, and the hot-path fixes the pass drove
(total_voting_power memo, update indexing, PeerVoteCursor)."""

import io
import json
import re
import textwrap
import tokenize
from pathlib import Path

import pytest

from cometbft_tpu.analysis import analyze_source
from cometbft_tpu.analysis import scaling
from cometbft_tpu.analysis.cli import main as cli_main

REPO_ROOT = Path(__file__).resolve().parents[1]
CONS = "cometbft_tpu/consensus/x.py"


def findings_of(src: str, path: str = CONS):
    return analyze_source(textwrap.dedent(src), path)


def ids_of(src: str, path: str = CONS):
    return sorted({f.rule_id for f in findings_of(src, path)})


# --- loop-domain classification: the callgraph gaps this PR closed ----
# (comprehension/generator loops and zip()/enumerate() destructuring
# used to be invisible to the pass)


def test_comprehension_loop_carries_domain():
    src = """
    class R:
        def __init__(self, validators):
            self.validators = validators
        def receive(self, msg, peer):
            return [v.address for v in self.validators]
    """
    assert "ASY117" in ids_of(src)


def test_generator_expression_loop_carries_domain():
    src = """
    class R:
        def __init__(self, validators):
            self.validators = validators
        def receive(self, msg, peer):
            return sum(v.power for v in self.validators)
    """
    assert "ASY117" in ids_of(src)


def test_zip_destructured_target_carries_domain():
    src = """
    class R:
        def __init__(self, validators, sigs):
            self.validators = validators
            self.sigs = sigs
        def receive(self, msg, peer):
            for v, s in zip(self.validators, self.sigs):
                print(v, s)
    """
    assert "ASY117" in ids_of(src)


def test_enumerate_destructured_target_carries_domain():
    src = """
    class R:
        def __init__(self, validators):
            self.validators = validators
        def receive(self, msg, peer):
            for i, v in enumerate(self.validators):
                print(i, v)
    """
    assert "ASY117" in ids_of(src)


def test_bounded_and_foreign_loops_stay_clean():
    src = """
    class R:
        def receive(self, msg, peer):
            for i in range(3):
                print(i)
            for ch in zip("abc", "def"):
                print(ch)
            for part in msg.parts:
                print(part)
    """
    assert "ASY117" not in ids_of(src)


# --- ASY117: chain payload + suppression sanctioning ------------------


def test_asy117_finding_carries_chain_and_domain_trace():
    src = """
    class R:
        def __init__(self, validators):
            self.validators = validators
        def receive(self, msg, peer):
            self._tally()
        def _tally(self):
            for v in self.validators:
                print(v)
    """
    hits = [f for f in findings_of(src) if f.rule_id == "ASY117"]
    assert hits, "expected an ASY117 finding"
    f = hits[0]
    assert f.chain[0] == "receive" and len(f.chain) >= 2, f.chain
    assert f.domain_trace and "validators" in f.domain_trace[0]
    # --json consumers get the same payload
    doc = f.to_json()
    assert doc["chain"] and doc["domain_trace"]


def test_asy117_suppressed_loop_line_sanctions_the_chain():
    """One justified comment on the LOOP line kills the whole fan of
    chain findings (the ASY114 sanctioned-sink contract)."""
    src = """
    class R:
        def __init__(self, validators):
            self.validators = validators
        def receive(self, msg, peer):
            self._tally()
        def _tally(self):
            for v in self.validators:  # bftlint: disable=ASY117 — once per height, memoized upstream
                print(v)
    """
    assert "ASY117" not in ids_of(src)


# --- ASY118: interprocedural nesting + suppression --------------------


def test_asy118_call_inside_committee_loop_reaching_committee_loop():
    src = """
    from typing import Sequence
    def scan(changes: Sequence[Validator], addr):
        for c in changes:
            if c.address == addr:
                return c
    def update(validators, changes: Sequence[Validator]):
        for v in validators:
            scan(changes, v.address)
    """
    hits = [f for f in findings_of(src) if f.rule_id == "ASY118"]
    assert hits, "expected interprocedural ASY118"


def test_asy118_inner_line_suppression():
    src = """
    from typing import Sequence
    def update(validators, changes: Sequence[Validator]):
        for v in validators:
            for c in changes:  # bftlint: disable=ASY118 — churn sets are tiny in practice, measured by the scaling leg
                print(v, c)
    """
    assert "ASY118" not in ids_of(src)


# --- ASY119: prune detection subtleties -------------------------------


def test_asy119_alias_prune_is_seen():
    """Draining through a local alias (fifo = self._q; fifo.pop(0))
    must count as a prune — the ConsensusState durable-FIFO shape."""
    src = """
    class R:
        def __init__(self):
            self._q = []
        def receive(self, msg, peer):
            self._q.append(msg)
        def drain(self):
            fifo = self._q
            while fifo:
                fifo.pop(0)
    """
    assert "ASY119" not in ids_of(src)


def test_asy119_registration_growth_is_not_hot():
    """Appends only reachable from startup/registration (not from a
    per-message handler) scale with config, not traffic."""
    src = """
    class R:
        def __init__(self):
            self.reactors = []
        def add_reactor(self, r):
            self.reactors.append(r)
    """
    assert "ASY119" not in ids_of(src)


def test_asy119_suppressed_init_line():
    src = """
    class R:
        def __init__(self):
            self.log = []  # bftlint: disable=ASY119 — bounded by validator count, dropped per height
        def receive(self, msg, peer):
            self.log.append(msg)
    """
    assert "ASY119" not in ids_of(src)


# --- suppression hygiene (tier-1) -------------------------------------

_DIRECTIVE = re.compile(
    r"#\s*bftlint:\s*disable(?:-next|-file)?\s*=\s*"
    r"[A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*"
)


def _repo_py_files():
    for sub in ("cometbft_tpu",):
        yield from (REPO_ROOT / sub).rglob("*.py")


def test_every_suppression_carries_a_justification():
    """A bare ``# bftlint: disable=X`` is a mute button; the pass
    requires the WHY on the same comment (>= 15 chars of prose after
    the rule list) so every sanctioned sink is auditable."""
    offenders = []
    for path in _repo_py_files():
        src = path.read_text(encoding="utf-8")
        # real COMMENT tokens only: directive syntax quoted in
        # docstrings/strings (suppress.py's own docs) is not a suppression
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = _DIRECTIVE.search(tok.string)
            if m is None:
                continue
            tail = tok.string[m.end():].strip().strip("—-: ").strip()
            if len(tail) < 15:
                offenders.append(
                    f"{path.relative_to(REPO_ROOT)}:{tok.start[0]}: "
                    f"{tok.string.strip()}"
                )
    assert not offenders, (
        "suppressions without justification:\n" + "\n".join(offenders)
    )


def test_baseline_entries_match_live_findings():
    """Every baseline allowance must still match a live finding — a
    stale entry means the violation was fixed and the ratchet must
    tighten (lint.sh enforces this with --fail-on-stale; this is the
    same check as a plain tier-1 assert)."""
    from cometbft_tpu.analysis import baseline as baseline_mod
    from cometbft_tpu.analysis.engine import run

    bl_path = REPO_ROOT / "tools" / "bftlint_baseline.json"
    bl = baseline_mod.load(str(bl_path))
    findings = run([str(REPO_ROOT / "cometbft_tpu")])
    _, stale = baseline_mod.apply(findings, bl)
    assert not stale, "\n".join(s.render() for s in stale)


# --- CLI satellites ---------------------------------------------------


def test_cli_json_emits_chain_and_domain_trace(tmp_path, capsys):
    bad = tmp_path / "consensus_probe.py"
    bad.write_text(
        textwrap.dedent(
            """
            class R:
                def __init__(self, validators):
                    self.validators = validators
                def receive(self, msg, peer):
                    self._tally()
                def _tally(self):
                    for v in self.validators:
                        print(v)
            """
        )
    )
    # path-scoped rules need an in-scope path: analyze the file via a
    # project rooted at it but report under its real (tmp) path —
    # ASY117 needs the hot-plane prefix, so copy into a shadow tree
    shadow = tmp_path / "cometbft_tpu" / "consensus"
    shadow.mkdir(parents=True)
    (shadow / "x.py").write_text(bad.read_text())
    rc = cli_main(
        [str(tmp_path / "cometbft_tpu"), "--json", "--no-baseline"]
    )
    out = capsys.readouterr().out
    assert rc == 1
    doc = json.loads(out)
    hits = [
        f for f in doc["findings"] if f["rule_id"] == "ASY117"
    ]
    assert hits
    assert hits[0]["chain"] and hits[0]["domain_trace"]


def test_cli_changed_only_scopes_the_report(tmp_path, capsys, monkeypatch):
    """--changed-only filters the REPORT to the git diff, without
    skipping the graph build (the finding below still resolves its
    chain through the whole scanned tree)."""
    from cometbft_tpu.analysis import cli as cli_mod

    shadow = tmp_path / "cometbft_tpu" / "consensus"
    shadow.mkdir(parents=True)
    target = shadow / "x.py"
    target.write_text(
        textwrap.dedent(
            """
            class R:
                def __init__(self, validators):
                    self.validators = validators
                def receive(self, msg, peer):
                    for v in self.validators:
                        print(v)
            """
        )
    )
    args = [str(tmp_path / "cometbft_tpu"), "--no-baseline", "--changed-only"]
    # the scanned file IS in the diff: finding reported
    monkeypatch.setattr(
        cli_mod, "_git_changed_files",
        lambda: {str(target.as_posix())},
    )
    assert cli_main(args) == 1
    capsys.readouterr()
    # the scanned file is NOT in the diff: report is empty
    monkeypatch.setattr(cli_mod, "_git_changed_files", lambda: set())
    assert cli_main(args) == 0
    assert "clean" in capsys.readouterr().out


# --- scaling probe: exponent fitting ----------------------------------


def test_fit_exponent_exact_powers():
    sizes = (4, 16, 64, 128)
    assert scaling.fit_exponent(sizes, [7.0] * 4) == pytest.approx(0.0)
    assert scaling.fit_exponent(
        sizes, [3.0 * n for n in sizes]
    ) == pytest.approx(1.0)
    assert scaling.fit_exponent(
        sizes, [0.5 * n * n for n in sizes]
    ) == pytest.approx(2.0)


def test_synthetic_sites_bracket_their_exponents():
    """Generous brackets: timing noise must never fail tier-1, only a
    wrong complexity CLASS should."""
    sites = {
        "o1": scaling.synthetic_site(0.0, unit=400),
        "on": scaling.synthetic_site(1.0, unit=60),
        "on2": scaling.synthetic_site(2.0, unit=8),
    }
    res = {
        r.site: r
        for r in scaling.run_probe(
            sites=sites, sizes=(4, 16, 48), min_wall_s=0.004
        )
    }
    assert res["o1"].exponent < 0.5, res["o1"]
    assert 0.5 < res["on"].exponent < 1.5, res["on"]
    assert res["on2"].exponent > 1.6, res["on2"]


def test_real_sites_fit_finite_sublinearish_exponents():
    """The four fixed hot-path sites must stay in the linear class at
    small sizes (the bench leg gates the tight 1.2 budget at full
    sizes; tier-1 uses a generous 1.6 class boundary so box noise
    cannot flake the suite)."""
    res = scaling.run_probe(sizes=(4, 16, 48), min_wall_s=0.004)
    assert {r.site for r in res} == {
        "vote_add", "commit_assembly", "gossip_pick", "fanout_publish",
    }
    for r in res:
        assert r.exponent < 1.6, scaling.format_results(res)


def test_injected_quadratic_site_is_flagged_and_drained():
    out = scaling.probe_for_chaos(inject_quadratic=True)
    assert out["injected"] == "chaos.injected_quadratic"
    assert "chaos.injected_quadratic" in out["breaches"]
    drained = scaling.drain_chaos_results()
    planted = [r for r in drained if r.injected]
    assert planted and not planted[0].ok
    assert scaling.injected_result(planted[0])
    # drain empties (net.py folds each run's results exactly once)
    assert scaling.drain_chaos_results() == []


def test_budget_file_loads_and_covers_every_real_site():
    budgets = scaling.load_exponent_budgets()
    for site in scaling.site_names():
        assert site in budgets, f"{site} missing a scaling budget"
        assert 1.0 <= budgets[site] <= scaling.DEFAULT_EXPONENT_BUDGET


def test_minimal_toml_fallback_parses_the_shipped_budgets():
    text = (REPO_ROOT / "tools" / "scaling_budgets.toml").read_text()
    parsed = scaling._parse_budget_toml_minimal(text)
    assert parsed == {
        s: {"max_exponent": b}
        for s, b in scaling.load_exponent_budgets().items()
    }


@pytest.mark.slow
def test_synthetic_exponents_stable_across_repeats():
    """Slow leg: the brackets hold across repeated fits (catching a
    calibration bug that only shows under sustained timing jitter)."""
    for _ in range(3):
        test_synthetic_sites_bracket_their_exponents()


@pytest.mark.slow
def test_chaos_scaling_probe_e2e_flags_injected_quadratic(tmp_path):
    """Chaos e2e: a scheduled scaling_probe with inject_quadratic runs
    mid-schedule under a live 4-node net; the report must carry the
    planted site OVER budget without turning it into a violation."""
    import asyncio

    from cometbft_tpu.chaos.net import run_schedule
    from cometbft_tpu.chaos.schedule import FaultEvent, FaultSchedule

    async def main():
        schedule = FaultSchedule(
            [
                FaultEvent(
                    "scaling_probe", at_height=2, inject_quadratic=True
                ),
                FaultEvent("crash", at_height=3, node=1),
                FaultEvent("restart", after_s=0.5, node=1),
            ]
        )
        report = await run_schedule(
            schedule, seed=1337, base_dir=str(tmp_path)
        )
        planted = [
            r
            for r in report.scaling_results
            if r["injected"] and not r["ok"]
        ]
        assert planted, report.scaling_results
        assert not any(
            "scaling_probe injected" in v for v in report.violations
        ), report.violations

    asyncio.run(asyncio.wait_for(main(), 300))


# --- hot-path fixes the pass drove ------------------------------------


def test_total_voting_power_memo_invalidates_on_churn():
    from cometbft_tpu.analysis.scaling import _committee
    from cometbft_tpu.types.validator_set import Validator

    vs, _, _, _ = _committee(4)
    assert vs.total_voting_power() == 40
    assert vs.total_voting_power() == 40  # memo hit
    # power update drops the memo
    v0 = vs.validators[0]
    vs.update_with_change_set([Validator(v0.pub_key, 25, v0.address)])
    assert vs.total_voting_power() == 55
    # removal drops it too
    vs.update_with_change_set([Validator(v0.pub_key, 0, v0.address)])
    assert vs.total_voting_power() == 30
    # copies carry the memo without sharing future invalidations
    cp = vs.copy()
    assert cp.total_voting_power() == 30


def test_update_with_change_set_indexing_parity():
    """The dict-indexed update (the ASY118 fix) must keep the exact
    reference semantics the next()-scan shape had: updates apply,
    adds land with the -1.125x priority, removals drop."""
    from cometbft_tpu.analysis.scaling import _committee
    from cometbft_tpu.crypto.keys import PubKey
    from cometbft_tpu.types.validator_set import Validator

    vs, _, _, _ = _committee(6)
    before = {v.address: v.voting_power for v in vs.validators}
    a_upd = vs.validators[1]
    a_del = vs.validators[4]
    new_pk = PubKey(bytes([9]) + (77).to_bytes(31, "big"))
    vs.update_with_change_set(
        [
            Validator(a_upd.pub_key, 42, a_upd.address),
            Validator(a_del.pub_key, 0, a_del.address),
            Validator(new_pk, 7),
        ]
    )
    after = {v.address: v.voting_power for v in vs.validators}
    assert after[a_upd.address] == 42
    assert a_del.address not in after
    assert after[new_pk.address()] == 7
    # untouched members keep their power
    for addr, power in before.items():
        if addr not in (a_upd.address, a_del.address):
            assert after[addr] == power
    assert vs.total_voting_power() == sum(after.values())
    # the new member entered with the reference catch-up priority:
    # strictly the lowest in the set (-1.125x total, then avg-shifted)
    new_val = vs.validators[
        [v.address for v in vs.validators].index(new_pk.address())
    ]
    assert all(
        new_val.proposer_priority < v.proposer_priority
        for v in vs.validators
        if v.address != new_val.address
    )


def _cursor_world(n=4):
    from cometbft_tpu.analysis.scaling import _committee
    from cometbft_tpu.consensus.reactor import (
        PeerRoundState,
        PeerVoteCursor,
        _vote_key,
    )
    from cometbft_tpu.types.vote import PRECOMMIT
    from cometbft_tpu.types.vote_set import VoteSet

    valset, votes, chain_id, height = _committee(n)
    precommits = VoteSet(
        chain_id, height, 0, PRECOMMIT, valset, verify_signatures=False
    )

    class _HVS:
        def prevotes(self, r):
            return None

        def precommits(self, r):
            return precommits if r == 0 else None

    class _RS:
        pass

    rs = _RS()
    rs.height, rs.round = height, 0
    rs.votes, rs.last_commit = _HVS(), None
    prs = PeerRoundState(height=height, round=0)
    cur = PeerVoteCursor()
    cur.reset(height)
    return cur, rs, prs, precommits, votes, _vote_key


def test_peer_vote_cursor_delivers_then_retransmits_then_acks():
    cur, rs, prs, precommits, votes, _vote_key = _cursor_world()
    for v in votes[:2]:
        precommits.add_vote(v)
    cur.ingest(rs, prs)
    due = cur.due_votes(prs, now=10.0, budget=16)
    assert {_vote_key(v) for v in due} == {
        _vote_key(v) for v in votes[:2]
    }
    # immediately after sending: nothing due (retransmit window)
    assert cur.due_votes(prs, now=10.1, budget=16) == []
    # window elapsed, still unacked: retransmit
    again = cur.due_votes(prs, now=10.4, budget=16)
    assert len(again) == 2
    # peer acks one: it drops from pending and never resends
    prs.has_votes.add(_vote_key(votes[0]))
    later = cur.due_votes(prs, now=11.0, budget=16)
    assert [_vote_key(v) for v in later] == [_vote_key(votes[1])]
    assert _vote_key(votes[0]) not in cur.pending


def test_peer_vote_cursor_is_incremental_not_rescanning():
    """A tick after steady state reads ZERO log entries — the O(new)
    contract that replaced the O(validators) rescan."""
    cur, rs, prs, precommits, votes, _vote_key = _cursor_world()
    for v in votes:
        precommits.add_vote(v)
        prs.has_votes.add(_vote_key(v))  # peer already has everything
    cur.ingest(rs, prs)
    cur.due_votes(prs, now=1.0, budget=16)
    assert cur.pending == {}  # acked: staged nothing
    read_before = dict(cur._read)
    cur.ingest(rs, prs)  # steady-state tick
    assert cur._read == read_before
    assert cur.due_votes(prs, now=2.0, budget=16) == []


def test_peer_vote_cursor_resets_on_height_advance():
    cur, rs, prs, precommits, votes, _vote_key = _cursor_world()
    precommits.add_vote(votes[0])
    cur.ingest(rs, prs)
    assert cur.pending
    cur.reset(rs.height + 1)
    assert cur.pending == {} and cur._read == {}
    assert cur.height == rs.height + 1


def test_vote_set_log_appends_in_accept_order():
    cur, rs, prs, precommits, votes, _vote_key = _cursor_world()
    precommits.add_vote(votes[2])
    precommits.add_vote(votes[0])
    assert [v.validator_index for v in precommits.vote_log] == [2, 0]
    # duplicates never re-append
    precommits.add_vote(votes[2])
    assert [v.validator_index for v in precommits.vote_log] == [2, 0]
