"""Chaos harness tests: link fault plane determinism (unit), the
seeded 4-node partition/heal + crash/restart schedule with all three
invariant checkers, byzantine-corruption detection, and same-seed
trace reproducibility (the ISSUE 2 acceptance scenarios)."""

import asyncio
import json

import pytest

from cometbft_tpu.chaos import (
    FaultEvent,
    FaultSchedule,
    LinkState,
    LinkTable,
    default_schedule,
    run_schedule,
)
from cometbft_tpu.chaos.links import DROP_PARTITION, PASS


def run(coro, timeout=300):
    return asyncio.run(asyncio.wait_for(coro, timeout))


class FakeConn:
    """Minimal SecretConnection surface recording the wire."""

    def __init__(self):
        self.wire = []
        self.closed = False

    async def write_msg(self, data: bytes) -> int:
        self.wire.append(bytes(data))
        return len(data)

    async def read_chunk(self) -> bytes:
        return b""

    def close(self) -> None:
        self.closed = True


async def _drive(table: LinkTable, n: int, src="a", dst="b"):
    conn = table.wrap(FakeConn(), src, dst)
    for i in range(n):
        await conn.write_msg(bytes([i & 0xFF]) * 8)
    return conn


# --- link plane units ---------------------------------------------------


def test_link_decisions_deterministic_per_seed():
    async def main():
        logs = []
        for _ in range(2):
            t = LinkTable(42, default=LinkState(loss=0.3, duplicate=0.2))
            await _drive(t, 200)
            logs.append(t.decision_log("a", "b"))
        t2 = LinkTable(43, default=LinkState(loss=0.3, duplicate=0.2))
        await _drive(t2, 200)
        assert logs[0] == logs[1], "same seed must replay identically"
        assert logs[0] != t2.decision_log("a", "b"), (
            "different seed should diverge"
        )

    run(main())


def test_link_rng_survives_reconnect():
    """A redialed connection continues the SAME per-link decision
    stream: decisions are indexed by link op count, not connection."""

    async def main():
        t1 = LinkTable(7, default=LinkState(loss=0.5))
        await _drive(t1, 100)
        one = t1.decision_log("a", "b")

        t2 = LinkTable(7, default=LinkState(loss=0.5))
        await _drive(t2, 60)  # first connection
        await _drive(t2, 40)  # reconnect, same link
        assert t2.decision_log("a", "b") == one

    run(main())


def test_partition_blackholes_then_heals():
    async def main():
        t = LinkTable(1)
        inner = FakeConn()
        conn = t.wrap(inner, "a", "b")
        await conn.write_msg(b"before")
        t.partition([["a"], ["b"]])
        assert not t.allow_dial("a", "b")
        await conn.write_msg(b"during")
        t.heal()
        assert t.allow_dial("a", "b")
        await conn.write_msg(b"after")
        assert inner.wire == [b"before", b"after"]
        assert t.decision_log("a", "b") == PASS + DROP_PARTITION + PASS

    run(main())


def test_partition_groups_directional_consistency():
    t = LinkTable(1)
    ids = ["w", "x", "y", "z"]
    t.partition([["w", "x"], ["y", "z"]])
    assert t.allow_dial("w", "x") and t.allow_dial("y", "z")
    for a in ("w", "x"):
        for b in ("y", "z"):
            assert not t.allow_dial(a, b)
            assert not t.allow_dial(b, a)
    # re-partition differently: intra-group links come back up
    t.partition([["w", "y"], ["x", "z"]])
    assert t.allow_dial("w", "y") and not t.allow_dial("w", "x")
    t.heal()
    for a in ids:
        for b in ids:
            if a != b:
                assert t.allow_dial(a, b)


def test_reorder_swaps_and_duplicate_duplicates():
    async def main():
        # reorder=1.0: every write is held then flushed after the next
        t = LinkTable(3, default=LinkState(reorder=1.0))
        inner = FakeConn()
        conn = t.wrap(inner, "a", "b")
        await conn.write_msg(b"m1")  # held
        await conn.write_msg(b"m2")  # m2 delivered, then m1
        assert inner.wire == [b"m2", b"m1"]
        # close drops a pending hold-back (degrades to loss)
        await conn.write_msg(b"m3")
        conn.close()
        assert inner.wire == [b"m2", b"m1"] and inner.closed

        t2 = LinkTable(3, default=LinkState(duplicate=1.0))
        inner2 = FakeConn()
        conn2 = t2.wrap(inner2, "a", "b")
        await conn2.write_msg(b"d1")
        assert inner2.wire == [b"d1", b"d1"]

    run(main())


def test_latency_draws_deterministic():
    async def main():
        delays = []
        real_sleep = asyncio.sleep
        for _ in range(2):
            t = LinkTable(11, default=LinkState(latency_s=0.001,
                                                jitter_s=0.002))
            conn = t.wrap(FakeConn(), "a", "b")
            got = []
            orig = asyncio.sleep

            async def spy(d):
                got.append(round(d, 9))
                await real_sleep(0)

            asyncio.sleep = spy
            try:
                for i in range(50):
                    await conn.write_msg(b"x")
            finally:
                asyncio.sleep = orig
            delays.append(got)
        assert delays[0] == delays[1]
        assert all(0.001 <= d <= 0.003 for d in delays[0])

    run(main())


def test_fuzz_composes_with_link_plane():
    """The point fuzzer (p2p/fuzz.py) layers under the link plane,
    sharing the link's deterministic stream."""
    from cometbft_tpu.p2p.fuzz import FuzzConnConfig

    async def main():
        counts = []
        for _ in range(2):
            cfg = FuzzConnConfig(enable=True, prob_drop_rw=0.5)
            t = LinkTable(5, fuzz_config=cfg)
            inner = FakeConn()
            conn = t.wrap(inner, "a", "b")
            for i in range(100):
                await conn.write_msg(b"z")
            counts.append(len(inner.wire))
        assert counts[0] == counts[1]
        assert 10 < counts[0] < 90  # fuzzer actually dropped some

    run(main())


# --- schedule -----------------------------------------------------------


def test_schedule_json_roundtrip_and_validation():
    sched = default_schedule(byzantine_node=2)
    again = FaultSchedule.from_json(sched.to_json())
    assert again == sched
    assert json.loads(sched.to_json())[0]["action"] == "partition"

    with pytest.raises(ValueError):
        FaultEvent("explode", at_height=1)
    with pytest.raises(ValueError):
        FaultEvent("heal")  # no trigger
    with pytest.raises(ValueError):
        FaultEvent("heal", at_height=1, after_s=1.0)  # two triggers
    with pytest.raises(ValueError):
        FaultEvent("crash", at_height=1)  # no node
    with pytest.raises(ValueError):
        FaultEvent("set_link", at_height=1, src=0)  # missing dst/link
    with pytest.raises(ValueError):
        FaultEvent("partition", at_height=1)  # no groups


# --- the acceptance scenarios (real 4-node nets) ------------------------


def test_partition_heal_crash_schedule_invariants_and_reproducibility(
    tmp_path,
):
    """Seeded partition/heal + crash/restart run passes agreement,
    liveness and WAL-replay checks — and a second run with the same
    seed reproduces the identical fault trace."""

    async def main():
        r1 = await run_schedule(
            default_schedule(), seed=42, base_dir=str(tmp_path / "a")
        )
        assert r1.ok, r1.format()
        assert r1.wal_checks == 1  # the crash/restart was verified
        assert [t["action"] for t in r1.trace] == [
            "partition", "heal", "crash", "restart",
        ]
        # every surviving node marched past the schedule
        assert all(h >= 5 for h in r1.final_heights.values())
        # the partition actually dropped traffic
        assert any(
            c.get("P", 0) > 0 for c in r1.link_decisions.values()
        )

        r2 = await run_schedule(
            default_schedule(), seed=42, base_dir=str(tmp_path / "b")
        )
        assert r2.ok, r2.format()
        assert r2.trace == r1.trace, "same seed must reproduce the trace"

    run(main())


def test_byzantine_commit_corruption_is_detected(tmp_path):
    """The same schedule plus an injected byzantine commit corruption
    MUST be flagged as an agreement violation — this validates the
    checker itself (a checker that cannot flag an injected fork proves
    nothing)."""

    async def main():
        report = await run_schedule(
            default_schedule(byzantine_node=2),
            seed=42,
            base_dir=str(tmp_path),
        )
        assert not report.ok
        assert any("agreement" in v for v in report.violations), (
            report.violations
        )
        byz = [t for t in report.trace if t["action"] == "byzantine"]
        assert byz and byz[0]["node"] == "n2" and byz[0]["tamper"]

    run(main())


def test_dead_network_is_a_liveness_violation_not_a_hang(tmp_path):
    """A schedule that crashes every node must terminate with a
    liveness violation — not hang on an unreachable at_height trigger,
    and not vacuously pass the liveness check over zero nodes."""

    async def main():
        schedule = FaultSchedule(
            [FaultEvent("crash", at_height=1, node=i) for i in range(4)]
            # unreachable on a dead net: must be flagged, not waited on
            + [FaultEvent("heal", at_height=99)]
        )
        report = await run_schedule(
            schedule, seed=13, base_dir=str(tmp_path), liveness_bound_s=5.0
        )
        assert not report.ok
        assert any("liveness" in v for v in report.violations), (
            report.violations
        )
        # the report still carries the replay contract
        assert [t["action"] for t in report.trace] == ["crash"] * 4

    run(main(), timeout=120)


@pytest.mark.slow
def test_chaos_soak_lossy_links_and_split_brain(tmp_path):
    """Longer soak: message loss + latency on every link, a 2-2 split
    (halts the chain — healed on a time trigger), a second crash cycle.
    Invariants must still hold."""

    async def main():
        schedule = FaultSchedule(
            [
                FaultEvent(
                    "set_link",
                    at_height=1,
                    src=0,
                    dst=3,
                    link={"loss": 0.1, "latency_s": 0.005,
                          "jitter_s": 0.01},
                ),
                FaultEvent(
                    "partition", at_height=3, groups=[[0, 1], [2, 3]]
                ),
                FaultEvent("heal", after_s=3.0),
                FaultEvent("crash", at_height=5, node=3),
                FaultEvent("restart", after_s=1.0, node=3),
                FaultEvent("crash", after_s=1.0, node=0),
                FaultEvent("restart", after_s=1.0, node=0),
            ]
        )
        report = await run_schedule(
            schedule,
            seed=77,
            base_dir=str(tmp_path),
            liveness_bound_s=120.0,
        )
        assert report.ok, report.format()
        assert report.wal_checks == 2

    run(main(), timeout=600)
