"""Statesync end-to-end: a fresh node restores an app snapshot
(light-verified against the source net's RPC), blocksyncs the tail,
and follows the chain (reference analog: statesync/syncer_test.go +
e2e statesync nodes)."""

import asyncio

from cometbft_tpu.config.config import test_config as make_test_cfg
from cometbft_tpu.node.inprocess import make_genesis
from cometbft_tpu.node.node import Node

N_VALS = 3


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def test_statesync_bootstrap_then_follow():
    gen, pvs = make_genesis(N_VALS, chain_id="ss-chain")

    async def main():
        from cometbft_tpu.models.kvstore import KVStoreApplication

        vals = []
        for i, pv in enumerate(pvs):
            cfg = make_test_cfg(".")
            cfg.base.moniker = f"val{i}"
            cfg.blocksync.enable = False
            # first-block commit on a freshly-dialed contended net can
            # exceed the 10s default; a timeout here must not
            # masquerade as a CheckTx rejection below
            cfg.rpc.timeout_broadcast_tx_commit_s = 30.0
            vals.append(
                Node(
                    cfg, gen, privval=pv,
                    app=KVStoreApplication(prove=True),
                )
            )
        for n in vals:
            await n.start()
        for i, a in enumerate(vals):
            for b in vals[i + 1:]:
                await a.dial(b.listen_addr)
        # land a tx BEFORE the first snapshot so the restored state
        # carries a provable key
        import aiohttp

        async with aiohttp.ClientSession() as s:
            async with s.get(
                f"http://{vals[0].rpc_server.listen_addr}"
                "/broadcast_tx_commit?tx=0x" + (b"ss=snap").hex()
            ) as resp:
                body = await resp.json()
        assert "error" not in body or not body["error"], body
        r = body.get("result") or {}
        assert r.get("check_tx", {}).get("code", 1) == 0, r
        # the key must land BEFORE the height-10 snapshot, or the
        # restored-state proof below would silently test ordinary
        # blocksync replay instead
        assert int(r["height"]) < 10, r
        # kvstore snapshots every 10 heights; wait for one + margin
        while vals[0].height < 13:
            await asyncio.sleep(0.05)

        trust = vals[0].parts.block_store.load_block(1)
        cfg = make_test_cfg(".")
        cfg.base.moniker = "statesyncer"
        cfg.statesync.enable = True
        cfg.statesync.rpc_servers = [
            vals[0].rpc_server.listen_addr,
            vals[1].rpc_server.listen_addr,
        ]
        cfg.statesync.trust_height = 1
        cfg.statesync.trust_hash = bytes(trust.hash()).hex()
        cfg.statesync.discovery_time_s = 10.0
        cfg.blocksync.enable = True
        fresh = Node(
            cfg, gen, privval=None, app=KVStoreApplication(prove=True)
        )
        await fresh.start()
        for v in vals:
            await fresh.dial(v.listen_addr)

        # must statesync (skipping early blocks), then follow the tip
        target = vals[0].height + 3
        for _ in range(1200):
            if fresh.height >= target:
                break
            await asyncio.sleep(0.1)
        assert fresh.height >= target, f"stuck at {fresh.height}"
        # early blocks were NEVER replayed: store base is post-snapshot
        assert fresh.parts.block_store.base() > 1
        # app state converged with the network
        h = fresh.height
        assert bytes(
            fresh.parts.block_store.load_block(h).hash()
        ) == bytes(vals[0].parts.block_store.load_block(h).hash())
        # the snapshot-RESTORED app still serves verifiable proofs:
        # the pre-snapshot key proves against the consensus-certified
        # AppHash of query_height+1 (the exact light-proxy check)
        from cometbft_tpu.abci import types as abci_t
        from cometbft_tpu.crypto import merkle

        res = fresh.parts.app.query(
            abci_t.RequestQuery(data=b"ss", path="/store", prove=True)
        )
        assert res.code == 0 and res.value == b"snap"
        while fresh.parts.block_store.height() < res.height + 1:
            await asyncio.sleep(0.05)
        want_hash = fresh.parts.block_store.load_block(
            res.height + 1
        ).header.app_hash
        merkle.ProofRuntime().verify_value(
            merkle.decode_proof_ops(res.proof_ops),
            want_hash,
            b"ss",
            b"snap",
        )
        for n in vals + [fresh]:
            await n.stop()

    run(main(), timeout=240)


def test_statesync_adaptive_handoff():
    """statesync -> adaptive blocksync: verified blocks are ingested
    straight into the (freshly started) consensus state machine."""
    gen, pvs = make_genesis(N_VALS, chain_id="ssa-chain")

    async def main():
        vals = []
        for i, pv in enumerate(pvs):
            cfg = make_test_cfg(".")
            cfg.base.moniker = f"val{i}"
            cfg.blocksync.enable = False
            vals.append(Node(cfg, gen, privval=pv))
        for n in vals:
            await n.start()
        for i, a in enumerate(vals):
            for b in vals[i + 1:]:
                await a.dial(b.listen_addr)
        while vals[0].height < 13:
            await asyncio.sleep(0.05)

        trust = vals[0].parts.block_store.load_block(1)
        cfg = make_test_cfg(".")
        cfg.base.moniker = "adaptive-ss"
        cfg.statesync.enable = True
        cfg.statesync.rpc_servers = [vals[0].rpc_server.listen_addr]
        cfg.statesync.trust_height = 1
        cfg.statesync.trust_hash = bytes(trust.hash()).hex()
        cfg.statesync.discovery_time_s = 10.0
        cfg.blocksync.enable = True
        cfg.blocksync.adaptive_sync = True
        fresh = Node(cfg, gen, privval=None)
        await fresh.start()
        for v in vals:
            await fresh.dial(v.listen_addr)

        target = vals[0].height + 3
        for _ in range(1200):
            if fresh.height >= target:
                break
            await asyncio.sleep(0.1)
        assert fresh.height >= target, f"stuck at {fresh.height}"
        assert fresh._cs_started  # consensus was live during sync
        assert fresh.parts.block_store.base() > 1
        for n in vals + [fresh]:
            await n.stop()

    run(main())


def test_statesync_failure_is_fatal():
    """Unreachable RPC servers: the node must stop, not idle."""
    gen, pvs = make_genesis(1, chain_id="ssf-chain")

    async def main():
        cfg = make_test_cfg(".")
        cfg.statesync.enable = True
        cfg.statesync.rpc_servers = ["127.0.0.1:1"]  # nothing there
        cfg.statesync.trust_height = 1
        cfg.statesync.trust_hash = "ab" * 32
        cfg.statesync.discovery_time_s = 1.0
        node = Node(cfg, gen, privval=None)
        await node.start()
        for _ in range(200):
            if node.statesync_error is not None:
                break
            await asyncio.sleep(0.1)
        assert node.statesync_error is not None
        assert not node._cs_started
        await node.stop()

    run(main())


def test_bootstrap_state_offline(tmp_path):
    """Offline statesync (reference node.BootstrapState): seed an empty
    home's stores with light-verified state, then start the node and
    watch it blocksync from that height instead of genesis."""
    gen, pvs = make_genesis(N_VALS, chain_id="bs-chain")

    async def main():
        vals = []
        for i, pv in enumerate(pvs):
            cfg = make_test_cfg(".")
            cfg.base.moniker = f"val{i}"
            cfg.blocksync.enable = False
            vals.append(Node(cfg, gen, privval=pv))
        for n in vals:
            await n.start()
        for i, a in enumerate(vals):
            for b in vals[i + 1:]:
                await a.dial(b.listen_addr)
        while vals[0].height < 8:
            await asyncio.sleep(0.05)

        from cometbft_tpu.node.bootstrap import bootstrap_state

        trust = vals[0].parts.block_store.load_block(1)
        cfg = make_test_cfg(str(tmp_path))
        cfg.base.db_backend = "sqlite"  # must persist across processes
        cfg.base.moniker = "bootstrapped"
        cfg.statesync.rpc_servers = [vals[0].rpc_server.listen_addr]
        cfg.statesync.trust_height = 1
        cfg.statesync.trust_hash = bytes(trust.hash()).hex()
        target_h = 5
        h = await asyncio.to_thread(
            bootstrap_state, cfg, gen, str(tmp_path), target_h
        )
        assert h == target_h
        # re-running against the now-populated store must refuse
        import pytest as _pytest

        with _pytest.raises(RuntimeError, match="refusing"):
            await asyncio.to_thread(
                bootstrap_state, cfg, gen, str(tmp_path), target_h
            )

        # node starts from the bootstrapped state and catches up
        cfg2 = make_test_cfg(str(tmp_path))
        cfg2.base.db_backend = cfg.base.db_backend
        cfg2.statesync.enable = False
        cfg2.blocksync.enable = True
        node = Node(cfg2, gen, privval=None, home=str(tmp_path))
        await node.start()
        for v in vals:
            await node.dial(v.listen_addr)
        target = vals[0].height + 2
        for _ in range(600):
            if node.height >= target:
                break
            await asyncio.sleep(0.1)
        assert node.height >= target, f"stuck at {node.height}"
        # blocks before the bootstrap height were never fetched
        assert node.parts.block_store.load_block(2) is None
        for n in vals + [node]:
            await n.stop()

    run(main())
