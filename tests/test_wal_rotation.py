"""Rotating WAL group tests (reference libs/autofile/group.go:65,265 +
consensus/wal.go:92): size-capped head rotation, group total cap,
cross-file SearchForEndHeight, repair, and crash-mid-rotation
recovery."""

import os
import struct
import subprocess
import sys

import pytest

from cometbft_tpu.consensus.wal import (
    MSG_END_HEIGHT,
    MSG_VOTE,
    WAL,
    WALMessage,
    _group_files,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_heights(w: WAL, heights, votes_per_height=20, size=64):
    for h in heights:
        for r in range(votes_per_height):
            w.write(
                WALMessage(
                    kind=MSG_VOTE, height=h, round=0, data=b"v" * size
                )
            )
        w.write_end_height(h)


def test_rotation_and_cross_file_search(tmp_path):
    path = str(tmp_path / "wal")
    w = WAL(path, head_size_limit=1024)
    _write_heights(w, range(1, 21))
    w.close()

    files = _group_files(path)
    assert len(files) > 3, "head must have rotated several times"
    assert files[-1] == path and all(
        f.startswith(path + ".") for f in files[:-1]
    )

    msgs = list(WAL.iter_messages(path))
    # all records survive rotation, in order
    assert sum(1 for m in msgs if m.kind == MSG_END_HEIGHT) == 20
    ends = [m.height for m in msgs if m.kind == MSG_END_HEIGHT]
    assert ends == list(range(1, 21))

    # end-height markers findable across file boundaries
    for h in (1, 7, 19):
        idx = WAL.search_for_end_height(path, h)
        assert idx is not None
        assert msgs[idx - 1].kind == MSG_END_HEIGHT
        assert msgs[idx - 1].height == h
    tail = list(WAL.messages_after_end_height(path, 19))
    assert tail and tail[-1].height == 20


def test_total_size_cap_deletes_oldest(tmp_path):
    path = str(tmp_path / "wal")
    w = WAL(path, head_size_limit=1024, total_size_limit=4096)
    _write_heights(w, range(1, 31))
    w.close()
    files = _group_files(path)
    total = sum(os.path.getsize(f) for f in files)
    # cap enforced (head itself never deleted, so allow one head slack)
    assert total <= 4096 + 2048
    # the oldest heights are gone, newest survive
    msgs = list(WAL.iter_messages(path))
    ends = [m.height for m in msgs if m.kind == MSG_END_HEIGHT]
    assert ends[-1] == 30
    assert 1 not in ends


def test_truncate_corrupt_tail_cross_file(tmp_path):
    path = str(tmp_path / "wal")
    w = WAL(path, head_size_limit=1024)
    _write_heights(w, range(1, 11))
    w.close()
    files = _group_files(path)
    assert len(files) >= 3
    victim = files[1]
    keep_prefix = list(WAL._iter_file(files[0]))
    victim_msgs = list(WAL._iter_file(victim))

    # corrupt the middle of the second file
    with open(victim, "r+b") as f:
        f.seek(os.path.getsize(victim) // 2)
        f.write(b"\xff" * 8)

    # iteration stops at the corruption (later files are suspect)
    readable = list(WAL.iter_messages(path))
    assert len(readable) < len(keep_prefix) + len(victim_msgs) + 1

    n = WAL.truncate_corrupt_tail(path)
    assert n == len(readable)
    msgs = list(WAL.iter_messages(path))
    assert len(msgs) == n
    # earlier file untouched, later files removed, head recreated
    assert list(WAL._iter_file(files[0])) == keep_prefix
    remaining = _group_files(path)
    assert files[2] not in remaining
    assert path in remaining

    # group still writable after repair
    w = WAL(path, head_size_limit=1024)
    w.write_end_height(999)
    w.close()
    assert WAL.search_for_end_height(path, 999) is not None


@pytest.mark.parametrize("fail_index", [0, 1])
def test_crash_mid_rotation_recovers(tmp_path, fail_index):
    """Kill the process exactly before/after the rotation rename; the
    group must stay readable and writable on restart."""
    path = str(tmp_path / "wal")
    script = f"""
import os
os.environ["FAIL_TEST_INDEX"] = "{fail_index}"
from cometbft_tpu.consensus.wal import WAL, WALMessage, MSG_VOTE
w = WAL({path!r}, head_size_limit=1024)
for h in range(1, 100):
    for r in range(20):
        w.write(WALMessage(kind=MSG_VOTE, height=h, data=b"v"*64))
    w.write_end_height(h)
raise SystemExit("fail point never hit")
"""
    proc = subprocess.run(
        [sys.executable, "-c", script],
        cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 99, proc.stderr

    # whatever hit disk is readable, in order, no duplicates
    msgs = list(WAL.iter_messages(path))
    assert msgs, "pre-crash records must survive"
    ends = [m.height for m in msgs if m.kind == MSG_END_HEIGHT]
    assert ends == sorted(set(ends))

    # restart: the group accepts new writes and rotation proceeds
    w = WAL(path, head_size_limit=1024)
    _write_heights(w, range(1000, 1005))
    w.close()
    assert WAL.search_for_end_height(path, 1004) is not None


def test_record_framing_unchanged(tmp_path):
    """The on-disk record layout stays CRC32+len framed (replay
    compatibility within the group)."""
    path = str(tmp_path / "wal")
    w = WAL(path)
    w.write(WALMessage(kind=MSG_VOTE, height=1, data=b"x"))
    w.close()
    with open(path, "rb") as f:
        crc, ln = struct.unpack(">II", f.read(8))
        payload = f.read(ln)
    import zlib

    assert zlib.crc32(payload) & 0xFFFFFFFF == crc
    assert WALMessage.decode(payload).height == 1
