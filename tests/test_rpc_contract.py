"""RPC contract test: docs/openapi.yaml and the live route table must
stay in sync (reference analog: dredd against rpc/openapi/openapi.yaml,
cmd/contract_tests)."""

import os

import yaml

from cometbft_tpu.rpc import core

WS_ONLY = {"subscribe", "unsubscribe"}  # handled by the WS endpoint


def _spec_methods():
    path = os.path.join(
        os.path.dirname(__file__), "..", "docs", "openapi.yaml"
    )
    with open(path) as f:
        spec = yaml.safe_load(f)
    return {p.lstrip("/") for p in spec["paths"]}


def test_every_route_is_documented():
    documented = _spec_methods()
    missing = set(core.ROUTES) - documented
    assert not missing, f"routes missing from openapi.yaml: {missing}"


def test_every_documented_method_exists():
    documented = _spec_methods()
    phantom = documented - set(core.ROUTES) - WS_ONLY
    assert not phantom, f"openapi.yaml documents unknown methods: {phantom}"


def test_documented_methods_respond():
    """Spot-check the spec against a live node: every documented GET
    endpoint must answer (result or a well-formed JSON-RPC error, not a
    404/500)."""
    import asyncio

    from cometbft_tpu.config.config import test_config
    from cometbft_tpu.node.inprocess import make_genesis
    from cometbft_tpu.node.node import Node

    async def go():
        from aiohttp import ClientSession

        gen, pvs = make_genesis(1, chain_id="contract-chain")
        node = Node(test_config("."), gen, privval=pvs[0])
        await node.start()
        try:
            while node.height < 2:
                await asyncio.sleep(0.05)
            base = f"http://{node.rpc_server.listen_addr}"
            results = {}
            async with ClientSession() as sess:
                for m in sorted(_spec_methods() - WS_ONLY):
                    async with sess.get(f"{base}/{m}") as r:
                        body = await r.json()
                        # contract: HTTP 200 + jsonrpc envelope with
                        # either a result or a structured error
                        results[m] = (
                            r.status,
                            "result" in body or "error" in body,
                        )
            return results
        finally:
            await node.stop()

    results = asyncio.run(asyncio.wait_for(go(), 120))
    bad = {
        m: r for m, r in results.items() if r[0] != 200 or not r[1]
    }
    assert not bad, f"endpoints violating the contract: {bad}"
