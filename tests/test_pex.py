"""PEX / address book tests: discovery of indirect peers, seed-mode
hang-up, unsolicited-response banning (reference p2p/pex tests)."""

import asyncio

from cometbft_tpu.config.config import test_config as make_test_cfg
from cometbft_tpu.node.inprocess import make_genesis
from cometbft_tpu.node.node import Node
from cometbft_tpu.p2p.pex import AddrBook, KnownAddress


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def test_addrbook_basics(tmp_path):
    book = AddrBook(str(tmp_path / "addrbook.json"), our_id="me")
    assert book.add_address("aa@1.2.3.4:1")
    assert not book.add_address("aa@1.2.3.4:1")  # dup
    assert not book.add_address("me@5.6.7.8:1")  # self
    book.mark_good("aa", "aa@1.2.3.4:1")
    book.add_address("bb@2.3.4.5:2", src="aa")
    sel = book.selection()
    assert "aa@1.2.3.4:1" in sel and "bb@2.3.4.5:2" in sel
    book.save()
    book2 = AddrBook(str(tmp_path / "addrbook.json"), our_id="me")
    assert book2.size() == 2
    assert book2.addrs["aa"].is_old


def test_pex_discovers_indirect_peer():
    """A knows only B; B knows C. PEX must connect A to C."""
    gen, pvs = make_genesis(3, chain_id="pex-chain")

    async def main():
        nodes = []
        for i, pv in enumerate(pvs):
            cfg = make_test_cfg(".")
            cfg.base.moniker = f"node{i}"
            cfg.blocksync.enable = False
            cfg.p2p.pex = True
            nodes.append(Node(cfg, gen, privval=pv))
        for n in nodes:
            await n.start()
        a, b, c = nodes
        await b.dial(c.listen_addr)  # B <-> C
        await asyncio.sleep(0.2)
        await a.dial(b.listen_addr)  # A -> B (outbound: requests addrs)
        # crawl interval is 5s; wait for A to find C via the book
        for _ in range(300):
            if c.node_key.node_id in a.switch.peers:
                break
            await asyncio.sleep(0.1)
        assert c.node_key.node_id in a.switch.peers, (
            f"A peers: {list(a.switch.peers)}, "
            f"book: {list(a.addr_book.addrs)}"
        )
        for n in nodes:
            await n.stop()

    run(main())
