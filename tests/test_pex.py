"""PEX / address book tests: discovery of indirect peers, seed-mode
hang-up, unsolicited-response banning (reference p2p/pex tests)."""

import asyncio

from cometbft_tpu.config.config import test_config as make_test_cfg
from cometbft_tpu.node.inprocess import make_genesis
from cometbft_tpu.node.node import Node
from cometbft_tpu.p2p.pex import AddrBook, KnownAddress


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def test_addrbook_basics(tmp_path):
    book = AddrBook(str(tmp_path / "addrbook.json"), our_id="me")
    assert book.add_address("aa@1.2.3.4:1")
    assert not book.add_address("aa@1.2.3.4:1")  # dup
    assert not book.add_address("me@5.6.7.8:1")  # self
    book.mark_good("aa", "aa@1.2.3.4:1")
    book.add_address("bb@2.3.4.5:2", src="aa")
    sel = book.selection()
    assert "aa@1.2.3.4:1" in sel and "bb@2.3.4.5:2" in sel
    book.save()
    book2 = AddrBook(str(tmp_path / "addrbook.json"), our_id="me")
    assert book2.size() == 2
    assert book2.addrs["aa"].is_old


def test_addrbook_bookkeeping_persists(tmp_path):
    """Dial success/failure history must survive a restart: the
    reconnect plane and pick_to_dial's backoff gating key on
    attempts/last_attempt/failures, which previously evaporated
    (save() dropped them)."""
    path = str(tmp_path / "addrbook.json")
    book = AddrBook(path, our_id="me")
    book.add_address("aa@1.2.3.4:1")
    book.mark_attempt("aa")
    book.mark_attempt("aa")
    book.mark_failed("aa")
    # mark_failed with addr creates the entry (persistent peer never
    # PEX-learned still accumulates health history)
    book.mark_failed("cc", "cc@9.9.9.9:3")
    book.save()
    again = AddrBook(path, our_id="me")
    aa = again.addrs["aa"]
    assert aa.attempts == 2
    assert aa.last_attempt > 0
    assert aa.failures == 1
    assert aa.last_failure > 0
    assert again.addrs["cc"].failures == 1
    # a success resets the attempt counter (the bad-address gate) but
    # keeps the flap history
    again.mark_good("aa", "aa@1.2.3.4:1")
    assert again.addrs["aa"].attempts == 0
    assert again.addrs["aa"].failures == 1


def test_addrbook_persisted_attempts_age_out(tmp_path):
    """Forgiveness: a never-connected address that crossed the
    bad-address attempt cap must NOT stay is_bad forever across
    restarts — stale attempt counters reload clean (failure history
    stays for diagnostics), while fresh ones persist."""
    import time as _time

    from cometbft_tpu.p2p.pex import FORGIVE_AFTER_S

    path = str(tmp_path / "addrbook.json")
    book = AddrBook(path, our_id="me")
    book.add_address("aa@h:1")
    book.addrs["aa"].attempts = 99  # crossed MAX_ATTEMPTS, no success
    book.addrs["aa"].failures = 99
    book.addrs["aa"].last_attempt = (
        _time.time() - FORGIVE_AFTER_S - 60
    )
    book.add_address("bb@h:2")
    book.mark_attempt("bb")  # fresh: must survive the reload
    book.save()
    again = AddrBook(path, our_id="me")
    assert again.addrs["aa"].attempts == 0  # forgiven
    assert not again.addrs["aa"].is_bad
    assert again.addrs["aa"].failures == 99  # history kept
    assert again.addrs["bb"].attempts == 1  # fresh: persisted
    # a re-learned NEW address also resets the counter live
    book.addrs["bb"].attempts = 99
    book.add_address("bb@moved:9")
    assert book.addrs["bb"].attempts == 0
    assert book.addrs["bb"].addr == "bb@moved:9"


def test_addrbook_relearned_address_replaces_failing_old_entry():
    """A moved peer must not be shadowed by its stale proven entry:
    while the known address keeps failing, re-learned routing info
    (PEX) replaces it; while it is healthy, it is sticky; and a LIVE
    connection at a new address always wins."""
    book = AddrBook(our_id="me")
    book.add_address("aa@old:1")
    book.mark_good("aa")  # proven -> is_old, addr sticky
    book.add_address("aa@moved:2", src="pex")
    assert book.addrs["aa"].addr == "aa@old:1"  # healthy: sticky
    book.mark_failed("aa")  # conn died / dials failing
    book.add_address("aa@moved:2", src="pex")
    assert book.addrs["aa"].addr == "aa@moved:2"  # failing: re-learn
    # a live conn at yet another address is the strongest evidence
    book.mark_good("aa", "aa@live:3")
    assert book.addrs["aa"].addr == "aa@live:3"
    assert book.addrs["aa"].is_old


def test_addrbook_selection_biases_old_then_new():
    """selection(): OLD (proven) addresses lead, NEW fill the tail,
    bad addresses are excluded (reference GetSelection bias)."""
    book = AddrBook(our_id="me")
    for i in range(6):
        book.add_address(f"new{i}@h:{i}")
    for i in range(3):
        book.add_address(f"old{i}@H:{i}")
        book.mark_good(f"old{i}")
    bad = book.addrs["new0"]
    bad.attempts = 100  # is_bad: many attempts, never a success
    sel = book.selection(limit=6)
    assert "new0@h:0" not in sel
    head = sel[:3]
    assert {a.partition("@")[0] for a in head} == {
        "old0", "old1", "old2",
    }, sel
    assert len(sel) == 6
    # deterministic across shuffles: old always first
    for _ in range(10):
        s = book.selection(limit=6)
        assert all(a.startswith("old") for a in s[:3])


def test_addrbook_pick_to_dial_gates_on_attempt_backoff():
    """pick_to_dial: excludes live/banned ids, bad addresses, and
    addresses attempted too recently (10s * (attempts+1) gate)."""
    import time as _time

    book = AddrBook(our_id="me")
    book.add_address("aa@h:1")
    book.add_address("bb@h:2")
    book.add_address("cc@h:3")
    # aa: attempted just now -> gated out
    book.mark_attempt("aa")
    # bb: attempted long ago -> eligible again
    book.mark_attempt("bb")
    book.addrs["bb"].last_attempt = _time.time() - 120.0
    picks = book.pick_to_dial(exclude={"cc"}, n=10)
    assert picks == ["bb@h:2"]
    # the gate scales with attempt count: 2 attempts => 30s window
    book.addrs["bb"].attempts = 2
    book.addrs["bb"].last_attempt = _time.time() - 25.0
    assert "bb@h:2" not in book.pick_to_dial(exclude=set(), n=10)


def test_pex_discovers_indirect_peer():
    """A knows only B; B knows C. PEX must connect A to C."""
    gen, pvs = make_genesis(3, chain_id="pex-chain")

    async def main():
        nodes = []
        for i, pv in enumerate(pvs):
            cfg = make_test_cfg(".")
            cfg.base.moniker = f"node{i}"
            cfg.blocksync.enable = False
            cfg.p2p.pex = True
            nodes.append(Node(cfg, gen, privval=pv))
        for n in nodes:
            await n.start()
        a, b, c = nodes
        await b.dial(c.listen_addr)  # B <-> C
        await asyncio.sleep(0.2)
        await a.dial(b.listen_addr)  # A -> B (outbound: requests addrs)
        # crawl interval is 5s; wait for A to find C via the book
        for _ in range(300):
            if c.node_key.node_id in a.switch.peers:
                break
            await asyncio.sleep(0.1)
        assert c.node_key.node_id in a.switch.peers, (
            f"A peers: {list(a.switch.peers)}, "
            f"book: {list(a.addr_book.addrs)}"
        )
        for n in nodes:
            await n.stop()

    run(main())
