"""ABCI vote extensions end-to-end (reference ABCI 2.0:
ExtendVote/VerifyVoteExtension at consensus/state.go, ExtendedCommit
persistence store/store.go:481, ExtendedCommitInfo into
PrepareProposal)."""

import asyncio

import pytest

from cometbft_tpu import types as T
from cometbft_tpu.node.inprocess import LocalNet, build_node, make_genesis
from cometbft_tpu.utils import codec


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def test_extended_commit_codec_roundtrip():
    ec = T.ExtendedCommit(
        height=5,
        round=1,
        block_id=T.BlockID(b"\x01" * 32, T.PartSetHeader(1, b"\x02" * 32)),
        extended_signatures=[
            T.ExtendedCommitSig(
                block_id_flag=T.BLOCK_ID_FLAG_COMMIT,
                validator_address=b"\x03" * 20,
                timestamp_ns=123,
                signature=b"\x04" * 64,
                extension=b"ext-data",
                extension_signature=b"\x05" * 64,
            ),
            T.ExtendedCommitSig(),  # absent
        ],
    )
    got = codec.decode_extended_commit(codec.encode_extended_commit(ec))
    assert got == ec
    c = got.to_commit()
    assert c.signatures[0].signature == b"\x04" * 64
    assert not hasattr(c.signatures[0], "extension") or isinstance(
        c.signatures[0], T.CommitSig
    )


def test_net_produces_verified_extensions():
    """4-node net with extensions enabled from height 1: every commit
    carries app-authored extensions, peers' extensions pass signature +
    app verification, and the proposer feeds them to PrepareProposal."""

    async def main():
        gen, pvs = make_genesis(4, chain_id="ext-chain")
        gen.consensus_params.abci.vote_extensions_enable_height = 1
        nodes = [build_node(gen, pv) for pv in pvs]
        net = LocalNet(nodes)
        await net.start()
        await net.wait_for_height(3, timeout=60)
        await net.stop()

        vs = gen.validator_set()
        for n in nodes:
            # extended commits persisted for committed heights
            for h in (1, 2):
                raw = n.block_store.load_extended_commit(h)
                assert raw, f"no extended commit at {h}"
                ec = codec.decode_extended_commit(raw)
                n_ext = 0
                bid_hash = n.block_store.load_block_meta(h).block_id.hash
                for i, s in enumerate(ec.extended_signatures):
                    if not s.for_block():
                        continue
                    assert s.extension.startswith(b"ext|%d|" % h)
                    # extension signature verifies against the valset
                    val = vs.get_by_index(i)
                    v = T.Vote(
                        type_=T.PRECOMMIT,
                        height=h,
                        round=ec.round,
                        block_id=ec.block_id,
                        timestamp_ns=s.timestamp_ns,
                        validator_address=s.validator_address,
                        validator_index=i,
                        extension=s.extension,
                        extension_signature=s.extension_signature,
                    )
                    assert val.pub_key.verify(
                        v.extension_sign_bytes(gen.chain_id),
                        s.extension_signature,
                    )
                    n_ext += 1
                assert n_ext * 3 > vs.size() * 2
            # peers' extensions were app-verified
            assert n.app.extensions_verified > 0

    run(main())


def test_blocksync_transfers_extended_commits():
    """A late blocksync joiner receives + verifies extended commits
    with the blocks (reference blocksync BlockResponse.ExtCommit), so
    it could propose with ExtendedCommitInfo immediately."""
    from cometbft_tpu.config.config import test_config as make_test_cfg
    from cometbft_tpu.node.node import Node

    async def main():
        gen, pvs = make_genesis(2, chain_id="ext-sync")
        gen.consensus_params.abci.vote_extensions_enable_height = 1

        def mk(pv, i, blocksync=False):
            cfg = make_test_cfg(".")
            cfg.p2p.laddr = "tcp://127.0.0.1:0"
            cfg.base.moniker = f"n{i}"
            cfg.blocksync.enable = blocksync
            return Node(cfg, gen, privval=pv)

        vals = [mk(pvs[0], 0), mk(pvs[1], 1)]
        for n in vals:
            await n.start()
        await vals[0].dial(vals[1].listen_addr)

        async def wait(pred, timeout, what):
            dl = asyncio.get_running_loop().time() + timeout
            while asyncio.get_running_loop().time() < dl:
                if pred():
                    return
                await asyncio.sleep(0.05)
            raise TimeoutError(what)

        await wait(lambda: all(n.height >= 3 for n in vals), 60, "h3")

        late = mk(None, 9, blocksync=True)
        await late.start()
        await late.dial(vals[0].listen_addr)
        await late.dial(vals[1].listen_addr)
        await wait(lambda: late.height >= 3, 60, "late sync")

        # EVERY commit path persists the EC (reference
        # SaveBlockWithExtendedCommit): blocksync saves it with each
        # applied block, and the consensus catch-up gossip now ships it
        # in MSG_COMMIT_BLOCK — so the late joiner can itself serve ECs
        # to future joiners at every height it holds
        assert late.height >= 3
        snapshot_h = late.height
        for h in range(1, snapshot_h + 1):
            raw = late.parts.block_store.load_extended_commit(h)
            assert raw, f"no extended commit persisted at height {h}"
            ec = codec.decode_extended_commit(raw)
            assert any(
                s.extension.startswith(b"ext|%d|" % h)
                for s in ec.extended_signatures
                if s.for_block()
            )
        for n in vals + [late]:
            await n.stop()

    run(main())


def test_bad_extension_signature_rejected():
    async def main():
        gen, pvs = make_genesis(2, chain_id="ext-rej")
        gen.consensus_params.abci.vote_extensions_enable_height = 1
        parts = build_node(gen, pvs[0])
        cs = parts.cs
        await cs.start()
        try:
            rs = cs.rs
            pv = pvs[1]
            idx, _ = gen.validator_set().get_by_address(
                pv.pub_key().address()
            )
            bid = T.BlockID(b"\x11" * 32, T.PartSetHeader(1, b"\x22" * 32))
            import time as _t

            vote = T.Vote(
                type_=T.PRECOMMIT,
                height=rs.height,
                round=0,
                block_id=bid,
                timestamp_ns=_t.time_ns(),
                validator_address=pv.pub_key().address(),
                validator_index=idx,
            )
            vote.extension = b"ext|%d|XXXXXXXX" % rs.height
            pv.sign_vote(gen.chain_id, vote)
            # tamper the extension AFTER signing: main sig valid,
            # extension sig missing/invalid
            vote.extension_signature = b"\x00" * 64
            cs._try_add_vote(vote, "peerX")
            assert rs.votes.precommits(0).get_vote(idx) is None

            # missing extension signature entirely is also rejected
            vote2 = T.Vote(
                type_=T.PRECOMMIT,
                height=rs.height,
                round=0,
                block_id=bid,
                timestamp_ns=_t.time_ns(),
                validator_address=pv.pub_key().address(),
                validator_index=idx,
            )
            pv.sign_vote(gen.chain_id, vote2)
            vote2.extension_signature = b""
            cs._try_add_vote(vote2, "peerX")
            assert rs.votes.precommits(0).get_vote(idx) is None
        finally:
            await cs.stop()

    run(main())


def test_blocksync_tolerates_peers_lacking_extended_commits():
    """ADVICE r2 (medium) + ADVICE r3 (low): an honest peer may hold
    blocks WITHOUT their extended commits (it pruned them, or tolerated
    missing ECs while syncing itself). Blocksync must distinguish that
    from a bad EC: retry without banning, then apply bare once every
    reachable peer came back EC-less — but ONLY for historical heights.
    The switch-to-consensus tip is never applied bare (a node that did
    so could neither propose at tip+1 nor serve the EC to later
    joiners); the joiner switches to consensus one block early and
    fetches the tip through consensus catch-up instead."""
    from cometbft_tpu.blocksync.reactor import BlockSyncReactor
    from cometbft_tpu.utils.chaingen import StorePeerClient, make_chain

    async def main():
        gen, pvs = make_genesis(3, chain_id="ext-miss")
        gen.consensus_params.abci.vote_extensions_enable_height = 1
        # chaingen signs plain commits only: the stores hold NO extended
        # commits at any height, exactly the stalling scenario
        src = make_chain(gen, [pv.priv_key for pv in pvs], 10)
        assert src.block_store.load_extended_commit(3) is None

        fresh = build_node(gen, None)
        caught = asyncio.Event()
        reactor = BlockSyncReactor(
            fresh.state,
            fresh.block_exec,
            fresh.block_store,
            on_caught_up=lambda st: caught.set(),
        )
        reactor.pool.set_peer_range(
            "src", StorePeerClient(src), 1, src.block_store.height()
        )
        await reactor.start()
        await asyncio.wait_for(caught.wait(), 60)
        await reactor.stop()
        # historical heights applied bare; the tip (max_peer_height-1,
        # the highest height blocksync can verify) deliberately NOT
        assert fresh.block_store.height() == src.block_store.height() - 2
        # the peer was never banned for lacking ECs
        assert not reactor.pool.banned_peers()

    run(main())


def test_blocksync_requires_distinct_peers_for_bare_apply():
    """ADVICE r3 (low): a single byzantine peer that wins every refetch
    must not force a bare apply while other peers exist — the EC-less
    tolerance counts DISTINCT peers, so the refetch (with the bare
    peer soft-excluded) reaches the honest peer, whose extended commit
    is applied."""
    from cometbft_tpu.blocksync.reactor import BlockSyncReactor
    from cometbft_tpu.utils.chaingen import StorePeerClient, make_chain

    async def main():
        gen, pvs = make_genesis(3, chain_id="ext-distinct")
        gen.consensus_params.abci.vote_extensions_enable_height = 1
        privs = [pv.priv_key for pv in pvs]
        src = make_chain(gen, privs, 10)
        # sign valid extended commits for the generated chain (chaingen
        # itself signs plain commits only)
        addr_to_priv = {p.pub_key().address(): p for p in privs}
        from cometbft_tpu.types.canonical import vote_extension_sign_bytes

        for h in range(1, src.block_store.height() + 1):
            commit = src.block_store.load_seen_commit(h)
            ext_sigs = []
            for s in commit.signatures:
                ext = b"ext|%d|" % h
                esig = addr_to_priv[s.validator_address].sign(
                    vote_extension_sign_bytes(
                        gen.chain_id, h, commit.round, ext
                    )
                )
                ext_sigs.append(
                    T.ExtendedCommitSig(
                        block_id_flag=s.block_id_flag,
                        validator_address=s.validator_address,
                        timestamp_ns=s.timestamp_ns,
                        signature=s.signature,
                        extension=ext,
                        extension_signature=esig,
                    )
                )
            ec = T.ExtendedCommit(
                height=h,
                round=commit.round,
                block_id=commit.block_id,
                extended_signatures=ext_sigs,
            )
            src.block_store.save_extended_commit(
                h, codec.encode_extended_commit(ec)
            )

        class BarePeer(StorePeerClient):
            """Serves the same blocks but stripped of ECs."""

            async def request_block(self, height):
                blk = await super().request_block(height)
                if blk is not None and hasattr(blk, "_ec_bytes"):
                    del blk._ec_bytes
                return blk

        fresh = build_node(gen, None)
        caught = asyncio.Event()
        reactor = BlockSyncReactor(
            fresh.state,
            fresh.block_exec,
            fresh.block_store,
            on_caught_up=lambda st: caught.set(),
        )
        reactor.pool.set_peer_range(
            "bare", BarePeer(src), 1, src.block_store.height()
        )
        reactor.pool.set_peer_range(
            "honest", StorePeerClient(src), 1,
            src.block_store.height(),
        )
        await reactor.start()
        await asyncio.wait_for(caught.wait(), 60)
        await reactor.stop()
        assert (
            fresh.block_store.height() >= src.block_store.height() - 2
        )
        # every applied extension-height block carries its EC (no bare
        # applies happened: the honest peer existed)
        for h in range(1, fresh.block_store.height() + 1):
            assert fresh.block_store.load_extended_commit(h) is not None, h

    run(main())
