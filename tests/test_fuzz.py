"""Property/fuzz tests (reference test/fuzz: mempool CheckTx, p2p
SecretConnection, rpc jsonrpc server — here via hypothesis)."""

import asyncio

import pytest

pytest.importorskip("hypothesis")

from hypothesis import HealthCheck, given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

FAST = settings(
    max_examples=40,
    deadline=None,
    suppress_health_check=[HealthCheck.too_slow],
)


# --- mempool CheckTx on arbitrary bytes (test/fuzz/tests mempool) -------


@settings(parent=FAST)
@given(tx=st.binary(min_size=0, max_size=512))
def test_fuzz_mempool_checktx(tx):
    from cometbft_tpu.abci.client import AppConns
    from cometbft_tpu.mempool.mempool import CListMempool
    from cometbft_tpu.models.kvstore import KVStoreApplication

    mp = CListMempool(AppConns.local(KVStoreApplication()).mempool)
    # must never raise, whatever the bytes
    mp.check_tx(tx)
    for t in mp.reap_max_bytes_max_gas(1 << 20, -1):
        assert t == tx


# --- proto parser on arbitrary bytes ------------------------------------


@settings(parent=FAST)
@given(raw=st.binary(min_size=0, max_size=256))
def test_fuzz_proto_parse_never_crashes_unexpectedly(raw):
    from cometbft_tpu.utils import proto

    try:
        proto.parse(raw)
    except ValueError:
        pass  # malformed input must raise ValueError, nothing else


@settings(parent=FAST)
@given(raw=st.binary(min_size=0, max_size=512))
def test_fuzz_abci_codec_decode(raw):
    from cometbft_tpu.abci import codec

    try:
        codec.decode_request(raw)
    except (ValueError, RuntimeError, UnicodeDecodeError):
        pass
    try:
        codec.decode_response(raw)
    except (ValueError, RuntimeError, UnicodeDecodeError):
        pass


# --- block/vote codec round-trips --------------------------------------


@settings(parent=FAST)
@given(raw=st.binary(min_size=0, max_size=512))
def test_fuzz_block_decode(raw):
    from cometbft_tpu.utils import codec

    for dec in (
        codec.decode_block,
        codec.decode_vote,
        codec.decode_header,
        codec.decode_commit,
        codec.decode_validator_set,
    ):
        try:
            dec(raw)
        except (ValueError, KeyError, IndexError, OverflowError,
                UnicodeDecodeError, struct_error):
            pass


import struct  # noqa: E402

struct_error = struct.error


# --- merkle proof ops (peer-supplied light-client proofs) ---------------


@settings(parent=FAST)
@given(raw=st.binary(min_size=0, max_size=512))
def test_fuzz_proof_ops_decode_and_verify(raw):
    """Adversarial proof-op bytes reach the light proxy from the
    primary: decode and both verify paths must raise ProofError /
    ValueError, never crash with anything else."""
    from cometbft_tpu.crypto import merkle

    rt = merkle.ProofRuntime()
    try:
        ops = merkle.decode_proof_ops(raw)
    except (ValueError, KeyError, UnicodeDecodeError):
        return
    for fn in (
        lambda: rt.verify_value(ops, b"\x00" * 32, b"key", b"val"),
        lambda: rt.verify_absence(ops, b"\x00" * 32, b"key"),
    ):
        try:
            fn()
        except (merkle.ProofError, ValueError, OverflowError):
            pass


@settings(parent=FAST)
@given(raw=st.binary(min_size=0, max_size=512))
def test_fuzz_native_commit_decode_agrees_with_python(raw):
    """The native decoder and the pure-Python reader must agree on
    every input: same decoded values or both error (the wrapper's
    ValueError fallback makes native-only strictness invisible)."""
    from cometbft_tpu.utils import codec, wirecodec

    if wirecodec.module() is None:
        return
    saved = wirecodec._mod
    try:
        got = err = None
        try:
            got = codec.decode_commit(raw)  # native-first path
        except (ValueError, OverflowError, struct_error) as e:
            err = type(e)
        wirecodec._mod = None
        try:
            want = codec.decode_commit(raw)  # pure python
        except (ValueError, OverflowError, struct_error) as e:
            assert err is not None, (raw, e)
            return
        assert err is None, raw
        assert got.height == want.height and got.round == want.round
        assert got.block_id == want.block_id
        assert got.signatures == want.signatures
    finally:
        wirecodec._mod = saved


@settings(parent=FAST)
@given(
    n_sigs=st.integers(0, 8),
    flips=st.lists(
        st.tuples(st.integers(0, 4095), st.integers(0, 255)),
        max_size=3,
    ),
    seed=st.integers(0, 2**32 - 1),
)
def test_fuzz_mutated_commit_native_python_agree(n_sigs, flips, seed):
    """Near-valid inputs (a real commit encoding with a few byte
    flips) probe the decoders' agreement far deeper than raw noise."""
    import random as _random

    from cometbft_tpu import types as T
    from cometbft_tpu.utils import codec, wirecodec

    if wirecodec.module() is None:
        return
    rng = _random.Random(seed)
    sigs = [
        T.CommitSig(
            block_id_flag=rng.choice([1, 2, 3]),
            validator_address=bytes(rng.randbytes(20)),
            timestamp_ns=rng.randrange(0, 2**62),
            signature=bytes(rng.randbytes(64)),
        )
        for _ in range(n_sigs)
    ]
    c = T.Commit(
        height=rng.randrange(1, 2**40),
        round=rng.randrange(0, 4),
        block_id=T.BlockID(
            bytes(rng.randbytes(32)),
            T.PartSetHeader(1, bytes(rng.randbytes(32))),
        ),
        signatures=sigs,
    )
    raw = bytearray(codec.encode_commit(c))
    for pos, val in flips:
        if raw:
            raw[pos % len(raw)] ^= val
    raw = bytes(raw)

    saved = wirecodec._mod
    try:
        got = err = None
        try:
            got = codec.decode_commit(raw)
        except (ValueError, OverflowError, struct_error) as e:
            err = type(e)
        wirecodec._mod = None
        try:
            want = codec.decode_commit(raw)
        except (ValueError, OverflowError, struct_error):
            assert err is not None
            return
        assert err is None
        assert (got.height, got.round, got.block_id, got.signatures) == (
            want.height,
            want.round,
            want.block_id,
            want.signatures,
        )
    finally:
        wirecodec._mod = saved


# --- SecretConnection vs garbage frames ---------------------------------


def test_fuzz_secret_connection_garbage():
    """Handshake against a peer that speaks garbage must fail cleanly,
    not hang or crash the process (reference test/fuzz p2p/secretconn)."""
    import os
    import socket

    from cometbft_tpu.p2p.conn.secret_connection import SecretConnection
    from cometbft_tpu.crypto.keys import Ed25519PrivKey

    async def go(payload: bytes):
        a, b = socket.socketpair()
        a.setblocking(False)
        b.setblocking(False)
        loop = asyncio.get_running_loop()
        reader, writer = await asyncio.open_connection(sock=a)

        async def attacker():
            rb, wb = await asyncio.open_connection(sock=b)
            wb.write(payload)
            try:
                await wb.drain()
                await asyncio.sleep(0.05)
            finally:
                wb.close()

        atk = asyncio.create_task(attacker())
        try:
            await asyncio.wait_for(
                SecretConnection.handshake(
                    reader, writer, Ed25519PrivKey.generate()
                ),
                timeout=2.0,
            )
        except Exception:
            pass  # any clean exception is fine; hang/timeout is not
        finally:
            await atk
            writer.close()

    rng = __import__("random").Random(1234)
    for _ in range(10):
        n = rng.randrange(0, 200)
        asyncio.run(go(bytes(rng.randrange(256) for _ in range(n))))


# --- pubsub query language ----------------------------------------------


@settings(parent=FAST)
@given(s=st.text(max_size=80))
def test_fuzz_pubsub_query_parse(s):
    from cometbft_tpu.utils import pubsub_query

    try:
        pubsub_query.parse(s)
    except (ValueError, KeyError):
        pass


# --- commit codec round-trip property (fast-path decoder) ---------------


@settings(parent=FAST)
@given(data=st.data())
def test_fuzz_commit_roundtrip(data):
    """decode(encode(c)) == c for generated commits — the specialized
    decode_commit scanner must agree with the writer on every shape
    (flags, empty/nil ids, zero timestamps, absent sigs)."""
    from cometbft_tpu import types as T
    from cometbft_tpu.utils import codec

    n_sigs = data.draw(st.integers(min_value=0, max_value=8))
    sigs = []
    for _ in range(n_sigs):
        flag = data.draw(st.sampled_from([1, 2, 3]))
        sigs.append(
            T.CommitSig(
                block_id_flag=flag,
                validator_address=data.draw(
                    st.binary(min_size=0, max_size=20)
                ),
                timestamp_ns=data.draw(
                    st.integers(min_value=0, max_value=2**62)
                ),
                signature=data.draw(st.binary(min_size=0, max_size=64)),
            )
        )
    bid = T.BlockID(
        data.draw(st.binary(min_size=0, max_size=32)),
        T.PartSetHeader(
            data.draw(st.integers(min_value=0, max_value=1 << 20)),
            data.draw(st.binary(min_size=0, max_size=32)),
        ),
    )
    c = T.Commit(
        height=data.draw(st.integers(min_value=0, max_value=2**62)),
        round=data.draw(st.integers(min_value=0, max_value=1 << 20)),
        block_id=bid,
        signatures=sigs,
    )
    got = codec.decode_commit(codec.encode_commit(c))
    assert got == c
