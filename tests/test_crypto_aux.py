"""Auxiliary crypto parity (VERDICT r2 missing #5): ASCII armor,
XChaCha20-Poly1305, NaCl secretbox (xsalsa20symmetric), and the typed
pubkey proto encoding layer."""

import os

import pytest

from cometbft_tpu.crypto import armor, encoding, xchacha20poly1305 as xcc
from cometbft_tpu.crypto import xsalsa20symmetric as xs
from cometbft_tpu.crypto.keys import Ed25519PrivKey


# --- armor (reference crypto/armor/armor_test.go shape) ----------------


def test_armor_roundtrip():
    data = os.urandom(100)
    s = armor.encode_armor(
        "TENDERMINT PRIVATE KEY", {"kdf": "bcrypt", "salt": "ABCD"}, data
    )
    bt, headers, out = armor.decode_armor(s)
    assert bt == "TENDERMINT PRIVATE KEY"
    assert headers == {"kdf": "bcrypt", "salt": "ABCD"}
    assert out == data


def test_armor_empty_headers_and_long_body():
    data = os.urandom(1000)  # multi-line base64
    s = armor.encode_armor("MESSAGE", {}, data)
    bt, headers, out = armor.decode_armor(s)
    assert (bt, headers, out) == ("MESSAGE", {}, data)


def test_armor_rejects_corruption():
    s = armor.encode_armor("MESSAGE", {}, b"payload-bytes-here")
    # flip a body character
    lines = s.split("\n")
    body_i = next(
        i for i, l in enumerate(lines)
        if l and not l.startswith("-") and ":" not in l and not l.startswith("=")
    )
    ch = "B" if lines[body_i][0] != "B" else "C"
    lines[body_i] = ch + lines[body_i][1:]
    with pytest.raises(ValueError):
        armor.decode_armor("\n".join(lines))
    with pytest.raises(ValueError):
        armor.decode_armor("not armor at all")


# --- HChaCha20 differential vectors (reference vector_test.go) ---------

HCHACHA_VECTORS = [
    (
        "0000000000000000000000000000000000000000000000000000000000000000",
        "000000000000000000000000000000000000000000000000",
        "1140704c328d1d5d0e30086cdf209dbd6a43b8f41518a11cc387b669b2ee6586",
    ),
    (
        "8000000000000000000000000000000000000000000000000000000000000000",
        "000000000000000000000000000000000000000000000000",
        "7d266a7fd808cae4c02a0a70dcbfbcc250dae65ce3eae7fc210f54cc8f77df86",
    ),
    (
        "0000000000000000000000000000000000000000000000000000000000000001",
        "000000000000000000000000000000000000000000000002",
        "e0c77ff931bb9163a5460c02ac281c2b53d792b1c43fea817e9ad275ae546963",
    ),
    (
        "000102030405060708090a0b0c0d0e0f101112131415161718191a1b1c1d1e1f",
        "000102030405060708090a0b0c0d0e0f1011121314151617",
        "51e3ff45a895675c4b33b46c64f4a9ace110d34df6a2ceab486372bacbd3eff6",
    ),
]


def test_hchacha20_vectors():
    for key_h, nonce_h, want_h in HCHACHA_VECTORS:
        got = xcc.hchacha20(
            bytes.fromhex(key_h), bytes.fromhex(nonce_h)[:16]
        )
        assert got.hex() == want_h


def test_xchacha20poly1305_roundtrip_and_auth():
    key = os.urandom(32)
    aead = xcc.XChaCha20Poly1305(key)
    nonce = os.urandom(24)
    pt = b"the quick brown fox" * 7
    ct = aead.seal(nonce, pt, aad=b"header")
    assert len(ct) == len(pt) + aead.overhead
    assert aead.open(nonce, ct, aad=b"header") == pt
    with pytest.raises(ValueError):
        aead.open(nonce, ct, aad=b"other")
    with pytest.raises(ValueError):
        aead.open(nonce, ct[:-1] + bytes([ct[-1] ^ 1]), aad=b"header")
    with pytest.raises(ValueError):
        xcc.XChaCha20Poly1305(b"short")
    with pytest.raises(ValueError):
        aead.seal(b"\x00" * 12, pt)  # 12B nonce is ChaCha20's, not ours


# --- xsalsa20symmetric (reference symmetric_test.go shape) -------------


def test_secretbox_roundtrip():
    secret = os.urandom(32)
    for size in (1, 15, 16, 17, 63, 64, 65, 300):
        pt = os.urandom(size)
        ct = xs.encrypt_symmetric(pt, secret)
        assert len(ct) == len(pt) + xs.NONCE_LEN + xs.OVERHEAD
        assert xs.decrypt_symmetric(ct, secret) == pt
    # reference quirk preserved (symmetric.go:42 uses <=): an empty
    # plaintext seals but its ciphertext is rejected on decrypt
    empty_ct = xs.encrypt_symmetric(b"", secret)
    with pytest.raises(ValueError, match="too short"):
        xs.decrypt_symmetric(empty_ct, secret)


def test_secretbox_rejects_wrong_secret_and_tamper():
    secret = os.urandom(32)
    ct = xs.encrypt_symmetric(b"attack at dawn", secret)
    with pytest.raises(ValueError):
        xs.decrypt_symmetric(ct, os.urandom(32))
    bad = ct[:-1] + bytes([ct[-1] ^ 1])
    with pytest.raises(ValueError):
        xs.decrypt_symmetric(bad, secret)
    with pytest.raises(ValueError):
        xs.decrypt_symmetric(ct[:30], secret)
    with pytest.raises(ValueError):
        xs.encrypt_symmetric(b"x", b"short-secret")


def test_hsalsa20_known_subkey():
    """XSalsa20 with an all-zero 24B nonce must equal Salsa20 under the
    HSalsa20-derived subkey — and the derivation must be deterministic."""
    key = bytes(range(32))
    a = xs.hsalsa20(key, b"\x00" * 16)
    b = xs.hsalsa20(key, b"\x00" * 16)
    assert a == b and len(a) == 32 and a != key


def test_armored_encrypted_key_flow():
    """The end-to-end armor+secretbox flow the reference tooling uses
    for private-key export."""
    import hashlib

    priv = Ed25519PrivKey.generate()
    secret = hashlib.sha256(b"correct horse battery staple").digest()
    boxed = xs.encrypt_symmetric(priv.seed, secret)
    s = armor.encode_armor(
        "TENDERMINT PRIVATE KEY", {"kdf": "sha256"}, boxed
    )
    bt, hdrs, data = armor.decode_armor(s)
    assert hdrs["kdf"] == "sha256"
    seed = xs.decrypt_symmetric(data, secret)
    assert Ed25519PrivKey.from_seed(seed).pub_key() == priv.pub_key()


# --- typed pubkey encoding (reference crypto/encoding/codec.go) --------


def test_pubkey_proto_roundtrip():
    pk = Ed25519PrivKey.generate().pub_key()
    b = encoding.pubkey_to_proto(pk)
    assert encoding.pubkey_from_proto(b) == pk


def test_pubkey_from_type_and_bytes_errors():
    with pytest.raises(encoding.ErrUnsupportedKey):
        encoding.pubkey_from_type_and_bytes("sr25519", b"\x00" * 32)
    with pytest.raises(encoding.ErrInvalidKeyLen) as ei:
        encoding.pubkey_from_type_and_bytes("ed25519", b"\x00" * 31)
    assert ei.value.got == 31 and ei.value.want == 32
    pk = encoding.pubkey_from_type_and_bytes("ed25519", b"\x01" * 32)
    assert pk.key_bytes == b"\x01" * 32
    with pytest.raises(encoding.ErrUnsupportedKey):
        encoding.pubkey_from_proto(b"")
