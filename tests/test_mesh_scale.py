"""Virtual-mesh scaling beyond the driver's 8 devices (VERDICT r4 #9).

The driver dryruns n_devices=8; these tests prove the SAME full
sharded step (kernel leg + psum quorum) at 16 and 32 virtual devices,
and that the dispatch padding keeps per-device partition math exact on
a RAGGED configuration (non-power-of-two device count whose shard
width does not divide the natural pad). Kernel-compiling lane: each
mesh size is a fresh XLA program (~40-60s cold on the 1-core box,
seconds warm via .jax_cache).
"""

import os
import subprocess
import sys

import pytest

pytestmark = [pytest.mark.tpu, pytest.mark.slow]  # tpu implies slow: keeps the `-m 'not slow'` fast lane kernel-free

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENTRY = os.path.join(REPO, "__graft_entry__.py")

WALL_CAP_S = 420


@pytest.mark.parametrize("n_devices", [16, 32])
def test_dryrun_at_scale(n_devices):
    """The full driver dryrun — sharded kernel leg, tally, psum
    quorum — on a 16/32-device virtual mesh. Asserts the kernel leg
    GENUINELY executed sharded (no host fallback) and the weighted
    tally is stable at every mesh size (one bad lane of 10 power)."""
    env = dict(os.environ)
    env.pop("GRAFT_DRYRUN_KERNEL", None)
    # wider meshes pay a larger partitioned-compile cost than the
    # driver's 8-device budget assumes; this test targets partition
    # math, not the driver's budget envelope (test_dryrun pins that)
    env["GRAFT_DRYRUN_KERNEL_BUDGET_S"] = "150"
    try:
        proc = subprocess.run(
            [sys.executable, ENTRY, "--dryrun", str(n_devices)],
            env=env,
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=WALL_CAP_S,
        )
    except subprocess.TimeoutExpired:
        pytest.fail(
            f"{n_devices}-device dryrun exceeded {WALL_CAP_S}s"
        )
    assert proc.returncode == 0, (
        proc.stdout[-2000:] + proc.stderr[-2000:]
    )
    assert "dryrun_multichip OK" in proc.stdout, proc.stdout[-2000:]
    line = next(
        l for l in proc.stdout.splitlines() if "kernel_leg=" in l
    )
    assert "sharded-kernel" in line, line
    assert f"mesh={n_devices}" in line, line
    # 2 lanes per device, one corrupted lane of power 10: the psum
    # tally must be exact at every mesh width
    n = n_devices * 2
    assert f"tally={10 * n - 10}/{10 * n}" in line, line


def test_ragged_lane_padding_on_6_device_mesh():
    """Non-power-of-two device count (6) with a batch whose natural
    pad (16) does not divide: dispatch must round the lanes up to a
    multiple of the device count (18), shard 3 lanes per device, and
    return exact verdicts for the real items."""
    script = f"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=6"
sys.path.insert(0, {REPO!r})
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update(
    "jax_compilation_cache_dir", os.path.join({REPO!r}, ".jax_cache")
)
import numpy as np
from cometbft_tpu.crypto import batch as cb
from cometbft_tpu.crypto import ref_ed25519 as ref
from cometbft_tpu.ops import ed25519 as ed

cb.set_default_backend("tpu")
cb.set_min_tpu_batch(1)
ed.PAD_MIN = 8  # natural pad for 9 items -> 16, NOT divisible by 6
rng = np.random.default_rng(11)
items = []
bad = {{4}}
for i in range(9):
    sk = rng.bytes(32)
    pk = ref.public_from_seed(sk)
    m = bytes(rng.bytes(21))
    sig = ref.sign(sk, m)
    if i in bad:
        sig = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
    items.append((m, pk, sig))
got = ed.verify_batch(items)
d = ed.LAST_DISPATCH
assert d["sharded"] and d["n_devices"] == 6, d
assert d["lanes"] == 18 and d["lanes"] % 6 == 0, d
assert list(got) == [i not in bad for i in range(9)], list(got)
print("RAGGED_OK lanes=", d["lanes"])
"""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", script],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=WALL_CAP_S,
            env={
                k: v
                for k, v in os.environ.items()
                if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
            },
        )
    except subprocess.TimeoutExpired:
        pytest.fail(f"ragged-mesh run exceeded {WALL_CAP_S}s")
    assert proc.returncode == 0, (
        proc.stdout[-2000:] + proc.stderr[-2000:]
    )
    assert "RAGGED_OK" in proc.stdout, proc.stdout[-1000:]
