"""Tracing plane (cometbft_tpu/trace) tier-1 suite.

Layers:
  1. tracer core contracts: preallocated ring reuse (no growth, no
     slot churn), disabled fast-path overhead bound, span/instant/
     counter semantics, observers;
  2. export + summary + CLI;
  3. live instrumentation: 1-node consensus span nesting, crypto
     parallel-verify chunk spans on the process tracer;
  4. the ISSUE 4 acceptance scenario: a 4-node in-process chaos run
     with tracing enabled produces a Perfetto-loadable trace whose
     consensus step spans nest correctly per height/round;
  5. ISSUE 7 cross-node timelines: clock-anchor rebase, per-height
     commit-latency attribution, stamp/correlate overhead guards,
     and the 4-node acceptance (complete attribution chain per
     committed height, same-seed structural determinism).
"""

import asyncio
import json
import time

import pytest

from cometbft_tpu.trace import (
    NOOP,
    SpanMetricsBridge,
    Tracer,
    attribute_heights,
    attribution_key,
    chrome_trace,
    format_waterfall,
    merge_events,
    percentile,
    read_jsonl,
    rebase,
    summarize,
    summarize_by_height,
    write_jsonl,
)
from cometbft_tpu.trace.cli import main as trace_cli


def run(coro, timeout=240):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# --- 1. tracer core ------------------------------------------------------


def test_ring_reuses_slots_without_growing():
    t = Tracer("ring", size=16)
    # warm up: lap the ring once
    for i in range(16):
        t.instant(f"e{i}")
    slot_ids = {id(s) for s in t._ring}
    assert len(t._ring) == 16
    # three more laps: same slot objects, same ring length
    for i in range(48):
        t.instant("later", k=i)
    assert len(t._ring) == 16
    assert {id(s) for s in t._ring} == slot_ids
    ev = t.snapshot()
    assert len(ev) == 16
    # only the newest 16 events survive, in seq order
    assert [e["args"]["k"] for e in ev] == list(range(32, 48))
    st = t.stats()
    assert st["written"] == 64 and st["dropped"] == 48


def test_disabled_tracer_fast_path_overhead():
    """The disabled span() path must stay a near-free attribute check.
    Envelope target is ~100ns/call on real hardware; standalone on
    this 2-vCPU throttled box it measures ~150ns bare / ~310ns with
    kwargs — but under full-suite contention every Python call
    inflates ~10x, so the bound SCALES with a no-op-call baseline
    measured in the same conditions (plus a generous absolute
    backstop). What this still catches: a disabled path that started
    doing real work (ring writes, clock reads, object churn) costs a
    large multiple of a bare call and blows the ratio regardless of
    box load."""
    import gc

    t = Tracer("off", size=64, enabled=False)
    en = Tracer("on", size=1024)
    N = 50_000

    def per_call(fn):
        best = None
        for _ in range(7):
            t0 = time.perf_counter_ns()
            for _ in range(N):
                fn()
            dt = (time.perf_counter_ns() - t0) / N
            best = dt if best is None else min(best, dt)
        return best

    def noop():
        pass

    gc.disable()
    try:
        baseline = per_call(noop)  # plain call cost on this box, now
        bare = per_call(lambda: t.span("x"))
        kw = per_call(lambda: t.span("x", height=1, round=0))
        enabled = per_call(lambda: en.span("x", height=1).end())
    finally:
        gc.enable()
    # ~100ns-envelope spirit: a handful of call-costs, never real work
    assert bare < max(1500, 12 * baseline), (
        f"disabled bare span() {bare:.0f}ns/call "
        f"(baseline {baseline:.0f}ns)"
    )
    assert kw < max(3000, 25 * baseline), (
        f"disabled kwargs span() {kw:.0f}ns/call "
        f"(baseline {baseline:.0f}ns)"
    )
    # and strictly cheaper than a real (enabled) span cycle
    assert bare < enabled, (bare, enabled)
    # and it must be an actual no-op: nothing entered the ring
    assert t.snapshot() == []
    # instant/counter share the guard
    t.instant("x", a=1)
    t.counter("c", 1)
    assert t.snapshot() == []


def test_span_semantics_and_observer():
    t = Tracer("s", size=64)
    with t.span("outer", tid="tr", height=1) as sp:
        sp.set(extra=7)
        with t.span("inner", tid="tr"):
            pass
    # manual begin/end (the consensus step machine's usage)
    h = t.span("manual", tid="tr")
    h.end()
    h.end()  # idempotent: records exactly once
    ev = t.snapshot()
    names = [e["name"] for e in ev]
    assert names == ["inner", "outer", "manual"]  # completion order
    outer = ev[1]
    inner = ev[0]
    assert outer["args"] == {"height": 1, "extra": 7}
    assert outer["ts_ns"] <= inner["ts_ns"]
    assert (
        outer["ts_ns"] + outer["dur_ns"]
        >= inner["ts_ns"] + inner["dur_ns"]
    )
    # observers see every completed span; a raising observer is
    # dropped without disturbing the hot path
    seen = []
    t.add_observer(lambda n, d, a: seen.append((n, a)))

    def bad(n, d, a):
        raise RuntimeError("boom")

    t.add_observer(bad)
    t.span("obs", k=2).end()
    t.span("obs2").end()
    assert ("obs", {"k": 2}) in seen and ("obs2", {}) in seen
    assert bad not in t._observers


def test_noop_tracer_is_disabled_and_shared():
    assert not NOOP.enabled
    sp = NOOP.span("anything", height=1)
    with sp:
        sp.set(x=1)
    NOOP.instant("i")
    NOOP.counter("c", 1)
    assert NOOP.snapshot() == []


def test_metrics_bridge_routes_by_span_name():
    got = []
    b = SpanMetricsBridge()
    b.route("consensus.step", lambda dur_s, args: got.append((dur_s, args)))
    t = Tracer("b", size=8)
    t.add_observer(b)
    t.span("consensus.step", step="PROPOSE").end()
    t.span("unrouted").end()
    assert len(got) == 1
    dur_s, args = got[0]
    assert args["step"] == "PROPOSE" and dur_s >= 0


# --- 2. export / summary / CLI ------------------------------------------


def _sample_tracer():
    t = Tracer("n0", size=64)
    with t.span("a.outer", tid="x", height=1):
        with t.span("a.inner", tid="x"):
            pass
    t.instant("mark", tid="y", k=1)
    t.counter("depth", 3, tid="y")
    return t


def test_chrome_trace_structure():
    t = _sample_tracer()
    ct = chrome_trace({"n0": t.snapshot()})
    json.loads(json.dumps(ct))  # serializable
    te = ct["traceEvents"]
    metas = [e for e in te if e["ph"] == "M"]
    assert {"process_name", "thread_name"} <= {e["name"] for e in metas}
    xs = [e for e in te if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"a.outer", "a.inner"}
    for e in xs:
        assert e["dur"] >= 0 and isinstance(e["pid"], int)
    assert [e for e in te if e["ph"] == "i"][0]["s"] == "t"
    assert [e for e in te if e["ph"] == "C"][0]["args"] == {"value": 3}


def test_jsonl_roundtrip_and_cli(tmp_path, capsys):
    t = _sample_tracer()
    p = write_jsonl(
        str(tmp_path / "n0.trace.jsonl"), "n0", t.snapshot()
    )
    back = read_jsonl([str(tmp_path)])
    assert list(back) == ["n0"] and len(back["n0"]) == 4

    assert trace_cli(["dump", p]) == 0
    lines = [
        json.loads(ln)
        for ln in capsys.readouterr().out.strip().splitlines()
    ]
    assert len(lines) == 4 and all(e["node"] == "n0" for e in lines)

    out = tmp_path / "trace.json"
    assert trace_cli(["convert", str(tmp_path), "-o", str(out)]) == 0
    capsys.readouterr()
    with open(out) as f:
        assert "traceEvents" in json.load(f)

    assert trace_cli(["summarize", p]) == 0
    text = capsys.readouterr().out
    assert "a.outer" in text and "p95ms" in text and "== n0 ==" in text

    assert trace_cli(["summarize", "--json", p]) == 0
    s = json.loads(capsys.readouterr().out)
    assert s["n0"]["a.outer"]["count"] == 1

    # empty input is an error, not a silent pass
    empty = tmp_path / "empty"
    empty.mkdir()
    assert trace_cli(["summarize", str(empty)]) == 1


def test_summary_percentiles():
    durs = list(range(1, 101))  # 1..100 "ns"
    events = [
        {"name": "k", "ph": "X", "ts_ns": 0, "dur_ns": d, "tid": "t"}
        for d in durs
    ]
    events.append(
        {"name": "c", "ph": "C", "ts_ns": 0, "dur_ns": 0, "tid": "t",
         "args": {"value": 9}}
    )
    s = summarize({"n": events})
    k = s["n"]["k"]
    assert k["count"] == 100
    assert abs(percentile(sorted(durs), 0.5) - 50.5) < 1e-9
    assert k["max_ms"] == round(100 / 1e6, 3)
    assert s["n"]["_counters"] == {"c": 9}
    assert percentile([], 0.5) == 0.0
    assert percentile([7], 0.99) == 7.0


# --- 3. live instrumentation --------------------------------------------


def test_consensus_span_nesting_one_node():
    """height ⊇ round ⊇ step on a real consensus run, plus mempool and
    commit events — the per-node wiring end-to-end."""
    from cometbft_tpu.node.inprocess import (
        LocalNet,
        build_node,
        make_genesis,
    )

    async def main():
        gen, pvs = make_genesis(1, chain_id="trace-nest")
        parts = build_node(gen, pvs[0])
        net = LocalNet([parts])
        await net.start()
        parts.mempool.check_tx(b"t=1")
        await net.wait_for_height(3, 120)
        await net.stop()
        return parts

    parts = run(main())
    assert parts.tracer.enabled  # always-on default
    ev = parts.tracer.snapshot()
    _assert_consensus_nesting(ev, min_heights=3)
    names = {e["name"] for e in ev}
    assert {"mempool.insert", "mempool.reap", "consensus.commit"} <= names
    reaps = [e for e in ev if e["name"] == "mempool.reap"]
    assert any(e["args"].get("txs", 0) >= 1 for e in reaps)


def _assert_consensus_nesting(events, min_heights=1, require_steps=()):
    def encloses(o, i):
        return (
            o["ts_ns"] <= i["ts_ns"]
            and o["ts_ns"] + o["dur_ns"] >= i["ts_ns"] + i["dur_ns"]
        )

    steps = [e for e in events if e["name"] == "consensus.step"]
    rounds = [e for e in events if e["name"] == "consensus.round"]
    heights = [e for e in events if e["name"] == "consensus.height"]
    assert len(heights) >= min_heights, (len(heights), min_heights)
    assert steps and rounds
    for s in steps:
        assert any(
            r["args"]["height"] == s["args"]["height"]
            and r["args"]["round"] == s["args"]["round"]
            and encloses(r, s)
            for r in rounds
        ), f"step span not nested in its round: {s}"
    for r in rounds:
        assert any(
            h["args"]["height"] == r["args"]["height"] and encloses(h, r)
            for h in heights
        ), f"round span not nested in its height: {r}"
    kinds = {s["args"]["step"] for s in steps}
    assert set(require_steps) <= kinds, (require_steps, kinds)


def test_crypto_chunk_spans_on_process_tracer():
    """The parallel-verify plane records dispatch instants + per-chunk
    worker spans (worker id, lane count, tier) on the process-wide
    tracer."""
    from cometbft_tpu.crypto.keys import Ed25519PrivKey
    from cometbft_tpu.crypto.parallel_verify import ParallelVerifyEngine
    from cometbft_tpu.trace import enable_global, global_tracer

    g = global_tracer()
    was_enabled = g.enabled
    enable_global()
    g.clear()
    try:
        priv = Ed25519PrivKey.from_seed(b"\x11" * 32)
        pk = priv.pub_key()
        items = []
        for i in range(40):
            m = b"chunk-span-%03d" % i
            items.append((pk, m, priv.sign(m)))
        eng = ParallelVerifyEngine(workers=2, min_parallel=8)
        try:
            assert all(eng.verify(items))
        finally:
            eng.close()
        ev = g.snapshot()
        dispatches = [
            e for e in ev if e["name"] == "crypto.batch.dispatch"
        ]
        chunks = [e for e in ev if e["name"] == "crypto.verify_chunk"]
        if eng.tier == "serial":  # restricted box: pool creation failed
            pytest.skip("no worker pool on this box")
        assert dispatches and dispatches[0]["args"]["lanes"] == 40
        assert dispatches[0]["args"]["tier"] == eng.tier
        if eng.tier == "thread":
            # thread tier shares the ring: chunk spans must be there,
            # carrying worker id + lanes + tier
            assert chunks
            assert sum(c["args"]["lanes"] for c in chunks) == 40
            assert all(
                c["args"]["tier"] == "thread" and c["tid"]
                for c in chunks
            )
    finally:
        enable_global(was_enabled)
        g.clear()


# --- 4. ISSUE 4 acceptance: 4-node chaos run with tracing ---------------


def test_chaos_run_traced_perfetto_loadable(tmp_path):
    """A 4-node in-process chaos net with tracing enabled exports a
    Perfetto-loadable trace whose consensus step spans nest correctly
    per height/round on every node, with WAL fsync spans alongside."""
    from cometbft_tpu.chaos import FaultSchedule, run_schedule

    async def main():
        return await run_schedule(
            FaultSchedule([]),  # no faults: the fast acceptance run
            seed=77,
            base_dir=str(tmp_path / "net"),
            n_nodes=4,
            settle_heights=3,
            liveness_bound_s=120.0,
            trace_dir=str(tmp_path / "traces"),
        )

    report = run(main())
    assert report.ok, report.format()
    assert report.trace_files
    jsonls = [p for p in report.trace_files if p.endswith(".jsonl")]
    chrome = [p for p in report.trace_files if p.endswith("trace.json")]
    # one ring per node (no restarts in this schedule)
    node_dumps = [p for p in jsonls if "/n" in p]
    assert len(node_dumps) == 4, report.trace_files
    assert len(chrome) == 1

    # Perfetto-loadable: valid JSON, traceEvents, process metadata for
    # every node, X events with ts+dur
    with open(chrome[0]) as f:
        ct = json.load(f)
    te = ct["traceEvents"]
    procs = {
        e["args"]["name"]
        for e in te
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert {"n0", "n1", "n2", "n3"} <= procs
    assert all(
        "ts" in e and "dur" in e for e in te if e["ph"] == "X"
    )

    by_node = read_jsonl(node_dumps)
    for node, events in by_node.items():
        _assert_consensus_nesting(
            events, min_heights=2,
            require_steps=("PROPOSE", "PREVOTE", "PRECOMMIT", "COMMIT"),
        )
        names = {e["name"] for e in events}
        # chaos homes persist a WAL: the fsync barrier must be spanned
        assert "wal.fsync" in names, (node, sorted(names))
        # ISSUE 7 cross-node tracing: every ring carries its clock
        # anchor and the stamped-correlation instants
        assert "clock.anchor" in names, (node, sorted(names))
        assert {"p2p.msg.send", "p2p.msg.recv"} <= names, node
        assert {
            "consensus.quorum.prevote", "consensus.quorum.precommit",
            "consensus.finalize",
        } <= names, (node, sorted(names))
    # and the summary machinery digests the whole dump
    s = summarize(by_node)
    assert all("consensus.step" in kinds for kinds in s.values())

    # ISSUE 7 acceptance: every committed height carries a COMPLETE
    # attribution chain — the proposer's proposal send correlated to
    # arrival instants on all committing peers, both quorum legs
    # measured per height
    rebased, offsets, _base = rebase(by_node)
    assert all(o is not None for o in offsets.values()), offsets
    heights = attribute_heights(rebased)
    assert len(heights) >= 2, sorted(heights)
    for h, rec in heights.items():
        assert rec["complete"], (h, rec)
        assert rec["proposer"] in rec["committed"], rec
        assert rec["quorum_prevote_ms"] and rec["quorum_precommit_ms"]
        for n, f in rec["finalize"].items():
            assert f["total_ms"] >= 0 and f["wal_ms"] is not None
    # non-proposer nodes saw the proposal propagate (positive delta
    # on the shared in-process clock)
    any_prop = [
        v for rec in heights.values()
        for v in rec["propagation_ms"].values()
    ]
    assert any_prop and all(v >= 0 for v in any_prop)
    # the waterfall table renders one row per height
    table = format_waterfall(heights)
    assert "complete" in table and "PARTIAL" not in table

    # the timeline CLI digests the same dump: --strict passes, -o
    # writes a Perfetto-loadable merged view on one rebased axis
    out = tmp_path / "timeline.json"
    assert (
        trace_cli(
            ["timeline", str(tmp_path / "traces"), "--strict",
             "-o", str(out)]
        )
        == 0
    )
    with open(out) as f:
        tl = json.load(f)
    assert tl["traceEvents"]


def test_chaos_same_seed_attribution_is_deterministic(tmp_path):
    """Same-seed chaos runs replay the same message decision stream,
    so the attribution table's STRUCTURE — committed heights, the
    proposer per height, chain completeness — reproduces exactly
    (latency columns are wall-clock and jitter run to run; the common
    committed prefix is compared because wall time decides how many
    heights land before the schedule ends)."""
    from cometbft_tpu.chaos import FaultSchedule, run_schedule

    async def one(i):
        return await run_schedule(
            FaultSchedule([]),
            seed=909,
            base_dir=str(tmp_path / f"net{i}"),
            n_nodes=4,
            settle_heights=2,
            liveness_bound_s=120.0,
            trace_dir=str(tmp_path / f"traces{i}"),
            profile_hz=0,
        )

    keys = []
    for i in range(2):
        report = run(one(i))
        assert report.ok, report.format()
        by_node = read_jsonl(
            [p for p in report.trace_files if "/n" in p]
        )
        rebased, _, _ = rebase(by_node)
        heights = attribute_heights(rebased)
        assert heights
        keys.append(
            {
                h: (rec["proposer"], rec["complete"])
                for h, rec in heights.items()
            }
        )
    common = sorted(set(keys[0]) & set(keys[1]))
    assert common, (sorted(keys[0]), sorted(keys[1]))
    for h in common:
        assert keys[0][h] == keys[1][h], (h, keys[0][h], keys[1][h])


# --- 5. ISSUE 7: cross-node timelines -----------------------------------


def _mk_ring(node, anchor_mono, anchor_wall, events):
    """Synthetic ring: a clock.anchor instant + the given events
    (ts_ns are monotonic in this ring's private clock domain)."""
    out = [
        {
            "seq": -1, "name": "clock.anchor", "ph": "i",
            "ts_ns": anchor_mono, "dur_ns": 0, "tid": "main",
            "args": {"wall_ns": anchor_wall},
        }
    ]
    for i, e in enumerate(events):
        out.append(
            {
                "seq": i, "ph": e.get("ph", "i"), "tid": "t",
                "dur_ns": e.get("dur_ns", 0),
                **{
                    k: e[k] for k in ("name", "ts_ns", "args")
                },
            }
        )
    return {node: out}


def test_rebase_aligns_rings_across_clock_domains():
    """Two rings whose monotonic clocks are wildly offset but whose
    anchors map to the same wall instant must land on ONE axis: an
    event stamped 5ms after n0's anchor and one 6ms after n1's anchor
    come out exactly 1ms apart."""
    WALL = 1_700_000_000_000_000_000
    by_node = {}
    by_node.update(_mk_ring("n0", 10_000_000, WALL, [
        {"name": "a", "ts_ns": 15_000_000, "args": {}},
    ]))
    by_node.update(_mk_ring("n1", 999_000_000_000, WALL, [
        {"name": "b", "ts_ns": 999_006_000_000, "args": {}},
    ]))
    rebased, offsets, base = rebase(by_node)
    assert offsets["n0"] != offsets["n1"]  # different mono domains
    ts = {
        e["name"]: e["ts_ns"]
        for evs in rebased.values()
        for e in evs
        if e["name"] in ("a", "b")
    }
    assert ts["b"] - ts["a"] == 1_000_000
    # zeroed at the earliest event (the anchors themselves)
    assert min(
        e["ts_ns"] for evs in rebased.values() for e in evs
    ) == 0
    # merged view is stable-sorted on the shared axis, nodes tagged
    flat = merge_events(rebased)
    assert [e["ts_ns"] for e in flat] == sorted(
        e["ts_ns"] for e in flat
    )
    assert all("node" in e for e in flat)


def test_rebase_unanchored_ring_borrows_median_offset():
    by_node = {}
    by_node.update(_mk_ring("n0", 100, 1_000_100, [
        {"name": "a", "ts_ns": 200, "args": {}},
    ]))
    # no anchor at all in n1's ring
    by_node["n1"] = [
        {"seq": 0, "name": "b", "ph": "i", "ts_ns": 250, "dur_ns": 0,
         "tid": "t", "args": {}},
    ]
    rebased, offsets, _ = rebase(by_node)
    assert offsets["n1"] is None
    ts = {
        e["name"]: e["ts_ns"]
        for evs in rebased.values() for e in evs
    }
    # borrowed n0's offset: raw deltas preserved on the shared axis
    assert ts["b"] - ts["a"] == 50


def test_attribute_heights_waterfall_and_completeness():
    """Synthetic 2-node height: proposal send on n0 correlates to
    n1's recv; quorum/verify/finalize legs land in the waterfall;
    dropping the peer's arrival flips the chain to PARTIAL."""
    W = 1_000_000_000

    def ring(node, send_recv):
        evs = [
            {"name": "consensus.quorum.prevote", "ph": "X",
             "ts_ns": 10_000_000, "dur_ns": 3_000_000,
             "args": {"height": 5, "round": 0, "step": "prevote"}},
            {"name": "consensus.quorum.precommit", "ph": "X",
             "ts_ns": 10_000_000, "dur_ns": 5_000_000,
             "args": {"height": 5, "round": 0, "step": "precommit"}},
            {"name": "consensus.verify", "ph": "X",
             "ts_ns": 11_000_000, "dur_ns": 400_000,
             "args": {"height": 5, "round": 0, "accepted": True}},
            {"name": "consensus.finalize", "ph": "X",
             "ts_ns": 16_000_000, "dur_ns": 2_000_000,
             "args": {"height": 5, "persist_ms": 0.5, "wal_ms": 1.0,
                      "apply_ms": 0.5}},
        ] + send_recv
        return _mk_ring(node, 0, W, evs)

    by_node = {}
    by_node.update(ring("n0", [
        {"name": "p2p.msg.send", "ph": "i", "ts_ns": 9_000_000,
         "args": {"kind": "proposal", "h": 5, "r": 0, "seq": 1}},
    ]))
    by_node.update(ring("n1", [
        {"name": "p2p.msg.recv", "ph": "i", "ts_ns": 9_800_000,
         "args": {"kind": "proposal", "h": 5, "r": 0, "seq": 1,
                  "origin": "n0"}},
        {"name": "consensus.proposal.complete", "ph": "i",
         "ts_ns": 9_900_000, "args": {"height": 5, "round": 0}},
    ]))
    heights = attribute_heights(rebase(by_node)[0])
    assert sorted(heights) == [5]
    rec = heights[5]
    assert rec["proposer"] == "n0"
    assert rec["committed"] == ["n0", "n1"]
    assert rec["complete"]
    assert rec["propagation_ms"] == {"n1": 0.8}
    assert rec["parts_ms"] == {"n1": 0.9}
    assert rec["quorum_prevote_ms"] == {"n0": 3.0, "n1": 3.0}
    assert rec["quorum_precommit_ms"] == {"n0": 5.0, "n1": 5.0}
    assert rec["verify_ms"] == {"n0": 0.4, "n1": 0.4}
    assert rec["finalize"]["n1"]["wal_ms"] == 1.0
    key = attribution_key(heights)
    assert key == [(5, "n0", ("n0", "n1"), True)]
    assert "complete" in format_waterfall(heights)

    # peel n1's arrival instants: the chain is no longer complete
    by_node["n1"] = [
        e for e in by_node["n1"]
        if e["name"] not in (
            "p2p.msg.recv", "consensus.proposal.complete"
        )
    ]
    heights = attribute_heights(rebase(by_node)[0])
    assert not heights[5]["complete"]
    assert heights[5]["missing_arrival"] == ["n1"]
    assert "PARTIAL" in format_waterfall(heights)

    # ...unless the node caught up via commit_block gossip, which is
    # its own causal chain (recv instant on the stamped catch-up)
    by_node["n1"].append(
        {"seq": 99, "name": "p2p.msg.recv", "ph": "i",
         "ts_ns": 15_000_000, "dur_ns": 0, "tid": "t",
         "args": {"kind": "commit_block", "h": 5, "seq": 9,
                  "origin": "n0"}}
    )
    heights = attribute_heights(rebase(by_node)[0])
    assert heights[5]["complete"]


def test_timeline_cli_json_and_strict(tmp_path):
    W = 2_000_000_000
    ring = _mk_ring("n0", 0, W, [
        {"name": "consensus.finalize", "ph": "X", "ts_ns": 5_000_000,
         "dur_ns": 1_000_000,
         "args": {"height": 3, "persist_ms": 0.1, "wal_ms": 0.2,
                  "apply_ms": 0.3}},
    ])
    p = write_jsonl(str(tmp_path / "n0.trace.jsonl"), "n0", ring["n0"])
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = trace_cli(["timeline", p, "--json"])
    assert rc == 0
    doc = json.loads(buf.getvalue())
    assert doc["offsets_ns"]["n0"] == W
    assert doc["heights"]["3"]["committed"] == ["n0"]
    # no proposal send anywhere: the chain is incomplete => --strict
    # exits 3 (and an empty dump is also strict-fatal)
    assert not doc["heights"]["3"]["complete"]
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = trace_cli(["timeline", p, "--strict"])
    assert rc == 3
    assert "PARTIAL" in buf.getvalue()


def test_summarize_by_height_groups_across_nodes(tmp_path, capsys):
    events = []
    for h in (1, 2):
        for node_dur in (1_000_000, 3_000_000):
            events.append(
                {"name": "consensus.quorum.prevote", "ph": "X",
                 "ts_ns": 0, "dur_ns": node_dur, "tid": "c",
                 "args": {"height": h, "step": "prevote"}}
            )
    # height-less spans stay out of the by-height grouping
    events.append(
        {"name": "wal.fsync", "ph": "X", "ts_ns": 0,
         "dur_ns": 9_000_000, "tid": "w", "args": {}}
    )
    bh = summarize_by_height({"n0": events[:2] + events[-1:],
                              "n1": events[2:4]})
    assert sorted(bh) == [1, 2]
    assert bh[1]["consensus.quorum.prevote"]["count"] == 2
    assert bh[1]["consensus.quorum.prevote"]["max_ms"] == 3.0
    assert "wal.fsync" not in bh[1]

    # CLI: --by-height lands in both the table and the JSON doc
    p = write_jsonl(
        str(tmp_path / "n0.trace.jsonl"), "n0", events
    )
    assert trace_cli(["summarize", p, "--by-height"]) == 0
    text = capsys.readouterr().out
    assert "== height 1 ==" in text and "== height 2 ==" in text
    assert trace_cli(["summarize", p, "--by-height", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert "summary" in doc and "by_height" in doc
    assert doc["by_height"]["1"]["consensus.quorum.prevote"]["count"] == 2


# --- 5b. ISSUE 7 overhead guards (stamp-encode / correlate) --------------


def _per_call(fn, n=20_000, repeats=7):
    best = None
    for _ in range(repeats):
        t0 = time.perf_counter_ns()
        for _ in range(n):
            fn()
        dt = (time.perf_counter_ns() - t0) / n
        best = dt if best is None else min(best, dt)
    return best


def test_stamp_and_correlate_overhead_bounds():
    """ISSUE 7 overhead guards: stamping a send and correlating a
    receive are per-MESSAGE costs on the p2p hot path, so they are
    bounded like the PR 4/6 guards — scaled against a no-op-call
    baseline measured under the same conditions, with an absolute
    backstop for this throttled box."""
    import gc

    from cometbft_tpu.p2p import tracewire

    payload = b"\x05" + b"v" * 120  # a realistic vote-sized message
    enabled = Tracer("on", size=4096)
    st = tracewire.TraceStamper(enabled, origin="n0")
    wire = st.wrap(payload, "vote", height=3, round_=0)
    ctx, _ = tracewire.unstamp(wire)
    disabled = Tracer("off", size=4, enabled=False)
    st_off = tracewire.TraceStamper(disabled, origin="n0")

    def noop():
        pass

    gc.disable()
    try:
        baseline = _per_call(noop)
        stamp_cost = _per_call(
            lambda: st.wrap(payload, "vote", height=3, round_=0)
        )
        unstamp_cost = _per_call(lambda: tracewire.unstamp(wire))
        correlate_cost = _per_call(lambda: st.on_receive(ctx, "peerid"))
        # tracer-disabled paths: recv correlation short-circuits on
        # enabled; the raw non-magic receive check is one startswith
        recv_off = _per_call(lambda: st_off.on_receive(ctx, "peerid"))
        plain_check = _per_call(
            lambda: payload[:2] == tracewire.MAGIC
        )
    finally:
        gc.enable()

    # enabled paths: real work (varint encode + ring append) but
    # strictly micro — a few dozen call-costs, never ms
    assert stamp_cost < max(25_000, 150 * baseline), (
        f"stamp-encode {stamp_cost:.0f}ns/call "
        f"(baseline {baseline:.0f}ns)"
    )
    assert unstamp_cost < max(15_000, 100 * baseline), (
        f"unstamp {unstamp_cost:.0f}ns/call"
    )
    assert correlate_cost < max(25_000, 150 * baseline), (
        f"correlate-on-receive {correlate_cost:.0f}ns/call"
    )
    # disabled paths: attribute checks only
    assert recv_off < max(2_000, 15 * baseline), (
        f"disabled on_receive {recv_off:.0f}ns/call"
    )
    assert plain_check < max(2_000, 15 * baseline), (
        f"magic check {plain_check:.0f}ns/call"
    )
    # and the disabled receive path recorded nothing
    assert disabled.snapshot() == []


def test_stamp_msg_disabled_switch_path_is_attribute_check():
    """Switch.stamp_msg with no stamping plane must stay a near-free
    None check (every per-peer gossip send pays it)."""
    import gc

    from cometbft_tpu.p2p import MemoryTransport, NodeInfo, NodeKey
    from cometbft_tpu.p2p.switch import Switch

    nk = NodeKey.generate()
    info = NodeInfo(node_id=nk.node_id, network="ovh")
    sw = Switch(MemoryTransport(nk, info), info)
    assert sw.stamper is None
    msg = b"m" * 64

    def noop():
        pass

    gc.disable()
    try:
        baseline = _per_call(noop)
        cost = _per_call(
            lambda: sw.stamp_msg(0x21, msg, "vote", height=1)
        )
    finally:
        gc.enable()
    assert cost < max(3_000, 25 * baseline), (
        f"disabled stamp_msg {cost:.0f}ns/call "
        f"(baseline {baseline:.0f}ns)"
    )
