"""Tracing plane (cometbft_tpu/trace) tier-1 suite.

Layers:
  1. tracer core contracts: preallocated ring reuse (no growth, no
     slot churn), disabled fast-path overhead bound, span/instant/
     counter semantics, observers;
  2. export + summary + CLI;
  3. live instrumentation: 1-node consensus span nesting, crypto
     parallel-verify chunk spans on the process tracer;
  4. the ISSUE 4 acceptance scenario: a 4-node in-process chaos run
     with tracing enabled produces a Perfetto-loadable trace whose
     consensus step spans nest correctly per height/round.
"""

import asyncio
import json
import time

import pytest

from cometbft_tpu.trace import (
    NOOP,
    SpanMetricsBridge,
    Tracer,
    chrome_trace,
    percentile,
    read_jsonl,
    summarize,
    write_jsonl,
)
from cometbft_tpu.trace.cli import main as trace_cli


def run(coro, timeout=240):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# --- 1. tracer core ------------------------------------------------------


def test_ring_reuses_slots_without_growing():
    t = Tracer("ring", size=16)
    # warm up: lap the ring once
    for i in range(16):
        t.instant(f"e{i}")
    slot_ids = {id(s) for s in t._ring}
    assert len(t._ring) == 16
    # three more laps: same slot objects, same ring length
    for i in range(48):
        t.instant("later", k=i)
    assert len(t._ring) == 16
    assert {id(s) for s in t._ring} == slot_ids
    ev = t.snapshot()
    assert len(ev) == 16
    # only the newest 16 events survive, in seq order
    assert [e["args"]["k"] for e in ev] == list(range(32, 48))
    st = t.stats()
    assert st["written"] == 64 and st["dropped"] == 48


def test_disabled_tracer_fast_path_overhead():
    """The disabled span() path must stay a near-free attribute check.
    Envelope target is ~100ns/call on real hardware; standalone on
    this 2-vCPU throttled box it measures ~150ns bare / ~310ns with
    kwargs — but under full-suite contention every Python call
    inflates ~10x, so the bound SCALES with a no-op-call baseline
    measured in the same conditions (plus a generous absolute
    backstop). What this still catches: a disabled path that started
    doing real work (ring writes, clock reads, object churn) costs a
    large multiple of a bare call and blows the ratio regardless of
    box load."""
    import gc

    t = Tracer("off", size=64, enabled=False)
    en = Tracer("on", size=1024)
    N = 50_000

    def per_call(fn):
        best = None
        for _ in range(7):
            t0 = time.perf_counter_ns()
            for _ in range(N):
                fn()
            dt = (time.perf_counter_ns() - t0) / N
            best = dt if best is None else min(best, dt)
        return best

    def noop():
        pass

    gc.disable()
    try:
        baseline = per_call(noop)  # plain call cost on this box, now
        bare = per_call(lambda: t.span("x"))
        kw = per_call(lambda: t.span("x", height=1, round=0))
        enabled = per_call(lambda: en.span("x", height=1).end())
    finally:
        gc.enable()
    # ~100ns-envelope spirit: a handful of call-costs, never real work
    assert bare < max(1500, 12 * baseline), (
        f"disabled bare span() {bare:.0f}ns/call "
        f"(baseline {baseline:.0f}ns)"
    )
    assert kw < max(3000, 25 * baseline), (
        f"disabled kwargs span() {kw:.0f}ns/call "
        f"(baseline {baseline:.0f}ns)"
    )
    # and strictly cheaper than a real (enabled) span cycle
    assert bare < enabled, (bare, enabled)
    # and it must be an actual no-op: nothing entered the ring
    assert t.snapshot() == []
    # instant/counter share the guard
    t.instant("x", a=1)
    t.counter("c", 1)
    assert t.snapshot() == []


def test_span_semantics_and_observer():
    t = Tracer("s", size=64)
    with t.span("outer", tid="tr", height=1) as sp:
        sp.set(extra=7)
        with t.span("inner", tid="tr"):
            pass
    # manual begin/end (the consensus step machine's usage)
    h = t.span("manual", tid="tr")
    h.end()
    h.end()  # idempotent: records exactly once
    ev = t.snapshot()
    names = [e["name"] for e in ev]
    assert names == ["inner", "outer", "manual"]  # completion order
    outer = ev[1]
    inner = ev[0]
    assert outer["args"] == {"height": 1, "extra": 7}
    assert outer["ts_ns"] <= inner["ts_ns"]
    assert (
        outer["ts_ns"] + outer["dur_ns"]
        >= inner["ts_ns"] + inner["dur_ns"]
    )
    # observers see every completed span; a raising observer is
    # dropped without disturbing the hot path
    seen = []
    t.add_observer(lambda n, d, a: seen.append((n, a)))

    def bad(n, d, a):
        raise RuntimeError("boom")

    t.add_observer(bad)
    t.span("obs", k=2).end()
    t.span("obs2").end()
    assert ("obs", {"k": 2}) in seen and ("obs2", {}) in seen
    assert bad not in t._observers


def test_noop_tracer_is_disabled_and_shared():
    assert not NOOP.enabled
    sp = NOOP.span("anything", height=1)
    with sp:
        sp.set(x=1)
    NOOP.instant("i")
    NOOP.counter("c", 1)
    assert NOOP.snapshot() == []


def test_metrics_bridge_routes_by_span_name():
    got = []
    b = SpanMetricsBridge()
    b.route("consensus.step", lambda dur_s, args: got.append((dur_s, args)))
    t = Tracer("b", size=8)
    t.add_observer(b)
    t.span("consensus.step", step="PROPOSE").end()
    t.span("unrouted").end()
    assert len(got) == 1
    dur_s, args = got[0]
    assert args["step"] == "PROPOSE" and dur_s >= 0


# --- 2. export / summary / CLI ------------------------------------------


def _sample_tracer():
    t = Tracer("n0", size=64)
    with t.span("a.outer", tid="x", height=1):
        with t.span("a.inner", tid="x"):
            pass
    t.instant("mark", tid="y", k=1)
    t.counter("depth", 3, tid="y")
    return t


def test_chrome_trace_structure():
    t = _sample_tracer()
    ct = chrome_trace({"n0": t.snapshot()})
    json.loads(json.dumps(ct))  # serializable
    te = ct["traceEvents"]
    metas = [e for e in te if e["ph"] == "M"]
    assert {"process_name", "thread_name"} <= {e["name"] for e in metas}
    xs = [e for e in te if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"a.outer", "a.inner"}
    for e in xs:
        assert e["dur"] >= 0 and isinstance(e["pid"], int)
    assert [e for e in te if e["ph"] == "i"][0]["s"] == "t"
    assert [e for e in te if e["ph"] == "C"][0]["args"] == {"value": 3}


def test_jsonl_roundtrip_and_cli(tmp_path, capsys):
    t = _sample_tracer()
    p = write_jsonl(
        str(tmp_path / "n0.trace.jsonl"), "n0", t.snapshot()
    )
    back = read_jsonl([str(tmp_path)])
    assert list(back) == ["n0"] and len(back["n0"]) == 4

    assert trace_cli(["dump", p]) == 0
    lines = [
        json.loads(ln)
        for ln in capsys.readouterr().out.strip().splitlines()
    ]
    assert len(lines) == 4 and all(e["node"] == "n0" for e in lines)

    out = tmp_path / "trace.json"
    assert trace_cli(["convert", str(tmp_path), "-o", str(out)]) == 0
    capsys.readouterr()
    with open(out) as f:
        assert "traceEvents" in json.load(f)

    assert trace_cli(["summarize", p]) == 0
    text = capsys.readouterr().out
    assert "a.outer" in text and "p95ms" in text and "== n0 ==" in text

    assert trace_cli(["summarize", "--json", p]) == 0
    s = json.loads(capsys.readouterr().out)
    assert s["n0"]["a.outer"]["count"] == 1

    # empty input is an error, not a silent pass
    empty = tmp_path / "empty"
    empty.mkdir()
    assert trace_cli(["summarize", str(empty)]) == 1


def test_summary_percentiles():
    durs = list(range(1, 101))  # 1..100 "ns"
    events = [
        {"name": "k", "ph": "X", "ts_ns": 0, "dur_ns": d, "tid": "t"}
        for d in durs
    ]
    events.append(
        {"name": "c", "ph": "C", "ts_ns": 0, "dur_ns": 0, "tid": "t",
         "args": {"value": 9}}
    )
    s = summarize({"n": events})
    k = s["n"]["k"]
    assert k["count"] == 100
    assert abs(percentile(sorted(durs), 0.5) - 50.5) < 1e-9
    assert k["max_ms"] == round(100 / 1e6, 3)
    assert s["n"]["_counters"] == {"c": 9}
    assert percentile([], 0.5) == 0.0
    assert percentile([7], 0.99) == 7.0


# --- 3. live instrumentation --------------------------------------------


def test_consensus_span_nesting_one_node():
    """height ⊇ round ⊇ step on a real consensus run, plus mempool and
    commit events — the per-node wiring end-to-end."""
    from cometbft_tpu.node.inprocess import (
        LocalNet,
        build_node,
        make_genesis,
    )

    async def main():
        gen, pvs = make_genesis(1, chain_id="trace-nest")
        parts = build_node(gen, pvs[0])
        net = LocalNet([parts])
        await net.start()
        parts.mempool.check_tx(b"t=1")
        await net.wait_for_height(3, 120)
        await net.stop()
        return parts

    parts = run(main())
    assert parts.tracer.enabled  # always-on default
    ev = parts.tracer.snapshot()
    _assert_consensus_nesting(ev, min_heights=3)
    names = {e["name"] for e in ev}
    assert {"mempool.insert", "mempool.reap", "consensus.commit"} <= names
    reaps = [e for e in ev if e["name"] == "mempool.reap"]
    assert any(e["args"].get("txs", 0) >= 1 for e in reaps)


def _assert_consensus_nesting(events, min_heights=1, require_steps=()):
    def encloses(o, i):
        return (
            o["ts_ns"] <= i["ts_ns"]
            and o["ts_ns"] + o["dur_ns"] >= i["ts_ns"] + i["dur_ns"]
        )

    steps = [e for e in events if e["name"] == "consensus.step"]
    rounds = [e for e in events if e["name"] == "consensus.round"]
    heights = [e for e in events if e["name"] == "consensus.height"]
    assert len(heights) >= min_heights, (len(heights), min_heights)
    assert steps and rounds
    for s in steps:
        assert any(
            r["args"]["height"] == s["args"]["height"]
            and r["args"]["round"] == s["args"]["round"]
            and encloses(r, s)
            for r in rounds
        ), f"step span not nested in its round: {s}"
    for r in rounds:
        assert any(
            h["args"]["height"] == r["args"]["height"] and encloses(h, r)
            for h in heights
        ), f"round span not nested in its height: {r}"
    kinds = {s["args"]["step"] for s in steps}
    assert set(require_steps) <= kinds, (require_steps, kinds)


def test_crypto_chunk_spans_on_process_tracer():
    """The parallel-verify plane records dispatch instants + per-chunk
    worker spans (worker id, lane count, tier) on the process-wide
    tracer."""
    from cometbft_tpu.crypto.keys import Ed25519PrivKey
    from cometbft_tpu.crypto.parallel_verify import ParallelVerifyEngine
    from cometbft_tpu.trace import enable_global, global_tracer

    g = global_tracer()
    was_enabled = g.enabled
    enable_global()
    g.clear()
    try:
        priv = Ed25519PrivKey.from_seed(b"\x11" * 32)
        pk = priv.pub_key()
        items = []
        for i in range(40):
            m = b"chunk-span-%03d" % i
            items.append((pk, m, priv.sign(m)))
        eng = ParallelVerifyEngine(workers=2, min_parallel=8)
        try:
            assert all(eng.verify(items))
        finally:
            eng.close()
        ev = g.snapshot()
        dispatches = [
            e for e in ev if e["name"] == "crypto.batch.dispatch"
        ]
        chunks = [e for e in ev if e["name"] == "crypto.verify_chunk"]
        if eng.tier == "serial":  # restricted box: pool creation failed
            pytest.skip("no worker pool on this box")
        assert dispatches and dispatches[0]["args"]["lanes"] == 40
        assert dispatches[0]["args"]["tier"] == eng.tier
        if eng.tier == "thread":
            # thread tier shares the ring: chunk spans must be there,
            # carrying worker id + lanes + tier
            assert chunks
            assert sum(c["args"]["lanes"] for c in chunks) == 40
            assert all(
                c["args"]["tier"] == "thread" and c["tid"]
                for c in chunks
            )
    finally:
        enable_global(was_enabled)
        g.clear()


# --- 4. ISSUE 4 acceptance: 4-node chaos run with tracing ---------------


def test_chaos_run_traced_perfetto_loadable(tmp_path):
    """A 4-node in-process chaos net with tracing enabled exports a
    Perfetto-loadable trace whose consensus step spans nest correctly
    per height/round on every node, with WAL fsync spans alongside."""
    from cometbft_tpu.chaos import FaultSchedule, run_schedule

    async def main():
        return await run_schedule(
            FaultSchedule([]),  # no faults: the fast acceptance run
            seed=77,
            base_dir=str(tmp_path / "net"),
            n_nodes=4,
            settle_heights=3,
            liveness_bound_s=120.0,
            trace_dir=str(tmp_path / "traces"),
        )

    report = run(main())
    assert report.ok, report.format()
    assert report.trace_files
    jsonls = [p for p in report.trace_files if p.endswith(".jsonl")]
    chrome = [p for p in report.trace_files if p.endswith("trace.json")]
    # one ring per node (no restarts in this schedule)
    node_dumps = [p for p in jsonls if "/n" in p]
    assert len(node_dumps) == 4, report.trace_files
    assert len(chrome) == 1

    # Perfetto-loadable: valid JSON, traceEvents, process metadata for
    # every node, X events with ts+dur
    with open(chrome[0]) as f:
        ct = json.load(f)
    te = ct["traceEvents"]
    procs = {
        e["args"]["name"]
        for e in te
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert {"n0", "n1", "n2", "n3"} <= procs
    assert all(
        "ts" in e and "dur" in e for e in te if e["ph"] == "X"
    )

    by_node = read_jsonl(node_dumps)
    for node, events in by_node.items():
        _assert_consensus_nesting(
            events, min_heights=2,
            require_steps=("PROPOSE", "PREVOTE", "PRECOMMIT", "COMMIT"),
        )
        names = {e["name"] for e in events}
        # chaos homes persist a WAL: the fsync barrier must be spanned
        assert "wal.fsync" in names, (node, sorted(names))
    # and the summary machinery digests the whole dump
    s = summarize(by_node)
    assert all("consensus.step" in kinds for kinds in s.values())
