"""Light-proxy ABCI-query / tx proof verification (VERDICT r3 #5).

The reference light RPC client verifies every ABCIQuery response
against the light-verified AppHash with a merkle proof runtime — value
proofs (light/rpc/client.go:126-181), absence proofs (:183-187), and
tx inclusion proofs (:473). Unit tests cover the proof-op runtime;
the e2e test runs a real 2-node net on a prove-enabled kvstore and
shows the proxy serving verified query/tx data AND rejecting a
tampering primary.
"""

import asyncio

import aiohttp
import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.config.config import test_config as make_test_cfg
from cometbft_tpu.crypto import merkle
from cometbft_tpu.models.kvstore import KVStoreApplication
from cometbft_tpu.node.inprocess import make_genesis
from cometbft_tpu.node.node import Node


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# --- proof-op runtime units --------------------------------------------


@pytest.fixture
def proved_app():
    app = KVStoreApplication(prove=True)
    app.height = 7
    app.state = {b"a": b"1", b"c": b"3", b"e": b"5"}
    app.app_hash = app._compute_hash()
    return app


def _ops(app, key):
    res = app.query(
        abci.RequestQuery(data=key, path="/store", prove=True)
    )
    return merkle.decode_proof_ops(res.proof_ops), res


def test_value_and_absence_proofs_roundtrip(proved_app):
    rt = merkle.ProofRuntime()
    ops, res = _ops(proved_app, b"c")
    assert res.value == b"3"
    rt.verify_value(ops, proved_app.app_hash, b"c", b"3")
    # a committed EMPTY value is provable as a value (not absence).
    # NOTE: state changes follow the commit contract — a NEW dict at a
    # new height (the app's hash/proof caches key on state identity
    # and height; in-place mutation between commits never happens in
    # production)
    new_state = dict(proved_app.state)
    new_state[b"d"] = b""
    proved_app.state = new_state
    proved_app.height = 8
    proved_app.app_hash = proved_app._compute_hash()
    ops, res = _ops(proved_app, b"d")
    assert res.code == 0 and res.value == b""
    rt.verify_value(ops, proved_app.app_hash, b"d", b"")
    for k in (b"b", b"0", b"zz"):  # between / before-first / after-last
        ops, res = _ops(proved_app, k)
        assert res.code != 0
        rt.verify_absence(ops, proved_app.app_hash, k)
    # empty store
    empty = KVStoreApplication(prove=True)
    empty.height = 1
    empty.app_hash = empty._compute_hash()
    ops, _ = _ops(empty, b"x")
    rt.verify_absence(ops, empty.app_hash, b"x")


def test_tampered_proofs_rejected(proved_app):
    rt = merkle.ProofRuntime()
    ops, _ = _ops(proved_app, b"c")
    with pytest.raises(merkle.ProofError):
        rt.verify_value(ops, proved_app.app_hash, b"c", b"4")
    with pytest.raises(merkle.ProofError):
        rt.verify_value(ops, b"\x00" * 32, b"c", b"3")
    # absence claim for an existing key via rewritten ops
    ops, _ = _ops(proved_app, b"b")
    ops[0].key = b"c"
    with pytest.raises(merkle.ProofError):
        rt.verify_absence(ops, proved_app.app_hash, b"c")
    # corrupt an aunt in the inclusion proof
    ops, _ = _ops(proved_app, b"a")
    p = merkle.decode_proof(ops[0].data)
    p.aunts[0] = bytes(32)
    ops[0].data = merkle.encode_proof(p)
    with pytest.raises(merkle.ProofError):
        rt.verify_value(ops, proved_app.app_hash, b"a", b"1")


def test_out_of_bounds_proof_indices_rejected(proved_app):
    """ADVICE r4 (medium): the extreme leaves' inclusion proofs ALSO
    recompute the correct root under inflated (rightmost) / negative
    (leftmost) indices — _leaf_root must enforce 0 <= index < total
    itself, or the absence-op adjacency checks sit on unverified index
    integrity."""
    rt = merkle.ProofRuntime()

    def mutate(key, value, index=None, total=None):
        ops, _ = _ops(proved_app, key)
        p = merkle.decode_proof(ops[0].data)
        if index is not None:
            p.index = index
        if total is not None:
            p.total = total
        ops[0].data = merkle.encode_proof(p)
        return ops

    # rightmost leaf (b"e", index 2 of 3): inflated index
    for idx, tot in ((5, 3), (3, 3), (2, 0), (2, -1)):
        with pytest.raises(merkle.ProofError):
            rt.verify_value(
                mutate(b"e", b"5", index=idx, total=tot),
                proved_app.app_hash, b"e", b"5",
            )
    # leftmost leaf (b"a", index 0): negative index
    with pytest.raises(merkle.ProofError):
        rt.verify_value(
            mutate(b"a", b"1", index=-1),
            proved_app.app_hash, b"a", b"1",
        )
    # unmutated controls still verify
    ops, _ = _ops(proved_app, b"e")
    rt.verify_value(ops, proved_app.app_hash, b"e", b"5")


# --- e2e: proxy over a live net ----------------------------------------


def test_light_cli_proxy_mode():
    """`cometbft-tpu light --laddr ...` serves the verified proxy (the
    reference command's primary role): drive the CLI as a subprocess
    against a live net and query a proof-verified key through it."""
    import socket
    import subprocess
    import sys

    gen, pvs = make_genesis(2, chain_id="cli-proxy")

    async def main():
        n0 = Node(
            make_test_cfg("."), gen, privval=pvs[0],
            app=KVStoreApplication(prove=True),
        )
        n1 = Node(
            make_test_cfg("."), gen, privval=pvs[1],
            app=KVStoreApplication(prove=True),
        )
        await n0.start()
        await n1.start()
        await n0.dial(n1.listen_addr)
        async with aiohttp.ClientSession() as s:
            async with s.get(
                f"http://{n0.rpc_server.listen_addr}"
                "/broadcast_tx_commit?tx=0x" + (b"cli=proxy").hex()
            ) as resp:
                assert (await resp.json())["result"]
        while n0.height < 4:
            await asyncio.sleep(0.05)

        trust = n0.parts.block_store.load_block(1)
        with socket.socket() as sock:
            sock.bind(("127.0.0.1", 0))
            port = sock.getsockname()[1]
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "cometbft_tpu", "light",
                "cli-proxy",
                "-p", n0.rpc_server.listen_addr,
                "--trust-height", "1",
                "--trust-hash", trust.hash().hex(),
                "--laddr", f"tcp://127.0.0.1:{port}",
                "--sequential",  # reference cmd light --sequential
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        try:
            deadline = asyncio.get_running_loop().time() + 30
            body = None
            async with aiohttp.ClientSession() as s:
                while asyncio.get_running_loop().time() < deadline:
                    try:
                        async with s.get(
                            f"http://127.0.0.1:{port}/abci_query?"
                            'path="/store"&data=0x' + b"cli".hex()
                        ) as resp:
                            body = await resp.json()
                        if body.get("result"):
                            break
                    except Exception:
                        pass
                    await asyncio.sleep(0.3)
            assert body and body.get("result"), body
            assert body["result"]["verified"] is True
            import base64

            assert (
                base64.b64decode(body["result"]["response"]["value"])
                == b"proxy"
            )
        finally:
            proc.terminate()
            proc.wait(10)
        await n0.stop()
        await n1.stop()

    run(main())


def test_light_cli_dir_persists_trust_across_restarts(tmp_path):
    """`light --dir` (reference light home db): a restarted daemon
    resumes from its last VERIFIED header — demonstrated by
    restarting with a BOGUS trust root, which an empty store would
    reject but a persisted one never consults."""
    import socket
    import subprocess
    import sys

    gen, pvs = make_genesis(2, chain_id="cli-dir")

    async def main():
        n0 = Node(make_test_cfg("."), gen, privval=pvs[0])
        n1 = Node(make_test_cfg("."), gen, privval=pvs[1])
        await n0.start()
        await n1.start()
        await n0.dial(n1.listen_addr)
        while n0.height < 4:
            await asyncio.sleep(0.05)
        trust = n0.parts.block_store.load_block(1)

        async def run_once(trust_hash):
            with socket.socket() as sock:
                sock.bind(("127.0.0.1", 0))
                port = sock.getsockname()[1]
            proc = subprocess.Popen(
                [
                    sys.executable, "-m", "cometbft_tpu", "light",
                    "cli-dir",
                    "-p", n0.rpc_server.listen_addr,
                    "--trust-height", "1",
                    "--trust-hash", trust_hash,
                    "--dir", str(tmp_path / "lighthome"),
                    "--laddr", f"tcp://127.0.0.1:{port}",
                ],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
            try:
                deadline = asyncio.get_running_loop().time() + 40
                async with aiohttp.ClientSession() as s:
                    while asyncio.get_running_loop().time() < deadline:
                        try:
                            async with s.get(
                                f"http://127.0.0.1:{port}/status"
                            ) as resp:
                                body = await resp.json()
                            if body.get("result", {}).get("verified"):
                                return body["result"]
                        except Exception:
                            pass
                        await asyncio.sleep(0.3)
            finally:
                proc.terminate()
                proc.wait(10)
            raise AssertionError("light proxy never served status")

        first = await run_once(trust.hash().hex())
        assert int(first["sync_info"]["latest_block_height"]) >= 1
        # same-root restart resumes from the persisted store
        second = await run_once(trust.hash().hex())
        assert int(second["sync_info"]["latest_block_height"]) >= 1
        # a MISMATCHED root against the persisted store must REFUSE to
        # start (reference checkTrustedHeaderAgainstOptions), not
        # silently serve either chain of trust
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "cometbft_tpu", "light",
                "cli-dir",
                "-p", n0.rpc_server.listen_addr,
                "--trust-height", "1",
                "--trust-hash", "00" * 32,
                "--dir", str(tmp_path / "lighthome"),
                "--laddr", "tcp://127.0.0.1:0",
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        out, _ = await asyncio.to_thread(proc.communicate, None, 40)
        assert proc.returncode != 0, out[-400:]
        assert "re-rooting" in out, out[-400:]
        await n0.stop()
        await n1.stop()

    run(main())


class _TamperingPrimary:
    """Wraps the proxy's HTTPClient; corrupts selected responses the
    way a byzantine full node would."""

    def __init__(self, real):
        self._real = real
        self.mode = None  # None | "value" | "absence" | "tx"

    async def call(self, method, **params):
        if method == "abci_query" and self.mode == "substitute":
            # answer with ANOTHER committed key's fully-genuine
            # response (valid proof for the wrong key)
            params = dict(params, data="0x" + b"other".hex())
            return await self._real.call(method, **params)
        res = await self._real.call(method, **params)
        if method == "abci_query" and self.mode == "value":
            import base64

            res["response"]["value"] = base64.b64encode(
                b"forged"
            ).decode()
        if method == "abci_query" and self.mode == "absence":
            res["response"]["code"] = 1
            res["response"]["value"] = ""
        if method == "tx" and self.mode == "tx":
            import base64

            res["tx"] = base64.b64encode(b"forged-tx=1").decode()
        if method == "tx" and self.mode == "txheight":
            # malformed/malicious: no committed height — must not
            # resolve the proof against a primary-chosen latest block
            res["height"] = "0"
        if method == "block_results" and self.mode == "results":
            for tr in res.get("txs_results") or []:
                tr["gas_used"] = str(int(tr.get("gas_used") or 0) + 7)
        if method == "consensus_params" and self.mode == "params":
            import base64

            from cometbft_tpu.state.state_types import ConsensusParams

            cp = ConsensusParams.decode(
                base64.b64decode(res["params_b64"])
            )
            cp.block.max_bytes += 1  # forged limit
            res["params_b64"] = base64.b64encode(cp.encode()).decode()
        if method == "consensus_params" and self.mode == "params_dict":
            # forge only the human-readable fields, keep bytes honest
            res["consensus_params"]["block"]["max_bytes"] = "1"
        return res

    def __getattr__(self, name):
        return getattr(self._real, name)


def test_proxy_verifies_queries_and_rejects_tampering():
    gen, pvs = make_genesis(2, chain_id="lproxy-prove")

    async def main():
        n0 = Node(
            make_test_cfg("."),
            gen,
            privval=pvs[0],
            app=KVStoreApplication(prove=True),
        )
        n1 = Node(
            make_test_cfg("."),
            gen,
            privval=pvs[1],
            app=KVStoreApplication(prove=True),
        )
        await n0.start()
        await n1.start()
        await n0.dial(n1.listen_addr)
        # land two txs and let them commit (the second key feeds the
        # substitution tamper case)
        async with aiohttp.ClientSession() as s:
            for txb in (b"foo=bar", b"other=val"):
                async with s.get(
                    f"http://{n0.rpc_server.listen_addr}"
                    "/broadcast_tx_commit?tx=0x" + txb.hex()
                ) as resp:
                    body = await resp.json()
        tx_height = int(body["result"]["height"])
        tx_hash_hex = body["result"]["hash"]
        while n0.height < tx_height + 2:
            await asyncio.sleep(0.05)

        from cometbft_tpu.light import Client, TrustOptions
        from cometbft_tpu.light.http_provider import HTTPProvider
        from cometbft_tpu.light.proxy import LightProxy

        trust = n0.parts.block_store.load_block(1)
        lc = await asyncio.to_thread(
            Client,
            "lproxy-prove",
            TrustOptions(
                period_ns=3600 * 10**9, height=1, hash=trust.hash()
            ),
            HTTPProvider("lproxy-prove", n0.rpc_server.listen_addr),
        )
        proxy = LightProxy(lc, n0.rpc_server.listen_addr)
        tamper = _TamperingPrimary(proxy.primary)
        proxy.primary = tamper
        await proxy.start("127.0.0.1:0")

        async def get(path):
            async with aiohttp.ClientSession() as s:
                async with s.get(
                    f"http://{proxy.listen_addr}{path}"
                ) as resp:
                    return await resp.json()

        # 1. verified value query
        body = await get(
            '/abci_query?path="/store"&data=0x' + b"foo".hex()
        )
        r = body.get("result") or pytest.fail(str(body))
        assert r["verified"] is True
        import base64

        assert base64.b64decode(r["response"]["value"]) == b"bar"

        # 2. verified absence query
        body = await get(
            '/abci_query?path="/store"&data=0x' + b"nope".hex()
        )
        assert body["result"]["verified"] is True
        assert int(body["result"]["response"]["code"]) != 0

        # 3. tampered value -> rejected
        tamper.mode = "value"
        body = await get(
            '/abci_query?path="/store"&data=0x' + b"foo".hex()
        )
        assert "error" in body and body["error"], body

        # 4. forged absence of an existing key -> rejected
        tamper.mode = "absence"
        body = await get(
            '/abci_query?path="/store"&data=0x' + b"foo".hex()
        )
        assert "error" in body and body["error"], body

        # 5. substituted (genuinely-provable) OTHER key -> rejected
        tamper.mode = "substitute"
        body = await get(
            '/abci_query?path="/store"&data=0x' + b"foo".hex()
        )
        assert "error" in body and body["error"], body
        tamper.mode = None

        # 5. verified tx inclusion
        body = await get(f"/tx?hash={tx_hash_hex}")
        assert body["result"]["verified"] is True

        # 6. forged tx bytes -> rejected
        tamper.mode = "tx"
        body = await get(f"/tx?hash={tx_hash_hex}")
        assert "error" in body and body["error"], body

        # 7. tx lookup WITHOUT a hash param -> refused up front (the
        # identity check would otherwise have nothing to bind to)
        tamper.mode = None
        body = await get("/tx")
        assert "error" in body and body["error"], body

        # 8. zeroed height in the response -> rejected before any
        # light-block resolution
        tamper.mode = "txheight"
        body = await get(f"/tx?hash={tx_hash_hex}")
        assert "error" in body and body["error"], body

        # 9. verified block_results (tx-results root vs the NEXT
        # trusted header's LastResultsHash) — VERDICT r4 missing #1
        tamper.mode = None
        body = await get(f"/block_results?height={tx_height}")
        r = body.get("result") or pytest.fail(str(body))
        assert r["verified"] is True
        assert len(r["txs_results"]) >= 1

        # 10. tampered tx results -> rejected
        tamper.mode = "results"
        body = await get(f"/block_results?height={tx_height}")
        assert "error" in body and body["error"], body
        tamper.mode = None

        # 11. height-less block_results: serves latest-1, verified
        body = await get("/block_results")
        assert body["result"]["verified"] is True

        # 12. verified consensus_params (hash vs the trusted header's
        # consensus_hash, reference light/rpc/client.go:229-256)
        body = await get(f"/consensus_params?height={tx_height}")
        assert body["result"]["verified"] is True, body

        # 13. forged params -> rejected
        tamper.mode = "params"
        body = await get(f"/consensus_params?height={tx_height}")
        assert "error" in body and body["error"], body
        tamper.mode = None

        # 14. forged human-readable dict next to honest params_b64:
        # the proxy serves the dict REBUILT from the verified bytes,
        # so the forgery never reaches the caller
        tamper.mode = "params_dict"
        body = await get(f"/consensus_params?height={tx_height}")
        r = body["result"]
        assert r["verified"] is True
        assert int(r["consensus_params"]["block"]["max_bytes"]) != 1
        tamper.mode = None

        await proxy.stop()
        await n0.stop()
        await n1.stop()

    run(main())
