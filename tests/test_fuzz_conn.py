"""FuzzedConnection determinism + teardown (ISSUE 2 satellite): same
seed => identical drop/delay decisions, prob_drop_conn actually tears
the connection down, and the injected-rng composition hook."""

import asyncio
import random

import pytest

from cometbft_tpu.p2p.fuzz import (
    MODE_DELAY,
    FuzzConnConfig,
    FuzzedConnection,
    maybe_fuzz,
)


class FakeSconn:
    def __init__(self, chunks=()):
        self.writes = []
        self.chunks = list(chunks)
        self.closed = False

    async def write_msg(self, data):
        self.writes.append(bytes(data))
        return len(data)

    async def read_chunk(self):
        if not self.chunks:
            raise ConnectionError("out of chunks")
        return self.chunks.pop(0)

    def close(self):
        self.closed = True


def run(coro, timeout=30):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def _drive_writes(cfg, n=200, rng=None):
    async def main():
        inner = FakeSconn()
        fc = FuzzedConnection(inner, cfg, rng=rng)
        delivered = []
        for i in range(n):
            await fc.write_msg(bytes([i & 0xFF]))
            delivered.append(len(inner.writes))
        return delivered, fc.dropped_writes

    return run(main())


def test_same_seed_identical_drop_decisions():
    runs = [
        _drive_writes(FuzzConnConfig(enable=True, prob_drop_rw=0.4, seed=9))
        for _ in range(2)
    ]
    assert runs[0] == runs[1]
    delivered, dropped = runs[0]
    assert dropped > 0 and delivered[-1] + dropped == 200
    # a different seed must (overwhelmingly) diverge
    other = _drive_writes(
        FuzzConnConfig(enable=True, prob_drop_rw=0.4, seed=10)
    )
    assert other != runs[0]


def test_same_seed_identical_read_decisions():
    async def drive():
        cfg = FuzzConnConfig(enable=True, prob_drop_rw=0.3, seed=4)
        inner = FakeSconn(chunks=[bytes([i]) for i in range(100)])
        fc = FuzzedConnection(inner, cfg)
        got = []
        try:
            while True:
                got.append(await fc.read_chunk())
        except ConnectionError:
            pass  # out of chunks
        return got, fc.dropped_reads

    a = run(drive())
    b = run(drive())
    assert a == b
    got, dropped = a
    assert dropped > 0 and len(got) + dropped == 100


def test_delay_mode_draws_deterministic():
    async def drive():
        cfg = FuzzConnConfig(
            enable=True,
            mode=MODE_DELAY,
            prob_sleep=0.5,
            max_delay_ms=100,
            seed=21,
        )
        inner = FakeSconn()
        fc = FuzzedConnection(inner, cfg)
        sleeps = []
        real_sleep = asyncio.sleep

        async def spy(d):
            sleeps.append(round(d, 9))
            await real_sleep(0)

        asyncio.sleep = spy
        try:
            for i in range(100):
                await fc.write_msg(b"x")
        finally:
            asyncio.sleep = real_sleep
        # delay mode never drops
        assert len(inner.writes) == 100
        return sleeps

    a = run(drive())
    b = run(drive())
    assert a == b and a
    assert all(0 <= d <= 0.1 for d in a)


def test_prob_drop_conn_tears_connection_down():
    async def main():
        cfg = FuzzConnConfig(enable=True, prob_drop_conn=1.0, seed=1)
        inner = FakeSconn(chunks=[b"x"])
        fc = FuzzedConnection(inner, cfg)
        with pytest.raises(ConnectionError):
            await fc.write_msg(b"dead")
        # the underlying connection was CLOSED, not just refused
        assert inner.closed
        assert not inner.writes
        # and the connection stays dead for every later op
        with pytest.raises(ConnectionError):
            await fc.read_chunk()
        with pytest.raises(ConnectionError):
            await fc.write_msg(b"still dead")

    run(main())


def test_drop_conn_probability_is_seed_deterministic():
    async def drive():
        cfg = FuzzConnConfig(
            enable=True, prob_drop_conn=0.02, prob_drop_rw=0.1, seed=77
        )
        inner = FakeSconn()
        fc = FuzzedConnection(inner, cfg)
        for i in range(1000):
            try:
                await fc.write_msg(b"y")
            except ConnectionError:
                return i  # the op index the connection died at
        return None

    assert run(drive()) == run(drive()) is not None


def test_injected_rng_overrides_config_seed():
    """The chaos link plane injects its own per-link stream; the
    config seed must then be ignored."""
    a = _drive_writes(
        FuzzConnConfig(enable=True, prob_drop_rw=0.4, seed=1),
        rng=random.Random(123),
    )
    b = _drive_writes(
        FuzzConnConfig(enable=True, prob_drop_rw=0.4, seed=2),
        rng=random.Random(123),
    )
    assert a == b


def test_maybe_fuzz_passthrough():
    inner = FakeSconn()
    assert maybe_fuzz(inner, None) is inner
    assert maybe_fuzz(inner, FuzzConnConfig(enable=False)) is inner
    assert isinstance(
        maybe_fuzz(inner, FuzzConnConfig(enable=True)), FuzzedConnection
    )
