"""P2P stack tests: secret connection, MConnection multiplexing,
switch-level nets (reference analog: p2p/conn/*_test.go,
p2p/switch_test.go via MakeConnectedSwitches)."""

import asyncio
import socket

import pytest

from cometbft_tpu.p2p import (
    ChannelDescriptor,
    MemoryTransport,
    NodeInfo,
    NodeKey,
    Reactor,
    Switch,
    TCPTransport,
    node_id_from_pubkey,
)
from cometbft_tpu.p2p.conn.connection import MConnection
from cometbft_tpu.p2p.conn.secret_connection import (
    HandshakeError,
    SecretConnection,
)


def run(coro, timeout=30):
    return asyncio.run(asyncio.wait_for(coro, timeout))


async def _pair():
    a, b = socket.socketpair()
    a.setblocking(False)
    b.setblocking(False)
    r1, w1 = await asyncio.open_connection(sock=a)
    r2, w2 = await asyncio.open_connection(sock=b)
    return (r1, w1), (r2, w2)


async def _sconn_pair(k1=None, k2=None):
    k1 = k1 or NodeKey.generate()
    k2 = k2 or NodeKey.generate()
    (r1, w1), (r2, w2) = await _pair()
    c1, c2 = await asyncio.gather(
        SecretConnection.handshake(r1, w1, k1.priv_key),
        SecretConnection.handshake(r2, w2, k2.priv_key),
    )
    return c1, c2, k1, k2


def test_secret_connection_identity_and_roundtrip():
    async def main():
        c1, c2, k1, k2 = await _sconn_pair()
        # each side learns the other's REAL pubkey
        assert bytes(c1.remote_pubkey) == bytes(k2.priv_key.pub_key())
        assert bytes(c2.remote_pubkey) == bytes(k1.priv_key.pub_key())
        await c1.write_msg(b"hello")
        assert await c2.read_chunk() == b"hello"
        # large message spans frames
        big = bytes(range(256)) * 20  # 5120 bytes
        await c2.write_msg(big)
        got = b""
        while len(got) < len(big):
            got += await c1.read_chunk()
        assert got == big

    run(main())


def test_secret_connection_tamper_detected():
    async def main():
        c1, c2, _, _ = await _sconn_pair()
        sealed = c1._seal(b"payload")
        tampered = bytes([sealed[0] ^ 0xFF]) + sealed[1:]
        with pytest.raises(Exception):
            c2._open(tampered)

    run(main())


def test_mconnection_multiplex_and_reassembly():
    async def main():
        c1, c2, _, _ = await _sconn_pair()
        got = {}
        done = asyncio.Event()

        def on_recv(cid, msg):
            got.setdefault(cid, []).append(msg)
            if sum(len(v) for v in got.values()) == 3:
                done.set()

        m1 = MConnection(c1, [(0x20, 5), (0x30, 1)], on_receive=lambda c, m: None)
        m2 = MConnection(c2, [(0x20, 5), (0x30, 1)], on_receive=on_recv)
        m1.start()
        m2.start()
        big = b"x" * 5000  # multi-packet message
        await m1.send(0x20, b"vote")
        await m1.send(0x30, big)
        await m1.send(0x20, b"proposal")
        await asyncio.wait_for(done.wait(), 10)
        assert got[0x20] == [b"vote", b"proposal"]
        assert got[0x30] == [big]
        await m1.stop()
        await m2.stop()

    run(main())


def test_mconnection_ping_pong_keepalive():
    async def main():
        c1, c2, _, _ = await _sconn_pair()
        errs = []
        m1 = MConnection(
            c1, [(0, 1)], on_receive=lambda c, m: None,
            on_error=errs.append, ping_interval_s=0.05, pong_timeout_s=1.0,
        )
        m2 = MConnection(c2, [(0, 1)], on_receive=lambda c, m: None)
        m1.start()
        m2.start()
        await asyncio.sleep(0.4)  # several ping cycles must survive
        assert not errs
        await m1.stop()
        await m2.stop()

    run(main())


class EchoReactor(Reactor):
    name = "echo"
    CHAN = 0x77

    def __init__(self):
        super().__init__()
        self.got = []
        self.peers_seen = []
        self.removed = []

    def get_channels(self):
        return [ChannelDescriptor(self.CHAN, priority=3)]

    def add_peer(self, peer):
        self.peers_seen.append(peer.peer_id)

    def remove_peer(self, peer, reason):
        self.removed.append(peer.peer_id)

    def receive(self, chan_id, peer, msg):
        self.got.append((peer.peer_id, msg))
        if not msg.startswith(b"ack:"):
            peer.try_send(chan_id, b"ack:" + msg)


def _make_switch(chain_id="p2p-test", transport_cls=TCPTransport):
    nk = NodeKey.generate()
    info = NodeInfo(node_id=nk.node_id, network=chain_id)
    tr = transport_cls(nk, info)
    sw = Switch(tr, info)
    er = sw.add_reactor("echo", EchoReactor())
    return sw, er


def test_switch_tcp_connect_broadcast():
    async def main():
        sw1, er1 = _make_switch()
        sw2, er2 = _make_switch()
        await sw1.transport.listen("127.0.0.1:0")
        await sw2.transport.listen("127.0.0.1:0")
        await sw1.start()
        await sw2.start()
        await sw1.dial_peer(sw2.transport.listen_addr)
        for _ in range(100):
            if sw2.num_peers() and sw1.num_peers():
                break
            await asyncio.sleep(0.05)
        assert sw1.num_peers() == 1 and sw2.num_peers() == 1
        assert er1.peers_seen and er2.peers_seen
        sw1.broadcast(EchoReactor.CHAN, b"ping-all")
        for _ in range(100):
            if er1.got:
                break
            await asyncio.sleep(0.05)
        # sw2 received and acked
        assert (sw1.node_info.node_id, b"ping-all") in er2.got
        assert (sw2.node_info.node_id, b"ack:ping-all") in er1.got
        await sw1.stop()
        await sw2.stop()

    run(main())


def test_switch_network_mismatch_rejected():
    async def main():
        sw1, _ = _make_switch(chain_id="chain-A")
        sw2, _ = _make_switch(chain_id="chain-B")
        await sw1.transport.listen("127.0.0.1:0")
        await sw2.transport.listen("127.0.0.1:0")
        await sw1.start()
        await sw2.start()
        with pytest.raises(Exception):
            await sw1.dial_peer(sw2.transport.listen_addr)
        assert sw1.num_peers() == 0
        await sw1.stop()
        await sw2.stop()

    run(main())


def test_switch_wrong_id_rejected():
    async def main():
        sw1, _ = _make_switch()
        sw2, _ = _make_switch()
        await sw1.transport.listen("127.0.0.1:0")
        await sw2.transport.listen("127.0.0.1:0")
        await sw1.start()
        await sw2.start()
        bogus_id = "00" * 20
        with pytest.raises(Exception):
            await sw1.dial_peer(
                f"{bogus_id}@{sw2.transport.listen_addr}"
            )
        assert sw1.num_peers() == 0
        await sw1.stop()
        await sw2.stop()

    run(main())


def test_switch_memory_transport_net():
    async def main():
        sws = [
            _make_switch(transport_cls=MemoryTransport) for _ in range(3)
        ]
        for sw, _ in sws:
            await sw.transport.listen()
            await sw.start()
        # fully connect
        for i, (sw, _) in enumerate(sws):
            for j, (other, _) in enumerate(sws):
                if j > i:
                    await sw.dial_peer(other.transport.listen_addr)
        assert all(sw.num_peers() == 2 for sw, _ in sws)
        sws[0][0].broadcast(EchoReactor.CHAN, b"hello-mem")
        for _ in range(100):
            if len(sws[0][1].got) >= 2:
                break
            await asyncio.sleep(0.05)
        acks = [m for _, m in sws[0][1].got if m == b"ack:hello-mem"]
        assert len(acks) == 2
        for sw, _ in sws:
            await sw.stop()

    run(main())


def test_peer_error_removes_and_notifies_reactors():
    async def main():
        sw1, er1 = _make_switch()
        sw2, er2 = _make_switch()
        await sw1.transport.listen("127.0.0.1:0")
        await sw2.transport.listen("127.0.0.1:0")
        await sw1.start()
        await sw2.start()
        peer = await sw1.dial_peer(sw2.transport.listen_addr)
        for _ in range(100):
            if sw2.num_peers():
                break
            await asyncio.sleep(0.05)
        sw1.stop_peer_for_error(peer, RuntimeError("test"))
        for _ in range(100):
            if er1.removed and sw1.num_peers() == 0:
                break
            await asyncio.sleep(0.05)
        assert er1.removed == [peer.peer_id]
        await sw1.stop()
        await sw2.stop()

    run(main())
