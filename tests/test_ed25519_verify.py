"""End-to-end tests of the TPU ed25519 batch verify kernel.

Differential vs the pure-python ZIP-215 oracle and OpenSSL signatures.
"""

import hashlib
import os
import random

import numpy as np

from cometbft_tpu.crypto import ref_ed25519 as ref
from cometbft_tpu.ops import ed25519 as ed
from cometbft_tpu.ops import sha512 as dsha

import pytest

pytestmark = [pytest.mark.tpu, pytest.mark.slow]  # tpu implies slow: keeps the `-m 'not slow'` fast lane kernel-free

rng = random.Random(7)


def test_sha512_device():
    import jax.numpy as jnp

    msgs = [b"", b"abc", b"a" * 111, b"b" * 112, b"c" * 239, os.urandom(200)]
    cap = 239
    n = len(msgs)
    data = np.zeros((cap, n), np.uint8)
    lens = np.zeros(n, np.int32)
    for i, m in enumerate(msgs):
        data[: len(m), i] = np.frombuffer(m, np.uint8)
        lens[i] = len(m)
    dig = np.asarray(dsha.sha512(jnp.asarray(data), jnp.asarray(lens), cap))
    for i, m in enumerate(msgs):
        assert bytes(dig[:, i]) == hashlib.sha512(m).digest(), i


def _signed_items(k):
    items, want = [], []
    for i in range(k):
        seed = os.urandom(32)
        pub = ref.public_from_seed(seed)
        msg = os.urandom(rng.randrange(0, 170))
        sig = ref.sign(seed, msg)
        items.append((msg, pub, sig))
        want.append(True)
    return items, want


def test_verify_valid_batch():
    items, want = _signed_items(9)
    got = ed.verify_batch(items)
    assert list(got) == want


def test_verify_rejects_tampered():
    items, _ = _signed_items(6)
    bad = []
    # tamper: message, sig R, sig S, pubkey, non-canonical S, short sig
    m, pk, sig = items[0]
    bad.append((m + b"!", pk, sig))
    m, pk, sig = items[1]
    bad.append((m, pk, bytes([sig[0] ^ 1]) + sig[1:]))
    m, pk, sig = items[2]
    bad.append((m, pk, sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]))
    m, pk, sig = items[3]
    other_pk = ref.public_from_seed(os.urandom(32))
    bad.append((m, other_pk, sig))
    m, pk, sig = items[4]
    s_big = (int.from_bytes(sig[32:], "little") + ref.L) % 2**256
    bad.append((m, pk, sig[:32] + s_big.to_bytes(32, "little")))
    m, pk, sig = items[5]
    bad.append((m, pk, sig[:63]))
    got = ed.verify_batch(bad)
    # each lane must agree with the python oracle
    for i, (m, pk, sig) in enumerate(bad):
        assert bool(got[i]) == ref.verify_zip215(pk, m, sig), i
    assert not got.any()


def test_verify_mixed_batch_lanes_independent():
    items, _ = _signed_items(5)
    items[2] = (items[2][0] + b"x", items[2][1], items[2][2])
    got = ed.verify_batch(items)
    assert list(got) == [True, True, False, True, True]


def test_verify_zip215_edge_cases():
    # identity pubkey + identity R + S=0 is valid under cofactored rules
    ident = ref.point_compress(ref.IDENTITY)
    items = [(b"whatever", ident, ident + b"\x00" * 32)]
    # small-order point encodings (order 2: y = -1)
    small = (ref.P - 1).to_bytes(32, "little")
    items.append((b"msg", small, ident + b"\x00" * 32))
    # non-canonical y >= p encoding of the identity
    noncanon = (ref.P + 1).to_bytes(32, "little")
    items.append((b"m2", noncanon, ident + b"\x00" * 32))
    got = ed.verify_batch(items)
    for i, (m, pk, sig) in enumerate(items):
        assert bool(got[i]) == ref.verify_zip215(pk, m, sig), i


def test_verify_openssl_cross():
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
    )

    items = []
    for _ in range(4):
        sk = Ed25519PrivateKey.generate()
        pk = sk.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        msg = os.urandom(100)
        items.append((msg, pk, sk.sign(msg)))
    assert list(ed.verify_batch(items)) == [True] * 4
