"""Remote signer + ABCI vote extensions: the extension signature rides
the SIGN_VOTE round trip (a remote-signer validator must not be
expelled from consensus when extensions are enabled)."""

import asyncio
import os
import tempfile

import pytest

from cometbft_tpu import types as T
from cometbft_tpu.node.inprocess import make_genesis
from cometbft_tpu.privval.signer import SignerClient, SignerServer


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def test_extension_signed_over_the_wire():
    async def main():
        gen, pvs = make_genesis(1, chain_id="rsx-chain")
        signer_pv = pvs[0]
        client = SignerClient("127.0.0.1:0")
        server = SignerServer(signer_pv, client.listen_addr)
        task = asyncio.create_task(server.serve())
        await asyncio.sleep(0.2)
        try:
            pub = await asyncio.to_thread(client.pub_key)
            bid = T.BlockID(b"\x11" * 32, T.PartSetHeader(1, b"\x22" * 32))
            vote = T.Vote(
                type_=T.PRECOMMIT,
                height=7,
                round=0,
                block_id=bid,
                timestamp_ns=123,
                validator_address=pub.address(),
                validator_index=0,
                extension=b"ext|7|payload",
            )
            await asyncio.to_thread(client.sign_vote, "rsx-chain", vote)
            # both signatures arrived in ONE round trip
            assert pub.verify(
                vote.sign_bytes("rsx-chain"), vote.signature
            )
            assert vote.extension_signature
            assert pub.verify(
                vote.extension_sign_bytes("rsx-chain"),
                vote.extension_signature,
            )
            # sign_vote_extension after the fact is a cheap no-op
            before = vote.extension_signature
            await asyncio.to_thread(
                client.sign_vote_extension, "rsx-chain", vote
            )
            assert vote.extension_signature == before

            # EMPTY extensions are signed too (default apps return
            # vote_extension=b""; peers at enabled heights require the
            # signature regardless of payload — FilePV parity)
            vote2 = T.Vote(
                type_=T.PRECOMMIT,
                height=8,
                round=0,
                block_id=bid,
                timestamp_ns=124,
                validator_address=pub.address(),
                validator_index=0,
            )
            await asyncio.to_thread(client.sign_vote, "rsx-chain", vote2)
            assert not vote2.extension_signature  # no ext in sign_vote
            await asyncio.to_thread(
                client.sign_vote_extension, "rsx-chain", vote2
            )
            assert vote2.extension_signature
            assert pub.verify(
                vote2.extension_sign_bytes("rsx-chain"),
                vote2.extension_signature,
            )
        finally:
            server.stop()
            task.cancel()

    run(main())
