"""Generated-manifest e2e lane (reference test/e2e/generator):
deterministic seeds -> random testnets -> full runner pass.

Default lane runs one seeded net; widen with
E2E_GEN_SEEDS="2,3,4" for soak runs. A failure names its seed, and the
seed alone reproduces the exact manifest.
"""

import asyncio
import os

import pytest

from cometbft_tpu.e2e.generator import generate_one
from cometbft_tpu.e2e import runner as runner_mod
from cometbft_tpu.e2e.runner import Runner

_SEEDS = [
    int(s)
    for s in os.environ.get("E2E_GEN_SEEDS", "1").split(",")
    if s.strip()
]


def test_generator_is_deterministic():
    a, b = generate_one(42), generate_one(42)
    assert a == b
    # different seeds explore the space
    assert any(generate_one(s) != a for s in range(43, 50))


def test_generated_manifests_valid():
    """Every generated net satisfies the manifest invariants across a
    seed sweep (cheap, no processes)."""
    for seed in range(100):
        m = generate_one(seed)
        assert any(
            n.mode == "validator" and n.start_at == 0
            for n in m.nodes.values()
        ), seed
        for n in m.nodes.values():
            if n.start_at > 0 and n.mode != "light":
                # light nodes sync via the light protocol, not
                # block/state sync
                assert n.block_sync or n.state_sync, (seed, n.name)
            for p in n.perturbations:
                assert 0 < p.height < m.target_height, (seed, n.name)
        # evidence perturbations only in nets with >2 validators
        n_vals = sum(
            1 for n in m.nodes.values() if n.mode == "validator"
        )
        if any(
            p.kind == "evidence"
            for n in m.nodes.values()
            for p in n.perturbations
        ):
            assert n_vals > 2, seed


@pytest.mark.slow
@pytest.mark.parametrize("seed", _SEEDS)
def test_generated_net_runs(tmp_path, seed):
    m = generate_one(seed)
    runner = Runner(
        # 30 ports/seed: up to 7 nodes x 3 ports each (p2p, rpc, grpc)
        # with headroom — adjacent seeds must never overlap when run
        # concurrently
        m, str(tmp_path / f"gen{seed}"), base_port=27600 + (seed % 50) * 30
    )
    runner.setup()
    try:
        ok = asyncio.run(
            asyncio.wait_for(
                runner.run(timeout_s=240.0),
                240
                + runner_mod.CONVERGENCE_BUDGET_S
                + runner_mod.POST_BUDGET_S,
            )
        )
    finally:
        runner.stop()
    assert ok, (seed, runner.failures)
