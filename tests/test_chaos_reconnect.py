"""Self-healing connectivity under the chaos plane (ISSUE 12).

1. ``reconnect_storm`` nemesis: repeated partition/heal cycles +
   targeted pong-timeout conn kills — the compound that used to
   exhaust the finite reconnect budget and permanently isolate a
   healed minority. With the plane, every heal must reconverge
   (liveness holds) inside the ``p2p.reconnect`` span budget.
2. The UN-PINNED matrix compound: partition x statesync_join x
   valset_churn — a seeded scenario that the generator previously
   forced to a clean network — runs invariant- AND budget-clean, with
   the mid-load joiner (and every validator) reaching the committed
   head: zero permanently-isolated nodes.
"""

import asyncio
from pathlib import Path

from cometbft_tpu.chaos import (
    FaultEvent,
    FaultSchedule,
    generate_scenario,
    run_scenario,
    run_schedule,
)

REPO_ROOT = Path(__file__).resolve().parents[1]
BUDGETS = str(REPO_ROOT / "tools" / "span_budgets.toml")

SEED = 1337
# scenario index of master seed 1337 whose axes are
# partition x statesync_join (lifecycle cycle: index % 5 == 1); the
# assertion below keeps this pin honest if the generator changes
PARTITION_JOIN_INDEX = 11


def run(coro, timeout=300):
    async def main():
        try:
            return await asyncio.wait_for(coro, timeout)
        finally:
            import sys

            cur = asyncio.current_task()
            for t in asyncio.all_tasks():
                if t is not cur:
                    print("LEFTOVER TASK:", t, file=sys.stderr)

    return asyncio.run(main())


def test_reconnect_storm_schedule_heals(tmp_path):
    """Two partition/heal cycles with injected pong-timeout conn
    kills on the victim: the net must keep agreement, the victim must
    rejoin after every heal (liveness), and reconnect convergence
    must hold the p2p.reconnect span budget."""
    schedule = FaultSchedule(
        [
            FaultEvent(
                "reconnect_storm", at_height=2, node=1,
                cycles=2, hold_s=1.0, gap_s=0.8,
            ),
            # a conn_kill on a HEALED net: pure pong-timeout deaths,
            # no partition — reconnect must be near-immediate
            FaultEvent("conn_kill", at_height=4, node=2),
        ]
    )

    async def main():
        return await run_schedule(
            schedule,
            seed=4242,
            base_dir=str(tmp_path),
            budget_file=BUDGETS,
        )

    report = run(main())
    assert report.ok, report.format()
    assert report.budget_ok, report.format()
    assert report.conns_killed >= 4, report.conns_killed
    # the storm + kill really exercised the plane: the trace carries
    # both events
    actions = [t["action"] for t in report.trace]
    assert actions == ["reconnect_storm", "conn_kill"]


def test_unpinned_partition_statesync_join_churn_scenario(tmp_path):
    """The acceptance compound (previously pinned out of the matrix):
    a seeded partition x statesync_join x valset_churn scenario runs
    invariant-clean AND budget-clean — after the final heal every
    node, including the mid-load joiner, reaches the committed head
    (the liveness checker holds ALL running nodes to the settle
    target, so a single isolated node fails the run)."""
    spec = generate_scenario(SEED, PARTITION_JOIN_INDEX)
    assert spec.axes["lifecycle"] == "statesync_join"
    assert spec.axes["network"] == "partition", (
        "generator draw moved; re-pin PARTITION_JOIN_INDEX to an "
        f"index with partition x statesync_join (got {spec.axes})"
    )
    assert any(
        e.action == "valset_churn" for e in spec.schedule.events
    ), "statesync_join lifecycle must carry the churn leg"

    async def main():
        return await run_scenario(
            spec, base_dir=str(tmp_path), budget_file=BUDGETS
        )

    report = run(main())
    assert report.ok, report.format()
    assert report.budget_ok, report.format()
    # the joiner exists and really committed
    joiners = [n for n in report.final_heights if n.startswith("j")]
    assert joiners, report.final_heights
    head = max(report.final_heights.values())
    for name, h in report.final_heights.items():
        # zero permanently-isolated nodes: everyone (validators AND
        # the joiner) holds a committed prefix near the head — the
        # in-run liveness gate already required every running node to
        # pass the settle target, this asserts nobody fell off after
        assert h > 0, (name, report.final_heights)
    assert head >= 11  # the join really happened mid-load
