"""Persistent light trust store (reference light/store/db +
cmd light home db): LightBlocks survive process restarts, a reopened
Client resumes from its last VERIFIED header rather than the CLI
trust root, and pruning removes the persisted copies too."""

import os

import pytest

from cometbft_tpu.light.client import LightClientError
from cometbft_tpu.light import Client, StoreBackedProvider, TrustOptions
from cometbft_tpu.light.store import DBLightStore, LightStore
from cometbft_tpu.node.inprocess import make_genesis
from cometbft_tpu.utils.chaingen import make_chain
from cometbft_tpu.utils.kv import open_kv


def test_db_light_store_roundtrip_and_resume(tmp_path):
    gen, pvs = make_genesis(3, chain_id="light-db")
    src = make_chain(gen, [pv.priv_key for pv in pvs], 12)
    provider = StoreBackedProvider(gen.chain_id, src.block_store, src.state_store)
    trust = src.block_store.load_block(1)
    path = str(tmp_path / "light.db")

    store = DBLightStore(open_kv("sqlite", path), "light-db")
    cli = Client(
        "light-db",
        TrustOptions(
            period_ns=7200 * 10**9, height=1, hash=trust.hash()
        ),
        primary=provider,
        store=store,
    )
    lb = cli.verify_light_block_at_height(9)
    assert lb.height == 9
    store.db.close()

    # reopen: the persisted roots load; a client with the SAME trust
    # root resumes and verifies onward without refetching history
    store2 = DBLightStore(open_kv("sqlite", path), "light-db")
    assert len(store2) == len(store)
    got = store2.get(9)
    assert got is not None and got.hash() == lb.hash()
    assert got.validator_set.hash() == lb.validator_set.hash()
    cli2 = Client(
        "light-db",
        TrustOptions(
            period_ns=7200 * 10**9, height=1, hash=trust.hash()
        ),
        primary=provider,
        store=store2,
    )
    lb2 = cli2.verify_light_block_at_height(11)
    assert lb2.height == 11

    # a MISMATCHED trust root against the persisted store is an error,
    # never a silent override (reference
    # checkTrustedHeaderAgainstOptions); re-rooting = clear the store
    with pytest.raises(LightClientError, match="re-rooting"):
        Client(
            "light-db",
            TrustOptions(
                period_ns=7200 * 10**9, height=1, hash=b"\x00" * 32
            ),
            primary=provider,
            store=store2,
        )

    # pruning removes the durable copies as well
    store2.prune(1)
    store2.db.close()
    store3 = DBLightStore(open_kv("sqlite", path), "light-db")
    assert len(store3) == 1

    # sparse store (trust height pruned away): the root is compared
    # against the PRIMARY's header — a mismatch still refuses, a
    # matching root resumes
    with pytest.raises(LightClientError, match="re-rooting"):
        Client(
            "light-db",
            TrustOptions(
                period_ns=7200 * 10**9, height=1, hash=b"\x11" * 32
            ),
            primary=provider,
            store=store3,
        )
    Client(
        "light-db",
        TrustOptions(
            period_ns=7200 * 10**9, height=1, hash=trust.hash()
        ),
        primary=provider,
        store=store3,
    )

    # chain-id prefix isolation: another chain's records don't bleed
    other = DBLightStore(store3.db, "other-chain")
    assert len(other) == 0
    store3.db.close()


def test_sparse_store_trust_check_anchors_to_chain(tmp_path):
    """ADVICE r4: when the persisted store no longer retains the trust
    height, the primary's header at that height must be ANCHORED to
    the stored trust chain before it can confirm the configured root —
    a colluding primary serving a forged header that matches a
    mis-rooted config must be refused, and an unreachable primary must
    tolerate (resume from the store), not silently confirm."""
    import dataclasses

    gen, pvs = make_genesis(3, chain_id="light-anchor")
    src = make_chain(gen, [pv.priv_key for pv in pvs], 12)
    provider = StoreBackedProvider(gen.chain_id, src.block_store, src.state_store)
    trust = src.block_store.load_block(1)

    def sparse_client(primary, trust_hash):
        # persisted store retaining only the tip: trust height 1 gone
        store = LightStore()
        cli = Client(
            "light-anchor",
            TrustOptions(
                period_ns=7200 * 10**9, height=1, hash=trust.hash()
            ),
            primary=provider,
            store=store,
        )
        cli.verify_light_block_at_height(9)
        store.prune(1)
        return Client(
            "light-anchor",
            TrustOptions(
                period_ns=7200 * 10**9, height=1, hash=trust_hash
            ),
            primary=primary,
            store=store,
        )

    class ForgingProvider:
        """Serves a forged header at the trust height whose hash
        matches the (mis-rooted) configured trust hash; genuine
        everywhere else — exactly a colluding primary confirming a
        typo'd root."""

        def __init__(self):
            genuine = provider.light_block(1)
            forged_header = dataclasses.replace(
                genuine.header, time_ns=genuine.header.time_ns + 1
            )
            self.forged = dataclasses.replace(
                genuine, header=forged_header
            )

        def light_block(self, height):
            if height == 1:
                return self.forged
            return provider.light_block(height)

    forger = ForgingProvider()
    with pytest.raises(LightClientError, match="does not chain"):
        sparse_client(forger, bytes(forger.forged.hash()))

    class DeadProvider:
        def light_block(self, height):
            raise ConnectionError("primary unreachable")

    # unreachable primary: resume from the store (prominently logged),
    # never a refusal and never a silent confirmation of ANY root
    cli = sparse_client(DeadProvider(), b"\x77" * 32)
    assert cli.store.latest() is not None

    # forged header ABOVE the lowest stored block: anchoring runs the
    # SKIPPING path, whose verifiers raise assorted (non-
    # LightClientError) types — those must classify as refusal, not as
    # a skippable provider error (code-review r5 finding)
    store2 = LightStore()
    for h in (2, 9):
        store2.save(provider.light_block(h))
    genuine5 = provider.light_block(1 + 4)
    forged5 = dataclasses.replace(
        genuine5,
        header=dataclasses.replace(
            genuine5.header, time_ns=genuine5.header.time_ns + 1
        ),
    )

    class MidForger:
        def light_block(self, height):
            if height == 5:
                return forged5
            return provider.light_block(height)

    with pytest.raises(LightClientError, match="does not chain"):
        Client(
            "light-anchor",
            TrustOptions(
                period_ns=7200 * 10**9, height=5,
                hash=bytes(forged5.hash()),
            ),
            primary=MidForger(),
            store=store2,
        )
