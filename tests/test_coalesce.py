"""Async coalescing vote-verification queue (crypto/coalesce.py).

The consensus-round hot path: a 150-validator vote wave must verify in
<= 2 batch dispatches, with per-vote verdicts, cache population, and
the state machine's inline re-verify hitting the cache (reference hot
path: types/vote.go:237 via consensus/state.go:2175 addVote; the
coalescing queue is the BASELINE.json north-star design).
"""

import asyncio
import time

import pytest

from cometbft_tpu import types as T
from cometbft_tpu.consensus.reactor import (
    VOTE_CHANNEL,
    ConsensusReactor,
    encode_vote_msg,
)
from cometbft_tpu.consensus.types import Step
from cometbft_tpu.crypto.coalesce import CoalescingVerifier
from cometbft_tpu.crypto.keys import Ed25519PrivKey
from cometbft_tpu.node.inprocess import build_node, make_genesis


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def _signed(priv, chain_id, msg):
    return priv.pub_key(), msg, priv.sign(msg)


def test_one_dispatch_per_window():
    async def main():
        v = CoalescingVerifier(window_s=0.01)
        privs = [Ed25519PrivKey.generate() for _ in range(20)]
        futs = []
        for i, p in enumerate(privs):
            pk, msg, sig = _signed(p, "c", b"msg-%d" % i)
            if i == 7:
                sig = sig[:-1] + bytes([sig[-1] ^ 1])  # corrupt one
            futs.append(v.submit(pk, msg, sig))
        oks = await asyncio.gather(*futs)
        assert v.dispatches == 1
        assert [i for i, ok in enumerate(oks) if not ok] == [7]

    run(main())


def test_cache_short_circuits_resubmit():
    async def main():
        cache = T.SignatureCache()
        v = CoalescingVerifier(cache=cache, window_s=0.005)
        p = Ed25519PrivKey.generate()
        pk, msg, sig = _signed(p, "c", b"hello")
        assert await v.submit(pk, msg, sig) is True
        assert v.dispatches == 1
        # second submit: resolved from cache, no new dispatch
        assert await v.submit(pk, msg, sig) is True
        assert v.dispatches == 1
        assert v.cache_hits == 1

    run(main())


def test_max_pending_flushes_immediately():
    async def main():
        v = CoalescingVerifier(window_s=60.0, max_pending=8)
        p = Ed25519PrivKey.generate()
        futs = [
            v.submit(*_signed(p, "c", b"m%d" % i)) for i in range(8)
        ]
        # window is 60s: only the max_pending flush can resolve these
        oks = await asyncio.wait_for(asyncio.gather(*futs), 30)
        assert all(oks)
        assert v.dispatches == 1
        await v.drain()

    run(main())


def test_150_validator_vote_wave_two_dispatches():
    """The VERDICT r1 'done' criterion: a 150-validator in-process
    round verifies its vote wave in <= 2 dispatches, bad votes are
    dropped before the state machine, and +2/3 drives the round
    forward."""

    async def main():
        gen, pvs = make_genesis(150, chain_id="wave")
        parts = build_node(gen, pvs[0])
        cs = parts.cs
        await cs.start()
        try:
            reactor = ConsensusReactor(cs, parts.block_store)
            # a block everyone pretends to prevote for
            bid = T.BlockID(b"\x11" * 32, T.PartSetHeader(1, b"\x22" * 32))
            vs = gen.validator_set()
            now = time.time_ns()

            class FakePeer:
                peer_id = "wavepeer"
                _data = {}

                def get(self, k):
                    return self._data.get(k)

                def set(self, k, v):
                    self._data[k] = v

                def try_send(self, *a, **kw):
                    return True

            peer = FakePeer()
            n_bad = 0
            for i, pv in enumerate(pvs[1:], start=1):
                vote = T.Vote(
                    type_=T.PREVOTE,
                    height=1,
                    round=0,
                    block_id=bid,
                    timestamp_ns=now,
                    validator_address=pv.pub_key().address(),
                    validator_index=i,
                    signature=b"",
                )
                sig = pv.priv_key.sign(vote.sign_bytes(gen.chain_id))
                if i == 5:  # one byzantine garbage signature
                    sig = sig[:-1] + bytes([sig[-1] ^ 1])
                    n_bad += 1
                vote.signature = sig
                reactor.receive(
                    VOTE_CHANNEL, peer, encode_vote_msg(vote)
                )
            await reactor.vote_verifier.drain()
            # let the state machine drain its queue
            for _ in range(50):
                await asyncio.sleep(0.01)
                if cs.rs.votes.prevotes(0) and (
                    cs.rs.votes.prevotes(0).sum > 0
                ):
                    if cs.queue.empty():
                        break

            ver = reactor.vote_verifier
            assert ver.submitted == 149
            assert ver.dispatches <= 2, ver.dispatches
            prevotes = cs.rs.votes.prevotes(0)
            # 148 good votes landed; the corrupted one was dropped
            # before the state machine (plus possibly our own prevote)
            good = sum(
                1
                for v in prevotes.votes
                if v is not None and v.block_id.key() == bid.key()
            )
            assert good >= 148
            assert prevotes.get_vote(5) is None
            assert prevotes.has_two_thirds_any()
            # inline add_vote re-verify hit the shared cache
            assert cs.sig_cache.hits >= 148
            # +2/3 prevotes for a block pushed the round to precommit+
            assert cs.rs.step >= Step.PRECOMMIT
        finally:
            await cs.stop()

    run(main())


def test_dispatch_failure_falls_back_to_host_verification(monkeypatch):
    """ADVICE r2 (low): a transient backend/device error must not mark
    a whole wave invalid — the reactor already announced has_vote, so
    the dropped votes would never be re-gossiped. Per-item host
    verification resolves the lanes instead."""
    from cometbft_tpu.crypto import batch as crypto_batch

    class ExplodingVerifier(crypto_batch.BatchVerifier):
        def __init__(self):
            self.items = []

        def add(self, pk, msg, sig):
            self.items.append((pk, msg, sig))

        def __len__(self):
            return len(self.items)

        def verify(self):
            raise RuntimeError("device went away")

    monkeypatch.setattr(
        crypto_batch, "create_batch_verifier", lambda: ExplodingVerifier()
    )

    async def main():
        v = CoalescingVerifier(window_s=0.005)
        privs = [Ed25519PrivKey.generate() for _ in range(6)]
        futs = []
        for i, p in enumerate(privs):
            msg = b"wave|%d" % i
            sig = p.sign(msg)
            if i == 3:
                sig = sig[:-1] + bytes([sig[-1] ^ 1])  # one bad lane
            futs.append(v.submit(p.pub_key(), msg, sig))
        got = await asyncio.gather(*futs)
        assert got == [i != 3 for i in range(6)]

    run(main())
