"""Scenario factory acceptance (docs/CHAOS.md "Scenario factory").

1. Generation determinism: scenario i of master seed S is a pure
   function of (S, i) — identical JSON across calls, independent of
   --count, with the lifecycle-coverage guarantee any 5-window needs.
2. The tier-1 smoke: ``chaos matrix --seed 1337 --count 5`` runs five
   distinct generated scenarios — covering statesync_join,
   crash_wave and wal_torn_tail — invariant-clean and budget-clean,
   with torn-tail recovery proven through the matrix replay path.
3. Same-seed run determinism: two runs of one generated scenario
   produce identical schedule JSON, identical fault traces and the
   same structural outcome (committed-prefix proposers, violations).
4. An INJECTED violation replays byte-for-byte from the scenario's
   seed (the printed seed line's contract).
5. Workload plane units: spec round-trip + deterministic tx streams.
"""

import asyncio
import json

import pytest

from cometbft_tpu.chaos import (
    LIFECYCLES,
    FaultEvent,
    WorkloadSpec,
    generate_matrix,
    generate_scenario,
    run_scenario,
)
from cometbft_tpu.chaos.generator import ScenarioSpec
from cometbft_tpu.chaos.matrix import matrix_main
from cometbft_tpu.chaos.workload import WorkloadDriver

SEED = 1337


def run(coro, timeout=300):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# --- 1. generation determinism + coverage -------------------------------


def test_generation_is_pure_function_of_seed_and_index():
    for i in range(8):
        a = generate_scenario(SEED, i)
        b = generate_scenario(SEED, i)
        assert a.to_json() == b.to_json()
        assert a.schedule.to_json() == b.schedule.to_json()
        assert a.seed == b.seed
    # independent of count: scenario 2 is the same whether generated
    # alone or inside any matrix
    alone = generate_matrix(SEED, 5, only=[2])[0]
    in_matrix = generate_matrix(SEED, 5)[2]
    assert alone.to_json() == in_matrix.to_json()
    # different indexes / seeds really differ
    assert (
        generate_scenario(SEED, 0).schedule.to_json()
        != generate_scenario(SEED, 5).schedule.to_json()
        or generate_scenario(SEED, 0).seed
        != generate_scenario(SEED, 5).seed
    )
    assert (
        generate_scenario(SEED, 1).seed
        != generate_scenario(SEED + 1, 1).seed
    )


def test_any_five_window_covers_every_lifecycle():
    for start in (0, 3, 17):
        specs = generate_matrix(SEED, 0, only=list(range(start, start + 5)))
        lifecycles = {s.axes["lifecycle"] for s in specs}
        assert lifecycles == set(LIFECYCLES), (start, lifecycles)


def test_seed_line_carries_generation_inputs():
    """The replay line must regenerate the IDENTICAL scenario: the
    soak profile consumes an extra committee-size draw and an
    explicit --nodes override skips it, so both must ride the line."""
    soak = generate_scenario(7, 9, profile="soak")
    assert "--profile soak" in soak.seed_line()
    # replaying with exactly the line's flags reproduces the schedule
    again = generate_scenario(7, 9, profile="soak")
    assert again.to_json() == soak.to_json()
    forced = generate_scenario(7, 9, n_nodes=5, profile="soak")
    assert "--nodes 5" in forced.seed_line()
    smoke = generate_scenario(7, 9)
    assert "--profile" not in smoke.seed_line()
    assert "--nodes" not in smoke.seed_line()


def test_schedule_roundtrip_keeps_explicit_none_over_nonnone_default():
    """An archived schedule must replay with identical semantics:
    crash_wave restart_after_s=None means "stay down" and must NOT
    round-trip back to the default 1.0 ("restart after 1s")."""
    from cometbft_tpu.chaos import FaultSchedule

    sched = FaultSchedule(
        [
            FaultEvent(
                "crash_wave", at_height=1, nodes=[1],
                restart_after_s=None,
            )
        ]
    )
    again = FaultSchedule.from_json(sched.to_json())
    assert again == sched
    assert again.events[0].restart_after_s is None
    # fields still at their defaults stay out of the JSON
    assert "stagger_s" not in json.loads(sched.to_json())[0]


def test_scenario_spec_json_roundtrip():
    spec = generate_scenario(SEED, 2)
    again = ScenarioSpec.from_json(spec.to_json())
    assert again.to_json() == spec.to_json()
    assert again.schedule == spec.schedule
    assert again.workload == spec.workload
    assert again.axes == spec.axes


# --- 2. the tier-1 smoke matrix (the acceptance run) --------------------


def test_smoke_matrix_five_scenarios_invariant_and_budget_clean(
    tmp_path, capsys
):
    """``python -m cometbft_tpu.chaos matrix --seed 1337 --count 5``:
    five distinct scenarios covering at least statesync_join,
    crash_wave and wal_torn_tail, all invariant- AND budget-clean,
    each preceded by its replay seed line."""
    out_json = tmp_path / "matrix.json"
    rc = matrix_main(
        [
            "--seed", str(SEED), "--count", "5", "--budget",
            "--json", str(out_json),
        ]
    )
    printed = capsys.readouterr().out
    assert rc == 0, printed
    with open(out_json) as f:
        matrix = json.load(f)
    assert matrix["ok"] and matrix["budget_ok"]
    scenarios = matrix["scenarios"]
    assert len(scenarios) == 5
    lifecycles = {
        s["spec"]["axes"]["lifecycle"] for s in scenarios
    }
    assert {"statesync_join", "crash_wave", "wal_torn_tail"} <= lifecycles
    # five DISTINCT scenarios
    assert len({json.dumps(s["spec"]["schedule"]) for s in scenarios}) == 5
    for s in scenarios:
        assert s["ok"] and not s["violations"], s
        # every scenario committed and carries its structural
        # fingerprint + a real workload
        assert s["final_heights"] and s["proposers"]
        assert s["workload"].get("submitted", 0) > 0
        # the seed line (the replay handle) was printed
        sid = s["spec"]["scenario_id"]
        idx = s["spec"]["index"]
        assert (
            f"SCENARIO {sid}" in printed
            and f"--seed {SEED} --only {idx}" in printed
        )
    # the statesync scenario really grew the net by a joiner
    ss = next(
        s for s in scenarios
        if s["spec"]["axes"]["lifecycle"] == "statesync_join"
    )
    joiners = [n for n in ss["final_heights"] if n.startswith("j")]
    assert joiners and all(
        ss["final_heights"][j] > 0 for j in joiners
    ), ss["final_heights"]
    # torn-tail recovery went through the matrix replay path: the
    # wal_torn_tail event executed (torn bytes appended) and the
    # restarted node passed the WAL-replay (no-amnesia) checks
    tt = next(
        s for s in scenarios
        if s["spec"]["axes"]["lifecycle"] == "wal_torn_tail"
    )
    torn = [
        t for t in tt["trace"] if t["action"] == "wal_torn_tail"
    ]
    assert torn and torn[0]["torn_bytes"] > 0, tt["trace"]


# --- 3. same-seed structural determinism --------------------------------


def test_same_seed_scenario_runs_reproduce_structure(tmp_path):
    """Two runs of one generated scenario: identical schedule JSON,
    identical fault trace (all seeded draws included), no violations,
    and the same proposer at every height of the common committed
    prefix WHILE the two runs' commit-round histories agree (wall
    time decides how FAR each run gets — and, on a contended box,
    whether a round whose proposer is mid-crash/restart times out,
    which shifts rotation for every later height; proposer selection
    itself is a pure function of the valset + round history, so the
    matched-round prefix must reproduce exactly)."""
    spec1 = generate_scenario(SEED, 4)
    spec2 = generate_scenario(SEED, 4)
    assert spec1.schedule.to_json() == spec2.schedule.to_json()

    async def one(spec, sub):
        return await run_scenario(spec, base_dir=str(tmp_path / sub))

    r1 = run(one(spec1, "a"))
    r2 = run(one(spec2, "b"))
    assert r1.ok, r1.format()
    assert r2.ok, r2.format()
    assert r1.trace == r2.trace, "same seed must reproduce the trace"
    common = sorted(set(r1.proposers) & set(r2.proposers))
    assert common, (r1.proposers, r2.proposers)
    matched = []
    for h in common:
        if r1.rounds.get(h) != r2.rounds.get(h):
            break  # round histories diverged: rotation forks here
        matched.append(h)
    assert matched, (common, r1.rounds, r2.rounds)
    for h in matched:
        assert r1.proposers[h] == r2.proposers[h], (
            h, r1.proposers[h], r2.proposers[h],
        )


def test_injected_violation_replays_byte_for_byte(tmp_path):
    """The seed-line contract under failure: the same generated
    scenario with an injected byzantine commit corruption must be
    FLAGGED in both runs, with identical fault traces (tamper bytes
    included — they come from the seeded master rng)."""
    def spec_with_byzantine():
        spec = generate_scenario(SEED, 0)
        spec.schedule.events.append(
            FaultEvent("byzantine", at_height=4, node=2)
        )
        return spec

    async def one(sub):
        return await run_scenario(
            spec_with_byzantine(), base_dir=str(tmp_path / sub)
        )

    r1 = run(one("a"))
    r2 = run(one("b"))
    for r in (r1, r2):
        assert not r.ok
        assert any("agreement" in v for v in r.violations), r.violations
    byz1 = [t for t in r1.trace if t["action"] == "byzantine"]
    byz2 = [t for t in r2.trace if t["action"] == "byzantine"]
    assert byz1 and byz1[0]["tamper"] == byz2[0]["tamper"]
    assert r1.trace == r2.trace


# --- 4. workload plane units --------------------------------------------


def test_workload_spec_roundtrip_and_validation():
    spec = WorkloadSpec("bursty", burst_txs=16, burst_gap_s=0.1)
    again = WorkloadSpec.from_dict(json.loads(json.dumps(spec.to_dict())))
    assert again == spec
    with pytest.raises(ValueError):
        WorkloadSpec("weird")
    with pytest.raises(ValueError):
        WorkloadSpec("sustained", tx_bytes=4)


def test_workload_tx_stream_is_deterministic():
    d1 = WorkloadDriver(WorkloadSpec("sustained", tx_bytes=64), seed=99)
    d2 = WorkloadDriver(WorkloadSpec("sustained", tx_bytes=64), seed=99)
    s1 = [d1._next_tx() for _ in range(50)]
    s2 = [d2._next_tx() for _ in range(50)]
    assert s1 == s2
    assert all(len(t) >= 64 for t in s1)
    assert len(set(s1)) == 50  # unique keys, no mempool dup rejects
    d3 = WorkloadDriver(WorkloadSpec("sustained", tx_bytes=64), seed=98)
    assert [d3._next_tx() for _ in range(50)] != s1


# --- fast-path slice (ISSUE 11) -----------------------------------------


def test_fastpath_matrix_slice_invariant_and_budget_clean(
    tmp_path, capsys
):
    """``chaos matrix --fastpath``: the live-consensus fast path (WAL
    group commit + in-round vote micro-batching + pipelined finalize,
    docs/PERF.md) under the seeded fault matrix, beneath the 2ms
    slow-disk fsync model so the calibrated group seam genuinely
    engages — gated on the SAME invariants and span budgets as the
    plain smoke. Proves the fast path fault-clean, not just fast."""
    from cometbft_tpu.consensus import wal as walmod

    out_json = tmp_path / "fastpath.json"
    rc = matrix_main(
        [
            "--seed", str(SEED), "--count", "2", "--fastpath",
            "--budget", "--json", str(out_json),
        ]
    )
    printed = capsys.readouterr().out
    assert rc == 0, printed
    # the model must be restored no matter what the run did
    assert walmod._FSYNC_MODEL_S == 0.0
    with open(out_json) as f:
        matrix = json.load(f)
    assert matrix["ok"] and matrix["budget_ok"]
    assert len(matrix["scenarios"]) == 2
    for s in matrix["scenarios"]:
        assert s["ok"] and not s["violations"], s
        assert s["final_heights"]


# --- 5. nightly-sized soak (slow marker) --------------------------------


@pytest.mark.slow
def test_soak_matrix_fifty_scenarios(tmp_path):
    """The ROADMAP item 5 target: a 50+-scenario seeded soak, every
    violation replayable from its printed seed line (here: none
    expected)."""
    out_json = tmp_path / "soak.json"
    rc = matrix_main(
        [
            "--seed", "20260804", "--count", "50", "--budget",
            "--profile", "soak", "--json", str(out_json),
        ]
    )
    with open(out_json) as f:
        matrix = json.load(f)
    failed = [
        s["spec"]["scenario_id"]
        for s in matrix["scenarios"]
        if not s["ok"]
    ]
    assert rc == 0 and not failed, failed
