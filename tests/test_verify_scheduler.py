"""Unified verify scheduler (crypto/scheduler.py): serial-equivalent
verdicts, priority ordering at chunk granularity, the aging/promotion
starvation guard, and the mesh backend's route/degrade ladder.

Device dispatches are exercised against a FAKE ops.ed25519 handle —
the real sharded kernel is differential-tested in
test_ed25519_verify.py / test_sharded_verify.py; here the contract
under test is the scheduler's routing, merging, and degrade paths.
"""

import threading
import time

import pytest

from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.crypto import mesh_backend as mesh_mod
from cometbft_tpu.crypto import parallel_verify as pv
from cometbft_tpu.crypto import scheduler as sched_mod
from cometbft_tpu.crypto.batch import CpuBatchVerifier
from cometbft_tpu.crypto.keys import Ed25519PrivKey, Secp256k1PrivKey
from cometbft_tpu.crypto.mesh_backend import LAST_MESH, MeshBatchVerifier
from cometbft_tpu.crypto.scheduler import (
    PRIORITY_CATCHUP,
    PRIORITY_LIGHT,
    PRIORITY_LIVE,
    VerifyScheduler,
    VerifyTicket,
)

# key generation dominates test wall time: a small reusable pool is
# plenty (verdicts depend on (msg, sig), not key uniqueness)
_ED_KEYS = [Ed25519PrivKey.generate() for _ in range(8)]
_SECP_KEYS = [Secp256k1PrivKey.generate() for _ in range(2)]


def make_items(n, bad=(), mixed=False):
    items = []
    for i in range(n):
        if mixed and i % 5 == 4:
            sk = _SECP_KEYS[i % len(_SECP_KEYS)]
        else:
            sk = _ED_KEYS[i % len(_ED_KEYS)]
        msg = b"sched-lane-%d" % i
        sig = sk.sign(msg)
        if i in bad:
            sig = b"\x00" * len(sig)
        items.append((sk.pub_key(), msg, sig))
    return items


def serial_verdicts(items):
    v = CpuBatchVerifier()
    for pk, msg, sig in items:
        v.add(pk, msg, sig)
    return v.verify()


@pytest.fixture
def sched():
    s = VerifyScheduler()
    yield s
    s.close()


@pytest.fixture
def cpu_backend():
    old = crypto_batch.default_backend()
    crypto_batch.set_default_backend("cpu")
    yield
    crypto_batch.set_default_backend(old)


@pytest.fixture
def restore_routing():
    old_backend = crypto_batch.default_backend()
    old_floor = crypto_batch._MIN_TPU_BATCH
    yield
    crypto_batch.set_default_backend(old_backend)
    crypto_batch.set_min_tpu_batch(old_floor)


class FakeDeviceHandle:
    """Stands in for ops.ed25519.AsyncVerdicts: verdicts computed by
    the same per-key host math the backends fall back to."""

    def __init__(self, ed_items):
        from cometbft_tpu.crypto.keys import Ed25519PubKey

        self.verdicts = [
            Ed25519PubKey(pk).verify(msg, sig)
            for msg, pk, sig in ed_items
        ]

    def wait_fetch(self):
        pass

    def result(self):
        return self.verdicts


# --- verdict parity ------------------------------------------------------


def test_serial_equivalence_differential(sched, cpu_backend):
    items = make_items(40, bad={3, 17, 39}, mixed=True)
    want_all, want = serial_verdicts(items)
    ticket = sched.submit(items, priority=PRIORITY_LIVE, label="diff")
    got_all, got = ticket.result(timeout=60)
    assert got == want
    assert got_all == want_all
    assert ticket.backend == "cpu"
    assert ticket.wall() is not None and ticket.wall() >= 0


def test_empty_submit_matches_batch_verifier(sched, cpu_backend):
    # BatchVerifier.verify() on zero lanes is (False, []); an empty
    # ticket must resolve immediately with the same shape
    t = sched.submit([], priority=PRIORITY_LIGHT)
    assert t.done()
    assert t.result(timeout=1) == (False, [])


def test_all_classes_same_verdicts(sched, cpu_backend):
    items = make_items(12, bad={5})
    want = serial_verdicts(items)
    tickets = [
        sched.submit(items, priority=p, label=f"cls-{p}")
        for p in (PRIORITY_LIVE, PRIORITY_LIGHT, PRIORITY_CATCHUP)
    ]
    for t in tickets:
        assert t.result(timeout=60) == want


def test_priority_clamped(sched, cpu_backend):
    items = make_items(2)
    t = sched.submit(items, priority=99)
    assert t.priority == PRIORITY_CATCHUP
    t.result(timeout=30)
    t2 = sched.submit(items, priority=-5)
    assert t2.priority == PRIORITY_LIVE
    t2.result(timeout=30)
    t3 = sched.submit(items, priority=None)
    assert t3.priority == PRIORITY_CATCHUP
    t3.result(timeout=30)


def test_custom_backend_passthrough(sched, restore_routing):
    """An operator-registered backend keeps its semantics verbatim:
    the scheduler builds it and resolves the whole ticket through it."""
    built = []

    class Recording(CpuBatchVerifier):
        def __init__(self):
            super().__init__()
            built.append(self)

    crypto_batch.register_backend("unit-test-backend", Recording)
    try:
        crypto_batch.set_default_backend("unit-test-backend")
        items = make_items(6, bad={2})
        want = serial_verdicts(items)
        t = sched.submit(items, priority=PRIORITY_LIVE)
        assert t.result(timeout=30) == want
        assert t.backend == "unit-test-backend"
        assert len(built) == 1 and len(built[0]) == 6
    finally:
        crypto_batch.set_default_backend("cpu")
        with crypto_batch._lock:
            crypto_batch._BACKENDS.pop("unit-test-backend", None)


# --- priority ordering / starvation guard --------------------------------


def _slow_chunks(monkeypatch, delay):
    """Make host chunks take a visible wall so ordering is observable,
    and force small chunks so every ticket splits into several."""
    real = pv._verify_chunk

    def slow(items, tier):
        time.sleep(delay)
        return real(items, tier)

    monkeypatch.setattr(pv, "_verify_chunk", slow)
    monkeypatch.setattr(
        pv.engine(), "chunk_size", lambda n: 4, raising=False
    )


def test_live_preempts_catchup_at_chunk_boundary(
    sched, cpu_backend, monkeypatch
):
    _slow_chunks(monkeypatch, 0.01)
    catchup_items = make_items(32)
    live_items = make_items(8)
    t_catchup = sched.submit(
        catchup_items, priority=PRIORITY_CATCHUP, label="storm"
    )
    # let the storm route and start chunking before the live wave lands
    time.sleep(0.02)
    t_live = sched.submit(live_items, priority=PRIORITY_LIVE, label="live")
    assert t_live.result(timeout=30) == serial_verdicts(live_items)
    assert t_catchup.result(timeout=30) == serial_verdicts(catchup_items)
    # live arrived mid-storm yet finished first: preemption happened
    # at a chunk boundary, not behind the storm's full residue
    assert t_live.t_done < t_catchup.t_done


def test_aging_promotion_unit():
    """_pick_locked serves an aged lower-class ticket once every
    promote_every picks — deterministic, no dispatcher involved."""
    s = VerifyScheduler(promote_after_s=0.0, promote_every=2)
    live = VerifyTicket([None] * 2, PRIORITY_LIVE, "live")
    old = VerifyTicket([None] * 2, PRIORITY_CATCHUP, "old")
    old.t_submit -= 1.0  # aged well past promote_after_s
    s._queues[PRIORITY_LIVE].append(live)
    s._queues[PRIORITY_CATCHUP].append(old)
    with s._cv:
        first = s._pick_locked()
        second = s._pick_locked()
    assert first is live  # credit accrues, threshold not yet met
    assert second is old  # every promote_every-th pick is the aged one
    assert s.promoted == 1


def test_catchup_completes_under_sustained_live_flood(
    cpu_backend, monkeypatch
):
    """The starvation-guard satellite: flood the live lane without a
    gap and assert a catch-up ticket still completes WHILE the flood
    is running, via aging promotion."""
    s = VerifyScheduler(promote_after_s=0.05, promote_every=2)
    _slow_chunks(monkeypatch, 0.002)
    stop = threading.Event()
    live_items = make_items(8)

    def flood():
        while not stop.is_set():
            s.submit(live_items, priority=PRIORITY_LIVE, label="flood")
            time.sleep(0.004)

    feeder = threading.Thread(target=flood, daemon=True)
    feeder.start()
    try:
        time.sleep(0.05)  # flood is established
        catchup = make_items(8, bad={1})
        t = s.submit(catchup, priority=PRIORITY_CATCHUP, label="starved")
        got = t.result(timeout=5.0)  # must resolve DURING the flood
        assert got == serial_verdicts(catchup)
        assert not stop.is_set()
        assert s.promoted >= 1
    finally:
        stop.set()
        feeder.join(timeout=5)
        assert s.drain(timeout=30)
        s.close()


# --- mesh backend --------------------------------------------------------


def test_mesh_route_dispatches_device(sched, restore_routing, monkeypatch):
    import cometbft_tpu.ops.ed25519 as ops_ed

    crypto_batch.set_default_backend("mesh")
    crypto_batch.set_min_tpu_batch(1)  # force past the batch floor
    monkeypatch.setattr(
        mesh_mod, "mesh_devices", lambda refresh=False: 8
    )
    dispatched = []

    def fake_async(ed_items):
        dispatched.append(len(ed_items))
        return FakeDeviceHandle(ed_items)

    monkeypatch.setattr(ops_ed, "verify_batch_async", fake_async)
    items = make_items(16, bad={7}, mixed=True)
    want = serial_verdicts(items)
    t = sched.submit(items, priority=PRIORITY_LIVE, label="mesh")
    assert t.result(timeout=30) == want
    assert t.backend == "mesh"
    assert dispatched == [sum(1 for pk, _, _ in items
                              if pk.type_ == "ed25519")]
    assert sched.device_dispatches == 1


def test_mesh_degrades_without_mesh(sched, restore_routing, monkeypatch):
    import cometbft_tpu.ops.ed25519 as ops_ed

    crypto_batch.set_default_backend("mesh")
    crypto_batch.set_min_tpu_batch(1)
    monkeypatch.setattr(
        mesh_mod, "mesh_devices", lambda refresh=False: 1
    )

    def boom(ed_items):  # pragma: no cover - must never be reached
        raise AssertionError("degraded route must not touch the device")

    monkeypatch.setattr(ops_ed, "verify_batch_async", boom)
    items = make_items(12, bad={4})
    want = serial_verdicts(items)
    t = sched.submit(items, priority=PRIORITY_CATCHUP, label="degrade")
    assert t.result(timeout=30) == want
    assert t.backend == "mesh-degraded"
    assert sched.degraded == 1
    assert sched.device_dispatches == 0


def test_mesh_degrades_on_dispatch_failure(
    sched, restore_routing, monkeypatch
):
    """The device dispatch itself failing must fall through to host
    chunks — degraded and visible, never wedged."""
    import cometbft_tpu.ops.ed25519 as ops_ed

    crypto_batch.set_default_backend("mesh")
    crypto_batch.set_min_tpu_batch(1)
    monkeypatch.setattr(
        mesh_mod, "mesh_devices", lambda refresh=False: 8
    )

    def boom(ed_items):
        raise RuntimeError("no XLA for you")

    monkeypatch.setattr(ops_ed, "verify_batch_async", boom)
    items = make_items(10, bad={0})
    want = serial_verdicts(items)
    t = sched.submit(items, priority=PRIORITY_LIVE)
    assert t.result(timeout=30) == want
    assert t.backend == "mesh-degraded"
    assert sched.degraded == 1


def test_mesh_backend_verifier_host_parity(restore_routing):
    """MeshBatchVerifier below the floor / without a mesh verifies on
    the host plane with CpuBatchVerifier-identical verdicts."""
    items = make_items(8, bad={2}, mixed=True)
    want = serial_verdicts(items)
    v = MeshBatchVerifier()
    for pk, msg, sig in items:
        v.add(pk, msg, sig)
    assert v.verify() == want
    assert LAST_MESH["path"] in ("host", "host-degraded")


def test_mesh_backend_registered(restore_routing):
    assert "mesh" in crypto_batch.backends()
    crypto_batch.set_default_backend("mesh")
    assert isinstance(
        crypto_batch.create_batch_verifier(), MeshBatchVerifier
    )


def test_mesh_backend_sharded_path(restore_routing, monkeypatch):
    import cometbft_tpu.ops.ed25519 as ops_ed

    crypto_batch.set_min_tpu_batch(1)
    monkeypatch.setattr(
        mesh_mod, "mesh_devices", lambda refresh=False: 8
    )
    monkeypatch.setattr(
        ops_ed,
        "verify_batch",
        lambda ed_items: FakeDeviceHandle(ed_items).verdicts,
    )
    items = make_items(16, bad={9}, mixed=True)
    want = serial_verdicts(items)
    v = MeshBatchVerifier()
    for pk, msg, sig in items:
        v.add(pk, msg, sig)
    assert v.verify() == want
    assert LAST_MESH["path"] == "mesh"
    assert LAST_MESH["devices"] == 8


def test_mesh_backend_degrades_on_kernel_error(
    restore_routing, monkeypatch
):
    import cometbft_tpu.ops.ed25519 as ops_ed

    crypto_batch.set_min_tpu_batch(1)
    monkeypatch.setattr(
        mesh_mod, "mesh_devices", lambda refresh=False: 8
    )

    def boom(ed_items):
        raise RuntimeError("mesh fell over")

    monkeypatch.setattr(ops_ed, "verify_batch", boom)
    items = make_items(8, bad={3})
    want = serial_verdicts(items)
    v = MeshBatchVerifier()
    for pk, msg, sig in items:
        v.add(pk, msg, sig)
    assert v.verify() == want  # bit-identical host degrade, no wedge
    assert LAST_MESH["path"] == "host-degraded"


# --- observability -------------------------------------------------------


def test_queue_stats_shape(sched, cpu_backend):
    items = make_items(6)
    sched.submit(items, priority=PRIORITY_LIVE).result(timeout=30)
    sched.submit(items, priority=PRIORITY_CATCHUP).result(timeout=30)
    st = sched.queue_stats()
    for key in (
        "depth",
        "high_watermark",
        "enqueued",
        "dropped",
        "inflight_chunks",
        "promoted",
        "device_dispatches",
        "host_chunks",
        "degraded",
        "live_depth",
        "light_depth",
        "catchup_depth",
    ):
        assert key in st, key
    assert st["depth"] == 0
    assert st["enqueued"] == 12
    assert st["high_watermark"] >= 6


def test_dispatch_span_emitted(sched, cpu_backend):
    from cometbft_tpu.trace import global_tracer

    tr = global_tracer()
    events = []
    was_enabled = tr.enabled

    def obs(name, dur_ns, args):
        if name == "crypto.sched.dispatch":
            events.append((dur_ns, dict(args or {})))

    tr.enabled = True
    tr.add_observer(obs)
    try:
        items = make_items(5, bad={1})
        sched.submit(items, priority=PRIORITY_LIGHT, label="span").result(
            timeout=30
        )
    finally:
        tr.remove_observer(obs)
        tr.enabled = was_enabled
    assert events, "no crypto.sched.dispatch span observed"
    args = events[-1][1]
    assert args.get("cls") == "light"
    assert args.get("backend") == "cpu"
    assert args.get("lanes") == 5


def test_verify_storm_action(cpu_backend):
    """The chaos verify_storm leg, net-free: three concurrent classes
    through the shared scheduler, verdict parity + live budget + a
    non-starved catch-up lane (the full-net slice runs in
    tools/chaos_smoke.sh)."""
    from cometbft_tpu.chaos.verify_storm import storm_for_chaos

    rec = storm_for_chaos(storm_s=0.4, live_budget_ms=2500.0)
    assert rec["parity_ok"]
    for name in ("live", "light", "catchup"):
        assert rec[name]["tickets"] > 0, name
    assert rec["live"]["p95_ms"] <= 2500.0


def test_verify_storm_schedulable():
    from cometbft_tpu.chaos import FaultEvent, FaultSchedule

    ev = FaultEvent("verify_storm", at_height=2, storm_s=0.5)
    sched = FaultSchedule([ev])
    again = FaultSchedule.from_json(sched.to_json())
    assert again.events[0].action == "verify_storm"
    assert again.events[0].storm_s == 0.5
    assert again.events[0].live_budget_ms == 2500.0


def test_sched_stats_if_running_registry_contract(cpu_backend):
    # never CREATES the scheduler...
    old = sched_mod._SCHED
    try:
        sched_mod._SCHED = None
        assert sched_mod.sched_stats_if_running() is None
        # ...but reports the live one's gauges
        s = VerifyScheduler()
        sched_mod._SCHED = s
        s.submit(make_items(3), priority=PRIORITY_LIVE).result(timeout=30)
        st = sched_mod.sched_stats_if_running()
        assert st is not None and st["enqueued"] == 3
        s.close()
    finally:
        sched_mod._SCHED = old
