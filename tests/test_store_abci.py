"""Storage round-trips + ABCI local client + kvstore app."""

import time

import pytest

from cometbft_tpu import types as T
from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.client import AppConns
from cometbft_tpu.models.kvstore import KVStoreApplication
from cometbft_tpu.state.state_types import ConsensusParams, State
from cometbft_tpu.state.store import Store, decode_state, encode_state
from cometbft_tpu.store import BlockStore
from cometbft_tpu.utils import codec, kv

NOW = int(time.time() * 1e9)
CHAIN = "store-chain"


def make_block(vs, privs, height, prev_bid, app_hash=b"\x01" * 32):
    header = T.Header(
        chain_id=CHAIN,
        height=height,
        time_ns=NOW + height,
        last_block_id=prev_bid,
        validators_hash=vs.hash(),
        next_validators_hash=vs.hash(),
        app_hash=app_hash,
        proposer_address=vs.validators[0].address,
    )
    data = T.Data(txs=[b"k%d=v%d" % (height, height)])
    last_commit = None
    if height > 1:
        last_commit = T.Commit(height - 1, 0, prev_bid, [])
    header = T.Header(
        **{
            **header.__dict__,
            "data_hash": data.hash(),
            "last_commit_hash": last_commit.hash() if last_commit else b"",
        }
    )
    blk = T.Block(header=header, data=data, last_commit=last_commit)
    return blk


def test_codec_roundtrips():
    vs, privs = T.random_validator_set(4)
    blk = make_block(vs, privs, 1, T.BlockID())
    enc = codec.encode_block(blk)
    dec = codec.decode_block(enc)
    assert dec.hash() == blk.hash()
    assert dec.data.txs == blk.data.txs
    # vote round trip
    v = T.Vote(
        type_=T.PRECOMMIT,
        height=5,
        round=2,
        block_id=T.BlockID(b"\x02" * 32, T.PartSetHeader(3, b"\x03" * 32)),
        timestamp_ns=NOW,
        validator_address=privs[0].pub_key().address(),
        validator_index=0,
        signature=b"\x05" * 64,
    )
    v2 = codec.decode_vote(codec.encode_vote(v))
    assert v2 == v
    assert v2.sign_bytes(CHAIN) == v.sign_bytes(CHAIN)
    # validator set round trip preserves order + proposer + priorities
    vs.increment_proposer_priority(3)
    vs2 = codec.decode_validator_set(codec.encode_validator_set(vs))
    assert [x.address for x in vs2.validators] == [
        x.address for x in vs.validators
    ]
    assert vs2.proposer.address == vs.proposer.address
    assert vs2.hash() == vs.hash()
    assert [x.proposer_priority for x in vs2.validators] == [
        x.proposer_priority for x in vs.validators
    ]


def test_block_store_save_load(tmp_path):
    db = kv.SqliteKV(str(tmp_path / "blocks.db"))
    bs = BlockStore(db)
    vs, privs = T.random_validator_set(4)
    prev = T.BlockID()
    blocks = []
    for h in (1, 2, 3):
        blk = make_block(vs, privs, h, prev)
        ps = T.PartSet.from_data(codec.encode_block(blk))
        seen = T.Commit(h, 0, T.BlockID(blk.hash(), ps.header), [])
        bs.save_block(blk, ps, seen)
        prev = T.BlockID(blk.hash(), ps.header)
        blocks.append(blk)
    assert bs.height() == 3
    assert bs.base() == 1
    got = bs.load_block(2)
    assert got.hash() == blocks[1].hash()
    assert bs.load_block_by_hash(blocks[0].hash()).height == 1
    meta = bs.load_block_meta(3)
    assert meta.header.height == 3
    sc = bs.load_seen_commit(3)
    assert sc.height == 3
    lc = bs.load_block_commit(1)  # commit FOR height 1 came with block 2
    assert lc.height == 1
    # non-contiguous save rejected
    blk5 = make_block(vs, privs, 5, prev)
    ps5 = T.PartSet.from_data(codec.encode_block(blk5))
    with pytest.raises(ValueError):
        bs.save_block(blk5, ps5, T.Commit(5, 0, T.BlockID(), []))
    # reopen from disk
    bs2 = BlockStore(db)
    assert bs2.height() == 3
    assert bs2.load_block(1).hash() == blocks[0].hash()
    # prune
    assert bs2.prune_blocks(3) == 2
    assert bs2.base() == 3
    assert bs2.load_block(1) is None


def test_state_store_roundtrip():
    vs, _ = T.random_validator_set(3)
    st = State(
        chain_id=CHAIN,
        initial_height=1,
        last_block_height=7,
        last_block_id=T.BlockID(b"\x09" * 32, T.PartSetHeader(1, b"\x0a" * 32)),
        last_block_time_ns=NOW,
        validators=vs,
        next_validators=vs.copy(),
        last_validators=vs.copy(),
        consensus_params=ConsensusParams(),
        app_hash=b"\x0b" * 32,
        last_results_hash=b"\x0c" * 32,
    )
    dec = decode_state(encode_state(st))
    assert dec.chain_id == CHAIN
    assert dec.last_block_height == 7
    assert dec.validators.hash() == vs.hash()
    assert dec.app_hash == st.app_hash
    db = kv.MemKV()
    store = Store(db)
    store.save(st)
    assert store.load().last_block_height == 7
    assert store.load_validators(9).hash() == vs.hash()


def test_kvstore_app_lifecycle():
    app = KVStoreApplication()
    conns = AppConns.local(app)
    info = conns.query.info(abci.RequestInfo())
    assert info.last_block_height == 0
    conns.consensus.init_chain(abci.RequestInitChain(chain_id=CHAIN))
    # check + finalize + commit
    assert conns.mempool.check_tx(abci.RequestCheckTx(tx=b"a=1")).is_ok()
    assert not conns.mempool.check_tx(abci.RequestCheckTx(tx=b"junk")).is_ok()
    resp = conns.consensus.finalize_block(
        abci.RequestFinalizeBlock(txs=[b"a=1", b"b=2"], height=1)
    )
    assert all(r.is_ok() for r in resp.tx_results)
    conns.consensus.commit()
    q = conns.query.query(abci.RequestQuery(data=b"a"))
    assert q.value == b"1"
    assert app.height == 1
    # determinism: same txs -> same app hash
    app2 = KVStoreApplication()
    app2.init_chain(abci.RequestInitChain(chain_id=CHAIN))
    r2 = app2.finalize_block(
        abci.RequestFinalizeBlock(txs=[b"a=1", b"b=2"], height=1)
    )
    assert r2.app_hash == resp.app_hash


def test_kvstore_snapshots():
    app = KVStoreApplication()
    app.init_chain(abci.RequestInitChain(chain_id=CHAIN))
    for h in range(1, 11):
        app.finalize_block(
            abci.RequestFinalizeBlock(txs=[b"k%d=v%d" % (h, h)], height=h)
        )
        app.commit()
    snaps = app.list_snapshots()
    assert snaps and snaps[-1].height == 10
    # restore into a fresh app
    app2 = KVStoreApplication()
    s = snaps[-1]
    app2.offer_snapshot(s, app.app_hash)
    for c in range(s.chunks):
        chunk = app.load_snapshot_chunk(s.height, 1, c)
        app2.apply_snapshot_chunk(c, chunk, "peer")
    assert app2.app_hash == app.app_hash
    assert app2.height == 10


def test_evidence_codec_roundtrip():
    from cometbft_tpu.evidence.types import (
        DuplicateVoteEvidence,
        decode_evidence,
    )

    vs, privs = T.random_validator_set(2)
    votes = []
    for tag in (b"a", b"b"):
        import hashlib

        bid = T.BlockID(
            hashlib.sha256(tag).digest(),
            T.PartSetHeader(1, hashlib.sha256(tag + b"p").digest()),
        )
        v = T.Vote(
            type_=T.PREVOTE,
            height=4,
            round=0,
            block_id=bid,
            timestamp_ns=NOW,
            validator_address=privs[0].pub_key().address(),
            validator_index=0,
        )
        v.signature = privs[0].sign(v.sign_bytes(CHAIN))
        votes.append(v)
    ev = DuplicateVoteEvidence.from_votes(
        votes[0], votes[1], 100, 200, NOW
    )
    ev.validate_basic()
    dec = decode_evidence(ev.encode())
    assert dec.hash() == ev.hash()
    assert dec.vote_a.signature == ev.vote_a.signature
