"""Stuck-await watchdog (the deadlock-detection analog; reference
libs/sync/deadlock.go swapped in by the `deadlock` build tag)."""

import asyncio
import io

import pytest

from cometbft_tpu.utils import log as L
from cometbft_tpu.utils.debug import StuckTaskWatchdog


def run(coro, timeout=30):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def test_reports_stuck_task_once():
    async def main():
        buf = io.StringIO()
        L.set_writer(buf)
        try:
            wd = StuckTaskWatchdog(interval_s=0.05, stall_s=0.2)
            wd.start()

            forever = asyncio.Event()

            async def stuck():
                await forever.wait()  # never set

            t = asyncio.get_running_loop().create_task(
                stuck(), name="stuck-task"
            )
            await asyncio.sleep(1.0)
            wd.stop()
            names = [n for n, _ in wd.stalled]
            assert "stuck-task" in names
            # reported once, not on every sample
            assert names.count("stuck-task") == 1
            out = buf.getvalue()
            assert "task stuck at the same await point" in out
            assert "stuck-task" in out
            forever.set()
            await t
        finally:
            L.set_writer(__import__("sys").stderr)

    run(main())


def test_active_tasks_not_reported():
    async def main():
        wd = StuckTaskWatchdog(interval_s=0.05, stall_s=0.2)
        wd.start()

        async def busy():
            for _ in range(100):
                await asyncio.sleep(0.01)

        t = asyncio.get_running_loop().create_task(busy(), name="busy")
        await t
        wd.stop()
        assert all(n != "busy" for n, _ in wd.stalled)

    run(main())
