"""Structured logfmt logger tests (reference libs/log/tm_logger.go)."""

import io

import pytest

from cometbft_tpu.utils import log as L


@pytest.fixture(autouse=True)
def _reset_levels():
    yield
    L.set_level("*:info")
    L.set_writer(__import__("sys").stderr)


def _capture():
    buf = io.StringIO()
    L.set_writer(buf)
    return buf


def test_logfmt_line_shape():
    buf = _capture()
    lg = L.get_logger("consensus")
    lg.info("entering new round", height=5, round=0)
    line = buf.getvalue().strip()
    assert "level=info" in line
    assert "module=consensus" in line
    assert 'msg="entering new round"' in line
    assert "height=5" in line and "round=0" in line
    assert line.startswith("ts=")


def test_quoting_and_bytes():
    buf = _capture()
    lg = L.get_logger("test")
    lg.info('msg with "quotes"', h=b"\xde\xad", flag=True, f=0.5)
    line = buf.getvalue()
    assert "h=dead" in line
    assert "flag=true" in line
    assert "f=0.5" in line
    assert '\\"quotes\\"' in line


def test_lazy_values_not_rendered_below_level():
    buf = _capture()
    calls = []

    def expensive():
        calls.append(1)
        return "deadbeef"

    lg = L.get_logger("lazymod")
    lg.debug("hidden", h=L.Lazy(expensive))  # below info: not rendered
    assert calls == []
    L.set_level("lazymod:debug")
    lg.debug("shown", h=L.Lazy(expensive))
    assert calls == [1]
    assert "h=deadbeef" in buf.getvalue()


def test_module_scoped_levels():
    buf = _capture()
    L.set_level("consensus:debug,p2p:error,*:info")
    L.get_logger("consensus").debug("a")
    L.get_logger("p2p").info("b")  # suppressed
    L.get_logger("other").info("c")
    out = buf.getvalue()
    assert 'msg=a' in out
    assert 'msg=b' not in out
    assert 'msg=c' in out


def test_bound_fields():
    buf = _capture()
    lg = L.get_logger("peer").with_fields(peer="abc123")
    lg.info("hello", n=1)
    assert "peer=abc123" in buf.getvalue()


def test_invalid_level_raises():
    with pytest.raises(ValueError):
        L.set_level("verbose")


def test_lazy_error_never_raises():
    buf = _capture()
    L.get_logger("x").info(
        "ok", v=L.Lazy(lambda: 1 / 0)
    )
    assert "lazy error" in buf.getvalue()
