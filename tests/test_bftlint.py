"""bftlint (cometbft_tpu.analysis) tier-1 gate + unit fixtures.

Three layers:
  1. per-rule positive/negative fixtures (pure-ast, no jax import);
  2. the suppression / baseline / CLI machinery contracts;
  3. the repo gate: the full pass over cometbft_tpu/ must be clean
     against the checked-in baseline, and tools/lint.sh must pass —
     this is what ratchets every future PR.
"""
import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from cometbft_tpu.analysis import analyze_source
from cometbft_tpu.analysis import baseline as baseline_mod
from cometbft_tpu.analysis.cli import main
from cometbft_tpu.analysis.findings import Finding
from cometbft_tpu.analysis.registry import (
    all_project_rules,
    all_rules,
    resolve,
)

REPO_ROOT = Path(__file__).resolve().parents[1]


def ids_of(src: str, path: str = "x.py"):
    return sorted(
        {f.rule_id for f in analyze_source(textwrap.dedent(src), path)}
    )


# path-scoped rules need their fixtures analyzed under an in-scope
# path (ASY107 only applies inside the tracing plane, ASY109 inside
# the hot planes)
FIXTURE_PATHS = {
    "ASY107": "cometbft_tpu/trace/x.py",
    "ASY109": "cometbft_tpu/mempool/x.py",
    "ASY110": "cometbft_tpu/p2p/x.py",
    "ASY111": "cometbft_tpu/consensus/x.py",
    "ASY112": "cometbft_tpu/p2p/x.py",
    "ASY113": "cometbft_tpu/light/x.py",
    "ASY114": "cometbft_tpu/consensus/x.py",
    "ASY115": "cometbft_tpu/consensus/x.py",
    "ASY117": "cometbft_tpu/consensus/x.py",
    "ASY118": "cometbft_tpu/consensus/x.py",
    "ASY119": "cometbft_tpu/consensus/x.py",
    "ASY120": "cometbft_tpu/store/x.py",
    "ASY121": "cometbft_tpu/blocksync/x.py",
    "ASY122": "cometbft_tpu/fleet/x.py",
    "ASY123": "cometbft_tpu/state/x.py",
}


# --- 1. rule fixtures -------------------------------------------------
#
# (rule_id, positive fixture that MUST flag, negative fixture that
# MUST stay clean for that rule)

FIXTURES = [
    (
        "ASY101",  # blocking-call-in-async
        """
        import time
        async def f():
            time.sleep(1.0)
        """,
        """
        import asyncio, time
        async def f():
            await asyncio.sleep(1.0)
            await asyncio.to_thread(time.sleep, 1.0)
        def g():
            time.sleep(1.0)  # sync context: fine
        """,
    ),
    (
        "ASY102",  # unawaited-coroutine
        """
        import asyncio
        async def f():
            asyncio.sleep(1.0)
        """,
        """
        import asyncio
        async def f():
            await asyncio.sleep(1.0)
            t = asyncio.sleep(1.0)
            await t
        """,
    ),
    (
        "ASY102",  # unawaited self-method coroutine
        """
        class R:
            async def pump(self):
                pass
            async def run(self):
                self.pump()
        """,
        """
        class R:
            async def pump(self):
                pass
            async def run(self):
                await self.pump()
                # chained receiver: target object unknown, not flagged
                self.pool.pump()
        """,
    ),
    (
        "ASY103",  # dropped-task
        """
        import asyncio
        async def f(coro):
            asyncio.create_task(coro)
        """,
        """
        import asyncio
        from cometbft_tpu.utils.tasks import spawn
        async def f(coro):
            t = asyncio.create_task(coro)
            spawn(coro)
            return t
        """,
    ),
    (
        "ASY104",  # broad-except-in-async: bare except over await
        """
        async def f(x):
            try:
                await x()
            except Exception:
                pass
        """,
        """
        import asyncio
        async def f(x):
            try:
                await x()
            except asyncio.CancelledError:
                raise
            except Exception:
                pass
        """,
    ),
    (
        "ASY104",  # tuple spelling still swallows CancelledError
        """
        import asyncio
        async def f(t):
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        """,
        """
        async def f(x):
            try:
                y = x + 1   # no await in try body: not our concern
            except Exception:
                y = 0
            return y
        """,
    ),
    (
        "ASY105",  # sync-lock-across-await
        """
        import asyncio
        async def f(self):
            with self._lock:
                await asyncio.sleep(0)
        """,
        """
        import asyncio
        async def f(self):
            async with self._lock:
                await asyncio.sleep(0)
            with self._lock:
                self.n += 1   # no await while held: fine
        """,
    ),
    (
        "ASY106",  # nested-event-loop
        """
        import asyncio
        async def f(coro):
            asyncio.run(coro)
        """,
        """
        import asyncio
        def cli(coro):
            asyncio.run(coro)   # sync entry point: fine
        """,
    ),
    (
        "JAX201",  # host-sync-in-jit
        """
        import jax
        @jax.jit
        def f(x):
            return x.sum().item()
        """,
        """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def f(x):
            n = int(x.shape[0])   # static metadata: fine
            return jnp.sum(x) + n
        def host(x):
            return x.sum().item()  # not jitted: fine
        """,
    ),
    (
        "JAX201",  # the `return jax.jit(core)` factory idiom is seen
        """
        import jax, numpy as np
        def make():
            def core(x):
                return np.asarray(x)
            return jax.jit(core)
        """,
        """
        import jax
        import jax.numpy as jnp
        def make():
            def core(x):
                return jnp.asarray(x)   # device-side: fine
            return jax.jit(core)
        """,
    ),
    (
        "JAX202",  # stray-block-until-ready
        """
        def f(res):
            res.block_until_ready()
        """,
        """
        def f(res):
            return res
        """,
    ),
    (
        "JAX203",  # traced-loop
        """
        import jax
        @jax.jit
        def f(x):
            s = 0.0
            for v in x:
                s = s + v
            return s
        """,
        """
        import jax
        @jax.jit
        def f(x, n):
            s = 0.0
            for i in range(4):      # static trip count: fine
                s = s + x[i]
            for j, w in enumerate((1, 2)):   # static pytree: fine
                s = s + w
            return s
        """,
    ),
    (
        "JAX204",  # per-call-jit
        """
        import jax
        def f(xs, g):
            out = []
            for x in xs:
                out.append(jax.jit(g)(x))
            return out
        """,
        """
        import jax
        def make(g):
            return jax.jit(g)   # bound once by the caller: fine
        """,
    ),
    (
        "ASY107",  # wallclock-in-trace (path-scoped: FIXTURE_PATHS)
        """
        import time
        def stamp():
            return time.time_ns()
        """,
        """
        import time
        def stamp():
            return time.monotonic_ns()
        def also_fine():
            return time.perf_counter()
        """,
    ),
    (
        "ASY108",  # sync-abci-in-receive
        """
        class MempoolishReactor(Reactor):
            def receive(self, chan_id, peer, msg):
                self.mempool.check_tx(msg, sender=peer.peer_id)
        class ServingReactor:
            def receive(self, chan_id, peer, msg):
                chunk = self.proxy.snapshot.load_snapshot_chunk(1, 0, 0)
        """,
        """
        class GoodReactor(Reactor):
            def receive(self, chan_id, peer, msg):
                self.ingest.submit_nowait(msg, sender=peer.peer_id)
                n = self.mempool.size()   # not an ABCI call: fine
        class NotAReactorClass:
            def receive(self, chan_id, peer, msg):
                self.mempool.check_tx(msg)  # not a reactor: fine
        class OtherReactor(Reactor):
            def add_peer(self, peer):
                self.proxy.info(None)  # not receive(): other rules' job
        """,
    ),
    (
        "ASY109",  # unbounded-queue-in-hot-plane (FIXTURE_PATHS)
        """
        import asyncio
        def build():
            a = asyncio.Queue()
            b = asyncio.Queue(maxsize=0)
            c = InstrumentedQueue(name="x")
            return a, b, c
        """,
        """
        import asyncio, queue
        def build():
            a = asyncio.Queue(100)
            b = asyncio.Queue(maxsize=256)
            c = InstrumentedQueue(512, name="x")
            d = queue.Queue()      # sync stdlib queue: not this rule
            e = Queue()            # ambiguous bare spelling: not ours
            return a, b, c, d, e
        """,
    ),
    (
        "ASY110",  # unbounded-await-in-stop (FIXTURE_PATHS)
        """
        import asyncio
        class Plane:
            async def stop(self):
                await self.inner.stop()
            async def close(self):
                await self.task
        """,
        """
        import asyncio
        class Plane:
            async def stop(self):
                await self._halt(True)          # covered delegation
                await asyncio.sleep(0.1)
            async def _halt(self, graceful):
                try:
                    await asyncio.wait_for(self.task, 5.0)
                except asyncio.TimeoutError:
                    pass
                await guard.stage("x", self.inner.stop())
                await asyncio.wait({self.task}, timeout=1.0)
            async def run(self):
                await self.inner.stop()         # not a stop path
        """,
    ),
    (
        "ASY112",  # finite-reconnect-give-up (FIXTURE_PATHS)
        """
        import asyncio
        class Switch:
            async def _reconnect_routine(self, peer_id, addr):
                for _ in range(20):
                    await asyncio.sleep(1.0)
                    try:
                        await self.dial_peer(addr, peer_id)
                        return
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        continue
        """,
        """
        import asyncio
        class Plane:
            async def _fast_routine(self, peer_id):
                attempt = 0
                while attempt < self.fast_attempts:
                    await asyncio.sleep(0.1)
                    attempt += 1
                    if await self._try_dial(peer_id):
                        return
                # budget spent = LANE TRANSITION, not a give-up
                self._park_slow_lane(peer_id)
            async def crawl(self):
                # iterating candidate ADDRESSES, not a retry budget
                for addr in self.book.pick_to_dial(set(), 3):
                    await self.dial_peer(addr)
            async def sweep(self):
                while True:
                    await asyncio.sleep(30.0)
                    await self.dial_peer("a@b:1")
        """,
    ),
    (
        "ASY111",  # direct-fsync-in-hot-plane (FIXTURE_PATHS)
        """
        import os
        def persist(f):
            f.flush()
            os.fsync(f.fileno())
        """,
        """
        def persist(self, msg):
            # barriers route through the WAL group-commit seam
            self.wal.write_sync(msg)
            return self.wal.write_group(msg)
        """,
    ),
    (
        "ASY113",  # uncoalesced-verify-in-light (FIXTURE_PATHS)
        """
        from .. import types as T
        def check(chain_id, vals, block_id, height, commit):
            T.verify_commit_light(
                chain_id, vals, block_id, height, commit
            )
            T.verify_commit_light_trusting(
                chain_id, vals, commit, cache=None
            )
        """,
        """
        from .. import types as T
        def check(self, chain_id, vals, block_id, height, commit):
            T.verify_commit_light(
                chain_id, vals, block_id, height, commit,
                cache=self.cache,
            )
            self.engine.verify_commit_light(
                vals, block_id, height, commit
            )
            engine.verify_commit_light_trusting(
                vals, commit, level
            )
        """,
    ),
    (
        "ASY114",  # transitive-blocking-call (interprocedural;
        # FIXTURE_PATHS — hot plane): the blocking leaf hides TWO
        # frames down a self.<attr>.<method> chain the attribute-type
        # inference must resolve
        """
        import time
        class Pool:
            def drain(self):
                self._wait()
            def _wait(self):
                time.sleep(0.5)
        class Reactor:
            def __init__(self):
                self.pool = Pool()
            async def run(self):
                self.pool.drain()
        """,
        """
        import asyncio, time
        class Pool:
            def drain(self):
                time.sleep(0.5)
        class Reactor:
            def __init__(self):
                self.pool = Pool()
            async def run(self):
                # a function REFERENCE passed to the offload seam is
                # an argument, not a call: no edge, no finding
                await asyncio.to_thread(self.pool.drain)
            def sync_entry(self):
                self.pool.drain()   # sync context: fine
        """,
    ),
    (
        "ASY115",  # await-holding-lock (interprocedural)
        """
        import os, threading
        class W:
            def __init__(self):
                self._lock = threading.Lock()
            def _barrier(self, f):
                os.fsync(f.fileno())
            def persist(self, f):
                with self._lock:
                    self._barrier(f)
        """,
        """
        import os, threading
        class W:
            def __init__(self):
                self._lock = threading.Lock()
            def _barrier(self, f):
                os.fsync(f.fileno())  # bftlint: disable=ASY111
            def persist(self, f):
                with self._lock:
                    f.write(b"x")
                self._barrier(f)   # outside the critical section
        """,
    ),
    (
        "ASY116",  # sync-listener-blocking-call (interprocedural):
        # the pre-ISSUE-15 indexer shape — a bus sync listener whose
        # chain ends in a DB batch write runs INSIDE every publish,
        # so the consensus finalize path pays the disk write
        """
        class Indexer:
            def __init__(self, db):
                self.db = db
            def index(self, e):
                self.db.write_batch([(b"k", b"v")])
        class Service:
            def __init__(self, bus, idx: Indexer):
                self.idx = idx
                bus.add_sync_listener(idx.index)
        """,
        """
        import asyncio
        class Indexer:
            def __init__(self, db):
                self.db = db
            def flush(self, bundle):
                self.db.write_batch(bundle)
        class Service:
            def __init__(self, bus, idx: Indexer):
                self.idx = idx
                self.pending = []
                bus.add_sync_listener(self.on_event)
            def on_event(self, e):
                # accumulate-only: the listener never touches the DB
                self.pending.append(e)
            async def drain(self):
                # the flush is OFFLOADED — a function reference is an
                # argument, not a call: no edge, no finding
                await asyncio.to_thread(self.idx.flush, self.pending)
        """,
    ),
    (
        "ASY117",  # superlinear-msg-handler (interprocedural): the
        # per-message receive path reaches a validators-domain loop
        # two hops down — O(V) per message, O(V^2) per height
        """
        class Reactor:
            def __init__(self, validators):
                self.validators = validators
            def receive(self, msg, peer):
                self._tally(msg)
            def _tally(self, msg):
                total = 0
                for v in self.validators:
                    total += v.voting_power
        """,
        """
        class Reactor:
            def __init__(self, validators):
                self.by_addr = {v.address: v for v in validators}
                self.total = 0
            def receive(self, msg, peer):
                # incremental: one dict lookup + a running sum, no
                # committee loop on the per-message path
                val = self.by_addr.get(msg.address)
                if val is not None:
                    self.total += val.voting_power
            def rebuild(self, validators):
                # membership change, not per-message: loop is fine
                self.by_addr = {v.address: v for v in validators}
        """,
    ),
    (
        "ASY118",  # nested-committee-loop: validator x validator is
        # the direct quadratic (the update_with_change_set shape
        # this PR fixed with a one-pass address index)
        """
        from typing import Sequence
        def update(validators, changes: Sequence[Validator]):
            out = []
            updates = [c for c in changes if c.power > 0]
            for v in validators:
                for c in updates:
                    if c.address == v.address:
                        out.append(c)
            return out
        """,
        """
        from typing import Sequence
        def update(validators, changes: Sequence[Validator]):
            by_addr = {c.address: c for c in changes}  # index once
            out = []
            for v in validators:
                c = by_addr.get(v.address)
                if c is not None:
                    out.append(c)
            return out
        def retries(validators):
            # committee x constant: bounded inner loop, not nesting
            for v in validators:
                for attempt in range(3):
                    pass
        """,
    ),
    (
        "ASY119",  # unbounded-growth-in-hot-plane: a container attr
        # fed by the per-message path with no prune anywhere is the
        # months-horizon soak leak
        """
        class Reactor:
            def __init__(self):
                self.seen = set()
            def receive(self, msg, peer):
                self.seen.add(msg.key())
        """,
        """
        class Reactor:
            def __init__(self):
                self.seen = set()
            def receive(self, msg, peer):
                self.seen.add(msg.key())
            def advance_height(self):
                self.seen.clear()  # pruned on height advance
        """,
    ),
    (
        "ASY120",  # unbounded-delete-in-hot-plane: a DB-scan loop
        # deleting one row per iteration — unbounded trip count and
        # no crash-consistency marker (the shape the retention
        # plane's sliced write_batch discipline replaces)
        """
        def prune(db, prefix):
            for k, v in db.iter_prefix(prefix):
                db.delete(k)
        """,
        """
        def prune(db, prefix, marker, enc):
            # sanctioned: collect doomed keys, ONE atomic batch with
            # the base-marker advance riding along
            doomed = [k for k, _ in db.iter_prefix(prefix)]
            db.write_batch([(marker, enc)], doomed)
        def drop_bounded(db, doomed):
            # bounded plain-list loop: not scan-driven, fine
            for k in doomed:
                db.delete(k)
        """,
    ),
    (
        "ASY121",  # verify-bypass-scheduler: a hot plane building a
        # BatchVerifier / touching the parallel-verify pool directly
        # verifies outside the scheduler's priority classes
        """
        from cometbft_tpu.crypto.batch import CpuBatchVerifier
        from cometbft_tpu.crypto import batch, parallel_verify
        def window_verify(jobs):
            v = CpuBatchVerifier()
            for pk, msg, sig in jobs:
                v.add(pk, msg, sig)
            return v.verify()
        def factory_verify(jobs):
            return batch.create_batch_verifier()
        def pool_verify(items):
            return parallel_verify.engine().verify(items)
        """,
        """
        from cometbft_tpu.crypto import scheduler as crypto_sched
        from cometbft_tpu.crypto.parallel_verify import (
            dispatch_stats_if_running,
        )
        from cometbft_tpu.crypto import parallel_verify
        def window_verify(jobs):
            # sanctioned: the unified scheduler's priority classes
            t = crypto_sched.scheduler().submit(
                jobs, priority=crypto_sched.PRIORITY_CATCHUP
            )
            return t.result()
        def gauges():
            # stats reads are not verification
            return parallel_verify.dispatch_stats_if_running()
        """,
    ),
    (
        "ASY122",  # serve-bypass-router: fleet code serving off a
        # replica's plane directly skips gate admission, consistency
        # tokens and lag/failover accounting
        """
        def handle_light(replica, height):
            s = replica.light_plane.open_session()
            return s.verified_block(height)
        def warm(replica, cache, height, fn):
            cache.get_or_verify(height, fn)
            return replica.light_plane.serve(height)
        """,
        """
        def handle_light(router, height, token):
            # sanctioned: the router seam admits, tokens and counts
            return router.serve_light(height, token)
        def rotate_out(replica):
            # plane lifecycle is not serving
            replica.light_plane.drain(5.0)
            replica.light_plane.resume()
            return replica.light_plane.stats()
        """,
    ),
    (
        "ASY123",  # per-item-hash-in-finalize-path: a for-loop
        # hashing per tx reached from a finalize phase root — the
        # host overhead the native finalize lane batches away
        """
        import hashlib
        class Exec:
            def apply_block(self, state, block):
                resp = self.proxy.finalize_block(block)
                self._persist(block, resp)
            def _persist(self, block, resp):
                hashes = []
                for tx in block.txs:
                    hashes.append(hashlib.sha256(tx).digest())
                self.store.save(block.height, hashes)
        """,
        """
        import hashlib
        from cometbft_tpu.state import native_finalize
        class Exec:
            def apply_block(self, state, block):
                resp = self.proxy.finalize_block(block)
                # sanctioned shape: ONE batched native pass, the
                # artifacts carry every per-item derivation
                arts = native_finalize.finalize_pass(block.txs, resp)
                self._persist(block, arts)
            def _persist(self, block, arts):
                self.store.save(block.height, arts.results_hash)
            def decode_rows(self, rows):
                # not finalize-reachable: per-item work off the
                # apply path is out of scope
                return [hashlib.sha256(r).digest() for r in rows]
        """,
    ),
    (
        "SYN000",  # syntax errors are findings, not crashes
        """
        def f(:
        """,
        """
        def f():
            return 1
        """,
    ),
]


@pytest.mark.parametrize(
    "rule_id,bad,good",
    FIXTURES,
    ids=[f"{r}-{i}" for i, (r, _, _) in enumerate(FIXTURES)],
)
def test_rule_fixture(rule_id, bad, good):
    path = FIXTURE_PATHS.get(rule_id, "x.py")
    assert rule_id in ids_of(bad, path), (
        f"{rule_id} missed its positive"
    )
    assert rule_id not in ids_of(good, path), (
        f"{rule_id} false-positived on its negative"
    )


def test_asy109_scoped_to_hot_planes():
    src = """
    import asyncio
    def f():
        return asyncio.Queue()
    """
    # tools / tests / utils are out of scope: an unbounded queue in a
    # CLI helper is not a hot-plane OOM hazard
    assert "ASY109" not in ids_of(src)
    assert "ASY109" not in ids_of(src, "cometbft_tpu/utils/x.py")
    for pkg in ("p2p", "consensus", "types", "obs", "rpc"):
        assert "ASY109" in ids_of(src, f"cometbft_tpu/{pkg}/x.py"), pkg


def test_asy107_scoped_to_trace_package():
    src = """
    import time
    def stamp():
        return time.time()
    """
    assert "ASY107" not in ids_of(src)  # outside the plane: fine
    assert "ASY107" in ids_of(src, "cometbft_tpu/trace/export.py")


def test_asy116_sanctioned_registration():
    """A justified suppression at the registration line is the
    escape hatch (state/indexer.py start(): the only blocking reach
    is the no-loop inline degrade)."""
    src = textwrap.dedent(
        """
        class Indexer:
            def __init__(self, db):
                self.db = db
            def index(self, e):
                self.db.write_batch([(b"k", b"v")])
        class Service:
            def __init__(self, bus, idx: Indexer):
                self.idx = idx
                bus.add_sync_listener(idx.index)  # bftlint: disable=ASY116
        """
    )
    assert "ASY116" not in ids_of(src)


def test_asy116_repo_indexer_shape_stays_clean():
    """The shipped IndexerService accumulates in memory — the one
    suppression in state/indexer.py must remain the ONLY one needed
    (the whole-repo gate below enforces zero new findings, this
    pins the specific rule)."""
    from cometbft_tpu.analysis.engine import REPO_ROOT, run

    findings = [
        f
        for f in run([str(REPO_ROOT / "cometbft_tpu" / "state")])
        if f.rule_id == "ASY116"
    ]
    assert findings == [], findings


def test_at_least_eight_distinct_rules_have_fixtures():
    covered = {r for r, _, _ in FIXTURES if r != "SYN000"}
    assert len(covered) >= 8, covered


def test_every_registered_rule_has_a_fixture():
    registered = {r.rule_id for r in all_rules()} | {
        pr.rule_id for pr in all_project_rules()
    }
    covered = {r for r, _, _ in FIXTURES}
    assert registered <= covered, registered - covered


# --- 2a. suppression machinery ---------------------------------------

TWO_RULES_ONE_LINE = """
import time
async def f(loop):
    loop.run_until_complete(time.sleep(1)){}
"""


def test_disable_silences_only_named_rule_on_that_line():
    # the line triggers BOTH ASY101 (time.sleep in async) and ASY106
    # (run_until_complete in async)
    base = ids_of(TWO_RULES_ONE_LINE.format(""))
    assert {"ASY101", "ASY106"} <= set(base)
    got = ids_of(
        TWO_RULES_ONE_LINE.format("  # bftlint: disable=ASY106")
    )
    assert "ASY106" not in got and "ASY101" in got


def test_disable_does_not_leak_to_other_lines():
    src = """
    import time
    async def f():
        time.sleep(1)  # bftlint: disable=ASY101
        time.sleep(2)
    """
    found = analyze_source(textwrap.dedent(src), "x.py")
    lines = [f.line for f in found if f.rule_id == "ASY101"]
    assert lines == [5]


def test_disable_by_rule_name_and_disable_next():
    src = """
    import time
    async def f():
        # bftlint: disable-next=blocking-call-in-async
        time.sleep(1)
    """
    assert "ASY101" not in ids_of(src)


def test_disable_file_silences_whole_file_one_rule_only():
    src = """
    # bftlint: disable-file=ASY101
    import asyncio, time
    async def f():
        time.sleep(1)
        asyncio.sleep(2)
    """
    got = ids_of(src)
    assert "ASY101" not in got and "ASY102" in got


def test_unknown_suppression_is_reported():
    src = """
    def f():
        return 1  # bftlint: disable=NOPE999
    """
    assert "SUP001" in ids_of(src)


def test_resolve_accepts_id_and_name():
    assert resolve("ASY101") == "ASY101"
    assert resolve("blocking-call-in-async") == "ASY101"
    assert resolve("nope") is None


# --- 2b. baseline machinery ------------------------------------------


def _f(path, line, rule="ASY104"):
    return Finding(path, line, 0, rule, "broad-except-in-async", "m")


def test_baseline_roundtrip(tmp_path):
    entries = baseline_mod.build([_f("a.py", 1), _f("a.py", 9)])
    p = tmp_path / "b.json"
    baseline_mod.save(str(p), entries)
    assert baseline_mod.load(str(p)) == {"a.py": {"ASY104": 2}}


def test_baseline_exact_count_is_clean_and_over_is_new():
    bl = {"a.py": {"ASY104": 2}}
    new, stale = baseline_mod.apply([_f("a.py", 1), _f("a.py", 9)], bl)
    assert new == [] and stale == []
    new, stale = baseline_mod.apply(
        [_f("a.py", 1), _f("a.py", 9), _f("a.py", 30)], bl
    )
    assert len(new) == 3  # count exceeded: all reported (can't tell
    assert stale == []    # old from new by line)


def test_stale_baseline_entries_are_reported():
    bl = {"a.py": {"ASY104": 2}, "gone.py": {"ASY101": 1}}
    new, stale = baseline_mod.apply([_f("a.py", 1)], bl)
    assert new == []
    got = {(s.path, s.rule_id, s.allowed, s.current) for s in stale}
    assert got == {("a.py", "ASY104", 2, 1), ("gone.py", "ASY101", 1, 0)}


# --- 2c. CLI exit-code contract --------------------------------------

CLEAN = "def f():\n    return 1\n"
DIRTY = "import time\nasync def f():\n    time.sleep(1)\n"


def test_cli_exit_zero_on_clean(tmp_path, capsys):
    p = tmp_path / "ok.py"
    p.write_text(CLEAN)
    assert main([str(p), "--no-baseline"]) == 0
    assert "clean" in capsys.readouterr().out


def test_cli_exit_one_on_violation(tmp_path, capsys):
    p = tmp_path / "bad.py"
    p.write_text(DIRTY)
    assert main([str(p), "--no-baseline"]) == 1
    assert "ASY101" in capsys.readouterr().out


def test_cli_baseline_covers_violation(tmp_path, capsys):
    p = tmp_path / "bad.py"
    p.write_text(DIRTY)
    bl = tmp_path / "bl.json"
    assert main([str(p), "--baseline", str(bl),
                 "--update-baseline"]) == 0
    capsys.readouterr()
    assert main([str(p), "--baseline", str(bl)]) == 0


def test_cli_stale_reported_and_fail_on_stale(tmp_path, capsys):
    p = tmp_path / "ok.py"
    p.write_text(CLEAN)
    bl = tmp_path / "bl.json"
    baseline_mod.save(str(bl), {"nothere.py": {"ASY101": 1}})
    assert main([str(p), "--baseline", str(bl)]) == 0
    assert "stale baseline" in capsys.readouterr().out
    assert main([str(p), "--baseline", str(bl),
                 "--fail-on-stale"]) == 1


def test_cli_json_format(tmp_path, capsys):
    p = tmp_path / "bad.py"
    p.write_text(DIRTY)
    assert main([str(p), "--no-baseline", "--format", "json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["findings"][0]["rule_id"] == "ASY101"


def test_cli_syntax_error_fails(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    assert main([str(p), "--no-baseline"]) == 1


def test_cli_nonexistent_path_is_usage_error(tmp_path, capsys):
    """A typo'd path must not report 'clean' (exit 0) — and must
    never reach --update-baseline, which would wipe the baseline."""
    ghost = str(tmp_path / "no_such_dir")
    assert main([ghost, "--no-baseline"]) == 2
    bl = tmp_path / "bl.json"
    baseline_mod.save(str(bl), {"a.py": {"ASY104": 1}})
    assert main([ghost, "--baseline", str(bl),
                 "--update-baseline"]) == 2
    assert baseline_mod.load(str(bl)) == {"a.py": {"ASY104": 1}}


def test_lockish_does_not_match_block_identifiers():
    """'lock' must be a name segment, not a substring: block_store /
    unblock are not locks (regression: blockchain codebase!)."""
    src = """
    import asyncio
    async def f(self):
        with self.block_writer():
            await asyncio.sleep(0)
    """
    assert "ASY105" not in ids_of(src)
    src2 = """
    import asyncio
    async def f(self):
        with self.state_lock:
            await asyncio.sleep(0)
    """
    assert "ASY105" in ids_of(src2)


def test_jit_wrap_invoke_in_loop_reports_once():
    src = """
    import jax
    def f(xs, g):
        for x in xs:
            y = jax.jit(g)(x)
        return y
    """
    found = [
        f for f in analyze_source(textwrap.dedent(src), "x.py")
        if f.rule_id == "JAX204"
    ]
    assert len(found) == 1, found


# --- 2d. call graph (interprocedural model) ---------------------------

import ast as _ast

from cometbft_tpu.analysis.callgraph import Project


def _proj(**files):
    """Project from {filename_stem: source}; stems become
    cometbft_tpu/consensus/<stem>.py so hot-plane rules apply."""
    return Project(
        [
            (f"cometbft_tpu/consensus/{k}.py",
             _ast.parse(textwrap.dedent(v)))
            for k, v in files.items()
        ]
    )


def _chain(p, qual):
    return p.blocking_chain(qual)


def test_callgraph_cycles_terminate():
    p = _proj(m="""
        import time
        def a():
            b()
        def b():
            a()
            time.sleep(1)
        def pure_cycle_x():
            pure_cycle_y()
        def pure_cycle_y():
            pure_cycle_x()
    """)
    f = "cometbft_tpu/consensus/m.py"
    # a -> b -> sleep (the b->a back-edge contributes nothing)
    assert _chain(p, f + "::a") == ["b", "time.sleep"]
    # a pure cycle has no chain and does not hang
    assert _chain(p, f + "::pure_cycle_x") is None


def test_callgraph_inheritance_and_super_dispatch():
    p = _proj(m="""
        import time
        class Base:
            def helper(self):
                time.sleep(1)
            def stop(self):
                self.helper()
        class Child(Base):
            def stop(self):
                super().stop()
        class GrandChild(Child):
            def run(self):
                self.helper()   # two levels up the chain
    """)
    f = "cometbft_tpu/consensus/m.py"
    assert _chain(p, f + "::Child.stop") == [
        "super().stop", "self.helper", "time.sleep"
    ]
    assert _chain(p, f + "::GrandChild.run") == [
        "self.helper", "time.sleep"
    ]


def test_callgraph_decorated_defs_still_resolve():
    p = _proj(m="""
        import functools, time
        def deco(fn):
            return fn
        @deco
        def helper():
            time.sleep(1)
        @functools.lru_cache(maxsize=None)
        def cached_helper():
            helper()
        def entry():
            cached_helper()
    """)
    f = "cometbft_tpu/consensus/m.py"
    assert _chain(p, f + "::entry") == [
        "cached_helper", "helper", "time.sleep"
    ]


def test_callgraph_functools_partial_edge():
    p = _proj(m="""
        import functools, time
        def helper(x):
            time.sleep(x)
        def entry():
            functools.partial(helper, 1)()
        def entry2(run):
            run(functools.partial(helper, 2))
    """)
    f = "cometbft_tpu/consensus/m.py"
    # partial(f, ...) creates the edge to f in both shapes
    assert _chain(p, f + "::entry") == ["helper", "time.sleep"]
    assert _chain(p, f + "::entry2") == ["helper", "time.sleep"]


def test_callgraph_lambda_callees_attributed_to_enclosing():
    p = _proj(m="""
        import time
        def helper():
            time.sleep(1)
        def entry(xs):
            return sorted(xs, key=lambda x: helper())
    """)
    f = "cometbft_tpu/consensus/m.py"
    assert _chain(p, f + "::entry") == ["helper", "time.sleep"]


def test_callgraph_attr_types_from_init_and_annotations():
    p = _proj(m="""
        class Wal:
            async def flush(self):
                pass
        class Pool:
            def __init__(self):
                self.inner = Wal()
        class CS:
            def __init__(self, wal: Wal):
                self.wal = wal
                self.pool = Pool()
    """)
    f = "cometbft_tpu/consensus/m.py"
    cs = p.module_classes[f]["CS"]
    assert cs.attr_types == {"wal": "Wal", "pool": "Pool"}
    pool = p.module_classes[f]["Pool"]
    assert pool.attr_types == {"inner": "Wal"}


def test_asy102_deep_chain_via_inferred_types():
    src = """
    class Pool:
        async def stop(self):
            pass
    class R:
        def __init__(self):
            self.pool = Pool()
        async def shutdown(self):
            self.pool.stop()
    """
    assert "ASY102" in ids_of(src)
    good = """
    class Pool:
        async def stop(self):
            pass
    class R:
        def __init__(self):
            self.pool = Pool()
        async def shutdown(self):
            await self.pool.stop()
        async def unknown_attr(self):
            self.other.stop()   # untyped attr: under-approximate
    """
    assert "ASY102" not in ids_of(good)


def test_asy114_reports_the_full_chain_in_message():
    src = textwrap.dedent("""
    import time
    class Pool:
        def drain(self):
            self._wait()
        def _wait(self):
            time.sleep(0.5)
    class Reactor:
        def __init__(self):
            self.pool = Pool()
        async def run(self):
            self.pool.drain()
    """)
    found = [
        f for f in analyze_source(src, "cometbft_tpu/consensus/x.py")
        if f.rule_id == "ASY114"
    ]
    assert len(found) == 1
    msg = found[0].message
    assert "self.pool.drain" in msg and "time.sleep" in msg


def test_sanctioned_leaf_suppression_kills_chains():
    """A blocking leaf line suppressed for ASY114 in its own file is
    a sanctioned sink: chains through it vanish for ASY114 AND
    ASY115 (the WAL-seam escape hatch)."""
    src = """
    import os, threading
    class W:
        def __init__(self):
            self._lock = threading.Lock()
        def _barrier(self, f):
            os.fsync(f.fileno())  # bftlint: disable=ASY114,ASY111
        def persist(self, f):
            with self._lock:
                self._barrier(f)
        async def apersist(self, f):
            self._barrier(f)
    """
    got = ids_of(src, "cometbft_tpu/consensus/x.py")
    assert "ASY114" not in got and "ASY115" not in got
    # the DIRECT-leaf-inside-the-lock shape honors the same sanction
    # (the WAL rotation barrier's exact form)
    direct = """
    import os, threading
    class W:
        def __init__(self):
            self._lock = threading.Lock()
        def persist(self, f):
            with self._lock:
                os.fsync(f.fileno())  # bftlint: disable=ASY114,ASY111
    """
    assert "ASY115" not in ids_of(direct, "cometbft_tpu/consensus/x.py")


def test_asy114_scoped_to_hot_planes():
    src = """
    import time
    def helper():
        time.sleep(1)
    async def f():
        helper()
    """
    assert "ASY114" in ids_of(src, "cometbft_tpu/consensus/x.py")
    assert "ASY114" in ids_of(src, "cometbft_tpu/node/x.py")
    # chaos/ is the injection harness; tools are out of scope
    assert "ASY114" not in ids_of(src, "cometbft_tpu/chaos/x.py")
    assert "ASY114" not in ids_of(src, "x.py")


def test_asy115_async_lock_flavor():
    src = """
    import time, asyncio
    class W:
        def _grind(self):
            time.sleep(0.1)
        async def hot(self):
            async with self._lock:
                self._grind()
    """
    assert "ASY115" in ids_of(src, "cometbft_tpu/consensus/x.py")


# --- 3. the repo gate -------------------------------------------------


def test_full_tree_is_clean_against_checked_in_baseline(capsys):
    """Every future PR runs this: the shipped tree must lint clean
    (new violations either fixed or explicitly baselined)."""
    rc = main([str(REPO_ROOT / "cometbft_tpu")])
    out = capsys.readouterr().out
    assert rc == 0, f"bftlint regressions:\n{out}"


def test_seeded_violation_fixture_fails_the_gate(tmp_path):
    """End-to-end: a fresh violation exits non-zero via the real CLI."""
    bad = tmp_path / "seeded.py"
    bad.write_text(
        "import asyncio, time\n"
        "async def reactor():\n"
        "    time.sleep(0.5)\n"
        "    asyncio.create_task(reactor())\n"
    )
    proc = subprocess.run(
        [sys.executable, "-m", "cometbft_tpu.analysis", str(bad)],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "ASY101" in proc.stdout and "ASY103" in proc.stdout


def test_lint_sh_entry_point():
    """tools/lint.sh = compileall syntax gate + the analysis pass
    (with --fail-on-stale so a shrinking baseline can never rot, and
    --timings so the interprocedural pass's cost stays visible)."""
    proc = subprocess.run(
        ["bash", str(REPO_ROOT / "tools" / "lint.sh")],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "rule timings" in proc.stdout
    assert "ASY114*" in proc.stdout  # project rules are timed too


def test_shipped_baseline_is_empty():
    """ISSUE 14 burned the ASY104 baseline to zero: every violation
    fixed, none baselined. The ratchet now starts from nothing — any
    new violation anywhere fails the gate outright."""
    doc = json.loads(
        (REPO_ROOT / "tools" / "bftlint_baseline.json").read_text()
    )
    assert doc["entries"] == {}


def test_whole_repo_pass_stays_under_budget():
    """Acceptance: the full interprocedural run must stay under 15s
    on the 2-vCPU box (it is ~5s today; this guards the growth
    curve). Wall-clock, generous to suite contention."""
    import time as _t

    t0 = _t.perf_counter()
    rc = main([str(REPO_ROOT / "cometbft_tpu")])
    wall = _t.perf_counter() - t0
    assert rc == 0
    assert wall < 15.0, f"bftlint took {wall:.1f}s (budget 15s)"
