"""Differential tests for scalar mod-L ops and Edwards point ops.

Layout convention: limb axis first, batch last — shape (20, N).
"""

import random

import jax
import numpy as np
import jax.numpy as jnp

from cometbft_tpu.crypto import ref_ed25519 as ref
from cometbft_tpu.ops import curve25519 as curve
from cometbft_tpu.ops import fe25519 as fe
from cometbft_tpu.ops import sc25519 as sc

import pytest

pytestmark = [pytest.mark.tpu, pytest.mark.slow]  # tpu implies slow: keeps the `-m 'not slow'` fast lane kernel-free

rng = random.Random(99)
L, P = sc.L, fe.P


def _stack_raw(vals, n):
    arr = jnp.asarray(np.stack([sc._raw(v, n) for v in vals], axis=1))
    return tuple(arr[i] for i in range(n))


def test_reduce_512():
    vals = [0, 1, L - 1, L, L + 1, 2**252, 2**512 - 1, sc._C]
    while len(vals) < 24:
        vals.append(rng.randrange(0, 1 << 512))
    x = _stack_raw(vals, 40)
    got = np.asarray(jnp.stack(jax.jit(sc.reduce_512)(x)))
    for i, v in enumerate(vals):
        assert sc.from_limbs(got[:, i]) == v % L, i


def test_neg_lt_bits():
    vals = [0, 1, L - 1, 2**252] + [rng.randrange(0, L) for _ in range(12)]
    h = _stack_raw(vals, 20)
    got = np.asarray(jnp.stack(sc.neg_mod_L(h)))
    for i, v in enumerate(vals):
        assert sc.from_limbs(got[:, i]) == L - v, i  # -0 -> L by design
    # lt_L
    vals2 = [0, L - 1, L, L + 1, 2**255 - 1]
    s = _stack_raw(vals2, 20)
    assert list(np.asarray(sc.lt_L(s))) == [v < L for v in vals2]
    # bits
    b = np.asarray(sc.bits(h))
    for i, v in enumerate(vals):
        for j in range(253):
            assert int(b[j, i]) == (v >> j) & 1


def _pt_lanes(pts):
    """list of ref extended points -> lane arrays (affine-normalized)."""
    xs, ys = [], []
    for p in pts:
        zi = pow(p[2], P - 2, P)
        xs.append(p[0] * zi % P)
        ys.append(p[1] * zi % P)
    X = fe.unstack(
        jnp.asarray(np.stack([fe.to_limbs(x) for x in xs], axis=1))
    )
    Y = fe.unstack(
        jnp.asarray(np.stack([fe.to_limbs(y) for y in ys], axis=1))
    )
    shape = jnp.shape(X[0])
    Z = tuple(
        jnp.full(shape, 1, jnp.int32) if i == 0
        else jnp.zeros(shape, jnp.int32)
        for i in range(fe.NLIMBS)
    )
    T = fe.mul(X, Y)
    return (X, Y, Z, T)


def _lanes_to_affine(pt):
    X, Y, Z, _ = (np.asarray(fe.stack(c)) for c in pt)
    out = []
    for i in range(X.shape[1]):
        zi = pow(fe.from_limbs(Z[:, i]), P - 2, P)
        out.append(
            (
                fe.from_limbs(X[:, i]) * zi % P,
                fe.from_limbs(Y[:, i]) * zi % P,
            )
        )
    return out


def _rand_points(n):
    return [ref.point_mul(rng.randrange(1, L), ref.BASE) for _ in range(n)]


def test_add_double_negate():
    pa, pb = _rand_points(8), _rand_points(8)
    la, lb = _pt_lanes(pa), _pt_lanes(pb)
    got = _lanes_to_affine(curve.add(la, lb))
    for i in range(8):
        w = ref.point_add(pa[i], pb[i])
        zi = pow(w[2], P - 2, P)
        assert got[i] == (w[0] * zi % P, w[1] * zi % P)
    got2 = _lanes_to_affine(curve.double(la))
    for i in range(8):
        w = ref.point_double(pa[i])
        zi = pow(w[2], P - 2, P)
        assert got2[i] == (w[0] * zi % P, w[1] * zi % P)
    # complete law: P + identity, P + P, P + (-P)
    ident = curve.identity((8,))
    assert _lanes_to_affine(curve.add(la, ident)) == _lanes_to_affine(la)
    negs = curve.negate(la)
    assert list(np.asarray(curve.is_identity(curve.add(la, negs)))) == [True] * 8
    assert _lanes_to_affine(curve.add(la, la)) == got2


def test_decompress():
    pts = _rand_points(6)
    encs = [ref.point_compress(p) for p in pts]
    # liberal encoding: y >= p; then a non-point
    encs.append((ref.P + 1).to_bytes(32, "little"))  # y=1 -> identity
    yv = 2
    while ref._recover_x(yv, 0) is not None:
        yv += 1
    encs.append(yv.to_bytes(32, "little"))
    raw = jnp.asarray(
        np.stack([np.frombuffer(e, np.uint8) for e in encs], axis=1)
    )
    pt, ok = jax.jit(curve.decompress)(raw)
    okl = list(np.asarray(ok))
    assert okl == [True] * 7 + [False]
    aff = _lanes_to_affine(pt)
    for i, p in enumerate(pts):
        zi = pow(p[2], P - 2, P)
        assert aff[i] == (p[0] * zi % P, p[1] * zi % P)
    assert aff[6] == (0, 1)  # identity from y = p+1


def test_decompress_sign_bit_and_x0():
    # x = 0, sign = 1 (non-canonical): ZIP-215 accepts, x stays 0.
    enc = bytearray((1).to_bytes(32, "little"))
    enc[31] |= 0x80
    p1 = _rand_points(1)[0]
    enc2 = ref.point_compress(p1)
    raw = jnp.asarray(
        np.stack(
            [np.frombuffer(bytes(enc), np.uint8),
             np.frombuffer(enc2, np.uint8)],
            axis=1,
        )
    )
    pt, ok = jax.jit(curve.decompress)(raw)
    assert list(np.asarray(ok)) == [True, True]
    aff = _lanes_to_affine(pt)
    assert aff[0] == (0, 1)
    zi = pow(p1[2], P - 2, P)
    assert aff[1] == (p1[0] * zi % P, p1[1] * zi % P)
