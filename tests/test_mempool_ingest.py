"""Mempool ingest plane (docs/PERF.md "Mempool ingest plane"):
batched CheckTx, async post-commit recheck, batched tx gossip.

Covers the PR's acceptance surface:
  - keyed TxCache + one-hash-per-tx ingest (satellite);
  - batched CheckTx verdict parity with the serial path, including
    intra-batch duplicates of app-rejected txs (round semantics);
  - check_tx_batch ABCI extension + automatic per-tx fallback;
  - gossip batch codec roundtrip + single-tx/old-peer interop;
  - async recheck: stale-verdict height guard (a tx committed
    mid-recheck never re-enters), reap masking, and update() wall
    time independent of pool size;
  - micro-batching ingest queue coalescing + non-blocking reactor
    receive;
  - bounded fallback `sent` set in the broadcast routine (satellite).
"""

import asyncio
import hashlib
import threading
import time

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.client import LocalClient
from cometbft_tpu.mempool import codec
from cometbft_tpu.mempool.ingest import IngestQueue
from cometbft_tpu.mempool.mempool import (
    CListMempool,
    TxCache,
    tx_key,
    tx_keys,
)
from cometbft_tpu.mempool.reactor import MEMPOOL_CHANNEL, MempoolReactor


class AcceptApp(abci.Application):
    def __init__(self):
        self.checked = 0
        self.batch_calls = 0
        self.batch_sizes = []

    def check_tx(self, req):
        self.checked += 1
        if req.tx.startswith(b"bad"):
            return abci.ResponseCheckTx(code=5, log="rejected")
        return abci.ResponseCheckTx(gas_wanted=1)

    def check_tx_batch(self, reqs):
        self.batch_calls += 1
        self.batch_sizes.append(len(reqs))
        return super().check_tx_batch(reqs)


def make_pool(app=None, **kw):
    app = app or AcceptApp()
    kw.setdefault("max_txs", 10_000)
    kw.setdefault("cache_size", 50_000)
    return CListMempool(LocalClient(app), **kw), app


# --- satellite: one hash per tx, keyed cache ---------------------------


def test_tx_keys_matches_hashlib():
    txs = [b"tx-%d" % i for i in range(64)] + [b"", b"x" * 4096]
    assert tx_keys(txs) == [hashlib.sha256(t).digest() for t in txs]
    assert tx_keys([]) == []


def test_txcache_keyed_api_and_lru():
    c = TxCache(size=2)
    k1, k2, k3 = (tx_key(b"%d" % i) for i in range(3))
    assert c.push(k1) and c.push(k2)
    assert not c.push(k1)  # dup
    assert c.has(k1)
    assert c.push(k3)  # evicts k2 (k1 was touched by the dup push)
    assert not c.has(k2) and c.has(k1) and c.has(k3)
    c.remove(k1)
    assert not c.has(k1)
    # batch push under one lock: in-batch dups reject like serial
    c2 = TxCache(size=10)
    assert c2.push_many([k1, k2, k1]) == [True, True, False]


def test_check_tx_hashes_once_per_tx(monkeypatch):
    """The serial ingest path computes the tx key exactly once (the
    seed hashed up to 3x: cache push + pool insert + log append)."""
    import cometbft_tpu.mempool.mempool as mm

    calls = {"n": 0}
    real = hashlib.sha256

    def counting_sha(data=b""):
        calls["n"] += 1
        return real(data)

    monkeypatch.setattr(mm.hashlib, "sha256", counting_sha)
    mp, _ = make_pool()
    mp.check_tx(b"only-hash-me-once")
    assert calls["n"] == 1


# --- batched CheckTx ---------------------------------------------------


def _mixed_workload(n=300):
    work = []
    for i in range(n):
        work.append(b"tx-%05d" % i)
        if i % 7 == 0:
            work.append(b"bad-%05d" % i)
        if i % 11 == 0:
            work.append(work[-2])  # in-stream duplicate
    work.append(b"z" * (2 << 20))  # oversize
    return work


def test_batch_verdict_parity_with_serial():
    work = _mixed_workload()
    mp_s, _ = make_pool()
    mp_b, _ = make_pool()
    serial = [mp_s.check_tx(t) for t in work]
    batched = mp_b.check_tx_batch(work)
    assert [r.code for r in serial] == [r.code for r in batched]
    assert [r.log for r in serial] == [r.log for r in batched]
    assert list(mp_s.pool.keys()) == list(mp_b.pool.keys())


def test_batch_intra_batch_dup_of_rejected_tx_is_rechecked():
    """Serial semantics: an app-rejected tx leaves the cache, so its
    duplicate later in the SAME batch goes to the app again — not a
    cache-dup reject."""

    class FlipApp(abci.Application):
        def __init__(self):
            self.seen = {}

        def check_tx(self, req):
            n = self.seen.get(req.tx, 0)
            self.seen[req.tx] = n + 1
            # rejected on first sight, accepted on retry (stateful
            # apps exist; the batch path must preserve the retry)
            if n == 0:
                return abci.ResponseCheckTx(code=7, log="first time")
            return abci.ResponseCheckTx()

    mp_b, _ = make_pool(app=FlipApp())
    res = mp_b.check_tx_batch([b"flip", b"flip"])
    mp_s, _ = make_pool(app=FlipApp())
    ref = [mp_s.check_tx(b"flip"), mp_s.check_tx(b"flip")]
    assert [r.code for r in res] == [r.code for r in ref] == [7, 0]


def test_batch_single_abci_call_and_sender_tracking():
    mp, app = make_pool()
    txs = [b"s-%d" % i for i in range(50)]
    mp.check_tx_batch(txs, senders=["peerA"] * len(txs))
    assert app.batch_calls == 1 and app.batch_sizes == [50]
    # duplicate batch from another peer: no ABCI calls, senders merged
    mp.check_tx_batch(txs, senders=["peerB"] * len(txs))
    assert app.batch_calls == 1
    assert mp.tx_senders(tx_key(txs[0])) == {"peerA", "peerB"}


def test_batch_mempool_full_verdict_parity():
    work = [b"full-%d" % i for i in range(20)]
    mp_s, _ = make_pool(max_txs=5)
    mp_b, _ = make_pool(max_txs=5)
    serial = [mp_s.check_tx(t).log for t in work]
    batched = [r.log for r in mp_b.check_tx_batch(work)]
    assert serial == batched
    assert serial.count("mempool full") == 15


def test_proxy_without_batch_extension_falls_back_per_tx():
    class BareProxy:
        """Minimal mempool-connection proxy: no check_tx_batch."""

        def __init__(self):
            self.calls = 0

        def check_tx(self, req):
            self.calls += 1
            return abci.ResponseCheckTx()

    proxy = BareProxy()
    mp = CListMempool(proxy, max_txs=100)
    res = mp.check_tx_batch([b"f-%d" % i for i in range(10)])
    assert all(r.is_ok() for r in res)
    assert proxy.calls == 10  # automatic per-tx fallback loop
    assert mp.size() == 10


def test_notify_and_txs_available_fire_once_per_batch():
    notifies = []
    app = AcceptApp()
    mp = CListMempool(
        LocalClient(app), max_txs=100, notify=lambda: notifies.append(1)
    )
    mp.check_tx_batch([b"n-%d" % i for i in range(10)])
    assert len(notifies) == 1
    assert mp.txs_available().is_set()


# --- gossip batch codec ------------------------------------------------


def test_codec_roundtrip_and_interop():
    cases = [
        [b"a"],
        [b"a", b"b", b"c"],
        [b""] * 3,
        [b"x" * 70000, b"y"],
        [codec.MAGIC + b"tx that starts with the magic"],
    ]
    for txs in cases:
        assert codec.decode_txs(codec.encode_txs(txs)) == txs
    # single non-magic tx keeps the OLD wire form (raw bytes)
    assert codec.encode_txs([b"legacy"]) == b"legacy"
    # old peer -> new node: raw tx decodes as itself
    assert codec.decode_txs(b"raw tx bytes") == [b"raw tx bytes"]
    # old peer relaying a raw tx that happens to start with MAGIC but
    # is not a well-formed batch: delivered as a single tx, not lost
    evil = codec.MAGIC + b"\xff\xff\xff\xff\xff\xff garbage"
    assert codec.decode_txs(evil) == [evil]
    # truncated batch after the magic: same fallback
    frame = codec.encode_batch([b"aa", b"bb"])
    assert codec.decode_txs(frame[:-1]) == [frame[:-1]]
    with pytest.raises(ValueError):
        codec.encode_batch([])


# --- async recheck -----------------------------------------------------


class GatedRecheckApp(abci.Application):
    """Recheck calls block until released; new CheckTx is instant."""

    def __init__(self):
        self.gate = threading.Event()
        self.rechecked = []

    def check_tx(self, req):
        if req.type_ == abci.CHECK_TX_TYPE_RECHECK:
            assert self.gate.wait(10), "recheck gate never released"
            self.rechecked.append(req.tx)
            if req.tx.startswith(b"drop"):
                return abci.ResponseCheckTx(code=9, log="invalid now")
        return abci.ResponseCheckTx()


def _wait(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return False


def test_async_recheck_applies_verdicts_and_unmasks():
    app = GatedRecheckApp()
    mp, _ = make_pool(app=app, async_recheck=True)
    for i in range(20):
        mp.check_tx(b"keep-%d" % i)
    mp.check_tx(b"drop-me")
    mp.lock()
    try:
        mp.update(1, [], [])
    finally:
        mp.unlock()
    # whole pool masked while the recheck is in flight
    assert mp.reap_max_bytes_max_gas(-1, -1) == []
    assert mp.recheck_pending() == 21
    app.gate.set()
    assert _wait(lambda: mp.recheck_pending() == 0)
    assert mp.size() == 20  # drop-me rechecked out
    assert len(mp.reap_max_bytes_max_gas(-1, -1)) == 20
    assert mp.txs_available().is_set()


def test_async_recheck_stale_height_guard():
    """A tx committed mid-recheck never re-enters the pool, and the
    superseded recheck's verdicts are dropped wholesale."""
    app = GatedRecheckApp()
    mp, _ = make_pool(app=app, async_recheck=True)
    victim = b"committed-mid-recheck"
    mp.check_tx(victim)
    mp.check_tx(b"drop-stale")  # would be removed by recheck 1
    mp.update(1, [], [])  # snapshot taken, recheck blocked on gate
    assert mp.recheck_pending() == 2
    # block 2 commits the victim while recheck 1 is still in flight
    mp.update(2, [victim], [abci.ResponseCheckTx()])
    assert tx_key(victim) not in mp.pool
    app.gate.set()
    # recheck 2 (for the remaining tx) applies; recheck 1 is stale
    assert _wait(lambda: mp.recheck_pending() == 0)
    assert tx_key(victim) not in mp.pool  # never re-entered
    # drop-stale was STILL removed — by recheck 2, not the stale one
    assert _wait(lambda: mp.size() == 0)


def test_async_recheck_flush_aborts_inflight():
    app = GatedRecheckApp()
    mp, _ = make_pool(app=app, async_recheck=True)
    mp.check_tx(b"drop-x")
    mp.update(1, [], [])
    mp.flush()
    assert mp.recheck_pending() == 0
    app.gate.set()
    time.sleep(0.05)  # stale recheck lands on an empty pool: no-op
    assert mp.size() == 0


def test_update_wall_time_independent_of_pool_size():
    """With async recheck, update() leaves the consensus critical
    section without touching the app: its wall time must not scale
    with the pooled tx count (the seed ran one synchronous ABCI
    round-trip per pooled tx here)."""

    class SlowRecheckApp(abci.Application):
        def check_tx(self, req):
            if req.type_ == abci.CHECK_TX_TYPE_RECHECK:
                time.sleep(0.002)  # 2ms per recheck round-trip
            return abci.ResponseCheckTx()

    def timed_update(n_txs):
        mp, _ = make_pool(app=SlowRecheckApp(), async_recheck=True)
        for i in range(n_txs):
            mp.check_tx(b"u-%d-%d" % (n_txs, i))
        mp.lock()
        try:
            t0 = time.perf_counter()
            mp.update(1, [], [])
            return time.perf_counter() - t0
        finally:
            mp.unlock()

    small, large = timed_update(25), timed_update(800)
    # a serial recheck of 800 txs at 2ms each would hold the lock for
    # >= 1.6s; the async update must return in milliseconds and stay
    # flat in pool size (generous bounds for this throttled box)
    assert large < 0.4, f"update held the lock {large:.3f}s"
    assert large < max(20 * small, 0.4), (small, large)


def test_sync_recheck_semantics_preserved():
    """async_recheck off: update still rechecks inline (one batched
    ABCI call) and prunes invalidated txs before returning."""
    app = GatedRecheckApp()
    app.gate.set()  # no blocking
    mp, _ = make_pool(app=app, async_recheck=False)
    mp.check_tx(b"keep-1")
    mp.check_tx(b"drop-1")
    mp.update(1, [], [])
    assert mp.size() == 1  # pruned inside update
    assert mp.recheck_pending() == 0
    assert len(mp.reap_max_bytes_max_gas(-1, -1)) == 1


# --- ingest queue ------------------------------------------------------


def test_ingest_queue_coalesces_and_resolves():
    async def main():
        mp, app = make_pool()
        q = IngestQueue(mp, batch_max_txs=64, batch_flush_ms=5.0)
        q.start()
        res = await asyncio.gather(
            *[q.submit(b"iq-%d" % i) for i in range(200)]
        )
        assert all(r.is_ok() for r in res)
        assert mp.size() == 200
        # coalesced: far fewer ABCI batches than txs
        assert 1 <= q.batches <= 30
        assert max(app.batch_sizes) > 1
        await q.stop()
        assert not q.running

    asyncio.run(main())


def test_ingest_queue_submit_nowait_and_overflow():
    async def main():
        mp, _ = make_pool()
        q = IngestQueue(mp, batch_max_txs=8, batch_flush_ms=1.0, max_queue=4)
        assert not q.submit_nowait(b"not-running")  # queue not started
        q.start()
        # stall the drainer so the queue genuinely fills
        accepted = sum(
            1 for i in range(64) if q.submit_nowait(b"ow-%d" % i)
        )
        assert accepted < 64 and q.dropped > 0
        await asyncio.sleep(0.1)
        assert mp.size() == accepted
        await q.stop()

    asyncio.run(main())


def test_ingest_queue_app_failure_fails_batch_not_plane():
    class BoomApp(abci.Application):
        def __init__(self):
            self.boom = True

        def check_tx(self, req):
            if self.boom:
                raise RuntimeError("app crashed")
            return abci.ResponseCheckTx()

    async def main():
        app = BoomApp()
        mp = CListMempool(LocalClient(app), max_txs=100)
        q = IngestQueue(mp, batch_max_txs=8, batch_flush_ms=1.0)
        q.start()
        res = await q.submit(b"boom-tx")
        assert res.code != 0 and "ingest failed" in res.log
        app.boom = False
        res2 = await q.submit(b"boom-tx-2")  # plane still alive
        assert res2.is_ok()
        await q.stop()

    asyncio.run(main())


# --- reactor: non-blocking receive + batched gossip --------------------


class FakePeer:
    def __init__(self, peer_id="peer-1"):
        self.peer_id = peer_id
        self.sent = []

    async def send(self, chan_id, msg):
        self.sent.append((chan_id, msg))
        return True

    def try_send(self, chan_id, msg):
        self.sent.append((chan_id, msg))
        return True


def test_receive_decodes_batches_and_stays_nonblocking():
    class SlowApp(abci.Application):
        def check_tx(self, req):
            time.sleep(0.01)  # a blocking receive would eat 10ms/tx
            return abci.ResponseCheckTx()

    async def main():
        mp = CListMempool(LocalClient(SlowApp()), max_txs=100)
        r = MempoolReactor(mp, broadcast=False, batch_flush_ms=1.0)
        await r.start()
        peer = FakePeer()
        frame = codec.encode_txs([b"g-%d" % i for i in range(20)])
        t0 = time.perf_counter()
        r.receive(MEMPOOL_CHANNEL, peer, frame)
        dt = time.perf_counter() - t0
        assert dt < 0.05, f"receive blocked for {dt:.3f}s"
        for _ in range(400):
            if mp.size() == 20:
                break
            await asyncio.sleep(0.01)
        assert mp.size() == 20
        assert mp.tx_senders(tx_key(b"g-0")) == {"peer-1"}
        await r.stop()

    asyncio.run(main())


def test_receive_without_started_ingest_degrades_to_direct():
    mp, _ = make_pool()
    r = MempoolReactor(mp, broadcast=False)
    r.receive(MEMPOOL_CHANNEL, FakePeer(), b"standalone-tx")
    assert mp.size() == 1  # processed inline, no event loop needed


def test_broadcast_routine_batches_txs():
    async def main():
        mp, _ = make_pool()
        r = MempoolReactor(
            mp, broadcast=True, gossip_batch_bytes=4096, batch_max_txs=64
        )
        peer = FakePeer("peer-b")
        mp.check_tx_batch([b"bb-%03d" % i for i in range(100)])
        r.add_peer(peer)
        for _ in range(100):
            if sum(
                len(codec.decode_txs(m)) for _, m in peer.sent
            ) >= 100:
                break
            await asyncio.sleep(0.01)
        r.remove_peer(peer, None)
        got = [
            tx for _, m in peer.sent for tx in codec.decode_txs(m)
        ]
        assert got == [b"bb-%03d" % i for i in range(100)]
        # actually coalesced: fewer messages than txs
        assert len(peer.sent) < 100
        await r.stop()

    asyncio.run(main())


def test_broadcast_frames_never_exceed_channel_cap():
    """A batch frame larger than the channel's max_msg_size kills the
    peer connection on the receiver — the routine must flush BEFORE a
    tx would push the frame past the cap, and a magic-prefixed tx too
    big for the batch-of-one escape goes out raw."""
    from cometbft_tpu.mempool.reactor import MAX_FRAME_BYTES

    async def main():
        mp, _ = make_pool(max_txs=200)
        # misconfigured soft target ABOVE the hard cap: the hard cap
        # must still hold
        r = MempoolReactor(
            mp, broadcast=True,
            gossip_batch_bytes=2 * MAX_FRAME_BYTES, batch_max_txs=10_000,
        )
        peer = FakePeer("cap-peer")
        big = b"B" * (900 * 1024)
        magic_big = codec.MAGIC + b"M" * (MAX_FRAME_BYTES - 4)
        txs = [b"c-%04d" % i + b"x" * 4096 for i in range(60)]
        txs += [big, magic_big]
        mp.check_tx_batch(txs)
        r.add_peer(peer)
        want = set(txs)
        for _ in range(200):
            got = [
                tx for _, m in peer.sent for tx in codec.decode_txs(m)
            ]
            if set(got) >= want:
                break
            await asyncio.sleep(0.01)
        r.remove_peer(peer, None)
        await r.stop()
        assert set(got) >= want, len(got)
        assert all(len(m) <= MAX_FRAME_BYTES for _, m in peer.sent), [
            len(m) for _, m in peer.sent if len(m) > MAX_FRAME_BYTES
        ]

    asyncio.run(main())


def test_ingest_stop_mid_window_resolves_collected_futures():
    """stop() while the drainer holds a partially collected batch
    must resolve those futures instead of leaving RPC callers
    hanging."""

    async def main():
        mp, _ = make_pool()
        # long flush window so the first tx sits in the drainer's
        # local batch, off the queue, when stop() lands
        q = IngestQueue(mp, batch_max_txs=64, batch_flush_ms=5000.0)
        q.start()
        fut = asyncio.ensure_future(q.submit(b"stuck-in-window"))
        await asyncio.sleep(0.1)  # drainer popped it, awaiting more
        assert not fut.done()
        await q.stop()
        res = await asyncio.wait_for(fut, 2)
        assert res.code != 0 and "stopped" in res.log

    asyncio.run(main())


def test_broadcast_skips_txs_from_the_peer_itself():
    async def main():
        mp, _ = make_pool()
        r = MempoolReactor(mp, broadcast=True)
        peer = FakePeer("origin-peer")
        mp.check_tx(b"mine", sender="origin-peer")
        mp.check_tx(b"other", sender="someone-else")
        r.add_peer(peer)
        await asyncio.sleep(0.15)
        r.remove_peer(peer, None)
        got = [
            tx for _, m in peer.sent for tx in codec.decode_txs(m)
        ]
        assert got == [b"other"]
        await r.stop()

    asyncio.run(main())


def test_fallback_sent_set_is_bounded():
    """Satellite: mempools without txs_after (the legacy walk) must
    not grow the per-peer dedup set forever."""
    import cometbft_tpu.mempool.reactor as reactor_mod

    class MinimalMempool:
        """No txs_after: forces the fallback path."""

        def __init__(self):
            self.txs = []

        def iter_txs(self):
            return list(self.txs)

    async def run_with_cap(cap, n_txs):
        old = reactor_mod.SENT_CACHE_SIZE
        reactor_mod.SENT_CACHE_SIZE = cap
        try:
            mp = MinimalMempool()
            r = MempoolReactor(mp, broadcast=True)
            peer = FakePeer("fb-peer")
            mp.txs = [b"fb-%04d" % i for i in range(n_txs)]
            r.add_peer(peer)
            await asyncio.sleep(0.18)  # several gossip ticks
            r.remove_peer(peer, None)
            await r.stop()
            got = [
                tx for _, m in peer.sent for tx in codec.decode_txs(m)
            ]
            return got
        finally:
            reactor_mod.SENT_CACHE_SIZE = old

    async def main():
        # cap >> pool: perfect dedup, each tx exactly once across ticks
        got = await run_with_cap(1000, 100)
        assert sorted(set(got)) == [b"fb-%04d" % i for i in range(100)]
        assert len(got) == 100
        # cap << pool: every tx still delivered, and the EVICTED keys
        # re-send on later ticks — proof the dedup set really is
        # bounded at the cap instead of growing with pool history
        got = await run_with_cap(16, 100)
        assert sorted(set(got)) == [b"fb-%04d" % i for i in range(100)]
        assert len(got) > 100

    asyncio.run(main())


# --- node-level: chaos run with async recheck --------------------------


def test_chaos_run_with_async_recheck_stays_invariant_clean(tmp_path):
    """4-node seeded chaos pass (partition + heal) with the async
    recheck plane explicitly pinned ON and txs flowing the whole
    time: every node keeps committing, the agreement invariant stays
    clean, and committed txs were really pumped through the batched
    ingest + background recheck path."""
    from cometbft_tpu.chaos.net import ChaosNet

    seen_cfgs = []

    def hook(cfg):
        cfg.mempool.async_recheck = True
        seen_cfgs.append(cfg)

    async def wait_height(net, target, timeout=60.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if net.max_height() >= target:
                return
            await asyncio.sleep(0.05)
        raise AssertionError(
            f"liveness: never reached height {target}: {net.heights()}"
        )

    async def main():
        net = ChaosNet(
            4, seed=77, base_dir=str(tmp_path), config_hook=hook
        )
        await net.start()
        try:
            stop_load = asyncio.Event()

            async def load():
                i = 0
                while not stop_load.is_set():
                    for _, node in net.running_nodes()[:2]:
                        node.parts.mempool.check_tx(b"chaos%06d=v" % i)
                        i += 1
                    await asyncio.sleep(0.02)

            loader = asyncio.create_task(load())
            try:
                await wait_height(net, 2)
                # majority partition (canonical smoke shape): the
                # 3-group keeps quorum and keeps committing txs while
                # the minority node is blackholed, then heals back
                ids = [cn.node_id for cn in net.nodes]
                net.table.partition([ids[:3], ids[3:]])
                await wait_height(net, net.max_height() + 2)
                net.table.heal()
                await wait_height(net, net.max_height() + 3)
            finally:
                stop_load.set()
                await loader
            net.agreement.final_check(net.running_nodes())
            # the plane was really on and really exercised
            for cn in net.nodes:
                mp = cn.node.parts.mempool
                assert mp.async_recheck
            committed = sum(
                n.parts.block_store.load_block(h).data.txs != []
                for _, n in net.running_nodes()
                for h in range(1, n.height + 1)
            )
            assert committed > 0, "no txs ever committed"
        finally:
            # the stop tail is bounded inside ChaosNet.stop (per-node
            # ShutdownGuard stages, obs/shutdown.py) — this outer
            # wait_for is the regression tripwire for the full-suite
            # wedge this test used to hit (loop alive, store fds
            # open) so a recurrence fails HERE instead of hanging CI
            await asyncio.wait_for(net.stop(), 120.0)
            assert not net.shutdown_stall_records(), (
                net.shutdown_stall_records()
            )

    asyncio.run(main())
    assert len(seen_cfgs) == 4 and all(
        c.mempool.async_recheck for c in seen_cfgs
    )
