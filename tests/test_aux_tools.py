"""Aux subsystems: FuzzedConnection, loadtime, debug/pprof, BLS gate,
psql sink gating (reference p2p/fuzz.go, test/loadtime,
commands/debug, crypto/bls12381, state/indexer/sink/psql)."""

import asyncio
import os

import pytest

from cometbft_tpu.p2p.fuzz import (
    FuzzConnConfig,
    FuzzedConnection,
    maybe_fuzz,
)


class _FakeSconn:
    def __init__(self, chunks=None):
        self.written = []
        self.chunks = list(chunks or [])
        self.closed = False

    async def write_msg(self, data):
        self.written.append(data)
        return len(data)

    async def read_chunk(self):
        if not self.chunks:
            raise ConnectionError("eof")
        return self.chunks.pop(0)

    def close(self):
        self.closed = True


def test_fuzz_passthrough_when_disabled():
    sconn = _FakeSconn()
    assert maybe_fuzz(sconn, None) is sconn
    assert maybe_fuzz(sconn, FuzzConnConfig(enable=False)) is sconn


def test_fuzz_drop_mode_drops_writes():
    sconn = _FakeSconn()
    cfg = FuzzConnConfig(
        enable=True, mode="drop", prob_drop_rw=0.5, seed=7
    )
    fz = FuzzedConnection(sconn, cfg)

    async def go():
        for _ in range(200):
            await fz.write_msg(b"x")

    asyncio.run(go())
    # with p=0.5 over 200 writes, both dropped and delivered are certain
    assert fz.dropped_writes > 20
    assert len(sconn.written) > 20
    assert fz.dropped_writes + len(sconn.written) == 200


def test_fuzz_drop_conn_kills():
    sconn = _FakeSconn()
    cfg = FuzzConnConfig(
        enable=True, mode="drop", prob_drop_rw=0.0, prob_drop_conn=1.0
    )
    fz = FuzzedConnection(sconn, cfg)
    with pytest.raises(ConnectionError):
        asyncio.run(fz.write_msg(b"x"))
    assert sconn.closed


def test_fuzz_delay_mode_preserves_traffic():
    sconn = _FakeSconn(chunks=[b"a", b"b"])
    cfg = FuzzConnConfig(
        enable=True, mode="delay", prob_sleep=1.0, max_delay_ms=1
    )
    fz = FuzzedConnection(sconn, cfg)

    async def go():
        await fz.write_msg(b"msg")
        return await fz.read_chunk(), await fz.read_chunk()

    a, b = asyncio.run(go())
    assert (a, b) == (b"a", b"b")
    assert sconn.written == [b"msg"]


# --- loadtime -----------------------------------------------------------


def test_latency_report_math():
    from cometbft_tpu.e2e.load import latency_report, make_tx

    class Hdr:
        def __init__(self, t):
            self.time_ns = t

    class Blk:
        def __init__(self, t, txs):
            self.header = Hdr(t)
            self.data = type("D", (), {"txs": txs})()

    base = 1_000_000_000_000
    blocks = {
        1: Blk(base + int(1e9), [make_tx(1, 64, base)]),
        2: Blk(
            base + int(2e9),
            [make_tx(2, 64, base), b"other=1"],
        ),
        3: Blk(base + int(3e9), []),
    }

    class FakeClient:
        async def block_decoded(self, h):
            return blocks[h]

    rep = asyncio.run(latency_report(FakeClient(), 1, 3))
    assert rep.count == 2
    assert rep.min_s == pytest.approx(1.0)
    assert rep.max_s == pytest.approx(2.0)
    assert rep.mean_s == pytest.approx(1.5)
    assert rep.heights == 3
    assert rep.block_interval_mean_s == pytest.approx(1.0)


# --- debug / pprof ------------------------------------------------------


def test_all_stacks_and_heap():
    import tracemalloc

    from cometbft_tpu.utils.debug import all_stacks, heap_stats

    out = all_stacks()
    assert "thread MainThread" in out
    try:
        heap_stats()  # starts tracing
        out = heap_stats()
        assert "current=" in out
    finally:
        # tracemalloc left tracing would tax EVERY allocation for the
        # REST of the suite (this file runs third alphabetically): it
        # measurably starved the chaos scenarios' event loops — loop
        # lag p50 jumped ~70ms and the statesync-join compound blew
        # its liveness budgets
        tracemalloc.stop()


def test_debug_server_endpoints():
    from aiohttp import ClientSession

    from cometbft_tpu.utils.debug import DebugServer

    async def go():
        srv = DebugServer("127.0.0.1:0")
        await srv.start()
        port = srv._runner.addresses[0][1]
        async with ClientSession() as sess:
            async with sess.get(
                f"http://127.0.0.1:{port}/debug/pprof/stacks"
            ) as r:
                assert r.status == 200
                assert "thread" in await r.text()
        await srv.stop()

    asyncio.run(go())


def test_collect_debug_dump(tmp_path):
    """dump against a fake node RPC; missing endpoints become .err
    entries rather than failures."""
    import json
    import zipfile

    from aiohttp import web

    from cometbft_tpu.utils.debug import collect_debug_dump

    async def go():
        app = web.Application()

        async def status(_r):
            return web.json_response({"result": {"ok": True}})

        app.router.add_get("/status", status)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = runner.addresses[0][1]
        path = await asyncio.to_thread(
            collect_debug_dump, f"127.0.0.1:{port}", str(tmp_path)
        )
        await runner.cleanup()
        return path

    path = asyncio.run(go())
    with zipfile.ZipFile(path) as z:
        names = z.namelist()
        assert "status.json" in names
        assert "net_info.json.err" in names
        meta = json.loads(z.read("meta.json"))
        assert "rpc" in meta


# --- BLS gate -----------------------------------------------------------


def test_bls_gated_off_by_default(monkeypatch):
    monkeypatch.delenv("COMETBFT_TPU_BLS12381", raising=False)
    from cometbft_tpu.crypto.keys import Bls12381PubKey

    with pytest.raises(NotImplementedError):
        Bls12381PubKey(b"\x00" * 48)


def test_bls_sign_verify_when_enabled(monkeypatch):
    monkeypatch.setenv("COMETBFT_TPU_BLS12381", "1")
    from cometbft_tpu.crypto.keys import (
        Bls12381PrivKey,
        pubkey_from_type_bytes,
    )

    priv = Bls12381PrivKey.from_seed(b"test-seed")
    pub = priv.pub_key()
    sig = priv.sign(b"vote-sign-bytes")
    assert pub.verify(b"vote-sign-bytes", sig)
    assert not pub.verify(b"other-bytes", sig)
    # registry dispatch
    pk2 = pubkey_from_type_bytes("bls12381", bytes(pub))
    assert pk2.verify(b"vote-sign-bytes", sig)


# --- psql sink gate -----------------------------------------------------


def test_psql_sink_gated_without_driver():
    from cometbft_tpu.state import psql_sink

    if psql_sink.available():  # pragma: no cover
        pytest.skip("psycopg2 installed in this image")
    with pytest.raises(RuntimeError, match="psycopg2"):
        psql_sink.PsqlSink("host=localhost", "chain")
