"""Host verification plane (crypto/parallel_verify) differential
suite: the parallel engine must be BIT-IDENTICAL to the serial cpu
backend on every input — RFC 8032 vectors, forged/mutated lanes
landing on their exact indices, ZIP-215 liberal edge cases (which
OpenSSL rejects and the liberal recheck must still accept), order
stability across chunk sizes and worker counts, and the process-pool
tier over the pure-Python crypto fallback. Plus the overlap contract:
the blocksync reactor's event loop stays responsive while a window's
verify wait runs, and block-store writes land one batch per window.
"""

import asyncio
import time

import numpy as np
import pytest

from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.crypto import keys as crypto_keys
from cometbft_tpu.crypto import native_verify
from cometbft_tpu.crypto import parallel_verify as pv
from cometbft_tpu.crypto.keys import Ed25519PrivKey, Secp256k1PrivKey
from cometbft_tpu.crypto.parallel_verify import ParallelVerifyEngine

# RFC 8032 §7.1 TEST 1-3 (seed, pub, msg, sig)
RFC8032_VECTORS = [
    (
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
        "",
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b",
    ),
    (
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
        "72",
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
        "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00",
    ),
    (
        "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
        "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
        "af82",
        "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
        "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a",
    ),
]


def _vector_items():
    """Vector lanes + a forged twin of each (sig bit flipped) — the
    forgeries must land on exactly the odd indices."""
    items = []
    for seed_hex, pub_hex, msg_hex, sig_hex in RFC8032_VECTORS:
        pk = crypto_keys.Ed25519PubKey(bytes.fromhex(pub_hex))
        msg = bytes.fromhex(msg_hex)
        sig = bytes.fromhex(sig_hex)
        assert (
            Ed25519PrivKey.from_seed(
                bytes.fromhex(seed_hex)
            ).pub_key().key_bytes
            == pk.key_bytes
        )
        items.append((pk, msg, sig))
        bad = bytearray(sig)
        bad[7] ^= 0x40
        items.append((pk, msg, bytes(bad)))
    return items


def _random_items(n, seed=3, n_keys=12):
    rng = np.random.default_rng(seed)
    privs = [
        Ed25519PrivKey.from_seed(rng.bytes(32)) for _ in range(n_keys)
    ]
    items = []
    for i in range(n):
        p = privs[i % n_keys]
        m = bytes(rng.bytes(40 + (i % 90)))
        items.append((p.pub_key(), m, p.sign(m)))
    return items


def _serial_verdicts(items):
    v = crypto_batch.CpuBatchVerifier()
    for it in items:
        v.add(*it)
    return v.verify()[1]


def test_rfc8032_vectors_parallel_vs_serial():
    items = _vector_items()
    want = [i % 2 == 0 for i in range(len(items))]
    assert _serial_verdicts(items) == want
    eng = ParallelVerifyEngine(min_parallel=1)
    try:
        assert eng.verify(items) == want
    finally:
        eng.close()


def test_forged_and_edge_lanes_land_on_exact_indices():
    """Mixed adversarial batch: valid lanes, a zeroed sig, a mutated
    msg, a wrong key, a truncated sig, a secp256k1 lane, and a
    ZIP-215 liberal lane (identity pubkey + S=0 sig: OpenSSL rejects
    it, the cofactored liberal check accepts — the exact case the
    native fast path must re-check in Python)."""
    from cometbft_tpu.crypto import ref_ed25519 as ref

    items = _random_items(120)
    sp = Secp256k1PrivKey.generate()
    sp_msg = b"mixed-lane"
    items[17] = (items[17][0], items[17][1], bytes(64))
    items[41] = (items[41][0], b"mutated!", items[41][2])
    items[42] = (items[0][0], items[42][1], items[42][2])
    items[77] = (items[77][0], items[77][1], items[77][2][:60])
    items[88] = (sp.pub_key(), sp_msg, sp.sign(sp_msg))
    ident = ref.point_compress(ref.IDENTITY)
    items[99] = (
        crypto_keys.Ed25519PubKey(ident),
        b"small order",
        ident + b"\x00" * 32,
    )
    # non-canonical pubkey encoding (y = p+1 ≡ identity): ZIP-215
    # decodes it liberally, OpenSSL's strict decoder rejects it — the
    # canonical "fast path rejects, liberal recheck accepts" lane
    items[100] = (
        crypto_keys.Ed25519PubKey((ref.P + 1).to_bytes(32, "little")),
        b"liberal encoding",
        ident + b"\x00" * 32,
    )
    want = _serial_verdicts(items)
    assert want[100], "liberal-encoding lane must verify"
    assert not want[17] and not want[41] and not want[42]
    assert not want[77]
    assert want[88], "secp lane must verify on the host path"
    assert want[99], "ZIP-215 liberal lane must verify"
    for tier in ("thread",):
        eng = ParallelVerifyEngine(min_parallel=1, tier=tier)
        try:
            assert eng.verify(items) == want, tier
        finally:
            eng.close()
    # and through the registered backend
    old = crypto_batch._default_backend
    crypto_batch.set_default_backend("cpu-parallel")
    try:
        v = crypto_batch.create_batch_verifier()
        for it in items:
            v.add(*it)
        all_ok, oks = v.verify()
        assert not all_ok and oks == want
        v2 = crypto_batch.create_batch_verifier()
        for it in items:
            v2.add(*it)
        assert v2.verify_async().result() == (False, want)
    finally:
        crypto_batch.set_default_backend(old)


def test_order_stability_across_chunk_sizes_and_workers():
    items = _random_items(257)  # deliberately not chunk-aligned
    items[3] = (items[3][0], items[3][1], bytes(64))
    items[255] = (items[255][0], b"x", items[255][2])
    want = _serial_verdicts(items)
    for workers in (2, 3):
        for target_s in (2e-4, 5e-3, 1.0):
            eng = ParallelVerifyEngine(
                workers=workers,
                min_parallel=1,
                chunk_target_s=target_s,
            )
            try:
                got = eng.verify(items)
                assert got == want, (workers, target_s)
            finally:
                eng.close()


def test_native_chunk_matches_python_loop():
    if native_verify.module() is None:
        pytest.skip("native extension unavailable (no compiler)")
    items = _random_items(64)
    items[5] = (items[5][0], items[5][1], bytes(64))
    want = [pk.verify(m, s) for pk, m, s in items]
    assert native_verify.verify_chunk(items) == want


def test_process_pool_tier_on_pure_python_fallback(monkeypatch):
    """With every OpenSSL tier gone (pure-Python crypto fallback) the
    engine must pick the PROCESS tier — pure verify holds the GIL, so
    threads cannot spread it — and verdicts stay bit-identical.
    The fork start method propagates the monkeypatched tier flags to
    the workers."""
    monkeypatch.setattr(crypto_keys, "_HAVE_OSSL", False)
    monkeypatch.setattr(crypto_keys, "_HAVE_CTYPES_OSSL", False)
    # the native extension rides libcrypto too: simulate its absence
    monkeypatch.setattr(native_verify, "_tried", True)
    monkeypatch.setattr(native_verify, "_mod", None)
    assert not pv._ed25519_releases_gil()
    items = _random_items(8, n_keys=2)
    items[2] = (items[2][0], items[2][1], bytes(64))
    want = [pk.verify(m, s) for pk, m, s in items]
    # workers pinned: tier SELECTION is under test, not cpu_count
    # detection — on a 1-vCPU box auto-detected workers=1 correctly
    # degrades to serial (covered by the test below), which would
    # mask the thread-vs-process choice this test asserts
    eng = ParallelVerifyEngine(min_parallel=1, workers=2)
    try:
        assert eng.tier == "process"
        got = eng.verify(items)
        assert got == want
        assert not got[2] and got[0]
    finally:
        eng.close()


def test_serial_degrade_when_single_worker():
    eng = ParallelVerifyEngine(workers=1)
    try:
        assert eng.tier == "serial"
        items = _random_items(30, n_keys=3)
        assert eng.verify(items) == _serial_verdicts(items)
    finally:
        eng.close()


def test_tpu_backend_host_lanes_ride_the_parallel_plane(monkeypatch):
    """Host-routed batches on the DEFAULT (tpu) backend must go
    through the shared engine — every coalesced caller gets the
    multi-core plane for free — and verify_async must hand back a
    genuinely pending handle, not an eagerly-resolved one."""
    calls = []
    real_engine = pv.engine()

    class Recorder:
        def verify(self, items):
            calls.append(("verify", len(items)))
            return real_engine.verify(items)

        def verify_async(self, items):
            calls.append(("verify_async", len(items)))
            return real_engine.verify_async(items)

    monkeypatch.setattr(pv, "engine", lambda: Recorder())
    old = crypto_batch._default_backend
    old_min = crypto_batch._MIN_TPU_BATCH
    crypto_batch.set_default_backend("tpu")
    crypto_batch.set_min_tpu_batch(1 << 30)  # force host routing
    try:
        items = _random_items(80, n_keys=4)
        v = crypto_batch.create_batch_verifier()
        for it in items:
            v.add(*it)
        ok, oks = v.verify()
        assert ok and all(oks)
        v2 = crypto_batch.create_batch_verifier()
        for it in items:
            v2.add(*it)
        handle = v2.verify_async()
        assert isinstance(handle, crypto_batch._PendingHostVerdicts)
        assert handle.result() == (True, [True] * 80)
        assert ("verify", 80) in calls
        assert ("verify_async", 80) in calls
    finally:
        crypto_batch.set_min_tpu_batch(old_min)
        crypto_batch.set_default_backend(old)


# --- reactor overlap + store batching -----------------------------------


def _make_src(n_blocks, n_vals=3, chain_id="pplane"):
    from cometbft_tpu.node.inprocess import make_genesis
    from cometbft_tpu.utils.chaingen import make_chain

    gen, pvs = make_genesis(n_vals, chain_id=chain_id)
    src = make_chain(gen, [pv_.priv_key for pv_ in pvs], n_blocks)
    return gen, src


def test_event_loop_responsive_during_window_verify(monkeypatch):
    """The reactor's verify wait runs in an executor: a heartbeat
    task must keep ticking while a (deliberately slow) window verify
    blocks. Before the overlapped path, each 0.4 s result() starved
    the loop for its full duration."""
    from cometbft_tpu.blocksync import reactor as reactor_mod
    from cometbft_tpu.blocksync.reactor import BlockSyncReactor
    from cometbft_tpu.node.inprocess import build_node
    from cometbft_tpu.utils.chaingen import StorePeerClient

    gen, src = _make_src(24)
    real = reactor_mod.verify_commits_coalesced_async
    slow_calls = []

    def wrapped(chain_id, jobs, cache=None, light=True, **kw):
        handle = real(chain_id, jobs, cache=cache, light=light, **kw)

        class Slow:
            def result(self):
                slow_calls.append(len(jobs))
                time.sleep(0.4)
                return handle.result()

        return Slow()

    monkeypatch.setattr(
        reactor_mod, "verify_commits_coalesced_async", wrapped
    )

    async def main():
        fresh = build_node(gen, None)
        caught = asyncio.Event()
        reactor = BlockSyncReactor(
            fresh.state,
            fresh.block_exec,
            fresh.block_store,
            on_caught_up=lambda st: caught.set(),
            verify_window=8,
        )
        reactor.pool.set_peer_range(
            "src", StorePeerClient(src), 1, src.block_store.height()
        )
        gaps = []
        stop = asyncio.Event()

        async def heartbeat():
            last = time.monotonic()
            while not stop.is_set():
                await asyncio.sleep(0.01)
                now = time.monotonic()
                gaps.append(now - last)
                last = now

        hb = asyncio.create_task(heartbeat())
        await reactor.start()
        await asyncio.wait_for(caught.wait(), 60)
        stop.set()
        await reactor.stop()
        await hb
        return fresh, max(gaps)

    fresh, max_gap = asyncio.run(asyncio.wait_for(main(), 120))
    assert fresh.block_store.height() >= src.block_store.height() - 2
    assert len(slow_calls) >= 2, "test must exercise >=2 slow waits"
    # each verify wait blocked 0.4s; a responsive loop never gaps
    # anywhere near that (generous margin for a loaded box)
    assert max_gap < 0.25, f"event loop starved: max gap {max_gap:.3f}s"


def test_block_store_writes_one_batch_per_window():
    from cometbft_tpu.blocksync.reactor import BlockSyncReactor
    from cometbft_tpu.node.inprocess import build_node
    from cometbft_tpu.utils.chaingen import StorePeerClient

    gen, src = _make_src(40, chain_id="pplane-batch")

    async def main():
        fresh = build_node(gen, None)
        caught = asyncio.Event()
        db = fresh.block_store.db
        counts = []
        orig = db.write_batch

        def counting(sets, deletes=()):
            counts.append(sum(1 for _ in sets))
            return orig(sets, deletes)

        db.write_batch = counting
        reactor = BlockSyncReactor(
            fresh.state,
            fresh.block_exec,
            fresh.block_store,
            on_caught_up=lambda st: caught.set(),
            verify_window=8,
        )
        reactor.pool.set_peer_range(
            "src", StorePeerClient(src), 1, src.block_store.height()
        )
        await reactor.start()
        await asyncio.wait_for(caught.wait(), 60)
        await reactor.stop()
        return fresh, reactor, counts

    fresh, reactor, counts = asyncio.run(
        asyncio.wait_for(main(), 120)
    )
    applied = reactor.blocks_applied
    assert applied >= src.block_store.height() - 2
    # one write_batch per WINDOW (plus pool-timing slack), nowhere
    # near one per block — windows are up to 7 applies at window=8
    assert len(counts) < applied / 2, (len(counts), applied)
    assert max(counts) > 4, "batches must carry multiple blocks"


def test_save_block_batch_contiguity_and_roundtrip():
    from cometbft_tpu import types as T
    from cometbft_tpu.store.block_store import BlockStore
    from cometbft_tpu.utils import codec, kv

    gen, src = _make_src(6, chain_id="pplane-store")
    store = BlockStore(kv.MemKV())

    def entry(h):
        blk = src.block_store.load_block(h)
        parts = T.PartSet.from_data(codec.encode_block(blk))
        return (blk, parts, src.block_store.load_seen_commit(h))

    store.save_block_batch([entry(1), entry(2), entry(3)])
    assert store.base() == 1 and store.height() == 3
    for h in (1, 2, 3):
        assert (
            store.load_block(h).hash()
            == src.block_store.load_block(h).hash()
        )
        assert store.load_seen_commit(h) is not None
    with pytest.raises(ValueError):
        store.save_block_batch([entry(5)])  # gap after 3
    with pytest.raises(ValueError):
        store.save_block_batch([entry(4), entry(6)])  # internal gap
    assert store.height() == 3
    store.save_block_batch([entry(4)])
    assert store.height() == 4
    assert store.load_block_commit(3) is not None
