"""Metrics endpoint test: /metrics serves live consensus gauges.

prometheus_client is a TIERED dependency (utils/metrics.py): the live
endpoint tests skip without the wheel, and the shim test proves a
node still builds and renders when the import is blocked."""

import asyncio
import importlib
import sys

import aiohttp
import pytest

from cometbft_tpu.config.config import test_config as make_test_cfg
from cometbft_tpu.node.inprocess import make_genesis
from cometbft_tpu.node.node import Node
from cometbft_tpu.utils import metrics as metrics_mod

needs_prometheus = pytest.mark.skipif(
    not metrics_mod.HAVE_PROMETHEUS,
    reason="prometheus_client wheel not installed (shim tier active)",
)


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def test_metrics_shim_without_prometheus():
    """With the wheel absent the module must land on the no-op shim:
    NodeMetrics constructs, accepts the whole attach/observe surface,
    and renders a placeholder instead of raising."""
    saved = {
        k: v for k, v in sys.modules.items()
        if k == "prometheus_client" or k.startswith("prometheus_client.")
    }
    for k in saved:
        # a None entry makes `import prometheus_client` raise
        # ImportError — the canonical absent-wheel simulation
        sys.modules[k] = None
    sys.modules["prometheus_client"] = None
    try:
        shimmed = importlib.reload(metrics_mod)
        assert not shimmed.HAVE_PROMETHEUS
        m = shimmed.NodeMetrics("shim-chain")
        m.height.set(3)
        m.total_txs.inc(2)
        m.block_interval.observe(0.5)
        m._h_step.labels(chain_id="shim-chain", step="PROPOSE").observe(
            0.01
        )
        assert b"unavailable" in m.render()
    finally:
        for k in list(sys.modules):
            if k == "prometheus_client" or k.startswith(
                "prometheus_client."
            ):
                del sys.modules[k]
        sys.modules.update(saved)
        importlib.reload(metrics_mod)
    assert metrics_mod.HAVE_PROMETHEUS == bool(saved)


def test_metrics_server_endpoint_shim_tier():
    """The /metrics HTTP endpoint itself must serve under the no-wheel
    shim tier (ISSUE 6 satellite): same aiohttp server, placeholder
    body, correct content type — a node with prometheus = true and no
    wheel still answers scrapes instead of 500ing."""
    saved = {
        k: v for k, v in sys.modules.items()
        if k == "prometheus_client" or k.startswith("prometheus_client.")
    }
    for k in saved:
        sys.modules[k] = None
    sys.modules["prometheus_client"] = None
    try:
        shimmed = importlib.reload(metrics_mod)
        assert not shimmed.HAVE_PROMETHEUS

        async def main():
            m = shimmed.NodeMetrics("shim-srv")
            srv = shimmed.MetricsServer(m)
            await srv.start("127.0.0.1:0")
            try:
                async with aiohttp.ClientSession() as s:
                    async with s.get(
                        f"http://{srv.listen_addr}/metrics"
                    ) as resp:
                        assert resp.status == 200
                        assert resp.content_type == "text/plain"
                        text = await resp.text()
                assert "unavailable" in text
            finally:
                await srv.stop()

        run(main())
    finally:
        for k in list(sys.modules):
            if k == "prometheus_client" or k.startswith(
                "prometheus_client."
            ):
                del sys.modules[k]
        sys.modules.update(saved)
        importlib.reload(metrics_mod)
    assert metrics_mod.HAVE_PROMETHEUS == bool(saved)


@needs_prometheus
def test_metrics_server_endpoint_real_tier_standalone():
    """Real-wheel twin of the shim test: a bare NodeMetrics (no node
    attached) serves the registered metric families over HTTP."""

    async def main():
        m = metrics_mod.NodeMetrics("real-srv")
        m.height.set(7)
        srv = metrics_mod.MetricsServer(m)
        await srv.start("127.0.0.1:0")
        try:
            async with aiohttp.ClientSession() as s:
                async with s.get(
                    f"http://{srv.listen_addr}/metrics"
                ) as resp:
                    assert resp.status == 200
                    text = await resp.text()
            line = [
                ln for ln in text.splitlines()
                if ln.startswith('cometbft_consensus_height{')
            ][0]
            assert float(line.split()[-1]) == 7
            # health-plane families registered even before attach
            assert "cometbft_loop_lag_seconds" in text
            assert "cometbft_loop_stalls_total" in text
            # cross-node tracing families (ISSUE 7) likewise
            assert "cometbft_consensus_quorum_latency_seconds" in text
            assert "cometbft_p2p_msg_propagation_seconds" in text
            assert (
                "cometbft_consensus_vote_arrival_skew_seconds" in text
            )
        finally:
            await srv.stop()

    run(main())


@needs_prometheus
def test_prometheus_metrics_endpoint():
    gen, pvs = make_genesis(1, chain_id="metrics-chain")

    async def main():
        cfg = make_test_cfg(".")
        cfg.instrumentation.prometheus = True
        cfg.instrumentation.prometheus_listen_addr = "127.0.0.1:0"
        node = Node(cfg, gen, privval=pvs[0])
        await node.start()
        node.parts.mempool.check_tx(b"m=1")
        while node.height < 3:
            await asyncio.sleep(0.05)
        async with aiohttp.ClientSession() as s:
            async with s.get(
                f"http://{node.metrics_server.listen_addr}/metrics"
            ) as resp:
                text = await resp.text()
        assert 'cometbft_consensus_height{chain_id="metrics-chain"}' in text
        h = [
            ln
            for ln in text.splitlines()
            if ln.startswith("cometbft_consensus_height{")
        ][0]
        assert float(h.split()[-1]) >= 3
        assert "cometbft_mempool_size" in text
        assert "cometbft_p2p_peers" in text
        assert "cometbft_consensus_total_txs" in text
        assert "cometbft_blocksync_pipeline_reused_total" in text
        # self-healing connectivity plane (p2p/reconnect.py)
        assert "cometbft_p2p_reconnect_attempts_total" in text
        assert "cometbft_p2p_peer_flaps_total" in text
        assert "cometbft_p2p_starvation_seconds" in text
        # span→metrics bridge (trace/bridge.py): consensus step spans
        # must have landed in the step-duration histogram by height 3
        step_counts = [
            ln
            for ln in text.splitlines()
            if ln.startswith(
                "cometbft_consensus_step_duration_seconds_count{"
            )
        ]
        assert step_counts and any(
            float(ln.split()[-1]) > 0 for ln in step_counts
        ), step_counts
        assert "cometbft_consensus_wal_fsync_seconds" in text
        assert "cometbft_blocksync_window_blocks_per_s" in text
        # runtime health plane (docs/OBS.md): watchdog lag beats have
        # landed in the histogram by height 3, queue gauges labeled
        lag_counts = [
            ln
            for ln in text.splitlines()
            if ln.startswith("cometbft_loop_lag_seconds_count{")
        ]
        assert lag_counts and any(
            float(ln.split()[-1]) > 0 for ln in lag_counts
        ), lag_counts
        q_depth = [
            ln
            for ln in text.splitlines()
            if ln.startswith("cometbft_queue_depth{")
        ]
        assert any('queue="consensus.inbox"' in ln for ln in q_depth)
        assert any('queue="mempool.ingest"' in ln for ln in q_depth)
        assert "cometbft_queue_high_watermark{" in text
        assert "cometbft_queue_dropped_total{" in text
        # cross-node tracing bridge (ISSUE 7): even a single-node
        # chain observes its own 2/3 quorum (it IS 2/3), so the
        # quorum-latency histogram must carry samples for both steps
        # by height 3, and the vote-skew gauge a peer="self" series
        q_counts = {
            ln
            for ln in text.splitlines()
            if ln.startswith(
                "cometbft_consensus_quorum_latency_seconds_count{"
            )
        }
        for step in ("prevote", "precommit"):
            lns = [ln for ln in q_counts if f'step="{step}"' in ln]
            assert lns and float(lns[0].split()[-1]) > 0, (step, q_counts)
        assert (
            "cometbft_consensus_vote_arrival_skew_seconds{"
            in text
        )
        skew = [
            ln
            for ln in text.splitlines()
            if ln.startswith(
                "cometbft_consensus_vote_arrival_skew_seconds{"
            )
        ]
        assert any('peer="self"' in ln for ln in skew), skew
        # no peers on a 1-node net: the propagation family is
        # registered but empty
        assert "cometbft_p2p_msg_propagation_seconds" in text
        await node.stop()

    run(main())


@needs_prometheus
def test_prometheus_metrics_over_lp2p():
    """Traffic gauges must read Lp2pPeer muxer counters, not mconn
    (regression: /metrics returned 500 with the lp2p switcher)."""
    gen, pvs = make_genesis(2, chain_id="metrics-lp2p")

    async def main():
        nodes = []
        for i, pv in enumerate(pvs):
            cfg = make_test_cfg(".")
            cfg.p2p.laddr = "tcp://127.0.0.1:0"
            cfg.p2p.use_libp2p_equivalent = True
            cfg.instrumentation.prometheus = True
            cfg.instrumentation.prometheus_listen_addr = "127.0.0.1:0"
            nodes.append(Node(cfg, gen, privval=pv))
        for n in nodes:
            await n.start()
        await nodes[0].dial(nodes[1].listen_addr)
        while any(n.height < 2 for n in nodes):
            await asyncio.sleep(0.05)
        async with aiohttp.ClientSession() as s:
            async with s.get(
                f"http://{nodes[0].metrics_server.listen_addr}/metrics"
            ) as resp:
                assert resp.status == 200
                text = await resp.text()
        recv = [
            ln
            for ln in text.splitlines()
            if ln.startswith("cometbft_p2p_message_receive_bytes_total{")
        ]
        assert recv and float(recv[0].split()[-1]) > 0
        # cross-node tracing over the lp2p switcher (ISSUE 7): the
        # stamping plane rides the shared Switch base, so stamped
        # consensus traffic between two same-process nodes lands live
        # propagation samples in the bridge histogram
        prop = [
            ln
            for ln in text.splitlines()
            if ln.startswith(
                "cometbft_p2p_msg_propagation_seconds_count{"
            )
        ]
        assert prop and any(
            float(ln.split()[-1]) > 0 for ln in prop
        ), prop
        for n in nodes:
            await n.stop()

    run(main())
