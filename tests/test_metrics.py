"""Metrics endpoint test: /metrics serves live consensus gauges."""

import asyncio

import aiohttp

from cometbft_tpu.config.config import test_config as make_test_cfg
from cometbft_tpu.node.inprocess import make_genesis
from cometbft_tpu.node.node import Node


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def test_prometheus_metrics_endpoint():
    gen, pvs = make_genesis(1, chain_id="metrics-chain")

    async def main():
        cfg = make_test_cfg(".")
        cfg.instrumentation.prometheus = True
        cfg.instrumentation.prometheus_listen_addr = "127.0.0.1:0"
        node = Node(cfg, gen, privval=pvs[0])
        await node.start()
        node.parts.mempool.check_tx(b"m=1")
        while node.height < 3:
            await asyncio.sleep(0.05)
        async with aiohttp.ClientSession() as s:
            async with s.get(
                f"http://{node.metrics_server.listen_addr}/metrics"
            ) as resp:
                text = await resp.text()
        assert 'cometbft_consensus_height{chain_id="metrics-chain"}' in text
        h = [
            ln
            for ln in text.splitlines()
            if ln.startswith("cometbft_consensus_height{")
        ][0]
        assert float(h.split()[-1]) >= 3
        assert "cometbft_mempool_size" in text
        assert "cometbft_p2p_peers" in text
        assert "cometbft_consensus_total_txs" in text
        assert "cometbft_blocksync_pipeline_reused_total" in text
        await node.stop()

    run(main())


def test_prometheus_metrics_over_lp2p():
    """Traffic gauges must read Lp2pPeer muxer counters, not mconn
    (regression: /metrics returned 500 with the lp2p switcher)."""
    gen, pvs = make_genesis(2, chain_id="metrics-lp2p")

    async def main():
        nodes = []
        for i, pv in enumerate(pvs):
            cfg = make_test_cfg(".")
            cfg.p2p.laddr = "tcp://127.0.0.1:0"
            cfg.p2p.use_libp2p_equivalent = True
            cfg.instrumentation.prometheus = True
            cfg.instrumentation.prometheus_listen_addr = "127.0.0.1:0"
            nodes.append(Node(cfg, gen, privval=pv))
        for n in nodes:
            await n.start()
        await nodes[0].dial(nodes[1].listen_addr)
        while any(n.height < 2 for n in nodes):
            await asyncio.sleep(0.05)
        async with aiohttp.ClientSession() as s:
            async with s.get(
                f"http://{nodes[0].metrics_server.listen_addr}/metrics"
            ) as resp:
                assert resp.status == 200
                text = await resp.text()
        recv = [
            ln
            for ln in text.splitlines()
            if ln.startswith("cometbft_p2p_message_receive_bytes_total{")
        ]
        assert recv and float(recv[0].split()[-1]) > 0
        for n in nodes:
            await n.stop()

    run(main())
