"""utils/backoff.py: the one shared reconnect/retry backoff policy
(exponential + full jitter + cap) used by p2p.Switch._schedule_reconnect
and, via inheritance, the Lp2pSwitch reconnect path."""

import random

import pytest

from cometbft_tpu.utils.backoff import Backoff


def test_ceiling_grows_exponentially_to_cap():
    b = Backoff(base_s=1.0, cap_s=30.0, rng=random.Random(1))
    ceilings = []
    for _ in range(8):
        ceilings.append(b.ceiling())
        b.next_delay()
    assert ceilings == [1.0, 2.0, 4.0, 8.0, 16.0, 30.0, 30.0, 30.0]


def test_full_jitter_bounds_and_determinism():
    a = Backoff(base_s=0.5, cap_s=8.0, rng=random.Random(7))
    b = Backoff(base_s=0.5, cap_s=8.0, rng=random.Random(7))
    da = [a.next_delay() for _ in range(20)]
    db = [b.next_delay() for _ in range(20)]
    assert da == db  # seeded => deterministic schedule
    cap = 0.5
    for d in da:
        assert 0.0 <= d <= min(8.0, cap)
        cap = min(cap * 2, 8.0)


def test_reset_restarts_the_schedule():
    b = Backoff(base_s=1.0, cap_s=30.0, rng=random.Random(3))
    for _ in range(5):
        b.next_delay()
    assert b.ceiling() == 30.0
    b.reset()
    assert b.ceiling() == 1.0


def test_rejects_nonsense_parameters():
    for kw in (
        {"base_s": 0.0},
        {"base_s": 2.0, "cap_s": 1.0},
        {"factor": 0.5},
    ):
        with pytest.raises(ValueError):
            Backoff(**kw)


def test_switch_reconnect_uses_shared_backoff():
    """The reconnect routine must construct the shared Backoff (no
    second hand-rolled schedule); both switch flavors share the
    routine by inheritance."""
    import inspect

    from cometbft_tpu.lp2p.switch import Lp2pSwitch
    from cometbft_tpu.p2p.switch import Switch

    src = inspect.getsource(Switch._schedule_reconnect)
    assert "Backoff(" in src
    assert Lp2pSwitch._schedule_reconnect is Switch._schedule_reconnect
