"""utils/backoff.py: the one shared reconnect/retry backoff policy
(exponential + full jitter + cap) used by the p2p self-healing
reconnect plane (p2p/reconnect.py) and, via inheritance, the
Lp2pSwitch reconnect path."""

import random

import pytest

from cometbft_tpu.utils.backoff import Backoff


def test_ceiling_grows_exponentially_to_cap():
    b = Backoff(base_s=1.0, cap_s=30.0, rng=random.Random(1))
    ceilings = []
    for _ in range(8):
        ceilings.append(b.ceiling())
        b.next_delay()
    assert ceilings == [1.0, 2.0, 4.0, 8.0, 16.0, 30.0, 30.0, 30.0]


def test_full_jitter_bounds_and_determinism():
    a = Backoff(base_s=0.5, cap_s=8.0, rng=random.Random(7))
    b = Backoff(base_s=0.5, cap_s=8.0, rng=random.Random(7))
    da = [a.next_delay() for _ in range(20)]
    db = [b.next_delay() for _ in range(20)]
    assert da == db  # seeded => deterministic schedule
    cap = 0.5
    for d in da:
        assert 0.0 <= d <= min(8.0, cap)
        cap = min(cap * 2, 8.0)


def test_reset_restarts_the_schedule():
    b = Backoff(base_s=1.0, cap_s=30.0, rng=random.Random(3))
    for _ in range(5):
        b.next_delay()
    assert b.ceiling() == 30.0
    b.reset()
    assert b.ceiling() == 1.0


def test_rejects_nonsense_parameters():
    for kw in (
        {"base_s": 0.0},
        {"base_s": 2.0, "cap_s": 1.0},
        {"factor": 0.5},
    ):
        with pytest.raises(ValueError):
            Backoff(**kw)


def test_switch_reconnect_uses_shared_backoff():
    """The reconnect plane must construct the shared Backoff (no
    second hand-rolled schedule); both switch flavors share the plane
    by inheritance (Lp2pSwitch subclasses Switch, which owns a
    ReconnectPlane)."""
    import inspect

    from cometbft_tpu.lp2p.switch import Lp2pSwitch
    from cometbft_tpu.p2p.reconnect import ReconnectPlane
    from cometbft_tpu.p2p.switch import Switch

    src = inspect.getsource(ReconnectPlane._backoff_for)
    assert "Backoff(" in src
    # one plane implementation for both switch flavors
    assert "reconnect" not in vars(Lp2pSwitch), (
        "Lp2pSwitch must inherit the Switch reconnect plane, not "
        "carry its own"
    )
    for name in ("_schedule_reconnect",):
        assert not hasattr(Switch, name), (
            "the old finite-attempts reconnect routine is gone"
        )
