"""Outbound fan-out plane (rpc/fanout.py, ISSUE 15): one-pass
delivery, per-subscriber shed isolation, height-keyed commit waiters,
and the live websocket paths over a single-node chain."""

import asyncio
import hashlib
import json

import pytest

from cometbft_tpu.config.config import test_config as make_test_cfg
from cometbft_tpu.node.inprocess import make_genesis
from cometbft_tpu.node.node import Node
from cometbft_tpu.rpc.client import HTTPClient
from cometbft_tpu.rpc.fanout import CommitWaiterMap, FanoutHub
from cometbft_tpu.types import events as ev
from cometbft_tpu.utils.pubsub_query import parse as parse_query


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


class StubWS:
    """Socket stand-in: infinite-speed sink recording frames."""

    def __init__(self):
        self.frames = []

    async def send_str(self, s):
        self.frames.append(s)


class StuckWS(StubWS):
    """A subscriber whose socket never completes a send."""

    async def send_str(self, s):
        self.frames.append(s)
        await asyncio.Event().wait()


def _bus():
    bus = ev.EventBus()
    bus.set_loop(asyncio.get_running_loop())
    return bus


def _attach(hub, ws, qs, sub_id):
    return hub.attach(ws, qs, parse_query(qs), sub_id)


# --- one-serialization-pass delivery ----------------------------------


def test_one_encode_per_group():
    """N subscribers over G query shapes: each event pays exactly one
    JSON serialization per MATCHING group, never per subscriber."""

    async def main():
        bus = _bus()
        hub = FanoutHub(bus)
        q_round = "tm.event='NewRound'"
        q_step = "tm.event='NewRoundStep'"
        subs_round = [
            _attach(hub, StubWS(), q_round, i) for i in range(40)
        ]
        subs_step = [
            _attach(hub, StubWS(), q_step, 100 + i) for i in range(10)
        ]
        for h in range(3):
            bus.publish_type(ev.EVENT_NEW_ROUND, h, height=h)
        bus.publish_type(ev.EVENT_NEW_ROUND_STEP, 9, height=9)
        await asyncio.sleep(0.2)
        # 3 NewRound events x 1 matching group + 1 step event x 1
        assert hub.encodes == 4, hub.encodes
        for s in subs_round:
            assert len(s.ws.frames) == 3
        for s in subs_step:
            assert len(s.ws.frames) == 1
        # frames carry the right envelope per subscriber, shared body
        b0 = json.loads(subs_round[0].ws.frames[0])
        b7 = json.loads(subs_round[7].ws.frames[0])
        assert b0["id"] == 0 and b7["id"] == 7
        assert b0["result"] == b7["result"]
        assert b0["result"]["query"] == q_round
        assert b0["result"]["events"]["tm.event"] == ["NewRound"]
        assert hub.queue_stats()["dropped"] == 0
        await hub.close()

    run(main())


def test_slow_subscriber_shed_isolation():
    """A stalled socket sheds ITS frames (counted) while every other
    subscriber keeps receiving everything."""

    async def main():
        bus = _bus()
        hub = FanoutHub(bus)
        qs = "tm.event='NewRound'"
        healthy = _attach(hub, StubWS(), qs, 1)
        stuck = _attach(hub, StuckWS(), qs, 2)
        # shrink the stalled subscriber's bound so the overflow is
        # cheap to provoke
        stuck.queue._maxsize = 4
        n_events = 12
        for h in range(n_events):
            bus.publish_type(ev.EVENT_NEW_ROUND, h, height=h)
        await asyncio.sleep(0.3)
        assert len(healthy.ws.frames) == n_events
        # stuck: exactly one frame in-flight forever, a full queue
        # behind it, and every further frame shed AND counted —
        # conservation: delivered + queued + dropped == published
        assert len(stuck.ws.frames) == 1
        assert stuck.queue.dropped >= 1
        assert (
            len(stuck.ws.frames)
            + stuck.queue.qsize()
            + stuck.queue.dropped
            == n_events
        )
        stats = hub.queue_stats()
        assert stats["dropped"] == stuck.queue.dropped
        assert hub.encodes == n_events  # one per event, not per sub
        await hub.close()

    run(main())


def test_detach_awaits_writer_task():
    """detach() must reap the writer: no mid-send task survives the
    subscription (the old fire-and-forget cancel leaked them into
    loop teardown)."""

    async def main():
        bus = _bus()
        hub = FanoutHub(bus)
        sub = _attach(hub, StuckWS(), "tm.event='NewRound'", 1)
        bus.publish_type(ev.EVENT_NEW_ROUND, 1, height=1)
        await asyncio.sleep(0.1)
        task = sub.task
        assert not task.done()  # parked in the stuck send
        await hub.detach(sub)
        assert task.done()
        assert hub.queue_stats()["subscribers"] == 0
        # empty hub tore its bus subscription down too
        assert hub._drain_task is None and hub._sub is None

    run(main())


# --- height-keyed commit waiters --------------------------------------


def test_commit_waiters_resolve_and_one_subscription():
    async def main():
        bus = _bus()
        cw = CommitWaiterMap(bus)
        keys = [hashlib.sha256(bytes([i])).hexdigest() for i in range(8)]
        futs = [cw.register(k) for k in keys]
        # publish cost stays O(1): ZERO subscriptions regardless of
        # in-flight waiter count (the old shape added one per RPC) —
        # the map rides one lossless sync listener instead
        assert len(bus._subs) == 0
        assert len(bus._sync_listeners) == 1
        for i, k in enumerate(keys):
            bus.publish_type(
                ev.EVENT_TX,
                {"height": 5, "index": i, "tx": bytes([i]), "result": None},
                hash=k,
            )
        got = await asyncio.wait_for(asyncio.gather(*futs), 5)
        assert [e.data["index"] for e in got] == list(range(8))
        assert cw.size() == 0 and cw.resolved == 8
        await cw.close()

    run(main())


def test_commit_waiter_survives_publish_burst():
    """A Tx publish burst larger than any bounded subscription queue
    must not lose the event a waiter needs: the sync-listener shape
    is lossless (a bounded-subscription drain shed NEW events at
    SUBSCRIPTION_QUEUE_SIZE, turning a committed tx into a false
    broadcast_tx_commit timeout)."""

    async def main():
        bus = _bus()
        cw = CommitWaiterMap(bus)
        key = hashlib.sha256(b"the-one").hexdigest()
        fut = cw.register(key)
        # burst past any bounded queue, then the waiter's event LAST
        # (the position a subscription queue would have shed)
        for i in range(ev.SUBSCRIPTION_QUEUE_SIZE + 8):
            bus.publish_type(
                ev.EVENT_TX,
                {"height": 1, "index": i, "tx": b"x", "result": None},
                hash=f"{i:064x}",
            )
        bus.publish_type(
            ev.EVENT_TX,
            {"height": 1, "index": 9999, "tx": b"the-one", "result": None},
            hash=key,
        )
        e = await asyncio.wait_for(fut, 5)
        assert e.data["index"] == 9999 and cw.resolved == 1
        await cw.close()
        assert len(bus._sync_listeners) == 0  # close detached it

    run(main())


def test_commit_waiter_timeout_unsubscribe_race():
    """A waiter that timed out and unregistered must not leak an
    entry, and a late event for its hash must not error; two waiters
    on the SAME hash both resolve."""

    async def main():
        bus = _bus()
        cw = CommitWaiterMap(bus)
        key = "ab" * 32
        fut = cw.register(key)
        with pytest.raises(asyncio.TimeoutError):
            await asyncio.wait_for(fut, 0.05)
        cw.unregister(key, fut)
        assert cw.size() == 0
        # the late event finds no waiter: dropped silently
        bus.publish_type(
            ev.EVENT_TX,
            {"height": 1, "index": 0, "tx": b"x", "result": None},
            hash=key,
        )
        await asyncio.sleep(0.05)
        assert cw.resolved == 0
        # duplicate tx hash: BOTH RPCs resolve from one event
        f1, f2 = cw.register(key), cw.register(key)
        bus.publish_type(
            ev.EVENT_TX,
            {"height": 2, "index": 0, "tx": b"x", "result": None},
            hash=key,
        )
        e1, e2 = await asyncio.wait_for(asyncio.gather(f1, f2), 5)
        assert e1.data["height"] == e2.data["height"] == 2
        await cw.close()

    run(main())


# --- live single-node paths -------------------------------------------


async def _single_node():
    gen, pvs = make_genesis(1, chain_id="fanout-chain")
    cfg = make_test_cfg(".")
    node = Node(cfg, gen, privval=pvs[0])
    await node.start()
    while node.height < 2:
        await asyncio.sleep(0.05)
    return node, HTTPClient(node.rpc_server.listen_addr)


def test_ws_subscription_through_hub_and_unsubscribe_all():
    """End-to-end over a real websocket: events flow through the hub
    (one encode per group), unsubscribe_all leaves no member and no
    writer task, and the registry entry reports the plane."""

    async def main():
        node, cli = await _single_node()
        sess = await cli._sess()
        ws = await sess.ws_connect(cli.base_url + "/websocket")
        q = "tm.event='NewBlock'"
        await ws.send_json(
            {"jsonrpc": "2.0", "id": 7, "method": "subscribe",
             "params": {"query": q}}
        )
        first = json.loads((await ws.receive()).data)
        assert "error" not in first
        hub = node.rpc_server.fanout
        assert hub.queue_stats()["subscribers"] == 1
        heights = []
        while len(heights) < 2:
            body = json.loads((await ws.receive()).data)
            res = body.get("result") or {}
            if res.get("query") == q:
                assert body["id"] == 7
                heights.append(
                    int(
                        res["data"]["value"]["block"]["header"]["height"]
                    )
                )
        assert heights[1] == heights[0] + 1
        # health surfaces the plane through the queue registry
        stats = node.queues.get("rpc.fanout")
        assert stats is not None and stats["enqueued"] >= 2
        assert stats["dropped"] == 0
        await ws.send_json(
            {"jsonrpc": "2.0", "id": 8, "method": "unsubscribe_all",
             "params": {}}
        )
        deadline = asyncio.get_running_loop().time() + 5
        while hub.queue_stats()["subscribers"]:
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.02)
        await ws.close()
        await cli.close()
        await node.stop()

    run(main())


def test_concurrent_broadcast_tx_commit_single_subscription():
    """K concurrent commit RPCs ride ONE waiter subscription (plus
    the hub's), and all commit."""

    async def main():
        node, cli = await _single_node()
        bus = node.parts.event_bus
        before = len(bus._subs)
        txs = [b"fk%d=fv%d" % (i, i) for i in range(5)]
        results = await asyncio.gather(
            *[cli.broadcast_tx_commit(t) for t in txs]
        )
        for r in results:
            assert r["tx_result"]["code"] == 0
            assert int(r["height"]) >= 1
        # the waiter map added AT MOST one subscription, total —
        # independent of the 5 concurrent RPCs
        assert len(bus._subs) <= before + 1
        assert node.rpc_env.commit_waiters().size() == 0
        await cli.close()
        await node.stop()

    run(main())

    # second run(): the asyncio.run teardown above is the regression
    # surface for leaked fanout/waiter tasks — a leaked task warns on
    # a closed loop; reaching here clean is the assertion


def test_indexer_queue_registered():
    async def main():
        node, cli = await _single_node()
        # commit one tx so a height flushed through the drain
        await cli.broadcast_tx_commit(b"iq=1")
        stats = node.queues.get("state.index")
        assert stats is not None
        assert stats["flushed_heights"] >= 1
        await cli.close()
        await node.stop()

    run(main())
