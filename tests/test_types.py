"""Types layer: validator sets, vote sets, commits, verification."""

import time
from fractions import Fraction

import pytest

from cometbft_tpu import types as T
from cometbft_tpu.crypto import merkle
from cometbft_tpu.crypto.keys import Ed25519PrivKey
from cometbft_tpu.types import validation

CHAIN = "test-chain"
NOW = int(time.time() * 1e9)


def make_block_id(tag: bytes = b"block") -> T.BlockID:
    import hashlib

    h = hashlib.sha256(tag).digest()
    return T.BlockID(h, T.PartSetHeader(1, hashlib.sha256(tag + b"p").digest()))


def make_commit(vs, privs, height=3, round_=1, block_id=None, nil_frac=0.0):
    block_id = block_id or make_block_id()
    votes = T.VoteSet(CHAIN, height, round_, T.PRECOMMIT, vs)
    n = len(privs)
    for i, priv in enumerate(privs):
        bid = block_id
        if i < int(n * nil_frac):
            bid = T.NIL_BLOCK_ID
        v = T.Vote(
            type_=T.PRECOMMIT,
            height=height,
            round=round_,
            block_id=bid,
            timestamp_ns=NOW + i,
            validator_address=priv.pub_key().address(),
            validator_index=i,
        )
        v.signature = priv.sign(v.sign_bytes(CHAIN))
        votes.add_vote(v)
    return votes.make_commit(), block_id


@pytest.fixture(scope="module")
def valset():
    return T.random_validator_set(7)


def test_proposer_rotation_weighted(valset=None):
    vs, _ = T.random_validator_set(3, power=1)
    # give one validator 3x power; over 5 rounds it proposes 3 times
    vs.validators[0].voting_power = 3
    vs = T.ValidatorSet(vs.validators)
    heavy = vs.validators[0].address
    seen = []
    work = vs.copy()
    for _ in range(5):
        work.increment_proposer_priority(1)
        seen.append(work.get_proposer().address)
    assert seen.count(heavy) == 3


def test_valset_hash_changes_with_update():
    vs, privs = T.random_validator_set(4)
    h1 = vs.hash()
    vs2 = vs.copy()
    vs2.update_with_change_set(
        [T.Validator(privs[0].pub_key(), 555)]
    )
    assert vs2.hash() != h1
    _, v = vs2.get_by_address(privs[0].pub_key().address())
    assert v.voting_power == 555
    # removal
    vs3 = vs2.copy()
    vs3.update_with_change_set([T.Validator(privs[1].pub_key(), 0)])
    assert vs3.size() == 3


def test_vote_set_quorum(valset):
    vs, privs = valset
    bid = make_block_id()
    votes = T.VoteSet(CHAIN, 5, 0, T.PREVOTE, vs)
    for i, priv in enumerate(privs):
        v = T.Vote(
            type_=T.PREVOTE,
            height=5,
            round=0,
            block_id=bid,
            timestamp_ns=NOW,
            validator_address=priv.pub_key().address(),
            validator_index=i,
        )
        v.signature = priv.sign(v.sign_bytes(CHAIN))
        assert votes.add_vote(v)
        has = votes.has_two_thirds_majority()
        assert has == ((i + 1) * 3 > len(privs) * 2)
    assert votes.two_thirds_majority().key() == bid.key()


def test_vote_set_rejects_bad_sig(valset):
    vs, privs = valset
    votes = T.VoteSet(CHAIN, 5, 0, T.PREVOTE, vs)
    v = T.Vote(
        type_=T.PREVOTE,
        height=5,
        round=0,
        block_id=make_block_id(),
        timestamp_ns=NOW,
        validator_address=privs[0].pub_key().address(),
        validator_index=0,
    )
    v.signature = b"\x01" * 64
    with pytest.raises(ValueError):
        votes.add_vote(v)


def test_vote_set_conflicting_votes_evidence(valset):
    vs, privs = valset
    votes = T.VoteSet(CHAIN, 5, 0, T.PREVOTE, vs)
    for tag in (b"a", b"b"):
        v = T.Vote(
            type_=T.PREVOTE,
            height=5,
            round=0,
            block_id=make_block_id(tag),
            timestamp_ns=NOW,
            validator_address=privs[0].pub_key().address(),
            validator_index=0,
        )
        v.signature = privs[0].sign(v.sign_bytes(CHAIN))
        if tag == b"a":
            votes.add_vote(v)
        else:
            with pytest.raises(T.ErrVoteConflictingVotes):
                votes.add_vote(v)


def test_verify_commit_roundtrip(valset):
    vs, privs = valset
    commit, bid = make_commit(vs, privs)
    T.verify_commit(CHAIN, vs, bid, 3, commit)
    T.verify_commit_light(CHAIN, vs, bid, 3, commit)
    T.verify_commit_light_trusting(CHAIN, vs, commit)


def test_verify_commit_with_nil_votes(valset):
    vs, privs = valset
    # 2 of 7 vote nil: still 5/7 > 2/3
    commit, bid = make_commit(vs, privs, nil_frac=0.29)
    T.verify_commit(CHAIN, vs, bid, 3, commit)
    T.verify_commit_light(CHAIN, vs, bid, 3, commit)


def test_verify_commit_insufficient_power(valset):
    vs, privs = valset
    votes = T.VoteSet(CHAIN, 3, 1, T.PRECOMMIT, vs)
    bid = make_block_id()
    # exactly 5 of 7 vote (> 2/3); then strip two sigs to force failure
    for i, priv in enumerate(privs[:5]):
        v = T.Vote(
            type_=T.PRECOMMIT,
            height=3,
            round=1,
            block_id=bid,
            timestamp_ns=NOW,
            validator_address=priv.pub_key().address(),
            validator_index=i,
        )
        v.signature = priv.sign(v.sign_bytes(CHAIN))
        votes.add_vote(v)
    commit = votes.make_commit()
    commit.signatures[0] = T.CommitSig.absent()
    commit.signatures[1] = T.CommitSig.absent()
    with pytest.raises(validation.ErrNotEnoughVotingPower):
        T.verify_commit(CHAIN, vs, bid, 3, commit)


def test_verify_commit_bad_signature(valset):
    vs, privs = valset
    commit, bid = make_commit(vs, privs)
    sigs = list(commit.signatures)
    cs = sigs[2]
    sigs[2] = T.CommitSig(
        cs.block_id_flag,
        cs.validator_address,
        cs.timestamp_ns,
        bytes([cs.signature[0] ^ 1]) + cs.signature[1:],
    )
    bad = T.Commit(commit.height, commit.round, commit.block_id, sigs)
    with pytest.raises(validation.ErrInvalidSignature):
        T.verify_commit(CHAIN, vs, bid, 3, bad)


def test_verify_commit_light_trusting_subset(valset):
    vs, privs = valset
    commit, bid = make_commit(vs, privs)
    # trusted set = 4 of the 7 validators (> 1/3 overlap by power)
    trusted = T.ValidatorSet(vs.validators[:4])
    T.verify_commit_light_trusting(CHAIN, trusted, commit)
    # trust level 1: requires every trusted validator signed
    T.verify_commit_light_trusting(
        CHAIN, trusted, commit, trust_level=Fraction(3, 4)
    )


def test_signature_cache_dedups(valset):
    vs, privs = valset
    commit, bid = make_commit(vs, privs)
    cache = T.SignatureCache()
    T.verify_commit(CHAIN, vs, bid, 3, commit, cache=cache)
    assert len(cache) == 7
    before_hits = cache.hits
    T.verify_commit(CHAIN, vs, bid, 3, commit, cache=cache)
    assert cache.hits >= before_hits + 7


def test_part_set_roundtrip():
    data = bytes(range(256)) * 1000  # 256 KB -> 4 parts
    ps = T.PartSet.from_data(data)
    assert ps.header.total == 4
    ps2 = T.PartSet(ps.header)
    for i in reversed(range(4)):
        assert ps2.add_part(ps.get_part(i))
    assert ps2.is_complete()
    assert ps2.assemble() == data
    # corrupt part fails proof
    p = ps.get_part(0)
    bad = T.Part(0, b"x" + p.bytes_[1:], p.proof)
    ps3 = T.PartSet(ps.header)
    with pytest.raises(ValueError):
        ps3.add_part(bad)


def test_merkle_proofs():
    items = [b"a", b"b", b"c", b"d", b"e"]
    root, proofs = merkle.proofs_from_byte_slices(items)
    assert root == merkle.hash_from_byte_slices(items)
    for i, item in enumerate(items):
        assert proofs[i].verify(root, item)
        assert not proofs[i].verify(root, item + b"!")


def test_header_hash_sensitivity():
    vs, _ = T.random_validator_set(2)
    h = T.Header(
        chain_id=CHAIN,
        height=9,
        time_ns=NOW,
        validators_hash=vs.hash(),
        next_validators_hash=vs.hash(),
        proposer_address=vs.validators[0].address,
    )
    h2 = T.Header(
        chain_id=CHAIN,
        height=10,
        time_ns=NOW,
        validators_hash=vs.hash(),
        next_validators_hash=vs.hash(),
        proposer_address=vs.validators[0].address,
    )
    assert h.hash() != h2.hash()
