"""Runtime concurrency sanitizer (cometbft_tpu/analysis/runtime.py).

Five layers:
  1. lock-order graph: ABBA inversion detected (both stacks carried),
     consistent order stays clean, multi-lock cycles, RLock
     reentrancy, Condition interop (wait releases the bookkeeping);
  2. loop-affinity guard: owner binding, foreign-thread findings,
     sanctioned handoff, adopt-on-first-use;
  3. disabled-mode contract: sanitized_lock returns the RAW lock
     (identity — zero per-acquire overhead by construction) and the
     enabled-mode proxy cost stays a small multiple of a bare
     acquire (scaled baseline a la the PR 4/6 guards);
  4. stall attribution: frames bucket to the owning subsystem;
  5. the chaos pipeline: inject_lock_inversion is deterministic, its
     findings are classified as injected, and a seeded lock_inversion
     schedule through run_schedule detects BOTH guards with the run
     otherwise invariant-clean.
"""
import asyncio
import threading
import time

import pytest

from cometbft_tpu.analysis import runtime as rt
from cometbft_tpu.analysis.runtime import (
    ConcurrencySanitizer,
    SanitizedLock,
    attribute_frames,
)


@pytest.fixture
def san():
    s = ConcurrencySanitizer()
    s.enable()
    return s


def _lock(san, name):
    return SanitizedLock(san, threading.Lock(), name)


# --- 1. lock-order graph -------------------------------------------------


def test_abba_inversion_detected_with_both_stacks(san):
    a, b = _lock(san, "plane.a"), _lock(san, "plane.b")
    with a:
        with b:
            pass
    assert not san.findings  # one order alone is fine
    with b:
        with a:
            pass
    kinds = [f.kind for f in san.findings]
    assert kinds == ["lock-order-cycle"]
    d = san.findings[0].detail
    assert sorted(d["locks"]) == ["plane.a", "plane.b"]
    # BOTH acquisition stacks present and point at this test
    assert any("test_sanitizer" in ln for ln in d["stack_forward"])
    assert any("test_sanitizer" in ln for ln in d["stack_reverse"])


def test_consistent_order_never_reports(san):
    a, b = _lock(san, "x.a"), _lock(san, "x.b")
    for _ in range(50):
        with a:
            with b:
                pass
    assert not san.findings
    assert san.stats()["edges"] == 1


def test_three_lock_cycle_detected(san):
    a, b, c = (_lock(san, n) for n in ("c3.a", "c3.b", "c3.c"))
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    assert not san.findings
    with c:
        with a:
            pass
    assert [f.kind for f in san.findings] == ["lock-order-cycle"]
    assert set(san.findings[0].detail["locks"]) == {
        "c3.a", "c3.b", "c3.c"
    }


def test_cycle_reported_once_per_lock_set(san):
    a, b = _lock(san, "once.a"), _lock(san, "once.b")
    for _ in range(5):
        with a:
            with b:
                pass
        with b:
            with a:
                pass
    assert len(san.findings) == 1


def test_rlock_reentrancy_no_self_edge(san):
    r = SanitizedLock(san, threading.RLock(), "re.lock")
    with r:
        with r:  # reentrant: not an ordering edge
            pass
    assert san.stats()["edges"] == 0 and not san.findings


def test_condition_wait_releases_bookkeeping(san):
    """threading.Condition over a sanitized RLock keeps exact
    semantics AND the held-stack: while wait() has released the lock,
    another thread's acquire must not record a bogus edge."""
    lk = SanitizedLock(san, threading.RLock(), "cond.lock")
    cond = threading.Condition(lk)
    other = _lock(san, "cond.other")
    woke = threading.Event()

    def waiter():
        with cond:
            cond.wait(timeout=5.0)
            woke.set()

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.1)
    # the waiter thread's held-stack must be empty mid-wait: taking
    # another lock here (on this thread) is unrelated
    with other:
        pass
    with cond:
        cond.notify_all()
    t.join(5.0)
    assert woke.is_set()
    assert not san.findings


# --- 2. loop-affinity guard ----------------------------------------------


def test_affinity_owner_thread_is_clean(san):
    san.tag("aff.obj")
    for _ in range(3):
        san.touch("aff.obj")
    assert not san.findings


def test_affinity_foreign_thread_flagged_once(san):
    san.tag("aff.hot")

    def foreign():
        san.touch("aff.hot")
        san.touch("aff.hot")  # deduped per (object, thread)

    t = threading.Thread(target=foreign, name="foreign-t")
    t.start()
    t.join(5.0)
    assert [f.kind for f in san.findings] == ["loop-affinity"]
    d = san.findings[0].detail
    assert d["object"] == "aff.hot" and d["thread"] == "foreign-t"
    assert any("test_sanitizer" in ln for ln in d["stack"])


def test_affinity_handoff_is_sanctioned(san):
    san.tag("aff.pool")

    def worker():
        with san.handoff("aff.pool"):
            san.touch("aff.pool")

    t = threading.Thread(target=worker)
    t.start()
    t.join(5.0)
    assert not san.findings
    # without the handoff the same touch DOES report
    t2 = threading.Thread(target=lambda: san.touch("aff.pool"))
    t2.start()
    t2.join(5.0)
    assert [f.kind for f in san.findings] == ["loop-affinity"]


def test_touch_adopt_binds_first_caller(san):
    san.touch_adopt("adopt.obj")  # first use adopts
    san.touch_adopt("adopt.obj")
    assert not san.findings
    t = threading.Thread(target=lambda: san.touch_adopt("adopt.obj"))
    t.start()
    t.join(5.0)
    assert [f.kind for f in san.findings] == ["loop-affinity"]


def test_untagged_touch_is_noop(san):
    san.touch("never.tagged")
    assert not san.findings


# --- 3. disabled-mode / overhead contract --------------------------------


def test_disabled_sanitized_lock_returns_raw_lock():
    """Disabled mode is free BY CONSTRUCTION: the raw lock comes back
    unchanged (identity), so hot-plane acquires cost exactly what
    they did before the sanitizer existed."""
    was = rt.get_sanitizer().enabled
    rt.disable()
    try:
        raw = threading.Lock()
        assert rt.sanitized_lock(raw, "free.lock") is raw
        rraw = threading.RLock()
        assert rt.sanitized_lock(rraw, "free.rlock") is rraw
    finally:
        if was:
            rt.enable()


def test_disabled_touch_is_attribute_check(san):
    san.disable()
    san.tag("cheap.obj")  # tag ignores enablement; touch must no-op

    def foreign():
        san.touch("cheap.obj")

    t = threading.Thread(target=foreign)
    t.start()
    t.join(5.0)
    assert not san.findings


def test_enabled_acquire_overhead_bounded(san):
    """Enabled-mode proxy acquire/release vs a bare lock: the steady
    state (edges already known, nothing else held) must stay a small
    multiple. Scaled baseline — an absolute ns bound flakes under
    full-suite contention on this throttled box."""
    import gc

    raw = threading.Lock()
    wrapped = _lock(san, "ov.lock")
    N = 20_000

    def per_call(fn):
        best = None
        for _ in range(5):
            t0 = time.perf_counter_ns()
            for _ in range(N):
                fn()
            dt = (time.perf_counter_ns() - t0) / N
            best = dt if best is None else min(best, dt)
        return best

    def raw_cycle():
        raw.acquire()
        raw.release()

    def wrapped_cycle():
        wrapped.acquire()
        wrapped.release()

    gc.disable()
    try:
        base = per_call(raw_cycle)
        got = per_call(wrapped_cycle)
    finally:
        gc.enable()
    # ~3 extra python calls + a tls read per cycle; 25x scaled +
    # 20us absolute backstop keeps the guard honest but unflaky
    assert got < base * 25 + 20_000, (base, got)


# --- 4. stall attribution ------------------------------------------------


def test_attribute_frames_buckets_by_plane():
    assert attribute_frames(
        ["consensus/wal.py:254 write", "asyncio/events.py:80 _run"]
    ) == "consensus"
    assert attribute_frames(
        ["chaos/nemesis.py:38 chaos_stall"]
    ) == "chaos"
    assert attribute_frames(
        ["asyncio/events.py:80 _run", "p2p/switch.py:100 receive"]
    ) == "p2p"
    assert attribute_frames(["somewhere/else.py:1 f"]) == "unknown"
    assert attribute_frames([]) == "unknown"


def test_flight_record_carries_subsystem():
    """The watchdog's flight record names the guilty subsystem (the
    chaos_stall frame lives in chaos/nemesis.py)."""
    from cometbft_tpu.obs import LoopWatchdog

    wd = LoopWatchdog(interval_s=0.02, stall_s=0.1, name="attr")

    async def main():
        wd.start()
        await asyncio.sleep(0.1)
        from cometbft_tpu.chaos.nemesis import chaos_stall

        chaos_stall(0.4)  # block the loop; monitor fires mid-stall
        await asyncio.sleep(0.2)
        wd.stop()
        return list(wd.stalls)

    stalls = asyncio.run(asyncio.wait_for(main(), 60))
    assert stalls, "stall not captured"
    assert stalls[0]["subsystem"] == "chaos", stalls[0]


# --- 5. chaos pipeline ---------------------------------------------------


def test_inject_lock_inversion_deterministic():
    g = rt.get_sanitizer()
    was = g.enabled
    g.enable()
    snap_before = g.snapshot()
    try:
        g.reset()
        rec = rt.inject_lock_inversion()
        assert rec["enabled"]
        assert rec["observed"] == ["lock-order-cycle", "loop-affinity"]
        finds = g.snapshot()
        assert {f["kind"] for f in finds} == {
            "lock-order-cycle", "loop-affinity"
        }
        # every injected finding is classified as injected (chaos
        # treats them as EXPECTED, everything else as a violation)
        assert all(rt.injected_finding(f) for f in finds)
        # and a genuine finding is NOT classified as injected
        assert not rt.injected_finding(
            {"detail": {"locks": ["wal.append", "mempool.pool"]}}
        )
    finally:
        g.reset()
        if not was:
            g.disable()


def test_chaos_lock_inversion_schedule_detects(tmp_path):
    """The acceptance shape: a seeded schedule carrying lock_inversion
    runs a real 4-node net, the sanitizer reports BOTH injected
    findings, they are expected (run stays OK), and they ride the
    report."""
    from cometbft_tpu.chaos import FaultEvent, FaultSchedule, run_schedule

    sched = FaultSchedule(
        [FaultEvent(action="lock_inversion", at_height=2)]
    )
    report = asyncio.run(
        asyncio.wait_for(
            run_schedule(
                sched,
                seed=1337,
                base_dir=str(tmp_path),
                n_nodes=4,
                liveness_bound_s=60.0,
            ),
            240,
        )
    )
    assert report.ok, report.violations
    kinds = {f["kind"] for f in report.sanitizer_findings}
    assert {"lock-order-cycle", "loop-affinity"} <= kinds
    # the nemesis trace records what the injection observed — part of
    # the seed-line replay contract
    ev = report.trace[0]
    assert ev["action"] == "lock_inversion"
    assert ev["observed"] == ["lock-order-cycle", "loop-affinity"]


def test_chaos_missed_detection_is_violation(tmp_path):
    """A sanitizer that cannot flag its own injection proves nothing:
    with the sanitizer force-disabled, a scheduled lock_inversion
    must FAIL the run."""
    from cometbft_tpu.chaos import FaultEvent, FaultSchedule, run_schedule

    g = rt.get_sanitizer()

    sched = FaultSchedule(
        [FaultEvent(action="lock_inversion", at_height=2)]
    )

    def no_sanitizer(cfg):
        # keep build_node from re-enabling the process-wide sanitizer
        cfg.instrumentation.sanitizer = False

    async def main():
        g.disable()
        return await run_schedule(
            sched,
            seed=1338,
            base_dir=str(tmp_path),
            n_nodes=4,
            liveness_bound_s=60.0,
            config_hook=no_sanitizer,
        )

    try:
        report = asyncio.run(asyncio.wait_for(main(), 240))
    finally:
        g.enable()
    assert not report.ok
    assert any("lock_inversion injected" in v for v in report.violations)
