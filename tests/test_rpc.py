"""RPC server + client tests over a live single-node chain
(reference analog: rpc/client/rpc_test.go)."""

import asyncio
import base64
import hashlib

import pytest

from cometbft_tpu.config.config import test_config as make_test_cfg
from cometbft_tpu.node.inprocess import make_genesis
from cometbft_tpu.node.node import Node
from cometbft_tpu.rpc.client import HTTPClient, RPCClientError


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


async def _single_node():
    gen, pvs = make_genesis(1, chain_id="rpc-chain")
    cfg = make_test_cfg(".")
    node = Node(cfg, gen, privval=pvs[0])
    await node.start()
    while node.height < 2:
        await asyncio.sleep(0.05)
    return node, HTTPClient(node.rpc_server.listen_addr)


def test_status_block_commit_validators():
    async def main():
        node, cli = await _single_node()
        st = await cli.status()
        assert st["node_info"]["network"] == "rpc-chain"
        assert int(st["sync_info"]["latest_block_height"]) >= 2

        blk = await cli.block_decoded(1)
        assert blk.height == 1
        assert bytes(blk.hash()) == bytes(
            node.parts.block_store.load_block(1).hash()
        )
        hdr, cm = await cli.commit_decoded(1)
        assert cm.height == 1 and hdr.height == 1
        vs = await cli.validators_decoded(1)
        assert vs.size() == 1
        assert (
            bytes(vs.hash())
            == bytes(node.parts.state_store.load_validators(1).hash())
        )
        # error path: future height
        with pytest.raises(RPCClientError):
            await cli.block(10_000)
        await cli.close()
        await node.stop()

    run(main())


def test_broadcast_tx_commit_and_tx_query():
    async def main():
        node, cli = await _single_node()
        tx = b"rpckey=rpcval"
        res = await cli.broadcast_tx_commit(tx)
        assert res["check_tx"]["code"] == 0
        assert res["tx_result"]["code"] == 0
        height = int(res["height"])
        assert height >= 1
        # tx route finds it by hash
        txr = await cli.call("tx", hash=hashlib.sha256(tx).hexdigest())
        assert int(txr["height"]) == height
        assert base64.b64decode(txr["tx"]) == tx
        # tx_search by height — with prove=true each hit carries a
        # verifiable inclusion proof against the block's data hash
        sr = await cli.call(
            "tx_search", query=f"tx.height={height}", prove=True
        )
        assert int(sr["total_count"]) >= 1
        hit = next(
            t for t in sr["txs"] if base64.b64decode(t["tx"]) == tx
        )
        from cometbft_tpu.crypto import merkle
        from cometbft_tpu.types.block import tx_hash

        proof = merkle.decode_proof(
            base64.b64decode(hit["proof"]["proof_b64"])
        )
        root = bytes.fromhex(hit["proof"]["root_hash"])
        assert proof.verify(root, tx_hash(tx))
        # abci_query sees the committed kv pair
        q = await cli.abci_query("/store", b"rpckey")
        assert base64.b64decode(q["response"]["value"] or "") == b"rpcval"
        await cli.close()
        await node.stop()

    run(main())


def test_ws_subscription_new_block():
    async def main():
        node, cli = await _single_node()
        events = await cli.subscribe("tm.event='NewBlock'")
        got = []
        async for e in events:
            got.append(e)
            if len(got) >= 2:
                break
        assert all(
            e["data"]["type"] == "tendermint/event/NewBlock" for e in got
        )
        heights = [
            int(e["data"]["value"]["block"]["header"]["height"])
            for e in got
        ]
        assert heights[1] == heights[0] + 1
        await cli.close()
        await node.stop()

    run(main())


def test_serving_role_and_fleet_status():
    """ISSUE 19 satellites: status/health carry serving_role +
    replica_lag_heights, and /fleet_status answers honestly on a node
    that fronts no fleet."""

    async def main():
        node, cli = await _single_node()
        st = await cli.status()
        # a privval-carrying node is a validator; its own head IS its
        # committee view, so replica lag is zero
        assert st["serving_role"] == "validator"
        assert st["replica_lag_heights"] == "0"
        h = await cli.call("health")
        assert h["serving_role"] == "validator"
        assert h["replica_lag_heights"] == 0
        assert "fleet" not in h  # no router attached
        # fleet_status on a routerless node: a clean JSON-RPC error,
        # not a 404 and not a fabricated empty fleet
        with pytest.raises(RPCClientError, match="serving fleet"):
            await cli.call("fleet_status")
        await cli.close()
        await node.stop()

    run(main())


def test_misc_routes():
    async def main():
        node, cli = await _single_node()
        # health is the obs plane's verdict now (docs/OBS.md): a
        # freshly committing single node must read ok with live lag
        # + queue telemetry attached
        h = await cli.call("health")
        assert h["status"] in ("ok", "degraded")
        assert "loop_lag_ms" in h and "p95_ms" in h["loop_lag_ms"]
        assert "queue_high_watermarks" in h
        assert int(h["latest_block_height"]) >= 1
        # ISSUE 7: per-phase attribution of the last committed height
        # so a degraded verdict can cite the dominant phase
        bd = h["last_height_commit_breakdown"]
        assert bd["height"] >= 1
        assert bd["dominant"] in bd["phases"]
        assert {"persist_ms", "wal_ms", "apply_ms", "total_ms"} <= set(
            bd["phases"]
        )
        assert all(v >= 0 for v in bd["phases"].values())
        dt = await cli.call("dump_tasks")
        assert int(dt["n_tasks"]) >= 1
        assert any(
            "consensus" in t["name"] or "receive" in t["name"]
            or t["stack"]
            for t in dt["tasks"]
        )
        gen = await cli.call("genesis")
        assert gen["genesis"]["chain_id"] == "rpc-chain"
        ni = await cli.call("net_info")
        assert ni["n_peers"] == "0"
        bc = await cli.call("blockchain", minHeight="1", maxHeight="2")
        assert len(bc["block_metas"]) == 2
        cp = await cli.call("consensus_params")
        assert int(cp["consensus_params"]["block"]["max_bytes"]) > 0
        cs = await cli.call("consensus_state")
        assert int(cs["round_state"]["height"]) >= 1
        ab = await cli.call("abci_info")
        assert int(ab["response"]["last_block_height"]) >= 1
        ut = await cli.call("num_unconfirmed_txs")
        assert "n_txs" in ut
        with pytest.raises(RPCClientError):
            await cli.call("nonexistent_route")
        await cli.close()
        await node.stop()

    run(main())


def test_unsafe_routes_gated_and_functional():
    """dial_peers/unsafe_flush_mempool exist only with rpc.unsafe
    (reference --rpc.unsafe AddUnsafeRoutes)."""

    async def main():
        node, cli = await _single_node()
        # default: unsafe routes hidden
        with pytest.raises(RPCClientError, match="not found"):
            await cli.call("unsafe_flush_mempool")
        # flip the gate (config object is live)
        node.config.rpc.unsafe = True
        await cli.call("broadcast_tx_sync", tx="0x" + b"u=1".hex())
        n0 = int(
            (await cli.call("num_unconfirmed_txs")).get("total", "0")
        )
        await cli.call("unsafe_flush_mempool")
        n1 = int(
            (await cli.call("num_unconfirmed_txs")).get("total", "0")
        )
        assert n1 == 0 <= n0
        res = await cli.call("unsafe_disconnect_peers")
        assert "disconnected" in res["log"]
        res = await cli.call("dial_peers", peers=[])
        assert "dialing" in res["log"]
        await cli.close()
        await node.stop()

    run(main())
