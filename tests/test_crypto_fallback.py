"""Dependency-gated crypto fallbacks (crypto/chacha20poly1305.py,
crypto/x25519.py): RFC vectors, construction cross-checks against the
vector-tested HChaCha20 core, and a differential pass against the
OpenSSL backend wherever `cryptography` is installed. These modules
are what keep the whole p2p/secret-connection stack alive in
containers without OpenSSL bindings."""

import struct

import pytest

from cometbft_tpu.crypto import chacha20poly1305 as ccp
from cometbft_tpu.crypto import x25519
from cometbft_tpu.crypto.xchacha20poly1305 import hchacha20


# --- poly1305 (RFC 8439 2.5.2) ------------------------------------------


def test_poly1305_rfc_vector():
    key = bytes.fromhex(
        "85d6be7857556d337f4452fe42d506a8"
        "0103808afb0db2fd4abff6af4149f51b"
    )
    tag = ccp.poly1305(key, b"Cryptographic Forum Research Group")
    assert tag == bytes.fromhex("a8061dc1305136c6c22b8baf0c0127a9")


# --- chacha20 core vs the vector-tested HChaCha20 -----------------------


def test_chacha20_core_matches_hchacha20():
    """hchacha20(key, n16) equals words (0..3, 12..15) of the raw
    permutation when n16 supplies (counter, nonce). This pins the
    constants, round structure, word order and serialization of the
    keystream core against the HChaCha20 implementation that has its
    own differential vectors (tests/test_crypto_aux.py)."""
    for key, n16 in [
        (bytes(range(32)), bytes(range(100, 116))),
        (b"\x00" * 32, b"\x00" * 16),
        (b"\xff" * 32, b"\x07" * 16),
    ]:
        counter = struct.unpack("<I", n16[:4])[0]
        nonce12 = n16[4:]
        ks = ccp.chacha20_keystream(key, nonce12, counter, 64)
        words = struct.unpack("<16I", ks)
        init = (
            list(struct.unpack("<4I", b"expand 32-byte k"))
            + list(struct.unpack("<8I", key))
            + [counter]
            + list(struct.unpack("<3I", nonce12))
        )
        perm = [(w - i) & 0xFFFFFFFF for w, i in zip(words, init)]
        got = struct.pack(
            "<8I", *(perm[i] for i in (0, 1, 2, 3, 12, 13, 14, 15))
        )
        assert got == hchacha20(key, n16)


def test_chacha20_keystream_block_boundaries():
    key, nonce = bytes(range(32)), bytes(12)
    full = ccp.chacha20_keystream(key, nonce, 0, 256)
    # counter addressing: suffix streams line up on block boundaries
    assert ccp.chacha20_keystream(key, nonce, 1, 192) == full[64:]
    assert ccp.chacha20_keystream(key, nonce, 3, 64) == full[192:]
    # partial lengths truncate, not re-derive
    assert ccp.chacha20_keystream(key, nonce, 0, 100) == full[:100]
    assert ccp.chacha20_keystream(key, nonce, 0, 0) == b""


# --- AEAD construction --------------------------------------------------


def test_aead_roundtrip_tamper_and_nonce_mismatch():
    key = bytes(range(32))
    a = ccp.PureChaCha20Poly1305(key)
    nonce = bytes.fromhex("000000000001020304050607")
    for pt, aad in [
        (b"", b""),
        (b"x", None),
        (b"hello world" * 95, b"header"),
        (b"\x00" * 1024, b""),
    ]:
        ct = a.encrypt(nonce, pt, aad)
        assert len(ct) == len(pt) + 16
        assert ccp.PureChaCha20Poly1305(key).decrypt(nonce, ct, aad) == pt
        with pytest.raises(ccp.InvalidTag):
            ccp.PureChaCha20Poly1305(key).decrypt(
                nonce, ct[:-1] + bytes([ct[-1] ^ 1]), aad
            )
        with pytest.raises(ccp.InvalidTag):
            ccp.PureChaCha20Poly1305(key).decrypt(bytes(12), ct, aad)


def test_aead_rejects_wrong_nonce_and_key_lengths():
    """The pure tier must match the OpenSSL backends' input
    validation — a short nonce must never be silently zero-extended
    by the keystream cache."""
    a = ccp.PureChaCha20Poly1305(bytes(32))
    for nonce in (b"", b"n" * 8, b"n" * 24):
        with pytest.raises(ValueError):
            a.encrypt(nonce, b"data", None)
        with pytest.raises(ValueError):
            a.decrypt(nonce, b"x" * 20, None)
    with pytest.raises(ValueError):
        ccp.PureChaCha20Poly1305(b"short")


def test_aead_sequential_cache_equals_random_access():
    """The sequential-nonce precompute cache must be invisible: a
    receiver decrypting the same nonces out of order and with fresh
    objects sees identical bytes."""
    key = b"\x42" * 32
    sender = ccp.PureChaCha20Poly1305(key)
    frames = {}
    for i in range(70):
        nonce = i.to_bytes(12, "little")
        pt = bytes([i]) * (1024 if i % 2 else 33)
        frames[nonce] = (pt, sender.encrypt(nonce, pt, None))
    # out-of-order, fresh object: no sequential pattern at all
    fresh = ccp.PureChaCha20Poly1305(key)
    for nonce in sorted(frames, reverse=True):
        pt, ct = frames[nonce]
        assert fresh.decrypt(nonce, ct, None) == pt


@pytest.mark.skipif(
    not ccp.HAVE_OPENSSL, reason="differential needs OpenSSL backend"
)
def test_aead_differential_vs_openssl():
    """Where OpenSSL exists, the pure construction must produce
    byte-identical ciphertexts (keystream cache path included)."""
    import random

    from cryptography.hazmat.primitives.ciphers.aead import (
        ChaCha20Poly1305 as Ossl,
    )

    rng = random.Random(5)
    key = bytes(rng.randrange(256) for _ in range(32))
    pure = ccp.PureChaCha20Poly1305(key)
    for i in range(50):
        nonce = i.to_bytes(12, "little")
        pt = bytes(
            rng.randrange(256) for _ in range(rng.randrange(0, 1500))
        )
        aad = bytes(rng.randrange(256) for _ in range(8))
        assert pure.encrypt(nonce, pt, aad) == Ossl(key).encrypt(
            nonce, pt, aad
        )


def test_ctypes_libcrypto_differential_vs_pure():
    """Where a system libcrypto exists (the middle gate tier,
    crypto/_ossl.py), its ed25519/x25519/AEAD must agree byte-for-byte
    with the pure implementations."""
    from cometbft_tpu.crypto import _ossl

    if not _ossl.available():
        pytest.skip("no system libcrypto")
    import random

    from cometbft_tpu.crypto import ref_ed25519 as ref

    rng = random.Random(11)
    for _ in range(3):
        seed = bytes(rng.randrange(256) for _ in range(32))
        msg = bytes(rng.randrange(256) for _ in range(rng.randrange(200)))
        assert _ossl.ed25519_public(seed) == ref.public_from_seed(seed)
        sig = _ossl.ed25519_sign(seed, msg)
        assert sig == ref.sign(seed, msg)
        pub = ref.public_from_seed(seed)
        assert _ossl.ed25519_verify(pub, msg, sig)
        assert not _ossl.ed25519_verify(pub, msg + b"x", sig)

    priv = bytes(rng.randrange(256) for _ in range(32))
    assert _ossl.x25519_public(priv) == x25519.scalar_mult(
        priv, (9).to_bytes(32, "little")
    )
    peer = _ossl.x25519_public(bytes(rng.randrange(256) for _ in range(32)))
    assert _ossl.x25519_shared(priv, peer) == x25519.scalar_mult(
        priv, peer
    )

    key = bytes(rng.randrange(256) for _ in range(32))
    o = _ossl.OsslChaCha20Poly1305(key)
    p = ccp.PureChaCha20Poly1305(key)
    for i in range(20):
        nonce = i.to_bytes(12, "little")
        pt = bytes(rng.randrange(256) for _ in range(rng.randrange(1400)))
        aad = bytes(rng.randrange(256) for _ in range(rng.randrange(24)))
        ct = o.encrypt(nonce, pt, aad)
        assert ct == p.encrypt(nonce, pt, aad)
        assert p.decrypt(nonce, ct, aad) == pt
        assert o.decrypt(nonce, ct, aad) == pt
    with pytest.raises(ccp.InvalidTag):
        o.decrypt(bytes(12), b"\x00" * 32, None)


# --- x25519 (RFC 7748) --------------------------------------------------


def test_x25519_rfc_vectors():
    k = bytes.fromhex(
        "a546e36bf0527c9d3b16154b82465edd"
        "62144c0ac1fc5a18506a2244ba449ac4"
    )
    u = bytes.fromhex(
        "e6db6867583030db3594c1a424b15f7c"
        "726624ec26b3353b10a903a6d0ab1c4c"
    )
    assert x25519.scalar_mult(k, u) == bytes.fromhex(
        "c3da55379de9c6908e94ea4df28d084f"
        "32eccf03491c71f754b4075577a28552"
    )
    # RFC 7748 6.1: Alice/Bob key exchange
    apriv = bytes.fromhex(
        "77076d0a7318a57d3c16c17251b26645"
        "df4c2f87ebc0992ab177fba51db92c2a"
    )
    bpriv = bytes.fromhex(
        "5dab087e624a8a4b79e17f8b83800ee6"
        "6f3bb1292618b6fd1c2f8b27ff88e0eb"
    )
    apub = bytes.fromhex(
        "8520f0098930a754748b7ddcb43ef75a"
        "0dbf3a0d26381af4eba4a98eaa9b4e6a"
    )
    bpub = bytes.fromhex(
        "de9edb7d7b7dc1b4d35b61c2ece43537"
        "3f8343c85b78674dadfc7e146f882b4f"
    )
    shared = bytes.fromhex(
        "4a5d9d5ba4ce2de1728e3bf480350f25"
        "e07e21c947d19e3376f09b3c1e161742"
    )
    assert x25519.public(apriv) == apub
    assert x25519.public(bpriv) == bpub
    assert x25519.shared(apriv, bpub) == shared
    assert x25519.shared(bpriv, apub) == shared


def test_x25519_dh_agreement_random_keys():
    for _ in range(3):
        a = x25519.generate_private()
        b = x25519.generate_private()
        assert x25519.shared(a, x25519.public(b)) == x25519.shared(
            b, x25519.public(a)
        )


def test_x25519_rejects_bad_lengths():
    with pytest.raises(ValueError):
        x25519.scalar_mult(b"short", bytes(32))
    with pytest.raises(ValueError):
        x25519.scalar_mult(bytes(32), b"short")


def test_secret_connection_end_to_end_over_fallback():
    """The consumer-level proof: a full secret-connection handshake +
    framed AEAD traffic over whatever backend this container has."""
    import asyncio
    import socket

    from cometbft_tpu.crypto.keys import Ed25519PrivKey
    from cometbft_tpu.p2p.conn.secret_connection import SecretConnection

    async def main():
        a, b = socket.socketpair()
        a.setblocking(False)
        b.setblocking(False)
        ra, wa = await asyncio.open_connection(sock=a)
        rb, wb = await asyncio.open_connection(sock=b)
        ka, kb = Ed25519PrivKey.generate(), Ed25519PrivKey.generate()
        ca, cb = await asyncio.gather(
            SecretConnection.handshake(ra, wa, ka),
            SecretConnection.handshake(rb, wb, kb),
        )
        assert ca.remote_pubkey.key_bytes == kb.pub_key().key_bytes
        assert cb.remote_pubkey.key_bytes == ka.pub_key().key_bytes
        payload = b"chaos" * 300
        await ca.write_msg(payload)
        got = b""
        while len(got) < len(payload):
            got += await cb.read_chunk()
        assert got == payload
        ca.close()
        cb.close()

    asyncio.run(asyncio.wait_for(main(), 30))
