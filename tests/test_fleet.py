"""Serving fleet (ISSUE 19, docs/FLEET.md): follower replicas,
session router, consistency tokens, lag-aware shedding, and
read-your-writes failover with lossless height-keyed resume.

Covers the contract end to end on in-process fleets:

- follower tail + ReplicaFanout frames are byte-identical to the
  validator-side FanoutHub envelope (what makes replay splices exact);
- least-loaded placement, bounded admission (counted sheds);
- consistency tokens route AWAY from a lagging replica, WAIT the
  height barrier when nobody satisfies them yet, and refuse
  (StaleReadError) rather than serve stale;
- a lagging replica degrades only ITS clients (lag-shed isolation)
  and rotates back in after catching up;
- replica death mid-stream: every stranded session resumes elsewhere
  with zero lost commits (store replay + live splice), and a router
  WITHOUT a store source sheds honestly instead of resuming lossily;
- LightServingPlane.drain is bounded and reversible (satellite);
- two followers sharing one VerifiedHeaderCache verify single-flight
  process-wide and the poison refusal is unchanged (satellite).
"""

import asyncio
import json
import threading
import time

import pytest

import cometbft_tpu.types as T
from cometbft_tpu.fleet import (
    FleetOverloadError,
    FollowerNode,
    NodeReplica,
    ReplicaFanout,
    RoutedSession,
    SessionRouter,
    StaleReadError,
    StoreSource,
    StreamSource,
    height_events,
)
from cometbft_tpu.fleet.follower import event_payload
from cometbft_tpu.fleet.router import _HEIGHT_RE
from cometbft_tpu.light.serving import (
    CachePoisonError,
    LightServingPlane,
    ServingOverloadError,
    VerifiedHeaderCache,
)
from cometbft_tpu.node.inprocess import make_genesis
from cometbft_tpu.utils.chaingen import make_chain
from cometbft_tpu.utils.pubsub_query import parse as parse_query

Q_BLOCK = "tm.event='NewBlock'"
Q_TX = "tm.event='Tx'"


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


class StubWS:
    def __init__(self):
        self.frames = []

    async def send_str(self, s):
        self.frames.append(s)

    def heights(self):
        return [
            int(_HEIGHT_RE.search(f).group(1)) for f in self.frames
        ]


class FailingWS(StubWS):
    async def send_str(self, s):
        raise RuntimeError("socket died")


def make_block(h, prev_bid, chain_id="fleet-chain", txs=1):
    data = T.Data(
        txs=[b"fleet/%d_%d=v" % (h, i) for i in range(txs)]
    )
    last_commit = T.Commit(h - 1, 0, prev_bid, []) if h > 1 else None
    header = T.Header(
        chain_id=chain_id,
        height=h,
        time_ns=h * 1_000_000_000,
        last_block_id=prev_bid,
        app_hash=b"\x03" * 32,
        data_hash=data.hash(),
        last_commit_hash=last_commit.hash() if last_commit else b"",
    )
    return T.Block(header=header, data=data, last_commit=last_commit)


def make_blocks(n, txs=1):
    out = []
    prev = T.BlockID()
    for h in range(1, n + 1):
        blk = make_block(h, prev, txs=txs)
        prev = T.BlockID(blk.hash(), T.PartSetHeader(1, blk.hash()))
        out.append(blk)
    return out


async def wait_until(pred, timeout=10.0, poll=0.01, what="condition"):
    deadline = asyncio.get_running_loop().time() + timeout
    while not pred():
        if asyncio.get_running_loop().time() > deadline:
            raise AssertionError(f"timed out waiting for {what}")
        await asyncio.sleep(poll)


async def _fleet(n=2, **router_kw):
    source = StreamSource()
    replicas = [
        FollowerNode(f"r{i}", source, poll_s=0.01) for i in range(n)
    ]
    router_kw.setdefault("lag_poll_s", 0.02)
    router = SessionRouter(
        replicas, store_source=source, **router_kw
    )
    for r in replicas:
        await r.start()
    await router.start()
    return source, replicas, router


async def _teardown(router, replicas):
    await router.close()
    for r in replicas:
        await r.stop()


def _replica_of(router, sess):
    return router._sessions.get(sess)


# --- follower tail + frame parity -------------------------------------


def test_follower_frames_match_hub_envelope():
    """Routed frames are byte-identical to what a FanoutHub would
    send: same prefix, same payload key order — the property the
    failover replay splice depends on."""

    async def main():
        source, replicas, router = await _fleet(1)
        ws = StubWS()
        sess = await router.subscribe(ws, Q_BLOCK, sub_id=7)
        blocks = make_blocks(3)
        for b in blocks:
            source.advance(b)
        await wait_until(lambda: len(ws.frames) == 3, what="frames")
        prefix = '{"jsonrpc": "2.0", "id": 7, "result": '
        for blk, frame in zip(blocks, ws.frames):
            e = height_events(blk)[0]
            assert frame == prefix + event_payload(e, Q_BLOCK) + "}"
        assert sess.last_delivered == 3
        assert replicas[0].served_height() == 3
        assert replicas[0].lag_heights() == 0
        await _teardown(router, replicas)

    run(main())


def test_store_source_tail_from_genesis():
    """A follower over a real block store (the blocksync stand-in)
    replays the whole chain when pinned to from_height=0."""
    gen, pvs = make_genesis(2, chain_id="fleet-store")
    node = make_chain(gen, [pv.priv_key for pv in pvs], 6)
    try:

        async def main():
            source = StoreSource(node.block_store)
            assert source.height() == 6
            follower = FollowerNode("r0", source, poll_s=0.01)
            router = SessionRouter([follower], store_source=source)
            await follower.start(from_height=0)
            await router.start()
            ws = StubWS()
            await router.subscribe(ws, Q_BLOCK)
            await wait_until(
                lambda: len(ws.frames) == 6, what="store tail"
            )
            assert ws.heights() == list(range(1, 7))
            await _teardown(router, [follower])

        run(main())
    finally:
        node.close_stores()


def test_mid_height_attach_is_a_clean_boundary():
    """A member attached while a height is being delivered receives
    NOTHING for that height — its first live height is a clean
    boundary (what makes the replay splice exact)."""

    async def main():
        fan = ReplicaFanout()
        q = parse_query(Q_BLOCK)
        m2 = RoutedSession(StubWS(), Q_BLOCK, q, 2)
        attached = [False]

        class AttachingWS(StubWS):
            async def send_str(self, s):
                await super().send_str(s)
                if not attached[0]:
                    attached[0] = True
                    fan.attach(m2)

        m1 = RoutedSession(AttachingWS(), Q_BLOCK, q, 1)
        blocks = make_blocks(2)
        fan.attach(m1)
        await fan.deliver(height_events(blocks[0]), 1)
        assert attached[0]
        assert len(m1.sink.frames) == 1 and m1.last_delivered == 1
        assert m2.sink.frames == [] and m2.last_delivered == 0
        await fan.deliver(height_events(blocks[1]), 2)
        assert m2.sink.heights() == [2] and m2.last_delivered == 2
        assert m1.sink.heights() == [1, 2]

    run(main())


# --- admission + placement --------------------------------------------


def test_least_loaded_placement():
    async def main():
        source, replicas, router = await _fleet(3)
        for i in range(9):
            await router.subscribe(StubWS(), Q_BLOCK, sub_id=i)
        assert [r.members() for r in replicas] == [3, 3, 3]
        await _teardown(router, replicas)

    run(main())


def test_admission_bound_sheds_and_releases():
    async def main():
        source, replicas, router = await _fleet(1, max_sessions=2)
        s1 = await router.subscribe(StubWS(), Q_BLOCK)
        await router.subscribe(StubWS(), Q_BLOCK)
        with pytest.raises(FleetOverloadError):
            await router.subscribe(StubWS(), Q_BLOCK)
        assert router.gate.stats()["dropped"] == 1
        assert router.fleet_status()["sheds"]["admit"] == 1
        # a departing session frees its admission slot
        await router.unsubscribe(s1)
        await router.subscribe(StubWS(), Q_BLOCK)
        await _teardown(router, replicas)

    run(main())


def test_failed_sink_degrades_only_its_session():
    async def main():
        source, replicas, router = await _fleet(1)
        bad = await router.subscribe(FailingWS(), Q_BLOCK)
        good_ws = StubWS()
        await router.subscribe(good_ws, Q_BLOCK)
        source.advance(make_blocks(1)[0])
        await wait_until(
            lambda: bad.closed and bad not in router._sessions,
            what="failed-sink reap",
        )
        assert bad.close_reason == "send_failed"
        assert len(good_ws.frames) == 1
        assert router.gate.stats()["depth"] == 1
        await _teardown(router, replicas)

    run(main())


# --- consistency tokens -----------------------------------------------


def test_token_routes_away_from_lagging_replica():
    """A request carrying token H lands only on a replica whose
    served height >= H — the lagging replica never sees it."""

    async def main():
        source, (r0, r1), router = await _fleet(
            2, max_lag_heights=100
        )
        blocks = make_blocks(8)
        for b in blocks[:5]:
            source.advance(b)
        await wait_until(
            lambda: r0.served_height() == 5 and r1.served_height() == 5,
            what="both at 5",
        )
        r0.stalled = True
        for b in blocks[5:]:
            source.advance(b)
        await wait_until(
            lambda: r1.served_height() == 8, what="r1 at 8"
        )
        token = router.issue_token()
        assert token == 8
        # ten tokened subscriptions: ALL land on the caught-up
        # replica even though least-loaded alone would alternate
        for i in range(10):
            await router.subscribe(
                StubWS(), Q_BLOCK, sub_id=i, token=token
            )
        assert r0.members() == 0 and r1.members() == 10
        assert (await router.route_read(token)) is r1
        await _teardown(router, [r0, r1])

    run(main())


def test_token_waits_barrier_then_serves():
    """Nobody satisfies the token yet: the router parks on the most
    advanced replica's height barrier and resolves as soon as the
    tail catches up — it never serves below the token."""

    async def main():
        source, (r0,), router = await _fleet(
            1, max_lag_heights=100, token_wait_s=5.0
        )
        blocks = make_blocks(5)
        for b in blocks[:3]:
            source.advance(b)
        await wait_until(
            lambda: r0.served_height() == 3, what="r0 at 3"
        )
        r0.stalled = True
        for b in blocks[3:]:
            source.advance(b)
        token = router.issue_token()
        assert token == 5
        read = asyncio.ensure_future(router.route_read(token))
        await asyncio.sleep(0.1)
        assert not read.done()  # parked on the barrier, not stale
        r0.stalled = False
        assert (await read) is r0
        assert r0.served_height() >= 5
        await _teardown(router, [r0])

    run(main())


def test_token_unsatisfiable_raises_stale_read():
    async def main():
        source, replicas, router = await _fleet(
            2, max_lag_heights=100, token_wait_s=0.2
        )
        for b in make_blocks(4)[:2]:
            source.advance(b)
        await wait_until(
            lambda: all(r.served_height() == 2 for r in replicas),
            what="both at 2",
        )
        for r in replicas:
            r.stalled = True
        source.advance(make_blocks(4)[3])
        token = router.issue_token()
        assert token == 4
        with pytest.raises(StaleReadError):
            await router.route_read(token)
        with pytest.raises(StaleReadError):
            await router.subscribe(StubWS(), Q_BLOCK, token=token)
        # the refused subscribe released its admission slot
        assert router.gate.stats()["depth"] == 0
        await _teardown(router, replicas)

    run(main())


# --- lag-aware shedding -----------------------------------------------


def test_lag_shed_isolates_victims_clients():
    """A replica stalled past max_lag_heights is drained and its
    sessions shed; bystanders on healthy replicas lose NOTHING. After
    the victim catches back up it rotates back into placement."""

    async def main():
        source, (r0, r1), router = await _fleet(
            2, max_lag_heights=2, lag_poll_s=0.02
        )
        s_a = await router.subscribe(StubWS(), Q_BLOCK, sub_id=0)
        s_b = await router.subscribe(StubWS(), Q_BLOCK, sub_id=1)
        victim_sess, bystander_sess = (
            (s_a, s_b) if _replica_of(router, s_a) is r0 else (s_b, s_a)
        )
        blocks = make_blocks(6)
        source.advance(blocks[0])
        await wait_until(
            lambda: r0.served_height() == 1 and r1.served_height() == 1,
            what="both at 1",
        )
        r0.stalled = True
        for b in blocks[1:]:
            source.advance(b)
        await wait_until(
            lambda: victim_sess.closed, what="lag shed"
        )
        assert victim_sess.close_reason == "shed_lag"
        st = router.fleet_status()
        assert st["sheds"]["lag"] == 1
        assert [
            r["degraded"] for r in st["replicas"]
        ] == [True, False]
        # the bystander saw every height, uninterrupted
        await wait_until(
            lambda: len(bystander_sess.sink.frames) == 6,
            what="bystander stream",
        )
        assert bystander_sess.sink.heights() == list(range(1, 7))
        assert not bystander_sess.closed
        # new placements avoid the degraded replica
        await router.subscribe(StubWS(), Q_BLOCK, sub_id=9)
        assert r0.members() == 0
        # recovery: unstall -> catches up -> rotated back in
        r0.stalled = False
        await wait_until(
            lambda: not router.fleet_status()["replicas"][0][
                "degraded"
            ],
            what="recovery",
        )
        await _teardown(router, [r0, r1])

    run(main())


# --- failover ---------------------------------------------------------


def test_failover_zero_lost_commits():
    """Replica death mid-stream: every stranded session is re-admitted
    on a survivor and its delivered stream is gap-free AND
    byte-identical to an uninterrupted one (store replay + splice)."""

    async def main():
        source, (r0, r1), router = await _fleet(2)
        sessions = []
        for i in range(4):
            q = Q_BLOCK if i % 2 == 0 else Q_TX
            sessions.append(
                await router.subscribe(StubWS(), q, sub_id=i)
            )
        stranded = [
            s for s in sessions if _replica_of(router, s) is r0
        ]
        assert len(stranded) == 2
        blocks = make_blocks(8, txs=2)
        for b in blocks[:4]:
            source.advance(b)
        await wait_until(
            lambda: r0.served_height() == 4 and r1.served_height() == 4,
            what="both at 4",
        )
        await r0.kill()
        for b in blocks[4:]:
            source.advance(b)
        await wait_until(
            lambda: all(
                _replica_of(router, s) is r1 for s in stranded
            ),
            what="failover",
        )
        st = router.fleet_status()
        assert st["failovers"] == 1
        assert st["sessions_resumed"] == 2
        assert st["sheds"]["failover"] == 0
        # every session — resumed or not — holds the full stream
        exp_block = [h for h in range(1, 9)]
        exp_tx = [h for h in range(1, 9) for _ in range(2)]
        for s in sessions:
            want = exp_block if s.query_str == Q_BLOCK else exp_tx
            await wait_until(
                lambda s=s, want=want: len(s.sink.frames)
                == len(want),
                what=f"full stream for {s.sub_id}",
            )
            assert s.sink.heights() == want, s.sub_id
        for s in stranded:
            assert s.resumes == 1
        # replayed frames are byte-identical to live ones: rebuild
        # the uninterrupted stream and compare wholesale
        for s in stranded:
            expect = []
            for blk in blocks:
                for e in height_events(blk):
                    from cometbft_tpu.rpc.fanout import _event_attrs

                    if s.query.matches(_event_attrs(e)):
                        expect.append(
                            s._prefix
                            + event_payload(e, s.query_str)
                            + "}"
                        )
            assert s.sink.frames == expect
        await _teardown(router, [r0, r1])

    run(main())


def test_failover_without_store_sheds_honestly():
    """No store to replay from -> a live-only re-admit would be lossy;
    the router sheds instead of silently dropping commits."""

    async def main():
        source = StreamSource()
        replicas = [
            FollowerNode(f"r{i}", source, poll_s=0.01)
            for i in range(2)
        ]
        router = SessionRouter(
            replicas, store_source=None, lag_poll_s=0.02
        )
        for r in replicas:
            await r.start()
        await router.start()
        ws = StubWS()
        sess = await router.subscribe(ws, Q_BLOCK)
        victim = _replica_of(router, sess)
        for b in make_blocks(2):
            source.advance(b)
        await wait_until(
            lambda: victim.served_height() == 2, what="victim at 2"
        )
        await victim.kill()
        await wait_until(lambda: sess.closed, what="failover shed")
        assert sess.close_reason == "failover_shed"
        st = router.fleet_status()
        assert st["sheds"]["failover"] == 1
        assert st["sessions_resumed"] == 0
        await _teardown(router, replicas)

    run(main())


# --- NodeReplica adapter ----------------------------------------------


def test_node_replica_adapter_surface():
    import types as _types

    from cometbft_tpu.rpc.fanout import FanoutHub
    from cometbft_tpu.types import events as ev

    async def main():
        bus = ev.EventBus()
        bus.set_loop(asyncio.get_running_loop())
        hub = FanoutHub(bus)
        node = _types.SimpleNamespace(
            parts=_types.SimpleNamespace(privval=None),
            rpc_server=_types.SimpleNamespace(fanout=hub),
            height=5,
            config=_types.SimpleNamespace(
                base=_types.SimpleNamespace(moniker="adapter")
            ),
        )
        rep = NodeReplica(node)
        assert rep.role == "follower"
        node.parts.privval = object()
        assert rep.role == "validator"
        assert rep.served_height() == 5 and rep.lag_heights() == 0
        assert await rep.wait_height(4, 0.1)
        assert not await rep.wait_height(9, 0.05)
        # sessions ride the node's hub; heights tracked by frame parse
        sess = RoutedSession(StubWS(), Q_BLOCK, parse_query(Q_BLOCK), 1)
        sess.parse_heights = rep.HUB_DELIVERY
        rep.attach(sess)
        assert rep.members() == 1
        blk = make_blocks(1)[0]
        bus.publish(
            ev.Event(
                ev.EVENT_NEW_BLOCK,
                {
                    "block": blk,
                    "block_id": None,
                    "result_events": [],
                },
                {"height": "1"},
            )
        )
        await wait_until(
            lambda: len(sess.sink.frames) == 1, what="hub frame"
        )
        assert sess.last_delivered == 1  # parsed, no on_height signal
        await rep.detach_member(sess)
        assert rep.members() == 0
        await hub.close()

    run(main())


# --- fleet status -----------------------------------------------------


def test_fleet_status_shape():
    async def main():
        source, replicas, router = await _fleet(2)
        await router.subscribe(StubWS(), Q_BLOCK)
        router.issue_token()
        st = router.fleet_status()
        assert st["sessions"] == 1
        assert st["tokens_issued"] == 1
        assert set(st["sheds"]) == {"admit", "lag", "failover"}
        assert st["admission"]["maxsize"] == 4096
        assert len(st["replicas"]) == 2
        for rs in st["replicas"]:
            assert rs["role"] == "follower"
            assert rs["alive"] and not rs["degraded"]
            assert rs["lag_heights"] == 0
        assert json.dumps(st)  # JSON-serializable for /fleet_status
        await _teardown(router, replicas)

    run(main())


# --- satellites: plane drain + shared cross-replica cache -------------

N_VALS = 2
CHAIN_LEN = 8


@pytest.fixture(scope="module")
def chain():
    gen, pvs = make_genesis(N_VALS, chain_id="fleet-light")
    node = make_chain(gen, [pv.priv_key for pv in pvs], CHAIN_LEN)
    yield gen, pvs, node
    node.close_stores()


def _light_client(gen, node):
    from cometbft_tpu.light.client import Client, TrustOptions
    from cometbft_tpu.light.provider import StoreBackedProvider

    provider = StoreBackedProvider(
        gen.chain_id, node.block_store, node.state_store
    )
    root = provider.light_block(1)
    return Client(
        gen.chain_id,
        TrustOptions(
            period_ns=24 * 3600 * 10**9, height=1, hash=root.hash()
        ),
        provider,
    )


def test_plane_drain_is_bounded_and_reversible(chain):
    gen, _, node = chain
    plane = LightServingPlane([_light_client(gen, node)])
    assert plane.serve(5).height == 5
    # a held in-flight slot: drain must time out BOUNDED, not hang
    assert plane.gate.enter(1.0)
    t0 = time.monotonic()
    assert plane.drain(timeout_s=0.3) is False
    assert 0.25 <= time.monotonic() - t0 < 2.0
    assert plane.stats()["draining"]
    # draining sheds new work with the standard overload error
    with pytest.raises(ServingOverloadError):
        plane.serve(6)
    with pytest.raises(ServingOverloadError):
        plane.open_session()
    shed_before = plane.requests_shed
    assert shed_before >= 1
    # in-flight resolves -> drain completes promptly
    plane.gate.exit()
    assert plane.drain(timeout_s=1.0) is True
    plane.resume()
    assert not plane.stats()["draining"]
    assert plane.serve(5).height == 5  # cache hit, serving again


def test_cross_replica_shared_cache_single_flight(chain):
    """Two followers, one VerifiedHeaderCache: a height requested
    through BOTH replicas' planes concurrently verifies exactly once
    process-wide, and the poison refusal is unchanged."""
    import dataclasses

    from cometbft_tpu.light.types import LightBlock

    gen, _, node = chain
    cache = VerifiedHeaderCache(gen.chain_id)
    planes = [
        LightServingPlane([_light_client(gen, node)], cache=cache)
        for _ in range(2)
    ]

    async def main():
        source = StoreSource(node.block_store)
        followers = [
            FollowerNode(
                f"r{i}", source, light_plane=planes[i], poll_s=0.01
            )
            for i in range(2)
        ]
        router = SessionRouter(followers, store_source=source)
        for f in followers:
            await f.start()
        await router.start()

        verify_calls = []
        for p in planes:
            orig = p._verify

            def counted(h, _orig=orig):
                verify_calls.append(h)
                time.sleep(0.05)  # hold the flight so peers pile up
                return _orig(h)

            p._verify = counted

        # concurrent requests for the SAME height through BOTH
        # replicas: the shared cache single-flights them fleet-wide
        got = []
        threads = [
            threading.Thread(
                target=lambda t=tok: got.append(
                    router.serve_light(6, t)
                )
            )
            for tok in (None, None, None, None)
        ]
        for t in threads:
            t.start()
        for t in threads:
            await asyncio.to_thread(t.join)
        assert len(got) == 4
        assert len(verify_calls) == 1, verify_calls
        assert all(lb.height == 6 for lb in got)
        assert cache.hits + cache.flight_waits >= 3
        # second replica's plane now hits the shared cache cold-free
        before = len(verify_calls)
        assert planes[1].serve(6).height == 6
        assert len(verify_calls) == before

        # poison refusal is unchanged with a shared cache
        lb = got[0]
        poisoned = LightBlock(
            header=dataclasses.replace(
                lb.header, app_hash=b"\x66" * 32
            ),
            commit=lb.commit,
            validator_set=lb.validator_set,
        )
        entries_before = len(cache)
        with pytest.raises(CachePoisonError):
            cache.publish(poisoned)
        assert len(cache) == entries_before

        await _teardown(router, followers)

    run(main())
