"""lp2p stack tests: stream muxer, host admission (gater + resource
manager), and switch-level nets over per-channel streams (reference
analog: lp2p/*_test.go with in-memory libp2p hosts)."""

import asyncio
import socket

import pytest

from cometbft_tpu.lp2p import (
    ConnGater,
    Host,
    Lp2pSwitch,
    Muxer,
    ResourceManager,
)
from cometbft_tpu.lp2p.switch import channel_protocol, protocol_channel
from cometbft_tpu.p2p import (
    ChannelDescriptor,
    MemoryTransport,
    NodeInfo,
    NodeKey,
    Reactor,
    TCPTransport,
)
from cometbft_tpu.p2p.conn.secret_connection import SecretConnection


def run(coro, timeout=30):
    return asyncio.run(asyncio.wait_for(coro, timeout))


async def _sconn_pair():
    a, b = socket.socketpair()
    a.setblocking(False)
    b.setblocking(False)
    r1, w1 = await asyncio.open_connection(sock=a)
    r2, w2 = await asyncio.open_connection(sock=b)
    k1, k2 = NodeKey.generate(), NodeKey.generate()
    return await asyncio.gather(
        SecretConnection.handshake(r1, w1, k1.priv_key),
        SecretConnection.handshake(r2, w2, k2.priv_key),
    )


# --- muxer ------------------------------------------------------------


def test_mux_open_send_recv_close():
    async def main():
        c1, c2 = await _sconn_pair()
        accepted = []
        m1 = Muxer(c1, initiator=True, on_stream=accepted.append)
        m2 = Muxer(c2, initiator=False, on_stream=accepted.append)
        m1.start()
        m2.start()
        st = await m1.open_stream("/cometbft/ch/0x20")
        await st.send(b"proposal")
        await st.send(b"vote")
        for _ in range(100):
            if accepted:
                break
            await asyncio.sleep(0.01)
        (remote,) = accepted
        assert remote.protocol == "/cometbft/ch/0x20"
        assert await remote.recv() == b"proposal"
        assert await remote.recv() == b"vote"
        # large message spans many secret-connection chunks
        big = bytes(256) * 300  # 76800 bytes
        await st.send(big)
        assert await remote.recv() == big
        await st.close()
        assert await remote.recv() is None  # FIN observed
        await m1.stop()
        await m2.stop()

    run(main())


def test_mux_streams_are_independent():
    """A full stream's backlog must not block another stream."""

    async def main():
        c1, c2 = await _sconn_pair()
        accepted = []
        m1 = Muxer(c1, initiator=True, on_stream=accepted.append)
        m2 = Muxer(c2, initiator=False, on_stream=accepted.append)
        m1.start()
        m2.start()
        slow = await m1.open_stream("/cometbft/ch/0x40")
        fast = await m1.open_stream("/cometbft/ch/0x22")
        for i in range(50):
            await slow.send(b"blocksync-%d" % i)
        await fast.send(b"urgent-vote")
        for _ in range(200):
            if len(accepted) == 2:
                break
            await asyncio.sleep(0.01)
        by_proto = {s.protocol: s for s in accepted}
        # the vote arrives regardless of the other stream's backlog
        got = await asyncio.wait_for(
            by_proto["/cometbft/ch/0x22"].recv(), 5
        )
        assert got == b"urgent-vote"
        await m1.stop()
        await m2.stop()

    run(main())


def test_mux_stream_limit_resets_excess():
    async def main():
        c1, c2 = await _sconn_pair()
        m1 = Muxer(c1, initiator=True, on_stream=lambda s: None)
        m2 = Muxer(
            c2, initiator=False, on_stream=lambda s: None, max_streams=2
        )
        m1.start()
        m2.start()
        for i in range(2):
            await m1.open_stream(f"/cometbft/ch/{i:#04x}")
        third = await m1.open_stream("/cometbft/ch/0x99")
        # receiver RSTs the stream over its cap
        assert await asyncio.wait_for(third.recv(), 5) is None
        assert third.reset
        await m1.stop()
        await m2.stop()

    run(main())


def test_protocol_mapping_roundtrip():
    for cid in (0x00, 0x20, 0x38, 0x61):
        assert protocol_channel(channel_protocol(cid)) == cid
    assert protocol_channel("/bogus/proto") is None


# --- host admission ---------------------------------------------------


def test_gater_denies_dial_and_secured():
    async def main():
        nk1, nk2 = NodeKey.generate(), NodeKey.generate()
        i1 = NodeInfo(node_id=nk1.node_id, network="lp2p-test")
        i2 = NodeInfo(node_id=nk2.node_id, network="lp2p-test")
        t1 = MemoryTransport(nk1, i1)
        t2 = MemoryTransport(nk2, i2)
        await t1.listen()
        await t2.listen()
        gater = ConnGater()
        gater.denied_peers.add(nk2.node_id)
        h1 = Host(t1, gater=gater)
        with pytest.raises(Exception):
            await h1.dial(f"mem://{nk2.node_id}", nk2.node_id)
        # denied at the secured stage even when the dial target was
        # not named up front
        with pytest.raises(Exception):
            await h1.dial(f"mem://{nk2.node_id}")
        assert h1.rcmgr.open_conns == 0
        await t1.close()
        await t2.close()

    run(main())


def test_resource_manager_conn_cap():
    async def main():
        nk1 = NodeKey.generate()
        i1 = NodeInfo(node_id=nk1.node_id, network="lp2p-test")
        t1 = MemoryTransport(nk1, i1)
        await t1.listen()
        h1 = Host(t1, rcmgr=ResourceManager(max_conns=0))
        nk2 = NodeKey.generate()
        i2 = NodeInfo(node_id=nk2.node_id, network="lp2p-test")
        t2 = MemoryTransport(nk2, i2)
        await t2.listen()
        with pytest.raises(Exception):
            await h1.dial(f"mem://{nk2.node_id}", nk2.node_id)
        await t1.close()
        await t2.close()

    run(main())


# --- switch-level -----------------------------------------------------


class EchoReactor(Reactor):
    name = "echo"
    CHAN = 0x77

    def __init__(self):
        super().__init__()
        self.got = []
        self.peers_seen = []
        self.removed = []

    def get_channels(self):
        return [ChannelDescriptor(self.CHAN, priority=3)]

    def add_peer(self, peer):
        self.peers_seen.append(peer.peer_id)

    def remove_peer(self, peer, reason):
        self.removed.append(peer.peer_id)

    def receive(self, chan_id, peer, msg):
        self.got.append((peer.peer_id, msg))
        if not msg.startswith(b"ack:"):
            peer.try_send(chan_id, b"ack:" + msg)


def _make_lp2p_switch(chain_id="lp2p-test", transport_cls=TCPTransport):
    nk = NodeKey.generate()
    info = NodeInfo(node_id=nk.node_id, network=chain_id)
    tr = transport_cls(nk, info)
    sw = Lp2pSwitch(tr, info)
    er = sw.add_reactor("echo", EchoReactor())
    return sw, er


def test_lp2p_switch_connect_broadcast():
    async def main():
        sw1, er1 = _make_lp2p_switch()
        sw2, er2 = _make_lp2p_switch()
        await sw1.transport.listen("127.0.0.1:0")
        await sw2.transport.listen("127.0.0.1:0")
        await sw1.start()
        await sw2.start()
        await sw1.dial_peer(sw2.transport.listen_addr)
        for _ in range(100):
            if sw2.num_peers() and sw1.num_peers():
                break
            await asyncio.sleep(0.05)
        assert sw1.num_peers() == 1 and sw2.num_peers() == 1
        assert er1.peers_seen and er2.peers_seen
        # wait for channel streams to open, then broadcast
        for _ in range(100):
            sw1.broadcast(EchoReactor.CHAN, b"ping-all")
            if (sw1.node_info.node_id, b"ping-all") in er2.got:
                break
            await asyncio.sleep(0.05)
        assert (sw1.node_info.node_id, b"ping-all") in er2.got
        for _ in range(100):
            if (sw2.node_info.node_id, b"ack:ping-all") in er1.got:
                break
            await asyncio.sleep(0.05)
        assert (sw2.node_info.node_id, b"ack:ping-all") in er1.got
        await sw1.stop()
        await sw2.stop()

    run(main())


def test_lp2p_ban_peer_feeds_gater():
    async def main():
        sw1, er1 = _make_lp2p_switch(transport_cls=MemoryTransport)
        sw2, _ = _make_lp2p_switch(transport_cls=MemoryTransport)
        await sw1.transport.listen()
        await sw2.transport.listen()
        await sw1.start()
        await sw2.start()
        await sw1.dial_peer(sw2.transport.listen_addr)
        for _ in range(100):
            if sw1.num_peers():
                break
            await asyncio.sleep(0.05)
        sw1.ban_peer(sw2.node_info.node_id)
        for _ in range(100):
            if not sw1.num_peers():
                break
            await asyncio.sleep(0.05)
        assert sw1.num_peers() == 0
        assert sw2.node_info.node_id in sw1.host.gater.denied_peers
        # redial by id is refused (banned set short-circuits); a dial
        # without a named id is stopped by the gater at secured stage
        got = await sw1.dial_peer(
            f"{sw2.node_info.node_id}@{sw2.transport.listen_addr}"
        )
        assert got is None
        with pytest.raises(Exception):
            await sw1.host.dial(sw2.transport.listen_addr)
        assert sw1.num_peers() == 0
        await sw1.stop()
        await sw2.stop()

    run(main())


def test_lp2p_peer_drop_notifies_reactors():
    async def main():
        sw1, er1 = _make_lp2p_switch(transport_cls=MemoryTransport)
        sw2, er2 = _make_lp2p_switch(transport_cls=MemoryTransport)
        await sw1.transport.listen()
        await sw2.transport.listen()
        await sw1.start()
        await sw2.start()
        await sw1.dial_peer(sw2.transport.listen_addr)
        for _ in range(100):
            if sw1.num_peers() and sw2.num_peers():
                break
            await asyncio.sleep(0.05)
        # hard-stop sw2's peer object; sw1 must notice the dead conn
        peer2 = next(iter(sw2.peers.values()))
        await peer2.stop()
        for _ in range(200):
            if er1.removed:
                break
            sw1.broadcast(EchoReactor.CHAN, b"probe")
            await asyncio.sleep(0.05)
        assert er1.removed
        assert sw1.host.rcmgr.open_conns == 0
        await sw1.stop()
        await sw2.stop()

    run(main())


# --- full nodes over the lp2p switcher --------------------------------


def test_consensus_over_lp2p_net():
    """4 validators reach consensus with the alternative switcher
    selected by config (reference analog: lp2p-backed e2e nets)."""
    from cometbft_tpu.config.config import test_config as make_test_cfg
    from cometbft_tpu.node.inprocess import make_genesis
    from cometbft_tpu.node.node import Node

    gen, pvs = make_genesis(4, chain_id="lp2p-chain")

    async def main():
        nodes = []
        for i, pv in enumerate(pvs):
            cfg = make_test_cfg(".")
            cfg.p2p.laddr = "tcp://127.0.0.1:0"
            cfg.p2p.use_libp2p_equivalent = True
            cfg.base.moniker = f"lpnode{i}"
            cfg.blocksync.enable = False
            nodes.append(Node(cfg, gen, privval=pv))
        for n in nodes:
            assert isinstance(n.switch, Lp2pSwitch)
            await n.start()
        for i, a in enumerate(nodes):
            for b in nodes[i + 1:]:
                await a.dial(b.listen_addr)
        for n in nodes:
            for _ in range(200):
                if n.switch.num_peers() >= 3:
                    break
                await asyncio.sleep(0.05)

        async def waiter():
            while not all(n.height >= 3 for n in nodes):
                await asyncio.sleep(0.05)

        await asyncio.wait_for(waiter(), 90)
        h2 = {
            bytes(n.parts.block_store.load_block(2).hash()) for n in nodes
        }
        assert len(h2) == 1
        for n in nodes:
            await n.stop()

    run(main())
