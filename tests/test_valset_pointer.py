"""ValidatorsInfo pointer scheme (reference state/store.go:185-251,
590-640): full valset records only at change/checkpoint heights,
pointer records elsewhere, priority reconstruction on load, and the
slim S:state blob carrying EXACT live priorities (VERDICT r2 next-round
#4 — the replay pipeline's dominant cost was four full valset encodings
per height)."""

import dataclasses

import pytest

from cometbft_tpu import types as T
from cometbft_tpu.state import store as state_store_mod
from cometbft_tpu.state.state_types import ConsensusParams, State
from cometbft_tpu.state.store import Store, VALSET_CHECKPOINT_INTERVAL
from cometbft_tpu.utils import kv


def _mk_state(vs, h, initial=1, changed=1):
    nvals = vs.copy_increment_proposer_priority(1)
    return State(
        chain_id="ptr-chain",
        initial_height=initial,
        last_block_height=h,
        last_block_id=T.BlockID(b"\x01" * 32, T.PartSetHeader(1, b"\x02" * 32)),
        last_block_time_ns=1000 + h,
        validators=vs,
        next_validators=nvals,
        last_validators=vs.copy(),
        last_height_validators_changed=changed,
        consensus_params=ConsensusParams(),
        app_hash=b"\x0b" * 32,
    )


def _evolve(store, vs0, n_heights, change_at=()):
    """Simulate the executor's per-height save loop from genesis."""
    state = _mk_state(vs0.copy(), 0, changed=1)
    store.save(state)  # genesis save (next_height == initial)
    for h in range(1, n_heights + 1):
        nvals = state.next_validators.copy()
        changed = state.last_height_validators_changed
        if h in change_at:
            extra = T.random_validator_set(1)[0].validators[0]
            nvals.update_with_change_set([extra])
            changed = h + 2  # updates from block h take effect at h+2
        nvals.increment_proposer_priority(1)
        state = dataclasses.replace(
            state,
            last_block_height=h,
            validators=state.next_validators.copy(),
            next_validators=nvals,
            last_validators=state.validators.copy(),
            last_height_validators_changed=changed,
        )
        store.save(state)
    return state


def test_pointer_records_written_for_unchanged_heights():
    vs, _ = T.random_validator_set(4)
    db = kv.MemKV()
    store = Store(db)
    _evolve(store, vs, 20)
    full = pointer = 0
    for h in range(1, 23):
        raw = db.get(b"S:vi:" + h.to_bytes(8, "big"))
        assert raw is not None, h
        got, changed = state_store_mod._decode_validators_info(raw)
        if got is None:
            pointer += 1
            assert changed == 1
        else:
            full += 1
    # genesis-adjacent records are full; the rest are pointers
    assert full <= 3 and pointer >= 19


def test_load_reconstructs_priorities_at_pointer_heights():
    vs, _ = T.random_validator_set(5)
    db = kv.MemKV()
    store = Store(db)
    state = _evolve(store, vs, 30)
    # membership + hash identical at every height
    for h in (2, 7, 19, 31):
        got = store.load_validators(h)
        assert got is not None
        assert got.hash() == vs.hash()
    # the live state's priorities round-trip EXACTLY through the slim
    # blob (no reconstruction drift on the consensus-resume path)
    loaded = store.load()
    for a, b in (
        (loaded.validators, state.validators),
        (loaded.next_validators, state.next_validators),
        (loaded.last_validators, state.last_validators),
    ):
        assert [v.proposer_priority for v in a.validators] == [
            v.proposer_priority for v in b.validators
        ]
        assert a.proposer.address == b.proposer.address
    assert loaded.last_block_height == state.last_block_height


def test_valset_change_writes_full_record():
    vs, _ = T.random_validator_set(4)
    db = kv.MemKV()
    store = Store(db)
    _evolve(store, vs, 12, change_at={6})
    raw = db.get(b"S:vi:" + (8).to_bytes(8, "big"))
    got, changed = state_store_mod._decode_validators_info(raw)
    assert got is not None and changed == 8
    assert got.size() == 5
    # heights after the change reconstruct from the new full record
    after = store.load_validators(11)
    assert after.size() == 5
    # heights before it still load the old membership
    before = store.load_validators(6)
    assert before.size() == 4


def test_checkpoint_bounds_reconstruction(monkeypatch):
    monkeypatch.setattr(
        state_store_mod, "VALSET_CHECKPOINT_INTERVAL", 10
    )
    vs, _ = T.random_validator_set(3)
    db = kv.MemKV()
    store = Store(db)
    _evolve(store, vs, 25)
    # checkpoint heights hold full records
    for cp in (10, 20):
        raw = db.get(b"S:vi:" + cp.to_bytes(8, "big"))
        got, _ = state_store_mod._decode_validators_info(raw)
        assert got is not None, cp
    # a height just past a checkpoint reconstructs from it, not genesis
    assert store.load_validators(21).hash() == vs.hash()


def test_prune_keeps_reconstruction_anchor(monkeypatch):
    monkeypatch.setattr(
        state_store_mod, "VALSET_CHECKPOINT_INTERVAL", 10
    )
    vs, _ = T.random_validator_set(3)
    db = kv.MemKV()
    store = Store(db)
    _evolve(store, vs, 25)
    store.prune_states(15)
    # the checkpoint at 10 (anchor for pointer records in [10, 20)) kept
    raw = db.get(b"S:vi:" + (10).to_bytes(8, "big"))
    assert raw is not None
    # heights >= retain still load
    assert store.load_validators(15).hash() == vs.hash()
    assert store.load_validators(22).hash() == vs.hash()
    # heights below the anchor are gone
    assert db.get(b"S:vi:" + (5).to_bytes(8, "big")) is None


def test_prune_keeps_legacy_anchor_of_upgraded_store(monkeypatch):
    """ADVICE r3 (medium): on a store upgraded from the legacy S:vals
    layout, prune_states with retain_height inside the legacy region
    must not delete the legacy record that post-upgrade pointer
    records anchor at (save() anchors them at the state's
    last_height_validators_changed, which can predate retain_height —
    and an upgrade-backfill FULL record in between must not mask the
    pointer's true anchor)."""
    from cometbft_tpu.utils import codec

    monkeypatch.setattr(state_store_mod, "VALSET_CHECKPOINT_INTERVAL", 10)
    vs, _ = T.random_validator_set(3)
    db = kv.MemKV()
    # legacy store: raw S:vals full records at heights 1..12
    for h in range(1, 13):
        db.set(
            b"S:vals:" + h.to_bytes(8, "big"),
            codec.encode_validator_set(vs),
        )
    store = Store(db)
    # first post-upgrade save: last change happened at legacy height 11
    state = _mk_state(vs.copy(), 12, changed=11)
    store.save(state)
    # the new record at 14 is a pointer anchored at 11 (max(cp=10, 11));
    # save() backfills a FULL record at 13 (no legacy record there)
    raw14 = db.get(b"S:vi:" + (14).to_bytes(8, "big"))
    got14, changed14 = state_store_mod._decode_validators_info(raw14)
    assert got14 is None and changed14 == 11
    store.prune_states(12)
    # the anchor at 11 survives even though 11 < retain_height
    assert db.get(b"S:vals:" + (11).to_bytes(8, "big")) is not None
    got = store.load_validators(14)
    assert got is not None and got.hash() == vs.hash()
    # retain_height ON the backfill FULL record at 13: a full record is
    # not a change point, so the pointer at 14 still anchors below it —
    # the scan must look past full records, not stop at them
    store.prune_states(13)
    assert db.get(b"S:vals:" + (11).to_bytes(8, "big")) is not None
    got = store.load_validators(14)
    assert got is not None and got.hash() == vs.hash()


def test_legacy_full_records_still_load():
    """Stores written before the pointer scheme (raw S:vals records)
    keep loading."""
    from cometbft_tpu.utils import codec

    vs, _ = T.random_validator_set(4)
    db = kv.MemKV()
    db.set(
        b"S:vals:" + (9).to_bytes(8, "big"), codec.encode_validator_set(vs)
    )
    store = Store(db)
    got = store.load_validators(9)
    assert got is not None and got.hash() == vs.hash()


def test_rollback_across_valset_change_keeps_history_consistent():
    """Code-review r3 finding: rollback after a validator-set change
    must clamp last_height_validators_changed (reference
    rollback.go:69-76) or the next save writes a FORWARD pointer over
    a correct record and historical loads return the wrong set."""
    from cometbft_tpu.node.inprocess import build_node, make_genesis
    from cometbft_tpu.state.rollback import rollback_state
    from cometbft_tpu.utils.chaingen import make_chain

    gen, pvs = make_genesis(4, chain_id="rb-ptr")
    node = build_node(gen, None)
    make_chain(gen, [pv.priv_key for pv in pvs], 5, node=node)
    # a validator-power update lands in block 6 -> takes effect at 8
    new_power_tx = b"val:%s!%d" % (
        pvs[0].priv_key.pub_key().key_bytes.hex().encode(),
        25,
    )
    node.mempool.check_tx(new_power_tx)
    make_chain(gen, [pv.priv_key for pv in pvs], 1, node=node, txs_per_block=0)
    st = node.state_store.load()
    assert st.last_height_validators_changed == 8
    make_chain(gen, [pv.priv_key for pv in pvs], 2, node=node)
    before = node.state_store.load_validators(7)
    assert before is not None

    # roll back height 8 (the change-effect height)
    rolled = rollback_state(node.state_store, node.block_store)
    assert rolled.last_block_height == 7
    assert rolled.last_height_validators_changed <= 9
    # saving the rolled-back state must NOT have corrupted height 7/8
    after = node.state_store.load_validators(7)
    assert after is not None
    assert after.hash() == before.hash()
    # and the reloaded state still reconstructs
    reloaded = node.state_store.load()
    assert reloaded.last_block_height == 7
    assert reloaded.validators.hash() == rolled.validators.hash()


def test_pool_soft_exclusion_steers_retry():
    """EC-miss refetch prefers a different peer (soft exclusion), but
    ignores the exclusion when no alternative exists (liveness)."""
    from cometbft_tpu.blocksync.pool import BlockPool, PoolPeer

    pool = BlockPool(1)
    # direct peer construction: set_peer_range spawns requester tasks,
    # which needs a running loop this sync test doesn't have
    pool.peers["fast"] = PoolPeer(
        "fast", object(), base=1, height=100, latency_ewma=0.01
    )
    pool.peers["slow"] = PoolPeer(
        "slow", object(), base=1, height=100, latency_ewma=0.9
    )
    # un-excluded: fastest wins
    assert pool._pick_peer(5).peer_id == "fast"
    pool.exclude_peer_for_height(5, "fast")
    assert pool._pick_peer(5).peer_id == "slow"
    # other heights unaffected
    assert pool._pick_peer(6).peer_id == "fast"
    # all excluded -> exclusion ignored (never a liveness risk)
    pool.exclude_peer_for_height(5, "slow")
    assert pool._pick_peer(5) is not None
    pool.clear_exclusions(5)
    assert pool._pick_peer(5).peer_id == "fast"
