"""Remote signer tests: a validator whose key lives in a separate
signer process-equivalent (async task) signing over the socket
protocol (reference privval/signer_client_test.go)."""

import asyncio
import os
import tempfile

import pytest

from cometbft_tpu import types as T
from cometbft_tpu.config.config import test_config as make_test_cfg
from cometbft_tpu.node.inprocess import make_genesis
from cometbft_tpu.node.node import Node
from cometbft_tpu.privval.file_pv import DoubleSignError, FilePV
from cometbft_tpu.privval.signer import (
    RemoteSignerError,
    RetrySignerClient,
    SignerClient,
    SignerServer,
)


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def _file_pv(priv):
    d = tempfile.mkdtemp(prefix="rs_")
    pv = FilePV(
        priv, os.path.join(d, "key.json"), os.path.join(d, "state.json")
    )
    pv.save_key()
    pv.save_state()
    return pv


def test_remote_signing_roundtrip_and_double_sign_guard():
    async def main():
        gen, pvs = make_genesis(1, chain_id="rs-chain")
        signer_pv = pvs[0]
        client = SignerClient("127.0.0.1:0")
        server = SignerServer(signer_pv, client.listen_addr)
        task = asyncio.create_task(server.serve())
        await asyncio.sleep(0.2)

        # pubkey round trip
        pub = await asyncio.to_thread(client.pub_key)
        assert bytes(pub) == bytes(signer_pv.pub_key())

        # vote signing round trip verifies
        bid = T.BlockID(b"\x11" * 32, T.PartSetHeader(1, b"\x22" * 32))
        vote = T.Vote(
            type_=T.PRECOMMIT, height=5, round=0, block_id=bid,
            timestamp_ns=123, validator_address=pub.address(),
            validator_index=0,
        )
        await asyncio.to_thread(client.sign_vote, "rs-chain", vote)
        assert pub.verify(vote.sign_bytes("rs-chain"), vote.signature)

        # double-sign guard fires REMOTELY (key-side protection)
        vote2 = T.Vote(
            type_=T.PRECOMMIT, height=5, round=0,
            block_id=T.BlockID(b"\x99" * 32, T.PartSetHeader(1, b"\x22" * 32)),
            timestamp_ns=124, validator_address=pub.address(),
            validator_index=0,
        )
        with pytest.raises(RemoteSignerError):
            await asyncio.to_thread(client.sign_vote, "rs-chain", vote2)

        server.stop()
        task.cancel()
        client.close()

    run(main())


def test_retry_signer_survives_connection_drop():
    """VERDICT r3 missing #2 (reference privval/retry_signer_client.go):
    the signer's connection drops MID-SESSION; the redialing server
    (serve_forever) reconnects, and RetrySignerClient's bounded
    retries land the vote instead of surfacing a one-shot failure."""

    async def main():
        gen, pvs = make_genesis(1, chain_id="retry-chain")
        raw = SignerClient("127.0.0.1:0", timeout_s=1.0)
        client = RetrySignerClient(raw, retries=10, interval_s=0.1)
        server = SignerServer(pvs[0], raw.listen_addr)
        task = asyncio.create_task(server.serve_forever(0.1))
        await asyncio.sleep(0.2)

        pub = await asyncio.to_thread(client.pub_key)
        assert bytes(pub) == bytes(pvs[0].pub_key())

        # kill the live connection from the node side: the next sign
        # call fails its first attempt(s), the signer redials, and the
        # retry succeeds
        raw._sconn.close()
        bid = T.BlockID(b"\x11" * 32, T.PartSetHeader(1, b"\x22" * 32))
        vote = T.Vote(
            type_=T.PRECOMMIT, height=7, round=0, block_id=bid,
            timestamp_ns=321, validator_address=pub.address(),
            validator_index=0,
        )
        await asyncio.to_thread(client.sign_vote, "retry-chain", vote)
        assert pub.verify(vote.sign_bytes("retry-chain"), vote.signature)

        # a DEFINITIVE refusal (double-sign guard) is NOT retried:
        # it surfaces immediately as RemoteSignerError
        conflicting = T.Vote(
            type_=T.PRECOMMIT, height=7, round=0,
            block_id=T.BlockID(
                b"\x99" * 32, T.PartSetHeader(1, b"\x22" * 32)
            ),
            timestamp_ns=322, validator_address=pub.address(),
            validator_index=0,
        )
        import time as _t

        t0 = _t.monotonic()
        with pytest.raises(RemoteSignerError):
            await asyncio.to_thread(
                client.sign_vote, "retry-chain", conflicting
            )
        assert _t.monotonic() - t0 < 0.5  # no retry sleeps burned

        # retries are BOUNDED: with the signer gone for good, the
        # wrapper gives up with a RemoteSignerError instead of hanging
        server.stop()
        task.cancel()
        raw._sconn.close()
        client.retries = 2
        raw.timeout_s = 0.3
        vote3 = T.Vote(
            type_=T.PRECOMMIT, height=8, round=0, block_id=bid,
            timestamp_ns=400, validator_address=pub.address(),
            validator_index=0,
        )
        with pytest.raises(RemoteSignerError, match="retries"):
            await asyncio.to_thread(
                client.sign_vote, "retry-chain", vote3
            )
        client.close()

    run(main())


def test_node_with_remote_signer_produces_blocks():
    """The signer runs on its own thread+loop, standing in for the
    separate signer process of a real deployment (consensus blocks the
    node loop while awaiting signatures, so an in-loop signer would
    deadlock — which is also true of the reference's sync client)."""
    import threading

    async def main():
        gen, pvs = make_genesis(1, chain_id="rsn-chain")
        client = SignerClient("127.0.0.1:0")
        server = SignerServer(pvs[0], client.listen_addr)
        t = threading.Thread(
            target=lambda: asyncio.run(server.serve()), daemon=True
        )
        t.start()
        await asyncio.sleep(0.3)

        cfg = make_test_cfg(".")
        node = Node(cfg, gen, privval=client)
        await node.start()
        while node.height < 3:
            await asyncio.sleep(0.05)
        assert node.height >= 3
        await node.stop()
        server.stop()
        client.close()

    run(main())
