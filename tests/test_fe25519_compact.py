"""Compact (rolled) field arithmetic vs the tuple (unrolled) form.

Compact mode exists so the XLA CPU backend can compile the verify
kernel (docs/PERF.md "CPU-backend compile pathology"); it must be
VALUE-IDENTICAL to the tuple form — same partial products, same carry
schedule. These tests run both forms eagerly on the CPU backend and
diff them against each other and the big-int oracle. Default test
lane (no kernel compile involved).
"""

import random

import numpy as np
import jax.numpy as jnp

import pytest

from cometbft_tpu.ops import fe25519 as fe
from cometbft_tpu.ops import sc25519 as sc

P = fe.P
rng = random.Random(99)


def _vals(n):
    vals = [0, 1, 2, P - 1, P - 2, P, P + 1, 2 * P - 1, (1 << 255) - 1]
    while len(vals) < n:
        vals.append(rng.randrange(0, 1 << 256))
    return vals[:n]


def _limbs(vals):
    return fe.unstack(
        jnp.asarray(np.stack([fe.to_limbs(v) for v in vals], axis=1))
    )


@pytest.fixture(params=[False, True], ids=["tuple", "compact"])
def compact(request):
    fe.set_compact(request.param)
    try:
        yield request.param
    finally:
        fe.set_compact(None)


def test_mul_square_carry_match_oracle(compact):
    va, vb = _vals(24), list(reversed(_vals(24)))
    a, b = _limbs(va), _limbs(vb)
    for got, want in (
        (fe.mul(a, b), [x * y for x, y in zip(va, vb)]),
        (fe.square(a), [x * x for x in va]),
        (fe.carry(tuple(x + y for x, y in zip(a, b)), 3),
         [x + y for x, y in zip(va, vb)]),
        (fe.mul_scalar(a, 121666), [x * 121666 for x in va]),
    ):
        arr = np.asarray(fe.stack(got))
        for i, w in enumerate(want):
            assert fe.from_limbs(arr[:, i]) == w % P, i


def test_forms_bitwise_identical():
    """Not just mod-p equal: the exact redundant limb representation
    matches (same carry schedule), so either form can feed the other
    mid-computation."""
    va, vb = _vals(16), _vals(16)[::-1]
    a, b = _limbs(va), _limbs(vb)
    fe.set_compact(False)
    try:
        t_mul = np.asarray(fe.stack(fe.mul(a, b)))
        t_sq = np.asarray(fe.stack(fe.square(b)))
        fe.set_compact(True)
        c_mul = np.asarray(fe.stack(fe.mul(a, b)))
        c_sq = np.asarray(fe.stack(fe.square(b)))
    finally:
        fe.set_compact(None)
    np.testing.assert_array_equal(t_mul, c_mul)
    np.testing.assert_array_equal(t_sq, c_sq)


def test_scalar_reduce_matches(compact):
    xs = [rng.randrange(0, 1 << 512) for _ in range(12)] + [
        0, sc.L - 1, sc.L, sc.L + 1, (1 << 512) - 1
    ]
    rows = np.zeros((40, len(xs)), np.int64)
    for i, x in enumerate(xs):
        v = x
        for j in range(40):
            rows[j, i] = v & fe.MASK
            v >>= fe.LIMB_BITS
    got = sc.reduce_512(
        fe.unstack_n(jnp.asarray(rows.astype(np.int32)), 40)
    )
    arr = np.asarray(fe.stack(got))
    for i, x in enumerate(xs):
        assert sc.from_limbs(arr[:, i]) == x % sc.L, i
