"""WAL group-commit seam (docs/PERF.md "Live consensus fast path").

The crash contract under test: a sync-barrier message is ACKED (its
SyncTicket completes) only after a covering fsync — so a power cut
can never lose an acked record, and a cut between enqueue and group
fsync behaves exactly like the reference serial WAL losing an
unwritten record (nothing was externalized for it).
"""

import asyncio
import os
import time


from cometbft_tpu.config.config import test_config as make_test_cfg
from cometbft_tpu.consensus.wal import (
    MSG_END_HEIGHT,
    MSG_VOTE,
    WAL,
    WALMessage,
)
from cometbft_tpu.node.inprocess import LocalNet, build_node, make_genesis


def _msgs(path):
    return list(WAL.iter_messages(path))


def test_group_ticket_completes_after_fsync(tmp_path):
    path = str(tmp_path / "wal")
    w = WAL(path, group_commit_ms=5.0, fsync_slow_s=0.0)
    tickets = [
        w.write_group(WALMessage(kind=MSG_VOTE, height=1, round=r))
        for r in range(8)
    ]
    for t in tickets:
        assert t.wait(5.0), "group fsync never landed"
    # one coalesced fsync covered the whole burst
    assert w.group_fsyncs >= 1
    assert w.group_coalesced == 8
    assert w.group_fsyncs < 8, "barriers did not coalesce"
    w.close()
    assert len(_msgs(path)) == 8


def test_window_zero_is_strict_serial(tmp_path):
    path = str(tmp_path / "wal")
    w = WAL(path, group_commit_ms=0.0)
    t = w.write_group(WALMessage(kind=MSG_END_HEIGHT, height=1))
    # strict path: durable before write_group returns
    assert t.done()
    assert w.group_fsyncs == 0
    w.crash_close()  # power cut AFTER the ack
    assert len(_msgs(path)) == 1  # acked record survives the cut


def test_crash_between_enqueue_and_group_fsync_loses_unacked(tmp_path):
    """Power cut inside the coalescing window: the record was appended
    to the userspace buffer but never fsynced — it must vanish (like a
    reference serial WAL crash before WriteSync returned) and its
    ticket must NEVER complete (no acked-then-lost)."""
    path = str(tmp_path / "wal")
    w = WAL(path, group_commit_ms=60_000.0, fsync_slow_s=0.0)  # window >> test: no fsync
    t0 = w.write_group(WALMessage(kind=MSG_VOTE, height=1))
    w.flush_sync()  # an explicit barrier acks everything appended so far
    assert t0.done()
    t1 = w.write_group(WALMessage(kind=MSG_VOTE, height=2))
    assert not t1.done()
    w.crash_close()
    assert not t1.done(), "acked a record the cut destroyed"
    msgs = _msgs(path)
    assert [m.height for m in msgs] == [1], (
        "unacked record survived / acked record lost"
    )


def test_any_fsync_acks_pending_group_tickets(tmp_path):
    """Durability is prefix-ordered: a strict write_sync (e.g. the
    end-height marker) must complete every pending group ticket — its
    fsync covers their records too."""
    path = str(tmp_path / "wal")
    w = WAL(path, group_commit_ms=60_000.0, fsync_slow_s=0.0)
    t = w.write_group(WALMessage(kind=MSG_VOTE, height=3))
    assert not t.done()
    w.write_end_height(3)  # strict barrier
    assert t.done()
    w.crash_close()
    assert [m.kind for m in _msgs(path)] == [MSG_VOTE, MSG_END_HEIGHT]


def test_graceful_close_flushes_pending_group(tmp_path):
    path = str(tmp_path / "wal")
    w = WAL(path, group_commit_ms=60_000.0, fsync_slow_s=0.0)
    t = w.write_group(WALMessage(kind=MSG_VOTE, height=9))
    w.close()
    assert t.done()
    assert len(_msgs(path)) == 1


def test_torn_tail_repair_after_group_commit_crash(tmp_path):
    """A cut mid-append can leave a torn partial record after the last
    group fsync; repair_torn_tail must trim it exactly like the serial
    WAL's torn tail (satellite: power-cut parity)."""
    path = str(tmp_path / "wal")
    w = WAL(path, group_commit_ms=5.0, fsync_slow_s=0.0)
    t = w.write_group(WALMessage(kind=MSG_VOTE, height=1))
    assert t.wait(5.0)
    w.crash_close()
    with open(path, "ab") as f:
        f.write(b"\xde\xad\xbe\xef")  # torn partial record
    removed = WAL.repair_torn_tail(path)
    assert removed == 4
    msgs = _msgs(path)
    assert len(msgs) == 1 and msgs[0].height == 1
    # the repaired head appends cleanly again
    w2 = WAL(path, group_commit_ms=5.0, fsync_slow_s=0.0)
    t2 = w2.write_group(WALMessage(kind=MSG_VOTE, height=2))
    assert t2.wait(5.0)
    w2.close()
    assert [m.height for m in _msgs(path)] == [1, 2]


def test_rotation_under_group_commit(tmp_path):
    """Rotation's flush+rename barrier composes with the group seam:
    records never span files and every ticket still completes."""
    path = str(tmp_path / "wal")
    w = WAL(path, head_size_limit=256, group_commit_ms=5.0, fsync_slow_s=0.0)
    tickets = [
        w.write_group(
            WALMessage(kind=MSG_VOTE, height=h, data=b"x" * 64)
        )
        for h in range(1, 13)
    ]
    for t in tickets:
        assert t.wait(5.0)
    w.close()
    assert [m.height for m in _msgs(path)] == list(range(1, 13))
    assert any(
        p != path and os.path.exists(p)
        for p in [f"{path}.{i:03d}" for i in range(4)]
    ), "head never rotated"


def _run(coro, timeout=90):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def test_consensus_crash_mid_group_commit_recovers(tmp_path):
    """End-to-end: a node running with group commit + pipelined
    finalize crash-closes mid-flight; a rebuilt node must replay the
    fsync'd WAL prefix (+ privval reconciliation for a lost own-vote
    tail) and resume producing blocks. The slow-disk model makes the
    calibrated router actually engage the group seam on this box."""
    from cometbft_tpu.consensus import wal as walmod

    async def main():
        home = str(tmp_path)
        gen, pvs = make_genesis(1)

        def cfg_for():
            cfg = make_test_cfg(home)
            cfg.consensus.wal_group_commit_ms = 2.0
            cfg.consensus.finalize_pipeline = True
            cfg.base.db_backend = "sqlite"
            return cfg

        walmod.set_fsync_model(0.002)  # engage the calibrated seam
        try:
            node = build_node(
                gen, pvs[0], config=cfg_for(), home=home, wal=True
            )
            net = LocalNet([node])
            await net.start()
            await net.wait_for_height(2, timeout=30)
            await node.cs.crash()  # power cut: buffered WAL tail lost
            node.close_stores()
            h = node.block_store.height()

            node2 = build_node(
                gen, pvs[0], config=cfg_for(), home=home, wal=True
            )
            net2 = LocalNet([node2])
            await net2.start()
            await net2.wait_for_height(h + 2, timeout=30)
            await net2.stop()
            assert node2.block_store.height() >= h + 2
            assert node2.cs.wal.group_coalesced > 0, (
                "slow-disk model never engaged the group seam"
            )
            node2.close_stores()
        finally:
            walmod.set_fsync_model(0.0)

    _run(main())


def test_privval_rollback_when_precommitted_block_unrecoverable():
    """The group-commit recovery hole's hard case: the signer state
    holds a non-nil precommit whose block data the WAL lost (crash
    inside one group window, sole validator). Injecting it would
    wedge the node in COMMIT waiting for parts that exist nowhere;
    reconciliation must instead roll the signer back to the newest
    WAL-proven record — safe because a vote absent from the fsync'd
    WAL was provably never broadcast (externalization is gated on
    the covering fsync)."""
    import time as _time

    from cometbft_tpu import types as T
    from cometbft_tpu.privval.file_pv import STEP_PRECOMMIT

    gen, pvs = make_genesis(1)
    node = build_node(gen, pvs[0], wal=True)
    cs = node.cs
    pv = pvs[0]
    # sign a precommit for a block that exists nowhere (its WAL
    # records were "lost" — we simply never write them)
    bid = T.BlockID(b"\x07" * 32, T.PartSetHeader(1, b"\x08" * 32))
    idx, _ = cs.rs.validators.get_by_address(pv.pub_key().address())
    lost = T.Vote(
        type_=T.PRECOMMIT,
        height=cs.rs.height,
        round=0,
        block_id=bid,
        timestamp_ns=_time.time_ns(),
        validator_address=pv.pub_key().address(),
        validator_index=idx,
    )
    pv.sign_vote(gen.chain_id, lost)
    assert pv.last.step == STEP_PRECOMMIT
    cs._reconcile_privval_state()
    # not injected (would wedge COMMIT), signer rolled back to the
    # WAL's knowledge (nothing): a fresh round-0 prevote for a
    # DIFFERENT block must sign cleanly now
    vs = cs.rs.votes.precommits(0)
    assert vs is None or vs.votes[idx] is None
    assert pv.last.step == 0
    fresh = T.Vote(
        type_=T.PREVOTE,
        height=cs.rs.height,
        round=0,
        block_id=T.BlockID(b"\x09" * 32, T.PartSetHeader(1, b"\x0a" * 32)),
        timestamp_ns=_time.time_ns(),
        validator_address=pv.pub_key().address(),
        validator_index=idx,
    )
    pv.sign_vote(gen.chain_id, fresh)
    assert fresh.signature


def test_vote_batch_serial_equivalence():
    """In-round batched vote verification must produce verdicts
    identical to the serial path — valid votes land, corrupted ones
    are rejected, across both configurations."""

    async def run_net(window_ms):
        gen, pvs = make_genesis(4)
        nodes = []
        for pv in pvs:
            cfg = make_test_cfg(".")
            cfg.consensus.vote_batch_window_ms = window_ms
            nodes.append(build_node(gen, pv, config=cfg))
        net = LocalNet(nodes)
        await net.start()
        await net.wait_for_height(2, timeout=60)
        await net.stop()
        hashes = [
            nodes[0].block_store.load_block_meta(h).block_id.hash
            for h in (1, 2)
        ]
        for n in nodes[1:]:
            for i, h in enumerate((1, 2)):
                assert (
                    n.block_store.load_block_meta(h).block_id.hash
                    == hashes[i]
                )
        coalesced = sum(
            n.cs._vote_coalescer.submitted
            for n in nodes
            if n.cs._vote_coalescer is not None
        )
        return hashes, coalesced

    async def main():
        _, serial_coalesced = await run_net(0.0)
        assert serial_coalesced == 0  # window 0 = serial inline path
        _, batched_coalesced = await run_net(2.0)
        assert batched_coalesced > 0, (
            "batched run never exercised the coalescing verifier"
        )

    _run(main())


def test_prestaged_invalid_vote_dropped():
    """A corrupted-signature peer vote routed through the batch
    verifier must be dropped with the same outcome as the serial
    path's inline rejection (serial-equivalent verdicts)."""

    async def main():
        from cometbft_tpu import types as T
        from cometbft_tpu.consensus.state import VoteMessage

        gen, pvs = make_genesis(2)
        cfg = make_test_cfg(".")
        cfg.consensus.vote_batch_window_ms = 2.0
        node = build_node(gen, pvs[0], config=cfg)
        net = LocalNet([node])
        await net.start()
        # forge a vote from validator 1 with a garbage signature
        addr1 = pvs[1].pub_key().address()
        idx, _ = node.cs.rs.validators.get_by_address(addr1)
        bad = T.Vote(
            type_=T.PREVOTE,
            height=node.cs.rs.height,
            round=0,
            block_id=T.NIL_BLOCK_ID,
            timestamp_ns=time.time_ns(),
            validator_address=addr1,
            validator_index=idx,
            signature=b"\x00" * 64,
        )
        node.cs.enqueue_nowait("vote", VoteMessage(bad), "peerX")
        await asyncio.sleep(0.3)
        vs = node.cs.rs.votes.prevotes(0)
        assert vs is None or vs.votes[idx] is None, (
            "invalid-signature vote was admitted"
        )
        await net.stop()

    _run(main())
