"""Blocksync + light client tests over in-memory peers.

These are the bulk paths: commits flow through the TPU batch verifier
with cross-height coalescing (CPU backend in tests, same code path).
"""

import asyncio

import pytest

from cometbft_tpu import types as T
from cometbft_tpu.blocksync import BlockPool, BlockSyncReactor
from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.light import (
    Client,
    StoreBackedProvider,
    TrustOptions,
    verifier,
)
from cometbft_tpu.light.detector import DivergenceError
from cometbft_tpu.node.inprocess import build_node, make_genesis
from cometbft_tpu.utils.chaingen import (
    StorePeerClient,
    TamperingPeerClient,
    make_chain,
)

N_VALS = 4
CHAIN_LEN = 30


@pytest.fixture(scope="module")
def source_chain():
    gen, pvs = make_genesis(N_VALS, chain_id="sync-chain")
    privs = [pv.priv_key for pv in pvs]
    node = make_chain(gen, privs, CHAIN_LEN, txs_per_block=1)
    return gen, pvs, node


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def test_blocksync_catches_up(source_chain):
    gen, pvs, src = source_chain

    async def main():
        fresh = build_node(gen, None)
        caught = asyncio.Event()
        reactor = BlockSyncReactor(
            fresh.state,
            fresh.block_exec,
            fresh.block_store,
            on_caught_up=lambda st: caught.set(),
        )
        reactor.pool.set_peer_range(
            "src", StorePeerClient(src), 1, src.block_store.height()
        )
        await reactor.start()
        await asyncio.wait_for(caught.wait(), 60)
        await reactor.stop()
        # synced to within one block of the source (last block needs the
        # NEXT height's commit, matching the reference's +1 semantics)
        assert fresh.block_store.height() >= src.block_store.height() - 1
        assert reactor.blocks_applied >= CHAIN_LEN - 1
        # app state converged: our app_hash after applying h must match
        # what the source chain recorded in the header of h+1
        h = fresh.block_store.height()
        assert (
            fresh.state_store.load().app_hash
            == src.block_store.load_block(h + 1).header.app_hash
        )
        for hh in (1, h // 2, h):
            assert (
                fresh.block_store.load_block(hh).hash()
                == src.block_store.load_block(hh).hash()
            )

    run(main())


def test_blocksync_bans_tampering_peer(source_chain):
    gen, pvs, src = source_chain

    async def main():
        fresh = build_node(gen, None)
        caught = asyncio.Event()
        reactor = BlockSyncReactor(
            fresh.state,
            fresh.block_exec,
            fresh.block_store,
            on_caught_up=lambda st: caught.set(),
        )
        reactor.pool.set_peer_range(
            "evil",
            TamperingPeerClient(src, bad_height=5),
            1,
            src.block_store.height(),
        )
        reactor.pool.set_peer_range(
            "good", StorePeerClient(src), 1, src.block_store.height()
        )
        await reactor.start()
        await asyncio.wait_for(caught.wait(), 90)
        await reactor.stop()
        assert fresh.block_store.height() >= src.block_store.height() - 1
        # the chain content is the honest one
        assert (
            fresh.block_store.load_block(5).hash()
            == src.block_store.load_block(5).hash()
        )

    run(main())


def test_light_client_bisection(source_chain):
    gen, pvs, src = source_chain
    provider = StoreBackedProvider(
        gen.chain_id, src.block_store, src.state_store
    )
    trusted = provider.light_block(1)
    client = Client(
        gen.chain_id,
        TrustOptions(
            period_ns=10**18, height=1, hash=trusted.hash()
        ),
        provider,
    )
    target_h = src.block_store.height()
    lb = client.verify_light_block_at_height(target_h)
    assert lb.height == target_h
    assert lb.hash() == src.block_store.load_block_meta(target_h).block_id.hash
    # skipping mode: with a static valset the jump is direct (1 hop)
    assert client.hops <= 3
    # cache was active
    assert client.cache.hits + client.cache.misses > 0


def test_light_client_sequential(source_chain):
    gen, pvs, src = source_chain
    provider = StoreBackedProvider(
        gen.chain_id, src.block_store, src.state_store
    )
    trusted = provider.light_block(1)
    from cometbft_tpu.light import SEQUENTIAL

    client = Client(
        gen.chain_id,
        TrustOptions(period_ns=10**18, height=1, hash=trusted.hash()),
        provider,
        verification_mode=SEQUENTIAL,
    )
    lb = client.verify_light_block_at_height(10)
    assert lb.height == 10
    assert client.hops == 9


def test_light_client_detects_witness_divergence(source_chain):
    gen, pvs, src = source_chain
    # a forked witness chain: same genesis, different blocks
    privs = [pv.priv_key for pv in pvs]
    fork = make_chain(gen, privs, 12, txs_per_block=2)
    provider = StoreBackedProvider(
        gen.chain_id, src.block_store, src.state_store
    )
    witness = StoreBackedProvider(
        gen.chain_id, fork.block_store, fork.state_store
    )
    trusted = provider.light_block(1)
    client = Client(
        gen.chain_id,
        TrustOptions(period_ns=10**18, height=1, hash=trusted.hash()),
        provider,
        witnesses=[witness],
    )
    # height 1 should agree? No: forks diverge from block 1 (different
    # txs) -> divergence must be detected and evidence reported
    with pytest.raises(DivergenceError):
        client.verify_light_block_at_height(10)
    assert witness.reported or provider.reported
    # lifecycle: the diverging witness is dropped from rotation after
    # the evidence is built (reference light/client.go:1019-1185)
    assert witness not in client.witnesses


def test_dead_witness_pruned_during_verification(source_chain):
    """VERDICT r4 missing #2 (witness lifecycle): a persistently
    unresponsive witness strikes out mid-verification and is pruned
    from rotation; verification itself succeeds via the healthy
    witness, and a runtime replacement can be installed."""
    from cometbft_tpu.light import SEQUENTIAL
    from cometbft_tpu.light.client import LightClientError

    gen, pvs, src = source_chain
    provider = StoreBackedProvider(
        gen.chain_id, src.block_store, src.state_store
    )

    class DeadWitness:
        calls = 0

        def light_block(self, height):
            DeadWitness.calls += 1
            raise ConnectionError("witness unreachable")

        def report_evidence(self, ev):
            pass

    good = StoreBackedProvider(
        gen.chain_id, src.block_store, src.state_store
    )
    dead = DeadWitness()
    trusted = provider.light_block(1)
    client = Client(
        gen.chain_id,
        TrustOptions(period_ns=10**18, height=1, hash=trusted.hash()),
        provider,
        witnesses=[good, dead],
        verification_mode=SEQUENTIAL,
    )
    # one cross-check (and so one strike) per verified target height
    for h in (5, 8, 10):
        lb = client.verify_light_block_at_height(h)
        assert lb.height == h
    assert dead not in client.witnesses, "dead witness not pruned"
    assert good in client.witnesses
    assert DeadWitness.calls == client.MAX_WITNESS_STRIKES

    # runtime replacement keeps the rotation healthy
    client.add_witness(
        StoreBackedProvider(
            gen.chain_id, src.block_store, src.state_store
        )
    )
    assert len(client.witnesses) == 2
    client.verify_light_block_at_height(15)

    # a client whose LAST witness strikes out must ERROR, not decay
    # into silently-unwitnessed verification
    lone = Client(
        gen.chain_id,
        TrustOptions(period_ns=10**18, height=1, hash=trusted.hash()),
        provider,
        witnesses=[DeadWitness()],
        verification_mode=SEQUENTIAL,
    )
    with pytest.raises(LightClientError, match="no witnesses remain"):
        for h in (5, 8, 10):
            lone.verify_light_block_at_height(h)


def test_unresponsive_primary_replaced_by_witness(source_chain):
    """Reference findNewPrimary (light/client.go:1000-1045): when the
    primary stops serving blocks, the first responsive witness is
    PROMOTED to primary (leaving the witness rotation) and the old
    primary is demoted to the back of the witness list, where the
    ordinary lifecycle judges it. With no promotable witness, the
    client errors instead of spinning."""
    from cometbft_tpu.light.client import LightClientError

    gen, pvs, src = source_chain

    class FlakyPrimary:
        """Healthy until killed."""

        def __init__(self, real):
            self.real = real
            self.dead = False

        def light_block(self, height):
            if self.dead:
                raise ConnectionError("primary down")
            return self.real.light_block(height)

        def report_evidence(self, ev):
            pass

    real = StoreBackedProvider(
        gen.chain_id, src.block_store, src.state_store
    )
    primary = FlakyPrimary(real)
    witness = FlakyPrimary(real)
    trusted = real.light_block(1)
    client = Client(
        gen.chain_id,
        TrustOptions(period_ns=10**18, height=1, hash=trusted.hash()),
        primary,
        witnesses=[witness],
    )
    client.verify_light_block_at_height(5)
    primary.dead = True
    lb = client.verify_light_block_at_height(10)
    assert lb.height == 10
    assert client.primary is witness, "witness was not promoted"
    # the demoted primary joined the rotation's tail
    assert client.witnesses == [primary]

    # the promoted primary dies too (its only witness, the demoted
    # old primary, is already dead): error out, never spin
    witness.dead = True
    with pytest.raises(LightClientError, match="no witness could"):
        client.verify_light_block_at_height(15)


def test_pruned_primary_promoted_and_notfound_never_strikes(
    source_chain,
):
    """A primary that PRUNED the requested height (not-found, not an
    outage) is replaced by a witness that retains it (reference treats
    ErrLightBlockNotFound as a findNewPrimary trigger); a height NO
    provider has surfaces as not-found and never strikes witnesses —
    a future-height poll must not burn the witness set."""
    from cometbft_tpu.light.provider import LightBlockNotFound

    gen, pvs, src = source_chain
    real = StoreBackedProvider(
        gen.chain_id, src.block_store, src.state_store
    )

    class PrunedPrimary:
        def light_block(self, height):
            if 0 < height < 8:
                raise LightBlockNotFound(f"height {height} pruned")
            return real.light_block(height)

        def report_evidence(self, ev):
            pass

    witness = StoreBackedProvider(
        gen.chain_id, src.block_store, src.state_store
    )
    trusted = real.light_block(10)

    pruned = PrunedPrimary()
    # FIRST witness is pruned too: the probe must keep scanning and
    # promote the later witness that retains the height
    client = Client(
        gen.chain_id,
        TrustOptions(
            period_ns=10**18, height=10, hash=trusted.hash()
        ),
        pruned,
        witnesses=[PrunedPrimary(), witness],
    )
    lb = client.verify_light_block_at_height(5)  # backwards walk
    assert lb.height == 5
    assert client.primary is witness, "pruned primary not replaced"

    # future-height poll: not-found surfaces, no strikes, set intact
    class NotFoundEverywhere:
        def light_block(self, height):
            raise LightBlockNotFound("beyond tip")

        def report_evidence(self, ev):
            pass

    client2 = Client(
        gen.chain_id,
        TrustOptions(
            period_ns=10**18, height=10, hash=trusted.hash()
        ),
        real,
        witnesses=[NotFoundEverywhere()],
    )
    for _ in range(5):
        with pytest.raises(LightBlockNotFound):
            client2.verify_light_block_at_height(10_000)
    assert len(client2.witnesses) == 1, "witness burned by polls"


def test_proposer_priority_divergence_halts(source_chain):
    """Same header, different proposer priorities: priorities are not
    header-committed, so neither side can be proven wrong — the client
    halts (reference ErrProposerPrioritiesDiverge)."""
    import dataclasses

    from cometbft_tpu.light.detector import (
        ProposerPrioritiesDivergeError,
    )

    gen, pvs, src = source_chain
    provider = StoreBackedProvider(
        gen.chain_id, src.block_store, src.state_store
    )

    class SkewedWitness:
        def __init__(self, real):
            self.real = real

        def light_block(self, height):
            lb = self.real.light_block(height)
            vs = lb.validator_set.copy()
            vs.validators[0] = dataclasses.replace(
                vs.validators[0],
                proposer_priority=(
                    vs.validators[0].proposer_priority + 99
                ),
            )
            return dataclasses.replace(lb, validator_set=vs)

        def report_evidence(self, ev):
            pass

    trusted = provider.light_block(1)
    client = Client(
        gen.chain_id,
        TrustOptions(period_ns=10**18, height=1, hash=trusted.hash()),
        provider,
        witnesses=[SkewedWitness(provider)],
    )
    with pytest.raises(ProposerPrioritiesDivergeError):
        client.verify_light_block_at_height(6)

    # a witness agreeing on the header but serving a valset that does
    # NOT hash to the header's validators_hash is provably lying:
    # removed (errBadWitness), never a halt
    class FabricatedValsetWitness:
        def __init__(self, real):
            self.real = real

        def light_block(self, height):
            lb = self.real.light_block(height)
            vs = T.ValidatorSet(lb.validator_set.validators[:-1])
            return dataclasses.replace(lb, validator_set=vs)

        def report_evidence(self, ev):
            pass

    good = StoreBackedProvider(
        gen.chain_id, src.block_store, src.state_store
    )
    liar = FabricatedValsetWitness(provider)
    client2 = Client(
        gen.chain_id,
        TrustOptions(period_ns=10**18, height=1, hash=trusted.hash()),
        provider,
        witnesses=[good, liar],
    )
    lb = client2.verify_light_block_at_height(6)
    assert lb.height == 6
    assert liar not in client2.witnesses
    assert good in client2.witnesses


def test_invalid_conflict_witness_removed_without_halt(source_chain):
    """A witness serving a SELF-INVALID conflicting block (commit not
    for the header) is provably bad: removed immediately, no evidence,
    verification proceeds (reference errBadWitness)."""
    import dataclasses

    gen, pvs, src = source_chain
    provider = StoreBackedProvider(
        gen.chain_id, src.block_store, src.state_store
    )

    class BadBlockWitness:
        def __init__(self, real):
            self.real = real

        def light_block(self, height):
            lb = self.real.light_block(height)
            return dataclasses.replace(
                lb,
                header=dataclasses.replace(
                    lb.header, time_ns=lb.header.time_ns + 1
                ),
            )

        def report_evidence(self, ev):
            pass

    good = StoreBackedProvider(
        gen.chain_id, src.block_store, src.state_store
    )
    bad = BadBlockWitness(provider)
    trusted = provider.light_block(1)
    client = Client(
        gen.chain_id,
        TrustOptions(period_ns=10**18, height=1, hash=trusted.hash()),
        provider,
        witnesses=[good, bad],
    )
    lb = client.verify_light_block_at_height(10)
    assert lb.height == 10
    assert bad not in client.witnesses
    assert good in client.witnesses


def test_verifier_rejects_forged_commit(source_chain):
    gen, pvs, src = source_chain
    provider = StoreBackedProvider(
        gen.chain_id, src.block_store, src.state_store
    )
    lb1 = provider.light_block(1)
    lb5 = provider.light_block(5)
    # forge: drop enough signatures to fall under 2/3
    sigs = [
        T.CommitSig.absent()
        if i < 2
        else cs
        for i, cs in enumerate(lb5.commit.signatures)
    ]
    forged = T.Commit(
        lb5.commit.height, lb5.commit.round, lb5.commit.block_id, sigs
    )
    from cometbft_tpu.light.types import LightBlock

    bad = LightBlock(
        header=lb5.header, commit=forged, validator_set=lb5.validator_set
    )
    with pytest.raises(Exception):
        verifier.verify_non_adjacent(
            gen.chain_id,
            lb1,
            lb1.validator_set,
            bad,
            bad.validator_set,
            10**18,
        )


def test_coalesced_commit_verification(source_chain):
    """Direct test of the cross-height batch path with TPU lanes forced."""
    gen, pvs, src = source_chain
    jobs = []
    for h in range(2, 12):
        commit = src.block_store.load_block(h).last_commit
        meta = src.block_store.load_block_meta(h - 1)
        jobs.append(
            (
                src.state_store.load_validators(h - 1),
                meta.block_id,
                h - 1,
                commit,
            )
        )
    errors = T.validation.verify_commits_coalesced(
        gen.chain_id, jobs, light=False
    )
    assert errors == [None] * len(jobs)
    # now corrupt one commit in the middle
    bad_commit = jobs[4][3]
    cs = bad_commit.signatures[0]
    bad_sigs = [
        T.CommitSig(
            cs.block_id_flag,
            cs.validator_address,
            cs.timestamp_ns,
            bytes([cs.signature[0] ^ 1]) + cs.signature[1:],
        )
    ] + list(bad_commit.signatures[1:])
    jobs[4] = (
        jobs[4][0],
        jobs[4][1],
        jobs[4][2],
        T.Commit(
            bad_commit.height,
            bad_commit.round,
            bad_commit.block_id,
            bad_sigs,
        ),
    )
    errors = T.validation.verify_commits_coalesced(
        gen.chain_id, jobs, light=False
    )
    assert errors[4] is not None
    assert [e is None for e in errors] == [
        i != 4 for i in range(len(jobs))
    ]
