"""Storage lifecycle plane (ISSUE 17, store/retention.py): min-wins
target reconciliation with its two floors, marker-atomic pruning
across blocks/index/states/WAL, crash-mid-prune resume idempotency
(in-process abort AND a true FAIL_TEST_INDEX power cut), anchored
index replay over a pruned store, snapshot store rotation + restart
survival, structured RPC below-base errors on every height route, and
the compressed-time soak slice (full 10k soak behind ``slow``)."""

import asyncio
import hashlib
import json
import os
import subprocess
import sys
from types import SimpleNamespace

import pytest

from cometbft_tpu.config.config import test_config as make_test_config
from cometbft_tpu.node.inprocess import build_node, make_genesis
from cometbft_tpu.statesync.snapshots import SnapshotStore
from cometbft_tpu.store.retention import RetentionPlane
from cometbft_tpu.utils.chaingen import make_chain

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(home, **storage):
    cfg = make_test_config(str(home))
    cfg.base.db_backend = "sqlite"
    cfg.tx_index.indexer = "kv"
    s = cfg.storage
    s.prune_interval_s = 3600.0  # reconciles are test-driven only
    for k, v in storage.items():
        setattr(s, k, v)
    return cfg


def _grow(home, heights, **storage):
    genesis, pvs = make_genesis(1)
    privs = [pv.priv_key for pv in pvs]
    node = build_node(
        genesis, None, config=_cfg(home, **storage), home=str(home)
    )
    make_chain(genesis, privs, heights, node=node)
    return genesis, privs, node


# --- target reconciliation (unit) ---------------------------------------


def _plane(retain=10, snap_interval=0, snap_store=None, app_retain=0):
    cfg = SimpleNamespace(
        retain_blocks=retain,
        retain_states=0,
        retain_index=0,
        prune_batch=4,
        prune_interval_s=3600.0,
        snapshot_interval=snap_interval,
        snapshot_keep_recent=2,
    )
    p = RetentionPlane(cfg, None, None, snapshot_store=snap_store)
    p._app_retain = app_retain
    return p


def test_target_min_wins_app_retain():
    # node window alone
    assert _plane(retain=10)._target(100, 10) == 90
    # app is MORE conservative: app wins
    assert _plane(retain=10, app_retain=40)._target(100, 10) == 40
    # app is LESS conservative: node window wins
    assert _plane(retain=10, app_retain=95)._target(100, 10) == 90
    # no node window, app only
    assert _plane(retain=0, app_retain=40)._target(100, 0) == 40
    # neither: nothing prunable
    assert _plane(retain=0)._target(100, 0) == 0


def test_target_snapshot_floor(tmp_path):
    ss = SnapshotStore(str(tmp_path), keep_recent=2)
    # snapshotting on, nothing held yet: NO pruning (the only
    # bootstrap anchor must exist before anything is discarded)
    p = _plane(retain=10, snap_interval=20, snap_store=ss)
    assert p._target(100, 10) == 0
    ss.save(60, b"blob")
    assert p._target(100, 10) == 60  # capped under the held snapshot


def test_target_serve_floor():
    p = _plane(retain=10)
    with p.serving(50):
        assert p._target(100, 10) == 50
        with p.serving(30):
            assert p._target(100, 10) == 30
        assert p._target(100, 10) == 50
    assert p._target(100, 10) == 90


# --- full-node pruning + markers ----------------------------------------


def test_reconcile_prunes_all_legs_and_markers(tmp_path):
    _, _, node = _grow(
        tmp_path, 120,
        retain_blocks=30, retain_states=40, retain_index=30,
        prune_batch=8, snapshot_interval=10, snapshot_keep_recent=2,
    )
    out = node.retention.reconcile_once()
    bs = node.block_store
    assert bs.base() == 90 and bs.height() == 120
    assert out["blocks"] == 89
    assert bs.load_block(90) is not None
    assert bs.load_block(89) is None
    assert node.tx_indexer.base_height() == 90
    assert node.tx_indexer.last_indexed_height() == 120
    # retained rows still queryable, pruned rows gone
    assert out["index"] > 0
    # snapshot rotation: newest two, rooted under <home>/snapshots
    hs = node.snapshot_store.heights()
    assert hs == [110, 120]
    # second pass is a no-op (idempotent targets)
    out2 = node.retention.reconcile_once()
    assert out2["blocks"] == 0 and out2["index"] == 0
    node.close_stores()


def test_app_retain_height_caps_node_window(tmp_path):
    """kvstore's retain_height knob flows through ABCI Commit ->
    BlockExecutor hook -> plane: min wins, the app's wider window
    overrides the node's aggressive one."""
    from cometbft_tpu.models.kvstore import KVStoreApplication

    genesis, pvs = make_genesis(1)
    privs = [pv.priv_key for pv in pvs]
    app = KVStoreApplication(retain_height=50)
    node = build_node(
        genesis, None, app=app,
        config=_cfg(tmp_path, retain_blocks=4, prune_batch=16),
        home=str(tmp_path),
    )
    make_chain(genesis, privs, 100, node=node)
    assert node.retention._app_retain == 50  # 100 - 50
    node.retention.reconcile_once()
    # node window alone would put base at 96; the app caps it at 50
    assert node.block_store.base() == 50
    node.close_stores()


# --- crash mid-prune -----------------------------------------------------


def test_crash_mid_prune_inprocess_resume(tmp_path):
    """Abort a pass between bounded batches via the chaos seam: every
    committed batch carried its own base advance, so the partial pass
    reads consistent and the resume finishes the same targets."""
    _, _, node = _grow(
        tmp_path, 60, retain_blocks=10, retain_index=10, prune_batch=5
    )

    class Boom(RuntimeError):
        pass

    calls = [0]

    def hook():
        calls[0] += 1
        if calls[0] > 2:
            raise Boom()

    node.retention.batch_hook = hook
    with pytest.raises(Boom):
        node.retention.reconcile_once()
    node.retention.batch_hook = None
    bs = node.block_store
    mid = bs.base()
    assert 1 < mid < 50  # partial progress, committed batches only
    assert bs.load_block(mid) is not None
    if mid > 1:
        assert bs.load_block(mid - 1) is None
    # resume: same targets, completes, idempotent
    node.retention.reconcile_once()
    assert bs.base() == 50
    assert node.tx_indexer.base_height() == 50
    out = node.retention.reconcile_once()
    assert out["blocks"] == 0 and out["index"] == 0
    node.close_stores()


@pytest.mark.parametrize("fail_index", [0, 2])
def test_crash_mid_prune_powercut_then_resume(tmp_path, fail_index):
    """The real thing: os._exit at the retention-prune-batch fail
    point (before the first / third bounded batch), then a rebuild
    from the same home must handshake cleanly and a resume pass must
    finish pruning with consistent markers."""
    home = str(tmp_path / "home")
    os.makedirs(home)
    script = f"""
import os
from cometbft_tpu.node.inprocess import build_node, make_genesis
from cometbft_tpu.utils.chaingen import make_chain
from cometbft_tpu.utils import fail
from cometbft_tpu.config.config import test_config as make_test_config
genesis, pvs = make_genesis(1)
# persist the genesis so the parent can rebuild the same node
with open({home!r} + "/genesis.json", "w") as f:
    f.write(genesis.to_json())
cfg = make_test_config({home!r})
cfg.base.db_backend = "sqlite"
cfg.tx_index.indexer = "kv"
cfg.storage.retain_blocks = 10
cfg.storage.retain_index = 10
cfg.storage.prune_batch = 5
cfg.storage.prune_interval_s = 3600.0
node = build_node(genesis, None, config=cfg, home={home!r})
privs = [pv.priv_key for pv in pvs]
make_chain(genesis, privs, 60, node=node)
os.environ["FAIL_TEST_INDEX"] = "{fail_index}"
fail.reset()
node.retention.reconcile_once()
raise SystemExit("fail point never hit")
"""
    proc = subprocess.run(
        [sys.executable, "-c", script],
        cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 99, proc.stderr

    from cometbft_tpu.types.genesis import GenesisDoc

    with open(os.path.join(home, "genesis.json")) as f:
        genesis = GenesisDoc.from_json(f.read())
    cfg = _cfg(home, retain_blocks=10, retain_index=10, prune_batch=5)
    node = build_node(genesis, None, config=cfg, home=home)
    bs = node.block_store
    # whatever batches committed are consistent: base readable,
    # below-base gone, markers never past the true first row
    mid = bs.base()
    assert bs.height() == 60
    assert bs.load_block(max(1, mid)) is not None
    if mid > 1:
        assert bs.load_block(mid - 1) is None
    assert node.tx_indexer.base_height() <= 50
    node.retention.reconcile_once()
    assert bs.base() == 50
    assert node.tx_indexer.base_height() == 50
    node.close_stores()


# --- anchored index replay over a pruned store ---------------------------


def test_indexer_replay_anchors_at_pruned_base(tmp_path):
    """A lost idx:last marker forces a full replay — which must
    anchor at the store base, not height 1 (the pruned prefix has no
    blocks to read)."""
    from cometbft_tpu.state.indexer import LAST_INDEXED_KEY

    _, _, node = _grow(
        tmp_path, 60, retain_blocks=20, retain_index=20, prune_batch=16
    )
    node.retention.reconcile_once()
    assert node.block_store.base() == 40
    # simulate marker loss (fresh index db / crash before any flush)
    node.tx_indexer.db.delete(LAST_INDEXED_KEY)
    assert node.tx_indexer.last_indexed_height() == 0
    n = node.indexer_service.replay(node.block_store, node.state_store)
    assert n == 21  # heights 40..60, NOT 1..60
    assert node.tx_indexer.last_indexed_height() == 60
    node.close_stores()


# --- snapshot store ------------------------------------------------------


def test_snapshot_store_rotation_and_restart(tmp_path):
    root = str(tmp_path / "snaps")
    ss = SnapshotStore(root, keep_recent=2)
    for h, blob in ((10, b"a" * 3000), (20, b"b" * 3000), (30, b"c" * 100)):
        ss.save(h, blob)
    assert ss.heights() == [20, 30]  # keep_recent rotation
    assert ss.latest_height() == 30
    # chunked read side round-trips and hash-verifies
    snaps = ss.list_snapshots()
    assert [s.height for s in snaps] == [20, 30]
    assert snaps[0].chunks == 3  # 3000 / 1024
    blob = ss.load_blob(20)
    assert blob == b"b" * 3000
    assert hashlib.sha256(blob).digest() == snaps[0].hash
    assert ss.load_chunk(20, format_=9, index=0) == b""  # format miss
    # restart survival: a fresh store over the same root serves the
    # same snapshots (the whole point of node-side persistence)
    ss2 = SnapshotStore(root, keep_recent=2)
    assert ss2.heights() == [20, 30]
    assert ss2.load_blob(30) == b"c" * 100


def test_snapshot_store_sweeps_incomplete_on_open(tmp_path):
    root = str(tmp_path / "snaps")
    ss = SnapshotStore(root, keep_recent=2)
    ss.save(10, b"complete")
    # a crash mid-save leaves chunks without meta.json
    d = os.path.join(root, f"{20:015d}")
    os.makedirs(d)
    with open(os.path.join(d, "chunk.0000"), "wb") as f:
        f.write(b"torn")
    ss2 = SnapshotStore(root, keep_recent=2)
    assert ss2.heights() == [10]
    assert not os.path.exists(d)


# --- RPC below-base hardening --------------------------------------------


def _env_for(node, genesis):
    from cometbft_tpu.rpc.env import Environment

    return Environment(
        chain_id=genesis.chain_id,
        block_store=node.block_store,
        state_store=node.state_store,
        tx_indexer=node.tx_indexer,
        block_indexer=node.block_indexer,
        genesis=genesis,
        proxy=node.proxy,
        config=node.config,
        retention=node.retention,
    )


def test_rpc_pruned_height_routes(tmp_path):
    from cometbft_tpu.rpc import core

    # retain_index WIDER than retain_blocks: index rows legitimately
    # outlive block bodies, so a block_search hit can land on a
    # pruned body (the case the structured error exists for)
    genesis, _, node = _grow(
        tmp_path, 40,
        retain_blocks=10, retain_states=10, retain_index=20,
        prune_batch=16,
    )
    node.retention.reconcile_once()
    env = _env_for(node, genesis)
    base = node.block_store.base()
    ibase = node.tx_indexer.base_height()
    assert base == 30 and ibase == 20

    for route in (core.block, core.block_results, core.commit):
        with pytest.raises(core.RPCError) as ei:
            route(env, height=base - 1)
        assert "pruned" in str(ei.value)
        assert json.loads(ei.value.data)["pruned"] is True
        assert json.loads(ei.value.data)["base"] == str(base)
    # retained heights still serve
    assert core.block(env, height=base)["block"] is not None

    # block_search: an index hit whose block body is pruned says so
    with pytest.raises(core.RPCError) as ei:
        asyncio.run(
            core.block_search(env, query=f"block.height={base - 1}")
        )
    assert "pruned" in str(ei.value)

    # tx: pruned index rows answer with the idx:base verdict
    with pytest.raises(core.RPCError) as ei:
        asyncio.run(core.tx(env, hash="00" * 32))
    assert "pruned below" in str(ei.value)
    assert json.loads(ei.value.data)["index_base"] == str(ibase)

    # status: the advertised earliest height IS the base, and the
    # health verdict carries the lifecycle stats
    st = core.status(env)
    assert st["sync_info"]["earliest_block_height"] == str(base)
    node.close_stores()


def test_light_proxy_forwards_pruned_error(tmp_path):
    """The light proxy must forward the structured below-base verdict
    verbatim, not re-wrap it as a generic upstream failure."""
    from cometbft_tpu.rpc.client import RPCClientError
    from cometbft_tpu.rpc.core import RPCError

    err = RPCError(-32603, "height 3 is pruned (base=9)",
                   data='{"pruned": true, "base": "9"}')
    # the client error carries code/message/data; _respond forwards
    ce = RPCClientError(err.code, str(err), data=err.data)
    assert ce.code == -32603
    assert "pruned" in ce.message
    assert json.loads(ce.data)["base"] == "9"


# --- restart survival (handshake over a pruned store) --------------------


def test_pruned_node_restart_replays_retained_tail_only(tmp_path):
    """Restarting a pruned node must NOT try to replay from block 1:
    build_node persists the default app's height, so the handshake
    replays app_height+1..store_height — all retained."""
    genesis, privs, node = _grow(
        tmp_path, 50, retain_blocks=10, prune_batch=16
    )
    node.retention.reconcile_once()
    assert node.block_store.base() == 40
    assert os.path.exists(os.path.join(str(tmp_path), "app_state.json"))
    node.close_stores()
    node2 = build_node(
        genesis, None,
        config=_cfg(tmp_path, retain_blocks=10, prune_batch=16),
        home=str(tmp_path),
    )
    assert node2.block_store.base() == 40
    assert node2.block_store.height() == 50
    # and the chain extends cleanly from the rebuilt node
    make_chain(genesis, privs, 5, node=node2)
    assert node2.block_store.height() == 55
    node2.close_stores()


# --- chaos nemesis e2e ---------------------------------------------------


def _run_chaos(schedule_events, seed, tmp_path, **kw):
    from cometbft_tpu.chaos import FaultSchedule, run_schedule
    from cometbft_tpu.chaos.schedule import FaultEvent

    schedule = FaultSchedule(
        [FaultEvent(**e) for e in schedule_events]
    )
    return asyncio.run(
        asyncio.wait_for(
            run_schedule(
                schedule, seed=seed, base_dir=str(tmp_path), **kw
            ),
            300,
        )
    )


def test_chaos_crash_mid_prune_and_snapshot_during_prune(tmp_path):
    """The two lifecycle nemesis actions run invariant-clean on a live
    4-node net (knobs auto-set by run_schedule) and their trace
    records carry only seeded parameters (byte-identical replay)."""
    events = [
        {"action": "crash_mid_prune", "at_height": 12, "node": 1},
        {"action": "snapshot_during_prune", "at_height": 14, "node": 0},
    ]
    r1 = _run_chaos(events, 1337, tmp_path / "a")
    assert r1.ok, r1.violations
    acts = [(t["action"], t.get("node")) for t in r1.trace]
    assert ("crash_mid_prune", "n1") in acts
    assert ("snapshot_during_prune", "n0") in acts
    r2 = _run_chaos(events, 1337, tmp_path / "b")
    assert r1.trace == r2.trace, "same seed must replay identically"


@pytest.mark.slow
def test_chaos_statesync_join_from_pruned_source(tmp_path):
    """A fresh joiner statesyncs from a node whose history below the
    snapshot is PRUNED (trust root anchored at the source's base),
    then blocksync-follows the tail."""
    events = [
        {"action": "crash_mid_prune", "at_height": 12, "node": 1},
        {"action": "statesync_join", "at_height": 15, "via": [1, 2]},
    ]
    report = _run_chaos(events, 7, tmp_path)
    assert report.ok, report.violations
    joined = [t for t in report.trace if t["action"] == "statesync_join"]
    assert joined and joined[0]["joined"] == "j4"
    assert report.final_heights[joined[0]["joined"]] >= 15


# --- compressed-time soak ------------------------------------------------


def test_soak_slice_bounded_disk_and_markers():
    """Tier-1 slice of the lifecycle soak: a few hundred heights with
    reconciles interleaved — disk plateaus once the window saturates,
    markers stay consistent, WAL rotation survives pruning, RPC
    answers below-base with the structured error."""
    from cometbft_tpu.chaos.soak import run_soak

    report = run_soak(
        seed=11, heights=300, step=50, warmup_frac=0.5,
        disk_factor=1.6, rss_factor=2.0,
    )
    assert report["ok"], report["violations"]
    assert report["retention"]["pruned_blocks_total"] > 0
    assert report["retention"]["pruned_wal_files"] > 0
    last = report["checkpoints"][-1]
    assert last["base"] == 300 - 64  # height - retain window


@pytest.mark.slow
def test_soak_10k_heights():
    """The full compressed-time 10k-height soak (ISSUE 17
    acceptance): bounded disk AND RSS over ~200 reconciles."""
    from cometbft_tpu.chaos.soak import run_soak

    report = run_soak(seed=1337, heights=10_000, step=50)
    assert report["ok"], report["violations"]
    assert report["checkpoints"][-1]["base"] == 10_000 - 64
