"""Pallas ladder vs XLA ladder: bit-identical output.

The Pallas kernel (ops/pallas_ladder) re-schedules the Straus ladder
for VMEM residency but must compute the exact same function as
ops/ed25519._straus. Runs the Pallas interpreter on the CPU backend
(Mosaic itself needs TPU hardware); kernel-compiling lane, see
pytest.ini.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from cometbft_tpu.crypto import ref_ed25519 as ref
from cometbft_tpu.ops import curve25519 as curve
from cometbft_tpu.ops import ed25519 as ed
from cometbft_tpu.ops import fe25519 as fe
from cometbft_tpu.ops import sc25519 as sc
from cometbft_tpu.ops.pallas_ladder import straus_pallas

pytestmark = [pytest.mark.tpu, pytest.mark.slow]  # tpu implies slow: keeps the `-m 'not slow'` fast lane kernel-free


def test_pallas_block_divisor_fallback(monkeypatch):
    """A configured block height that does not divide the sublane-row
    count must fall back to a valid divisor — NOT silently drop
    remainder rows (code-review r4 finding) — and since the r5
    silicon contact the chosen height must ALSO satisfy Mosaic's
    sublane constraint (multiple of 8, or the whole dim). N=384
    (3 rows) with blocks of 2: the largest divisor <= 2 is 1, which
    Mosaic rejects, so the block grows to the whole dim (3 rows, one
    grid step). Verdicts must stay bit-identical across the width."""
    import jax

    from cometbft_tpu.ops import pallas_ladder

    monkeypatch.setattr(pallas_ladder, "BLOCK_SUBLANES", 2)
    # since round 5 the block height is a STATIC jit arg of
    # _ladder_call, so the monkeypatched value keys its own cache
    # entry — no clear_caches needed (kept as a cheap belt: the
    # backend-key change is exactly what made this safe)
    jax.clear_caches()
    _ladder_equivalence(384)


def test_pallas_divisor_fallback_respects_mosaic_floor(monkeypatch):
    """The live fallback case on silicon: N=2048 (16 rows) with a
    configured block of 12. 12 does not divide 16; the largest
    divisor <= 12 is 8, which is also a multiple of 8 — so the
    kernel runs a 2-step grid of 8-row blocks (no remainder rows
    dropped, Mosaic constraint honored) and must be bit-identical."""
    import jax

    from cometbft_tpu.ops import pallas_ladder

    monkeypatch.setattr(pallas_ladder, "BLOCK_SUBLANES", 12)
    jax.clear_caches()
    _ladder_equivalence(2048)


def test_pallas_ladder_matches_xla_ladder():
    _ladder_equivalence(128)


def test_pallas_8_sublane_blocking_matches(monkeypatch):
    """The bench sweep's s8 leg (GRAFT_PALLAS_SUBLANES=8) at a width
    where 8-sublane blocking actually engages (1024 lanes = 8 rows =
    one full block): bit-identical to the XLA ladder."""
    from cometbft_tpu.ops import pallas_ladder

    monkeypatch.setattr(pallas_ladder, "BLOCK_SUBLANES", 8)
    _ladder_equivalence(1024)


def test_in_process_backend_flip(monkeypatch):
    """VERDICT r4 weak #6: GRAFT_PALLAS flipped mid-process must reach
    the NEXT verify_batch — the verify jit cache is keyed by ladder
    backend, so this cannot silently reuse the pre-flip trace — and
    both backends must return bit-identical verdicts (including a
    corrupted signature).

    Since r5, LAST_DISPATCH's backend_key[0] reports the ladder the
    kernel ACTUALLY used at the dispatch's per-device width (the
    pallas kernel needs 128-multiple per-device lanes). Under the
    conftest's 8-device virtual mesh the default 128-lane pad leaves
    16 lanes/device — pallas genuinely cannot engage there — so pad
    to 1024 lanes (128/device) to exercise the real flip."""
    monkeypatch.setattr(ed, "PAD_MIN", 1024)
    items = []
    rng = np.random.default_rng(5)
    for _ in range(9):
        sk = rng.bytes(32)
        pk = ref.public_from_seed(sk)
        m = bytes(rng.bytes(40))
        items.append((m, pk, ref.sign(sk, m)))
    m, pk, sig = items[4]
    items[4] = (m, pk, sig[:32] + bytes(32))  # corrupt one

    monkeypatch.delenv("GRAFT_PALLAS", raising=False)
    out_xla = ed.verify_batch(items)
    assert ed.LAST_DISPATCH["backend_key"][0] == "xla"

    monkeypatch.setenv("GRAFT_PALLAS", "1")
    out_pal = ed.verify_batch(items)
    assert ed.LAST_DISPATCH["backend_key"][0] == "pallas"
    np.testing.assert_array_equal(out_xla, out_pal)

    expected = [True] * 9
    expected[4] = False
    assert out_xla.tolist() == expected

    # flip back: the xla trace is still cached under its own key
    monkeypatch.delenv("GRAFT_PALLAS")
    out_back = ed.verify_batch(items)
    assert ed.LAST_DISPATCH["backend_key"][0] == "xla"
    np.testing.assert_array_equal(out_back, out_xla)


def _ladder_equivalence(N):
    rng = np.random.default_rng(17)
    sk = rng.bytes(32)
    pk = ref.public_from_seed(sk)
    pkb = jnp.asarray(
        np.tile(np.frombuffer(pk, np.uint8)[:, None], (1, N))
    )
    A, okA = curve.decompress(pkb)
    assert bool(np.asarray(okA).all())

    s_bytes = np.zeros((32, N), np.uint8)
    for i in range(N):
        v = int(rng.integers(0, 2**62)) ** 4 % sc.L
        s_bytes[:, i] = np.frombuffer(v.to_bytes(32, "little"), np.uint8)
    s = fe.from_bytes_256(jnp.asarray(s_bytes))
    h = sc.neg_mod_L(
        sc.reduce_512(
            sc.hash_bytes_to_limbs(
                jnp.asarray(np.vstack([s_bytes, s_bytes]))
            )
        )
    )
    ds, dh = sc.digits4(s), sc.digits4(h)

    q_ref = ed._straus(ds, dh, A, (N,))
    q_pal = straus_pallas(ds, dh, A, (N,), interpret=True)
    for k in range(3):
        np.testing.assert_array_equal(
            np.asarray(fe.stack(q_ref[k])),
            np.asarray(fe.stack(q_pal[k])),
            err_msg=f"component {k}",
        )
