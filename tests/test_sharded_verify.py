"""Production multi-chip verify path (VERDICT r1 missing #1).

On the virtual 8-device CPU mesh (conftest), the PRODUCTION seam —
crypto/batch.TpuBatchVerifier -> ops/ed25519.verify_batch — must
lane-shard over all local devices via shard_map and return verdicts
identical to the single-device/host path. The driver's
dryrun_multichip exercises the same code path.
"""

import numpy as np
import pytest

import jax

from cometbft_tpu import types as T
from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.crypto import ref_ed25519 as ref
from cometbft_tpu.crypto.keys import Ed25519PubKey
from cometbft_tpu.ops import ed25519 as ed

pytestmark = pytest.mark.tpu  # compiles the full kernel; see pytest.ini


@pytest.fixture(autouse=True)
def _tpu_backend():
    old_min = crypto_batch._MIN_TPU_BATCH
    crypto_batch.set_default_backend("tpu")
    crypto_batch.set_min_tpu_batch(1)
    yield
    crypto_batch.set_min_tpu_batch(old_min)
    crypto_batch.set_default_backend("cpu")


def test_verify_batch_shards_over_all_devices():
    rng = np.random.default_rng(3)
    items = []
    bad = {2, 9}
    for i in range(24):
        sk = rng.bytes(32)
        pk = ref.public_from_seed(sk)
        m = bytes(rng.bytes(23))
        sig = ref.sign(sk, m)
        if i in bad:
            sig = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
        items.append((m, pk, sig))
    got = ed.verify_batch(items)
    assert ed.LAST_DISPATCH["sharded"] is True
    assert ed.LAST_DISPATCH["n_devices"] == len(jax.devices())
    assert ed.LAST_DISPATCH["lanes"] % len(jax.devices()) == 0
    want = [i not in bad for i in range(24)]
    assert list(got) == want


def test_verify_commits_coalesced_sharded_matches_host():
    """Same commits, sharded TPU path vs host path: identical verdicts
    (including the bad-signature job)."""
    from cometbft_tpu.node.inprocess import make_genesis
    from cometbft_tpu.utils.chaingen import make_chain

    gen, pvs = make_genesis(6, chain_id="shard")
    parts = make_chain(gen, pvs, 4)
    store = parts.block_store
    vs = gen.validator_set()
    jobs = []
    for h in range(1, 4):
        jobs.append(
            (
                vs,
                store.load_block_meta(h).block_id,
                h,
                store.load_seen_commit(h),
            )
        )
    # corrupt one signature in an extra copy of the last job's commit
    import copy

    bad_commit = copy.deepcopy(store.load_seen_commit(3))
    s = bytearray(bad_commit.signatures[0].signature)
    s[0] ^= 1
    bad_commit.signatures[0].signature = bytes(s)
    jobs.append(
        (vs, store.load_block_meta(3).block_id, 3, bad_commit)
    )

    tpu_errors = T.verify_commits_coalesced(gen.chain_id, jobs)
    assert ed.LAST_DISPATCH["sharded"] is True

    crypto_batch.set_default_backend("cpu")
    host_errors = T.verify_commits_coalesced(gen.chain_id, jobs)

    assert [e is None for e in tpu_errors] == [
        e is None for e in host_errors
    ]
    assert tpu_errors[:3] == [None, None, None]
    assert tpu_errors[3] is not None
