"""Production multi-chip verify path (VERDICT r1 missing #1).

On the virtual 8-device CPU mesh (conftest), the PRODUCTION seam —
crypto/batch.TpuBatchVerifier -> ops/ed25519.verify_batch — must
lane-shard over all local devices via shard_map and return verdicts
identical to the single-device/host path. The driver's
dryrun_multichip exercises the same code path.
"""

import numpy as np
import pytest

import jax

from cometbft_tpu import types as T
from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.crypto import ref_ed25519 as ref
from cometbft_tpu.crypto.keys import Ed25519PubKey
from cometbft_tpu.ops import ed25519 as ed

# Since round 4 the compact field mode (ops/fe25519) makes the kernel
# graph CPU-compilable (~40-60s per shape cold, seconds warm — the old
# platform skip guarded a >128 GB / >90 min compile, docs/PERF.md), so
# the sharded kernel executes on the virtual 8-device mesh everywhere.
# The first test runs in the DEFAULT lane — every CI pass proves real
# sharded-kernel execution (VERDICT r3 #4; the full dryrun in
# tests/test_dryrun.py does too). The remaining tests compile extra
# kernel shapes and stay in the `-m tpu` lane to keep the default lane
# fast; that lane now also runs fine on a CPU box.


@pytest.fixture(autouse=True)
def _tpu_backend():
    old_min = crypto_batch._MIN_TPU_BATCH
    crypto_batch.set_default_backend("tpu")
    crypto_batch.set_min_tpu_batch(1)
    yield
    crypto_batch.set_min_tpu_batch(old_min)
    crypto_batch.set_default_backend("cpu")


def test_verify_batch_shards_over_all_devices():
    rng = np.random.default_rng(3)
    items = []
    bad = {2, 9}
    for i in range(24):
        sk = rng.bytes(32)
        pk = ref.public_from_seed(sk)
        m = bytes(rng.bytes(23))
        sig = ref.sign(sk, m)
        if i in bad:
            sig = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
        items.append((m, pk, sig))
    got = ed.verify_batch(items)
    assert ed.LAST_DISPATCH["sharded"] is True
    assert ed.LAST_DISPATCH["n_devices"] == len(jax.devices())
    assert ed.LAST_DISPATCH["lanes"] % len(jax.devices()) == 0
    want = [i not in bad for i in range(24)]
    assert list(got) == want


@pytest.mark.tpu
@pytest.mark.slow
def test_plain_kernel_branch_at_bulk_widths(monkeypatch):
    """Above PRECOMP_MAX_LANES per device, verify_batch switches to the
    plain kernel (device-side pubkey validation included). Exercised at
    tiny shapes by shrinking the cutoff + padding."""
    monkeypatch.setattr(ed, "PRECOMP_MAX_LANES", 1)
    monkeypatch.setattr(ed, "PAD_MIN", 16)
    rng = np.random.default_rng(4)
    items = []
    bad = {1, 5}
    for i in range(12):
        sk = rng.bytes(32)
        pk = ref.public_from_seed(sk)
        m = bytes(rng.bytes(23))
        sig = ref.sign(sk, m)
        if i == 1:
            sig = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
        if i == 5:
            pk = b"\x00" * 31 + b"\xff"  # invalid point encoding
        items.append((m, pk, sig))
    got = ed.verify_batch(items)
    assert ed.LAST_DISPATCH["precomp"] is False
    want = [ref.verify_zip215(pk, m, sig) for m, pk, sig in items]
    assert not want[1]  # corrupted signature
    assert list(got) == want


@pytest.mark.tpu
@pytest.mark.slow
def test_precomp_tuple_mode_matches_stacked(monkeypatch):
    """docs/PERF.md lever #6 (round 5): GRAFT_PRECOMP_TUPLE=1 hands A
    to the kernel as a pytree of 80 (N,) arrays instead of one stacked
    (4,20,N) input. Verdicts must be bit-identical to the stacked
    precomp kernel through the SHARDED production seam, and the
    backend-keyed dispatch must flip cleanly mid-process."""
    rng = np.random.default_rng(6)
    items = []
    bad = {3}
    for i in range(12):
        sk = rng.bytes(32)
        pk = ref.public_from_seed(sk)
        m = bytes(rng.bytes(19))
        sig = ref.sign(sk, m)
        if i in bad:
            sig = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
        items.append((m, pk, sig))

    monkeypatch.setenv("GRAFT_PRECOMP_TUPLE", "1")
    got = ed.verify_batch(items)
    assert ed.LAST_DISPATCH["mode"] == "precomp_tuple"
    assert ed.LAST_DISPATCH["sharded"] is True

    monkeypatch.delenv("GRAFT_PRECOMP_TUPLE")
    want = ed.verify_batch(items)
    assert ed.LAST_DISPATCH["mode"] == "precomp"
    np.testing.assert_array_equal(got, want)
    assert list(want) == [i not in bad for i in range(12)]


@pytest.mark.tpu
@pytest.mark.slow
def test_verify_commits_coalesced_sharded_matches_host():
    """Same commits, sharded TPU path vs host path: identical verdicts
    (including the bad-signature job)."""
    from cometbft_tpu.node.inprocess import make_genesis
    from cometbft_tpu.utils.chaingen import make_chain

    gen, pvs = make_genesis(6, chain_id="shard")
    parts = make_chain(gen, [pv.priv_key for pv in pvs], 4)
    store = parts.block_store
    vs = gen.validator_set()
    jobs = []
    for h in range(1, 4):
        jobs.append(
            (
                vs,
                store.load_block_meta(h).block_id,
                h,
                store.load_seen_commit(h),
            )
        )
    # corrupt one signature in an extra copy of the last job's commit
    # (CommitSig is frozen: rebuild the lane via dataclasses.replace)
    import copy
    import dataclasses

    bad_commit = copy.deepcopy(store.load_seen_commit(3))
    s = bytearray(bad_commit.signatures[0].signature)
    s[0] ^= 1
    bad_commit.signatures[0] = dataclasses.replace(
        bad_commit.signatures[0], signature=bytes(s)
    )
    jobs.append(
        (vs, store.load_block_meta(3).block_id, 3, bad_commit)
    )

    from cometbft_tpu.types.validation import verify_commits_coalesced

    tpu_errors = verify_commits_coalesced(gen.chain_id, jobs)
    assert ed.LAST_DISPATCH["sharded"] is True

    crypto_batch.set_default_backend("cpu")
    host_errors = verify_commits_coalesced(gen.chain_id, jobs)

    assert [e is None for e in tpu_errors] == [
        e is None for e in host_errors
    ]
    assert tpu_errors[:3] == [None, None, None]
    assert tpu_errors[3] is not None
