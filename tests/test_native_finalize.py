"""Native finalize lane vs pure Python (native/finalize.cpp).

The one GIL-releasing finalize pass — per-tx SHA-256, ExecTxResult
encodes, LastResultsHash, ABCI event encodes, part leaf hashes — must
be byte-identical to the portable Python twin AND to the pre-lane
implementations it replaced (execution.results_hash, _enc_abci_event,
r.encode(), hashlib.sha256). The portable path stays the semantic
source of truth and the no-compiler fallback; the loader mirrors the
wirecodec prewarm discipline and must degrade gracefully around a
corrupted build artifact (the crash-mid-build test below).

Native-backed cases skip cleanly when the extension cannot build; the
portable/degraded-path cases always run.
"""

import hashlib
import os
import random

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.crypto import merkle
from cometbft_tpu.state import execution, native_finalize
from cometbft_tpu.state.execution import (
    _enc_abci_event,
    decode_finalize_response,
    encode_finalize_response,
)
from cometbft_tpu.state.indexer import _enc_tx_result

nat = native_finalize.module()
needs_native = pytest.mark.skipif(
    nat is None, reason="native finalize unavailable (no compiler)"
)

rng = random.Random(20)


def _rand_attr(i):
    roll = rng.random()
    if roll < 0.2:
        return (b"bk%d" % i, b"bv%d" % i)  # bare 2-tuple, idx=True
    if roll < 0.4:
        return ("k%d" % i, "vé%d" % i, rng.random() < 0.5)  # unicode
    return abci.EventAttribute(
        key="key%d" % i,
        value="value-%d" % rng.randrange(1000),
        index=rng.random() < 0.7,
    )


def _rand_event():
    return abci.Event(
        type_=rng.choice(["app", "transfer", "vént", ""]),
        attributes=[_rand_attr(i) for i in range(rng.randrange(0, 4))],
    )


def _rand_result(force_empty_events=False):
    return abci.ExecTxResult(
        code=rng.choice([0, 0, 0, 1, 5]),
        data=bytes(rng.randbytes(rng.randrange(0, 24))),
        gas_wanted=rng.randrange(0, 2**40),
        gas_used=rng.randrange(0, 2**40),
        codespace=rng.choice(["", "", "bank", "cøde"]),
        events=(
            []
            if force_empty_events
            else [_rand_event() for _ in range(rng.randrange(0, 3))]
        ),
    )


def _rand_block(n_txs=None):
    n = rng.randrange(0, 9) if n_txs is None else n_txs
    txs = [bytes(rng.randbytes(rng.randrange(0, 64))) for _ in range(n)]
    # force some empty-event txs so the index-keyed field-5 alignment
    # (skip-by-index) is always exercised
    results = [
        _rand_result(force_empty_events=(i % 3 == 1)) for i in range(n)
    ]
    resp = abci.ResponseFinalizeBlock(
        events=[_rand_event() for _ in range(rng.randrange(0, 3))],
        tx_results=results,
        app_hash=bytes(rng.randbytes(32)),
    )
    return txs, resp


def _check_parity(txs, resp, arts):
    """arts (either backend) against the pre-lane derivations."""
    assert arts.tx_hashes == [hashlib.sha256(t).digest() for t in txs]
    assert arts.results_enc == [r.encode() for r in resp.tx_results]
    assert arts.results_hash == execution.results_hash(resp.tx_results)
    assert arts.tx_events_enc == [
        [_enc_abci_event(e) for e in r.events] for r in resp.tx_results
    ]
    assert arts.block_events_enc == [
        _enc_abci_event(e) for e in resp.events
    ]


# --- differential fuzz -------------------------------------------------


@needs_native
def test_native_vs_portable_byte_identical():
    for _ in range(40):
        txs, resp = _rand_block()
        a_nat = native_finalize.finalize_pass(txs, resp)
        a_py = native_finalize.finalize_pass(txs, resp, portable=True)
        assert a_nat.native and not a_py.native
        for attr in (
            "tx_hashes",
            "results_enc",
            "results_hash",
            "tx_events_flat",
            "tx_events_enc",
            "block_events_flat",
            "block_events_enc",
        ):
            assert getattr(a_nat, attr) == getattr(a_py, attr), attr
        _check_parity(txs, resp, a_nat)


def test_portable_pass_matches_legacy_derivations():
    """The degraded (no-g++) path: portable artifacts must equal the
    pre-lane per-item implementations byte for byte."""
    for _ in range(25):
        txs, resp = _rand_block()
        arts = native_finalize.finalize_pass(txs, resp, portable=True)
        _check_parity(txs, resp, arts)


def test_encode_finalize_response_artifacts_identical():
    """Stored-response bytes with artifacts == without, and the
    decode roundtrip (incl. index-keyed empty-event alignment)."""
    for portable in (True, False):
        for _ in range(20):
            txs, resp = _rand_block()
            arts = native_finalize.finalize_pass(
                txs, resp, portable=portable
            )
            plain = encode_finalize_response(resp)
            with_arts = encode_finalize_response(resp, arts)
            assert plain == with_arts
            back = decode_finalize_response(with_arts)
            assert [r.encode() for r in back.tx_results] == [
                r.encode() for r in resp.tx_results
            ]
            assert [
                [_enc_abci_event(e) for e in r.events]
                for r in back.tx_results
            ] == [
                [_enc_abci_event(e) for e in r.events]
                for r in resp.tx_results
            ]


def test_enc_tx_result_precomputed_events_identical():
    for _ in range(20):
        r = _rand_result()
        enc = [_enc_abci_event(e) for e in r.events]
        assert _enc_tx_result(r, enc) == _enc_tx_result(r)


def test_indexer_rows_with_precomputed_forms_identical():
    from cometbft_tpu.utils.kv import MemKV

    from cometbft_tpu.state.indexer import BlockIndexer, TxIndexer

    txi = TxIndexer(MemKV())
    bi = BlockIndexer(MemKV())
    for _ in range(15):
        txs, resp = _rand_block(n_txs=4)
        arts = native_finalize.finalize_pass(txs, resp, portable=True)
        for i, tx in enumerate(txs):
            plain = txi.tx_sets(7, i, tx, resp.tx_results[i])
            pre = txi.tx_sets(
                7, i, tx, resp.tx_results[i],
                tx_hash=arts.tx_hashes[i],
                events_flat=arts.tx_events_flat[i],
                events_enc=arts.tx_events_enc[i],
            )
            assert plain == pre
        assert bi.block_sets(7, resp.events) == bi.block_sets(
            7, resp.events, events_flat=arts.block_events_flat
        )


def test_flatten_events_single_pass_form():
    evs = [_rand_event() for _ in range(6)]
    flat = native_finalize.flatten_events(evs)
    assert [native_finalize.encode_event_flat(fe) for fe in flat] == [
        _enc_abci_event(e) for e in evs
    ]


# --- part hashing ------------------------------------------------------


@needs_native
def test_part_leaf_hashes_native_parity():
    chunks = [bytes(rng.randbytes(n)) for n in (0, 1, 100, 65536, 7)]
    lh = native_finalize.part_leaf_hashes(chunks)
    assert lh == [merkle.leaf_hash(c) for c in chunks]


def test_proofs_from_leaf_hashes_identical():
    for n in (1, 2, 3, 5, 8, 13):
        items = [bytes(rng.randbytes(50)) for _ in range(n)]
        r1, p1 = merkle.proofs_from_byte_slices(items)
        r2, p2 = merkle.proofs_from_leaf_hashes(
            [merkle.leaf_hash(it) for it in items]
        )
        assert r1 == r2
        assert p1 == p2
        assert r1 == merkle.hash_from_byte_slices(items)
        for i, p in enumerate(p2):
            assert p.verify(r2, items[i])


def test_partset_from_data_matches_python_proofs(monkeypatch):
    """PartSet.from_data must produce identical header/proofs whether
    the native leaf hasher engaged or not."""
    from cometbft_tpu.types.part_set import PartSet

    data = bytes(rng.randbytes(3 * 65536 + 123))
    ps_maybe_native = PartSet.from_data(data)
    monkeypatch.setattr(native_finalize, "_mod", None)
    monkeypatch.setattr(native_finalize, "_tried", True)
    ps_py = PartSet.from_data(data)
    assert ps_maybe_native.header == ps_py.header
    for a, b in zip(ps_maybe_native.parts, ps_py.parts):
        assert (a.index, a.bytes_, a.proof) == (b.index, b.bytes_, b.proof)


# --- loader discipline (crash-mid-build, prewarm, env gate) ------------


def _fresh_loader_state(monkeypatch, so_path):
    monkeypatch.setattr(native_finalize, "_SO", str(so_path))
    monkeypatch.setattr(native_finalize, "_mod", None)
    monkeypatch.setattr(native_finalize, "_tried", False)


def test_corrupt_build_artifact_degrades_then_recovers(
    tmp_path, monkeypatch
):
    """Crash-mid-build shape (mirrors the wirecodec discipline): a
    truncated/garbage .so left by a killed build must not take the
    node down — module() returns None, every caller keeps the
    byte-identical portable path — and a later clean build recovers."""
    so = tmp_path / "_finalize.so"
    so.write_bytes(b"\x7fELFgarbage-not-a-real-object")
    # make the artifact look NEWER than the source so the loader
    # tries to load it as-is instead of rebuilding over it
    src_mtime = os.path.getmtime(native_finalize._SRC)
    os.utime(so, (src_mtime + 60, src_mtime + 60))
    _fresh_loader_state(monkeypatch, so)
    assert native_finalize.module() is None
    assert native_finalize._tried  # no retry storm on the hot path
    txs, resp = _rand_block(n_txs=3)
    arts = native_finalize.finalize_pass(txs, resp)
    assert not arts.native
    _check_parity(txs, resp, arts)
    if nat is None:
        return  # no compiler: recovery leg can't build
    # operator clears the corrupt artifact; the next cold start's
    # prewarm rebuilds and the lane comes back
    so.unlink()
    _fresh_loader_state(monkeypatch, so)
    t = native_finalize.prewarm()
    assert t is not None
    t.join(120)
    mod = native_finalize.module()
    assert mod is not None
    a_nat = native_finalize.finalize_pass(txs, resp)
    assert a_nat.native
    assert a_nat.results_hash == arts.results_hash
    assert a_nat.results_enc == arts.results_enc


def test_env_gate_disables_native(tmp_path, monkeypatch):
    monkeypatch.setenv("GRAFT_NATIVE_FINALIZE", "0")
    _fresh_loader_state(monkeypatch, tmp_path / "_finalize.so")
    assert native_finalize.module() is None
    txs, resp = _rand_block(n_txs=2)
    arts = native_finalize.finalize_pass(txs, resp)
    assert not arts.native
    _check_parity(txs, resp, arts)


def test_prewarm_is_idempotent_once_tried(monkeypatch):
    monkeypatch.setattr(native_finalize, "_tried", True)
    assert native_finalize.prewarm() is None


# --- vectorized hot-state apply ----------------------------------------


def test_vecbank_scalar_vs_vector_digest_identical():
    from cometbft_tpu.models.vecbank import (
        VecBankApplication,
        make_block_txs,
        make_transfer,
    )

    r = random.Random(11)
    vec = VecBankApplication(n_accounts=512)
    ser = VecBankApplication(n_accounts=512, scalar=True)
    if vec._np is None:
        pytest.skip("numpy unavailable")
    assert vec.app_hash == ser.app_hash
    for h in range(1, 8):
        txs = make_block_txs(r, 64, 512)
        txs.append(b"bogus")  # invalid length
        txs.append(make_transfer(9999, 0, 5))  # out-of-range account
        ra = vec.finalize_block(
            abci.RequestFinalizeBlock(txs=txs, height=h)
        )
        rb = ser.finalize_block(
            abci.RequestFinalizeBlock(txs=txs, height=h)
        )
        assert ra.app_hash == rb.app_hash
        assert [t.code for t in ra.tx_results] == [
            t.code for t in rb.tx_results
        ]
        vec.commit()
        ser.commit()
    assert vec.height == ser.height == 7
    # wraparound transfer: commutativity holds mod 2^64 either way
    big = make_transfer(1, 2, (1 << 64) - 3)
    for app in (vec, ser):
        app.finalize_block(
            abci.RequestFinalizeBlock(txs=[big, big], height=8)
        )
        app.commit()
    assert vec.app_hash == ser.app_hash
