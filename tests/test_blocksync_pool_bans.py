"""BlockPool ban lifecycle (ISSUE 2 satellite): expiry re-admits a
peer, mid-request bans reroute the height to another peer, bans
survive peer churn, and an all-banned pool never starves (the
liveness guard in _pick_peer)."""

import asyncio

import pytest

from cometbft_tpu.blocksync import pool as pool_mod
from cometbft_tpu.blocksync.pool import BlockPool


class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def monotonic(self):
        return self.now


@pytest.fixture
def clock(monkeypatch):
    c = FakeClock()
    monkeypatch.setattr(pool_mod, "_now", c.monotonic)
    return c


class StubClient:
    """request_block resolves instantly, or hangs when told to."""

    def __init__(self, name, hang=False):
        self.name = name
        self.hang = hang
        self.requests = []

    async def request_block(self, height):
        self.requests.append(height)
        if self.hang:
            await asyncio.Event().wait()  # never resolves
        return ("block", self.name, height)


def run(coro, timeout=30):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def _mk_pool(clock, *clients, height=20):
    p = BlockPool(1)
    # no event loop in the sync pick-logic tests: inhibit requester
    # task spawning (set_peer_range/redo_request would create_task)
    p._stopped = True
    for c in clients:
        p.peers[c.name] = pool_mod.PoolPeer(
            c.name, c, base=1, height=height
        )
    return p


def test_ban_expiry_readmits_peer(clock):
    a, b = StubClient("a"), StubClient("b")
    p = _mk_pool(clock, a, b)
    p.ban_peer("a")
    assert p.banned_peers() == ["a"]
    # while banned, b is always picked
    for _ in range(10):
        assert p._pick_peer(1).peer_id == "b"
    # after expiry the ban lapses and a competes again
    clock.now += pool_mod.BAN_DURATION_S + 1
    assert p.banned_peers() == []
    picked = {p._pick_peer(1).peer_id for _ in range(50)}
    assert "a" in picked


def test_bans_survive_peer_churn(clock):
    a, b = StubClient("a"), StubClient("b")
    p = _mk_pool(clock, a, b)
    p.ban_peer("a", "bad block")
    # the banned peer disconnects and re-dials (churn): the ban must
    # NOT be laundered by the reconnect
    p.remove_peer("a")
    p.set_peer_range("a", a, 1, 20)
    assert "a" in p.banned_peers()
    for _ in range(10):
        assert p._pick_peer(1).peer_id == "b"


def test_all_banned_pool_does_not_starve(clock):
    a, b = StubClient("a"), StubClient("b")
    p = _mk_pool(clock, a, b)
    p.ban_peer("a")
    clock.now += 10.0
    p.ban_peer("b")
    # liveness guard: least-recently-banned peer still serves
    got = p._pick_peer(1)
    assert got is not None and got.peer_id == "a"
    # a height nobody serves is still None
    assert p._pick_peer(999) is None


def test_starvation_guard_still_respects_soft_exclusions(clock):
    """All peers banned AND one soft-excluded for the height: the
    guard must prefer the banned-but-capable peer over the one known
    to be structurally unable to serve it."""
    a, b = StubClient("a"), StubClient("b")
    p = _mk_pool(clock, a, b)
    p.ban_peer("a")
    clock.now += 10.0
    p.ban_peer("b")
    # 'a' would win on ban recency, but it is excluded for height 5
    p.exclude_peer_for_height(5, "a")
    assert p._pick_peer(5).peer_id == "b"
    # other heights keep the recency order
    assert p._pick_peer(6).peer_id == "a"
    # everyone excluded: exclusion yields (never a liveness risk)
    p.exclude_peer_for_height(5, "b")
    assert p._pick_peer(5) is not None


def test_expired_bans_are_pruned_not_just_ignored(clock):
    """Peer churn over a long sync must not grow banned_until
    unboundedly: expired entries are deleted on the next scan."""
    a = StubClient("a")
    p = _mk_pool(clock, a)
    for i in range(50):
        p.ban_peer(f"ghost-{i}")
    assert len(p.banned_until) == 50
    clock.now += pool_mod.BAN_DURATION_S + 1
    p.ban_peer("a")
    assert p.banned_peers() == ["a"]
    assert len(p.banned_until) == 1  # the 50 ghosts were pruned


def test_ban_mid_request_reroutes_height(monkeypatch):
    """A peer banned while its request is in flight: redo_request drops
    its buffered blocks and the refetch lands on the other peer."""
    # keep the in-flight request's own timeout short so the hung
    # requester re-picks (now rerouted away from the banned peer) fast
    monkeypatch.setattr(pool_mod, "REQUEST_TIMEOUT_S", 0.3)

    async def main():
        slow = StubClient("slow", hang=True)
        fast = StubClient("fast")
        p = BlockPool(1)
        p.set_peer_range("slow", slow, 1, 5)
        # 'slow' is the only peer: every requester hangs in flight on it
        await asyncio.sleep(0.1)
        assert set(slow.requests) == {1, 2, 3, 4, 5}
        assert 1 in p._tasks and not p.blocks

        # a second peer appears; buffered blocks from 'slow' at later
        # heights simulate earlier deliveries
        p.set_peer_range("fast", fast, 1, 5)
        p.blocks[3] = (("block", "slow", 3), "slow")

        # mid-request ban + reroute (the reactor's bad-block path)
        p.redo_request(1, ban_peer="slow")
        assert "slow" in p.banned_peers()
        assert 3 not in p.blocks  # buffered blocks from the peer dropped

        async def fetched():
            while 1 not in p.blocks:
                await asyncio.sleep(0.01)

        await asyncio.wait_for(fetched(), 10)
        blk, peer_id = p.blocks[1]
        assert peer_id == "fast" and blk == ("block", "fast", 1)
        # height 3 was respawned and also rerouted to 'fast'
        await asyncio.sleep(0.1)
        assert 3 in fast.requests or 3 in p.blocks
        p.stop()

    run(main())


def test_redo_request_keeps_other_peers_blocks(clock):
    a, b = StubClient("a"), StubClient("b")
    p = _mk_pool(clock, a, b, height=10)
    p.blocks[2] = (("block", "a", 2), "a")
    p.blocks[3] = (("block", "b", 3), "b")
    p.redo_request(2, ban_peer="a")
    assert 3 in p.blocks  # the innocent peer's block survives
    assert 2 not in p.blocks
