"""Out-of-process ABCI: socket + gRPC servers/clients (reference
abci/server, abci/client/socket_client.go, grpc_client.go)."""

import asyncio
import threading

import pytest

from cometbft_tpu.abci import codec
from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.server import ABCIServer, GRPCServer
from cometbft_tpu.abci.socket_client import (
    GRPCClient,
    SocketClient,
    connect_app_conns,
)
from cometbft_tpu.models.kvstore import KVStoreApplication
from cometbft_tpu.state.state_types import ConsensusParams


def test_codec_roundtrip_all_kinds():
    cases = [
        (codec.ECHO, "hello"),
        (codec.FLUSH, None),
        (codec.INFO, abci.RequestInfo(version="1.0", block_version=11)),
        (
            codec.INIT_CHAIN,
            abci.RequestInitChain(
                time_ns=123,
                chain_id="test-chain",
                consensus_params=ConsensusParams(),
                validators=[abci.ValidatorUpdate("ed25519", b"\x01" * 32, 10)],
                app_state_bytes=b"{}",
                initial_height=7,
            ),
        ),
        (codec.QUERY, abci.RequestQuery(data=b"k", path="/store", height=5)),
        (codec.CHECK_TX, abci.RequestCheckTx(tx=b"a=1", type_=1)),
        (
            codec.FINALIZE_BLOCK,
            abci.RequestFinalizeBlock(
                txs=[b"a=1", b"", b"b=2"],
                decided_last_commit=abci.CommitInfo(
                    round=2,
                    votes=[
                        abci.VoteInfo(b"\x02" * 20, 5, abci.BLOCK_ID_FLAG_COMMIT)
                    ],
                ),
                misbehavior=[
                    abci.Misbehavior(
                        type_=abci.MISBEHAVIOR_DUPLICATE_VOTE,
                        validator_address=b"\x03" * 20,
                        validator_power=9,
                        height=44,
                        time_ns=1,
                        total_voting_power=100,
                    )
                ],
                hash=b"\xaa" * 32,
                height=44,
                time_ns=99,
            ),
        ),
        (codec.INSERT_TX, b"tx-bytes"),
        (codec.REAP_TXS, (1000, -1)),
        (codec.OFFER_SNAPSHOT, (abci.Snapshot(height=10, chunks=3), b"h")),
        (codec.LOAD_SNAPSHOT_CHUNK, (10, 0, 2)),
        (codec.APPLY_SNAPSHOT_CHUNK, (1, b"chunk", "peer1")),
    ]
    for kind, req in cases:
        raw = codec.encode_request(kind, req)
        k2, r2 = codec.decode_request(raw)
        assert k2 == kind
        assert r2 == req

    resp_cases = [
        (codec.ECHO, "hello"),
        (codec.INFO, abci.ResponseInfo(data="kv", last_block_height=3,
                                       last_block_app_hash=b"\x01" * 8)),
        (codec.CHECK_TX, abci.ResponseCheckTx(code=1, log="bad",
                                              codespace="mem")),
        (
            codec.FINALIZE_BLOCK,
            abci.ResponseFinalizeBlock(
                events=[abci.Event("commit", [abci.EventAttribute("k", "v")])],
                tx_results=[abci.ExecTxResult(code=0, data=b"ok")],
                validator_updates=[
                    abci.ValidatorUpdate("ed25519", b"\x01" * 32, 0)
                ],
                app_hash=b"\x07" * 32,
            ),
        ),
        (codec.REAP_TXS, [b"a", b"", b"c"]),
    ]
    for kind, resp in resp_cases:
        raw = codec.encode_response(kind, resp)
        k2, r2 = codec.decode_response(raw)
        assert k2 == kind
        assert r2 == resp


def test_exception_response_raises():
    raw = codec.encode_response(codec.EXCEPTION, ValueError("boom"))
    with pytest.raises(RuntimeError, match="boom"):
        codec.decode_response(raw)


def _run_socket_server(app):
    """Start an ABCIServer on an ephemeral port in a background loop."""
    loop = asyncio.new_event_loop()
    server = ABCIServer(app, "tcp://127.0.0.1:0")
    started = threading.Event()

    def run():
        asyncio.set_event_loop(loop)

        async def go():
            await server.start()
            started.set()

        loop.run_until_complete(go())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    started.wait(5)
    return server, loop


def test_socket_client_against_kvstore():
    app = KVStoreApplication()
    server, loop = _run_socket_server(app)
    try:
        addr = server.listen_addr
        conns = connect_app_conns(addr)
        assert conns.query.echo("ping") == "ping"
        conns.consensus.init_chain(
            abci.RequestInitChain(chain_id="t", initial_height=1)
        )
        r = conns.mempool.check_tx(abci.RequestCheckTx(tx=b"k=v"))
        assert r.is_ok()
        # pipelined async check_tx
        futs = [
            conns.mempool.check_tx_async(
                abci.RequestCheckTx(tx=f"k{i}=v".encode())
            )
            for i in range(16)
        ]
        assert all(f.result(5).is_ok() for f in futs)
        fr = conns.consensus.finalize_block(
            abci.RequestFinalizeBlock(txs=[b"k=v"], height=1)
        )
        assert len(fr.tx_results) == 1 and fr.tx_results[0].is_ok()
        conns.consensus.commit()
        q = conns.query.query(abci.RequestQuery(data=b"k", path="/store"))
        assert q.value == b"v"
        for c in (conns.consensus, conns.mempool, conns.query, conns.snapshot):
            c.close()
    finally:
        loop.call_soon_threadsafe(loop.stop)


def test_grpc_client_against_kvstore():
    app = KVStoreApplication()
    server = GRPCServer(app, "tcp://127.0.0.1:0")
    server.start()
    try:
        client = GRPCClient(f"tcp://127.0.0.1:{server.port}")
        assert client.echo("ping") == "ping"
        client.init_chain(abci.RequestInitChain(chain_id="t"))
        assert client.check_tx(abci.RequestCheckTx(tx=b"x=1")).is_ok()
        fr = client.finalize_block(
            abci.RequestFinalizeBlock(txs=[b"x=1"], height=1)
        )
        assert fr.tx_results[0].is_ok()
        client.commit()
        assert client.query(
            abci.RequestQuery(data=b"x", path="/store")
        ).value == b"1"
        client.close()
    finally:
        server.stop()


def test_build_node_dials_remote_app(tmp_path):
    """config.base.proxy_app routes the node's AppConns over the socket
    protocol (reference node/setup.go:119 createAndStartProxyAppConns)."""
    from cometbft_tpu.abci.types import RequestInfo
    from cometbft_tpu.config.config import test_config
    from cometbft_tpu.node.inprocess import build_node
    from cometbft_tpu.privval import FilePV
    from cometbft_tpu.types.genesis import GenesisDoc
    from cometbft_tpu.types.validator_set import Validator

    app = KVStoreApplication()
    server, loop = _run_socket_server(app)
    try:
        pv = FilePV.generate(
            str(tmp_path / "key.json"), str(tmp_path / "state.json")
        )
        pub = pv.pub_key()
        gen = GenesisDoc(
            chain_id="remote-app-chain",
            validators=[Validator(pub_key=pub, voting_power=10)],
        )
        cfg = test_config(str(tmp_path))
        cfg.base.proxy_app = server.listen_addr
        cfg.base.abci = "socket"
        parts = build_node(gen, pv, config=cfg, home=str(tmp_path))
        assert parts.app is None
        info = parts.proxy.query.info(RequestInfo())
        assert info.last_block_height == app.height
        for c in (
            parts.proxy.consensus,
            parts.proxy.mempool,
            parts.proxy.query,
            parts.proxy.snapshot,
        ):
            c.close()
    finally:
        loop.call_soon_threadsafe(loop.stop)


def test_socket_server_reports_app_exception():
    class Boom(KVStoreApplication):
        def info(self, req):
            raise RuntimeError("app exploded")

    server, loop = _run_socket_server(Boom())
    try:
        c = SocketClient(server.listen_addr)
        with pytest.raises(RuntimeError, match="app exploded"):
            c.info(abci.RequestInfo())
        # connection survives an app-level exception
        assert c.echo("still-alive") == "still-alive"
        c.close()
    finally:
        loop.call_soon_threadsafe(loop.stop)
