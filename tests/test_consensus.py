"""In-process consensus tests (the reference's consensus/common_test.go
strategy): single-node chains, multi-node local nets, WAL crash replay.

Uses the CPU verifier backend (single-sig votes) — the TPU batch path
is exercised by blocksync/light tests.
"""

import asyncio
import os
import tempfile

import pytest

from cometbft_tpu import types as T
from cometbft_tpu.abci import types as abci
from cometbft_tpu.consensus.wal import WAL, WALMessage, MSG_END_HEIGHT
from cometbft_tpu.node.inprocess import (
    LocalNet,
    build_node,
    make_genesis,
)


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def test_single_node_produces_blocks():
    async def main():
        gen, pvs = make_genesis(1)
        node = build_node(gen, pvs[0])
        net = LocalNet([node])
        await net.start()
        # inject a tx mid-flight
        node.mempool.check_tx(b"hello=world")
        await net.wait_for_height(3, timeout=30)
        await net.stop()
        assert node.block_store.height() >= 3
        # the tx landed in some block
        found = False
        for h in range(1, node.block_store.height() + 1):
            blk = node.block_store.load_block(h)
            if b"hello=world" in blk.data.txs:
                found = True
        assert found
        q = node.proxy.query.query(abci.RequestQuery(data=b"hello"))
        assert q.value == b"world"
        # commits verify against the valset
        vs = gen.validator_set()
        for h in range(1, 3):
            commit = node.block_store.load_seen_commit(h)
            meta = node.block_store.load_block_meta(h)
            T.verify_commit(
                gen.chain_id, vs, meta.block_id, h, commit
            )

    run(main())


def test_four_node_net_agrees():
    async def main():
        gen, pvs = make_genesis(4)
        nodes = [build_node(gen, pv) for pv in pvs]
        net = LocalNet(nodes)
        await net.start()
        nodes[0].mempool.check_tx(b"a=1")
        nodes[1].mempool.check_tx(b"b=2")
        await net.wait_for_height(3, timeout=40)
        await net.stop()
        # all agree on block hashes
        for h in range(1, 4):
            hashes = {
                n.block_store.load_block_meta(h).block_id.hash for n in nodes
            }
            assert len(hashes) == 1, f"disagreement at height {h}"
        # app state converged
        app_hashes = {n.app.app_hash for n in nodes}
        assert len(app_hashes) == 1

    run(main())


def test_net_survives_one_faulty_node_down():
    """3 of 4 validators are enough to keep committing."""

    async def main():
        gen, pvs = make_genesis(4)
        nodes = [build_node(gen, pv) for pv in pvs[:3]]  # node 3 never runs
        net = LocalNet(nodes)
        await net.start()
        await net.wait_for_height(2, timeout=60)
        await net.stop()
        assert all(n.block_store.height() >= 2 for n in nodes)

    run(main())


def test_wal_replay_after_crash():
    async def main():
        home = tempfile.mkdtemp(prefix="cswal_")
        gen, pvs = make_genesis(1)
        node = build_node(gen, pvs[0], home=home, wal=True)
        net = LocalNet([node])
        await net.start()
        await net.wait_for_height(2, timeout=30)
        await net.stop()
        h_before = node.block_store.height()
        wal_path = node.cs._wal_path
        msgs = list(WAL.iter_messages(wal_path))
        assert any(m.kind == MSG_END_HEIGHT for m in msgs)
        # "crash": discard the node, rebuild from the same dbs + WAL
        # (memdb is per-instance, so rebuild from stores via a fresh app
        # exercises the ABCI handshake replay path)
        node2 = build_node(
            gen,
            pvs[0],
            home=home,
            wal=True,
        )
        # fresh app replayed to stored height
        assert node2.app.height == 0  # memdb: new app, fresh dbs
        await node2.cs.stop()

    run(main())


def test_handshake_replays_blocks_to_fresh_app(tmp_path):
    """Crash-recovery: store has blocks, app restarts at 0 ->
    handshake replays them (reference consensus/replay.go:288)."""

    async def main():
        gen, pvs = make_genesis(1)
        cfgdir = str(tmp_path)
        from cometbft_tpu.config.config import test_config

        cfg = test_config(cfgdir)
        cfg.base.db_backend = "sqlite"
        node = build_node(gen, pvs[0], config=cfg, home=cfgdir)
        net = LocalNet([node])
        await net.start()
        node.mempool.check_tx(b"x=y")
        await net.wait_for_height(3, timeout=30)
        await net.stop()
        height = node.block_store.height()
        app_hash = node.app.app_hash
        node.block_db.close()
        node.state_db.close()
        # new process: fresh app, same disk stores
        node2 = build_node(gen, pvs[0], config=cfg, home=cfgdir)
        assert node2.app.height == height >= 3
        assert node2.app.app_hash == app_hash
        q = node2.proxy.query.query(abci.RequestQuery(data=b"x"))
        assert q.value == b"y"
        await node2.cs.stop()

    run(main())


def test_double_sign_protection(tmp_path):
    from cometbft_tpu.privval import DoubleSignError, FilePV

    pv = FilePV.generate(
        str(tmp_path / "key.json"), str(tmp_path / "state.json")
    )
    bid = T.BlockID(b"\x01" * 32, T.PartSetHeader(1, b"\x02" * 32))
    v1 = T.Vote(
        type_=T.PREVOTE,
        height=5,
        round=0,
        block_id=bid,
        timestamp_ns=1000,
        validator_address=pv.pub_key().address(),
        validator_index=0,
    )
    pv.sign_vote("c", v1)
    assert v1.signature
    # same vote again: same signature returned
    v2 = T.Vote(**{**v1.__dict__, "signature": b""})
    pv.sign_vote("c", v2)
    assert v2.signature == v1.signature
    # conflicting block at same HRS: refuse
    v3 = T.Vote(
        **{
            **v1.__dict__,
            "signature": b"",
            "block_id": T.BlockID(b"\x03" * 32, T.PartSetHeader(1, b"\x04" * 32)),
        }
    )
    with pytest.raises(DoubleSignError):
        pv.sign_vote("c", v3)
    # height regression: refuse
    v4 = T.Vote(**{**v1.__dict__, "signature": b"", "height": 4})
    with pytest.raises(DoubleSignError):
        pv.sign_vote("c", v4)
    # state survives reload
    pv2 = FilePV.load(
        str(tmp_path / "key.json"), str(tmp_path / "state.json")
    )
    with pytest.raises(DoubleSignError):
        pv2.sign_vote("c", v3)


def test_wal_corruption_tolerant(tmp_path):
    path = str(tmp_path / "wal")
    w = WAL(path)
    for h in (1, 2, 3):
        w.write_sync(WALMessage(kind=MSG_END_HEIGHT, height=h))
    w.close()
    msgs = list(WAL.iter_messages(path))
    assert len(msgs) == 3
    # corrupt the tail
    with open(path, "ab") as f:
        f.write(b"\x00garbage\xff" * 3)
    msgs = list(WAL.iter_messages(path))
    assert len(msgs) == 3  # stops at corruption
    assert WAL.search_for_end_height(path, 2) == 2
    n = WAL.truncate_corrupt_tail(path)
    assert n == 3
