"""Cross-node trace-context stamping (p2p/tracewire.py) tier-1 suite.

Layers:
  1. wire codec contracts: stamp/unstamp round-trip, the zero-header
     escape, lossless fallback on anything unparseable (the
     backward-compat framing satellite of ISSUE 7);
  2. TraceStamper semantics: send/recv instants, channel-cap skip,
     clock-domain gating of live propagation spans;
  3. switch-level interop over a real MemoryTransport net: stamping
     node <-> non-stamping node, payloads delivered byte-identical
     both directions while the stamping side records correlations.
"""

import asyncio

import pytest

from cometbft_tpu.p2p import (
    ChannelDescriptor,
    MemoryTransport,
    NodeInfo,
    NodeKey,
    Reactor,
    Switch,
)
from cometbft_tpu.p2p import tracewire
from cometbft_tpu.trace import Tracer


def run(coro, timeout=30):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# --- 1. wire codec -------------------------------------------------------


def test_stamp_unstamp_roundtrip_all_kinds():
    payload = b"\x01proposal-bytes" * 3
    for kind in tracewire.KINDS:
        wire = tracewire.stamp(
            payload, kind, seq=7, origin="n0", height=12, round_=2,
            send_ns=123456789,
        )
        assert wire.startswith(tracewire.MAGIC)
        ctx, out = tracewire.unstamp(wire)
        assert out == payload
        assert ctx is not None
        assert ctx.kind == kind and ctx.seq == 7
        assert ctx.height == 12 and ctx.round == 2
        assert ctx.origin == "n0" and ctx.send_ns == 123456789
        assert ctx.clock == tracewire.CLOCK_DOMAIN


def test_stamp_roundtrip_edge_values():
    # no-round messages (blocksync) encode round -1 losslessly; empty
    # payloads and long origins survive (origin truncated to the cap)
    ctx, out = tracewire.unstamp(
        tracewire.stamp(b"", "bs.status", seq=0, origin="x" * 64)
    )
    assert out == b"" and ctx.round == -1 and ctx.height == 0
    assert ctx.origin == "x" * tracewire._MAX_ORIGIN_LEN


def test_unstamped_passthrough_and_escape():
    # plain bytes pass through untouched...
    raw = b"ordinary reactor message"
    assert tracewire.unstamp(raw) == (None, raw)
    assert tracewire.encode_plain(raw) == raw
    # ...and a payload that happens to BEGIN with the magic is
    # escaped by a stamping-disabled sender so the receiver cannot
    # misparse it: unstamp(escape(m)) == m, ctx None
    tricky = tracewire.MAGIC + b"not actually a stamp"
    wire = tracewire.encode_plain(tricky)
    assert wire != tricky
    ctx, out = tracewire.unstamp(wire)
    assert ctx is None and out == tricky


def test_unparseable_after_magic_falls_back_to_raw():
    # an OLD peer relaying raw bytes that start with our magic but do
    # not parse must come back unchanged (lossless both directions)
    for tail in (
        b"",  # bare magic
        b"\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff",  # overlong varint
        b"\x7f",  # header length way past the buffer
        b"\x03\x63\x00\x00",  # unknown kind id (99)
    ):
        msg = tracewire.MAGIC + tail
        ctx, out = tracewire.unstamp(msg)
        assert ctx is None and out == msg

    # truncated header: cut a valid stamp mid-header
    wire = tracewire.stamp(b"payload", "vote", 1, "n1", height=3)
    cut = wire[: len(tracewire.MAGIC) + 3]
    assert tracewire.unstamp(cut) == (None, cut)

    # origin length overrunning the declared header is rejected
    hdr = bytearray()
    tracewire._put_uvarint(hdr, 0)  # kind
    tracewire._put_uvarint(hdr, 0)  # seq
    tracewire._put_uvarint(hdr, 0)  # send_ns
    tracewire._put_uvarint(hdr, 1)  # clock
    tracewire._put_uvarint(hdr, 0)  # height
    tracewire._put_uvarint(hdr, 0)  # round+1
    tracewire._put_uvarint(hdr, 40)  # origin len LIE (past header end)
    bad = bytearray(tracewire.MAGIC)
    tracewire._put_uvarint(bad, len(hdr))
    bad += hdr
    bad = bytes(bad)
    assert tracewire.unstamp(bad) == (None, bad)


# --- 2. TraceStamper -----------------------------------------------------


def test_stamper_records_correlated_send_recv_and_propagation():
    t_send = Tracer("sender", size=64)
    t_recv = Tracer("receiver", size=64)
    sender = tracewire.TraceStamper(t_send, origin="n0")
    receiver = tracewire.TraceStamper(t_recv, origin="n1")

    wire = sender.wrap(b"vote-bytes", "vote", height=5, round_=1,
                       peer="abcdef", npeers=3)
    ctx, payload = tracewire.unstamp(wire)
    assert payload == b"vote-bytes"
    receiver.on_receive(ctx, "sender-peer-id")

    send_ev = [e for e in t_send.snapshot() if e["name"] == "p2p.msg.send"]
    assert len(send_ev) == 1
    assert send_ev[0]["args"]["kind"] == "vote"
    assert send_ev[0]["args"]["h"] == 5 and send_ev[0]["args"]["seq"] == 0
    # the ring instant carries the EXACT instant baked into the stamp
    assert send_ev[0]["ts_ns"] == ctx.send_ns

    recv = {e["name"]: e for e in t_recv.snapshot()}
    assert recv["p2p.msg.recv"]["args"]["origin"] == "n0"
    assert recv["p2p.msg.recv"]["args"]["seq"] == 0
    # same process => same clock domain => live propagation span
    prop = recv["p2p.msg.propagation"]
    assert prop["ts_ns"] == ctx.send_ns and prop["dur_ns"] >= 0

    # a foreign clock domain must NOT produce a propagation span
    # (monotonic clocks don't compare across processes)
    t_recv.clear()
    foreign = tracewire.TraceCtx(
        "vote", 1, ctx.send_ns, ctx.clock ^ 0xFFFF, 5, 1, "other"
    )
    receiver.on_receive(foreign, "p")
    names = [e["name"] for e in t_recv.snapshot()]
    assert "p2p.msg.recv" in names
    assert "p2p.msg.propagation" not in names


def test_stamper_skips_payloads_near_channel_cap():
    t = Tracer("s", size=16)
    st = tracewire.TraceStamper(t, origin="n0")
    big = b"x" * 1000
    wire = st.wrap(big, "txs", cap=1000 + tracewire.STAMP_MAX_OVERHEAD - 1)
    assert wire == big  # unstamped: would cross the cap
    assert t.snapshot() == []  # and no phantom send instant
    # with headroom it stamps
    wire = st.wrap(big, "txs", cap=1000 + tracewire.STAMP_MAX_OVERHEAD)
    assert wire.startswith(tracewire.MAGIC)
    # magic-prefixed payload near the cap is escaped IF it fits,
    # raw otherwise (never oversized, never misparsed)
    tricky = tracewire.MAGIC + b"y" * 998
    wire = st.wrap(tricky, "txs", cap=1001)
    assert tracewire.unstamp(wire) == (None, tricky)


# --- 3. switch-level interop over MemoryTransport ------------------------


class SinkReactor(Reactor):
    name = "sink"
    CHAN = 0x55

    def __init__(self):
        super().__init__()
        self.got = []

    def get_channels(self):
        return [ChannelDescriptor(self.CHAN, priority=3)]

    def add_peer(self, peer):
        pass

    def remove_peer(self, peer, reason):
        pass

    def receive(self, chan_id, peer, msg):
        self.got.append(bytes(msg))


def _switch(chain_id="tracewire-net"):
    nk = NodeKey.generate()
    info = NodeInfo(node_id=nk.node_id, network=chain_id)
    sw = Switch(MemoryTransport(nk, info), info)
    rx = sw.add_reactor("sink", SinkReactor())
    return sw, rx


def test_switch_interop_stamping_vs_plain_peer():
    """New (stamping) node <-> old (non-stamping) node: payloads are
    byte-identical in both directions, including a payload that
    starts with the magic bytes; the stamping side records correlated
    send/recv instants, the plain side records nothing."""

    async def main():
        sw_new, rx_new = _switch()
        sw_old, rx_old = _switch()
        tr = Tracer("new", size=256)
        sw_new.enable_stamping(tr, "new-node")
        for sw in (sw_new, sw_old):
            await sw.transport.listen()
            await sw.start()
        await sw_new.dial_peer(sw_old.transport.listen_addr)
        for _ in range(100):
            if sw_new.num_peers() and sw_old.num_peers():
                break
            await asyncio.sleep(0.02)

        tricky = tracewire.MAGIC + b"looks-like-a-stamp"
        # new -> old: stamped broadcast decodes to the original
        # payload on a switch with NO stamping plane at all
        sw_new.broadcast(SinkReactor.CHAN, b"stamped-hello",
                         tkind="vote", height=9)
        # new -> old: kind-less broadcast goes out raw
        sw_new.broadcast(SinkReactor.CHAN, b"plain-hello")
        # old -> new: raw sends, one of them magic-prefixed
        sw_old.broadcast(SinkReactor.CHAN, b"old-hello")
        sw_old.broadcast(SinkReactor.CHAN, tricky)
        for _ in range(100):
            if len(rx_old.got) >= 2 and len(rx_new.got) >= 2:
                break
            await asyncio.sleep(0.02)

        assert rx_old.got == [b"stamped-hello", b"plain-hello"]
        # the magic-prefixed raw payload survives IF it did not parse
        # as a stamp (tracewire guarantees unparseable => unchanged)
        assert rx_new.got == [b"old-hello", tricky]

        ev = tr.snapshot()
        sends = [e for e in ev if e["name"] == "p2p.msg.send"]
        assert len(sends) == 1 and sends[0]["args"]["kind"] == "vote"
        assert sends[0]["args"]["h"] == 9
        await sw_new.stop()
        await sw_old.stop()

    run(main())


def test_switch_interop_both_stamping_records_recv():
    async def main():
        sw_a, rx_a = _switch()
        sw_b, rx_b = _switch()
        tr_a, tr_b = Tracer("a", size=256), Tracer("b", size=256)
        sw_a.enable_stamping(tr_a, "node-a")
        sw_b.enable_stamping(tr_b, "node-b")
        for sw in (sw_a, sw_b):
            await sw.transport.listen()
            await sw.start()
        await sw_a.dial_peer(sw_b.transport.listen_addr)
        for _ in range(100):
            if sw_a.num_peers() and sw_b.num_peers():
                break
            await asyncio.sleep(0.02)
        sw_a.broadcast(SinkReactor.CHAN, b"payload", tkind="proposal",
                       height=4, round_=0)
        for _ in range(100):
            if rx_b.got:
                break
            await asyncio.sleep(0.02)
        assert rx_b.got == [b"payload"]
        recvs = [
            e for e in tr_b.snapshot() if e["name"] == "p2p.msg.recv"
        ]
        assert len(recvs) == 1
        a = recvs[0]["args"]
        assert a["origin"] == "node-a" and a["kind"] == "proposal"
        assert a["h"] == 4
        # same process: the live propagation span fired too
        props = [
            e for e in tr_b.snapshot()
            if e["name"] == "p2p.msg.propagation"
        ]
        assert props and props[0]["args"]["origin"] == "node-a"
        await sw_a.stop()
        await sw_b.stop()

    run(main())


def test_switch_receive_only_records_arrivals_without_stamping():
    """trace_msg_stamp=False gates only the OUTBOUND stamp
    (config.py): the node's own sends go out unstamped, but arrivals
    from stamping peers are still recorded in its ring."""

    async def main():
        sw_rx, rx_rx = _switch()
        sw_tx, rx_tx = _switch()
        tr_rx, tr_tx = Tracer("rx", size=256), Tracer("tx", size=256)
        sw_rx.enable_stamping(tr_rx, "rx-node", outbound=False)
        sw_tx.enable_stamping(tr_tx, "tx-node")
        for sw in (sw_rx, sw_tx):
            await sw.transport.listen()
            await sw.start()
        await sw_rx.dial_peer(sw_tx.transport.listen_addr)
        for _ in range(100):
            if sw_rx.num_peers() and sw_tx.num_peers():
                break
            await asyncio.sleep(0.02)
        sw_tx.broadcast(SinkReactor.CHAN, b"stamped", tkind="vote",
                        height=2)
        sw_rx.broadcast(SinkReactor.CHAN, b"from-rx", tkind="vote",
                        height=2)
        for _ in range(100):
            if rx_rx.got and rx_tx.got:
                break
            await asyncio.sleep(0.02)
        assert rx_rx.got == [b"stamped"] and rx_tx.got == [b"from-rx"]
        # receive side still correlates...
        recvs = [
            e for e in tr_rx.snapshot() if e["name"] == "p2p.msg.recv"
        ]
        assert len(recvs) == 1 and recvs[0]["args"]["origin"] == "tx-node"
        # ...but its own sends were unstamped: no send instant here,
        # no recv instant on the stamping peer
        assert not [
            e for e in tr_rx.snapshot() if e["name"] == "p2p.msg.send"
        ]
        assert not [
            e for e in tr_tx.snapshot() if e["name"] == "p2p.msg.recv"
        ]
        await sw_rx.stop()
        await sw_tx.stop()

    run(main())


def test_stamp_msg_disabled_is_identity():
    sw, _ = _switch()
    msg = b"anything"
    assert sw.stamper is None
    assert sw.stamp_msg(0x55, msg, "vote", height=1) is msg
