"""Pipelined blocksync verify dispatch (VERDICT r3 #3).

The window loop pre-dispatches the NEXT window's signature batch
before applying the current one; the pre-dispatched handle is reused
only when its inputs (valset hash + block object identities) match
exactly, and dropped on every redo/ban/valset-change path. These
tests instrument the dispatch seam to prove both the reuse and the
discards, and check end-state correctness around them.
"""

import asyncio

import pytest

from cometbft_tpu.blocksync import reactor as reactor_mod
from cometbft_tpu.blocksync.reactor import BlockSyncReactor
from cometbft_tpu.node.inprocess import build_node, make_genesis
from cometbft_tpu.utils.chaingen import StorePeerClient, make_chain


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


class _DispatchCounter:
    """Wraps verify_commits_coalesced_async: counts dispatches and the
    number of jobs each carried."""

    def __init__(self, monkeypatch):
        self.calls = []
        real = reactor_mod.verify_commits_coalesced_async

        def wrapped(chain_id, jobs, cache=None, light=True, **kw):
            self.calls.append(len(jobs))
            return real(chain_id, jobs, cache=cache, light=light, **kw)

        monkeypatch.setattr(
            reactor_mod, "verify_commits_coalesced_async", wrapped
        )


def _sync(gen, src, window=8, peers=None, prefill=0):
    async def main():
        fresh = build_node(gen, None)
        caught = asyncio.Event()
        reactor = BlockSyncReactor(
            fresh.state,
            fresh.block_exec,
            fresh.block_store,
            on_caught_up=lambda st: caught.set(),
            verify_window=window,
        )
        for name, client in peers or [("src", StorePeerClient(src))]:
            reactor.pool.set_peer_range(
                name, client, 1, src.block_store.height()
            )
        # deterministic pipelining on a loaded box: let the requesters
        # buffer a lookahead BEFORE the verify loop starts, so the
        # predispatch/reuse/discard sequence doesn't depend on fetch
        # timing (set_peer_range already spawned the requesters)
        deadline = asyncio.get_running_loop().time() + 30
        while len(reactor.pool.blocks) < prefill:
            if asyncio.get_running_loop().time() > deadline:
                raise TimeoutError("pool prefill")
            await asyncio.sleep(0.01)
        await reactor.start()
        await asyncio.wait_for(caught.wait(), 90)
        await reactor.stop()
        return fresh, reactor

    return run(main())


def test_pipeline_reuses_predispatched_windows(monkeypatch):
    """Steady-state sync: nearly every pass consumes the handle
    pre-dispatched by the previous pass, so total dispatches stay
    close to the number of windows (they would roughly DOUBLE if
    every pre-dispatch were discarded)."""
    gen, pvs = make_genesis(3, chain_id="pipe-chain")
    src = make_chain(gen, [pv.priv_key for pv in pvs], 40)
    counter = _DispatchCounter(monkeypatch)
    fresh, reactor = _sync(gen, src, window=8, prefill=24)
    assert fresh.block_store.height() >= src.block_store.height() - 1
    jobs_total = sum(counter.calls)
    applied = reactor.blocks_applied
    # every dispatched job that was APPLIED was dispatched exactly
    # once; waste = jobs dispatched beyond the applies (discarded
    # handles, final partial windows). With working reuse the waste
    # is bounded by ~2 windows; with no reuse it would be ~applied.
    assert jobs_total - applied <= 2 * 8, (jobs_total, applied)
    # and the pipeline genuinely pre-dispatched (more than one call)
    assert len(counter.calls) >= 2
    # steady state: only the first window pays a fresh dispatch; every
    # later pass consumes the previous pass's lookahead
    stats = reactor.pipeline_stats
    assert stats["reused"] >= stats["dispatched"], stats
    assert stats["reused"] >= 2, stats


def test_pipeline_discards_on_refetch():
    """Deterministic direct drive of _process_window (no network
    races over which peer serves the bad height): a tampered block
    mid-window breaks the pass, the pre-dispatched handle is dropped
    (discarded), the refetched honest block forces a FRESH dispatch,
    and the sync completes with honest content."""
    from cometbft_tpu.utils import codec

    gen, pvs = make_genesis(3, chain_id="pipe-evil")
    src = make_chain(gen, [pv.priv_key for pv in pvs], 24)
    fresh = build_node(gen, None)
    reactor = BlockSyncReactor(
        fresh.state,
        fresh.block_exec,
        fresh.block_store,
        verify_window=8,
    )

    def fill(h0, h1, tamper=()):
        for h in range(h0, h1 + 1):
            if h in reactor.pool.blocks:
                continue
            blk = src.block_store.load_block(h)
            if h in tamper:
                # same corruption as TamperingPeerClient: an injected
                # tx changes the data hash, so blk.hash() no longer
                # matches what h+1's commit signed
                blk.data.txs = list(blk.data.txs) + [b"evil=1"]
                blk.data._hash = None
                if hasattr(blk, "_raw_bytes"):
                    del blk._raw_bytes
            reactor.pool.blocks[h] = (blk, "evil" if h in tamper else "good")

    # pass 1: clean window 1..7 applied; lookahead 8..14 pre-dispatched
    fill(1, 17, tamper={12})
    applied = reactor._process_window(reactor.pool.peek_window(16))
    assert applied == 7
    assert reactor._inflight is not None
    assert reactor.pipeline_stats["predispatched"] == 1

    # pass 2: reuses the lookahead, applies 8..11, breaks at the
    # tampered 12 -> its own lookahead (15..) must be DISCARDED
    applied = reactor._process_window(reactor.pool.peek_window(16))
    assert applied == 4, applied
    assert reactor._inflight is None
    assert reactor.pipeline_stats["reused"] == 1
    assert reactor.pipeline_stats["discarded"] >= 1, (
        reactor.pipeline_stats
    )

    # the redo dropped the tampered block; refetch honest + continue:
    # the refetched window cannot match any old key -> fresh dispatch
    before = reactor.pipeline_stats["dispatched"]
    fill(12, 17)
    applied = reactor._process_window(reactor.pool.peek_window(16))
    assert applied >= 5
    assert reactor.pipeline_stats["dispatched"] == before + 1
    assert (
        fresh.block_store.load_block(12).hash()
        == src.block_store.load_block(12).hash()
    )


def test_pipeline_discards_across_valset_change(monkeypatch):
    """A REAL validator-set change mid-chain (kvstore val-update tx):
    windows truncate at the change, the pre-dispatched key (bound to
    the pre-change valset hash) stops matching, and verdicts are never
    carried across the change. End state must be a full, correct
    sync that verified post-change commits against the NEW set."""
    from cometbft_tpu.crypto.keys import Ed25519PrivKey

    gen, pvs = make_genesis(4, chain_id="pipe-valset")
    privs = [pv.priv_key for pv in pvs]
    src = build_node(gen, None)
    make_chain(gen, privs, 12, node=src)
    # add a 5th validator via the kvstore app at height 13 (takes
    # effect two heights later, state/execution.go:713 semantics)
    newv = Ed25519PrivKey.from_seed(b"\x07" * 32)
    pk_hex = newv.pub_key().key_bytes.hex().encode()
    src.mempool.check_tx(b"val:" + pk_hex + b"!5")
    make_chain(gen, privs + [newv], 28, node=src)
    assert src.state.validators.size() == 5
    counter = _DispatchCounter(monkeypatch)
    fresh, reactor = _sync(gen, src, window=8)
    assert fresh.block_store.height() >= src.block_store.height() - 1
    assert fresh.state_store.load().validators.size() == 5


def test_blocksync_interrupt_and_resume(tmp_path):
    """Blocksync stopped abruptly mid-catch-up (in-flight pipelined
    lookahead and all) must resume cleanly from the persisted stores
    in a fresh process-equivalent and complete the sync."""
    from cometbft_tpu.config.config import test_config

    gen, pvs = make_genesis(3, chain_id="resume-chain")
    src = make_chain(gen, [pv.priv_key for pv in pvs], 40)
    home = str(tmp_path / "node")

    def build(h):
        import os

        os.makedirs(h, exist_ok=True)
        cfg = test_config(h)
        cfg.base.db_backend = "sqlite"
        return build_node(gen, None, config=cfg, home=h)

    fresh = build(home)

    async def phase1():
        r = BlockSyncReactor(
            fresh.state, fresh.block_exec, fresh.block_store,
            verify_window=8,
        )
        r.pool.set_peer_range(
            "src", StorePeerClient(src), 1, src.block_store.height()
        )
        # prefill so the pipelined lookahead genuinely engages before
        # the abrupt stop (otherwise this degrades to a plain restart
        # test on a slow-fetch box)
        deadline = asyncio.get_running_loop().time() + 30
        while len(r.pool.blocks) < 20:
            if asyncio.get_running_loop().time() > deadline:
                raise TimeoutError("pool prefill")
            await asyncio.sleep(0.01)
        await r.start()
        while fresh.block_store.height() < 15:
            await asyncio.sleep(0.01)
        stats = dict(r.pipeline_stats)
        await r.stop()  # abrupt: lookahead handle dies with it
        assert stats["predispatched"] >= 1, stats

    run(phase1())
    h1 = fresh.block_store.height()
    assert h1 >= 15
    fresh.close_stores()

    # "restart": a new node over the same home resumes from disk
    fresh2 = build(home)
    assert fresh2.block_store.height() == h1
    assert fresh2.state.last_block_height == h1

    async def phase2():
        caught = asyncio.Event()
        r = BlockSyncReactor(
            fresh2.state, fresh2.block_exec, fresh2.block_store,
            on_caught_up=lambda st: caught.set(),
            verify_window=8,
        )
        r.pool.set_peer_range(
            "src", StorePeerClient(src), 1, src.block_store.height()
        )
        await r.start()
        await asyncio.wait_for(caught.wait(), 60)
        await r.stop()

    run(phase2())
    assert (
        fresh2.block_store.height() >= src.block_store.height() - 1
    )
    h = fresh2.block_store.height()
    assert (
        fresh2.block_store.load_block(h).hash()
        == src.block_store.load_block(h).hash()
    )
    fresh2.close_stores()


def test_async_handle_matches_sync_verdicts():
    """verify_commits_coalesced_async().result() ==
    verify_commits_coalesced() on the same jobs (incl. a bad one)."""
    import copy
    import dataclasses

    from cometbft_tpu.types.validation import (
        verify_commits_coalesced,
        verify_commits_coalesced_async,
    )

    gen, pvs = make_genesis(4, chain_id="pipe-eq")
    src = make_chain(gen, [pv.priv_key for pv in pvs], 5)
    vs = gen.validator_set()
    store = src.block_store
    jobs = []
    for h in range(1, 5):
        jobs.append(
            (
                vs,
                store.load_block_meta(h).block_id,
                h,
                store.load_seen_commit(h),
            )
        )
    bad = copy.deepcopy(store.load_seen_commit(2))
    sig = bytearray(bad.signatures[0].signature)
    sig[0] ^= 1
    bad.signatures[0] = dataclasses.replace(
        bad.signatures[0], signature=bytes(sig)
    )
    jobs.append((vs, store.load_block_meta(2).block_id, 2, bad))

    sync_errors = verify_commits_coalesced(gen.chain_id, jobs)
    async_errors = verify_commits_coalesced_async(
        gen.chain_id, jobs
    ).result()
    assert [e is None for e in sync_errors] == [
        e is None for e in async_errors
    ]
    assert sync_errors[:4] == [None] * 4
    assert async_errors[4] is not None
