"""Byzantine validator end-to-end (reference
consensus/byzantine_test.go:38 TestByzantinePrevoteEquivocation +
test/e2e/runner/evidence.go injection).

A validator equivocates prevotes over the real TCP p2p stack; the
DuplicateVoteEvidence must: (1) form in the first honest node's pool,
(2) gossip to the other honest nodes on channel 0x38, (3) land in a
proposed block, and (4) reach every app as FinalizeBlock Misbehavior —
the app-side record that makes the offender's power slashable.
"""

import asyncio
import time

import pytest

from cometbft_tpu import types as T
from cometbft_tpu.config.config import test_config as make_test_cfg
from cometbft_tpu.consensus.reactor import VOTE_CHANNEL, encode_vote_msg
from cometbft_tpu.node.inprocess import make_genesis
from cometbft_tpu.node.node import Node


def run(coro, timeout=180):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def _mk_node(gen, pv, i):
    cfg = make_test_cfg(".")
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.base.moniker = f"node{i}"
    cfg.blocksync.enable = False
    return Node(cfg, gen, privval=pv)


async def _connect_all(nodes):
    for i, a in enumerate(nodes):
        for b in nodes[i + 1:]:
            await a.dial(b.listen_addr)
    for n in nodes:
        for _ in range(200):
            if n.switch.num_peers() >= len(nodes) - 1:
                break
            await asyncio.sleep(0.05)


async def _wait(pred, timeout, what):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if pred():
            return
        await asyncio.sleep(0.05)
    raise TimeoutError(what)


def test_prevote_equivocation_slashed_end_to_end():
    gen, pvs = make_genesis(4, chain_id="byz-chain")
    byz_pv = pvs[3]  # its node never runs; the key equivocates

    async def main():
        nodes = [_mk_node(gen, pvs[i], i) for i in range(3)]
        for n in nodes:
            await n.start()
        await _connect_all(nodes)
        # chain must progress with 3/4 power
        await _wait(
            lambda: all(n.height >= 1 for n in nodes), 60, "height 1"
        )

        # craft two CONFLICTING prevotes from the byzantine key for
        # node0's CURRENT (height, round) and hand both to node0 over
        # the real vote channel (signed correctly — only the block ids
        # differ: equivocation, not forgery)
        target = nodes[0]
        vs = gen.validator_set()
        byz_idx, byz_val = vs.get_by_address(
            byz_pv.pub_key().address()
        )
        assert byz_idx >= 0

        async def equivocate_until_evidence():
            # the round may advance between reading rs and delivery, so
            # re-inject at the then-current (height, round) until the
            # conflict registers
            peer = next(iter(target.switch.peers.values()))
            for _ in range(100):
                if target.parts.evpool.pending_evidence(1 << 20):
                    return
                rs = target.parts.cs.rs
                votes = []
                for tag in (b"\xaa", b"\xbb"):
                    v = T.Vote(
                        type_=T.PREVOTE,
                        height=rs.height,
                        round=rs.round,
                        block_id=T.BlockID(
                            tag * 32, T.PartSetHeader(1, tag * 32)
                        ),
                        timestamp_ns=time.time_ns(),
                        validator_address=byz_pv.pub_key().address(),
                        validator_index=byz_idx,
                        signature=b"",
                    )
                    v.signature = byz_pv.priv_key.sign(
                        v.sign_bytes(gen.chain_id)
                    )
                    votes.append(v)
                # deliver through the reactor's receive path, as if a
                # byzantine peer sent them
                reactor = target.switch.reactor("consensus")
                for v in votes:
                    reactor.receive(
                        VOTE_CHANNEL, peer, encode_vote_msg(v)
                    )
                await asyncio.sleep(0.1)
            raise TimeoutError("evidence never formed")

        await equivocate_until_evidence()

        # (2) evidence gossips to the OTHER honest nodes (0x38)
        def evidence_everywhere():
            return all(
                n.parts.evpool.pending_evidence(1 << 20)
                or _app_saw_misbehavior(n)
                for n in nodes
            )

        def _app_saw_misbehavior(n):
            return any(
                addr == byz_pv.pub_key().address()
                for (_, _, addr, _, _) in n.parts.app.misbehavior_seen
            )

        await _wait(evidence_everywhere, 30, "evidence gossip")

        # (3) + (4) evidence lands in a committed block and reaches
        # every app as Misbehavior
        await _wait(
            lambda: all(_app_saw_misbehavior(n) for n in nodes),
            60,
            "misbehavior at apps",
        )

        # the app-side record carries the offender's power: slashable
        for n in nodes:
            recs = [
                r
                for r in n.parts.app.misbehavior_seen
                if r[2] == byz_pv.pub_key().address()
            ]
            assert recs
            assert recs[0][3] == byz_val.voting_power

        # the evidence is in a committed block on-chain
        found = False
        h = nodes[0].height
        for height in range(1, h + 1):
            blk = nodes[0].parts.block_store.load_block(height)
            if blk is not None and blk.evidence:
                found = True
        assert found, "evidence never landed in a committed block"

        for n in nodes:
            await n.stop()

    run(main())


def test_lca_evidence_internal_consistency_enforced():
    """Soundness regression (found round 5, mirrors reference
    evidence ValidateBasic -> LightBlock.ValidateBasic,
    types/evidence.go:385): a GENUINE commit (real >2/3 signatures
    over the real block) paired with a FABRICATED header must be
    rejected — accepting it would 'prove' an attack by the honest
    signers and slash them. Also: common_height may not exceed the
    conflicting block's height."""
    import dataclasses

    import pytest as _pytest

    from cometbft_tpu.evidence.pool import EvidenceError
    from cometbft_tpu.evidence.types import LightClientAttackEvidence
    from cometbft_tpu.light.types import LightBlock
    from cometbft_tpu.utils.chaingen import make_chain

    gen, pvs = make_genesis(4, chain_id="lca-forge")
    src = make_chain(gen, [pv.priv_key for pv in pvs], 8)
    evpool = src.evpool
    real = src.block_store.load_block(5)
    real_commit = src.block_store.load_seen_commit(5)
    vs = src.state_store.load_validators(5)

    fabricated_header = dataclasses.replace(
        real.header, app_hash=b"\x55" * 32
    )
    lb = LightBlock(
        header=fabricated_header,
        commit=real_commit,  # genuine sigs, for the REAL block id
        validator_set=vs,
    )
    ev = LightClientAttackEvidence(
        conflicting_block=lb,
        common_height=4,
        total_voting_power=vs.total_voting_power(),
        timestamp_ns=time.time_ns(),
    )
    ev.byzantine_validators = ev.byzantine_from(
        src.state_store.load_validators(4)
    )
    with _pytest.raises(EvidenceError, match="invalid conflicting"):
        evpool.add_evidence(ev)

    # common height ahead of the conflicting block's height
    real_lb = LightBlock(
        header=real.header, commit=real_commit, validator_set=vs
    )
    ev2 = LightClientAttackEvidence(
        conflicting_block=real_lb,
        common_height=7,
        total_voting_power=vs.total_voting_power(),
        timestamp_ns=time.time_ns(),
    )
    with _pytest.raises(EvidenceError):
        evpool.add_evidence(ev2)


def test_light_client_attack_slashed_end_to_end():
    """VERDICT r4 #6: the full light-client-attack path. Two of four
    validators (1/2 power — enough for a lunatic fork to pass
    non-adjacent trusting verification) sign a forged header with a
    claimed 2-validator valset. A light client whose PRIMARY serves
    the fork (1) verifies it, (2) detects divergence against an honest
    witness, (3) builds LCA evidence with the DERIVED byzantine set
    and reports it over the witness's real RPC, after which the
    evidence must (4) verify in the node's pool, (5) gossip on 0x38,
    (6) land in a committed block, and (7) reach every app as
    LIGHT_CLIENT_ATTACK misbehavior carrying both attackers' powers —
    the slashable record (reference light/detector.go:98,
    evidence/verify.go:124-136)."""
    import dataclasses

    from cometbft_tpu.abci.types import MISBEHAVIOR_LIGHT_CLIENT_ATTACK
    from cometbft_tpu.light import Client, TrustOptions
    from cometbft_tpu.light.detector import DivergenceError
    from cometbft_tpu.light.http_provider import HTTPProvider
    from cometbft_tpu.light.types import LightBlock

    gen, pvs = make_genesis(4, chain_id="byz-lca")
    byz = [pvs[2], pvs[3]]  # pvs[3]'s node never runs

    async def main():
        nodes = [_mk_node(gen, pvs[i], i) for i in range(3)]
        for n in nodes:
            await n.start()
        await _connect_all(nodes)
        await _wait(
            lambda: all(n.height >= 4 for n in nodes), 90, "height 4"
        )

        # --- forge the lunatic block at committed height 3 ----------
        ATTACK_H = 3
        real = nodes[0].parts.block_store.load_block(ATTACK_H)
        vs = gen.validator_set()
        byz_vals = []
        for pv in byz:
            _, v = vs.get_by_address(pv.pub_key().address())
            byz_vals.append(v)
        fvs = T.ValidatorSet(byz_vals)
        forged_header = dataclasses.replace(
            real.header,
            app_hash=b"\x66" * 32,
            validators_hash=fvs.hash(),
            next_validators_hash=fvs.hash(),
        )
        fbid = T.BlockID(
            forged_header.hash(),
            T.PartSetHeader(1, forged_header.hash()),
        )
        ts = forged_header.time_ns
        sigs = []
        for pv in byz:
            v = T.Vote(
                type_=T.PRECOMMIT,
                height=ATTACK_H,
                round=0,
                block_id=fbid,
                timestamp_ns=ts,
                validator_address=pv.pub_key().address(),
                validator_index=0,
            )
            sig = pv.priv_key.sign(v.sign_bytes(gen.chain_id))
            sigs.append(
                T.CommitSig(
                    block_id_flag=T.BLOCK_ID_FLAG_COMMIT,
                    validator_address=pv.pub_key().address(),
                    timestamp_ns=ts,
                    signature=sig,
                )
            )
        forged_lb = LightBlock(
            header=forged_header,
            commit=T.Commit(ATTACK_H, 0, fbid, sigs),
            validator_set=fvs,
        )

        # --- light client: forging primary, honest witness ----------
        honest0 = HTTPProvider(
            gen.chain_id, nodes[0].rpc_server.listen_addr
        )
        witness = HTTPProvider(
            gen.chain_id, nodes[1].rpc_server.listen_addr
        )

        class ForgingPrimary:
            """Honest until asked for the attack height."""

            reported = []

            def light_block(self, height):
                if height == ATTACK_H:
                    return forged_lb
                return honest0.light_block(height)

            def report_evidence(self, ev):
                ForgingPrimary.reported.append(ev)

        trust = nodes[0].parts.block_store.load_block(1)
        lc = await asyncio.to_thread(
            Client,
            gen.chain_id,
            TrustOptions(
                period_ns=3600 * 10**9, height=1, hash=trust.hash()
            ),
            ForgingPrimary(),
            witnesses=[witness],
        )
        # (1)+(2)+(3): the forged header VERIFIES (that is the attack),
        # the witness cross-check detects it, evidence is reported
        with pytest.raises(DivergenceError) as exc:
            await asyncio.to_thread(
                lc.verify_light_block_at_height, ATTACK_H
            )
        ev = exc.value.evidence
        assert bytes(ev.conflicting_block.hash()) == bytes(
            forged_header.hash()
        )
        byz_addrs = {pv.pub_key().address() for pv in byz}
        assert {
            v.address for v in ev.byzantine_validators
        } == byz_addrs

        # (4) the witness's node accepted it into its pool (via its
        # real broadcast_evidence RPC) and (5) it gossips to all
        def lca_at_apps():
            return all(_app_saw_lca(n) for n in nodes)

        def _app_saw_lca(n):
            seen = {
                r[2]
                for r in n.parts.app.misbehavior_seen
                if r[1] == MISBEHAVIOR_LIGHT_CLIENT_ATTACK
            }
            return byz_addrs <= seen

        await _wait(
            lambda: any(
                n.parts.evpool.pending_evidence(1 << 20) for n in nodes
            )
            or lca_at_apps(),
            30,
            "evidence at nodes",
        )

        # (6)+(7) committed on-chain and delivered to every app with
        # both attackers' powers
        await _wait(lca_at_apps, 60, "LCA misbehavior at apps")
        for n in nodes:
            for pv in byz:
                _, val = vs.get_by_address(pv.pub_key().address())
                recs = [
                    r
                    for r in n.parts.app.misbehavior_seen
                    if r[1] == MISBEHAVIOR_LIGHT_CLIENT_ATTACK
                    and r[2] == pv.pub_key().address()
                ]
                assert recs, f"no LCA record for {pv} at {n}"
                assert recs[0][3] == val.voting_power

        found = False
        for height in range(1, nodes[0].height + 1):
            blk = nodes[0].parts.block_store.load_block(height)
            if blk is not None and blk.evidence:
                found = True
        assert found, "LCA evidence never landed in a committed block"

        honest0.close()
        witness.close()
        for n in nodes:
            await n.stop()

    run(main())
