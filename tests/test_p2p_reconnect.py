"""Self-healing connectivity plane (p2p/reconnect.py + switch dedup).

1. Lane mechanics: fast-lane budget -> slow-lane park -> the sweep
   reconnects after heal (the healed-minority starvation regression at
   switch level — at HEAD-before semantics the finite budget abandoned
   the peer and the minority stayed isolated forever), backoff reset
   on success, counters + the p2p.reconnect span.
2. Incarnation-safe dialing: a restarted remote's fresh dial evicts
   the zombie entry instead of being dup-discarded; simultaneous
   cross-dials resolve deterministically (lower dialer node id wins on
   both ends, loser closed synchronously).
3. Starvation -> PEX re-learn storm on dial success.
4. lp2p parity: the same healed-minority scenario over Lp2pSwitch
   (the plane is shared by inheritance).
5. RPC health `connectivity` verdict.
"""

import asyncio

from cometbft_tpu.chaos.links import LinkTable
from cometbft_tpu.lp2p import Lp2pSwitch
from cometbft_tpu.p2p import (
    ChannelDescriptor,
    MemoryTransport,
    NodeInfo,
    NodeKey,
    Reactor,
    Switch,
)
from cometbft_tpu.trace import Tracer


def run(coro, timeout=60):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# small budgets so a test crosses fast lane -> slow lane in well under
# a second instead of minutes
FAST_RECONNECT = {
    "base_s": 0.02,
    "cap_s": 0.08,
    "fast_attempts": 2,
    "slow_interval_s": 0.15,
    "starvation_s": 0.2,
}


class SinkReactor(Reactor):
    name = "sink"
    CHAN = 0x7A

    def __init__(self):
        super().__init__()
        self.added = []
        self.removed = []

    def get_channels(self):
        return [ChannelDescriptor(self.CHAN, priority=1)]

    def add_peer(self, peer):
        self.added.append(peer.peer_id)

    def remove_peer(self, peer, reason):
        self.removed.append(peer.peer_id)

    def receive(self, chan_id, peer, msg):
        pass


def _mem_switch(table=None, cls=Switch, chain="reconnect-test", **kw):
    nk = NodeKey.generate()
    info = NodeInfo(node_id=nk.node_id, network=chain)
    tr = MemoryTransport(nk, info, link_hook=table)
    sw = cls(tr, info, reconnect_config=dict(FAST_RECONNECT), **kw)
    sw.add_reactor("sink", SinkReactor())
    sw.tracer = Tracer(name=nk.node_id[:8], size=2048)
    return sw


async def _mesh(switches):
    """Ring-dial (i -> i+1): with 3 switches that is the full mesh,
    and EVERY switch owns one persistent outbound dial — so each
    side's reconnect plane has something to redial (the reference
    semantics only redial peers *we* dialed)."""
    for sw in switches:
        await sw.transport.listen()
        await sw.start()
    n = len(switches)
    for i, a in enumerate(switches):
        b = switches[(i + 1) % n]
        await a.dial_peer(
            f"{b.node_info.node_id}@mem://{b.node_info.node_id}",
            persistent=True,
        )
    for sw in switches:
        for _ in range(200):
            if sw.num_peers() == n - 1:
                break
            await asyncio.sleep(0.01)
        assert sw.num_peers() == n - 1


async def _wait(cond, timeout=20.0, what=""):
    for _ in range(int(timeout / 0.02)):
        if cond():
            return
        await asyncio.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def _kill_all_conns(sw):
    for peer in list(sw.peers.values()):
        peer.inject_error(ConnectionError("pong timeout (injected)"))


def _minority_scenario(cls):
    """Partition the minority off, kill its conns (pong-timeout
    style), let every fast lane exhaust into the slow lane, heal —
    the sweep must reconverge the full mesh. The pre-plane semantics
    (finite attempts, no slow lane) fail this: after exhaustion
    nobody ever redials."""

    async def main():
        table = LinkTable(seed=7)
        sws = [_mem_switch(table, cls=cls) for _ in range(3)]
        try:
            await _mesh(sws)
            minority = sws[2]
            others = [sws[0], sws[1]]
            table.partition(
                [
                    [s.node_info.node_id for s in others],
                    [minority.node_info.node_id],
                ]
            )
            _kill_all_conns(minority)
            await _wait(
                lambda: minority.num_peers() == 0
                and all(s.num_peers() == 1 for s in others),
                what="conn deaths to propagate",
            )
            # the partition outlasts the whole fast budget: each
            # side's fast lane must PARK its dead persistent peer
            # (minority dialed sws[0]; sws[1] dialed the minority),
            # not give up
            await _wait(
                lambda: minority.reconnect.slow_parks_total >= 1
                and sws[1].reconnect.slow_parks_total >= 1,
                what="fast budgets to exhaust into the slow lane",
            )
            assert minority.reconnect.slow_lane, "peers abandoned!"
            assert minority.reconnect.attempts_total >= 2
            assert minority.reconnect.flaps_total >= 2
            table.heal()
            await _wait(
                lambda: all(s.num_peers() == 2 for s in sws),
                what="slow-lane sweep to reconverge the mesh",
            )
            # success resets the backoff (next flap starts fast) and
            # drains both lanes
            plane = minority.reconnect
            assert not plane.slow_lane and not plane._fast_tasks
            for bo in plane._backoffs.values():
                assert bo.attempt == 0
            assert plane.recoveries_total >= 1
            # convergence is a recorded span (budget-gated in chaos)
            spans = [
                e
                for e in minority.tracer.snapshot()
                if e["name"] == "p2p.reconnect"
            ]
            assert spans, "no p2p.reconnect span recorded"
            assert any(
                e["args"].get("recovered") for e in spans
            ), spans
        finally:
            for sw in sws:
                await sw.stop()

    run(main())


def test_healed_minority_reconverges_native_switch():
    _minority_scenario(Switch)


def test_healed_minority_reconverges_lp2p_switch():
    # parity: Lp2pSwitch inherits the same plane (shared lifecycle)
    _minority_scenario(Lp2pSwitch)


def test_boot_dial_failure_routes_to_plane():
    """A persistent dial that fails before ANY conn existed (target
    down at boot) must land on the plane — and succeed once the
    target appears."""

    async def main():
        table = LinkTable(seed=11)
        a = _mem_switch(table)
        b = _mem_switch(table)
        await a.transport.listen()
        await a.start()
        # b exists as a hub target id but is partitioned off
        await b.transport.listen()
        table.partition(
            [[a.node_info.node_id], [b.node_info.node_id]]
        )
        try:
            await a.dial_peer(
                f"{b.node_info.node_id}@mem://{b.node_info.node_id}",
                persistent=True,
            )
        except Exception:
            pass
        assert a.reconnect.is_scheduled(b.node_info.node_id)
        assert a.reconnect.dial_failures_total >= 1
        await b.start()
        table.heal()
        await _wait(
            lambda: a.num_peers() == 1 and b.num_peers() == 1,
            what="boot-failed dial to recover via the plane",
        )
        await a.stop()
        await b.stop()

    run(main())


def test_restarted_incarnation_evicts_zombie_entry():
    """The rejoin wedge: A holds a still-open conn to B's PREVIOUS
    life; restarted B (same node id, fresh incarnation) dials A. The
    old semantics dup-discarded the fresh conn against the zombie
    entry — now the zombie is evicted synchronously and the fresh
    conn registers."""

    async def main():
        a = _mem_switch()
        b1 = _mem_switch()
        # pin both to the same identity: b2 is b1's next incarnation
        key = b1.transport.node_key
        await _mesh([a, b1])
        bid = b1.node_info.node_id
        old_inc = a.peers[bid].node_info.incarnation
        assert old_inc  # incarnation rides the handshake

        # "restart" b: a fresh switch with the same key; b1's conn to
        # a is left OPEN (the zombie: a has no idea b died)
        info2 = NodeInfo(node_id=bid, network="reconnect-test")
        tr2 = MemoryTransport(key, info2)  # re-registers the mem hub
        b2 = Switch(tr2, info2, reconnect_config=dict(FAST_RECONNECT))
        b2.add_reactor("sink", SinkReactor())
        await b2.transport.listen()
        await b2.start()
        peer = await b2.dial_peer(
            f"{a.node_info.node_id}@mem://{a.node_info.node_id}",
            persistent=True,
        )
        assert peer is not None and peer.peer_id == a.node_info.node_id
        await _wait(
            lambda: a.peers.get(bid) is not None
            and a.peers[bid].node_info.incarnation
            == info2.incarnation,
            what="fresh incarnation to replace the zombie entry",
        )
        assert a.peers[bid].node_info.incarnation != old_inc
        assert a.num_peers() == 1  # replaced, not duplicated
        await a.stop()
        await b2.stop()
        b1.abort()

    run(main())


def test_acceptor_redial_beats_long_established_zombie():
    """One-sided death at the original ACCEPTOR: its redial must not
    be dup-discarded against the dialer's zombie entry (the cross-dial
    lower-id tiebreak only applies to genuinely simultaneous dials —
    a fresh conn against a LONG-established one is a redial and
    wins)."""

    async def main():
        a = _mem_switch()
        b = _mem_switch()
        await _mesh([a, b])  # ring: a dialed b AND b dialed a... 2
        # nodes: a->b and b->a are the same pair; keep only a's
        # outbound view by construction below
        aid, bid = a.node_info.node_id, b.node_info.node_id
        old_peer = a.peers[bid]
        # age the established conn out of the cross-dial window
        old_peer.established_at -= 60.0
        # one-sided death at b: b loses its ENTRY while the conn fds
        # stay open on both ends (a's registered conn is now a zombie
        # from b's point of view; a has noticed nothing)
        b.peers.pop(aid)
        await asyncio.sleep(0.05)
        # b's plane would redial; simulate the dial directly
        await b.dial_peer(f"{aid}@mem://{aid}", persistent=True)
        await _wait(
            lambda: a.peers.get(bid) is not None
            and a.peers[bid] is not old_peer,
            what="redial to evict the zombie entry at a",
        )
        assert a.num_peers() == 1 and b.num_peers() == 1
        await a.stop()
        await b.stop()

    run(main())


def test_simultaneous_cross_dial_resolves_deterministically():
    """Both sides dial at once: each pair must converge to exactly ONE
    conn, and the surviving conn is the one dialed by the LOWER node
    id on BOTH ends (no close/redial livelock)."""

    async def main():
        a = _mem_switch()
        b = _mem_switch()
        for sw in (a, b):
            await sw.transport.listen()
            await sw.start()
        aid, bid = a.node_info.node_id, b.node_info.node_id
        low = min(aid, bid)
        await asyncio.gather(
            a.dial_peer(f"{bid}@mem://{bid}", persistent=True),
            b.dial_peer(f"{aid}@mem://{aid}", persistent=True),
            return_exceptions=True,
        )
        await _wait(
            lambda: a.num_peers() == 1 and b.num_peers() == 1,
            what="cross-dial to settle on one conn per side",
        )
        # give any in-flight duplicate resolution a beat, then check
        # stability: still exactly one conn, consistent direction
        await asyncio.sleep(0.3)
        assert a.num_peers() == 1 and b.num_peers() == 1
        winner_dialed_by_a = a.peers[bid].outbound
        winner_dialed_by_b = b.peers[aid].outbound
        # exactly one side's outbound conn survived, and it is the
        # lower node id's
        assert winner_dialed_by_a != winner_dialed_by_b
        assert winner_dialed_by_a == (low == aid)
        await a.stop()
        await b.stop()

    run(main())


def test_starvation_triggers_pex_relearn():
    """Zero peers past the starvation threshold: the next dial success
    must fire a rate-limit-bypassing PEX request so the minority
    re-learns moved addresses immediately."""

    class PexStub(Reactor):
        name = "pex"

        def __init__(self):
            super().__init__()
            self.requested = []

        def get_channels(self):
            return []

        def request_now(self, peer):
            self.requested.append(peer.peer_id)

        def receive(self, chan_id, peer, msg):
            pass

    async def main():
        a = _mem_switch()
        b = _mem_switch()
        stub = a.add_reactor("pex", PexStub())
        for sw in (a, b):
            await sw.transport.listen()
            await sw.start()
        # a is MEANT to be connected (boot config names b) but has
        # zero peers past the threshold: starving
        bid = b.node_info.node_id
        a.persistent_addrs[bid] = f"mem://{bid}"
        await asyncio.sleep(0.3)
        assert a.reconnect.starving()
        # a switch with nothing to dial is NOT starving
        assert not b.reconnect.starving()
        await a.dial_peer(
            f"{b.node_info.node_id}@mem://{b.node_info.node_id}",
            persistent=True,
        )
        assert stub.requested == [b.node_info.node_id]
        assert not a.reconnect.starving()
        # starvation clock accumulated the episode
        assert a.reconnect.starvation_seconds() >= 0.3
        await a.stop()
        await b.stop()

    run(main())


def test_health_connectivity_verdict():
    """rpc health: ok for a node with nothing to dial; degraded (with
    reconnect detail) once it expects peers it does not have."""
    from cometbft_tpu.rpc import core
    from cometbft_tpu.rpc.env import Environment

    class StubStore:
        def height(self):
            return 0

        def load_block_meta(self, h):
            return None

    async def main():
        sw = _mem_switch()
        env = Environment(block_store=StubStore(), switch=sw)
        h = core.health(env)
        # no persistent peers, empty book, no flaps: no expectation
        assert h["connectivity"]["status"] == "ok"
        assert h["status"] == "ok"
        # now the node is MEANT to be connected and is not
        sw.persistent_addrs["deadbeef"] = "mem://deadbeef"
        h = core.health(env)
        conn = h["connectivity"]
        assert conn["status"] == "degraded"
        assert conn["n_peers"] == 0 and conn["min_peers"] >= 1
        assert any(
            "connectivity" in r for r in h["reasons"]
        ), h["reasons"]
        assert h["status"] == "degraded"

    run(main())
