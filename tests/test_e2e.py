"""E2E harness smoke test: manifest-driven multi-process net with a
kill/restart perturbation, a paused node, tx load, and a late
blocksync joiner (reference test/e2e, scaled down for CI)."""

import asyncio
import os

import pytest

from cometbft_tpu.e2e.manifest import Manifest
from cometbft_tpu.e2e import runner as runner_mod
from cometbft_tpu.e2e.runner import Runner

MANIFEST = {
    "chain_id": "e2e-smoke",
    "target_height": 12,
    "load_tx_rate": 4,
    "node": {
        "val0": {"mode": "validator", "evidence_at": 4, "grpc": True},
        "val1": {"mode": "validator", "kill_at": 5},
        "val2": {"mode": "validator", "pause_at": 4, "pause_s": 2.0},
        "val3": {
            "mode": "validator",
            "disconnect_at": 6,
            "disconnect_s": 2.0,
        },
        "val4": {"mode": "validator", "upgrade_at": 5},
        "full0": {
            "mode": "full",
            "start_at": 6,
            "block_sync": True,
        },
    },
}


@pytest.mark.slow
def test_e2e_smoke(tmp_path):
    m = Manifest.from_dict(MANIFEST)
    runner = Runner(m, str(tmp_path / "net"), base_port=27300)
    runner.setup()
    heights = {}
    try:
        ok = asyncio.run(
            asyncio.wait_for(
                runner.run(timeout_s=240.0),
                240
                + runner_mod.CONVERGENCE_BUDGET_S
                + runner_mod.POST_BUDGET_S,
            )
        )
        heights = {
            name: runner._height(rn)
            for name, rn in runner.nodes.items()
        }
    finally:
        runner.stop()
    assert ok, runner.failures
    # block-interval stats recorded (reference runner/benchmark.go)
    bench = getattr(runner, "benchmark", None)
    assert bench and bench["interval_mean_s"] > 0, bench
    # the killed validator recovered; the late full node blocksynced
    assert heights["val1"] >= m.target_height, heights
    assert heights["full0"] >= m.target_height, heights
    # the upgraded validator came back as the new version and rejoined
    assert getattr(runner, "_upgraded_ok", False), runner.failures
    assert heights["val4"] >= m.target_height, heights


def test_manifest_validation():
    with pytest.raises(ValueError):
        Manifest.from_dict({"node": {}})
    with pytest.raises(ValueError):
        Manifest.from_dict(
            {"node": {"a": {"mode": "full"}}}
        )
    m = Manifest.from_dict(MANIFEST)
    assert m.nodes["val0"].perturbations[0].kind == "evidence"
    assert m.nodes["val1"].perturbations[0].kind == "kill"
    assert m.nodes["val2"].perturbations[0].kind == "pause"
