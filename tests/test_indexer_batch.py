"""Per-height batched indexing + crash-consistent replay
(state/indexer.py, ISSUE 15)."""

import asyncio
import time

from cometbft_tpu import types as T
from cometbft_tpu.abci import types as abci
from cometbft_tpu.state.execution import encode_finalize_response
from cometbft_tpu.state.indexer import (
    LAST_INDEXED_KEY,
    BlockIndexer,
    IndexerService,
    TxIndexer,
)
from cometbft_tpu.state.store import Store as StateStore
from cometbft_tpu.store import BlockStore
from cometbft_tpu.types import events as ev
from cometbft_tpu.utils import codec, kv
from cometbft_tpu.utils.pubsub_query import parse as parse_query

NOW = int(time.time() * 1e9)
CHAIN = "idx-chain"


class CountingKV(kv.MemKV):
    def __init__(self):
        super().__init__()
        self.batches = 0

    def write_batch(self, sets, deletes=()):
        self.batches += 1
        super().write_batch(sets, deletes)


def _tx_result(i):
    return abci.ExecTxResult(
        code=0,
        events=[
            abci.Event(
                "transfer",
                [abci.EventAttribute("sender", f"addr{i}", True)],
            )
        ],
    )


def _make_block(vs, height, prev_bid, txs):
    data = T.Data(txs=txs)
    last_commit = (
        T.Commit(height - 1, 0, prev_bid, []) if height > 1 else None
    )
    header = T.Header(
        chain_id=CHAIN,
        height=height,
        time_ns=NOW + height,
        last_block_id=prev_bid,
        validators_hash=vs.hash(),
        next_validators_hash=vs.hash(),
        app_hash=b"\x01" * 32,
        proposer_address=vs.validators[0].address,
        data_hash=data.hash(),
        last_commit_hash=last_commit.hash() if last_commit else b"",
    )
    return T.Block(header=header, data=data, last_commit=last_commit)


def _publish_height(bus, blk, block_events):
    """The exact _fire_events shape (state/execution.py)."""
    bus.publish_type(
        ev.EVENT_NEW_BLOCK,
        {"block": blk, "block_id": None, "result_events": block_events},
        height=blk.height,
    )
    import hashlib

    for i, tx in enumerate(blk.data.txs):
        bus.publish_type(
            ev.EVENT_TX,
            {
                "height": blk.height,
                "index": i,
                "tx": tx,
                "result": _tx_result(i),
            },
            hash=hashlib.sha256(tx).hexdigest(),
        )


def _blocks(n, txs_per_height=2):
    vs, _ = T.random_validator_set(1)
    prev = T.BlockID()
    out = []
    for h in range(1, n + 1):
        txs = [b"k%d_%d=v" % (h, i) for i in range(txs_per_height)]
        blk = _make_block(vs, h, prev, txs)
        prev = T.BlockID(
            blk.hash(), T.PartSet.from_data(codec.encode_block(blk)).header
        )
        out.append(blk)
    return out


def _service(db=None):
    db = db if db is not None else CountingKV()
    svc = IndexerService(TxIndexer(db), BlockIndexer(db), ev.EventBus())
    return db, svc


BLOCK_EVENTS = [
    abci.Event("commit_meta", [abci.EventAttribute("lane", "a", True)])
]


def test_one_write_batch_per_height_inline():
    """No drain running (sync embedders): sealing a height flushes
    ONE atomic batch carrying every row AND the marker."""
    db, svc = _service()
    svc.start()
    blks = _blocks(3)
    for blk in blks:
        _publish_height(svc.bus, blk, BLOCK_EVENTS)
    assert db.batches == 3  # one per height, never per tx
    assert svc.tx_indexer.last_indexed_height() == 3
    # every tx row + attribute row queryable
    for h in (1, 2, 3):
        hits = svc.tx_indexer.search(parse_query(f"tx.height={h}"))
        assert len(hits) == 2
    hits = svc.tx_indexer.search(parse_query("transfer.sender='addr1'"))
    assert len(hits) == 3  # one per height (tx index 1)
    assert svc.block_indexer.search(
        parse_query("commit_meta.lane='a'")
    ) == [1, 2, 3]


def test_async_drain_flush_and_barrier():
    """With the drain running, publishes do ZERO db work inline; the
    barrier gives read-your-writes."""

    async def main():
        db, svc = _service()
        svc.start()
        svc.bus.set_loop(asyncio.get_running_loop())
        await svc.start_async()
        blks = _blocks(4)
        for blk in blks:
            _publish_height(svc.bus, blk, BLOCK_EVENTS)
        # publish path touched NO db (seal handed to the drain)
        assert db.batches <= 4
        await svc.barrier()
        assert db.batches == 4
        assert svc.tx_indexer.last_indexed_height() == 4
        assert svc.flushed_heights == 4
        await svc.stop()

    asyncio.run(main())


def test_zero_tx_height_seals_immediately():
    db, svc = _service()
    svc.start()
    blk = _blocks(1, txs_per_height=0)[0]
    _publish_height(svc.bus, blk, BLOCK_EVENTS)
    assert db.batches == 1
    assert svc.tx_indexer.last_indexed_height() == 1


def _stores_with_chain(n_heights):
    """A block store + state store holding n committed heights with
    stored finalize responses (tx + block events persisted — the
    replay source)."""
    bdb, sdb = kv.MemKV(), kv.MemKV()
    bs, ss = BlockStore(bdb), StateStore(sdb)
    blks = _blocks(n_heights)
    for blk in blks:
        pset = T.PartSet.from_data(codec.encode_block(blk))
        bs.save_block(
            blk, pset, T.Commit(blk.height, 0, T.BlockID(blk.hash(), pset.header), [])
        )
        resp = abci.ResponseFinalizeBlock(
            events=BLOCK_EVENTS,
            tx_results=[
                _tx_result(i) for i in range(len(blk.data.txs))
            ],
            app_hash=b"\x01" * 32,
        )
        ss.save_finalize_block_response(
            blk.height, encode_finalize_response(resp)
        )
    return bs, ss, blks


def test_kill_mid_index_restart_replays_no_gap_no_dup():
    """Crash contract: the idx:last marker rides the same atomic
    batch as its height's rows, so a kill between heights leaves
    marker == last fully indexed height; a restarted service replays
    forward from the marker and the result has NO gap and NO
    duplicate attribute rows — and replaying again changes nothing
    (idempotent)."""
    bs, ss, blks = _stores_with_chain(5)
    db, svc = _service()
    svc.start()
    # live-index heights 1..3, then "kill" (drop the service; height
    # 4-5 events never processed — the mid-index crash)
    for blk in blks[:3]:
        _publish_height(svc.bus, blk, BLOCK_EVENTS)
    assert svc.tx_indexer.last_indexed_height() == 3
    snapshot_after_crash = dict(db._d)

    # restart: a FRESH service over the same db replays from marker
    db2, svc2 = _service(db)
    assert svc2.replay(bs, ss) == 2  # heights 4..5 only
    assert svc2.tx_indexer.last_indexed_height() == 5
    # NO GAP: every height's txs and attributes are queryable
    for h in range(1, 6):
        hits = svc2.tx_indexer.search(parse_query(f"tx.height={h}"))
        assert len(hits) == 2, h
    assert svc2.block_indexer.search(
        parse_query("commit_meta.lane='a'")
    ) == [1, 2, 3, 4, 5]
    # NO DUP: exact attribute-row census — 2 tx.height rows + 2
    # transfer.sender rows per height, 5 heights
    tx_attr_rows = [
        k for k, _ in db.iter_prefix(b"tx:a:tx.height=")
    ]
    assert len(tx_attr_rows) == len(set(tx_attr_rows)) == 10
    sender_rows = [
        k for k, _ in db.iter_prefix(b"tx:a:transfer.sender=")
    ]
    assert len(sender_rows) == len(set(sender_rows)) == 10
    # the crash-surviving prefix was not rewritten differently
    for k, v in snapshot_after_crash.items():
        if k != LAST_INDEXED_KEY:
            assert db._d[k] == v, k
    # IDEMPOTENT: a second replay is a no-op on content
    full = dict(db._d)
    assert svc2.replay(bs, ss) == 0  # marker says all done
    assert dict(db._d) == full
    # and even a forced re-run over indexed heights rewrites
    # byte-identical rows (marker rolled back by hand)
    db.set(LAST_INDEXED_KEY, b"\x00" * 8)
    assert svc2.replay(bs, ss) == 5
    assert dict(db._d) == full


def test_marker_advances_contiguously_out_of_order():
    """The overflow path can flush a NEWER height while older ones
    still sit in the in-memory queue: the idx:last marker must lag
    until the gap closes, or a crash would skip the queued heights
    on replay (the 'every height <= marker is fully indexed'
    contract). Replay's ascending walk is anchored and may jump."""
    from cometbft_tpu.state.indexer import HeightBundle

    db, svc = _service()
    blks = _blocks(4)
    bundles = [
        HeightBundle(
            b.height,
            [(i, tx, _tx_result(i)) for i, tx in enumerate(b.data.txs)],
            BLOCK_EVENTS,
        )
        for b in blks
    ]
    svc._flush(bundles[0])  # h=1
    assert svc.tx_indexer.last_indexed_height() == 1
    svc._flush(bundles[3])  # h=4 out of order: marker must NOT jump
    assert svc.tx_indexer.last_indexed_height() == 1
    svc._flush(bundles[2])  # h=3: still gapped below
    assert svc.tx_indexer.last_indexed_height() == 1
    svc._flush(bundles[1])  # h=2 closes the gap -> marker catches up
    assert svc.tx_indexer.last_indexed_height() == 4
    assert svc._done_heights == set()
    # anchored (replay) flush over a pruned-style gap may jump
    db2, svc2 = _service()
    svc2._flush(bundles[3], anchored=True)
    assert svc2.tx_indexer.last_indexed_height() == 4


def test_joiner_far_above_marker_still_advances():
    """A statesync-restored joiner live-indexes from snapshot+1 with
    idx:last still 0 and the gap below pruned: the first live-sealed
    height anchors the contiguity floor, so the marker advances
    (heights below it can only ever arrive via replay()'s anchored
    walk) instead of parking every height in _done_heights forever."""
    from cometbft_tpu.state.indexer import HeightBundle

    db, svc = _service()
    for h in (50, 51, 52):
        svc._seal(
            HeightBundle(h, [(0, b"j%d=v" % h, _tx_result(0))], BLOCK_EVENTS)
        )
    assert svc.tx_indexer.last_indexed_height() == 52
    assert svc._done_heights == set()
    # the floor never claims a height another LIVE seal could still
    # deliver: once 50 sealed first, nothing below 50 can seal
    assert svc._first_sealed == 50


def test_overflow_never_drops(monkeypatch):
    """A full drain queue flushes off-loop instead of shedding: index
    rows are never lost to backpressure (counted as overflow)."""

    async def main():
        db, svc = _service()
        monkeypatch.setattr(IndexerService, "QUEUE_SIZE", 2)
        svc._queue = type(svc._queue)(2, name="state.index")
        svc.start()
        svc.bus.set_loop(asyncio.get_running_loop())
        svc._loop = asyncio.get_running_loop()  # drain NOT running:
        # bundles pile into the tiny queue, overflow path kicks in
        blks = _blocks(6)
        for blk in blks:
            _publish_height(svc.bus, blk, BLOCK_EVENTS)
        # let the overflow to_thread flushes land, then start the
        # drain for the queued remainder
        await asyncio.sleep(0.3)
        await svc.start_async()
        await svc.barrier()
        deadline = asyncio.get_running_loop().time() + 5
        while svc.tx_indexer.last_indexed_height() < 6:
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.02)
        for h in range(1, 7):
            assert len(
                svc.tx_indexer.search(parse_query(f"tx.height={h}"))
            ) == 2
        assert svc._queue.dropped >= 1  # overflow was exercised
        await svc.stop()

    asyncio.run(asyncio.wait_for(main(), 30))


def test_overflow_flush_failure_counted(monkeypatch):
    """A failed OVERFLOW-path flush lands in the sealed-vs-flushed
    ledger exactly like a failed drain flush — otherwise barrier()
    burns its full timeout on every index query for the rest of the
    process (the height can only land via restart replay)."""

    async def main():
        db, svc = _service()
        svc._queue = type(svc._queue)(1, name="state.index")
        svc.start()
        svc.bus.set_loop(asyncio.get_running_loop())
        svc._loop = asyncio.get_running_loop()  # drain NOT running

        def boom(bundle, anchored=False):
            raise RuntimeError("disk hiccup (injected)")

        monkeypatch.setattr(svc, "_flush", boom)
        for blk in _blocks(3):
            _publish_height(svc.bus, blk, BLOCK_EVENTS)
        # 1 bundle queued, 2 overflowed into failing off-loop flushes
        deadline = asyncio.get_running_loop().time() + 5
        while svc.flush_failures < 2:
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.01)
        # drain the queued one (also fails) — ledger fully balanced,
        # so barrier() returns promptly instead of timing out
        await svc.start_async()
        t0 = asyncio.get_running_loop().time()
        await svc.barrier(timeout_s=5.0)
        assert asyncio.get_running_loop().time() - t0 < 4.0
        assert svc.flush_failures == 3
        await svc.stop()

    asyncio.run(main())


def test_reindex_event_marker_stays_contiguous(tmp_path):
    """cmd reindex-event with --start-height above idx:last+1 writes
    the rows but must NOT advance the crash marker over the gap —
    IndexerService.replay() walks from marker+1 and would skip the
    never-indexed heights forever. A pruned gap (below the store
    base) may still be jumped, mirroring replay's anchored walk."""
    from types import SimpleNamespace

    from cometbft_tpu.cmd.main import cmd_reindex_event

    data = tmp_path / "data"
    data.mkdir(parents=True)
    bdb = kv.open_kv("sqlite", str(data / "blockstore.db"))
    sdb = kv.open_kv("sqlite", str(data / "state.db"))
    bs, ss = BlockStore(bdb), StateStore(sdb)
    for blk in _blocks(6):
        pset = T.PartSet.from_data(codec.encode_block(blk))
        bs.save_block(
            blk,
            pset,
            T.Commit(
                blk.height, 0, T.BlockID(blk.hash(), pset.header), []
            ),
        )
        resp = abci.ResponseFinalizeBlock(
            events=BLOCK_EVENTS,
            tx_results=[_tx_result(i) for i in range(len(blk.data.txs))],
            app_hash=b"\x01" * 32,
        )
        ss.save_finalize_block_response(
            blk.height, encode_finalize_response(resp)
        )
    bdb.close()
    sdb.close()

    # partial reindex above the (zero) marker: rows land, marker
    # must stay put — heights 1..4 were never indexed
    args = SimpleNamespace(
        home=str(tmp_path), start_height=5, end_height=6
    )
    assert cmd_reindex_event(args) == 0
    idb = kv.open_kv("sqlite", str(data / "tx_index.db"))
    txi = TxIndexer(idb)
    assert txi.last_indexed_height() == 0
    assert len(txi.search(parse_query("tx.height=5"))) == 2
    idb.close()

    # a full run from the store base closes the gap and the marker
    # advances to the end
    args = SimpleNamespace(
        home=str(tmp_path), start_height=None, end_height=None
    )
    assert cmd_reindex_event(args) == 0
    idb = kv.open_kv("sqlite", str(data / "tx_index.db"))
    txi = TxIndexer(idb)
    assert txi.last_indexed_height() == 6
    for h in range(1, 7):
        assert len(txi.search(parse_query(f"tx.height={h}"))) == 2
    idb.close()
