"""Test configuration: force JAX onto a virtual 8-device CPU platform.

Multi-chip TPU hardware is not available in CI; shardings are validated on a
virtual CPU mesh exactly as the driver's dryrun does.  Must run before any
``import jax`` anywhere in the test process.
"""

import os

# overwrite, not setdefault: the ambient environment may pin
# JAX_PLATFORMS to a hardware plugin (e.g. axon)
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# This box has ONE cpu core: XLA-compiling the full verify kernel takes
# minutes, so framework tests route signature batches to the host
# verifier (identical dispatch/coalescing code, different backend). The
# kernel itself is covered by the differential tests in
# test_ed25519_verify.py, which budget for the compile.
from cometbft_tpu.crypto import batch as _batch  # noqa: E402

_batch.set_default_backend("cpu")

# persistent XLA compile cache (shared with bench.py): the tuple-form
# verify kernel costs minutes to compile per shape on this 1-core box;
# cached recompiles land in seconds across test runs
import jax  # noqa: E402

# a sitecustomize hook may have already force-registered a hardware
# platform via jax.config.update("jax_platforms", ...) — the env var
# above doesn't win against that; re-pin the config itself
jax.config.update("jax_platforms", "cpu")
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.dirname(__file__)), ".jax_cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)
