"""Native logdb storage engine (native/logdb.cpp via ctypes): KV
contract, batch atomicity on replay, torn-tail crash recovery,
compaction (reference analog: the cometbft-db engines)."""

import os
import random

import pytest

from cometbft_tpu.utils import logdb


pytestmark = pytest.mark.skipif(
    not logdb.available(), reason="g++ unavailable to build logdb"
)


def test_kv_contract(tmp_path):
    db = logdb.LogDB(str(tmp_path / "a.db"))
    # second opener must fail cleanly while we hold the flock
    with pytest.raises(OSError):
        logdb.LogDB(str(tmp_path / "a.db"))
    assert db.get(b"k") is None
    db.set(b"k", b"v1")
    assert db.get(b"k") == b"v1"
    db.set(b"k", b"v2")  # overwrite
    assert db.get(b"k") == b"v2"
    db.set(b"empty", b"")
    assert db.get(b"empty") == b""
    db.delete(b"k")
    assert db.get(b"k") is None
    db.delete(b"never-existed")  # no-op
    db.close()
    # use-after-close is a clean Python error, not a native crash
    with pytest.raises(OSError):
        db.get(b"k")


def test_batch_and_prefix_iteration(tmp_path):
    db = logdb.LogDB(str(tmp_path / "b.db"))
    sets = [(b"blk:%08d" % i, b"v%d" % i) for i in range(100)]
    sets += [(b"st:%04d" % i, b"s%d" % i) for i in range(10)]
    db.write_batch(sets, deletes=[])
    got = list(db.iter_prefix(b"blk:"))
    assert len(got) == 100
    assert got == sorted(got)  # ordered
    assert got[0] == (b"blk:00000000", b"v0")
    db.write_batch([], deletes=[b"blk:%08d" % i for i in range(50)])
    assert len(list(db.iter_prefix(b"blk:"))) == 50
    assert db.count() == 60
    db.close()


def test_persistence_across_reopen(tmp_path):
    path = str(tmp_path / "c.db")
    db = logdb.LogDB(path)
    rng = random.Random(7)
    model = {}
    for _ in range(300):
        k = b"k%03d" % rng.randrange(80)
        if rng.random() < 0.25:
            db.delete(k)
            model.pop(k, None)
        else:
            v = rng.randbytes(rng.randrange(0, 200))
            db.set(k, v)
            model[k] = v
    db.close()
    db2 = logdb.LogDB(path)
    assert db2.count() == len(model)
    for k, v in model.items():
        assert db2.get(k) == v, k
    db2.close()


def test_torn_tail_truncated_on_replay(tmp_path):
    path = str(tmp_path / "d.db")
    db = logdb.LogDB(path)
    db.set(b"good", b"value")
    db.flush()
    db.close()
    size = os.path.getsize(path)
    # simulate a crash mid-append: garbage half-record at the tail
    with open(path, "ab") as f:
        f.write(b"\x01\x02\x03\x04\x05\x06\x07")
    db2 = logdb.LogDB(path)
    assert db2.get(b"good") == b"value"
    db2.set(b"after", b"recovery")
    db2.close()
    db3 = logdb.LogDB(path)
    assert db3.get(b"good") == b"value"
    assert db3.get(b"after") == b"recovery"
    db3.close()
    assert os.path.getsize(path) > size


def test_compaction_reclaims_dead_space(tmp_path):
    path = str(tmp_path / "e.db")
    db = logdb.LogDB(path)
    for i in range(50):
        db.set(b"hot", b"x" * 1000)  # 49 dead versions
        db.set(b"cold%02d" % i, b"y")
    before = os.path.getsize(path)
    freed = db.compact()
    assert freed > 45_000
    assert os.path.getsize(path) < before
    assert db.get(b"hot") == b"x" * 1000
    assert db.count() == 51
    # engine still writable after swap
    db.set(b"post", b"compaction")
    db.close()
    db2 = logdb.LogDB(path)
    assert db2.get(b"post") == b"compaction"
    assert db2.count() == 52
    db2.close()


def test_node_runs_on_logdb(tmp_path):
    """The block/state stores work end-to-end on the native engine."""
    import asyncio

    from cometbft_tpu.config.config import test_config
    from cometbft_tpu.node.inprocess import build_node, make_genesis

    gen, pvs = make_genesis(1, chain_id="logdb-chain")
    cfg = test_config(str(tmp_path))
    cfg.base.db_backend = "logdb"

    async def go():
        parts = build_node(gen, pvs[0], config=cfg, home=str(tmp_path))
        await parts.cs.start()
        for _ in range(400):
            if parts.block_store.height() >= 3:
                break
            await asyncio.sleep(0.05)
        assert parts.block_store.height() >= 3
        blk = parts.block_store.load_block(2)
        assert blk is not None and blk.height == 2
        await parts.cs.stop()
        parts.close_stores()

    asyncio.run(asyncio.wait_for(go(), 60))
    # reopen: chain state survived in the native engine (and the
    # exclusive flock was released by close_stores)
    parts2 = build_node(gen, pvs[0], config=cfg, home=str(tmp_path))
    assert parts2.block_store.height() >= 3
    parts2.close_stores()


def test_batch_is_crash_atomic(tmp_path):
    """A torn batch record must apply NONE of its ops on replay (the
    whole batch is one CRC frame)."""
    path = str(tmp_path / "f.db")
    db = logdb.LogDB(path)
    db.set(b"pre", b"existing")
    db.flush()
    pre_size = os.path.getsize(path)
    db.write_batch(
        [(b"height", b"h-1"), (b"meta", b"m")],
        deletes=[b"pre"],
    )
    db.close()
    full_size = os.path.getsize(path)
    # crash inside the batch: cut the file anywhere within the record
    with open(path, "r+b") as f:
        f.truncate(pre_size + (full_size - pre_size) // 2)
    db2 = logdb.LogDB(path)
    # nothing from the batch: no partial application
    assert db2.get(b"height") is None
    assert db2.get(b"meta") is None
    assert db2.get(b"pre") == b"existing"
    db2.close()
