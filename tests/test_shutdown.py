"""Bounded-shutdown hardening (obs/shutdown.py, docs/OBS.md).

1. ShutdownGuard unit behavior: completed stages pass, overrunning
   stages are flight-recorded + cancelled, cancel-ignoring stages are
   abandoned, later stages still run.
2. The regression the plane exists for: a node whose reactor stop()
   HANGS (the CHANGES.md PR 7 full-suite wedge class) must still
   complete Node.stop() within its budget, leave a flight-recorder
   dump in the trace ring, and release its store fds.
3. ChaosNet.stop() is bounded end-to-end under the same injected
   hang.
"""

import asyncio
import os

import pytest

from cometbft_tpu.obs.shutdown import ShutdownGuard
from cometbft_tpu.trace import Tracer


def run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# --- 1. ShutdownGuard unit behavior -------------------------------------


def test_guard_clean_stage_completes():
    async def main():
        guard = ShutdownGuard(name="t", budget_s=5.0)
        done = []

        async def ok_stage():
            done.append(1)

        assert await guard.stage("ok", ok_stage()) is True
        assert guard.clean and not guard.stalls and done == [1]

    run(main())


def test_guard_overrun_stage_is_recorded_cancelled_and_bounded():
    async def main():
        tracer = Tracer(name="t", size=256)
        guard = ShutdownGuard(tracer=tracer, name="t", budget_s=0.2)
        cancelled = []

        async def hang():
            try:
                await asyncio.sleep(60)
            except asyncio.CancelledError:
                cancelled.append(1)
                raise

        t0 = asyncio.get_running_loop().time()
        ok = await guard.stage("wedge", hang())
        elapsed = asyncio.get_running_loop().time() - t0
        assert ok is False
        assert elapsed < 5.0, "stage was not bounded"
        assert cancelled == [1], "escalation never cancelled the stage"
        # flight record captured mid-hang, with the stage task's stack
        assert len(guard.stalls) == 1
        rec = guard.stalls[0]
        assert rec["stage"] == "wedge"
        assert "hang" in rec.get("stage_stack", "")
        assert not guard.abandoned  # it honored its cancel
        # and it landed on the trace ring next to whatever was running
        names = [e["name"] for e in tracer.snapshot()]
        assert "obs.shutdown.stall" in names
        assert "obs.shutdown.tasks" in names

    run(main())


def test_guard_cancel_ignoring_stage_is_abandoned_and_later_stages_run():
    async def main():
        guard = ShutdownGuard(name="t", budget_s=0.2)
        ran_after = []
        release = asyncio.Event()

        async def ignores_cancel():
            while not release.is_set():
                try:
                    await asyncio.sleep(60)
                except asyncio.CancelledError:
                    continue  # the wedge class: swallowed cancel

        async def after():
            ran_after.append(1)

        assert await guard.stage("zombie", ignores_cancel()) is False
        assert guard.abandoned == ["zombie"]
        assert await guard.stage("after", after()) is True
        assert ran_after == [1]
        release.set()  # let the zombie die with the loop

    run(main())


def test_guard_stage_exception_is_swallowed_and_stage_counts_done():
    async def main():
        guard = ShutdownGuard(name="t", budget_s=1.0)

        async def boom():
            raise RuntimeError("already dead")

        assert await guard.stage("boom", boom()) is True
        assert guard.clean  # failing fast is not a stall

    run(main())


# --- 2. the hanging-reactor regression ----------------------------------


def _hang_reactor_stop(node, release: asyncio.Event):
    """Swap the mempool reactor's stop() for one that ignores its
    cancel until released — the injected wedge."""

    async def hanging_stop():
        while not release.is_set():
            try:
                await asyncio.sleep(60)
            except asyncio.CancelledError:
                continue

    node.mempool_reactor.stop = hanging_stop


def test_node_stop_survives_hanging_reactor_stop(tmp_path):
    """A reactor stop() that never returns (and swallows its cancel)
    must not wedge Node.stop(): shutdown completes within the staged
    budget, the breach is flight-recorded into the trace ring, and
    the store fds are released (a rebuild on the same home works)."""
    from cometbft_tpu.config.config import test_config
    from cometbft_tpu.node.inprocess import make_genesis
    from cometbft_tpu.node.node import Node
    from cometbft_tpu.p2p import MemoryTransport, NodeInfo, NodeKey

    async def main():
        gen, pvs = make_genesis(1, chain_id="shutdown-test")
        home = str(tmp_path / "n0")
        os.makedirs(home, exist_ok=True)

        def build():
            cfg = test_config(home)
            cfg.base.moniker = "n0"
            cfg.base.db_backend = "sqlite"
            cfg.rpc.laddr = ""
            cfg.blocksync.enable = False
            cfg.p2p.pex = False
            # small budgets so the test is fast; escalation still has
            # to run its full stop->cancel->abandon ladder
            cfg.instrumentation.shutdown_stage_budget_s = 0.3
            key = NodeKey.generate()
            info = NodeInfo(
                node_id=key.node_id, network=gen.chain_id, moniker="n0"
            )
            return Node(
                cfg, gen, privval=pvs[0], node_key=key,
                transport=MemoryTransport(key, info), home=home,
            )

        node = build()
        await node.start()
        for _ in range(600):
            if node.height >= 1:
                break
            await asyncio.sleep(0.05)
        assert node.height >= 1

        release = asyncio.Event()
        _hang_reactor_stop(node, release)
        t0 = asyncio.get_running_loop().time()
        await asyncio.wait_for(node.stop(), 30.0)
        elapsed = asyncio.get_running_loop().time() - t0
        # bounded: staged budget + cancel grace, nowhere near a hang
        assert elapsed < 15.0, f"stop took {elapsed:.1f}s"

        guard = node.shutdown_guard
        assert guard is not None and not guard.clean
        stages = [r["stage"] for r in guard.stalls]
        # the hang lives inside the switch stage (reactor stops run
        # under Switch.stop, each bounded at 5s > our 0.3s budget)
        assert "switch" in stages, stages
        # flight-recorder dump landed in the TRACE RING
        names = [e["name"] for e in node.parts.tracer.snapshot()]
        assert "obs.shutdown.stall" in names
        # the hung stage was abandoned but stores were still released:
        # a rebuild on the same home must reopen every database
        release.set()
        node2 = build()
        await node2.start()
        assert node2.height >= 1  # recovered the committed chain
        await asyncio.wait_for(node2.stop(), 30.0)

    run(main())


def test_chaosnet_stop_is_bounded_with_hanging_reactor(tmp_path):
    """The full-suite wedge regression: ChaosNet.stop() with one
    node's reactor stop() wedged completes within budget and the
    report surfaces the shutdown stall records."""
    from cometbft_tpu.chaos.net import ChaosNet

    async def main():
        def hook(cfg):
            cfg.instrumentation.shutdown_stage_budget_s = 0.3

        net = ChaosNet(
            2, seed=5150, base_dir=str(tmp_path), config_hook=hook
        )
        await net.start()
        release = asyncio.Event()
        try:
            for _ in range(600):
                if net.max_height() >= 1:
                    break
                await asyncio.sleep(0.05)
            _hang_reactor_stop(net.nodes[0].node, release)
        finally:
            t0 = asyncio.get_running_loop().time()
            await asyncio.wait_for(net.stop(), 60.0)
            elapsed = asyncio.get_running_loop().time() - t0
        assert elapsed < 30.0, f"net.stop took {elapsed:.1f}s"
        stalls = net.shutdown_stall_records()
        assert stalls, "breach was not flight-recorded"
        assert any(r.get("stage") == "switch" for r in stalls), stalls
        release.set()

    run(main())


def test_abandoned_switch_stage_still_kills_conns_so_restart_rejoins(
    tmp_path,
):
    """The rejoin wedge the scenario matrix surfaced: if a node's
    switch stop stage is abandoned with its conns left OPEN, peers
    keep a live zombie peer entry and dup-discard every dial from the
    node's next incarnation — it can never rejoin. The escalation
    floor (Switch.abort on an abandoned stage) must close the fds so
    peers drop the zombie and the restarted node reconnects and the
    net keeps committing."""
    from cometbft_tpu.chaos.net import ChaosNet

    async def main():
        def hook(cfg):
            cfg.instrumentation.shutdown_stage_budget_s = 0.2

        net = ChaosNet(
            3, seed=616, base_dir=str(tmp_path), config_hook=hook
        )
        await net.start()
        try:
            for _ in range(600):
                if net.max_height() >= 1:
                    break
                await asyncio.sleep(0.05)
            # wedge n0's whole switch stop: the stage must abandon it
            node0 = net.nodes[0].node

            async def hang():
                await asyncio.sleep(600)

            node0.switch.stop = hang
            await net.crash(0)
            stalls = net.nodes[0].shutdown_stalls
            assert any(r["stage"] == "switch" for r in stalls), stalls
            await asyncio.sleep(0.3)
            # peers must have dropped the zombie (abort closed the fds)
            for cn in net.nodes[1:]:
                assert net.nodes[0].node_id not in cn.node.switch.peers
            await net.restart(0)
            # the restarted incarnation must REJOIN: its peers accept
            # its dials and it keeps committing with the net
            n0 = net.nodes[0].node
            target = net.max_height() + 2
            for _ in range(1200):
                if n0.height >= target and n0.switch.num_peers() >= 2:
                    break
                await asyncio.sleep(0.05)
            assert n0.switch.num_peers() >= 2, "never reconnected"
            assert n0.height >= target, (
                f"wedged at {n0.height} < {target}: the zombie-conn "
                "rejoin failure"
            )
            net.agreement.final_check(net.running_nodes())
        finally:
            await asyncio.wait_for(net.stop(), 60.0)

    run(main())


# --- 3. WAL torn-tail repair (consensus/wal.py) -------------------------


def test_wal_repair_torn_tail_keeps_valid_prefix(tmp_path):
    from cometbft_tpu.consensus.wal import WAL, WALMessage

    path = str(tmp_path / "cs.wal")
    w = WAL(path)
    for h in (1, 2, 3):
        w.write_sync(WALMessage(kind=6, height=h))
    w.close()
    with open(path, "ab") as f:
        f.write(b"\xde\xad\xbe\xef torn tail garbage")
    # iteration already stops at the garbage…
    assert len(list(WAL.iter_messages(path))) == 3
    # …but WITHOUT repair, appended records after it would be lost:
    removed = WAL.repair_torn_tail(path)
    assert removed > 0
    w2 = WAL(path)
    w2.write_sync(WALMessage(kind=6, height=4))
    w2.close()
    msgs = list(WAL.iter_messages(path))
    assert [m.height for m in msgs] == [1, 2, 3, 4]
    # idempotent on a clean head
    assert WAL.repair_torn_tail(path) == 0


def test_wal_append_after_torn_tail_without_repair_loses_records(tmp_path):
    """Documents the hole the repair closes: garbage + append means
    the appended record is unreadable (this is WHY consensus start
    repairs before reopening)."""
    from cometbft_tpu.consensus.wal import WAL, WALMessage

    path = str(tmp_path / "cs.wal")
    w = WAL(path)
    w.write_sync(WALMessage(kind=6, height=1))
    w.close()
    with open(path, "ab") as f:
        f.write(b"\x00garbage\xff")
    w2 = WAL(path)  # raw open, no repair
    w2.write_sync(WALMessage(kind=6, height=2))
    w2.close()
    assert [m.height for m in WAL.iter_messages(path)] == [1]
