"""Runtime health plane (cometbft_tpu/obs) tier-1 suite.

Layers:
  1. loop watchdog: lag sampling + deterministic flight-recorder
     capture of an injected stall (offending frame present), overhead
     guard on the per-beat bookkeeping;
  2. sampling profiler: attributes a named hot function, folded
     output format, disabled/han-off cost bounds;
  3. backpressure telemetry: InstrumentedQueue counters, registry
     aggregation, bounded event-bus shed-and-count, put_nowait
     overhead guard;
  4. span budgets: evaluation semantics + the summarize --budget CLI
     exit-code contract (pass on recorded budgets, fail on an
     artificially blown one);
  5. the chaos stall acceptance: a seeded nemesis stall event is
     flight-recorded on every node with chaos_stall in the snapshot.
"""

import asyncio
import json
import threading
import time

import pytest

from cometbft_tpu.obs import (
    InstrumentedQueue,
    LoopWatchdog,
    QueueRegistry,
    SamplingProfiler,
    evaluate_budgets,
    format_verdicts,
    load_budgets,
)
from cometbft_tpu.trace import Tracer


def run(coro, timeout=240):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# --- 1. loop watchdog ----------------------------------------------------


def _blockingly_hog_the_loop(duration_s: float) -> None:
    """Named needle for the flight-record assertions below."""
    time.sleep(duration_s)


def test_watchdog_flight_records_injected_stall():
    """A synchronous callback blocking the loop past the threshold is
    snapshotted MID-STALL: the record's loop stack contains the
    offending frame, instants land on the trace ring, and the lag
    window registers the stall-sized lag afterwards."""
    tr = Tracer("wd", size=256)

    async def main():
        wd = LoopWatchdog(
            tracer=tr, interval_s=0.05, stall_s=0.15, name="wd-test"
        )
        wd.start()
        try:
            await asyncio.sleep(0.3)  # a few clean beats first
            _blockingly_hog_the_loop(0.7)
            await asyncio.sleep(0.3)  # let the post-stall beat land
        finally:
            wd.stop()
        return wd

    wd = run(main())
    assert wd.stall_count >= 1
    rec = wd.stalls[0]
    assert rec["stalled_s"] >= 0.15
    assert any(
        "_blockingly_hog_the_loop" in line for line in rec["loop_stack"]
    ), rec["loop_stack"]
    # task stacks captured alongside the thread frames
    assert rec["tasks"], rec
    # ring instants: the Perfetto-visible form, offending stack in args
    ev = tr.snapshot()
    stalls = [e for e in ev if e["name"] == "obs.stall"]
    assert stalls and "_blockingly_hog_the_loop" in (
        stalls[0]["args"]["loop_stack"]
    )
    assert any(e["name"] == "obs.stall.tasks" for e in ev)
    # the heartbeat that finally ran observed the stall as lag
    lag = wd.lag_stats()
    assert lag["samples"] >= 3
    assert lag["max_ms"] >= 150.0, lag
    # and lag spans rode the ring for the metrics bridge
    assert any(e["name"] == "obs.loop.lag" for e in ev)


def test_watchdog_quiet_loop_no_stalls():
    async def main():
        wd = LoopWatchdog(
            tracer=Tracer("q", size=64),
            interval_s=0.05,
            stall_s=0.5,
            name="quiet",
        )
        wd.start()
        try:
            for _ in range(6):
                await asyncio.sleep(0.05)
        finally:
            wd.stop()
        return wd

    wd = run(main())
    assert wd.stall_count == 0
    assert wd.last_stall_ago_s() is None
    assert wd.lag_stats()["samples"] >= 3


def test_watchdog_beat_bookkeeping_overhead_bounded():
    """The per-beat cost (_record_beat: one deque append + one ring
    append) must stay a handful of call-costs — it runs 10x/s on
    every node forever. Scaled baseline like test_trace's guard: an
    absolute ns bound would flake under full-suite contention on this
    throttled box."""
    import gc

    wd = LoopWatchdog(tracer=Tracer("ov", size=4096), name="ov")
    N = 20_000

    def per_call(fn):
        best = None
        for _ in range(5):
            t0 = time.perf_counter_ns()
            for _ in range(N):
                fn()
            dt = (time.perf_counter_ns() - t0) / N
            best = dt if best is None else min(best, dt)
        return best

    def noop():
        pass

    gc.disable()
    try:
        baseline = per_call(noop)
        now_ns = time.monotonic_ns()
        beat = per_call(lambda: wd._record_beat(0.001, now_ns))
        # disabled-tracer beat: the path every node pays when tracing
        # is off — must be cheaper still
        wd_off = LoopWatchdog(name="off")  # NOOP tracer
        beat_off = per_call(lambda: wd_off._record_beat(0.001, now_ns))
    finally:
        gc.enable()
    assert beat < max(20_000, 60 * baseline), (beat, baseline)
    assert beat_off < max(8_000, 25 * baseline), (beat_off, baseline)


# --- 2. sampling profiler ------------------------------------------------


def _spin_named(stop: "threading.Event") -> None:
    """CPU-burning needle the profiler must attribute."""
    x = 0
    while not stop.is_set():
        x = (x * 1103515245 + 12345) & 0xFFFFFFFF


def test_profiler_attributes_named_hot_function():
    stop = threading.Event()
    t = threading.Thread(target=_spin_named, args=(stop,), daemon=True)
    t.start()
    try:
        # poll-until-seen with a generous deadline: under full-suite
        # contention on this 2-vCPU box the sampler thread can starve
        # for long stretches, but a few samples MUST eventually catch
        # the cpu-pinned needle
        p = SamplingProfiler(hz=97).start()
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            time.sleep(0.2)
            if p.samples >= 10 and "_spin_named" in p.folded():
                break
        p.stop()
    finally:
        stop.set()
        t.join()
    assert p.samples >= 10
    folded = p.folded()
    assert "_spin_named" in folded, folded[:500]
    # collapsed format: every line is "stack count"
    for line in folded.splitlines():
        stack, _, count = line.rpartition(" ")
        assert stack and count.isdigit(), line
    top = p.top_lines(5)
    assert top and top[0]["samples"] >= 1 and 0 < top[0]["pct"] <= 100


def test_profiler_write_folded_and_idle_filter(tmp_path):
    stop = threading.Event()
    # a parked thread: must be filtered from the default profile
    idle = threading.Thread(target=stop.wait, daemon=True)
    idle.start()
    p = SamplingProfiler(hz=97).start()
    time.sleep(0.4)
    p.stop()
    stop.set()
    idle.join()
    path = p.write_folded(str(tmp_path / "p.folded"))
    text = open(path).read()
    assert text.startswith("#") and "Hz" in text.splitlines()[0]
    assert not any(
        ln.rpartition(" ")[0].endswith("threading:wait")
        for ln in text.splitlines()[1:]
        if ln
    ), text


def test_profiler_sample_cost_bounded():
    """One sample (all threads, bounded depth) must stay in the
    tens-of-microseconds class: at the default ~47 Hz that is <0.3%
    duty cycle. Bounded loosely (ms) so suite contention can't flake
    it while still catching accidental O(heap) work per sample."""
    p = SamplingProfiler(hz=1)
    best = None
    for _ in range(50):
        t0 = time.perf_counter_ns()
        p.sample_once()
        dt = time.perf_counter_ns() - t0
        best = dt if best is None else min(best, dt)
    assert best < 5_000_000, f"sample_once {best}ns"
    assert p.samples == 50


# --- 3. backpressure telemetry ------------------------------------------


def test_instrumented_queue_counters():
    async def main():
        q = InstrumentedQueue(4, name="t")
        for i in range(3):
            q.put_nowait(i)
        assert q.stats()["depth"] == 3
        assert q.high_watermark == 3
        q.get_nowait()
        await q.put(99)  # put() funnels through put_nowait
        assert q.enqueued == 4
        assert q.high_watermark == 3
        q.put_nowait(1)
        with pytest.raises(asyncio.QueueFull):
            q.put_nowait(2)
        q.count_drop()
        s = q.stats()
        assert s == {
            "depth": 4,
            "high_watermark": 4,
            "enqueued": 5,
            "dropped": 1,
            "maxsize": 4,
        }

    run(main())


def test_queue_registry_snapshot_and_aggregates():
    reg = QueueRegistry()
    q = InstrumentedQueue(8, name="a")
    reg.register_queue("a", lambda: q)
    reg.register("down", lambda: None)  # plane not running
    reg.register(
        "cb", lambda: {"depth": 2, "high_watermark": 7, "dropped": 3}
    )

    def boom():
        raise RuntimeError("torn read")

    reg.register("broken", boom)
    snap = reg.snapshot()
    assert set(snap) == {"a", "cb"}  # None + raising entries skipped
    assert reg.high_watermarks() == {"a": 0, "cb": 7}
    assert reg.total_dropped() == 3
    assert reg.get("down") is None and reg.get("broken") is None


def test_event_bus_bounded_subscribers_shed_and_count():
    from cometbft_tpu.types import events as ev

    async def main():
        bus = ev.EventBus()
        bus.set_loop(asyncio.get_running_loop())
        sub = ev.Subscription(bus, lambda e: True, queue_size=8)
        bus._subs.append(sub)
        for i in range(20):
            bus.publish(ev.Event("Tx", {"i": i}))
        # publish defers via call_soon_threadsafe; let it drain
        await asyncio.sleep(0.05)
        assert sub.queue.qsize() == 8  # bounded, not 20
        assert bus.dropped == 12
        assert sub.queue.dropped == 12
        stats = bus.queue_stats()
        assert stats["dropped"] == 12 and stats["subscribers"] == 1
        # the retained events are the OLDEST 8 (head-of-line kept)
        first = await sub.queue.get()
        assert first.data["i"] == 0

    run(main())


def test_instrumented_queue_put_overhead_bounded():
    """put_nowait adds two attribute writes + one compare over the
    stock queue — it is on the p2p per-message path, so bound the
    multiple."""
    import gc

    async def main():
        plain = asyncio.Queue(100_000)
        inst = InstrumentedQueue(100_000, name="ov")
        N = 30_000

        def timed(q):
            best = None
            for _ in range(4):
                while not q.empty():
                    q.get_nowait()
                t0 = time.perf_counter_ns()
                for i in range(N):
                    q.put_nowait(i)
                dt = (time.perf_counter_ns() - t0) / N
                best = dt if best is None else min(best, dt)
            return best

        gc.disable()
        try:
            base = timed(plain)
            ours = timed(inst)
        finally:
            gc.enable()
        assert ours < max(4 * base, base + 3000), (ours, base)

    run(main())


def test_node_queue_registry_wired():
    """A built Node registers every hot-plane queue and health reads
    them live."""
    from cometbft_tpu.config.config import test_config
    from cometbft_tpu.node.inprocess import make_genesis
    from cometbft_tpu.node.node import Node
    from cometbft_tpu.rpc import core
    from cometbft_tpu.rpc.env import Environment

    gen, pvs = make_genesis(1, chain_id="obs-reg")

    async def main():
        node = Node(test_config("."), gen, privval=pvs[0])
        await node.start()
        try:
            while node.height < 1:
                await asyncio.sleep(0.05)
            names = set(node.queues.names())
            assert {
                "mempool.ingest",
                "consensus.inbox",
                "events.subs",
                "p2p.send",
                "blocksync.window",
                "crypto.verify.dispatch",
            } <= names
            snap = node.queues.snapshot()
            assert snap["consensus.inbox"]["enqueued"] >= 1
            h = core.health(Environment.from_node(node))
            assert h["status"] in ("ok", "degraded")
            assert "consensus.inbox" in h["queue_high_watermarks"]
            assert "loop_lag_ms" in h
        finally:
            await node.stop()

    run(main())


# --- 4. span budgets -----------------------------------------------------

_BUDGET_TOML = """
[budget."k.fast"]
p95_ms = 10.0
p99_ms = 20.0
min_count = 3

[budget."k.rare"]
p99_ms = 1.0
min_count = 100

[budget."k.slow"]
max_ms = 5.0
"""


def _summary(slow_ms: float):
    from cometbft_tpu.trace import summarize

    events = [
        {"name": "k.fast", "ph": "X", "ts_ns": 0, "dur_ns": int(2e6)}
        for _ in range(10)
    ]
    events.append(
        {"name": "k.rare", "ph": "X", "ts_ns": 0, "dur_ns": int(9e6)}
    )
    events.append(
        {
            "name": "k.slow",
            "ph": "X",
            "ts_ns": 0,
            "dur_ns": int(slow_ms * 1e6),
        }
    )
    return summarize({"n0": events})


def test_budget_evaluation_semantics(tmp_path):
    p = tmp_path / "b.toml"
    p.write_text(_BUDGET_TOML)
    budgets = load_budgets(str(p))
    ok_rows = evaluate_budgets(_summary(slow_ms=1.0), budgets)
    # k.rare skipped (min_count 100 unmet) — a thin tail is not a pass
    assert {r["span"] for r in ok_rows} == {"k.fast", "k.slow"}
    assert all(r["ok"] for r in ok_rows)
    bad_rows = evaluate_budgets(_summary(slow_ms=50.0), budgets)
    over = [r for r in bad_rows if not r["ok"]]
    assert len(over) == 1 and over[0]["span"] == "k.slow"
    table = format_verdicts(bad_rows)
    assert "OVER" in table and "FAIL" in table
    assert "PASS" in format_verdicts(ok_rows)
    # unknown keys are a config error, not silence
    p2 = tmp_path / "bad.toml"
    p2.write_text('[budget."x"]\np95_sec = 1.0\n')
    with pytest.raises(ValueError):
        load_budgets(str(p2))


def test_summarize_budget_cli_exit_codes(tmp_path, capsys):
    """ISSUE 6 acceptance: summarize --budget fails (exit 2) on an
    artificially inflated span and passes on budgets that hold."""
    from cometbft_tpu.trace import write_jsonl
    from cometbft_tpu.trace.cli import main as trace_cli

    budget = tmp_path / "b.toml"
    budget.write_text('[budget."k.slow"]\nmax_ms = 5.0\n')
    slow = [
        {
            "name": "k.slow", "ph": "X", "ts_ns": 0,
            "dur_ns": int(80e6), "tid": "t",
        }
    ]
    fast = [dict(slow[0], dur_ns=int(1e6))]
    p_bad = write_jsonl(str(tmp_path / "bad.trace.jsonl"), "n0", slow)
    p_ok = write_jsonl(str(tmp_path / "ok.trace.jsonl"), "n0", fast)

    rc = trace_cli(["summarize", p_bad, "--budget", str(budget)])
    out = capsys.readouterr().out
    assert rc == 2 and "OVER" in out and "FAIL" in out

    rc = trace_cli(["summarize", p_ok, "--budget", str(budget)])
    out = capsys.readouterr().out
    assert rc == 0 and "PASS" in out

    # --json carries the verdicts structurally
    rc = trace_cli(
        ["summarize", "--json", p_bad, "--budget", str(budget)]
    )
    doc = json.loads(capsys.readouterr().out)
    assert rc == 2
    assert doc["budget_verdicts"][0]["span"] == "k.slow"
    assert doc["summary"]["n0"]["k.slow"]["count"] == 1


def test_checked_in_budget_file_loads():
    """The shipped tools/span_budgets.toml must parse and bound the
    span kinds the instrumented planes actually emit."""
    import os

    from cometbft_tpu.obs.budget import default_budget_file

    path = default_budget_file(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    budgets = load_budgets(path)
    assert {"consensus.step", "wal.fsync", "obs.loop.lag"} <= set(budgets)
    for span, entry in budgets.items():
        assert any(
            k in entry for k in ("p50_ms", "p95_ms", "p99_ms", "max_ms")
        ), span


# --- 5. chaos stall acceptance ------------------------------------------


def test_chaos_stall_is_flight_recorded(tmp_path):
    """ISSUE 6 acceptance: a forced loop stall under chaos produces a
    flight-recorder dump whose snapshot contains the offending frame,
    reproducible from one seed line — and the run stays
    invariant-clean (the stall is a perf fault, not a BFT one)."""
    from cometbft_tpu.chaos import FaultEvent, FaultSchedule, run_schedule

    async def main():
        return await run_schedule(
            FaultSchedule(
                [FaultEvent("stall", at_height=2, duration_s=1.2)]
            ),
            seed=606,
            base_dir=str(tmp_path / "net"),
            n_nodes=4,
            settle_heights=2,
            liveness_bound_s=120.0,
            trace_dir=str(tmp_path / "traces"),
        )

    report = run(main())
    assert report.ok, report.format()
    assert report.stall_records, "flight recorder missed the stall"
    assert any(
        any("chaos_stall" in ln for ln in r.get("loop_stack", []))
        for r in report.stall_records
    ), report.stall_records
    # the stall instants are in the dumped rings next to the spans
    from cometbft_tpu.trace import read_jsonl

    jsonls = [p for p in report.trace_files if p.endswith(".jsonl")]
    all_events = [
        e for evs in read_jsonl(jsonls).values() for e in evs
    ]
    stall_instants = [
        e for e in all_events if e["name"] == "obs.stall"
    ]
    assert stall_instants
    assert any(
        "chaos_stall" in e["args"].get("loop_stack", "")
        for e in stall_instants
    )
    # the chaos profiler wrote folded stacks beside the trace files
    assert report.profile_file and "profile.folded" in report.profile_file
