"""Crash-point recovery tests (reference consensus/replay_test.go +
libs/fail): kill a node at exact WAL/commit interleavings via
FAIL_TEST_INDEX, restart it, and require full recovery — the
subtle-bug farm called out in SURVEY.md §7."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rpc(port, path, timeout=3.0):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/{path}", timeout=timeout
    ) as r:
        return json.load(r)["result"]


def _wait_height(port, h, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            cur = int(
                _rpc(port, "status")["sync_info"]["latest_block_height"]
            )
            if cur >= h:
                return cur
        except Exception:
            pass
        time.sleep(0.3)
    raise TimeoutError(f"port {port} never reached height {h}")


def _launch(home, port, fail_index=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    if fail_index is not None:
        env["FAIL_TEST_INDEX"] = str(fail_index)
    return subprocess.Popen(
        [sys.executable, "-m", "cometbft_tpu", "--home", home, "start"],
        cwd=REPO,
        env=env,
        stdout=open(os.path.join(home, "node.log"), "a"),
        stderr=subprocess.STDOUT,
        start_new_session=True,
    )


@pytest.mark.parametrize("fail_index", [0, 2, 5, 9, 17])
def test_crash_at_fail_point_then_recover(tmp_path, fail_index):
    """Crash at the fail_index'th crash-point call, restart, verify the
    chain recovers and keeps producing (handshake replay repairs any
    store/app divergence)."""
    home = str(tmp_path / "node")
    port = 27400 + fail_index
    subprocess.run(
        [sys.executable, "-m", "cometbft_tpu", "--home", home, "init",
         "--chain-id", f"crash-{fail_index}"],
        cwd=REPO, check=True, capture_output=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    # point config at our test port + fast blocks
    cfg_path = os.path.join(home, "config", "config.toml")
    with open(cfg_path) as f:
        text = f.read()
    text = text.replace(
        'laddr = "tcp://0.0.0.0:26656"', 'laddr = "tcp://127.0.0.1:0"'
    ).replace(
        'laddr = "tcp://127.0.0.1:26657"',
        f'laddr = "tcp://127.0.0.1:{port}"',
    ).replace("timeout_commit_s = 1.0", "timeout_commit_s = 0.1")
    with open(cfg_path, "w") as f:
        f.write(text)

    # run with the crash armed; it must die with exit code 99
    proc = _launch(home, port, fail_index=fail_index)
    try:
        rc = proc.wait(timeout=120)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        raise
    assert rc == 99, f"expected fail-point death, got exit {rc}"

    # restart WITHOUT injection: must recover and keep producing
    proc = _launch(home, port)
    try:
        h = _wait_height(port, 3, timeout=90)
        # app state consistent: replayed chain serves queries
        res = _rpc(port, "abci_info")
        assert int(res["response"]["last_block_height"]) >= 1
        # and it's still advancing
        h2 = _wait_height(port, h + 2, timeout=30)
        assert h2 > h
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()
