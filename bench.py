"""North-star benchmarks (all five BASELINE.json configs).

1. kernel      — ed25519 batch verify throughput (headline metric)
2. batch64     — 64-signature BatchVerifier batch (small-batch latency)
3. commit150   — single 150-validator VerifyCommitLight latency
4. replay      — 10k-block x 150-validator blocksync replay wall-clock
5. bisect      — light-client bisection over a 50k-height skip
6. mixed       — mixed-curve (ed25519 + secp256k1) split batch
(+ host legs: ingest, live, pipeline, serve — the 1k-session
light-client serving storm, baseline vs shared-cache vs coalesced —
and rpcfanout — the 10k-subscriber outbound event fan-out storm,
one-encode-per-group vs per-subscriber serialization)

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} with
every config's numbers under "detail.configs". Baselines are the host
CPU path measured in-process (OpenSSL via `cryptography` — the fastest
CPU path in this image; same order as the reference's Go voi batch).

Env knobs: BENCH_N (kernel lanes), BENCH_REPLAY_BLOCKS (default
10000), BENCH_CONFIGS=comma list | "all" (default all).

NOTE (axon platform): block_until_ready does not block through the
tunnel; timings always fetch results to host.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
N_VALS = 150
# set False by main() when the accelerator probe fails: device
# measurements return None and configs report host numbers only
_DEVICE_OK = True

# --- budgets + incremental checkpointing --------------------------------
# BENCH_r05 failure mode: one wedged leg ate the driver's whole bench
# window and the round recorded rc=124 with parsed: null — every
# number measured before the wedge was lost. Three defenses:
#   1. every config runs under a per-config time budget (daemon
#      thread; a leg that blows it is abandoned and recorded as such);
#   2. the result JSON is checkpointed after EVERY config, so the
#      final line can always be assembled from partial results;
#   3. SIGTERM/SIGINT (the driver's `timeout` sends TERM first) print
#      the checkpointed line and exit 0 — partial results always land
#      on stdout's final line.

_CKPT = {"configs": {}, "t_start": None, "emitted": False}
_WEDGED: list = []
# sampling profiler attached to the whole run (obs/profiler.py):
# folded stacks are embedded in the final JSON so every bench line
# carries its own attribution. BENCH_PROFILE=0 disables.
_PROFILER = None

_DEFAULT_BUDGETS_S = {
    "corpus": 3600.0,
    "kernel": 1500.0,
    "replay": 5400.0,
    "bisect": 1500.0,
    "commit150": 600.0,
    "batch64": 600.0,
    "mixed": 600.0,
    "pipeline": 900.0,
    "live": 1500.0,
    "serve": 1200.0,
    "rpcfanout": 1200.0,
    "fleet": 1500.0,
    "scaling": 300.0,
    "verifysched": 600.0,
    "meshdryrun": 900.0,
}


def _config_budget_s(name: str) -> float:
    v = os.environ.get(f"BENCH_BUDGET_{name.upper()}")
    if v is None:
        v = os.environ.get("BENCH_CONFIG_BUDGET_S")
    if v is not None:
        return float(v)
    return _DEFAULT_BUDGETS_S.get(name, 900.0)


def _checkpoint_path() -> str:
    return os.environ.get(
        "BENCH_CHECKPOINT_PATH",
        os.path.join(REPO, ".bench_checkpoint.json"),
    )


def _final_payload() -> dict:
    """Assemble the headline JSON from whatever configs have landed —
    callable at ANY point (checkpoint after each config, signal
    handler, normal end of run)."""
    configs = _CKPT["configs"]
    headline = configs.get("kernel") or {}
    for leg_name in ("kernel_pallas_default", "kernel_precomp_tuple"):
        leg = configs.get(leg_name) or {}
        if (leg.get("rate") or 0) > (headline.get("rate") or 0):
            headline = leg
    metric = "ed25519_batch_verify_throughput"
    value = headline.get("rate")
    unit = "verifies/sec"
    vs_baseline = headline.get("vs_cpu")
    rep = configs.get("replay") or {}
    if (
        value is None
        and rep.get("wall_s")
        and rep.get("mode") == "host-only"
    ):
        # device headline unavailable: the HOST replay throughput is
        # the round's measured number — record it as the headline
        # rather than a null (VERDICT r4 weak #2); detail carries the
        # device outage note. Gated on mode so a device-path replay is
        # never mislabeled as host
        metric = "blocksync_replay_throughput_host"
        value = rep.get("blocks_per_s")
        unit = "blocks/sec (10k-block x 150-val replay, host pipeline)"
        vs_baseline = rep.get("parallel_vs_serial") or rep.get(
            "vs_sequential"
        )
    t0 = _CKPT["t_start"] or time.time()
    detail = {
        "configs": configs,
        "total_bench_s": round(time.time() - t0, 1),
    }
    if _PROFILER is not None and _PROFILER.samples:
        # folded-stack profile of the run so far (top stacks only:
        # the full collapsed file is a flamegraph input, not a JSON
        # payload; BENCH_PROFILE_OUT writes it separately)
        detail["profile"] = {
            "hz": _PROFILER.hz,
            "samples": _PROFILER.samples,
            "folded_top": _PROFILER.top_lines(25),
        }
    return {
        "metric": metric,
        "value": value,
        "unit": unit,
        "vs_baseline": vs_baseline,
        "detail": detail,
    }


def _record(name: str, entry: dict) -> None:
    """Land one config's numbers and re-checkpoint the full line."""
    _CKPT["configs"][name] = entry
    if os.environ.get("BENCH_CHILD") == "1":
        return  # children report via stdout; never clobber the
        # parent's checkpoint file
    try:
        tmp = _checkpoint_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(_final_payload(), f)
        os.replace(tmp, _checkpoint_path())
    except OSError:
        pass  # checkpointing is best-effort; stdout is authoritative


def _emit_final(note: "str | None" = None) -> None:
    if _CKPT["emitted"]:
        return
    _CKPT["emitted"] = True
    payload = _final_payload()
    if note:
        payload["detail"]["note"] = note
    print(json.dumps(payload), flush=True)


def _install_signal_handlers() -> None:
    import signal

    def _handler(signum, frame):
        _emit_final(
            note=f"interrupted by signal {signum}; every config "
            "recorded before the interrupt is present, the one in "
            f"flight is not (wedged so far: {_WEDGED or 'none'})"
        )
        os._exit(0)

    for s in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(s, _handler)
        except (ValueError, OSError):  # pragma: no cover - non-main thread
            pass


def _run_budgeted(name: str, fn):
    """Run one config under its time budget on a daemon thread. On
    overrun the leg is ABANDONED (the thread cannot be killed — it
    may be wedged inside a jit) and an honest entry records the
    budget; _WEDGED makes the caller skip the remaining in-process
    configs, since they would contend with the zombie leg."""
    budget = _config_budget_s(name)
    box: dict = {}

    def run():
        try:
            box["out"] = fn()
        except BaseException as e:  # report, never crash the bench
            box["err"] = repr(e)[:400]

    t = threading.Thread(target=run, daemon=True, name=f"bench-{name}")
    t.start()
    t.join(budget)
    if t.is_alive():
        _WEDGED.append(name)
        return {
            "rate": None,
            "note": f"leg killed by its {budget:.0f}s budget "
            "(abandoned on a daemon thread); later in-process "
            "configs skipped to avoid contending with it",
        }
    if "err" in box:
        return {"rate": None, "note": f"config failed: {box['err']}"}
    return box["out"]


def _ms(x):
    return None if x is None else round(x * 1e3, 2)


def _ratio(a, b):
    return None if (a is None or b is None) else round(a / b, 2)


def _setup_jax():
    import jax

    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # subprocess legs that must not touch the (possibly wedged)
        # axon platform: the env var alone loses to sitecustomize's
        # config pin, so re-pin here before any backend init
        jax.config.update("jax_platforms", "cpu")
    cache_dir = os.path.join(REPO, ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)
    except Exception:
        pass
    return jax


def _probe_timeout_s() -> float:
    return float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "180"))


def _probe_device(timeout_s: "float | None" = None) -> dict:
    """One tiny jit with a hard deadline. The tunneled device can wedge
    platform-wide (observed round 3: even `lambda a: a+1` hung >5 min);
    a hung bench records NOTHING for the round, so on a dead device the
    device configs are skipped and the JSON line says why instead.

    Returns a STRUCTURED verdict — ``{ok, reason, wall_s}`` — so the
    checkpointed ``device`` entry records WHAT failed (wedged jit vs
    init error vs clean) instead of a bare bool the JSON reader can't
    attribute; the caller degrades to the host path on any not-ok."""
    import threading

    if timeout_s is None:
        timeout_s = _probe_timeout_s()

    box = {"ok": False, "err": None}

    def run():
        try:
            import jax
            import jax.numpy as jnp

            np.asarray(jax.jit(lambda a: a + 1)(jnp.arange(4)))
            box["ok"] = True
        except Exception as e:
            box["err"] = repr(e)[:200]

    t0 = time.time()
    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout_s)
    wall = round(time.time() - t0, 2)
    if box["ok"]:
        return {"ok": True, "reason": "ok", "wall_s": wall}
    if t.is_alive():
        # the jit never returned: the probe thread is abandoned (it
        # cannot be cancelled) and the verdict says wedged, not failed
        return {
            "ok": False,
            "reason": f"wedged: tiny jit still running after "
            f"{timeout_s:.0f}s",
            "wall_s": wall,
        }
    return {
        "ok": False,
        "reason": f"error: {box['err'] or 'unknown'}",
        "wall_s": wall,
    }


# --- 1. kernel throughput (headline) -----------------------------------


def bench_kernel() -> dict:
    jax = _setup_jax()
    import jax.numpy as jnp

    from cometbft_tpu.crypto import ref_ed25519 as ref
    from cometbft_tpu.ops import ed25519 as ed

    rng = np.random.default_rng(42)
    # default batch = replay-scale coalescing (10k-block catch-up at
    # 150 validators yields ~1.5M signatures; 131072 lanes is where the
    # kernel saturates the chip)
    N = int(os.environ.get("BENCH_N", "131072"))
    CAP = 175  # covers canonical vote sign bytes (chain-id dependent)
    MSG_LEN = 120

    n_keys = N_VALS
    seeds = [rng.bytes(32) for _ in range(n_keys)]
    pubs = [ref.public_from_seed(s) for s in seeds]

    try:
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PrivateKey,
        )

        def sign(seed, m):
            return Ed25519PrivateKey.from_private_bytes(seed).sign(m)

    except Exception:  # pragma: no cover
        sign = ref.sign

    msgs = np.zeros((CAP, N), np.uint8)
    lens = np.full(N, MSG_LEN, np.int32)
    pks = np.zeros((32, N), np.uint8)
    rs = np.zeros((32, N), np.uint8)
    ss = np.zeros((32, N), np.uint8)
    host_items = []
    # distinct (msg, sig) pool sized like a large commit wave; lanes
    # cycle through it (signing N distinct messages on the host would
    # dominate bench wall time without changing the device work)
    pool = max(n_keys, min(N, 4096))
    pool_items = []
    for j in range(pool):
        k = j % n_keys
        m = rng.bytes(MSG_LEN)
        pool_items.append((k, m, sign(seeds[k], m)))
    for i in range(N):
        k, m, sig = pool_items[i % pool]
        msgs[:MSG_LEN, i] = np.frombuffer(m, np.uint8)
        pks[:, i] = np.frombuffer(pubs[k], np.uint8)
        rs[:, i] = np.frombuffer(sig[:32], np.uint8)
        ss[:, i] = np.frombuffer(sig[32:], np.uint8)
        host_items.append((pubs[k], m, sig))

    # measure the kernel production picks at this width (see
    # ops/ed25519.PRECOMP_MAX_LANES): plain for bulk widths, precomp
    # (host-expanded pubkeys) for latency-sensitive small batches.
    # GRAFT_PRECOMP_MAX_LANES + GRAFT_PRECOMP_TUPLE reach here so the
    # lever-#6 A/B leg can force tuple-form precomp at bulk widths.
    if N <= ed._precomp_max_lanes():
        a_arr = np.zeros((4, 20, N), np.int32)
        for i in range(N):
            k, _, _ = pool_items[i % pool]  # lane i's key, same as pks
            a_arr[:, :, i] = ed._expand_pubkey(pubs[k])
        if ed.precomp_tuple_enabled():
            arrays = (
                msgs, lens, ed.a_tree_from_stacked(a_arr),
                pks, rs, ss,
            )
            kernel = ed._verify_core_precomp_tuple
        else:
            arrays = (msgs, lens, a_arr, pks, rs, ss)
            kernel = ed._verify_core_precomp
    else:
        arrays = (msgs, lens, pks, rs, ss)
        kernel = ed._verify_core
    args = [jax.device_put(a) for a in arrays]
    comp = jax.jit(kernel).lower(*args).compile()
    out = np.asarray(comp(*args))  # warm-up + correctness
    assert out.all(), "benchmark signatures must all verify"

    # Chain several dispatches per fetch and subtract the measured
    # host<->device round-trip (~100ms tunnel latency is NOT kernel
    # time; production pipelines batches). Inputs re-derive from the
    # previous output so dispatches form a real dependency chain.
    CHAIN = 8
    tiny = jax.device_put(jnp.zeros((1,), jnp.int32))
    noopc = jax.jit(lambda x: x + 1).lower(tiny).compile()
    np.asarray(noopc(tiny))
    rts = []
    for _ in range(5):
        t0 = time.time()
        np.asarray(noopc(tiny))
        rts.append(time.time() - t0)
    rt = min(rts)

    times = []
    for trial in range(3):
        msgs[0, 0] = trial
        a0 = jax.device_put(jnp.asarray(msgs))
        t0 = time.time()
        got = None
        for k in range(CHAIN):
            got = comp(a0, *args[1:])
            a0 = a0.at[0, 0].set(
                (got[0].astype(jnp.uint8) + trial * (CHAIN + 1) + k + 1)
                & 0xFF
            )
        got = np.asarray(got)
        raw = (time.time() - t0) / CHAIN
        dt = (time.time() - t0 - rt) / CHAIN
        times.append(dt if dt > 0 else raw)
        assert got[1:].all()
    tpu_dt = min(times)
    tpu_rate = N / tpu_dt

    # CPU baseline: sequential OpenSSL verify on a sample, extrapolated
    sample = min(N, 1500)
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PublicKey,
    )

    t0 = time.time()
    for pk, m, sig in host_items[:sample]:
        Ed25519PublicKey.from_public_bytes(pk).verify(sig, m)
    cpu_rate = sample / (time.time() - t0)

    # self-report which ladder/kernel THIS run actually measured (the
    # headline label reads it back instead of re-deriving from env —
    # code-review r5: a duplicated BENCH_N literal could mislabel)
    from cometbft_tpu.ops.pallas_ladder import (
        block_sublanes,
        effective_block,
        pallas_enabled,
    )

    # label with the EFFECTIVE sublane block the kernel actually runs
    # (effective_block adjusts a non-dividing configured value, and
    # returns None when no VMEM-safe blocking exists — the kernel then
    # fell back to the XLA ladder; ADVICE r5 low)
    eff = (
        effective_block(block_sublanes(), N // 128)
        if (N % 128 == 0 and pallas_enabled(N))
        else None
    )
    ladder = f"pallas-s{eff}" if eff is not None else "xla"
    if ed.precomp_tuple_enabled() and N <= ed._precomp_max_lanes():
        ladder += "+precomp-tuple"
    return {
        "rate": round(tpu_rate, 1),
        "vs_cpu": round(tpu_rate / cpu_rate, 3),
        "batch": N,
        "tpu_ms": round(tpu_dt * 1e3, 2),
        "cpu_rate": round(cpu_rate, 1),
        "ladder_backend": ladder,
    }


def _subprocess_config(
    config: str, env_extra: dict, budget_s: int, what: str
) -> dict:
    """Run ONE bench config in a budgeted subprocess and return its
    entry. Used where the in-process run could wedge: a cold Mosaic
    compile through the tunnel, or any jit while the axon platform is
    down (a hung compile cannot be cancelled in-process; on timeout
    the config records the degradation instead of eating the driver's
    whole bench window)."""
    import subprocess

    env = dict(os.environ)
    env.update(env_extra)
    env["BENCH_CONFIGS"] = config
    # children must never recurse into the ablation-leg sweep; an
    # explicit marker beats inferring childhood from GRAFT_* values
    # (code-review r5: a leg with GRAFT_PALLAS="" would recurse)
    env["BENCH_CHILD"] = "1"
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=budget_s,
        )
    except subprocess.TimeoutExpired:
        return {
            "rate": None,
            "note": f"{what} exceeded its {budget_s}s budget",
        }
    if proc.returncode != 0:
        return {
            "rate": None,
            "note": f"{what} failed: "
            + (proc.stderr or proc.stdout)[-400:],
        }
    try:
        line = [
            l for l in proc.stdout.splitlines() if l.startswith("{")
        ][-1]
        return json.loads(line)["detail"]["configs"][config]
    except Exception as e:  # pragma: no cover - malformed child output
        return {"rate": None, "note": f"unparseable child output: {e}"}




def _budget_verdicts(tsum):
    """Per-span budget verdicts for a traced config (obs/budget.py
    against the checked-in tools/span_budgets.toml) — the regression
    gate future perf PRs diff this JSON against."""
    if not tsum:
        return None
    try:
        from cometbft_tpu.obs.budget import (
            evaluate_budgets,
            load_budgets,
        )

        budgets = load_budgets(
            os.path.join(REPO, "tools", "span_budgets.toml")
        )
        return evaluate_budgets(tsum, budgets)
    except Exception as e:  # budgets must never sink a bench leg
        return [{"error": repr(e)[:200], "ok": True}]


def _quorum_summary(tsum):
    """Quorum-latency rows (ISSUE 7 cross-node tracing) pulled out of
    a trace summary: the consensus.quorum.* waterfall legs plus live
    p2p propagation, surfaced next to the budget verdicts so perf PRs
    diff the commit-latency attribution, not just span totals. Replay
    configs have no live consensus — the note says so explicitly
    instead of the key silently vanishing."""
    if not tsum:
        return None
    out = {}
    for node, kinds in tsum.items():
        rows = {
            k: v
            for k, v in kinds.items()
            if k.startswith("consensus.quorum.")
            or k == "p2p.msg.propagation"
        }
        if rows:
            out[node] = rows
    return out or {
        "note": "no live-consensus quorum spans in this config"
    }


# --- corpus: 150-validator chain (cached across rounds) ----------------


def _corpus(n_blocks: int):
    """(genesis, privs, NodeParts) for the replay corpus; built once,
    cached under .bench_chain/ (sqlite stores + keys on disk)."""
    import cometbft_tpu.types as T
    from cometbft_tpu.config.config import test_config
    from cometbft_tpu.crypto.keys import Ed25519PrivKey
    from cometbft_tpu.node.inprocess import build_node
    from cometbft_tpu.types.genesis import GenesisDoc
    from cometbft_tpu.utils.chaingen import make_chain

    home = os.path.join(REPO, ".bench_chain", f"v1-{N_VALS}x{n_blocks}")
    meta_path = os.path.join(home, "meta.json")

    if os.path.exists(meta_path):
        # resume: the persisted genesis is authoritative — regenerating
        # it (fresh genesis_time_ns) while appending to the existing
        # store would leave earlier blocks predating the new genesis,
        # so replay/bisect would verify against a genesis that does not
        # match the stored chain (ADVICE r2). meta.json is only ever
        # written on from-scratch creation below.
        with open(meta_path) as f:
            meta = json.load(f)
        privs = [
            Ed25519PrivKey.from_seed(bytes.fromhex(s))
            for s in meta["seeds"]
        ]
        gen = GenesisDoc.from_json(meta["genesis"])
    else:
        os.makedirs(home, exist_ok=True)
        rng = np.random.default_rng(7)
        privs = [
            Ed25519PrivKey.from_seed(rng.bytes(32)) for _ in range(N_VALS)
        ]
        vals = [T.Validator(p.pub_key(), 10) for p in privs]
        gen = GenesisDoc(
            chain_id="bench-chain",
            validators=vals,
            genesis_time_ns=time.time_ns()
            - (n_blocks + 120) * 1_000_000_000,
        )
        with open(meta_path, "w") as f:
            json.dump(
                {
                    "seeds": [p.seed.hex() for p in privs],
                    "genesis": gen.to_json(),
                },
                f,
            )
    cfg = test_config(home)
    cfg.base.db_backend = "sqlite"
    parts = build_node(gen, None, config=cfg, home=home)
    if parts.block_store.height() >= n_blocks:
        return gen, privs, parts
    t0 = time.time()
    done = parts.block_store.height()
    while done < n_blocks:
        step = min(500, n_blocks - done)
        make_chain(gen, privs, step, txs_per_block=1, node=parts)
        done += step
        print(
            f"[corpus] {done}/{n_blocks} blocks "
            f"({time.time() - t0:.0f}s)",
            file=sys.stderr,
            flush=True,
        )
    return gen, privs, parts


# --- shared backend-swap scaffolding -----------------------------------


def _timed_with_backend(backend: str, fn, repeats: int = 5):
    """Best-of-N wall time of fn() under the given verifier backend;
    always restores the prior backend/threshold (even on a raising
    benchmark).

    Backends: "tpu" FORCES the device path (min batch 1), "cpu" is the
    SERIAL host baseline, "cpu-parallel" is the multi-core host plane
    (crypto/parallel_verify), "auto" is the PRODUCTION policy — tpu
    backend with the measured dispatch-crossover calibration deciding
    per batch (crypto/batch._Calibration; VERDICT r2 weak #3)."""
    from cometbft_tpu.crypto import batch as crypto_batch

    if backend in ("tpu", "auto") and not _DEVICE_OK:
        return None, None
    old_backend = crypto_batch._default_backend
    old_min = crypto_batch._MIN_TPU_BATCH
    crypto_batch.set_default_backend(
        backend if backend in ("cpu", "cpu-parallel") else "tpu"
    )
    if backend == "tpu":
        crypto_batch.set_min_tpu_batch(1)
    best = None
    out = None
    try:
        for _ in range(repeats):
            t0 = time.time()
            out = fn()
            dt = time.time() - t0
            best = dt if best is None else min(best, dt)
    finally:
        # capture the route the LAST timed run actually took BEFORE
        # restoring the backend: LAST_ROUTE is only written by
        # TpuBatchVerifier, so reading the global later can return a
        # stale value (e.g. after a cpu-backend timing or the
        # device-probe-failed degrade) — ADVICE r3
        _timed_with_backend.last_route = (
            crypto_batch.LAST_ROUTE["path"]
            if backend in ("tpu", "auto")
            else None
        )
        crypto_batch.set_min_tpu_batch(old_min)
        crypto_batch.set_default_backend(old_backend)
    return best, out


# --- 2/3. small-batch + single-commit latency --------------------------


def bench_batch64() -> dict:
    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.crypto.keys import Ed25519PrivKey

    rng = np.random.default_rng(11)
    items = []
    for _ in range(64):
        p = Ed25519PrivKey.from_seed(rng.bytes(32))
        m = bytes(rng.bytes(120))
        items.append((p.pub_key(), m, p.sign(m)))

    def once():
        v = crypto_batch.create_batch_verifier()
        for pk, m, s in items:
            v.add(pk, m, s)
        ok, _ = v.verify()
        assert ok
        return ok

    tpu, _ = _timed_with_backend("tpu", once)
    cpu, _ = _timed_with_backend("cpu", once)
    cpu_par, _ = _timed_with_backend("cpu-parallel", once)
    auto, _ = _timed_with_backend("auto", once)
    return {
        "tpu_ms": _ms(tpu),
        "cpu_ms": _ms(cpu),
        "cpu_parallel_ms": _ms(cpu_par),
        "auto_ms": _ms(auto),
        "auto_path": _timed_with_backend.last_route,
        "vs_cpu": _ratio(cpu, auto),
        "note": "64 sigs; auto = calibrated production routing",
    }


def bench_ingest() -> dict:
    """Mempool ingest plane ablation (docs/PERF.md): the identical tx
    workload (valid + app-rejected + duplicate + oversize txs) through
    the serial check_tx loop vs the batched check_tx_batch path —
    per-tx verdicts asserted identical, median of 3 runs each on this
    throttled box. Host-only: measures the amortized per-item costs
    (client mutex, cache/pool locks, tx_key hashing, ABCI dispatch),
    no device involved."""
    import statistics

    from cometbft_tpu.abci import types as abci_t
    from cometbft_tpu.abci.client import LocalClient
    from cometbft_tpu.mempool.mempool import CListMempool

    n = int(os.environ.get("BENCH_INGEST_TXS", "20000"))
    batch = int(os.environ.get("BENCH_INGEST_BATCH", "256"))
    repeats = int(os.environ.get("BENCH_INGEST_REPEATS", "3"))

    class _App(abci_t.Application):
        def check_tx(self, req):
            if req.tx.startswith(b"bad"):
                return abci_t.ResponseCheckTx(code=5, log="rejected")
            return abci_t.ResponseCheckTx(gas_wanted=1)

    work = []
    for i in range(n):
        work.append(b"ingest-%08d=%s" % (i, b"v" * 80))
        if i % 23 == 0:
            work.append(b"bad-%08d" % i)
        if i % 17 == 0:
            work.append(work[-2])  # in-stream duplicate
    work.append(b"x" * (2 << 20))  # oversize

    def build():
        return CListMempool(
            LocalClient(_App()),
            max_txs=len(work) + 16,
            cache_size=2 * len(work),
            recheck=False,
        )

    import gc

    # segment-interleaved pairing: within one repeat, serial and
    # batched each process the SAME workload on their own fresh pool,
    # alternating every `seg` txs — this box's throttling spikes
    # (±30% run-to-run) then average over both legs instead of
    # sinking whichever whole pass they land on. GC is collected
    # before and disabled during the timed region for the same
    # reason (a gen2 cycle mid-pass skews one leg).
    seg = 2000
    segments = [
        (i, min(i + seg, len(work))) for i in range(0, len(work), seg)
    ]

    def run_pair(flip: bool):
        mp_s, mp_b = build(), build()
        codes_s, codes_b = [], []
        t_s = t_b = 0.0
        gc.collect()
        gc.disable()
        try:
            for si, (lo, hi) in enumerate(segments):
                for which in ((si + flip) % 2, (si + flip + 1) % 2):
                    if which == 0:
                        t0 = time.perf_counter()
                        codes_s.extend(
                            mp_s.check_tx(tx).code for tx in work[lo:hi]
                        )
                        t_s += time.perf_counter() - t0
                    else:
                        t0 = time.perf_counter()
                        for j in range(lo, hi, batch):
                            codes_b.extend(
                                r.code
                                for r in mp_b.check_tx_batch(
                                    work[j:min(j + batch, hi)]
                                )
                            )
                        t_b += time.perf_counter() - t0
        finally:
            gc.enable()
        return t_s, t_b, codes_s, codes_b, mp_s.size(), mp_b.size()

    # one throwaway pass: first-touch effects (native hasher
    # build/dlopen, allocator warmup) must not land on either side
    run_pair(False)
    serial_ts, batched_ts, ratios = [], [], []
    parity = True
    for r in range(repeats):
        t_s, t_b, codes_s, codes_b, size_s, size_b = run_pair(bool(r % 2))
        serial_ts.append(t_s)
        batched_ts.append(t_b)
        ratios.append(t_s / t_b)
        parity = parity and codes_s == codes_b and size_s == size_b
    assert parity, "serial vs batched CheckTx verdicts diverged"
    serial_rate = len(work) / statistics.median(serial_ts)
    batched_rate = len(work) / statistics.median(batched_ts)

    # profiler overhead guard (docs/OBS.md): the sampling profiler at
    # its default Hz must add <3% SAMPLING WORK to the ingest leg.
    # Measured against an idle-waker CONTROL, not an empty process:
    # on this cgroup-throttled 2-vCPU box ANY thread waking at 29 Hz
    # costs a noisy 0-30% end-to-end (GIL handoff + quota effects —
    # measured directly while building this guard), and a node
    # already runs such threads (watchdog monitors, executors). The
    # control thread has the IDENTICAL lifecycle (create/start/join
    # per pass) and wake cadence; the only difference is sampling
    # frames vs doing nothing — so the paired, pass-alternated ratio
    # isolates exactly the profiler's own work. waker-vs-nothing is
    # recorded (not asserted) as the platform's ambient thread cost.
    import threading as _threading

    from cometbft_tpu.obs import SamplingProfiler

    hz = float(os.environ.get("BENCH_PROFILE_HZ", "29"))
    ambient = _PROFILER
    ambient_was_running = ambient is not None and ambient.running
    if ambient_was_running:
        ambient.stop()

    class _IdleWaker:
        """Same thread lifecycle + wake cadence as the profiler,
        zero work per wake."""

        def __init__(self, whz: float):
            self.interval = 1.0 / whz
            self._stop = _threading.Event()
            self._t = None

        def start(self):
            self._t = _threading.Thread(target=self._run, daemon=True)
            self._t.start()
            return self

        def _run(self):
            while not self._stop.wait(self.interval):
                pass

        def stop(self):
            self._stop.set()
            self._t.join()

    def _guard_pass(kind: str) -> float:
        mp = build()
        gc.collect()
        gc.disable()
        try:
            w = (
                SamplingProfiler(hz=hz).start()
                if kind == "prof"
                else _IdleWaker(hz).start()
                if kind == "waker"
                else None
            )
            t0 = time.perf_counter()
            for j in range(0, len(work), batch):
                mp.check_tx_batch(work[j : j + batch])
            dt = time.perf_counter() - t0
            if w is not None:
                w.stop()
        finally:
            gc.enable()
        return dt

    try:
        _guard_pass("none")  # warm (allocator, native hasher)
        kinds = ("prof", "waker", "none")
        walls = {k: [] for k in kinds}
        for i in range(18):  # 6 per group: median rejects the box's
            k = kinds[i % 3]  # multi-second throttle spikes
            walls[k].append(_guard_pass(k))
    finally:
        if ambient_was_running:
            ambient.start()
    med = {k: statistics.median(v) for k, v in walls.items()}
    overhead = med["prof"] / med["waker"]
    ambient_thread_cost = med["waker"] / med["none"]
    assert overhead < 1.10, (
        f"profiler sampling overhead {overhead:.3f}x vs the idle-"
        f"waker control on the ingest leg (target <1.03, bound 1.10;"
        f" medians {med})"
    )
    return {
        "rate": round(batched_rate, 1),
        "serial_txs_s": round(serial_rate, 1),
        "batched_txs_s": round(batched_rate, 1),
        "speedup": round(statistics.median(ratios), 2),
        "speedups": [round(x, 2) for x in ratios],
        "verdict_parity": True,
        "n_txs": len(work),
        "batch": batch,
        "repeats": repeats,
        "profiler_overhead": {
            "sampling_ratio_vs_idle_waker": round(overhead, 4),
            "ambient_thread_ratio_vs_none": round(
                ambient_thread_cost, 4
            ),
            "hz": hz,
            "target": "<1.03 sampling work",
            "asserted_bound": 1.10,
        },
        "note": "serial check_tx loop vs batched check_tx_batch, "
        "identical workload + verdicts; speedup = median of "
        f"{repeats} paired-run ratios; profiler_overhead = paired "
        "batched passes with the sampling profiler on vs off",
    }


def bench_live() -> dict:
    """Live-consensus fast path ablation (docs/PERF.md "Live consensus
    fast path"): the SAME 4-validator LocalNet workload producing N
    heights through

    - serial   — the reference-like path: one inline fsync per WAL
      sync barrier, inline per-vote signature verification, blocking
      finalize;
    - fastpath — WAL group commit (calibrated seam) + pipelined
      finalize (persist/fsync off-loop, single in-flight height).

    Two disk models: the REAL disk (cached NVMe, ~0.1 ms fsync — the
    calibrated router keeps the strict inline barrier, so fastpath
    must hold parity) and a 2 ms synthetic barrier (consensus/wal.py
    set_fsync_model) standing in for sync-through production media,
    where the group seam engages and the ablation measures its win.
    Runs are pass-interleaved (serial/fast/serial/fast...) with
    medians, the same defense bench_ingest uses against this box's
    throttling spikes. Per mode: agreement asserted (every node,
    every height, identical block hashes). A separate leg exercises
    the in-round vote micro-batch (vote_batch_window_ms) and asserts
    its verdicts are serial-equivalent."""
    import asyncio
    import shutil
    import statistics
    import tempfile

    from cometbft_tpu.config.config import test_config
    from cometbft_tpu.consensus import wal as walmod
    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.node.inprocess import (
        LocalNet,
        build_node,
        make_genesis,
    )

    n_nodes = int(os.environ.get("BENCH_LIVE_NODES", "4"))
    heights = int(os.environ.get("BENCH_LIVE_HEIGHTS", "20"))
    txs_per_height = int(os.environ.get("BENCH_LIVE_TXS", "20"))
    repeats = int(os.environ.get("BENCH_LIVE_REPEATS", "3"))
    slow_fsync_ms = float(os.environ.get("BENCH_LIVE_SLOW_FSYNC_MS", "2"))

    def run_once(fast: bool, vote_ms: float = 0.0, nodes_n=None) -> dict:
        base = tempfile.mkdtemp(prefix="bench_live_")
        old_backend = crypto_batch._default_backend
        crypto_batch.set_default_backend("cpu")
        try:
            nn = nodes_n or n_nodes
            gen, pvs = make_genesis(nn, chain_id="bench-live")
            nodes = []
            for i, pv in enumerate(pvs):
                home = os.path.join(base, f"n{i}")
                os.makedirs(home, exist_ok=True)
                cfg = test_config(home)
                cfg.base.moniker = f"n{i}"
                cfg.base.db_backend = "sqlite"  # real persist leg
                cfg.consensus.skip_timeout_commit = True
                cfg.consensus.timeout_commit_s = 0.0
                cfg.tx_index.indexer = "null"
                cfg.consensus.vote_batch_window_ms = vote_ms
                if fast:
                    cfg.consensus.wal_group_commit_ms = 2.0
                    cfg.consensus.finalize_pipeline = True
                else:
                    cfg.consensus.wal_group_commit_ms = 0.0
                    cfg.consensus.finalize_pipeline = False
                nodes.append(
                    build_node(gen, pv, config=cfg, home=home, wal=True)
                )
            net = LocalNet(nodes)

            async def main():
                await net.start()

                async def feed():
                    i = 0
                    while True:
                        for _ in range(txs_per_height):
                            try:
                                nodes[i % nn].mempool.check_tx(
                                    b"live-%08d=%04d" % (i, i % 7919)
                                )
                            except Exception:
                                pass
                            i += 1
                        await asyncio.sleep(0.05)

                feeder = asyncio.ensure_future(feed())
                t0 = time.perf_counter()
                await net.wait_for_height(heights, timeout=600)
                wall = time.perf_counter() - t0
                feeder.cancel()
                await net.stop()
                return wall

            wall = asyncio.run(main())
            # agreement = the live path's verdict-parity gate: every
            # node must hold identical block hashes at every height
            # (header app_hash pins app agreement one height back; a
            # raw app.app_hash comparison would race nodes sitting
            # one height apart at stop)
            for h in range(1, heights + 1):
                hs = {
                    n.block_store.load_block_meta(h).block_id.hash
                    for n in nodes
                }
                assert len(hs) == 1, f"disagreement at height {h}"
            quorum_ns = []
            for n in nodes:
                quorum_ns.extend(
                    e["dur_ns"]
                    for e in n.tracer.snapshot()
                    if e["name"].startswith("consensus.quorum.")
                )
            quorum_ns.sort()
            out = {
                "wall_s": wall,
                "blocks_per_s": heights / wall,
                "p95_quorum_ms": (
                    quorum_ns[int(0.95 * (len(quorum_ns) - 1))] / 1e6
                    if quorum_ns
                    else None
                ),
                "group_fsyncs": sum(
                    n.cs.wal.group_fsyncs for n in nodes if n.cs.wal
                ),
                "group_barriers": sum(
                    n.cs.wal.group_coalesced for n in nodes if n.cs.wal
                ),
                "vote_batches": sum(
                    n.cs._vote_coalescer.dispatches
                    for n in nodes
                    if n.cs._vote_coalescer is not None
                ),
                "votes_batched": sum(
                    n.cs._vote_coalescer.submitted
                    for n in nodes
                    if n.cs._vote_coalescer is not None
                ),
            }
            for n in nodes:
                n.close_stores()
            return out
        finally:
            crypto_batch.set_default_backend(old_backend)
            shutil.rmtree(base, ignore_errors=True)

    def ablate(disk: str) -> dict:
        """Interleaved serial/fast repeats under one disk model;
        medians + speedups."""
        runs = {"serial": [], "fastpath": []}
        if disk == "slow":
            walmod.set_fsync_model(slow_fsync_ms / 1e3)
        try:
            for _ in range(repeats):
                runs["serial"].append(run_once(fast=False))
                runs["fastpath"].append(run_once(fast=True))
        finally:
            walmod.set_fsync_model(0.0)
        med = {
            mode: {
                "blocks_per_s": round(
                    statistics.median(
                        r["blocks_per_s"] for r in rs
                    ),
                    2,
                ),
                "p95_quorum_ms": round(
                    statistics.median(
                        r["p95_quorum_ms"] or 0 for r in rs
                    ),
                    1,
                ),
                "group_fsyncs": rs[-1]["group_fsyncs"],
                "group_barriers": rs[-1]["group_barriers"],
            }
            for mode, rs in runs.items()
        }
        out = {
            "disk": (
                "real (cached NVMe, ~0.1ms fsync)"
                if disk == "real"
                else f"{slow_fsync_ms}ms synthetic barrier "
                "(sync-through disk model)"
            ),
            **med,
            "blocks_per_s_speedup": _ratio(
                med["fastpath"]["blocks_per_s"],
                med["serial"]["blocks_per_s"],
            ),
        }
        s_q = med["serial"]["p95_quorum_ms"]
        f_q = med["fastpath"]["p95_quorum_ms"]
        if s_q and f_q:
            out["p95_quorum_reduction"] = round(1.0 - f_q / s_q, 3)
        return out

    def vote_batch_leg() -> dict:
        """In-round vote micro-batching: serial-equivalent verdicts
        asserted two ways — a direct CoalescingVerifier-vs-serial
        verdict comparison over valid + forged votes, and a live net
        run with the window on (agreement per height + the coalescer
        provably engaged)."""
        from cometbft_tpu.crypto.coalesce import CoalescingVerifier
        from cometbft_tpu.crypto.keys import Ed25519PrivKey

        rng = np.random.default_rng(31)
        privs = [
            Ed25519PrivKey.from_seed(rng.bytes(32)) for _ in range(8)
        ]
        items = []
        for i in range(200):
            p = privs[i % len(privs)]
            m = bytes(rng.bytes(96))
            sig = p.sign(m)
            if i % 17 == 0:
                sig = bytes(64)  # forged lane
            items.append((p.pub_key(), m, sig))
        serial_verdicts = [
            pk.verify(m, sig) for pk, m, sig in items
        ]

        async def coalesced():
            vc = CoalescingVerifier(window_s=0.001)
            futs = [vc.submit(pk, m, sig) for pk, m, sig in items]
            await vc.drain()
            return [bool(f.result()) for f in futs]

        batched_verdicts = asyncio.run(coalesced())
        assert batched_verdicts == serial_verdicts, (
            "coalesced vote verdicts diverged from serial"
        )
        live = run_once(fast=False, vote_ms=2.0, nodes_n=n_nodes)
        assert live["votes_batched"] > 0 and live["vote_batches"] > 0, (
            "live run never exercised the vote coalescer"
        )
        return {
            "verdicts_identical": True,
            "lanes": len(items),
            "forged_lanes": sum(1 for v in serial_verdicts if not v),
            "live_blocks_per_s": round(live["blocks_per_s"], 2),
            "live_votes_batched": live["votes_batched"],
            "live_vote_batches": live["vote_batches"],
            "note": (
                "window=2ms on the state-machine prestage; on this "
                "in-process 2-vCPU harness the handoff costs more "
                "than the ~80us/sig it batches (committee waves of "
                "3), so the knob defaults off — the reactor's "
                "always-on coalescing serves networked nodes"
            ),
        }

    run_once(fast=False)  # warm pass (sqlite, allocator, pools)
    real = ablate("real")
    slow = ablate("slow")
    votes = vote_batch_leg()
    if slow["fastpath"]["group_barriers"] == 0:
        raise AssertionError(
            "slow-disk model never engaged the WAL group seam"
        )
    return {
        "rate": slow["fastpath"]["blocks_per_s"],
        "nodes": n_nodes,
        "heights": heights,
        "txs_per_height": txs_per_height,
        "repeats_per_mode": repeats,
        "real_disk": real,
        "slow_disk": slow,
        "vote_batch": votes,
        "verdict_parity": _verdict_parity(),
        "note": (
            "serial = inline fsync per barrier + blocking finalize; "
            "fastpath = calibrated WAL group commit + pipelined "
            "finalize (persist/fsync off-loop). Headline = slow-disk "
            "ablation (the seam's target media); real-disk leg "
            "proves the calibrated router holds parity where fsync "
            "is ~free. Pass-interleaved medians; agreement asserted "
            "per mode per height."
        ),
    }


def bench_finalize() -> dict:
    """Native finalize lane ablation (ISSUE 20, docs/PERF.md "Native
    finalize lane"): three legs —

    - localnet — a 4-validator LocalNet driving the vecbank app
      (models/vecbank.py, the vectorized apply — sub-ms per block, so
      the finalize span exposes the hash/encode lane instead of
      drowning it under a pure-Python per-tx app apply) at thousands
      of 16-byte transfers per height, pipelined finalize + off-loop
      apply ON in BOTH modes, native lane vs the portable twin
      (loader forced unavailable), order-ALTERNATED repeats with
      medians: blocks/s, the consensus.finalize span p95 the lane
      targets, and the WAL->apply sub-leg median where the per-item
      work lived;
    - apply    — vecbank (models/vecbank.py) vectorized scatter-add vs
      scalar per-tx apply over IDENTICAL blocks, app-hash parity
      asserted per pass — carries the >=1.5x blocks/s gate;
    - parity   — in-bench byte-parity: finalize_pass native vs the
      portable twin over an event-heavy randomized block (unicode
      attrs, an empty-event tx), AND the degraded path
      (GRAFT_NATIVE_FINALIZE=0 — what a no-g++ box runs) pinned to the
      same bytes. A run whose parity leg fails raises — the number is
      only worth recording if the bytes agree.
    """
    import asyncio
    import random
    import shutil
    import statistics
    import tempfile

    from cometbft_tpu.abci import types as abci
    from cometbft_tpu.config.config import test_config
    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.models.vecbank import (
        VecBankApplication,
        make_block_txs,
        make_transfer,
    )
    from cometbft_tpu.node.inprocess import (
        LocalNet,
        build_node,
        make_genesis,
    )
    from cometbft_tpu.state import native_finalize

    n_nodes = int(os.environ.get("BENCH_FIN_NODES", "4"))
    heights = int(os.environ.get("BENCH_FIN_HEIGHTS", "12"))
    txs_per_height = int(os.environ.get("BENCH_FIN_TXS", "2000"))
    repeats = int(os.environ.get("BENCH_FIN_REPEATS", "4"))
    n_accounts = 1 << 14
    apply_txs = int(os.environ.get("BENCH_FIN_APPLY_TXS", "4096"))
    apply_heights = int(os.environ.get("BENCH_FIN_APPLY_HEIGHTS", "40"))

    # mode toggling: the loader is process-wide state (module-level
    # _mod/_tried, the wirecodec discipline), so the portable mode
    # forces "tried, nothing loaded" and the native mode resets and
    # re-resolves OFF the measured path (the .so is cached — no g++
    # inside a timed run)
    def force_portable():
        with native_finalize._lock:
            native_finalize._mod = None
            native_finalize._tried = True

    def restore_native():
        with native_finalize._lock:
            native_finalize._mod = None
            native_finalize._tried = False
        return native_finalize.module()

    def run_once() -> dict:
        base = tempfile.mkdtemp(prefix="bench_fin_")
        old_backend = crypto_batch._default_backend
        crypto_batch.set_default_backend("cpu")
        try:
            gen, pvs = make_genesis(n_nodes, chain_id="bench-fin")
            nodes = []
            for i, pv in enumerate(pvs):
                home = os.path.join(base, f"n{i}")
                os.makedirs(home, exist_ok=True)
                cfg = test_config(home)
                cfg.base.moniker = f"n{i}"
                cfg.base.db_backend = "sqlite"  # real persist leg
                # PACED heights: commit waits let the mempool refill
                # so every block actually carries ~txs_per_height txs
                # — free-running heights drain the feeder instantly
                # and finalize near-empty blocks (nothing to hash)
                cfg.consensus.skip_timeout_commit = False
                cfg.consensus.timeout_commit_s = 0.25
                cfg.tx_index.indexer = "null"
                # both modes ride the full fast path — the ablation
                # isolates the native hash/encode lane, nothing else
                cfg.consensus.wal_group_commit_ms = 2.0
                cfg.consensus.finalize_pipeline = True
                cfg.consensus.finalize_offload_apply = True
                nodes.append(
                    build_node(
                        gen,
                        pv,
                        app=VecBankApplication(n_accounts=n_accounts),
                        config=cfg,
                        home=home,
                        wal=True,
                    )
                )
            net = LocalNet(nodes)

            async def main():
                await net.start()

                async def feed():
                    # unique valid transfers (dedup-safe: the amount
                    # term keeps every tx distinct until i wraps),
                    # RATE-MATCHED to block cadence: overfeeding just
                    # grows the mempool until post-commit re-checks
                    # dominate every span and drown the ablation
                    i = 0
                    # ~txs_per_height per commit-timeout window
                    per_tick = max(1, txs_per_height // 5)
                    while True:
                        for _ in range(per_tick):
                            try:
                                nodes[i % n_nodes].mempool.check_tx(
                                    make_transfer(
                                        i % n_accounts,
                                        (i * 7 + 3) % n_accounts,
                                        (i % 997) + 1,
                                    )
                                )
                            except Exception:
                                pass
                            i += 1
                        await asyncio.sleep(0.05)

                feeder = asyncio.ensure_future(feed())
                t0 = time.perf_counter()
                await net.wait_for_height(heights, timeout=600)
                wall = time.perf_counter() - t0
                feeder.cancel()
                await net.stop()
                return wall

            wall = asyncio.run(main())
            for h in range(1, heights + 1):
                hs = {
                    n.block_store.load_block_meta(h).block_id.hash
                    for n in nodes
                }
                assert len(hs) == 1, f"disagreement at height {h}"
            fin_ns, apply_ms, hp_ms = [], [], []
            for n in nodes:
                for e in n.tracer.snapshot():
                    if e["name"] == "consensus.finalize.hash_persist":
                        hp_ms.append(e["dur_ns"] / 1e6)
                    if e["name"] != "consensus.finalize":
                        continue
                    fin_ns.append(e["dur_ns"])
                    a = (e.get("args") or {}).get("apply_ms")
                    if a is not None:
                        apply_ms.append(a)
            fin_ns.sort()
            out = {
                "wall_s": wall,
                "blocks_per_s": heights / wall,
                "p95_finalize_ms": (
                    fin_ns[int(0.95 * (len(fin_ns) - 1))] / 1e6
                    if fin_ns
                    else None
                ),
                # the WAL->apply sub-leg: where the per-item
                # hash/encode lived before the native pass — a much
                # tighter signal than the whole span (which also
                # carries sqlite persist + loop-handoff scheduling)
                "med_apply_ms": (
                    statistics.median(apply_ms) if apply_ms else None
                ),
                # the leg the lane OWNS: hash/encode + response
                # persist on the thread hop — the direct before/after
                "med_hash_persist_ms": (
                    statistics.median(hp_ms) if hp_ms else None
                ),
                "finalize_spans": len(fin_ns),
            }
            for n in nodes:
                n.close_stores()
            return out
        finally:
            crypto_batch.set_default_backend(old_backend)
            shutil.rmtree(base, ignore_errors=True)

    def localnet_leg() -> dict:
        runs = {"portable": [], "native": []}
        native_ok = False

        def one(mode: str):
            if mode == "portable":
                force_portable()
            else:
                nonlocal native_ok
                native_ok = restore_native() is not None
            runs[mode].append(run_once())

        try:
            for i in range(repeats):
                # ALTERNATE the order each repeat: this box's cpu
                # throttling drifts over a leg, and a fixed A-then-B
                # order would bill the drift to whichever mode always
                # runs second
                first, second = (
                    ("portable", "native")
                    if i % 2 == 0
                    else ("native", "portable")
                )
                one(first)
                one(second)
        finally:
            restore_native()
        med = {
            mode: {
                "blocks_per_s": round(
                    statistics.median(
                        r["blocks_per_s"] for r in rs
                    ),
                    2,
                ),
                "p95_finalize_ms": round(
                    statistics.median(
                        r["p95_finalize_ms"] or 0 for r in rs
                    ),
                    2,
                ),
                "med_apply_ms": round(
                    statistics.median(
                        r["med_apply_ms"] or 0 for r in rs
                    ),
                    2,
                ),
                "med_hash_persist_ms": round(
                    statistics.median(
                        r["med_hash_persist_ms"] or 0 for r in rs
                    ),
                    2,
                ),
            }
            for mode, rs in runs.items()
        }
        out = {
            "native_module_loaded": native_ok,
            **med,
            "blocks_per_s_speedup": _ratio(
                med["native"]["blocks_per_s"],
                med["portable"]["blocks_per_s"],
            ),
        }
        p_p = med["portable"]["p95_finalize_ms"]
        n_p = med["native"]["p95_finalize_ms"]
        if p_p and n_p:
            out["p95_finalize_reduction"] = round(1.0 - n_p / p_p, 3)
        p_a = med["portable"]["med_apply_ms"]
        n_a = med["native"]["med_apply_ms"]
        if p_a and n_a:
            out["apply_ms_reduction"] = round(1.0 - n_a / p_a, 3)
        p_h = med["portable"]["med_hash_persist_ms"]
        n_h = med["native"]["med_hash_persist_ms"]
        if p_h and n_h:
            out["hash_persist_reduction"] = round(1.0 - n_h / p_h, 3)
        if not native_ok:
            out["note"] = (
                "native module unavailable on this box: both modes "
                "ran the portable twin (honest degraded ablation)"
            )
        return out

    def apply_leg() -> dict:
        """Vectorized vs scalar vecbank apply over identical blocks —
        the blocks/s ceiling of the state-apply half of the lane.
        Digest-parity asserted per pass; >=1.5x gate asserted here
        (wraparound-commutative scatter-add vs the per-tx loop)."""
        rng = random.Random(20)
        blocks = [
            make_block_txs(rng, apply_txs, 1 << 14)
            for _ in range(apply_heights)
        ]

        def drive(scalar: bool):
            app = VecBankApplication(scalar=scalar)
            t0 = time.perf_counter()
            for h, txs in enumerate(blocks, 1):
                app.finalize_block(
                    abci.RequestFinalizeBlock(height=h, txs=txs)
                )
                app.commit()
            dt = time.perf_counter() - t0
            return app.app_hash, apply_heights / dt

        s_rates, v_rates = [], []
        for _ in range(3):  # pass-interleaved, like every host leg
            sh, sr = drive(scalar=True)
            vh, vr = drive(scalar=False)
            assert sh == vh, "vecbank scalar/vector app-hash diverged"
            s_rates.append(sr)
            v_rates.append(vr)
        s = statistics.median(s_rates)
        v = statistics.median(v_rates)
        speedup = v / s
        assert speedup >= 1.5, (
            f"vectorized apply speedup {speedup:.2f}x < 1.5x gate"
        )
        return {
            "txs_per_block": apply_txs,
            "blocks": apply_heights,
            "scalar_blocks_per_s": round(s, 2),
            "vector_blocks_per_s": round(v, 2),
            "speedup": round(speedup, 2),
            "digest_parity": True,
        }

    def parity_leg() -> dict:
        """finalize_pass byte-parity, asserted in-bench: whatever mode
        the box resolves vs the forced-portable twin, and the env-gated
        degraded path vs the same twin."""
        rng = random.Random(7)
        txs = [rng.randbytes(rng.randrange(1, 200)) for _ in range(24)]
        results = []
        for i, _ in enumerate(txs):
            evs = []
            if i % 3 != 1:  # every third tx ships no events
                for j in range(rng.randrange(1, 4)):
                    evs.append(
                        abci.Event(
                            type_=f"transfer.{j}",
                            attributes=[
                                abci.EventAttribute(
                                    key=f"k{j}",
                                    value=f"vé-{i}-{j}",
                                    index=bool(j % 2),
                                )
                            ],
                        )
                    )
            results.append(
                abci.ExecTxResult(
                    code=i % 2,
                    data=rng.randbytes(8),
                    gas_wanted=i,
                    gas_used=i * 2,
                    codespace="bench" if i % 4 == 0 else "",
                    events=evs,
                )
            )
        resp = abci.ResponseFinalizeBlock(
            tx_results=results,
            events=[
                abci.Event(
                    type_="block.reward",
                    attributes=[
                        abci.EventAttribute(
                            key="amount", value="42", index=True
                        )
                    ],
                )
            ],
        )

        def same(a, b) -> bool:
            return (
                a.tx_hashes == b.tx_hashes
                and a.results_enc == b.results_enc
                and a.results_hash == b.results_hash
                and a.tx_events_enc == b.tx_events_enc
                and a.block_events_enc == b.block_events_enc
            )

        port = native_finalize.finalize_pass(txs, resp, portable=True)
        live = native_finalize.finalize_pass(txs, resp)
        assert same(live, port), "native finalize_pass parity broke"

        # degraded path: the env gate is exactly what a no-compiler
        # box (or an operator opt-out) runs — same bytes, native=False
        old_env = os.environ.get("GRAFT_NATIVE_FINALIZE")
        os.environ["GRAFT_NATIVE_FINALIZE"] = "0"
        with native_finalize._lock:
            native_finalize._mod = None
            native_finalize._tried = False
        try:
            gated = native_finalize.finalize_pass(txs, resp)
            assert not gated.native, "env gate did not disable native"
            assert same(gated, port), "degraded-path parity broke"
        finally:
            if old_env is None:
                os.environ.pop("GRAFT_NATIVE_FINALIZE", None)
            else:
                os.environ["GRAFT_NATIVE_FINALIZE"] = old_env
            restore_native()
        # raw single-threaded compute ratio on a realistic big block
        # (1000 txs, 1 indexed attr each): the lane's win with no
        # scheduler in the frame — the localnet caveat's counterpart
        big_txs = [
            rng.randbytes(64) for _ in range(1000)
        ]
        big_resp = abci.ResponseFinalizeBlock(
            tx_results=[
                abci.ExecTxResult(
                    code=0,
                    events=[
                        abci.Event(
                            type_="app",
                            attributes=[
                                abci.EventAttribute(
                                    key="key",
                                    value=f"r{i}",
                                    index=True,
                                )
                            ],
                        )
                    ],
                )
                for i in range(1000)
            ]
        )

        def med_ms(portable: bool, n: int = 9) -> float:
            ts = []
            for _ in range(n):
                t0 = time.perf_counter()
                native_finalize.finalize_pass(
                    big_txs, big_resp,
                    portable=True if portable else None,
                )
                ts.append(time.perf_counter() - t0)
            return statistics.median(ts) * 1e3

        med_ms(True, 2)
        med_ms(False, 2)  # warm
        p_ms, n_ms = med_ms(True), med_ms(False)
        return {
            "native_ran": live.native,
            "degraded_env_gate_parity": True,
            "txs": len(txs),
            "parity_ok": True,
            "pass_portable_ms": round(p_ms, 2),
            "pass_native_ms": round(n_ms, 2),
            "pass_speedup": _ratio(p_ms, n_ms),
        }

    parity = parity_leg()  # gate FIRST: no number without parity
    run_once()  # warm pass (sqlite, allocator, native .so resolve)
    localnet = localnet_leg()
    apply_ = apply_leg()
    return {
        "rate": localnet["native"]["blocks_per_s"],
        "nodes": n_nodes,
        "heights": heights,
        "txs_per_height": txs_per_height,
        "repeats_per_mode": repeats,
        "localnet": localnet,
        "apply": apply_,
        "parity": parity,
        "verdict_parity": _verdict_parity(),
        "note": (
            "localnet = native lane vs portable twin on the pipelined "
            "fast path, vecbank app, paced 2000-tx heights "
            "(consensus.finalize p95 is the lane's target span); "
            "apply = vecbank scatter-add vs per-tx loop (>=1.5x "
            "gate, digest parity per pass); parity = finalize_pass "
            "bytes pinned native==portable==env-gated degraded. "
            "Order-alternated medians throughout. CAVEAT "
            "(hash_persist span): 4 in-process nodes oversubscribe "
            "2 vCPUs, so the native pass's GIL-FREE window gets "
            "billed wall-clock loop work the portable (GIL-holding) "
            "twin simply blocks — read the end-to-end numbers "
            "(blocks/s, p95, apply_ms) for the verdict and the "
            "single-threaded micro ratio for the raw compute win"
        ),
    }


def bench_lifecycle() -> dict:
    """Storage lifecycle plane overhead gate (ISSUE 17,
    docs/STORAGE.md): the SAME 4-validator LocalNet workload with the
    retention plane OFF (immortal storage, reference semantics) vs ON
    (retention-windowed pruning + node-side snapshots on a live
    background cadence). Two gates:

    - throughput — lifecycle ON must cost < 5% blocks/s vs OFF
      (pass-interleaved medians, the bench_live defense against this
      box's throttling spikes);
    - placement — every ``storage.prune`` / ``storage.snapshot`` span
      must have run OFF the consensus event loop: span tid is the
      plane's own ``retention`` timeline and the plane's recorded
      reconcile thread ident differs from the loop thread's.

    The ON leg must actually do lifecycle work to be an honest
    ablation: the run asserts blocks were pruned, the base advanced,
    and a snapshot was persisted."""
    import asyncio
    import shutil
    import statistics
    import tempfile
    import threading

    from cometbft_tpu.config.config import test_config
    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.node.inprocess import (
        LocalNet,
        build_node,
        make_genesis,
    )

    n_nodes = int(os.environ.get("BENCH_LIFECYCLE_NODES", "4"))
    heights = int(os.environ.get("BENCH_LIFECYCLE_HEIGHTS", "24"))
    txs_per_height = int(os.environ.get("BENCH_LIFECYCLE_TXS", "10"))
    repeats = int(os.environ.get("BENCH_LIFECYCLE_REPEATS", "3"))
    max_overhead = float(
        os.environ.get("BENCH_LIFECYCLE_MAX_OVERHEAD", "0.05")
    )

    def run_once(lifecycle: bool) -> dict:
        base = tempfile.mkdtemp(prefix="bench_lifecycle_")
        old_backend = crypto_batch._default_backend
        crypto_batch.set_default_backend("cpu")
        try:
            gen, pvs = make_genesis(n_nodes, chain_id="bench-lifecycle")
            nodes = []
            for i, pv in enumerate(pvs):
                home = os.path.join(base, f"n{i}")
                os.makedirs(home, exist_ok=True)
                cfg = test_config(home)
                cfg.base.moniker = f"n{i}"
                cfg.base.db_backend = "sqlite"  # real persist leg
                cfg.consensus.skip_timeout_commit = True
                cfg.consensus.timeout_commit_s = 0.0
                cfg.tx_index.indexer = "null"
                if lifecycle:
                    cfg.storage.retain_blocks = 8
                    cfg.storage.retain_states = 8
                    cfg.storage.prune_batch = 4
                    cfg.storage.prune_interval_s = 0.2
                    cfg.storage.snapshot_interval = 10
                    cfg.storage.snapshot_keep_recent = 2
                nodes.append(
                    build_node(gen, pv, config=cfg, home=home, wal=True)
                )
            net = LocalNet(nodes)

            async def main():
                loop_tid = threading.get_ident()
                await net.start()
                for n in nodes:
                    await n.retention.start()

                async def feed():
                    i = 0
                    while True:
                        for _ in range(txs_per_height):
                            try:
                                nodes[i % n_nodes].mempool.check_tx(
                                    b"life-%08d=%04d" % (i, i % 7919)
                                )
                            except Exception:
                                pass
                            i += 1
                        await asyncio.sleep(0.05)

                feeder = asyncio.ensure_future(feed())
                t0 = time.perf_counter()
                await net.wait_for_height(heights, timeout=600)
                wall = time.perf_counter() - t0
                feeder.cancel()
                for n in nodes:
                    await n.retention.stop()
                await net.stop()
                return wall, loop_tid

            wall, loop_tid = asyncio.run(main())
            # agreement over the surviving window: pruned nodes no
            # longer hold blocks below their base, so compare from the
            # highest base across the net
            lo = max(n.block_store.base() for n in nodes)
            for h in range(lo, heights + 1):
                hs = {
                    n.block_store.load_block_meta(h).block_id.hash
                    for n in nodes
                }
                assert len(hs) == 1, f"disagreement at height {h}"
            storage_spans = []
            for n in nodes:
                storage_spans.extend(
                    e
                    for e in n.tracer.snapshot()
                    if e["name"].startswith("storage.")
                )
            out = {
                "wall_s": wall,
                "blocks_per_s": heights / wall,
                "base": lo,
                "storage_spans": len(storage_spans),
            }
            if lifecycle:
                # the ablation is honest only if lifecycle work
                # actually ran: blocks pruned, base advanced, a
                # snapshot held
                pruned = sum(
                    n.retention.pruned_blocks_total for n in nodes
                )
                assert pruned > 0, "lifecycle leg never pruned a block"
                assert lo > 1, "lifecycle leg never advanced the base"
                snaps = sum(
                    len(n.snapshot_store.heights()) for n in nodes
                )
                assert snaps > 0, (
                    "lifecycle leg never persisted a snapshot"
                )
                # placement gate: prune work must never run on the
                # consensus event loop. Two independent witnesses —
                # every storage span sits on the plane's own trace
                # timeline, and the reconcile worker's OS thread
                # differs from the loop thread.
                off_tid = [
                    e for e in storage_spans if e["tid"] != "retention"
                ]
                assert not off_tid, (
                    f"storage spans off the retention timeline: "
                    f"{sorted({e['name'] for e in off_tid})}"
                )
                for n in nodes:
                    ti = n.retention.last_thread_ident
                    assert ti is not None, "retention never reconciled"
                    assert ti != loop_tid, (
                        "a reconcile pass ran ON the event loop thread"
                    )
                out["pruned_blocks"] = pruned
                out["snapshots"] = snaps
            else:
                assert not storage_spans, (
                    "lifecycle OFF leg emitted storage spans"
                )
            for n in nodes:
                n.close_stores()
            return out
        finally:
            crypto_batch.set_default_backend(old_backend)
            shutil.rmtree(base, ignore_errors=True)

    run_once(lifecycle=False)  # warm pass (sqlite, allocator, pools)
    runs = {"off": [], "on": []}
    for _ in range(repeats):
        runs["off"].append(run_once(lifecycle=False))
        runs["on"].append(run_once(lifecycle=True))
    med = {
        mode: round(
            statistics.median(r["blocks_per_s"] for r in rs), 2
        )
        for mode, rs in runs.items()
    }
    overhead = round(1.0 - med["on"] / med["off"], 4)
    if overhead > max_overhead:
        raise AssertionError(
            f"lifecycle overhead {overhead:.1%} exceeds the "
            f"{max_overhead:.0%} gate (on={med['on']} "
            f"off={med['off']} blocks/s)"
        )
    last = runs["on"][-1]
    return {
        "rate": med["on"],
        "nodes": n_nodes,
        "heights": heights,
        "repeats_per_mode": repeats,
        "blocks_per_s_off": med["off"],
        "blocks_per_s_on": med["on"],
        "overhead": overhead,
        "overhead_gate": max_overhead,
        "pruned_blocks": last["pruned_blocks"],
        "snapshots": last["snapshots"],
        "base": last["base"],
        "storage_spans": last["storage_spans"],
        "note": (
            "4-node LocalNet, retention plane OFF vs ON (retain 8, "
            "snapshot every 10, 0.2s cadence); pass-interleaved "
            "medians; agreement asserted over the surviving window; "
            "every storage.prune/storage.snapshot span proven off "
            "the consensus loop (retention timeline + worker-thread "
            "ident)"
        ),
    }


def bench_serve() -> dict:
    """Light-client serving plane storm (ISSUE 13, docs/PERF.md
    "Light-client serving plane"): 1k+ concurrent light sessions
    (connect/bisect/verify) against one serving front, ablated three
    ways over the SAME seeded request schedule:

    - baseline   — today's per-request, per-client shape: every
      session is its own fresh Client (own signature cache, own
      store) paying root verify + full bisection;
    - coalesced  — cold shared plane: cross-client verified-header
      cache + single-flight + coalesced commit verification
      (light/serving.py);
    - warm       — the same plane, second pass (cache hot).

    Pass-interleaved (baseline/cold/warm per repeat) with medians,
    the same throttling defense as bench_ingest/bench_live. In-bench
    verdict parity: coalesced engine verdicts vs serial
    verify_commit_light over valid + forged commits, plus served
    blocks hash-compared against a per-request client. The
    light.serve.request p99 is gated against
    tools/span_budgets.toml. A small LIVE sub-leg storms a running
    LocalNet node's stores through the same plane."""
    import concurrent.futures
    import statistics
    import time as _time

    import cometbft_tpu.types as T
    from cometbft_tpu.crypto.keys import Ed25519PrivKey
    from cometbft_tpu.light.client import Client, TrustOptions
    from cometbft_tpu.light.provider import Provider
    from cometbft_tpu.light.serving import (
        CoalescedCommitVerifier,
        LightServingPlane,
    )
    from cometbft_tpu.light.types import LightBlock
    from cometbft_tpu.obs.budget import (
        default_budget_file,
        evaluate_budgets,
        load_budgets,
    )
    from cometbft_tpu.trace import summarize
    from cometbft_tpu.trace.tracer import Tracer

    SESSIONS = int(os.environ.get("BENCH_SERVE_SESSIONS", "1000"))
    WORKERS = int(os.environ.get("BENCH_SERVE_WORKERS", "64"))
    REPEATS = int(os.environ.get("BENCH_SERVE_REPEATS", "3"))
    TARGET = int(os.environ.get("BENCH_SERVE_HEIGHTS", "4000"))
    DISTINCT = int(os.environ.get("BENCH_SERVE_DISTINCT", "40"))
    POOL = int(os.environ.get("BENCH_SERVE_POOL", "8"))
    # small committee: serving cost scales with signatures and the
    # baseline pays them 1000x over — 32 vals keeps the ablation
    # honest AND inside the leg budget on this box
    NV = 32
    EPOCH = 400
    SHIFT = 14  # 1-epoch overlap 18/32 (>1/3); 2+ epochs 4/32 (<1/3)
    chain_id = "bench-serve"

    rng = np.random.default_rng(41)
    n_epochs = TARGET // EPOCH + 2
    pool_keys = [
        Ed25519PrivKey.from_seed(rng.bytes(32))
        for _ in range(n_epochs * SHIFT + NV)
    ]
    t0_ns = time.time_ns() - (TARGET + 120) * 1_000_000_000
    _vs_cache: dict = {}

    def vals_at(height: int):
        epoch = height // EPOCH
        vs = _vs_cache.get(epoch)
        if vs is None:
            start = epoch * SHIFT
            vs = T.ValidatorSet(
                [
                    T.Validator(p.pub_key(), 10)
                    for p in pool_keys[start : start + NV]
                ]
            )
            _vs_cache[epoch] = vs
        return vs

    priv_by_addr = {p.pub_key().address(): p for p in pool_keys}

    class MintingProvider(Provider):
        """Synthetic signed chain (bench_bisect's shape), memoized so
        mint cost is paid once per height — the measured deltas are
        verification policy, not signing."""

        def __init__(self):
            self.chain_id = chain_id
            self._minted: dict = {}
            self._lock = threading.Lock()

        def light_block(self, height: int) -> LightBlock:
            with self._lock:
                got = self._minted.get(height)
            if got is not None:
                return got
            vs_h = vals_at(height)
            h = T.Header(
                chain_id=chain_id,
                height=height,
                time_ns=t0_ns + height * 1_000_000_000,
                validators_hash=vs_h.hash(),
                next_validators_hash=vals_at(height + 1).hash(),
            )
            bid = T.BlockID(h.hash(), T.PartSetHeader(1, h.hash()))
            sigs = []
            for i, val in enumerate(vs_h.validators):
                v = T.Vote(
                    type_=T.PRECOMMIT,
                    height=height,
                    round=0,
                    block_id=bid,
                    timestamp_ns=h.time_ns,
                    validator_address=val.address,
                    validator_index=i,
                )
                sigs.append(
                    T.CommitSig(
                        block_id_flag=T.BLOCK_ID_FLAG_COMMIT,
                        validator_address=val.address,
                        timestamp_ns=h.time_ns,
                        signature=priv_by_addr[val.address].sign(
                            v.sign_bytes(chain_id)
                        ),
                    )
                )
            lb = LightBlock(
                h,
                T.Commit(
                    height=height, round=0, block_id=bid,
                    signatures=sigs,
                ),
                vs_h,
            )
            with self._lock:
                self._minted[height] = lb
            return lb

        def report_evidence(self, ev) -> None:
            pass

    provider = MintingProvider()
    root = provider.light_block(1)
    trust = TrustOptions(
        period_ns=10 * 365 * 86400 * 10**9, height=1, hash=root.hash()
    )
    req_rng = np.random.default_rng(1013)
    distinct = sorted(
        int(x)
        for x in req_rng.choice(
            np.arange(TARGET // 2, TARGET), size=DISTINCT,
            replace=False,
        )
    )
    schedule = [
        distinct[int(i) % len(distinct)] for i in range(SESSIONS)
    ]

    def run_sessions(serve_one) -> tuple:
        """Drive the seeded schedule through ``serve_one(height)``
        on WORKERS threads; returns (sorted per-session ms, wall s)."""
        lat = []
        lock = threading.Lock()

        def one(sid: int) -> None:
            t0 = _time.monotonic()
            lb = serve_one(schedule[sid])
            dt = (_time.monotonic() - t0) * 1e3
            assert lb.height == schedule[sid]
            with lock:
                lat.append(dt)

        t0 = _time.monotonic()
        with concurrent.futures.ThreadPoolExecutor(WORKERS) as ex:
            for f in [
                ex.submit(one, sid) for sid in range(SESSIONS)
            ]:
                f.result()
        wall = _time.monotonic() - t0
        lat.sort()
        return lat, wall

    def pcts(lat: list, wall: "float | None" = None) -> dict:
        out = {
            "p50_ms": round(lat[int(0.50 * (len(lat) - 1))], 3),
            "p99_ms": round(lat[int(0.99 * (len(lat) - 1))], 3),
            "mean_ms": round(sum(lat) / len(lat), 3),
        }
        if wall is not None:
            out["sessions_per_s"] = round(len(lat) / wall, 1)
        return out

    tracer = Tracer(name="serve", size=1 << 17)

    def baseline_pass() -> dict:
        def serve_one(h):
            # per-session client: root verify + own bisection — the
            # pre-plane proxy shape (connect cost included: a fresh
            # session IS a connect)
            c = Client(chain_id, trust, provider)
            return c.verify_light_block_at_height(h)

        return pcts(*run_sessions(serve_one))

    def plane_passes() -> tuple:
        clients = [
            Client(chain_id, trust, provider) for _ in range(POOL)
        ]
        plane = LightServingPlane(
            clients,
            max_sessions=SESSIONS + WORKERS,
            max_inflight=WORKERS,
            tracer=tracer,
        )

        def serve_one(h):
            with plane.open_session() as s:
                return s.verified_block(h)

        cold = pcts(*run_sessions(serve_one))
        warm = pcts(*run_sessions(serve_one))
        return cold, warm, plane.stats()

    runs = {"baseline": [], "coalesced_cold": [], "warm": []}
    plane_stats = None
    for _ in range(REPEATS):
        runs["baseline"].append(baseline_pass())
        cold, warm, plane_stats = plane_passes()
        runs["coalesced_cold"].append(cold)
        runs["warm"].append(warm)
    med = {
        mode: {
            k: round(statistics.median(r[k] for r in rs), 3)
            for k in (
                "p50_ms", "p99_ms", "mean_ms", "sessions_per_s",
            )
        }
        for mode, rs in runs.items()
    }

    # --- in-bench verdict parity (serial vs coalesced engine) ----------
    def parity() -> dict:
        import dataclasses
        from fractions import Fraction

        good = provider.light_block(distinct[0])
        forged_commit = dataclasses.replace(
            good.commit,
            signatures=[
                dataclasses.replace(
                    good.commit.signatures[0], signature=bytes(64)
                )
            ]
            + list(good.commit.signatures[1:]),
        )
        jobs = [
            ("light", good.validator_set, good.commit.block_id,
             good.height, good.commit),
            ("light", good.validator_set, good.commit.block_id,
             good.height, forged_commit),
            ("trusting", good.validator_set, good.commit,
             Fraction(1, 3)),
        ]
        serial = []
        for job in jobs:
            try:
                if job[0] == "light":
                    T.verify_commit_light(
                        chain_id, job[1], job[2], job[3], job[4]
                    )
                else:
                    T.verify_commit_light_trusting(
                        chain_id, job[1], job[2], trust_level=job[3]
                    )
                serial.append(None)
            except T.CommitVerifyError as e:
                serial.append(type(e).__name__)
        engine = CoalescedCommitVerifier(chain_id, window_s=0.01)
        coalesced = [None] * len(jobs)
        errs = []

        def submit(i, job):
            try:
                if job[0] == "light":
                    engine.verify_commit_light(
                        job[1], job[2], job[3], job[4]
                    )
                else:
                    engine.verify_commit_light_trusting(
                        job[1], job[2], job[3]
                    )
            except T.CommitVerifyError as e:
                coalesced[i] = type(e).__name__
            except Exception as e:
                errs.append(repr(e))

        ths = [
            threading.Thread(target=submit, args=(i, j))
            for i, j in enumerate(jobs)
        ]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        identical = serial == coalesced and not errs
        # served-block parity: the plane's answer is bit-identical to
        # a per-request client's for sampled heights
        solo = Client(chain_id, trust, provider)
        clients = [Client(chain_id, trust, provider)]
        plane = LightServingPlane(clients, max_inflight=4)
        served_equal = all(
            bytes(plane.serve(h).hash())
            == bytes(solo.verify_light_block_at_height(h).hash())
            for h in distinct[:3]
        )
        return {
            "identical": bool(identical),
            "serial": serial,
            "coalesced": coalesced,
            "served_blocks_equal": bool(served_equal),
            "batched": engine.stats()["dispatches"] > 0,
        }

    parity_out = parity()
    assert parity_out["identical"] and parity_out[
        "served_blocks_equal"
    ], f"serving verdict parity broken: {parity_out}"

    # --- span-budget gate (tools/span_budgets.toml) --------------------
    tsum = summarize({"serve": tracer.snapshot()})
    verdicts = [
        v
        for v in evaluate_budgets(
            tsum, load_budgets(default_budget_file())
        )
        if v["span"] == "light.serve.request"
    ]
    budget_ok = all(v["ok"] for v in verdicts)

    # --- live sub-leg: storm a RUNNING LocalNet node -------------------
    def live_leg() -> dict:
        import asyncio
        import shutil
        import tempfile

        from cometbft_tpu.config.config import test_config
        from cometbft_tpu.light.provider import StoreBackedProvider
        from cometbft_tpu.node.inprocess import (
            LocalNet,
            build_node,
            make_genesis,
        )

        n_live = int(os.environ.get("BENCH_SERVE_LIVE_SESSIONS", "300"))
        heights = 12
        base = tempfile.mkdtemp(prefix="bench_serve_live_")
        try:
            gen, pvs = make_genesis(2, chain_id="bench-serve-live")
            nodes = []
            for i, pv in enumerate(pvs):
                home = os.path.join(base, f"n{i}")
                os.makedirs(home, exist_ok=True)
                cfg = test_config(home)
                cfg.base.moniker = f"n{i}"
                cfg.consensus.skip_timeout_commit = True
                cfg.consensus.timeout_commit_s = 0.0
                cfg.tx_index.indexer = "null"
                nodes.append(
                    build_node(gen, pv, config=cfg, home=home)
                )
            net = LocalNet(nodes)

            async def main():
                await net.start()
                await net.wait_for_height(heights, timeout=300)
                src = nodes[0]
                prov = StoreBackedProvider(
                    gen.chain_id, src.block_store, src.state_store
                )
                lroot = prov.light_block(1)
                ltrust = TrustOptions(
                    period_ns=24 * 3600 * 10**9,
                    height=1,
                    hash=lroot.hash(),
                )
                plane = LightServingPlane(
                    [
                        Client(gen.chain_id, ltrust, prov)
                        for _ in range(4)
                    ],
                    max_sessions=n_live + 32,
                    max_inflight=32,
                )
                lrng = np.random.default_rng(7)
                hs = [
                    int(x)
                    for x in lrng.integers(2, heights + 1, n_live)
                ]

                def storm():
                    lat = []
                    lock = threading.Lock()

                    def one(sid):
                        t0 = _time.monotonic()
                        with plane.open_session() as s:
                            lb = s.verified_block(hs[sid])
                        dt = (_time.monotonic() - t0) * 1e3
                        want = src.block_store.load_block_meta(
                            hs[sid]
                        ).block_id.hash
                        assert bytes(lb.hash()) == bytes(want)
                        with lock:
                            lat.append(dt)

                    with concurrent.futures.ThreadPoolExecutor(
                        32
                    ) as ex:
                        for f in [
                            ex.submit(one, i) for i in range(n_live)
                        ]:
                            f.result()
                    lat.sort()
                    return lat

                # the node keeps committing WHILE the storm runs
                lat = await asyncio.to_thread(storm)
                stats = plane.stats()
                await net.stop()
                return lat, stats

            lat, stats = asyncio.run(main())
            for n in nodes:
                n.close_stores()
            return {
                "sessions": n_live,
                **pcts(lat),
                "cache": stats["cache"],
                "verdict_parity": True,
            }
        except Exception as e:
            return {"note": f"live leg degraded: {e!r}"}
        finally:
            shutil.rmtree(base, ignore_errors=True)

    live = live_leg()

    speedup = _ratio(
        med["baseline"]["p99_ms"], med["coalesced_cold"]["p99_ms"]
    )
    return {
        "rate": med["warm"]["sessions_per_s"],
        "sessions": SESSIONS,
        "workers": WORKERS,
        "distinct_heights": DISTINCT,
        "target_height": TARGET,
        "validators": NV,
        "repeats": REPEATS,
        "baseline": med["baseline"],
        "coalesced_cold": med["coalesced_cold"],
        "warm": med["warm"],
        "p99_speedup_cold_vs_baseline": speedup,
        "p99_speedup_warm_vs_baseline": _ratio(
            med["baseline"]["p99_ms"], med["warm"]["p99_ms"]
        ),
        "plane": plane_stats,
        "verdict_parity": parity_out,
        "budget": {"ok": budget_ok, "verdicts": verdicts},
        "live": live,
        "note": (
            "baseline = per-session fresh Client (root verify + own "
            "bisection, the pre-plane proxy shape); coalesced_cold = "
            "shared verified-header cache + single-flight + "
            "coalesced commit verify from cold; warm = same plane, "
            "hot cache. Pass-interleaved medians of per-session "
            "latency; rate = warm sessions/s."
        ),
    }


def bench_rpcfanout() -> dict:
    """Outbound event fan-out storm (ISSUE 15, docs/PERF.md "Outbound
    fan-out plane"): 10k websocket subscribers over a handful of
    query shapes receive a sustained committed block/tx event stream,
    ablated two ways over the SAME seeded events and the SAME sink
    sockets:

    - baseline — the pre-plane rpc/server.py shape: one pump per
      subscriber, attrs flattened AND the full payload JSON-encoded
      per subscriber per event;
    - fanout   — the FanoutHub: attrs once per event, ONE encode per
      (event, query shape), per-subscriber frames spliced from the
      shared payload.

    Pass-interleaved medians; parity of delivered event streams
    asserted across modes (sampled subscribers, parsed-JSON
    equality); ZERO sheds required (the sinks drain instantly, so
    any drop is a plane bug); end-to-end delivery p99 and the
    fanout.deliver span gated against tools/span_budgets.toml.
    Gate: >=5x delivered-frames/s vs the baseline."""
    import asyncio
    import hashlib
    import statistics
    import time as _time

    import cometbft_tpu.types as T
    from cometbft_tpu.abci import types as abci
    from cometbft_tpu.obs.budget import (
        default_budget_file,
        evaluate_budgets,
        load_budgets,
    )
    from cometbft_tpu.rpc.fanout import (
        FanoutHub,
        _event_attrs,
        _event_json,
    )
    from cometbft_tpu.trace import summarize
    from cometbft_tpu.trace.tracer import Tracer
    from cometbft_tpu.types import events as ev
    from cometbft_tpu.utils.pubsub_query import parse as parse_query

    SUBS = int(os.environ.get("BENCH_FANOUT_SUBS", "10000"))
    HEIGHTS = int(os.environ.get("BENCH_FANOUT_HEIGHTS", "16"))
    TXS = int(os.environ.get("BENCH_FANOUT_TXS", "2"))
    REPEATS = int(os.environ.get("BENCH_FANOUT_REPEATS", "3"))
    chain_id = "bench-fanout"

    # --- seeded sustained-ingest event stream (the PR 5/PR 10
    # workload driver's tx shape: deterministic k=v payloads) --------
    from cometbft_tpu.chaos.workload import WorkloadSpec

    wl = WorkloadSpec(pattern="sustained", tx_bytes=64)
    tx_rng = np.random.default_rng(4242)
    vs, _ = T.random_validator_set(1)
    t0_ns = time.time_ns() - (HEIGHTS + 60) * 1_000_000_000

    def make_height(h, prev_bid):
        txs = [
            b"bench/f%d_%d=%s"
            % (h, i, tx_rng.bytes(wl.tx_bytes // 2).hex().encode())
            for i in range(TXS)
        ]
        data = T.Data(txs=txs)
        last_commit = (
            T.Commit(h - 1, 0, prev_bid, []) if h > 1 else None
        )
        header = T.Header(
            chain_id=chain_id,
            height=h,
            time_ns=t0_ns + h * 1_000_000_000,
            last_block_id=prev_bid,
            validators_hash=vs.hash(),
            next_validators_hash=vs.hash(),
            app_hash=b"\x01" * 32,
            proposer_address=vs.validators[0].address,
            data_hash=data.hash(),
            last_commit_hash=last_commit.hash() if last_commit else b"",
        )
        return T.Block(header=header, data=data, last_commit=last_commit)

    def tx_result(i):
        return abci.ExecTxResult(
            code=0,
            events=[
                abci.Event(
                    "transfer",
                    [abci.EventAttribute("lane", f"l{i % 4}", True)],
                )
            ],
        )

    events = []
    prev = T.BlockID()
    for h in range(1, HEIGHTS + 1):
        blk = make_height(h, prev)
        prev = T.BlockID(blk.hash(), T.PartSetHeader(1, blk.hash()))
        events.append(
            ev.Event(
                ev.EVENT_NEW_BLOCK,
                {"block": blk, "block_id": None, "result_events": []},
                {"height": str(h)},
            )
        )
        for i, tx in enumerate(blk.data.txs):
            events.append(
                ev.Event(
                    ev.EVENT_TX,
                    {
                        "height": h,
                        "index": i,
                        "tx": tx,
                        "result": tx_result(i),
                    },
                    {"hash": hashlib.sha256(tx).hexdigest()},
                )
            )

    # query shapes: most subscribers follow new blocks (the real-world
    # exchange/wallet mix), the rest follow tx streams
    SHAPES = [
        ("tm.event='NewBlock'", 70),
        ("tm.event='Tx'", 20),
        ("tm.event='Tx' AND transfer.lane='l1'", 7),
        ("tm.event='NewBlockHeader'", 3),  # matches nothing published
    ]
    weights = [w for _, w in SHAPES]
    srng = np.random.default_rng(99)
    draws = srng.choice(len(SHAPES), size=SUBS, p=[w / 100 for w in weights])
    shape_of = [int(x) for x in draws]  # subscriber -> shape (seeded)
    queries = [(qs, parse_query(qs)) for qs, _ in SHAPES]

    def expected_frames(shape_idx) -> int:
        qs, q = queries[shape_idx]
        return sum(1 for e in events if q.matches(_event_attrs(e)))

    per_shape_frames = [expected_frames(i) for i in range(len(SHAPES))]
    total_expected = sum(
        per_shape_frames[s] for s in shape_of
    )

    class SinkWS:
        __slots__ = ("frames", "stamps")

        def __init__(self):
            self.frames = []
            self.stamps = []

        async def send_str(self, s):
            self.frames.append(s)
            self.stamps.append(_time.monotonic())

    SAMPLE = [  # parity sample: first subscriber of each shape
        shape_of.index(i) for i in range(len(SHAPES)) if i in shape_of
    ]

    def baseline_pass() -> tuple:
        """The pre-ISSUE-15 rpc/server.py architecture, faithfully:
        one bus Subscription + one pump task PER SUBSCRIBER, each
        pump flattening attrs, matching its query and json-encoding
        the whole response itself (what pump + ws.send_json paid) —
        N subscribers, N serializations per event."""
        sinks = [SinkWS() for _ in range(SUBS)]
        encode_box = [0]

        async def run() -> float:
            bus = ev.EventBus()
            bus.set_loop(asyncio.get_running_loop())
            tasks = []

            async def pump(sub, sink, sid):
                qs, q = queries[shape_of[sid]]
                try:
                    while True:
                        e = await sub.queue.get()
                        attrs = _event_attrs(e)
                        if not q.matches(attrs):
                            continue
                        frame = json.dumps(
                            {
                                "jsonrpc": "2.0",
                                "id": sid,
                                "result": {
                                    "query": qs,
                                    "data": _event_json(e),
                                    "events": attrs,
                                },
                            }
                        )
                        encode_box[0] += 1
                        await sink.send_str(frame)
                except asyncio.CancelledError:
                    pass

            for sid in range(SUBS):
                sub = bus.subscribe()
                tasks.append(
                    asyncio.ensure_future(
                        pump(sub, sinks[sid], sid)
                    )
                )
            t0 = _time.monotonic()
            for e in events:
                bus.publish(e)
                await asyncio.sleep(0)
            deadline = asyncio.get_running_loop().time() + 600
            while (
                sum(len(s.frames) for s in sinks) < total_expected
            ):
                if asyncio.get_running_loop().time() > deadline:
                    raise RuntimeError("baseline delivery stalled")
                await asyncio.sleep(0.005)
            wall = _time.monotonic() - t0
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            return wall

        wall = asyncio.run(run())
        return sinks, encode_box[0], wall

    tracer = Tracer(name="rpcfanout", size=1 << 16)

    def fanout_pass() -> tuple:
        sinks = [SinkWS() for _ in range(SUBS)]
        pub_stamps = {}

        async def run() -> tuple:
            bus = ev.EventBus()
            bus.set_loop(asyncio.get_running_loop())
            hub = FanoutHub(bus, tracer=tracer)
            for sid in range(SUBS):
                qs, q = queries[shape_of[sid]]
                hub.attach(sinks[sid], qs, q, sid)
            t0 = _time.monotonic()
            for i, e in enumerate(events):
                pub_stamps[i] = _time.monotonic()
                bus.publish(e)
                # sustained ingest: yield so delivery interleaves
                # with publishing (the live loop's shape) instead of
                # batching every event behind the last publish
                await asyncio.sleep(0)
            deadline = asyncio.get_running_loop().time() + 120
            while (
                sum(len(s.frames) for s in sinks) < total_expected
            ):
                if asyncio.get_running_loop().time() > deadline:
                    raise RuntimeError(
                        "fanout delivery stalled: "
                        f"{sum(len(s.frames) for s in sinks)}"
                        f"/{total_expected}"
                    )
                await asyncio.sleep(0.002)
            wall = _time.monotonic() - t0
            stats = hub.queue_stats()
            encodes = hub.encodes
            await hub.close()
            return wall, stats, encodes

        wall, stats, encodes = asyncio.run(run())
        return sinks, encodes, wall, stats, pub_stamps

    runs = {"baseline": [], "fanout": []}
    parity_checked = False
    shed_total = 0
    delivery_lat_ms: list = []
    for _ in range(REPEATS):
        b_sinks, b_encodes, b_wall = baseline_pass()
        f_sinks, f_encodes, f_wall, f_stats, pub_stamps = fanout_pass()
        shed_total += f_stats["dropped"]
        runs["baseline"].append(
            {
                "wall_s": b_wall,
                "frames_per_s": total_expected / b_wall,
                "encodes": b_encodes,
            }
        )
        runs["fanout"].append(
            {
                "wall_s": f_wall,
                "frames_per_s": total_expected / f_wall,
                "encodes": f_encodes,
            }
        )
        # end-to-end delivery latency per frame: sink stamp minus the
        # LAST publish at or before it (frames deliver in publish
        # order, so that publish is the frame's own event or a later
        # one — an upper bound on staleness, never an undercount)
        all_stamps = sorted(
            ts for s in f_sinks for ts in s.stamps
        )
        pub_sorted = sorted(pub_stamps.values())
        import bisect as _bisect

        for ts in all_stamps:
            i = _bisect.bisect_right(pub_sorted, ts) - 1
            if i >= 0:
                delivery_lat_ms.append((ts - pub_sorted[i]) * 1e3)
        if not parity_checked:
            # parity: parsed frame streams identical per sampled
            # subscriber across modes
            for sid in SAMPLE:
                bl = [json.loads(x) for x in b_sinks[sid].frames]
                fl = [json.loads(x) for x in f_sinks[sid].frames]
                assert bl == fl, (
                    f"fan-out delivery diverged for subscriber {sid} "
                    f"({len(bl)} vs {len(fl)} frames)"
                )
            parity_checked = True

    assert shed_total == 0, (
        f"{shed_total} frames shed with instant-drain sinks — the "
        "fan-out plane dropped deliverable work"
    )
    med = {
        mode: {
            k: round(statistics.median(r[k] for r in rs), 3)
            for k in ("wall_s", "frames_per_s", "encodes")
        }
        for mode, rs in runs.items()
    }
    ratio = _ratio(
        med["fanout"]["frames_per_s"], med["baseline"]["frames_per_s"]
    )
    assert ratio is not None and ratio >= 5.0, (
        f"fan-out delivery only {ratio}x the per-subscriber-"
        "serialization baseline (gate: >=5x)"
    )
    delivery_lat_ms.sort()

    def pct(p):
        return round(
            delivery_lat_ms[int(p * (len(delivery_lat_ms) - 1))], 3
        )

    # span-budget gate (tools/span_budgets.toml fanout.deliver)
    tsum = summarize({"rpcfanout": tracer.snapshot()})
    verdicts = [
        v
        for v in evaluate_budgets(
            tsum, load_budgets(default_budget_file())
        )
        if v["span"] == "fanout.deliver"
    ]
    budget_ok = all(v["ok"] for v in verdicts)
    assert budget_ok, f"fanout.deliver budget breached: {verdicts}"

    events_per_height = 1 + TXS
    return {
        "rate": med["fanout"]["frames_per_s"],
        "subscribers": SUBS,
        "heights": HEIGHTS,
        "events": len(events),
        "expected_frames": total_expected,
        "repeats": REPEATS,
        "shapes": [qs for qs, _ in SHAPES],
        "baseline": med["baseline"],
        "fanout": med["fanout"],
        "throughput_ratio": ratio,
        "encode_ratio": _ratio(
            med["baseline"]["encodes"], med["fanout"]["encodes"]
        ),
        "delivery_p50_ms": pct(0.50),
        "delivery_p99_ms": pct(0.99),
        "blocks_per_s_delivered": round(
            HEIGHTS
            * events_per_height
            / max(med["fanout"]["wall_s"], 1e-9)
            / events_per_height,
            2,
        ),
        "sheds": shed_total,
        "parity_ok": True,
        "budget": {"ok": budget_ok, "verdicts": verdicts},
        "note": (
            "baseline = per-subscriber attrs+JSON encode per event "
            "(the pre-ISSUE-15 pump shape) into the same sink "
            "sockets; fanout = FanoutHub one-encode-per-(event,"
            "query-shape). Pass-interleaved medians; parity = parsed "
            "frame streams identical per sampled subscriber; "
            "delivery latency = publish->sink per frame."
        ),
    }


def bench_fleet() -> dict:
    """Serving-fleet storm (ISSUE 19, docs/FLEET.md, docs/PERF.md
    "Serving fleet"): N follower replicas behind a SessionRouter vs
    ONE FanoutHub carrying the same TOTAL subscriber load, over the
    SAME seeded committed-block event stream:

    - hub   — the single-node plane (rpc/fanout.py): every session on
      one FanoutHub, per-subscriber elastic queue + writer task;
    - fleet — N FollowerNode replicas tail-following one StreamSource,
      sessions admitted + least-loaded-placed by the SessionRouter,
      replica-paced direct delivery (fleet/follower.py).

    Pass-interleaved medians for the throughput legs, then ONE storm
    pass at full scale: routed light sessions (consistency tokens,
    shared cross-replica VerifiedHeaderCache) ride along while one
    replica is KILLED mid-stream — every stranded session must resume
    elsewhere with zero lost commits (store replay + live splice),
    gap-freeness checked per session against the seeded chain and
    frame content store-verified on a kept sample. Gates: aggregate
    delivered-frames/s >= 2.5x the single-hub plane at equal load,
    re-admit p99 inside the fleet.failover budget, zero sheds, and
    the fleet.route/fleet.failover spans against
    tools/span_budgets.toml."""
    import asyncio
    import statistics
    import time as _time

    import cometbft_tpu.types as T
    from cometbft_tpu.abci import types as abci
    from cometbft_tpu.chaos.workload import WorkloadSpec
    from cometbft_tpu.crypto.keys import Ed25519PrivKey
    from cometbft_tpu.fleet import (
        FollowerNode,
        SessionRouter,
        StreamSource,
    )
    from cometbft_tpu.fleet.follower import event_payload, height_events
    from cometbft_tpu.fleet.router import _HEIGHT_RE
    from cometbft_tpu.light.client import Client, TrustOptions
    from cometbft_tpu.light.provider import Provider
    from cometbft_tpu.light.serving import (
        LightServingPlane,
        VerifiedHeaderCache,
    )
    from cometbft_tpu.light.types import LightBlock
    from cometbft_tpu.obs.budget import (
        default_budget_file,
        evaluate_budgets,
        load_budgets,
    )
    from cometbft_tpu.rpc.fanout import FanoutHub, _event_attrs
    from cometbft_tpu.trace import summarize
    from cometbft_tpu.trace.tracer import Tracer
    from cometbft_tpu.types import events as ev
    from cometbft_tpu.utils.pubsub_query import parse as parse_query

    REPLICAS = int(os.environ.get("BENCH_FLEET_REPLICAS", "3"))
    SUBS_PER = int(os.environ.get("BENCH_FLEET_SUBS", "10000"))
    LIGHT = int(os.environ.get("BENCH_FLEET_LIGHT", "1000"))
    HEIGHTS = int(os.environ.get("BENCH_FLEET_HEIGHTS", "16"))
    TXS = int(os.environ.get("BENCH_FLEET_TXS", "2"))
    REPEATS = int(os.environ.get("BENCH_FLEET_REPEATS", "2"))
    LIGHT_WORKERS = int(
        os.environ.get("BENCH_FLEET_LIGHT_WORKERS", "16")
    )
    LIGHT_TARGET = int(
        os.environ.get("BENCH_FLEET_LIGHT_HEIGHTS", "256")
    )
    TOTAL = REPLICAS * SUBS_PER
    KILL_AT = max(2, HEIGHTS // 2)
    KEEP_N = 512  # sessions whose full frames are kept for parity
    chain_id = "bench-fleet"

    # --- seeded committed chain (bench_rpcfanout's block shape) -----
    wl = WorkloadSpec(pattern="sustained", tx_bytes=64)
    tx_rng = np.random.default_rng(5151)
    vs, _ = T.random_validator_set(1)
    t0_ns = time.time_ns() - (HEIGHTS + 60) * 1_000_000_000

    def make_height(h, prev_bid):
        txs = [
            b"bench/fl%d_%d=%s"
            % (h, i, tx_rng.bytes(wl.tx_bytes // 2).hex().encode())
            for i in range(TXS)
        ]
        data = T.Data(txs=txs)
        last_commit = (
            T.Commit(h - 1, 0, prev_bid, []) if h > 1 else None
        )
        header = T.Header(
            chain_id=chain_id,
            height=h,
            time_ns=t0_ns + h * 1_000_000_000,
            last_block_id=prev_bid,
            validators_hash=vs.hash(),
            next_validators_hash=vs.hash(),
            app_hash=b"\x02" * 32,
            proposer_address=vs.validators[0].address,
            data_hash=data.hash(),
            last_commit_hash=last_commit.hash() if last_commit else b"",
        )
        return T.Block(header=header, data=data, last_commit=last_commit)

    def results_fn(block, i, tx):
        return abci.ExecTxResult(
            code=0,
            events=[
                abci.Event(
                    "transfer",
                    [abci.EventAttribute("lane", f"l{i % 4}", True)],
                )
            ],
        )

    blocks = []
    flat = []  # (height, event) in canonical delivery order
    prev = T.BlockID()
    for h in range(1, HEIGHTS + 1):
        blk = make_height(h, prev)
        prev = T.BlockID(blk.hash(), T.PartSetHeader(1, blk.hash()))
        blocks.append(blk)
        for e in height_events(blk, results_fn):
            flat.append((h, e))

    SHAPES = [
        ("tm.event='NewBlock'", 70),
        ("tm.event='Tx'", 20),
        ("tm.event='Tx' AND transfer.lane='l1'", 7),
        ("tm.event='NewBlockHeader'", 3),  # matches nothing published
    ]
    weights = [w for _, w in SHAPES]
    srng = np.random.default_rng(107)
    draws = srng.choice(
        len(SHAPES), size=TOTAL, p=[w / 100 for w in weights]
    )
    shape_of = [int(x) for x in draws]
    queries = [(qs, parse_query(qs)) for qs, _ in SHAPES]

    # per shape: the store-derived expectation every delivered stream
    # is judged against (heights for gap-freeness, parsed payloads for
    # content) — THE zero-lost-commits oracle
    exp_heights = []
    expected_results = []
    for qs, q in queries:
        matched = [
            (h, e) for h, e in flat if q.matches(_event_attrs(e))
        ]
        exp_heights.append([h for h, _ in matched])
        expected_results.append(
            [json.loads(event_payload(e, qs)) for _, e in matched]
        )
    per_shape_frames = [len(x) for x in exp_heights]
    total_expected = sum(per_shape_frames[s] for s in shape_of)

    class Sink:
        __slots__ = ("count", "keep", "record", "frames", "heights",
                     "stamps")

        def __init__(self, keep=False, record=False):
            self.count = 0
            self.keep = keep
            self.record = record
            self.frames = []
            self.heights = []
            self.stamps = []

        async def send_str(self, s):
            self.count += 1
            if self.record:
                self.stamps.append(_time.monotonic())
                m = _HEIGHT_RE.search(s)
                if m:
                    self.heights.append(int(m.group(1)))
            if self.keep:
                self.frames.append(s)

    def check_content(sinks, sids, where):
        for sid in sids:
            got = [json.loads(x)["result"] for x in sinks[sid].frames]
            assert got == expected_results[shape_of[sid]], (
                f"{where}: frame stream diverged from the store for "
                f"session {sid} ({len(got)} frames)"
            )

    # --- routed-light corpus: small signed chain, static committee --
    light_chain = "bench-fleet-light"
    NV = 8
    lrng = np.random.default_rng(61)
    light_keys = [
        Ed25519PrivKey.from_seed(lrng.bytes(32)) for _ in range(NV)
    ]
    light_vs = T.ValidatorSet(
        [T.Validator(p.pub_key(), 10) for p in light_keys]
    )
    priv_by_addr = {p.pub_key().address(): p for p in light_keys}
    lt0_ns = time.time_ns() - (LIGHT_TARGET + 120) * 1_000_000_000

    class MintingProvider(Provider):
        def __init__(self):
            self.chain_id = light_chain
            self._minted: dict = {}
            self._lock = threading.Lock()

        def light_block(self, height: int) -> LightBlock:
            with self._lock:
                got = self._minted.get(height)
            if got is not None:
                return got
            h = T.Header(
                chain_id=light_chain,
                height=height,
                time_ns=lt0_ns + height * 1_000_000_000,
                validators_hash=light_vs.hash(),
                next_validators_hash=light_vs.hash(),
            )
            bid = T.BlockID(h.hash(), T.PartSetHeader(1, h.hash()))
            sigs = []
            for i, val in enumerate(light_vs.validators):
                v = T.Vote(
                    type_=T.PRECOMMIT,
                    height=height,
                    round=0,
                    block_id=bid,
                    timestamp_ns=h.time_ns,
                    validator_address=val.address,
                    validator_index=i,
                )
                sigs.append(
                    T.CommitSig(
                        block_id_flag=T.BLOCK_ID_FLAG_COMMIT,
                        validator_address=val.address,
                        timestamp_ns=h.time_ns,
                        signature=priv_by_addr[val.address].sign(
                            v.sign_bytes(light_chain)
                        ),
                    )
                )
            lb = LightBlock(
                h,
                T.Commit(
                    height=height, round=0, block_id=bid,
                    signatures=sigs,
                ),
                light_vs,
            )
            with self._lock:
                self._minted[height] = lb
            return lb

        def report_evidence(self, evd) -> None:
            pass

    light_provider = MintingProvider()
    light_root = light_provider.light_block(1)
    light_trust = TrustOptions(
        period_ns=10 * 365 * 86400 * 10**9,
        height=1,
        hash=light_root.hash(),
    )
    lreq = np.random.default_rng(1117)
    light_sched = [
        int(x)
        for x in lreq.integers(
            LIGHT_TARGET // 2, LIGHT_TARGET, size=max(LIGHT, 1)
        )
    ]

    tracer = Tracer(name="fleet", size=1 << 18)

    def hub_pass():
        """Single-node plane at the fleet's TOTAL load: one FanoutHub,
        every session on it — the equal-load comparator the >=2.5x
        aggregate gate divides by."""
        sinks = [Sink(keep=sid < KEEP_N) for sid in range(TOTAL)]

        async def run():
            bus = ev.EventBus()
            bus.set_loop(asyncio.get_running_loop())
            hub = FanoutHub(bus, tracer=tracer)
            for sid in range(TOTAL):
                qs, q = queries[shape_of[sid]]
                hub.attach(sinks[sid], qs, q, sid)
            t0 = _time.monotonic()
            for _h, e in flat:
                bus.publish(e)
                await asyncio.sleep(0)
            deadline = asyncio.get_running_loop().time() + 600
            while sum(s.count for s in sinks) < total_expected:
                if asyncio.get_running_loop().time() > deadline:
                    raise RuntimeError(
                        "hub delivery stalled: "
                        f"{sum(s.count for s in sinks)}"
                        f"/{total_expected}"
                    )
                await asyncio.sleep(0.005)
            wall = _time.monotonic() - t0
            stats = hub.queue_stats()
            enc = hub.encodes
            await hub.close()
            return wall, stats, enc

        wall, stats, enc = asyncio.run(run())
        return sinks, wall, stats, enc

    def fleet_pass(kill=False, light=False):
        """N replicas behind the router over the same stream; with
        ``kill`` one replica dies mid-storm (failover must be
        lossless), with ``light`` routed light sessions ride along on
        worker threads (tokens honored, shared cross-replica cache)."""
        record = kill
        sinks = [
            Sink(keep=sid < KEEP_N, record=record)
            for sid in range(TOTAL)
        ]
        out = {}

        async def run():
            source = StreamSource(results_fn=results_fn)
            planes = None
            if light:
                shared_cache = VerifiedHeaderCache(
                    light_chain, tracer=tracer
                )
                planes = [
                    LightServingPlane(
                        [
                            Client(
                                light_chain, light_trust,
                                light_provider,
                            )
                            for _ in range(2)
                        ],
                        max_sessions=LIGHT + 64,
                        max_inflight=LIGHT_WORKERS,
                        cache=shared_cache,
                        tracer=tracer,
                    )
                    for _ in range(REPLICAS)
                ]
            replicas = [
                FollowerNode(
                    f"bench-r{i}",
                    source,
                    light_plane=planes[i] if planes else None,
                    poll_s=0.02,
                    tracer=tracer,
                )
                for i in range(REPLICAS)
            ]
            router = SessionRouter(
                replicas,
                store_source=source,
                max_sessions=TOTAL + 64,
                # the bench feeds heights as fast as delivery allows —
                # transient lag is the workload, not a stall; lag
                # shedding is exercised by tests/chaos, not here
                max_lag_heights=HEIGHTS + 64,
                lag_poll_s=0.05,
                token_wait_s=10.0,
                resume_replay_max=max(64, HEIGHTS),
                tracer=tracer,
            )
            for r in replicas:
                await r.start()
            await router.start()
            sessions = []
            for sid in range(TOTAL):
                qs, q = queries[shape_of[sid]]
                sessions.append(
                    await router.subscribe(
                        sinks[sid], qs, q, sub_id=sid
                    )
                )
            victim = replicas[0]
            victim_sids = (
                [
                    sid
                    for sid, sess in enumerate(sessions)
                    if router._sessions.get(sess) is victim
                ]
                if kill
                else []
            )
            light_futs = []
            ex = None
            light_lat = []
            llock = threading.Lock()
            if light:
                import concurrent.futures as _cf

                loop = asyncio.get_running_loop()
                ex = _cf.ThreadPoolExecutor(LIGHT_WORKERS)

                def light_one(i):
                    # deterministic stagger spreads the light storm
                    # across the ingest window
                    _time.sleep((i % 100) * 0.003)
                    lt0 = _time.monotonic()
                    token = router.issue_token()
                    lb = router.serve_light(light_sched[i], token)
                    dt = (_time.monotonic() - lt0) * 1e3
                    assert lb.height == light_sched[i]
                    assert (
                        lb.hash()
                        == light_provider.light_block(
                            light_sched[i]
                        ).hash()
                    )
                    with llock:
                        light_lat.append(dt)

                light_futs = [
                    loop.run_in_executor(ex, light_one, i)
                    for i in range(LIGHT)
                ]
            t0 = _time.monotonic()
            t_kill = None
            for h, blk in enumerate(blocks, 1):
                source.advance(blk)
                await asyncio.sleep(0)
                if kill and h == KILL_AT:
                    # the victim must actually be mid-stream: let it
                    # serve through this height, then kill it with
                    # more heights still coming
                    while victim.served_height() < h:
                        await asyncio.sleep(0.002)
                    t_kill = _time.monotonic()
                    await victim.kill()
            deadline = asyncio.get_running_loop().time() + 600
            while sum(s.count for s in sinks) < total_expected:
                if asyncio.get_running_loop().time() > deadline:
                    raise RuntimeError(
                        "fleet delivery stalled: "
                        f"{sum(s.count for s in sinks)}"
                        f"/{total_expected}; sheds="
                        f"{router.fleet_status()['sheds']}"
                    )
                await asyncio.sleep(0.005)
            wall = _time.monotonic() - t0
            if light_futs:
                await asyncio.gather(*light_futs)
                ex.shutdown(wait=True)
            out["wall"] = wall
            out["t_kill"] = t_kill
            out["victim_sids"] = victim_sids
            out["encodes"] = sum(
                r.fanout.encodes for r in replicas
            )
            out["status"] = router.fleet_status()
            out["light_lat"] = sorted(light_lat)
            await router.close()
            for r in replicas:
                await r.stop()

        asyncio.run(run())
        return sinks, out

    # --- throughput legs: hub vs fleet at equal TOTAL load ----------
    runs = {"hub": [], "fleet": []}
    hub_sheds = 0
    parity_checked = False
    for _ in range(REPEATS):
        h_sinks, h_wall, h_stats, h_enc = hub_pass()
        hub_sheds += h_stats["dropped"]
        f_sinks, f_out = fleet_pass()
        st = f_out["status"]
        assert (
            st["sheds"]["admit"] == 0
            and st["sheds"]["lag"] == 0
            and st["sheds"]["failover"] == 0
        ), f"fleet shed sessions in a clean pass: {st['sheds']}"
        if not parity_checked:
            keep = [
                sid
                for sid in range(min(KEEP_N, TOTAL))
                if per_shape_frames[shape_of[sid]]
            ]
            check_content(h_sinks, keep, "hub")
            check_content(f_sinks, keep, "fleet")
            parity_checked = True
        runs["hub"].append(
            {
                "wall_s": h_wall,
                "frames_per_s": total_expected / h_wall,
                "encodes": h_enc,
            }
        )
        runs["fleet"].append(
            {
                "wall_s": f_out["wall"],
                "frames_per_s": total_expected / f_out["wall"],
                "encodes": f_out["encodes"],
            }
        )
        del h_sinks, f_sinks
    assert hub_sheds == 0, (
        f"{hub_sheds} frames shed by the hub with instant-drain sinks"
    )
    med = {
        mode: {
            k: round(statistics.median(r[k] for r in rs), 3)
            for k in ("wall_s", "frames_per_s", "encodes")
        }
        for mode, rs in runs.items()
    }
    ratio = _ratio(
        med["fleet"]["frames_per_s"], med["hub"]["frames_per_s"]
    )
    assert ratio is not None and ratio >= 2.5, (
        f"fleet aggregate only {ratio}x the single-hub plane at "
        "equal load (gate: >=2.5x)"
    )

    # --- the storm pass: kill one replica mid-stream ----------------
    s_sinks, s_out = fleet_pass(kill=True, light=LIGHT > 0)
    st = s_out["status"]
    victim_sids = s_out["victim_sids"]
    t_kill = s_out["t_kill"]
    assert t_kill is not None and victim_sids, (
        "storm pass never killed a replica"
    )
    assert st["failovers"] >= 1, f"no failover recorded: {st}"
    assert st["sessions_resumed"] == len(victim_sids), (
        f"{st['sessions_resumed']}/{len(victim_sids)} stranded "
        "sessions resumed"
    )
    assert (
        st["sheds"]["admit"] == 0
        and st["sheds"]["lag"] == 0
        and st["sheds"]["failover"] == 0
    ), f"storm pass shed sessions: {st['sheds']}"
    # zero lost commits, store-verified: every session's delivered
    # height sequence equals the chain-derived expectation (order,
    # multiplicity, no gap at the kill/resume splice)
    lost = 0
    for sid in range(TOTAL):
        if s_sinks[sid].heights != exp_heights[shape_of[sid]]:
            lost += 1
    assert lost == 0, (
        f"{lost} sessions lost or reordered commits across the "
        "replica kill"
    )
    check_content(
        s_sinks,
        [
            sid
            for sid in range(min(KEEP_N, TOTAL))
            if per_shape_frames[shape_of[sid]]
        ],
        "storm",
    )
    # re-admit latency: kill -> first replayed frame, per stranded
    # session that still had frames coming
    readmit_ms = []
    for sid in victim_sids:
        if not per_shape_frames[shape_of[sid]]:
            continue
        post = [ts for ts in s_sinks[sid].stamps if ts > t_kill]
        if post:
            readmit_ms.append((post[0] - t_kill) * 1e3)
    readmit_ms.sort()

    def rpct(p):
        return round(
            readmit_ms[int(p * (len(readmit_ms) - 1))], 3
        )

    assert readmit_ms, "no stranded session saw a post-kill frame"
    # mirror of the fleet.failover p99 budget (span_budgets.toml)
    assert rpct(0.99) <= 20000.0, (
        f"re-admit p99 {rpct(0.99)}ms blew the 20s failover envelope"
    )
    light_lat = s_out["light_lat"]
    light_stats = None
    if LIGHT:
        assert len(light_lat) == LIGHT, (
            f"{len(light_lat)}/{LIGHT} routed light sessions served"
        )
        assert st["tokens_issued"] >= LIGHT
        light_stats = {
            "served": len(light_lat),
            "p50_ms": round(
                light_lat[int(0.50 * (len(light_lat) - 1))], 3
            ),
            "p99_ms": round(
                light_lat[int(0.99 * (len(light_lat) - 1))], 3
            ),
        }
    del s_sinks

    # --- span-budget gate (fleet.route + fleet.failover) ------------
    tsum = summarize({"fleet": tracer.snapshot()})
    verdicts = [
        v
        for v in evaluate_budgets(
            tsum, load_budgets(default_budget_file())
        )
        if v["span"] in ("fleet.route", "fleet.failover")
    ]
    budget_ok = all(v["ok"] for v in verdicts)
    assert budget_ok, f"fleet budget breached: {verdicts}"

    return {
        "rate": med["fleet"]["frames_per_s"],
        "replicas": REPLICAS,
        "sessions": TOTAL,
        "light_sessions": LIGHT,
        "heights": HEIGHTS,
        "expected_frames": total_expected,
        "repeats": REPEATS,
        "shapes": [qs for qs, _ in SHAPES],
        "hub": med["hub"],
        "fleet": med["fleet"],
        "aggregate_ratio": ratio,
        "encode_ratio": _ratio(
            med["hub"]["encodes"], med["fleet"]["encodes"]
        ),
        "storm": {
            "wall_s": round(s_out["wall"], 3),
            "frames_per_s": round(
                total_expected / s_out["wall"], 1
            ),
            "killed_sessions": len(victim_sids),
            "resumed": st["sessions_resumed"],
            "failovers": st["failovers"],
            "readmit_p50_ms": rpct(0.50),
            "readmit_p99_ms": rpct(0.99),
            "sheds": st["sheds"],
            "lost_commits": 0,
            "light": light_stats,
        },
        "budget": {"ok": budget_ok, "verdicts": verdicts},
        "note": (
            "hub = one FanoutHub carrying the fleet's whole session "
            "load (the single-node plane); fleet = routed sessions "
            "over replica-paced direct delivery. Equal seeded load, "
            "pass-interleaved medians; storm pass kills a replica "
            "mid-stream and every stranded session resumes "
            "elsewhere, gap-free against the store (heights + "
            "content) with routed light sessions riding along."
        ),
    }


def bench_scaling() -> dict:
    """Committee-scaling probe (docs/LINT.md "Complexity rules"): the
    runtime half of the static complexity pass. Drives the hot-path
    sites ASY117/118 flagged (and this tree fixed) — vote_add,
    commit_assembly, gossip_pick, fanout_publish — at committee sizes
    {4, 16, 64, 128} in-process, fits the log-log wall exponent per
    site, and gates each against tools/scaling_budgets.toml
    (fixed-site target: slope <= 1.2 at 4->128). Host-only and
    seconds-cheap; exponents (not absolute walls) so the gate
    survives box changes."""
    from cometbft_tpu.analysis import scaling

    budgets = scaling.load_exponent_budgets()
    results = scaling.run_probe(
        budgets=budgets,
        min_wall_s=float(os.environ.get("BENCH_SCALING_WALL_S", "0.02")),
        repeats=int(os.environ.get("BENCH_SCALING_REPEATS", "5")),
    )
    print(scaling.format_results(results))
    breaches = [r.site for r in results if not r.ok and not r.injected]
    return {
        "sizes": list(scaling.SIZES),
        "sites": {r.site: r.as_dict() for r in results},
        "exponents": {r.site: round(r.exponent, 3) for r in results},
        "breaches": breaches,
        "ok": not breaches,
        "note": (
            "log-log wall slope per flagged hot-path site; budget "
            "per tools/scaling_budgets.toml (default "
            f"{scaling.DEFAULT_EXPONENT_BUDGET}); a breach means a "
            "fixed super-linear site regressed"
        ),
    }


def bench_commit150(gen, parts) -> dict:
    import cometbft_tpu.types as T

    vs = gen.validator_set()
    meta = parts.block_store.load_block_meta(1)
    commit = parts.block_store.load_seen_commit(1)

    def once():
        T.verify_commit_light(gen.chain_id, vs, meta.block_id, 1, commit)

    tpu, _ = _timed_with_backend("tpu", once)
    cpu, _ = _timed_with_backend("cpu", once)
    cpu_par, _ = _timed_with_backend("cpu-parallel", once)
    auto, _ = _timed_with_backend("auto", once)
    return {
        "tpu_ms": _ms(tpu),
        "cpu_ms": _ms(cpu),
        "cpu_parallel_ms": _ms(cpu_par),
        "auto_ms": _ms(auto),
        "auto_path": _timed_with_backend.last_route,
        "vs_cpu": _ratio(cpu, auto),
    }


# --- 4. 10k-block blocksync replay -------------------------------------


def _verdict_parity() -> dict:
    """Bit-identical-verdicts check for the ablation: the SAME lane
    set (valid + forged + mutated lanes) through the serial cpu
    backend and the parallel plane at several chunk sizes — verdict
    lists must match element-for-element, with failures landing on
    the exact forged indices."""
    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.crypto.keys import Ed25519PrivKey
    from cometbft_tpu.crypto.parallel_verify import ParallelVerifyEngine

    rng = np.random.default_rng(23)
    privs = [Ed25519PrivKey.from_seed(rng.bytes(32)) for _ in range(16)]
    items = []
    for i in range(600):
        p = privs[i % len(privs)]
        m = bytes(rng.bytes(110))
        items.append((p.pub_key(), m, p.sign(m)))
    forged = [3, 171, 599]
    items[forged[0]] = (
        items[forged[0]][0], items[forged[0]][1], bytes(64),
    )
    items[forged[1]] = (
        items[forged[1]][0], b"mutated", items[forged[1]][2],
    )
    items[forged[2]] = (
        privs[0].pub_key(), items[forged[2]][1], items[forged[2]][2],
    )
    serial = crypto_batch.CpuBatchVerifier()
    for it in items:
        serial.add(*it)
    _, want = serial.verify()
    chunk_targets_ms = (0.5, 4.0, 50.0)
    for tgt in chunk_targets_ms:
        eng = ParallelVerifyEngine(chunk_target_s=tgt / 1e3)
        got = eng.verify(items)
        eng.close()
        if got != want:
            return {"identical": False, "chunk_target_ms": tgt}
    failed_indices = [i for i, v in enumerate(want) if not v]
    return {
        "identical": True,
        "lanes": len(items),
        "forged_lanes_flagged": failed_indices == forged,
        "chunk_targets_ms": list(chunk_targets_ms),
    }


def bench_replay(gen, parts, n_blocks: int) -> dict:
    import asyncio

    from cometbft_tpu.blocksync import BlockSyncReactor
    from cometbft_tpu.config.config import test_config
    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.node.inprocess import build_node
    from cometbft_tpu.utils.chaingen import StorePeerClient

    n_sigs = (n_blocks - 1) * N_VALS  # tip block is left to consensus

    trace_on = os.environ.get("BENCH_TRACE") == "1"

    def replay(limit, window):
        cfg = test_config(".")
        cfg.base.db_backend = "memdb"
        fresh = build_node(gen, None, config=cfg)

        async def main():
            caught = asyncio.Event()
            reactor = BlockSyncReactor(
                fresh.state,
                fresh.block_exec,
                fresh.block_store,
                on_caught_up=lambda st: caught.set(),
                verify_window=window,
            )
            # window spans land on the replay node's ring (--trace
            # embeds their summary in the checkpointed JSON)
            reactor.tracer = fresh.tracer
            reactor.pool.set_peer_range(
                "src", StorePeerClient(parts), 1, limit
            )
            await reactor.start()
            t0 = time.time()
            await asyncio.wait_for(caught.wait(), 3600)
            dt = time.time() - t0
            await reactor.stop()
            # blocksync applies up to limit-1 or limit-2: the tip
            # blocks need the NEXT height's LastCommit, and
            # is_caught_up (pool next-height >= maxPeer-1, reference
            # pool.go:227) can fire between window passes either side
            # of the final single-block pass
            assert fresh.block_store.height() >= limit - 2
            tsum = None
            if trace_on:
                from cometbft_tpu.trace import global_tracer, summarize

                tsum = summarize(
                    {
                        "replay": fresh.tracer.snapshot(),
                        "process": global_tracer().snapshot(),
                    }
                )
                global_tracer().clear()
            return dt, dict(reactor.pipeline_stats), tsum

        return asyncio.run(main())

    if not _DEVICE_OK:
        # HOST-ONLY mode (device wedged): the full-corpus replay on
        # the production host pipeline is still the round's most
        # load-bearing number — capture it rather than dropping the
        # config (VERDICT r4 weak #2). The ablation the host plane
        # demands (docs/PERF.md): the SAME windowed pipeline under
        # cpu-parallel (production) vs serial cpu, both full-length.
        # The old window=2 per-block sequential baseline is implied by
        # the serial leg — window coalescing is host-cost-neutral
        # (169.5 s vs 170.0 s, r5 measurement), so serial windowed ≈
        # per-block sequential; BENCH_SEQ_FULL=1 still measures it
        # explicitly when the budget allows.
        from cometbft_tpu.crypto.parallel_verify import engine

        crypto_batch.set_default_backend("cpu-parallel")
        replay(min(129, n_blocks), 128)  # warm stores/caches
        par_dt, pipe_stats, tsum = replay(n_blocks, 128)
        crypto_batch.set_default_backend("cpu")
        ser_dt, _, _ = replay(n_blocks, 128)
        seq = {}
        if os.environ.get("BENCH_SEQ_FULL", "0") == "1":
            seq_dt = replay(n_blocks, 2)[0]
            seq = {
                "sequential_wall_s": round(seq_dt, 2),
                "sequential_note": (
                    "full-length window=2 per-block serial verify"
                ),
            }
        # production host default stays the parallel plane
        crypto_batch.set_default_backend("cpu-parallel")
        return {
            "blocks": n_blocks,
            "validators": N_VALS,
            "mode": "host-only",
            "backend": "cpu-parallel",
            "wall_s": round(par_dt, 2),
            "blocks_per_s": round(n_blocks / par_dt, 1),
            "sigs_per_s": round(n_sigs / par_dt, 1),
            "serial_cpu_wall_s": round(ser_dt, 2),
            "serial_cpu_blocks_per_s": round(n_blocks / ser_dt, 1),
            "parallel_vs_serial": round(ser_dt / par_dt, 2),
            "verdict_parity": _verdict_parity(),
            "cores": os.cpu_count(),
            "verify_plane": engine().stats(),
            "pipeline": pipe_stats,
            "note": (
                "serial baseline = the same windowed pipeline on the "
                "serial cpu backend (window coalescing is host-cost-"
                "neutral, PERF.md r5, so this also stands in for the "
                "per-block sequential baseline)"
            ),
            **({"trace_summary": tsum,
    "budget_verdicts": _budget_verdicts(tsum),
    "quorum_latency": _quorum_summary(tsum)} if tsum else {}),
            **seq,
        }

    # TPU path: full corpus, wide windows (128 blocks x 150 sigs per
    # dispatch). Warm the window-shape compile OUTSIDE the timed run —
    # steady-state replay throughput is the metric, and the CPU
    # baseline pays no compile either. The warm-up is a REAL 129-block
    # replay: the timed path verifies light (stops at >2/3 power, ~101
    # of 150 sigs), so only an identical replay is guaranteed to hit
    # the same _pad_n lane bucket as the timed windows.
    crypto_batch.set_default_backend("tpu")
    replay(min(129, n_blocks), 128)
    tpu_dt, pipe_stats, tsum = replay(n_blocks, 128)
    # CPU baseline: sequential verify on a 300-block slice, extrapolated
    crypto_batch.set_default_backend("cpu")
    cpu_slice = min(300, n_blocks)
    cpu_dt = replay(cpu_slice, 128)[0] * (n_blocks / cpu_slice)
    crypto_batch.set_default_backend("tpu")
    return {
        "blocks": n_blocks,
        "validators": N_VALS,
        "wall_s": round(tpu_dt, 2),
        "blocks_per_s": round(n_blocks / tpu_dt, 1),
        "sigs_per_s": round(n_sigs / tpu_dt, 1),
        "cpu_wall_s_extrap": round(cpu_dt, 2),
        "vs_cpu": round(cpu_dt / tpu_dt, 2),
        # pipelined-dispatch observability: reused ~= windows proves
        # the lookahead overlap genuinely engaged during the run
        "pipeline": pipe_stats,
        **({"trace_summary": tsum,
    "budget_verdicts": _budget_verdicts(tsum),
    "quorum_latency": _quorum_summary(tsum)} if tsum else {}),
    }


# --- 5. light bisection over 50k heights -------------------------------


def bench_bisect(gen, privs) -> dict:
    import cometbft_tpu.types as T
    from cometbft_tpu.light.client import Client, TrustOptions
    from cometbft_tpu.light.provider import Provider
    from cometbft_tpu.light.types import LightBlock

    TARGET = 50_000
    # Validator-set ROTATION across epochs: with a static valset a
    # 50k-height skip is one trusting verify (no bisection at all), so
    # the epoch windows slide over a larger key pool — skips spanning
    # >1 epoch lack the 1/3 trust overlap and force real 9/16
    # bisection (reference verifySkipping, light/client.go:29).
    EPOCH = 2_500
    SHIFT = 60  # keys rotated per epoch: 1-epoch overlap 90/150 (>1/3)
    from cometbft_tpu.crypto.keys import Ed25519PrivKey

    rng = np.random.default_rng(99)
    n_epochs = TARGET // EPOCH + 2
    # linear pool (NO wraparound): windows 2+ epochs apart overlap
    # <=30/150 (<1/3 trust), so long skips genuinely fail and bisect
    extra = [
        Ed25519PrivKey.from_seed(rng.bytes(32))
        for _ in range(n_epochs * SHIFT + N_VALS - len(privs))
    ]
    pool = list(privs) + extra
    # anchor the synthetic chain's clock so the TARGET header is ~2min
    # in the past — the verifier rejects headers from the future
    # (light/verifier.py clock-drift check)
    t0_ns = time.time_ns() - (TARGET + 120) * 1_000_000_000
    chain_id = gen.chain_id

    _vs_cache = {}

    def vals_at(height: int):
        import cometbft_tpu.types as T

        epoch = height // EPOCH
        if epoch not in _vs_cache:
            start = epoch * SHIFT
            window = pool[start : start + N_VALS]
            vs = T.ValidatorSet(
                [T.Validator(p.pub_key(), 10) for p in window]
            )
            _vs_cache[epoch] = vs
        return _vs_cache[epoch]

    priv_by_addr = {p.pub_key().address(): p for p in pool}

    class SyntheticProvider(Provider):
        """Mints a valid signed header at any height on demand (the
        reference's light bench shape, light/client_benchmark_test.go:
        bisection never checks hash-chaining between hops, only commit
        + valset relationships)."""

        chain_id = gen.chain_id
        fetched = 0

        def light_block(self, height: int) -> LightBlock:
            type(self).fetched += 1
            vs_h = vals_at(height)
            h = T.Header(
                chain_id=chain_id,
                height=height,
                time_ns=t0_ns + height * 1_000_000_000,
                validators_hash=vs_h.hash(),
                next_validators_hash=vals_at(height + 1).hash(),
            )
            bid = T.BlockID(h.hash(), T.PartSetHeader(1, h.hash()))
            sigs = []
            for i, val in enumerate(vs_h.validators):
                v = T.Vote(
                    type_=T.PRECOMMIT,
                    height=height,
                    round=0,
                    block_id=bid,
                    timestamp_ns=h.time_ns,
                    validator_address=val.address,
                    validator_index=i,
                )
                sig = priv_by_addr[val.address].sign(
                    v.sign_bytes(chain_id)
                )
                sigs.append(
                    T.CommitSig(
                        block_id_flag=T.BLOCK_ID_FLAG_COMMIT,
                        validator_address=val.address,
                        timestamp_ns=h.time_ns,
                        signature=sig,
                    )
                )
            commit = T.Commit(
                height=height, round=0, block_id=bid, signatures=sigs
            )
            return LightBlock(h, commit, vs_h)

    def once():
        provider = SyntheticProvider()
        root = provider.light_block(1)
        client = Client(
            chain_id,
            TrustOptions(
                period_ns=10 * 365 * 86400 * 10**9,
                height=1,
                hash=root.hash(),
            ),
            provider,
        )
        client.verify_light_block_at_height(TARGET)
        return client.hops

    tpu_dt, hops = _timed_with_backend("tpu", once, repeats=2)
    cpu_dt, cpu_hops = _timed_with_backend("cpu", once, repeats=2)
    auto_dt, _ = _timed_with_backend("auto", once, repeats=2)
    if hops is None:
        hops = cpu_hops
    return {
        "target_height": TARGET,
        "hops": hops,
        "tpu_s": None if tpu_dt is None else round(tpu_dt, 2),
        "cpu_s": round(cpu_dt, 2),
        "auto_s": None if auto_dt is None else round(auto_dt, 2),
        "auto_path": _timed_with_backend.last_route,
        "vs_cpu": _ratio(cpu_dt, auto_dt),
    }


# --- 6. overlapped dispatch (production pipelining claim) --------------


def bench_pipeline() -> dict:
    """Substantiates docs/PERF.md's "a production node pipelines
    batches": K verify windows dispatched back-to-back (XLA async
    dispatch, ops/ed25519.verify_batch_async) vs the same K resolved
    one at a time. The delta is the amortized per-dispatch link
    latency — the dominant cost of every small config on this link."""
    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.crypto.keys import Ed25519PrivKey
    from cometbft_tpu.ops import ed25519 as ed

    K = 8
    WINDOW = 2048  # ~13 blocks x 150 sigs, a realistic replay window
    rng = np.random.default_rng(17)
    windows = []
    keys = [Ed25519PrivKey.from_seed(rng.bytes(32)) for _ in range(64)]
    for _ in range(K):
        items = []
        for i in range(WINDOW):
            p = keys[i % len(keys)]
            m = bytes(rng.bytes(64))
            items.append((m, p.pub_key().key_bytes, p.sign(m)))
        windows.append(items)

    # warm the compile for this shape
    ed.verify_batch(windows[0])

    def sequential():
        for w in windows:
            out = ed.verify_batch(w)
            assert out.all()

    def pipelined():
        handles = [ed.verify_batch_async(w) for w in windows]
        for h in handles:
            assert h.result().all()

    best_seq = best_pipe = None
    for _ in range(3):
        t0 = time.time()
        sequential()
        dt = time.time() - t0
        best_seq = dt if best_seq is None else min(best_seq, dt)
        t0 = time.time()
        pipelined()
        dt = time.time() - t0
        best_pipe = dt if best_pipe is None else min(best_pipe, dt)

    return {
        "windows": K,
        "lanes_per_window": WINDOW,
        "sequential_ms": round(best_seq * 1e3, 2),
        "pipelined_ms": round(best_pipe * 1e3, 2),
        "overlap_speedup": round(best_seq / best_pipe, 2),
        "pipelined_rate": round(K * WINDOW / best_pipe, 1),
    }


# --- 7. mixed-curve split ----------------------------------------------


def bench_mixed() -> dict:
    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.crypto.keys import Ed25519PrivKey, Secp256k1PrivKey

    rng = np.random.default_rng(13)
    items = []
    for i in range(128):
        m = bytes(rng.bytes(120))
        if i % 2 == 0:
            p = Ed25519PrivKey.from_seed(rng.bytes(32))
        else:
            p = Secp256k1PrivKey.generate()
        items.append((p.pub_key(), m, p.sign(m)))

    def once():
        v = crypto_batch.create_batch_verifier()
        for pk, m, s in items:
            v.add(pk, m, s)
        ok, verdicts = v.verify()
        assert ok and all(verdicts)

    # ed25519 half on device, secp on host (device legs None when the
    # platform is down — the host leg still records)
    tpu, _ = _timed_with_backend("tpu", once, repeats=3)
    cpu, _ = _timed_with_backend("cpu", once, repeats=3)
    auto, _ = _timed_with_backend("auto", once, repeats=3)
    return {
        "n": 128,
        "split": "64 ed25519 (device) + 64 secp256k1 (host)",
        "tpu_ms": _ms(tpu),
        "cpu_ms": _ms(cpu),
        "auto_ms": _ms(auto),
        "vs_cpu": _ratio(cpu, auto),
        "note": "reference abandons batching on mixed sets",
    }


# the leg's live-class gate: the chunk-preemption bound (~workers x
# chunk-wall, single-digit ms) with generous box-noise headroom. The
# chaos/span-budget envelope (tools/span_budgets.toml
# crypto.sched.dispatch, 2500ms) covers fault schedules; this leg runs
# fault-free, so a live p95 past 250ms means priorities are not
# holding, not that the box is slow.
_VERIFY_SCHED_LIVE_P95_MS = 250.0


def bench_verify_sched() -> dict:
    """Unified verify scheduler leg (docs/PERF.md "Unified verify
    scheduler"): live-round verify p95 while a sustained catch-up
    storm shares the engine. Two scenarios over the identical
    workload, host plane both (queueing policy is the measurement,
    not the backend):

    - ``priority``: live waves submitted PRIORITY_LIVE — chunk
      preemption must bound their wall to ~workers x chunk-wall;
    - ``fifo`` baseline: the same live waves submitted in the storm's
      own class (no priority) — each wave queues behind the storm
      tickets ahead of it, the contention the classes exist to bound.

    Gates: priority live p95 <= the leg budget AND the FIFO baseline
    VISIBLY worse (breaches the same budget or >= 3x the priority
    p95); verdicts parity-asserted on every wave and storm ticket."""
    import statistics

    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.crypto import scheduler as sched_mod
    from cometbft_tpu.crypto.keys import Ed25519PrivKey

    rng = np.random.default_rng(23)
    keys = [Ed25519PrivKey.from_seed(rng.bytes(32)) for _ in range(8)]

    def mk(n, bad=()):
        items, want = [], []
        for i in range(n):
            sk = keys[i % len(keys)]
            m = bytes(rng.bytes(96))
            s = sk.sign(m) if i not in bad else b"\x00" * 64
            items.append((sk.pub_key(), m, s))
            want.append(i not in bad)
        return items, want

    live_items, live_want = mk(8)
    storm_items, storm_want = mk(8192, bad={17, 4001})
    storm_s = float(os.environ.get("BENCH_VERIFY_SCHED_STORM_S", "8"))

    def scenario(live_priority: int) -> dict:
        s = sched_mod.VerifyScheduler()
        deadline = time.perf_counter() + storm_s
        parity = {"ok": True}
        catchup_done = [0]

        def storm():
            while time.perf_counter() < deadline:
                t = s.submit(
                    storm_items,
                    priority=sched_mod.PRIORITY_CATCHUP,
                    label="bench-storm",
                )
                _, oks = t.result(timeout=120)
                if oks != storm_want:
                    parity["ok"] = False
                catchup_done[0] += 1

        feeders = [
            threading.Thread(target=storm, daemon=True)
            for _ in range(3)
        ]
        for f in feeders:
            f.start()
        time.sleep(0.2)  # storm established before the first wave
        walls = []
        while time.perf_counter() < deadline:
            t = s.submit(
                live_items, priority=live_priority, label="bench-live"
            )
            _, oks = t.result(timeout=120)
            if oks != live_want:
                parity["ok"] = False
            walls.append(t.wall() or 0.0)
            time.sleep(0.015)
        for f in feeders:
            f.join(timeout=180)
        s.drain(timeout=180)
        s.close()
        walls.sort()
        return {
            "live_waves": len(walls),
            "live_p50_ms": _ms(statistics.median(walls)) if walls else None,
            "live_p95_ms": _ms(
                walls[min(len(walls) - 1, int(0.95 * len(walls)))]
            ) if walls else None,
            "catchup_tickets": catchup_done[0],
            "catchup_lanes_per_s": round(
                catchup_done[0] * len(storm_items) / storm_s, 1
            ),
            "parity_ok": parity["ok"],
        }

    old_backend = crypto_batch.default_backend()
    crypto_batch.set_default_backend("cpu-parallel")
    try:
        pri = scenario(sched_mod.PRIORITY_LIVE)
        fifo = scenario(sched_mod.PRIORITY_CATCHUP)
    finally:
        crypto_batch.set_default_backend(old_backend)
    budget = _VERIFY_SCHED_LIVE_P95_MS
    p95_pri = pri["live_p95_ms"]
    p95_fifo = fifo["live_p95_ms"]
    priority_holds = p95_pri is not None and p95_pri <= budget
    baseline_visibly_worse = (
        p95_pri is not None
        and p95_fifo is not None
        and (p95_fifo > budget or p95_fifo >= 3.0 * p95_pri)
    )
    return {
        "priority": pri,
        "fifo_baseline": fifo,
        "live_p95_budget_ms": budget,
        "priority_holds_budget": priority_holds,
        "baseline_visibly_worse": baseline_visibly_worse,
        "parity_ok": pri["parity_ok"] and fifo["parity_ok"],
        "gate_ok": (
            priority_holds
            and baseline_visibly_worse
            and pri["parity_ok"]
            and fifo["parity_ok"]
        ),
        "note": "live 8-lane waves vs 3x8192-lane catch-up storm "
        "through ONE scheduler, host plane; fifo = same waves "
        "submitted classless (the pre-scheduler contention)",
    }


def bench_mesh_dryrun() -> dict:
    """Mesh-vs-host verify throughput on the multi-device path
    (docs/PERF.md "Unified verify scheduler", mesh backend). With >1
    device (real mesh, or the 8-virtual-device dryrun the parent
    spawns this config under) the ``mesh`` backend shards the batch
    across devices; verdict parity against the host plane is the
    in-bench gate. On a single-device box the DEGRADE is the
    measurement: the structured verdict records that the batch fell
    through to the host plane without wedging — the degradable
    contract selecting "mesh" promises."""
    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.crypto.keys import Ed25519PrivKey
    from cometbft_tpu.crypto.mesh_backend import (
        LAST_MESH,
        MeshBatchVerifier,
        mesh_devices,
    )
    from cometbft_tpu.crypto.parallel_verify import engine

    devices = mesh_devices(refresh=True)
    rng = np.random.default_rng(31)
    n = int(os.environ.get("BENCH_MESH_BATCH", "1024"))
    keys = [Ed25519PrivKey.from_seed(rng.bytes(32)) for _ in range(8)]
    items, want = [], []
    bad = {7, n - 3}
    for i in range(n):
        sk = keys[i % len(keys)]
        m = bytes(rng.bytes(96))
        s = sk.sign(m) if i not in bad else b"\x00" * 64
        items.append((sk.pub_key(), m, s))
        want.append(i not in bad)

    def host_once():
        return engine().verify(items)

    t0 = time.perf_counter()
    host_oks = host_once()
    host_dt = time.perf_counter() - t0
    host_ok = list(host_oks) == want

    def mesh_once():
        v = MeshBatchVerifier()
        for pk, m, s in items:
            v.add(pk, m, s)
        return v.verify()

    if devices <= 1:
        # the common path on this box: no mesh materializes — the
        # batch must still verify (host degrade), and the verdict is
        # STRUCTURED so the JSON reader sees a degraded mesh, not a
        # missing leg
        _, oks = mesh_once()
        return {
            "degraded": True,
            "devices": devices,
            "mesh_path": LAST_MESH["path"],
            "parity_ok": oks == want and host_ok,
            "host_rate": round(n / host_dt, 1),
            "note": "single device: mesh backend degraded to the "
            "host plane (bit-identical verdicts, no wedge) — the "
            "multi-device number runs under the 8-virtual-device "
            "dryrun child",
        }

    mesh_once()  # warmup: sharded-program compile paid outside timing
    t0 = time.perf_counter()
    _, mesh_oks = mesh_once()
    mesh_dt = time.perf_counter() - t0
    return {
        "degraded": False,
        "devices": devices,
        "mesh_path": LAST_MESH["path"],
        "batch": n,
        "mesh_rate": round(n / mesh_dt, 1),
        "host_rate": round(n / host_dt, 1),
        "mesh_vs_host": _ratio(host_dt, mesh_dt),
        "parity_ok": list(mesh_oks) == want and host_ok,
        "note": f"{n} sigs sharded over {devices} devices "
        "(shard_map data axis) vs the cpu-parallel host plane; "
        "parity gated on planted-bad-signature verdicts",
    }


def main() -> None:
    global _PROFILER
    t_start = time.time()
    _CKPT["t_start"] = t_start
    if "--trace" in sys.argv:
        # bench.py --trace: node tracers stay attached (they always
        # are) and the per-config span summary is embedded in the
        # checkpointed JSON (docs/TRACE.md)
        os.environ["BENCH_TRACE"] = "1"
    if os.environ.get("BENCH_PROFILE", "1") != "0":
        from cometbft_tpu.obs import SamplingProfiler

        _PROFILER = SamplingProfiler(
            hz=float(os.environ.get("BENCH_PROFILE_HZ", "29"))
        ).start()
    _install_signal_handlers()
    _setup_jax()

    which = os.environ.get("BENCH_CONFIGS", "all")
    todo = (
        {
            "kernel",
            "batch64",
            "commit150",
            "replay",
            "bisect",
            "mixed",
            "pipeline",
            "ingest",
            "live",
            "finalize",
            "lifecycle",
            "serve",
            "rpcfanout",
            "fleet",
            "scaling",
            "verifysched",
            "meshdryrun",
        }
        if which == "all"
        else set(which.split(","))
    )
    configs = _CKPT["configs"]

    def run_config(name: str, fn) -> None:
        """One budgeted, checkpointed config (see _run_budgeted)."""
        if _WEDGED:
            _record(
                name,
                {
                    "rate": None,
                    "note": "skipped: earlier wedged leg(s) "
                    f"{_WEDGED} still hold the process",
                },
            )
            return
        _record(name, _run_budgeted(name, fn))

    global _DEVICE_OK
    probe = _probe_device()
    _DEVICE_OK = probe["ok"]
    if not _DEVICE_OK:
        # run EVERYTHING that has a host path (through the same
        # production dispatch seam) and say so — better an honest
        # degraded line than a driver-timeout blank. Only the kernel
        # configs are device-only (VERDICT r4 weak #2: the host replay
        # and pipeline numbers must be driver-captured even when the
        # platform is down). The host default is the PARALLEL plane —
        # the production policy this round (docs/PERF.md host plane);
        # the serial cpu backend stays the ablation baseline.
        _record(
            "device",
            {
                "available": False,
                "degraded": True,
                "probe": probe,
                "note": "device probe not ok "
                f"({probe['reason']}); device configs skipped, "
                "host path (cpu-parallel plane) carries the round",
            },
        )
        from cometbft_tpu.crypto import batch as crypto_batch

        crypto_batch.set_default_backend("cpu-parallel")
        todo -= {"kernel"}

    # soft budget for the OPTIONAL host configs in degraded mode: the
    # load-bearing ones (replay, commit150, batch64, bisect) always
    # run; pipeline/mixed are skipped with an honest note if the run
    # is already long (a driver-timeout blank records nothing at all)
    host_budget_s = float(os.environ.get("BENCH_HOST_BUDGET_S", "1500"))

    def budget_left() -> bool:
        return _DEVICE_OK or (time.time() - t_start) < host_budget_s

    ambient_child = os.environ.get("BENCH_CHILD") == "1"
    if "kernel" in todo:
        if ambient_child:
            configs["kernel"] = bench_kernel()
        else:
            # the in-process leg stays on the XLA ladder: a cold
            # Mosaic compile (~7-9 min, uncacheable — docs/PERF.md)
            # belongs in a budgeted subprocess AFTER the proven
            # configs are recorded, never in the main process where a
            # hang would wedge the whole bench (the production pallas
            # default is measured by the kernel_pallas_default leg)
            prev = os.environ.get("GRAFT_PALLAS")
            os.environ["GRAFT_PALLAS"] = "0"
            try:
                run_config("kernel", bench_kernel)
            finally:
                if prev is None:
                    os.environ.pop("GRAFT_PALLAS", None)
                else:
                    os.environ["GRAFT_PALLAS"] = prev
    need_corpus = todo & {"commit150", "replay", "bisect"}
    corpus_parts = None
    if need_corpus and _WEDGED:
        # same skip policy run_config applies: a corpus build would
        # contend with the zombie leg for up to an hour and its
        # consumers below would be skipped anyway
        for name in sorted(need_corpus):
            _record(
                name,
                {
                    "rate": None,
                    "note": "skipped: earlier wedged leg(s) "
                    f"{_WEDGED} still hold the process",
                },
            )
        need_corpus = set()
    if need_corpus:
        n_blocks = int(os.environ.get("BENCH_REPLAY_BLOCKS", "10000"))
        corpus_box = _run_budgeted(
            "corpus", lambda: _corpus(n_blocks)
        )
        if not isinstance(corpus_box, tuple):
            # budget overrun / failure: the corpus configs cannot run
            for name in sorted(need_corpus):
                _record(name, dict(corpus_box))
        else:
            gen, privs, corpus_parts = corpus_box
            if "commit150" in todo:
                run_config(
                    "commit150",
                    lambda: bench_commit150(gen, corpus_parts),
                )
            if "replay" in todo:
                run_config(
                    "replay",
                    lambda: bench_replay(
                        gen, corpus_parts, n_blocks
                    ),
                )
            if "bisect" in todo:
                run_config("bisect", lambda: bench_bisect(gen, privs))
            if not _WEDGED:
                corpus_parts.close_stores()
    if "batch64" in todo:
        run_config("batch64", bench_batch64)
    if "ingest" in todo:
        # host-only mempool ingest ablation: cheap enough to always
        # run (no corpus, no device, ~a minute on this box)
        run_config("ingest", bench_ingest)
    if "live" in todo:
        # host-only live-consensus fast-path ablation (ISSUE 11):
        # 4-node LocalNet blocks/s + p95 quorum latency, serial vs
        # batched — the first optimization leg behind the PR 7 quorum
        # waterfall
        run_config("live", bench_live)
    if "finalize" in todo:
        # host-only native finalize lane ablation (ISSUE 20): one
        # GIL-releasing hash/encode pass per block vs the portable
        # twin on a 4-node LocalNet (consensus.finalize p95 target),
        # vecbank vectorized-vs-scalar apply >=1.5x gate, byte-parity
        # asserted in-bench incl. the env-gated degraded path
        run_config("finalize", bench_finalize)
    if "lifecycle" in todo:
        # host-only storage lifecycle ablation (ISSUE 17): 4-node
        # LocalNet, retention plane OFF vs ON — <5% overhead gate +
        # proof every prune/snapshot span ran off the consensus loop
        run_config("lifecycle", bench_lifecycle)
    if "serve" in todo:
        # host-only light-client serving storm (ISSUE 13): 1k-session
        # baseline vs shared-cache vs coalesced ablation + a live
        # LocalNet sub-leg, p99 budget-gated
        run_config("serve", bench_serve)
    if "rpcfanout" in todo:
        # host-only outbound fan-out storm (ISSUE 15): 10k websocket
        # subscribers, one-encode-per-group vs per-subscriber
        # serialization, >=5x gate + delivery p99 budget-gated
        run_config("rpcfanout", bench_rpcfanout)
    if "fleet" in todo:
        # host-only serving-fleet storm (ISSUE 19): follower replicas
        # behind the SessionRouter vs one FanoutHub at equal total
        # load, mid-storm replica kill with lossless resume, routed
        # light sessions — >=2.5x aggregate gate, budget-gated
        run_config("fleet", bench_fleet)
    if "scaling" in todo:
        # host-only committee-scaling exponent gate (complexity
        # plane): seconds-cheap, always runs — a fixed super-linear
        # hot path regressing must not hide behind a budget skip
        run_config("scaling", bench_scaling)
    if "verifysched" in todo:
        # unified verify scheduler (this round's tentpole): live p95
        # under a catch-up storm, priority classes vs the classless
        # FIFO baseline — host plane, runs regardless of the device
        run_config("verifysched", bench_verify_sched)
    if "meshdryrun" in todo:
        if ambient_child:
            run_config("meshdryrun", bench_mesh_dryrun)
        else:
            n_dev = 1
            if _DEVICE_OK:
                try:
                    import jax

                    n_dev = len(jax.devices())
                except Exception:
                    n_dev = 1
            if n_dev > 1:
                # a real mesh is attached: measure it in-process
                run_config("meshdryrun", bench_mesh_dryrun)
            else:
                # the 8-virtual-device dryrun contract: a cpu-pinned
                # child (a wedged axon platform can't hang it) with
                # the forced host device count — same flags the test
                # conftest validates shardings under
                flags = os.environ.get("XLA_FLAGS", "")
                if "xla_force_host_platform_device_count" not in flags:
                    flags = (
                        flags
                        + " --xla_force_host_platform_device_count=8"
                    ).strip()
                entry = _subprocess_config(
                    "meshdryrun",
                    {"BENCH_FORCE_CPU": "1", "XLA_FLAGS": flags},
                    int(
                        os.environ.get(
                            "BENCH_MESHDRYRUN_BUDGET_S", "900"
                        )
                    ),
                    "mesh-vs-host verify on the 8-device virtual "
                    "dryrun",
                )
                _record("meshdryrun", entry)
    budget_skip = {
        "skipped": f"host budget ({host_budget_s:.0f}s) "
        "exhausted before this config"
    }
    if "pipeline" in todo:
        if not budget_left():
            _record("pipeline", dict(budget_skip))
        elif _DEVICE_OK:
            run_config("pipeline", bench_pipeline)
        else:
            # the in-process jax platform is the WEDGED axon backend;
            # the XLA-CPU kernel leg must run in a cpu-pinned child
            entry = _subprocess_config(
                "pipeline",
                {"BENCH_FORCE_CPU": "1"},
                int(os.environ.get("BENCH_PIPELINE_BUDGET_S", "900")),
                "host pipeline leg (XLA-CPU compact kernel)",
            )
            entry.setdefault(
                "note",
                "XLA-CPU compact-kernel leg (device down): overlap "
                "measures async-dispatch amortization on host, not "
                "the device link",
            )
            _record("pipeline", entry)
    if "mixed" in todo:
        if budget_left():
            run_config("mixed", bench_mixed)
        else:
            _record("mixed", dict(budget_skip))
    # the experimental kernel legs run LAST: each budgeted subprocess
    # may burn many minutes on a cold Mosaic compile, and the proven
    # configs above must be recorded before that risk is taken. The
    # in-process kernel leg above is pinned to the XLA ladder for
    # exactly that reason; the production default (pallas s8 at bulk
    # widths — the r5 silicon A/B measured 801k vs 320k verifies/s
    # @131072) is measured by kernel_pallas_default here, and the
    # tuple-form precomp A input (lever #6) rides the same default.
    # Best rate wins the headline.
    if "kernel" in todo and _DEVICE_OK and not ambient_child:
        leg_budget = int(
            os.environ.get("BENCH_PALLAS_BUDGET_S", "1200")
        )
        extra_wall = float(
            os.environ.get("BENCH_EXTRA_LEGS_BUDGET_S", "2700")
        )
        t_extra = time.time()
        # per-leg gates record WHY a leg was skipped — the ablation
        # table must never read as if a suppressed leg was unplanned
        skip_pallas = os.environ.get("BENCH_SKIP_PALLAS") == "1"
        legs = [
            (
                "kernel_pallas_default",
                {"GRAFT_PALLAS": ""},
                "production-default ladder (pallas s8 at bulk "
                "widths); Mosaic compile risk budgeted here",
                skip_pallas,
            ),
            (
                "kernel_precomp_tuple",
                {
                    "GRAFT_PRECOMP_TUPLE": "1",
                    "GRAFT_PRECOMP_MAX_LANES": "1000000000",
                },
                "tuple-form precomp A at bulk width (lever #6, "
                "rides the default pallas ladder)",
                os.environ.get("BENCH_SKIP_PRECOMP_TUPLE") == "1",
            ),
        ]
        for name, envx, what, gated_off in legs:
            if gated_off:
                _record(
                    name,
                    {
                        "rate": None,
                        "note": f"leg gated off by env: {what}",
                    },
                )
                continue
            if time.time() - t_extra > extra_wall:
                _record(
                    name,
                    {
                        "rate": None,
                        "note": f"extra-legs wall budget "
                        f"({extra_wall:.0f}s) exhausted before: "
                        f"{what}",
                    },
                )
                continue
            inner = _subprocess_config("kernel", envx, leg_budget, what)
            if inner.get("rate") is not None or "note" not in inner:
                inner["note"] = what
            _record(name, inner)

    # headline = the best of every measured kernel leg, falling back
    # to the host replay throughput in degraded mode (assembled by
    # _final_payload — the same function the checkpoint and the
    # signal handler use, so a killed run prints the identical line
    # shape with whatever landed)
    if _PROFILER is not None:
        _PROFILER.stop()
        out = os.environ.get("BENCH_PROFILE_OUT")
        if out:
            _PROFILER.write_folded(out)
    _emit_final()


if __name__ == "__main__":
    main()
