"""North-star benchmark: ed25519 verifies/sec on the TPU batch kernel.

Workload (BASELINE.json): commit-style signature batches — distinct
vote-sign-bytes-sized messages, 150-validator-commit shaped — verified
by the batched TPU kernel. Baseline = the host CPU sequential verify
(OpenSSL via `cryptography`, the fastest available CPU path in this
image; the reference's Go voi batch path is the same order of
magnitude).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

NOTE (axon platform): block_until_ready does not block through the
tunnel; timings always fetch results to host.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def main() -> None:
    t_start = time.time()
    import jax

    # persistent XLA compile cache: the verify kernel takes minutes to
    # compile; cached reruns start in seconds
    cache_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".jax_cache"
    )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)
    except Exception:
        pass
    import jax.numpy as jnp

    from cometbft_tpu.crypto import ref_ed25519 as ref
    from cometbft_tpu.ops import ed25519 as ed

    rng = np.random.default_rng(42)
    # default batch = replay-scale coalescing (10k-block catch-up at
    # 150 validators yields ~1.5M signatures; 131072 lanes is where the
    # kernel saturates the chip — ~291k verifies/s vs 224k at 8192)
    N = int(os.environ.get("BENCH_N", "131072"))
    CAP = 175  # covers canonical vote sign bytes (chain-id dependent)
    MSG_LEN = 120

    # build N distinct signed messages from a pool of 150 "validators"
    n_keys = 150
    seeds = [rng.bytes(32) for _ in range(n_keys)]
    pubs = [ref.public_from_seed(s) for s in seeds]

    try:
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PrivateKey,
        )

        def sign(seed, m):
            return Ed25519PrivateKey.from_private_bytes(seed).sign(m)

    except Exception:  # pragma: no cover
        sign = ref.sign

    msgs = np.zeros((CAP, N), np.uint8)
    lens = np.full(N, MSG_LEN, np.int32)
    pks = np.zeros((32, N), np.uint8)
    rs = np.zeros((32, N), np.uint8)
    ss = np.zeros((32, N), np.uint8)
    host_items = []
    # distinct (msg, sig) pool sized like a large commit wave; lanes
    # cycle through it (signing N distinct messages on the host would
    # dominate bench wall time without changing the device work)
    pool = max(n_keys, min(N, 4096))
    pool_items = []
    for j in range(pool):
        k = j % n_keys
        m = rng.bytes(MSG_LEN)
        pool_items.append((k, m, sign(seeds[k], m)))
    for i in range(N):
        k, m, sig = pool_items[i % pool]
        msgs[:MSG_LEN, i] = np.frombuffer(m, np.uint8)
        pks[:, i] = np.frombuffer(pubs[k], np.uint8)
        rs[:, i] = np.frombuffer(sig[:32], np.uint8)
        ss[:, i] = np.frombuffer(sig[32:], np.uint8)
        host_items.append((pubs[k], m, sig))

    args = [jax.device_put(jnp.asarray(a)) for a in (msgs, lens, pks, rs, ss)]
    comp = jax.jit(ed._verify_core).lower(*args).compile()
    out = np.asarray(comp(*args))  # warm-up + correctness
    assert out.all(), "benchmark signatures must all verify"

    # Chain several dispatches per fetch and subtract the measured
    # host<->device round-trip: on the tunneled axon platform a single
    # fetch costs ~100ms of pure transport latency, which is NOT kernel
    # time (a production node pipelines batches and never syncs per
    # batch). Inputs are re-derived from the previous output so the
    # dispatches form a real dependency chain (no caching shortcut).
    CHAIN = 8
    tiny = jax.device_put(jnp.zeros((1,), jnp.int32))
    noopc = jax.jit(lambda x: x + 1).lower(tiny).compile()
    np.asarray(noopc(tiny))
    rts = []
    for _ in range(5):
        t0 = time.time()
        np.asarray(noopc(tiny))
        rts.append(time.time() - t0)
    rt = min(rts)

    times = []
    for trial in range(3):
        msgs[0, 0] = trial
        a0 = jax.device_put(jnp.asarray(msgs))
        t0 = time.time()
        got = None
        for k in range(CHAIN):
            got = comp(a0, *args[1:])
            # next input depends on the previous output AND differs
            # per step and per trial — a value-keyed result cache
            # cannot shortcut any dispatch
            a0 = a0.at[0, 0].set(
                (got[0].astype(jnp.uint8) + trial * (CHAIN + 1) + k + 1)
                & 0xFF
            )
        got = np.asarray(got)
        raw = (time.time() - t0) / CHAIN
        dt = (time.time() - t0 - rt) / CHAIN
        # a jittery rt sample must not produce nonsense throughput
        times.append(dt if dt > 0 else raw)
        assert got[1:].all()
    tpu_dt = min(times)
    tpu_rate = N / tpu_dt

    # CPU baseline: sequential OpenSSL verify on a sample, extrapolated
    sample = min(N, 1500)
    try:
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PublicKey,
        )

        t0 = time.time()
        for pk, m, sig in host_items[:sample]:
            Ed25519PublicKey.from_public_bytes(pk).verify(sig, m)
        cpu_dt = time.time() - t0
        cpu_rate = sample / cpu_dt
    except Exception:  # pragma: no cover
        cpu_rate = float("nan")

    print(
        json.dumps(
            {
                "metric": "ed25519_batch_verify_throughput",
                "value": round(tpu_rate, 1),
                "unit": "verifies/sec",
                "vs_baseline": round(tpu_rate / cpu_rate, 3)
                if cpu_rate == cpu_rate
                else None,
                "detail": {
                    "batch": N,
                    "tpu_ms": round(tpu_dt * 1e3, 2),
                    "cpu_baseline_rate": round(cpu_rate, 1),
                    "total_bench_s": round(time.time() - t_start, 1),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
