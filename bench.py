"""North-star benchmark: ed25519 verifies/sec on the TPU batch kernel.

Workload (BASELINE.json): commit-style signature batches — distinct
vote-sign-bytes-sized messages, 150-validator-commit shaped — verified
by the batched TPU kernel. Baseline = the host CPU sequential verify
(OpenSSL via `cryptography`, the fastest available CPU path in this
image; the reference's Go voi batch path is the same order of
magnitude).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

NOTE (axon platform): block_until_ready does not block through the
tunnel; timings always fetch results to host.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def main() -> None:
    t_start = time.time()
    import jax
    import jax.numpy as jnp

    from cometbft_tpu.crypto import ref_ed25519 as ref
    from cometbft_tpu.ops import ed25519 as ed

    rng = np.random.default_rng(42)
    N = int(os.environ.get("BENCH_N", "8192"))
    CAP = 175  # covers canonical vote sign bytes (chain-id dependent)
    MSG_LEN = 120

    # build N distinct signed messages from a pool of 150 "validators"
    n_keys = 150
    seeds = [rng.bytes(32) for _ in range(n_keys)]
    pubs = [ref.public_from_seed(s) for s in seeds]

    try:
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PrivateKey,
        )

        def sign(seed, m):
            return Ed25519PrivateKey.from_private_bytes(seed).sign(m)

    except Exception:  # pragma: no cover
        sign = ref.sign

    msgs = np.zeros((CAP, N), np.uint8)
    lens = np.full(N, MSG_LEN, np.int32)
    pks = np.zeros((32, N), np.uint8)
    rs = np.zeros((32, N), np.uint8)
    ss = np.zeros((32, N), np.uint8)
    host_items = []
    for i in range(N):
        k = i % n_keys
        m = rng.bytes(MSG_LEN)
        sig = sign(seeds[k], m)
        msgs[:MSG_LEN, i] = np.frombuffer(m, np.uint8)
        pks[:, i] = np.frombuffer(pubs[k], np.uint8)
        rs[:, i] = np.frombuffer(sig[:32], np.uint8)
        ss[:, i] = np.frombuffer(sig[32:], np.uint8)
        host_items.append((pubs[k], m, sig))

    args = [jax.device_put(jnp.asarray(a)) for a in (msgs, lens, pks, rs, ss)]
    comp = jax.jit(ed._verify_core).lower(*args).compile()
    out = np.asarray(comp(*args))  # warm-up + correctness
    assert out.all(), "benchmark signatures must all verify"

    times = []
    for trial in range(3):
        # touch an input so tunnel-side result caching cannot shortcut
        msgs[0, 0] = trial
        a0 = jax.device_put(jnp.asarray(msgs))
        t0 = time.time()
        got = np.asarray(comp(a0, *args[1:]))
        times.append(time.time() - t0)
        assert got[1:].all()
    tpu_dt = min(times)
    tpu_rate = N / tpu_dt

    # CPU baseline: sequential OpenSSL verify on a sample, extrapolated
    sample = min(N, 1500)
    try:
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PublicKey,
        )

        t0 = time.time()
        for pk, m, sig in host_items[:sample]:
            Ed25519PublicKey.from_public_bytes(pk).verify(sig, m)
        cpu_dt = time.time() - t0
        cpu_rate = sample / cpu_dt
    except Exception:  # pragma: no cover
        cpu_rate = float("nan")

    print(
        json.dumps(
            {
                "metric": "ed25519_batch_verify_throughput",
                "value": round(tpu_rate, 1),
                "unit": "verifies/sec",
                "vs_baseline": round(tpu_rate / cpu_rate, 3)
                if cpu_rate == cpu_rate
                else None,
                "detail": {
                    "batch": N,
                    "tpu_ms": round(tpu_dt * 1e3, 2),
                    "cpu_baseline_rate": round(cpu_rate, 1),
                    "total_bench_s": round(time.time() - t_start, 1),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
