// Native finalize lane (CPython extension): ONE GIL-releasing pass
// per block over the finalize data path.
//
// PR 11 pipelined finalize but measured that the pure-Python
// apply/hash leg cannot be threaded — it just fights the GIL — so it
// stayed on-loop and became the dominant span of the commit
// waterfall. This module moves exactly that leg's byte work to C++
// behind a single call: per-tx SHA-256, ExecTxResult encoding, the
// RFC 6962 LastResultsHash fold and ABCI event/attr encoding all run
// with the GIL RELEASED (inputs are copied into a C++ arena first),
// so consensus/state.py can ride the whole hash+persist phase on
// asyncio.to_thread and the event loop keeps scheduling.
//
// Byte-parity contract: every output is byte-identical to the
// pure-Python implementations in state/execution.py (results_hash,
// _enc_abci_event, ExecTxResult.encode) — the Python path stays the
// semantic source of truth and the no-compiler fallback
// (state/native_finalize.py, differential-tested in
// tests/test_native_finalize.py).
//
// The SHA-256 / proto-writer / merkle helpers mirror
// native/wirecodec.cpp (same deterministic proto subset: zero
// varints and empty bytes omitted, negatives as 64-bit two's
// complement).

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <dlfcn.h>

#include <array>
#include <cstdint>
#include <cstring>
#include <string>
#include <utility>
#include <vector>

namespace {

// --- proto writer (mirror utils/proto.py) -------------------------------

struct Buf {
  std::vector<uint8_t> d;
  void put_varint(uint64_t v) {
    while (v >= 0x80) {
      d.push_back((uint8_t)(v | 0x80));
      v >>= 7;
    }
    d.push_back((uint8_t)v);
  }
  void put_tag(unsigned field, unsigned wire) {
    put_varint((uint64_t)((field << 3) | wire));
  }
  // matches proto.field_varint: zero omitted; negatives two's-complement
  void field_varint(unsigned field, int64_t v) {
    if (v == 0) return;
    put_tag(field, 0);
    put_varint((uint64_t)v);
  }
  // matches proto.field_bytes / field_string: empty omitted
  void field_bytes(unsigned field, const uint8_t* p, size_t n) {
    if (n == 0) return;
    put_tag(field, 2);
    put_varint((uint64_t)n);
    d.insert(d.end(), p, p + n);
  }
  void field_bytes(unsigned field, const std::string& s) {
    field_bytes(field, (const uint8_t*)s.data(), s.size());
  }
};

// --- SHA-256 (FIPS 180-4, from-spec; wirecodec.cpp twin) ----------------

struct Sha256 {
  uint32_t h[8];
  uint8_t buf[64];
  uint64_t len = 0;
  size_t fill = 0;

  static constexpr uint32_t K[64] = {
      0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
      0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
      0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
      0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
      0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
      0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
      0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
      0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
      0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
      0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
      0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
      0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
      0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

  Sha256() { reset(); }
  void reset() {
    h[0] = 0x6a09e667; h[1] = 0xbb67ae85; h[2] = 0x3c6ef372;
    h[3] = 0xa54ff53a; h[4] = 0x510e527f; h[5] = 0x9b05688c;
    h[6] = 0x1f83d9ab; h[7] = 0x5be0cd19;
    len = 0;
    fill = 0;
  }
  static uint32_t rotr(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
  }
  void block(const uint8_t* p) {
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
      w[i] = ((uint32_t)p[4 * i] << 24) | ((uint32_t)p[4 * i + 1] << 16) |
             ((uint32_t)p[4 * i + 2] << 8) | p[4 * i + 3];
    for (int i = 16; i < 64; i++) {
      uint32_t s0 =
          rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 =
          rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; i++) {
      uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + S1 + ch + K[i] + w[i];
      uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }
  void update(const uint8_t* p, size_t n) {
    len += n;
    if (fill) {
      while (n && fill < 64) {
        buf[fill++] = *p++;
        n--;
      }
      if (fill == 64) {
        block(buf);
        fill = 0;
      }
    }
    while (n >= 64) {
      block(p);
      p += 64;
      n -= 64;
    }
    while (n) {
      buf[fill++] = *p++;
      n--;
    }
  }
  void final(uint8_t out[32]) {
    uint64_t bits = len * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t z = 0;
    while (fill != 56) update(&z, 1);
    uint8_t lb[8];
    for (int i = 0; i < 8; i++) lb[i] = (uint8_t)(bits >> (56 - 8 * i));
    update(lb, 8);
    for (int i = 0; i < 8; i++) {
      out[4 * i] = (uint8_t)(h[i] >> 24);
      out[4 * i + 1] = (uint8_t)(h[i] >> 16);
      out[4 * i + 2] = (uint8_t)(h[i] >> 8);
      out[4 * i + 3] = (uint8_t)h[i];
    }
  }
};
constexpr uint32_t Sha256::K[64];

// one-shot SHA256 via libcrypto when present (hardware SHA
// extensions); portable fallback is the same function, so digests
// are identical either way
typedef unsigned char* (*fn_ossl_sha256)(const unsigned char*, size_t,
                                         unsigned char*);

static fn_ossl_sha256 ossl_sha256() {
  static fn_ossl_sha256 fn = []() -> fn_ossl_sha256 {
    const char* names[] = {"libcrypto.so.3", "libcrypto.so.1.1",
                           "libcrypto.so"};
    for (const char* n : names) {
      if (void* lib = dlopen(n, RTLD_NOW | RTLD_GLOBAL)) {
        if (void* sym = dlsym(lib, "SHA256"))
          return reinterpret_cast<fn_ossl_sha256>(sym);
      }
    }
    return nullptr;
  }();
  return fn;
}

static void sha256_oneshot(const uint8_t* p, size_t n, uint8_t out[32]) {
  fn_ossl_sha256 fast = ossl_sha256();
  if (fast) {
    fast((const unsigned char*)p, n, out);
    return;
  }
  Sha256 s;
  s.update(p, n);
  s.final(out);
}

static void leaf_hash(const uint8_t* p, size_t n, uint8_t out[32]) {
  Sha256 s;
  uint8_t pfx = 0x00;
  s.update(&pfx, 1);
  s.update(p, n);
  s.final(out);
}

static void inner_hash(const uint8_t l[32], const uint8_t r[32],
                       uint8_t out[32]) {
  Sha256 s;
  uint8_t pfx = 0x01;
  s.update(&pfx, 1);
  s.update(l, 32);
  s.update(r, 32);
  s.final(out);
}

// binary-carry RFC 6962 reduction (crypto/merkle.hash_from_byte_slices)
struct TreeAcc {
  std::vector<std::pair<std::array<uint8_t, 32>, size_t>> stack;
  void push_leaf(const uint8_t* p, size_t n) {
    std::array<uint8_t, 32> h;
    leaf_hash(p, n, h.data());
    size_t s = 1;
    while (!stack.empty() && stack.back().second == s) {
      std::array<uint8_t, 32> m;
      inner_hash(stack.back().first.data(), h.data(), m.data());
      stack.pop_back();
      h = m;
      s *= 2;
    }
    stack.emplace_back(h, s);
  }
  void root(uint8_t out[32]) {
    if (stack.empty()) {  // empty tree: SHA-256("")
      Sha256 s;
      s.final(out);
      return;
    }
    std::array<uint8_t, 32> h = stack.back().first;
    stack.pop_back();
    while (!stack.empty()) {
      std::array<uint8_t, 32> m;
      inner_hash(stack.back().first.data(), h.data(), m.data());
      stack.pop_back();
      h = m;
    }
    std::memcpy(out, h.data(), 32);
  }
};

// --- copy-in arena ------------------------------------------------------
//
// Everything below the GIL line works on these plain structs only; no
// Python object is touched between Py_BEGIN/END_ALLOW_THREADS.

struct AttrIn {
  std::string k, v;
  int64_t idx;
};

struct EventIn {
  std::string type;
  std::vector<AttrIn> attrs;
};

struct ResultIn {
  int64_t code, gas_wanted, gas_used;
  std::string data, codespace;
  std::vector<EventIn> events;
};

static bool copy_str(PyObject* o, std::string* out) {
  char* p;
  Py_ssize_t n;
  if (PyBytes_AsStringAndSize(o, &p, &n) < 0) return false;
  out->assign(p, (size_t)n);
  return true;
}

static bool copy_i64(PyObject* o, int64_t* out) {
  *out = (int64_t)PyLong_AsLongLong(o);
  return !PyErr_Occurred();
}

// events: sequence of (type_bytes, [(k_bytes, v_bytes, idx_int), ...])
static bool copy_events(PyObject* events, std::vector<EventIn>* out) {
  PyObject* seq = PySequence_Fast(events, "events must be a sequence");
  if (!seq) return false;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  out->resize((size_t)n);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* ev = PySequence_Fast_GET_ITEM(seq, i);
    PyObject* tseq = PySequence_Fast(ev, "event must be a tuple");
    if (!tseq) {
      Py_DECREF(seq);
      return false;
    }
    if (PySequence_Fast_GET_SIZE(tseq) < 2) {
      Py_DECREF(tseq);
      Py_DECREF(seq);
      PyErr_SetString(PyExc_ValueError, "event tuple needs 2 items");
      return false;
    }
    EventIn& e = (*out)[(size_t)i];
    if (!copy_str(PySequence_Fast_GET_ITEM(tseq, 0), &e.type)) {
      Py_DECREF(tseq);
      Py_DECREF(seq);
      return false;
    }
    PyObject* aseq = PySequence_Fast(
        PySequence_Fast_GET_ITEM(tseq, 1), "attrs must be a sequence");
    if (!aseq) {
      Py_DECREF(tseq);
      Py_DECREF(seq);
      return false;
    }
    Py_ssize_t na = PySequence_Fast_GET_SIZE(aseq);
    e.attrs.resize((size_t)na);
    for (Py_ssize_t j = 0; j < na; j++) {
      PyObject* at = PySequence_Fast_GET_ITEM(aseq, j);
      PyObject* atseq = PySequence_Fast(at, "attr must be a tuple");
      if (!atseq || PySequence_Fast_GET_SIZE(atseq) < 3) {
        Py_XDECREF(atseq);
        Py_DECREF(aseq);
        Py_DECREF(tseq);
        Py_DECREF(seq);
        if (!PyErr_Occurred())
          PyErr_SetString(PyExc_ValueError, "attr tuple needs 3 items");
        return false;
      }
      AttrIn& a = e.attrs[(size_t)j];
      if (!copy_str(PySequence_Fast_GET_ITEM(atseq, 0), &a.k) ||
          !copy_str(PySequence_Fast_GET_ITEM(atseq, 1), &a.v) ||
          !copy_i64(PySequence_Fast_GET_ITEM(atseq, 2), &a.idx)) {
        Py_DECREF(atseq);
        Py_DECREF(aseq);
        Py_DECREF(tseq);
        Py_DECREF(seq);
        return false;
      }
      Py_DECREF(atseq);
    }
    Py_DECREF(aseq);
    Py_DECREF(tseq);
  }
  Py_DECREF(seq);
  return true;
}

// mirror state/execution._enc_abci_event over the flattened form
static void encode_event(const EventIn& e, Buf* out) {
  out->field_bytes(1, e.type);
  Buf sub;
  for (const AttrIn& a : e.attrs) {
    sub.d.clear();
    sub.field_bytes(1, a.k);
    sub.field_bytes(2, a.v);
    sub.field_varint(3, a.idx ? 1 : 0);
    out->field_bytes(2, sub.d.data(), sub.d.size());
  }
}

// mirror abci.ExecTxResult.encode (fields 1, 2, 5, 6, 8)
static void encode_result(const ResultIn& r, Buf* out) {
  out->field_varint(1, r.code);
  out->field_bytes(2, r.data);
  out->field_varint(5, r.gas_wanted);
  out->field_varint(6, r.gas_used);
  out->field_bytes(8, r.codespace);
}

static PyObject* bytes_from(const std::vector<uint8_t>& v) {
  return PyBytes_FromStringAndSize((const char*)v.data(),
                                   (Py_ssize_t)v.size());
}

// --- finalize_pass ------------------------------------------------------
//
// finalize_pass(txs, results) ->
//     (tx_hashes, results_enc, results_hash, tx_events_enc)
//
//   txs:      sequence[bytes]
//   results:  sequence[(code, data, gas_wanted, gas_used,
//                       codespace_bytes, events)]
//   events:   sequence[(type_bytes, [(k, v, idx), ...])]
//
//   tx_hashes:     list[bytes32]        sha256(tx) per tx
//   results_enc:   list[bytes]          ExecTxResult.encode() per result
//   results_hash:  bytes32              RFC 6962 root over results_enc
//   tx_events_enc: list[list[bytes]]    _enc_abci_event per event per tx
//
// Inputs are copied into a C++ arena under the GIL; ALL hashing and
// encoding then runs with the GIL released.
static PyObject* fz_finalize_pass(PyObject*, PyObject* args) {
  PyObject* txs_o;
  PyObject* results_o;
  if (!PyArg_ParseTuple(args, "OO", &txs_o, &results_o)) return nullptr;

  // copy-in: txs
  std::vector<std::string> txs;
  {
    PyObject* seq = PySequence_Fast(txs_o, "txs must be a sequence");
    if (!seq) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    txs.resize((size_t)n);
    for (Py_ssize_t i = 0; i < n; i++) {
      if (!copy_str(PySequence_Fast_GET_ITEM(seq, i), &txs[(size_t)i])) {
        Py_DECREF(seq);
        return nullptr;
      }
    }
    Py_DECREF(seq);
  }

  // copy-in: results
  std::vector<ResultIn> results;
  {
    PyObject* seq =
        PySequence_Fast(results_o, "results must be a sequence");
    if (!seq) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    results.resize((size_t)n);
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject* r = PySequence_Fast_GET_ITEM(seq, i);
      PyObject* rseq = PySequence_Fast(r, "result must be a tuple");
      if (!rseq) {
        Py_DECREF(seq);
        return nullptr;
      }
      if (PySequence_Fast_GET_SIZE(rseq) < 6) {
        Py_DECREF(rseq);
        Py_DECREF(seq);
        PyErr_SetString(PyExc_ValueError, "result tuple needs 6 items");
        return nullptr;
      }
      ResultIn& ri = results[(size_t)i];
      if (!copy_i64(PySequence_Fast_GET_ITEM(rseq, 0), &ri.code) ||
          !copy_str(PySequence_Fast_GET_ITEM(rseq, 1), &ri.data) ||
          !copy_i64(PySequence_Fast_GET_ITEM(rseq, 2), &ri.gas_wanted) ||
          !copy_i64(PySequence_Fast_GET_ITEM(rseq, 3), &ri.gas_used) ||
          !copy_str(PySequence_Fast_GET_ITEM(rseq, 4), &ri.codespace) ||
          !copy_events(PySequence_Fast_GET_ITEM(rseq, 5), &ri.events)) {
        Py_DECREF(rseq);
        Py_DECREF(seq);
        return nullptr;
      }
      Py_DECREF(rseq);
    }
    Py_DECREF(seq);
  }

  // compute: GIL released — no Python object is touched in here
  std::vector<std::array<uint8_t, 32>> tx_hashes(txs.size());
  std::vector<std::vector<uint8_t>> res_enc(results.size());
  std::vector<std::vector<std::vector<uint8_t>>> ev_enc(results.size());
  uint8_t root[32];
  Py_BEGIN_ALLOW_THREADS;
  for (size_t i = 0; i < txs.size(); i++)
    sha256_oneshot((const uint8_t*)txs[i].data(), txs[i].size(),
                   tx_hashes[i].data());
  TreeAcc acc;
  Buf b;
  for (size_t i = 0; i < results.size(); i++) {
    b.d.clear();
    encode_result(results[i], &b);
    res_enc[i] = b.d;
    acc.push_leaf(b.d.data(), b.d.size());
    ev_enc[i].resize(results[i].events.size());
    for (size_t j = 0; j < results[i].events.size(); j++) {
      b.d.clear();
      encode_event(results[i].events[j], &b);
      ev_enc[i][j] = b.d;
    }
  }
  acc.root(root);
  Py_END_ALLOW_THREADS;

  // copy-out
  PyObject* hashes = PyList_New((Py_ssize_t)tx_hashes.size());
  PyObject* encs = PyList_New((Py_ssize_t)res_enc.size());
  PyObject* evs = PyList_New((Py_ssize_t)ev_enc.size());
  PyObject* root_b = PyBytes_FromStringAndSize((const char*)root, 32);
  if (!hashes || !encs || !evs || !root_b) goto oom;
  for (size_t i = 0; i < tx_hashes.size(); i++) {
    PyObject* h =
        PyBytes_FromStringAndSize((const char*)tx_hashes[i].data(), 32);
    if (!h) goto oom;
    PyList_SET_ITEM(hashes, (Py_ssize_t)i, h);
  }
  for (size_t i = 0; i < res_enc.size(); i++) {
    PyObject* e = bytes_from(res_enc[i]);
    if (!e) goto oom;
    PyList_SET_ITEM(encs, (Py_ssize_t)i, e);
  }
  for (size_t i = 0; i < ev_enc.size(); i++) {
    PyObject* per_tx = PyList_New((Py_ssize_t)ev_enc[i].size());
    if (!per_tx) goto oom;
    PyList_SET_ITEM(evs, (Py_ssize_t)i, per_tx);
    for (size_t j = 0; j < ev_enc[i].size(); j++) {
      PyObject* e = bytes_from(ev_enc[i][j]);
      if (!e) goto oom;
      PyList_SET_ITEM(per_tx, (Py_ssize_t)j, e);
    }
  }
  return Py_BuildValue("(NNNN)", hashes, encs, root_b, evs);
oom:
  Py_XDECREF(hashes);
  Py_XDECREF(encs);
  Py_XDECREF(evs);
  Py_XDECREF(root_b);
  return nullptr;
}

// encode_events(events) -> list[bytes]: _enc_abci_event per event
// over the flattened form (block-level events ride this; the per-tx
// events ride finalize_pass). GIL released for the encode loop.
static PyObject* fz_encode_events(PyObject*, PyObject* args) {
  PyObject* events_o;
  if (!PyArg_ParseTuple(args, "O", &events_o)) return nullptr;
  std::vector<EventIn> events;
  if (!copy_events(events_o, &events)) return nullptr;
  std::vector<std::vector<uint8_t>> enc(events.size());
  Py_BEGIN_ALLOW_THREADS;
  Buf b;
  for (size_t i = 0; i < events.size(); i++) {
    b.d.clear();
    encode_event(events[i], &b);
    enc[i] = b.d;
  }
  Py_END_ALLOW_THREADS;
  PyObject* out = PyList_New((Py_ssize_t)enc.size());
  if (!out) return nullptr;
  for (size_t i = 0; i < enc.size(); i++) {
    PyObject* e = bytes_from(enc[i]);
    if (!e) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, (Py_ssize_t)i, e);
  }
  return out;
}

// leaf_hashes(items) -> list[bytes32]: RFC 6962 leaf hash
// sha256(0x00 || item) per item, GIL released — the proposal path's
// block-part hashing (types/part_set.py PartSet.from_data feeds the
// 64KB part chunks through here; merkle.proofs_from_leaf_hashes
// builds identical proofs over the precomputed leaves).
static PyObject* fz_leaf_hashes(PyObject*, PyObject* args) {
  PyObject* items_o;
  if (!PyArg_ParseTuple(args, "O", &items_o)) return nullptr;
  std::vector<std::string> items;
  {
    PyObject* seq = PySequence_Fast(items_o, "items must be a sequence");
    if (!seq) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    items.resize((size_t)n);
    for (Py_ssize_t i = 0; i < n; i++) {
      if (!copy_str(PySequence_Fast_GET_ITEM(seq, i),
                    &items[(size_t)i])) {
        Py_DECREF(seq);
        return nullptr;
      }
    }
    Py_DECREF(seq);
  }
  std::vector<std::array<uint8_t, 32>> hashes(items.size());
  Py_BEGIN_ALLOW_THREADS;
  for (size_t i = 0; i < items.size(); i++)
    leaf_hash((const uint8_t*)items[i].data(), items[i].size(),
              hashes[i].data());
  Py_END_ALLOW_THREADS;
  PyObject* out = PyList_New((Py_ssize_t)hashes.size());
  if (!out) return nullptr;
  for (size_t i = 0; i < hashes.size(); i++) {
    PyObject* h =
        PyBytes_FromStringAndSize((const char*)hashes[i].data(), 32);
    if (!h) {
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, (Py_ssize_t)i, h);
  }
  return out;
}

static PyMethodDef Methods[] = {
    {"finalize_pass", fz_finalize_pass, METH_VARARGS,
     "finalize_pass(txs, results) -> (tx_hashes, results_enc, "
     "results_hash, tx_events_enc); one GIL-releasing pass"},
    {"encode_events", fz_encode_events, METH_VARARGS,
     "encode_events(events) -> list[bytes] (_enc_abci_event form)"},
    {"leaf_hashes", fz_leaf_hashes, METH_VARARGS,
     "leaf_hashes(items) -> list of RFC 6962 leaf hashes"},
    {nullptr, nullptr, 0, nullptr}};

static struct PyModuleDef Module = {PyModuleDef_HEAD_INIT, "_finalize",
                                    nullptr, -1, Methods};

}  // namespace

PyMODINIT_FUNC PyInit__finalize(void) {
  return PyModule_Create(&Module);
}
