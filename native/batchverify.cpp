// Native chunk verifier for the host verification plane
// (cometbft_tpu/crypto/parallel_verify.py; loader:
// cometbft_tpu/crypto/native_verify.py — logdb/wirecodec pattern:
// built on demand with g++, pure-Python fallback remains the
// semantic source of truth).
//
// Motivation (docs/PERF.md "Host verification plane"): the per-lane
// Python path costs ~6 ctypes transitions per signature with the GIL
// reacquired between them — worker threads convoy on those short
// GIL-held windows and the thread tier stops scaling. This extension
// verifies a WHOLE chunk per call with the GIL released for the
// entire C loop, so a chunk behaves like one long hashlib-style call:
// threads scale to the hardware and the per-call ctypes overhead
// (~20-40us/sig) disappears.
//
// Strictness contract: OpenSSL's Ed25519 verify is RFC 8032
// (cofactorless) — a strict SUBSET of the ZIP-215 semantics the
// framework pins. A 0-verdict here therefore means "OpenSSL
// rejected", and the Python caller re-runs the liberal pure check on
// exactly those lanes (crypto/keys.Ed25519PubKey.verify does the
// same), keeping verdicts bit-identical across every tier.
//
// libcrypto is dlopen'd at module init (no OpenSSL headers needed at
// build time; the runtime library is the same one crypto/_ossl.py
// binds via ctypes).

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <dlfcn.h>
#include <stdint.h>
#include <string.h>

namespace {

constexpr int kEvpPkeyEd25519 = 1087;  // NID_ED25519

typedef void *(*fn_new_raw_pub)(int, void *, const unsigned char *,
                                size_t);
typedef void (*fn_pkey_free)(void *);
typedef void *(*fn_md_ctx_new)();
typedef void (*fn_md_ctx_free)(void *);
typedef int (*fn_dv_init)(void *, void *, void *, void *, void *);
typedef int (*fn_dv)(void *, const unsigned char *, size_t,
                     const unsigned char *, size_t);

struct Ossl {
  fn_new_raw_pub new_raw_pub = nullptr;
  fn_pkey_free pkey_free = nullptr;
  fn_md_ctx_new md_ctx_new = nullptr;
  fn_md_ctx_free md_ctx_free = nullptr;
  fn_dv_init dv_init = nullptr;
  fn_dv dv = nullptr;
  bool ok = false;
};

Ossl g_ossl;

void load_ossl() {
  const char *names[] = {"libcrypto.so.3", "libcrypto.so.1.1",
                         "libcrypto.so"};
  void *lib = nullptr;
  for (const char *n : names) {
    lib = dlopen(n, RTLD_NOW | RTLD_GLOBAL);
    if (lib) break;
  }
  if (!lib) return;
  g_ossl.new_raw_pub = reinterpret_cast<fn_new_raw_pub>(
      dlsym(lib, "EVP_PKEY_new_raw_public_key"));
  g_ossl.pkey_free =
      reinterpret_cast<fn_pkey_free>(dlsym(lib, "EVP_PKEY_free"));
  g_ossl.md_ctx_new =
      reinterpret_cast<fn_md_ctx_new>(dlsym(lib, "EVP_MD_CTX_new"));
  g_ossl.md_ctx_free =
      reinterpret_cast<fn_md_ctx_free>(dlsym(lib, "EVP_MD_CTX_free"));
  g_ossl.dv_init =
      reinterpret_cast<fn_dv_init>(dlsym(lib, "EVP_DigestVerifyInit"));
  g_ossl.dv = reinterpret_cast<fn_dv>(dlsym(lib, "EVP_DigestVerify"));
  g_ossl.ok = g_ossl.new_raw_pub && g_ossl.pkey_free &&
              g_ossl.md_ctx_new && g_ossl.md_ctx_free &&
              g_ossl.dv_init && g_ossl.dv;
}

// Serial RFC 8032 verify of n lanes; verdicts out[i] in {0, 1}. Runs
// with the GIL released — touches only the raw input buffers.
void verify_lanes(const unsigned char *pubs, const unsigned char *sigs,
                  const unsigned char *msgs, const uint32_t *lens,
                  Py_ssize_t n, unsigned char *out) {
  size_t off = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    const unsigned char *msg = msgs + off;
    size_t mlen = lens[i];
    off += mlen;
    out[i] = 0;
    void *pkey = g_ossl.new_raw_pub(kEvpPkeyEd25519, nullptr,
                                    pubs + 32 * i, 32);
    if (!pkey) continue;
    void *ctx = g_ossl.md_ctx_new();
    if (ctx) {
      if (g_ossl.dv_init(ctx, nullptr, nullptr, nullptr, pkey) == 1 &&
          g_ossl.dv(ctx, sigs + 64 * i, 64, msg, mlen) == 1) {
        out[i] = 1;
      }
      g_ossl.md_ctx_free(ctx);
    }
    g_ossl.pkey_free(pkey);
  }
}

PyObject *py_available(PyObject *, PyObject *) {
  return PyBool_FromLong(g_ossl.ok ? 1 : 0);
}

// verify_ed25519(pubs: bytes, sigs: bytes, msgs: bytes, lens: bytes,
//                n: int) -> bytes
//   pubs: n*32 bytes; sigs: n*64 bytes; msgs: concatenated messages;
//   lens: n uint32 (native-endian) message lengths. Returns n verdict
//   bytes (1 = RFC 8032 valid, 0 = rejected — caller applies the
//   liberal ZIP-215 recheck on the zeros).
PyObject *py_verify_ed25519(PyObject *, PyObject *args) {
  Py_buffer pubs, sigs, msgs, lens;
  Py_ssize_t n;
  if (!PyArg_ParseTuple(args, "y*y*y*y*n", &pubs, &sigs, &msgs, &lens,
                        &n)) {
    return nullptr;
  }
  PyObject *ret = nullptr;
  if (!g_ossl.ok) {
    PyErr_SetString(PyExc_RuntimeError, "libcrypto unavailable");
  } else if (pubs.len != 32 * n || sigs.len != 64 * n ||
             lens.len != static_cast<Py_ssize_t>(sizeof(uint32_t)) * n) {
    PyErr_SetString(PyExc_ValueError, "buffer sizes do not match n");
  } else {
    const uint32_t *lp = static_cast<const uint32_t *>(lens.buf);
    uint64_t total = 0;
    for (Py_ssize_t i = 0; i < n; i++) total += lp[i];
    if (static_cast<uint64_t>(msgs.len) != total) {
      PyErr_SetString(PyExc_ValueError, "msg buffer / lens mismatch");
    } else {
      ret = PyBytes_FromStringAndSize(nullptr, n);
      if (ret) {
        unsigned char *out = reinterpret_cast<unsigned char *>(
            PyBytes_AS_STRING(ret));
        Py_BEGIN_ALLOW_THREADS;
        verify_lanes(static_cast<const unsigned char *>(pubs.buf),
                     static_cast<const unsigned char *>(sigs.buf),
                     static_cast<const unsigned char *>(msgs.buf), lp,
                     n, out);
        Py_END_ALLOW_THREADS;
      }
    }
  }
  PyBuffer_Release(&pubs);
  PyBuffer_Release(&sigs);
  PyBuffer_Release(&msgs);
  PyBuffer_Release(&lens);
  return ret;
}

PyMethodDef kMethods[] = {
    {"available", py_available, METH_NOARGS,
     "libcrypto loaded and all EVP symbols resolved"},
    {"verify_ed25519", py_verify_ed25519, METH_VARARGS,
     "chunked RFC 8032 ed25519 verify, GIL released for the C loop"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {
    PyModuleDef_HEAD_INIT, "_batchverify",
    "native GIL-releasing chunk verifier", -1, kMethods,
};

}  // namespace

PyMODINIT_FUNC PyInit__batchverify(void) {
  load_ossl();
  return PyModule_Create(&kModule);
}
