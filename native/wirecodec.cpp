// Native wire codec for the replay hot loop (CPython extension).
//
// The blocksync replay pipeline encodes/decodes hundreds of thousands
// of commit signatures (150 validators x 2 commits x every height);
// profiling (docs/PERF.md round 4) shows the pure-Python proto
// writer/reader burning ~40% of the non-signature host time in varint
// byte-appends alone. This module moves exactly that loop to C++:
// whole-commit encode and decode in one call each, byte-for-byte
// identical to cometbft_tpu/utils/codec.py's encode_commit /
// decode_commit (the repo's deterministic proto subset — field order
// fixed, zero varints and empty bytes omitted, timestamps as
// {1: secs, 2: nanos}).
//
// Decode handles ADVERSARIAL input (peer-supplied bytes): every read
// is bounds-checked and malformed shapes raise ValueError with the
// same classes of message as the Python reader. The Python wrapper
// (utils/codec.py) falls back to the pure-Python path when the
// extension is unavailable; a property test cross-checks both.

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <dlfcn.h>

#include <array>
#include <cstdint>
#include <cstring>
#include <string>
#include <utility>
#include <vector>

namespace {

// --- writer -------------------------------------------------------------

struct Buf {
  std::vector<uint8_t> d;
  void put_varint(uint64_t v) {
    while (v >= 0x80) {
      d.push_back((uint8_t)(v | 0x80));
      v >>= 7;
    }
    d.push_back((uint8_t)v);
  }
  void put_tag(unsigned field, unsigned wire) {
    put_varint((uint64_t)((field << 3) | wire));
  }
  // matches proto.field_varint: zero omitted; negatives two's-complement
  void field_varint(unsigned field, int64_t v) {
    if (v == 0) return;
    put_tag(field, 0);
    put_varint((uint64_t)v);
  }
  void field_bytes(unsigned field, const uint8_t* p, size_t n) {
    if (n == 0) return;
    put_tag(field, 2);
    put_varint((uint64_t)n);
    d.insert(d.end(), p, p + n);
  }
  // matches proto.field_message: emitted even when empty
  void field_message(unsigned field, const uint8_t* p, size_t n) {
    put_tag(field, 2);
    put_varint((uint64_t)n);
    if (n) d.insert(d.end(), p, p + n);
  }
};

// timestamp payload {1: secs, 2: nanos}; ns >= 0 in practice, but the
// Python divmod (floor) semantics are mirrored for negatives anyway
static void put_timestamp(Buf& out, unsigned field, int64_t ns) {
  int64_t secs = ns / 1000000000;
  int64_t nanos = ns % 1000000000;
  if (nanos < 0) {  // floor semantics like Python divmod
    nanos += 1000000000;
    secs -= 1;
  }
  Buf ts;
  ts.field_varint(1, secs);
  ts.field_varint(2, nanos);
  out.field_message(field, ts.d.data(), ts.d.size());
}

// Returns a NEW reference to the attribute (or nullptr on error) and
// fills p/n with its buffer. The caller must hold the returned
// reference until it is done with *p: if the attribute were a property
// returning a fresh bytes object, an early DECREF would leave *p
// dangling (use-after-free).
static PyObject* get_bytes_attr(PyObject* obj, const char* name,
                                const uint8_t** p, Py_ssize_t* n) {
  PyObject* v = PyObject_GetAttrString(obj, name);
  if (!v) return nullptr;
  char* cp;
  if (PyBytes_AsStringAndSize(v, &cp, n) < 0) {
    Py_DECREF(v);
    return nullptr;
  }
  *p = (const uint8_t*)cp;
  return v;
}

static bool get_i64_attr(PyObject* obj, const char* name, int64_t* out) {
  PyObject* v = PyObject_GetAttrString(obj, name);
  if (!v) return false;
  *out = (int64_t)PyLong_AsLongLong(v);
  Py_DECREF(v);
  return !(PyErr_Occurred());
}

// encode one CommitSig object into sub (cleared first); false on error
// with the Python exception set. Attribute references are held until
// their buffers have been copied into sub.
static bool encode_commitsig(PyObject* cs, Buf& sub) {
  int64_t flag, ts;
  const uint8_t *addr, *sig;
  Py_ssize_t addr_n, sig_n;
  if (!get_i64_attr(cs, "block_id_flag", &flag)) return false;
  PyObject* addr_o =
      get_bytes_attr(cs, "validator_address", &addr, &addr_n);
  if (!addr_o) return false;
  if (!get_i64_attr(cs, "timestamp_ns", &ts)) {
    Py_DECREF(addr_o);
    return false;
  }
  PyObject* sig_o = get_bytes_attr(cs, "signature", &sig, &sig_n);
  if (!sig_o) {
    Py_DECREF(addr_o);
    return false;
  }
  sub.d.clear();
  sub.field_varint(1, flag);
  sub.field_bytes(2, addr, (size_t)addr_n);
  put_timestamp(sub, 3, ts);
  sub.field_bytes(4, sig, (size_t)sig_n);
  Py_DECREF(addr_o);
  Py_DECREF(sig_o);
  return true;
}

// encode_commit(height, round, block_id_bytes, sigs) -> bytes
// sigs: sequence of objects with block_id_flag / validator_address /
// timestamp_ns / signature attributes (CommitSig).
static PyObject* wc_encode_commit(PyObject*, PyObject* args) {
  long long height, round_;
  PyObject* bid;
  PyObject* sigs;
  if (!PyArg_ParseTuple(args, "LLSO", &height, &round_, &bid, &sigs))
    return nullptr;
  const uint8_t* bidp = (const uint8_t*)PyBytes_AS_STRING(bid);
  size_t bidn = (size_t)PyBytes_GET_SIZE(bid);

  Buf out;
  out.field_varint(1, (int64_t)height);
  out.field_varint(2, (int64_t)round_);
  out.field_message(3, bidp, bidn);

  PyObject* seq = PySequence_Fast(sigs, "sigs must be a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  Buf sub;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* cs = PySequence_Fast_GET_ITEM(seq, i);
    if (!encode_commitsig(cs, sub)) {
      Py_DECREF(seq);
      return nullptr;
    }
    out.field_message(4, sub.d.data(), sub.d.size());
  }
  Py_DECREF(seq);
  return PyBytes_FromStringAndSize((const char*)out.d.data(),
                                   (Py_ssize_t)out.d.size());
}

// --- reader -------------------------------------------------------------

struct Reader {
  const uint8_t* p;
  size_t n;
  size_t pos = 0;
  bool fail = false;
  std::string err;

  void error(const char* m) {
    if (!fail) {
      fail = true;
      err = m;
    }
  }
  uint64_t varint() {
    // Any value that does not fit 64 bits errors out (ValueError in
    // the wrapper -> pure-Python fallback): Python's reader keeps
    // arbitrary precision there, so silently truncating would make
    // the two builds decode the SAME bytes differently.
    uint64_t out = 0;
    int shift = 0;
    while (true) {
      if (pos >= n) {
        error("truncated varint");
        return 0;
      }
      uint8_t b = p[pos++];
      uint8_t bits = b & 0x7F;
      if (shift > 63 ? bits != 0
                     : (shift == 63 && bits > 1)) {
        error("varint overflows 64 bits");
        return 0;
      }
      if (shift <= 63) out |= (uint64_t)bits << shift;
      if (!(b & 0x80)) break;
      shift += 7;
      if (shift > 70) {
        error("varint too long");
        return 0;
      }
    }
    return out;
  }
  // overflow-safe "ln more bytes available?" (pos + ln can wrap)
  bool has(uint64_t ln) const { return ln <= (uint64_t)(n - pos); }
  bool skip_wire(unsigned w) {
    if (w == 1) {
      if (pos + 8 > n) {
        error("truncated fixed64 field");
        return false;
      }
      pos += 8;
    } else if (w == 5) {
      if (pos + 4 > n) {
        error("truncated fixed32 field");
        return false;
      }
      pos += 4;
    } else {
      error("unsupported wire type");
      return false;
    }
    return true;
  }
};

// timestamp payload -> ns; falls back like _decode_timestamp_ns: any
// non-varint field shape is an error here (Python falls back to the
// generic parser which itself errors on unknown wire types inside a
// timestamp, so semantics match for valid input; for the unusual-but-
// valid shapes the wrapper keeps the Python path via exceptions).
static int64_t read_timestamp(const uint8_t* p, size_t n, bool* ok) {
  Reader r{p, n};
  int64_t secs = 0, nanos = 0;
  while (r.pos < r.n && !r.fail) {
    uint64_t key = r.varint();
    unsigned f = (unsigned)(key >> 3), w = (unsigned)(key & 7);
    if (w != 0) {
      if (!r.skip_wire(w)) break;
      continue;  // ignore odd fields like the generic parser would
    }
    uint64_t v = r.varint();
    if (f == 1)
      secs = (int64_t)v;
    else if (f == 2)
      nanos = (int64_t)v;
  }
  // secs*1e9 + nanos must fit int64: Python computes it in arbitrary
  // precision, so on overflow we ERROR (-> Python fallback) instead
  // of silently wrapping (signed overflow is UB anyway)
  int64_t ns;
  if (__builtin_mul_overflow(secs, (int64_t)1000000000, &ns) ||
      __builtin_add_overflow(ns, nanos, &ns)) {
    *ok = false;
    return 0;
  }
  *ok = !r.fail;
  return ns;
}

// decode_commit(buf) -> (height, round, bid_bytes|None, sig_tuples)
// sig tuple = (flag, addr, ts_ns, sig)
static PyObject* wc_decode_commit(PyObject*, PyObject* args) {
  Py_buffer buf;
  if (!PyArg_ParseTuple(args, "y*", &buf)) return nullptr;
  Reader r{(const uint8_t*)buf.buf, (size_t)buf.len};

  int64_t height = 0, round_ = 0;
  PyObject* bid = nullptr;     // bytes or nullptr
  PyObject* sigs = PyList_New(0);
  if (!sigs) {
    PyBuffer_Release(&buf);
    return nullptr;
  }

  auto bail = [&](const char* m) -> PyObject* {
    Py_XDECREF(bid);
    Py_DECREF(sigs);
    PyBuffer_Release(&buf);
    PyErr_SetString(PyExc_ValueError, m);
    return nullptr;
  };

  while (r.pos < r.n) {
    uint64_t key = r.varint();
    if (r.fail) return bail(r.err.c_str());
    unsigned f = (unsigned)(key >> 3), w = (unsigned)(key & 7);
    if (w == 0) {
      uint64_t v = r.varint();
      if (r.fail) return bail(r.err.c_str());
      if (f == 1)
        height = (int64_t)v;
      else if (f == 2)
        round_ = (int64_t)v;
      else if (f == 3 || f == 4)
        return bail("commit field: expected bytes");
    } else if (w == 2) {
      uint64_t ln = r.varint();
      if (r.fail) return bail(r.err.c_str());
      if (!r.has(ln)) return bail("truncated bytes field");
      const uint8_t* sub = r.p + r.pos;
      size_t subn = (size_t)ln;
      r.pos += ln;
      if (f == 1 || f == 2)
        return bail("commit field: expected varint");
      if (f == 3) {
        Py_XDECREF(bid);
        bid = PyBytes_FromStringAndSize((const char*)sub,
                                        (Py_ssize_t)subn);
        if (!bid) return bail("oom");
      } else if (f == 4) {
        // inline commit-sig scan (mirror _decode_commit_sig_fast)
        Reader s{sub, subn};
        int64_t flag = 0, ts = 0;
        const uint8_t* addr = nullptr;
        size_t addr_n = 0;
        const uint8_t* sig = nullptr;
        size_t sig_n = 0;
        while (s.pos < s.n) {
          uint64_t k2 = s.varint();
          if (s.fail) return bail(s.err.c_str());
          unsigned f2 = (unsigned)(k2 >> 3), w2 = (unsigned)(k2 & 7);
          if (w2 == 0) {
            uint64_t v2 = s.varint();
            if (s.fail) return bail(s.err.c_str());
            if (f2 == 1)
              flag = (int64_t)v2;
            else if (f2 == 2 || f2 == 3 || f2 == 4)
              return bail("commit sig field: expected bytes");
          } else if (w2 == 2) {
            uint64_t l2 = s.varint();
            if (s.fail) return bail(s.err.c_str());
            if (!s.has(l2)) return bail("truncated bytes field");
            const uint8_t* v2 = s.p + s.pos;
            s.pos += l2;
            if (f2 == 1)
              return bail("commit sig field 1: expected varint");
            if (f2 == 2) {
              addr = v2;
              addr_n = (size_t)l2;
            } else if (f2 == 3) {
              bool ok;
              ts = read_timestamp(v2, (size_t)l2, &ok);
              if (!ok) return bail("malformed timestamp");
            } else if (f2 == 4) {
              sig = v2;
              sig_n = (size_t)l2;
            }
          } else {
            if (!s.skip_wire(w2)) return bail(s.err.c_str());
          }
        }
        PyObject* t = Py_BuildValue(
            "(Ly#Ly#)", (long long)flag, (const char*)(addr ? addr : (const uint8_t*)""),
            (Py_ssize_t)addr_n, (long long)ts,
            (const char*)(sig ? sig : (const uint8_t*)""),
            (Py_ssize_t)sig_n);
        if (!t) return bail("oom");
        if (PyList_Append(sigs, t) < 0) {
          Py_DECREF(t);
          return bail("oom");
        }
        Py_DECREF(t);
      }
    } else {
      if (!r.skip_wire(w)) return bail(r.err.c_str());
    }
  }
  PyObject* out =
      Py_BuildValue("(LLNN)", (long long)height, (long long)round_,
                    bid ? bid : (Py_INCREF(Py_None), Py_None), sigs);
  PyBuffer_Release(&buf);
  if (!out) {
    // Py_BuildValue with N already stole refs on success; on failure
    // they leak — acceptable for an OOM path
    return nullptr;
  }
  return out;
}

// --- SHA-256 (FIPS 180-4) + RFC 6962 merkle roots -----------------------
//
// No OpenSSL headers in this image, so the compression function is
// implemented from the spec (fixed public constants). Used for the
// merkle tree hot paths: commit hashes (150 leaf encodes + tree per
// commit) and generic roots over pre-encoded leaves.

struct Sha256 {
  uint32_t h[8];
  uint8_t buf[64];
  uint64_t len = 0;
  size_t fill = 0;

  static constexpr uint32_t K[64] = {
      0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
      0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
      0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
      0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
      0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
      0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
      0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
      0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
      0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
      0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
      0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
      0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
      0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

  Sha256() { reset(); }
  void reset() {
    h[0] = 0x6a09e667; h[1] = 0xbb67ae85; h[2] = 0x3c6ef372;
    h[3] = 0xa54ff53a; h[4] = 0x510e527f; h[5] = 0x9b05688c;
    h[6] = 0x1f83d9ab; h[7] = 0x5be0cd19;
    len = 0;
    fill = 0;
  }
  static uint32_t rotr(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
  }
  void block(const uint8_t* p) {
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
      w[i] = ((uint32_t)p[4 * i] << 24) | ((uint32_t)p[4 * i + 1] << 16) |
             ((uint32_t)p[4 * i + 2] << 8) | p[4 * i + 3];
    for (int i = 16; i < 64; i++) {
      uint32_t s0 =
          rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 =
          rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; i++) {
      uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + S1 + ch + K[i] + w[i];
      uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }
  void update(const uint8_t* p, size_t n) {
    len += n;
    if (fill) {
      while (n && fill < 64) {
        buf[fill++] = *p++;
        n--;
      }
      if (fill == 64) {
        block(buf);
        fill = 0;
      }
    }
    while (n >= 64) {
      block(p);
      p += 64;
      n -= 64;
    }
    while (n) {
      buf[fill++] = *p++;
      n--;
    }
  }
  void final(uint8_t out[32]) {
    uint64_t bits = len * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t z = 0;
    while (fill != 56) update(&z, 1);
    uint8_t lb[8];
    for (int i = 0; i < 8; i++) lb[i] = (uint8_t)(bits >> (56 - 8 * i));
    update(lb, 8);
    for (int i = 0; i < 8; i++) {
      out[4 * i] = (uint8_t)(h[i] >> 24);
      out[4 * i + 1] = (uint8_t)(h[i] >> 16);
      out[4 * i + 2] = (uint8_t)(h[i] >> 8);
      out[4 * i + 3] = (uint8_t)h[i];
    }
  }
};
constexpr uint32_t Sha256::K[64];

static void leaf_hash(const uint8_t* p, size_t n, uint8_t out[32]) {
  Sha256 s;
  uint8_t pfx = 0x00;
  s.update(&pfx, 1);
  s.update(p, n);
  s.final(out);
}

static void inner_hash(const uint8_t l[32], const uint8_t r[32],
                       uint8_t out[32]) {
  Sha256 s;
  uint8_t pfx = 0x01;
  s.update(&pfx, 1);
  s.update(l, 32);
  s.update(r, 32);
  s.final(out);
}

// binary-carry RFC 6962 reduction, mirroring
// crypto/merkle.hash_from_byte_slices
struct TreeAcc {
  std::vector<std::pair<std::array<uint8_t, 32>, size_t>> stack;
  void push_leaf(const uint8_t* p, size_t n) {
    std::array<uint8_t, 32> h;
    leaf_hash(p, n, h.data());
    size_t s = 1;
    while (!stack.empty() && stack.back().second == s) {
      std::array<uint8_t, 32> m;
      inner_hash(stack.back().first.data(), h.data(), m.data());
      stack.pop_back();
      h = m;
      s *= 2;
    }
    stack.emplace_back(h, s);
  }
  void root(uint8_t out[32]) {
    if (stack.empty()) {  // empty tree: SHA-256("")
      Sha256 s;
      s.final(out);
      return;
    }
    std::array<uint8_t, 32> h = stack.back().first;
    stack.pop_back();
    while (!stack.empty()) {
      std::array<uint8_t, 32> m;
      inner_hash(stack.back().first.data(), h.data(), m.data());
      stack.pop_back();
      h = m;
    }
    std::memcpy(out, h.data(), 32);
  }
};

// merkle_root(leaves: sequence[bytes]) -> bytes32
static PyObject* wc_merkle_root(PyObject*, PyObject* args) {
  PyObject* leaves;
  if (!PyArg_ParseTuple(args, "O", &leaves)) return nullptr;
  PyObject* seq = PySequence_Fast(leaves, "leaves must be a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  TreeAcc acc;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* it = PySequence_Fast_GET_ITEM(seq, i);
    char* p;
    Py_ssize_t ln;
    if (PyBytes_AsStringAndSize(it, &p, &ln) < 0) {
      Py_DECREF(seq);
      return nullptr;
    }
    acc.push_leaf((const uint8_t*)p, (size_t)ln);
  }
  Py_DECREF(seq);
  uint8_t out[32];
  acc.root(out);
  return PyBytes_FromStringAndSize((const char*)out, 32);
}

// commit_merkle_root(sigs) -> bytes32: encode each CommitSig (same
// wire form as encode_commit's entries) and fold the RFC 6962 tree,
// all in one call — the Commit.hash() hot path.
static PyObject* wc_commit_merkle_root(PyObject*, PyObject* args) {
  PyObject* sigs;
  if (!PyArg_ParseTuple(args, "O", &sigs)) return nullptr;
  PyObject* seq = PySequence_Fast(sigs, "sigs must be a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  TreeAcc acc;
  Buf sub;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* cs = PySequence_Fast_GET_ITEM(seq, i);
    if (!encode_commitsig(cs, sub)) {
      Py_DECREF(seq);
      return nullptr;
    }
    acc.push_leaf(sub.d.data(), sub.d.size());
  }
  Py_DECREF(seq);
  uint8_t out[32];
  acc.root(out);
  return PyBytes_FromStringAndSize((const char*)out, 32);
}

// sha256_many(items) -> list[bytes32]: one digest per input, computed
// in a single C++ pass. The mempool ingest plane hashes every tx key
// of a batch through here (mempool/mempool.py tx_keys): per-call
// hashlib overhead (object alloc + GIL bounce per tx) dominates the
// actual compression work at typical ~100-byte tx sizes. When
// libcrypto is present its one-shot SHA256() is used (hardware SHA
// extensions — the portable implementation below exists for the
// merkle tree and as the no-libcrypto fallback; both are sha256, so
// the digests are identical either way).
typedef unsigned char* (*fn_ossl_sha256)(const unsigned char*, size_t,
                                         unsigned char*);

static fn_ossl_sha256 ossl_sha256() {
  static fn_ossl_sha256 fn = []() -> fn_ossl_sha256 {
    const char* names[] = {"libcrypto.so.3", "libcrypto.so.1.1",
                           "libcrypto.so"};
    for (const char* n : names) {
      if (void* lib = dlopen(n, RTLD_NOW | RTLD_GLOBAL)) {
        if (void* sym = dlsym(lib, "SHA256"))
          return reinterpret_cast<fn_ossl_sha256>(sym);
      }
    }
    return nullptr;
  }();
  return fn;
}

static PyObject* wc_sha256_many(PyObject*, PyObject* args) {
  PyObject* items;
  if (!PyArg_ParseTuple(args, "O", &items)) return nullptr;
  PyObject* seq = PySequence_Fast(items, "items must be a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  PyObject* out = PyList_New(n);
  if (!out) {
    Py_DECREF(seq);
    return nullptr;
  }
  fn_ossl_sha256 fast = ossl_sha256();
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* it = PySequence_Fast_GET_ITEM(seq, i);
    char* p;
    Py_ssize_t ln;
    if (PyBytes_AsStringAndSize(it, &p, &ln) < 0) {
      Py_DECREF(seq);
      Py_DECREF(out);
      return nullptr;
    }
    uint8_t d[32];
    if (fast) {
      fast((const unsigned char*)p, (size_t)ln, d);
    } else {
      Sha256 s;
      s.update((const uint8_t*)p, (size_t)ln);
      s.final(d);
    }
    PyObject* b = PyBytes_FromStringAndSize((const char*)d, 32);
    if (!b) {
      Py_DECREF(seq);
      Py_DECREF(out);
      return nullptr;
    }
    PyList_SET_ITEM(out, i, b);
  }
  Py_DECREF(seq);
  return out;
}

// varints(seq_of_ints) -> bytes: concatenated LEB128 varints with the
// proto writer's semantics (negatives as 10-byte two's complement) —
// the state store's priority-vector hot loop.
static PyObject* wc_varints(PyObject*, PyObject* args) {
  PyObject* seq_in;
  if (!PyArg_ParseTuple(args, "O", &seq_in)) return nullptr;
  PyObject* seq = PySequence_Fast(seq_in, "varints needs a sequence");
  if (!seq) return nullptr;
  Buf out;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* it = PySequence_Fast_GET_ITEM(seq, i);
    long long v = PyLong_AsLongLong(it);
    if (v == -1 && PyErr_Occurred()) {  // non-int or >64-bit
      Py_DECREF(seq);
      return nullptr;
    }
    out.put_varint((uint64_t)(int64_t)v);
  }
  Py_DECREF(seq);
  return PyBytes_FromStringAndSize((const char*)out.d.data(),
                                   (Py_ssize_t)out.d.size());
}

static PyMethodDef Methods[] = {
    {"varints", wc_varints, METH_VARARGS,
     "varints(ints) -> concatenated LEB128 bytes"},
    {"encode_commit", wc_encode_commit, METH_VARARGS,
     "encode_commit(height, round, bid_bytes, sigs) -> bytes"},
    {"decode_commit", wc_decode_commit, METH_VARARGS,
     "decode_commit(buf) -> (height, round, bid|None, sig_tuples)"},
    {"merkle_root", wc_merkle_root, METH_VARARGS,
     "merkle_root(leaves) -> 32-byte RFC 6962 root"},
    {"commit_merkle_root", wc_commit_merkle_root, METH_VARARGS,
     "commit_merkle_root(sigs) -> 32-byte root of encoded CommitSigs"},
    {"sha256_many", wc_sha256_many, METH_VARARGS,
     "sha256_many(items) -> list of 32-byte digests, one per item"},
    {nullptr, nullptr, 0, nullptr}};

static struct PyModuleDef Module = {PyModuleDef_HEAD_INIT, "_wirecodec",
                                    nullptr, -1, Methods};

}  // namespace

PyMODINIT_FUNC PyInit__wirecodec(void) {
  return PyModule_Create(&Module);
}
